"""graftlint rules engine: JAX/TPU-aware AST static analysis.

The hazard classes this pass exists for are the ones that silently erase
the warm-start wins measured in PR 1 (>94% of cold wall-clock is XLA
compilation): code patterns that force avoidable retraces, promote the
x32 hot path to float64, or synchronize host<->device inside a jitted
program.  None of them raise at import time, and only some raise under
trace — the rest just make the sweep slow, which is why they need a
static pass.

Rule IDs (each documented with rationale + example in ``docs/lint.rst``):

=======  ====================  ==============================================
GL101    numpy-on-tracer       ``np.*`` call receives a traced value inside a
                               jit-reachable function (constant-folds at
                               trace time at best, ``TracerArrayConversion``
                               at worst)
GL102    host-cast-on-tracer   ``float()/int()/bool()/complex()`` applied to
                               a traced value (forces a device sync, breaks
                               under ``vmap``)
GL103    traced-python-branch  ``if``/``while``/``assert``/``for``/ternary
                               on a traced value (trace-time specialization:
                               either a ConcretizationTypeError or a silent
                               retrace per branch)
GL104    static-arg-hazard     ``static_argnames``/``static_argnums`` naming
                               a missing parameter, an array-typed parameter
                               (retrace per VALUE), or an unhashable default
GL105    float64-literal       explicit ``float64``/``complex128`` dtype
                               that defeats the x32 path
GL106    host-sync-in-jit      ``.item()``/``.tolist()``/``print``/
                               ``np.asarray``/``device_get``/
                               ``block_until_ready`` inside jit-reachable
                               code
GL107    nondeterministic-     iteration over a ``set`` (or unsorted
         iteration             ``os.listdir``) where the order can feed
                               compiled-program structure or cache keys
GL201    env-knob-contract     a ``RAFT_TPU_*``/``JAX_*``/``XLA_FLAGS`` env
                               read that is missing from the knob registry
                               (``lint/knobs.py``), or that executes inside
                               jit-traced code without being classified
                               key-salted (its value bakes into compiled
                               programs the AOT key cannot distinguish)
GL202    non-atomic-publish    a direct write to a path under a durable
                               cache/checkpoint root — artifacts must be
                               published via tmp + ``os.replace`` so a kill
                               mid-write never leaves a truncated file a
                               later run trusts
GL203    unbounded-subprocess  a subprocess invocation outside
                               ``resilience.retry.checked_subprocess`` that
                               carries no hard ``timeout=`` (a hung child
                               wedges the sweep forever)
GL204    donation-contract     ``donate_argnums``/``donate_argnames`` on a
                               bare ``jax.jit`` (invisible to the AOT
                               registry's donation salt), or donating an
                               argument index that does not exist at the
                               call site / a function with no output to
                               alias
GL301    unlocked-global-      bare mutation of module-global mutable state
         mutation              (dict/list/set/deque subscript-assign,
                               ``.append``/``.clear``/``+=``/...) inside a
                               function, outside any ``with <lock>:`` block
                               — a resident multi-threaded daemon interleaves
                               such writes (the PR 11 span-stack lesson)
GL302    check-then-act-memo   ``if k not in d: d[k] = ...`` (or
                               ``d.get(k)``-then-assign) on a module-global
                               dict without a lock — the in-process memo
                               pattern that double-computes (double-COMPILES,
                               for the AOT memo) under concurrent requests
GL303    env-read-in-          an env-knob read inside code reachable from a
         concurrent-path       registered *concurrent* entry point
                               (``lint/registry.py`` ``concurrent=True`` /
                               ``CONCURRENT_FUNCTIONS``, or an in-module
                               ``__graftlint_concurrent__`` declaration): a
                               resident process must snapshot knobs at arm
                               time — a mid-process env change silently
                               diverges behavior from the AOT key it was
                               salted into
GL401    host-divergent-       a host-divergent value (env read, wall
         control-flow          clock, random, hostname, pid,
                               ``jax.process_index()``) steering a branch/
                               loop that reaches an SPMD dispatch in code
                               reachable from a *multihost* entry point:
                               all hosts must execute the same program in
                               the same order, or the collective deadlocks
                               the pod (key-salted ``aot_key`` knobs pass —
                               the GL303 triage precedent)
GL402    shared-root-write-    a write under a durable cache/ckpt/obs/
         collision             ledger root, reachable from a multihost
                               entry, whose filename is neither salted by
                               ``jax.process_index()`` nor serialized
                               under a lock: two hosts sharing the root
                               clobber each other (a pid-only suffix does
                               NOT pass — pids collide across hosts)
GL403    unsharded-large-      a batched dispatch (``jit(vmap(f))`` /
         operand               ``cached_*(tag, vmap(f), ...)``) on a
                               multihost path with no ``in_shardings``/
                               ``mesh=``, or a closure-captured large
                               constant not routed through ``consts=`` —
                               both replicate per device instead of
                               sharding the batch axis
GL404    mesh-axis-contract    an axis name in ``PartitionSpec``/``psum``/
                               ``shard_map`` that no ``Mesh`` in the repo
                               declares (typo'd axes fail at dispatch
                               time, on the pod), or a collective placed
                               lexically inside a host-conditional branch
                               (only some hosts enter it: deadlock)
=======  ====================  ==============================================

Reachability: a function is *jit-reachable* when it is decorated with (or
passed to) a tracing transform — ``jit``/``vmap``/``grad``/``shard_map``/
``lax.scan``/... — or is called (or referenced) from the body of another
jit-reachable function, including across modules through ``from X import
y`` edges.  Parameters of reachable functions are considered traced unless
they are listed in ``static_argnames`` or annotated as plain Python
scalars (``int``/``bool``/``str``); names assigned from traced names
become traced (shape/dtype/``is None`` inspections do not propagate
taint, because they are static under trace).

Suppression: append ``# graftlint: disable=GL101`` (comma-separate for
several rules, ``all`` for every rule) to the flagged line, or put
``# graftlint: disable-file=GL105`` on its own line anywhere in the file
to suppress a rule file-wide.  Suppressions are for *justified* host-side
uses — e.g. ``np.float64`` canonicalization inside a cache-key hasher.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re

from raft_tpu.lint import knobs as _knobs

RULES = {
    "GL101": "numpy-on-tracer",
    "GL102": "host-cast-on-tracer",
    "GL103": "traced-python-branch",
    "GL104": "static-arg-hazard",
    "GL105": "float64-literal",
    "GL106": "host-sync-in-jit",
    "GL107": "nondeterministic-iteration",
    "GL201": "env-knob-contract",
    "GL202": "non-atomic-publish",
    "GL203": "unbounded-subprocess",
    "GL204": "donation-contract",
    "GL301": "unlocked-global-mutation",
    "GL302": "check-then-act-memo",
    "GL303": "env-read-in-concurrent-path",
    "GL401": "host-divergent-control-flow",
    "GL402": "shared-root-write-collision",
    "GL403": "unsharded-large-operand",
    "GL404": "mesh-axis-contract",
}

# ---------------------------------------------------------------- GL3xx --
# constructors whose module-level result is shared mutable state the
# concurrency contract (docs/architecture.rst "Concurrency contracts")
# applies to: locked, thread-local, or suppressed-with-reason
_MUTABLE_CONSTRUCTORS = {"dict", "list", "set", "deque", "Counter",
                         "defaultdict", "OrderedDict"}

# in-place mutators of those containers (reads are free; rebinding a
# module global needs an explicit ``global`` and rides the AugAssign arm)
_MUTATOR_METHODS = {"append", "appendleft", "extend", "extendleft",
                    "insert", "add", "discard", "remove", "pop",
                    "popitem", "popleft", "clear", "update", "setdefault",
                    "sort", "reverse", "subtract"}

#: module-level declaration marking functions as concurrent entry points
#: for GL303 (the in-file analog of ``lint/registry.py``'s
#: ``CONCURRENT_FUNCTIONS`` — a daemon module declares its own handlers)
CONCURRENT_DECL = "__graftlint_concurrent__"

#: module-level declaration marking functions as multi-host entry points
#: for GL401/GL402/GL403 (the in-file analog of ``lint/registry.py``'s
#: ``MULTIHOST_FUNCTIONS`` — code on the pod-scale sweep path)
MULTIHOST_DECL = "__graftlint_multihost__"

# ---------------------------------------------------------------- GL4xx --
# cross-device collective primitives (jax.lax namespace): every host must
# reach these in the same order, which is the whole GL401/GL404 contract
_COLLECTIVE_FNS = {"psum", "pmax", "pmin", "pmean", "all_gather",
                   "all_to_all", "ppermute", "pshuffle", "psum_scatter",
                   "axis_index"}

# calls whose result salts a filename per HOST (pid alone does not — pids
# collide across hosts, which is exactly what GL402 exists to catch)
_PROCESS_SALT_FNS = {"process_index", "process_tag"}

# host-divergent value sources for GL401/GL404: (module, attr names).
# Any env read counts too (handled separately, with the aot_key-knob
# exemption per the GL303 triage precedent).
_DIVERGENT_TIME_FNS = {"time", "time_ns", "perf_counter", "monotonic",
                       "process_time"}
_DIVERGENT_HOST_FNS = {"gethostname", "getfqdn", "node", "getpid",
                       "process_index"}

# array constructors whose literal-shape product decides whether a
# closure-captured constant is "large" for GL403 (replicates per device)
_BIG_ARRAY_CTORS = {"zeros", "ones", "full", "empty", "arange",
                    "linspace"}
_BIG_CONST_ELEMS = 4096

# the AOT registry's compile entry points: a function handed to one of
# these is traced and compiled exactly like a jax.jit target (GL1xx
# reachability roots), and its donation signature is key-salted
_CACHED_COMPILE_FNS = {"cached_compile", "cached_callable"}

# functions whose return value names a durable on-disk root (warm-start
# cache layers, checkpoint store): paths derived from them are published
# artifacts and fall under the GL202 atomic-publish contract
_DURABLE_ROOT_FNS = {"subdir", "cache_dir", "resolve_dir", "default_dir",
                     "root"}

# numpy writers that take a PATH first argument (a file object from the
# tmp+os.replace idiom is fine and not matched by the taint check)
_NP_WRITE_FNS = {"savez", "savez_compressed", "save"}

_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output", "Popen"}

# transforms whose function argument is traced with abstract values
_TRACING_TRANSFORMS = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "jacfwd", "jacrev",
    "jvp", "vjp", "linearize", "hessian", "checkpoint", "remat",
    "custom_jvp", "custom_vjp", "shard_map", "scan", "while_loop", "cond",
    "switch", "fori_loop", "map", "associative_scan", "make_jaxpr",
    "named_call", "pallas_call",
}

# names valid only under the lax namespace: ``jax.tree.map`` is a HOST
# function and must not alias to ``lax.map``
_LAX_ONLY_TRANSFORMS = {"scan", "while_loop", "cond", "switch",
                        "fori_loop", "map", "associative_scan"}

# attribute bases under which a transform name is accepted (after alias
# resolution): jax.X, lax.X, jax.lax.X, pallas.X, shard_map module, ...
_JAXY_BASES = {"jax", "lax", "experimental", "pallas", "shard_map",
               "pjit", "ad_checkpoint", "checkpoint"}

# attribute/function inspections that are static under trace: a traced
# name appearing only inside these does NOT make the expression traced
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr",
                 "ndim", "shape", "result_type", "issubdtype", "treedef",
                 "tree_structure"}

# numpy functions that are pure host-constant producers and legitimately
# appear in traced code when fed only non-traced values (handled by the
# taint check anyway; listed for documentation)
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\s]+)"
)

# annotations marking a parameter as static Python configuration rather
# than trace data: scalars, device meshes, and user callables
_SCALAR_ANNOTATIONS = {"int", "bool", "str", "Mesh", "Callable"}


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str          # repo-relative path
    line: int
    col: int
    func: str          # enclosing function qualname, or "<module>"
    msg: str
    source: str = ""   # stripped source line (baseline fingerprint input)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{RULES[self.rule]}] {self.msg}")

    def fingerprint(self) -> str:
        """Line-number-free identity used by the committed baseline: the
        rule + file + enclosing function + the stripped source text.  A
        pure reformat elsewhere in the file cannot churn the baseline."""
        h = hashlib.sha256(
            f"{self.rule}|{self.path}|{self.func}|{self.source}".encode()
        ).hexdigest()[:16]
        return f"{self.rule}:{self.path}:{h}"


@dataclasses.dataclass
class FuncInfo:
    node: ast.AST                      # FunctionDef / AsyncFunctionDef / Lambda
    qualname: str
    module: "ModuleInfo"
    parent: "FuncInfo | None"
    params: list[str] = dataclasses.field(default_factory=list)
    static_params: set[str] = dataclasses.field(default_factory=set)
    is_root: bool = False
    reachable: bool = False
    concurrent: bool = False      # reachable from a concurrent entry point
    multihost: bool = False       # reachable from a multihost entry point
    spmd: bool = False            # contains or reaches a collective/dispatch


class ModuleInfo:
    """Per-file AST plus resolved aliases and the local function table."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.numpy_aliases: set[str] = set()
        self.jnp_aliases: set[str] = set()
        self.jax_aliases: set[str] = set()
        self.lax_aliases: set[str] = set()
        self.os_aliases: set[str] = set()
        self.partial_names: set[str] = set()
        self.functools_aliases: set[str] = set()
        # bare name -> transform name (e.g. from jax import vmap)
        self.transform_names: dict[str, str] = {}
        # local name -> (dotted module, attr-or-None) for cross-module edges
        self.import_map: dict[str, tuple[str, str | None]] = {}
        self.functions: dict[str, FuncInfo] = {}
        self.lambda_infos: dict[int, FuncInfo] = {}   # id(node) -> info
        # names bound to numpy/jnp float64/complex128 via from-imports
        self.wide_dtype_names: dict[str, str] = {}
        self.file_suppress: set[str] = set()
        self.line_suppress: dict[int, set[str]] = {}
        # module-level NAME = "string" constants (resolves the
        # ``ENV_VAR = "RAFT_TPU_X"; os.environ.get(ENV_VAR)`` spelling)
        self.str_constants: dict[str, str] = {}
        # module-global mutable containers (GL301/GL302 state-ownership
        # contract targets) and the module's declared concurrent entry
        # points (GL303 seeds)
        self.mutable_globals: set[str] = set()
        self.concurrent_decls: tuple = ()
        self.multihost_decls: tuple = ()
        self._collect_suppressions()
        self._collect_imports()
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.str_constants[t.id] = node.value.value
        self._collect_mutable_globals()

    def _collect_mutable_globals(self) -> None:
        """Module-level names bound to a mutable container (literal,
        comprehension, or dict/list/set/deque/Counter/defaultdict call) —
        the state GL301/GL302 hold to the lock-or-thread-local contract.
        Module-scope init itself is exempt (the import lock serializes
        it); only mutations from inside functions are checked."""
        for node in self.tree.body:
            targets: list = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names:
                continue
            if CONCURRENT_DECL in names:
                self.concurrent_decls = tuple(
                    n.value for n in ast.walk(value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str))
                continue
            if MULTIHOST_DECL in names:
                self.multihost_decls = tuple(
                    n.value for n in ast.walk(value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str))
                continue
            mutable = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                         ast.DictComp, ast.ListComp,
                                         ast.SetComp))
            if not mutable and isinstance(value, ast.Call):
                fn = value.func
                ctor = (fn.id if isinstance(fn, ast.Name)
                        else fn.attr if isinstance(fn, ast.Attribute)
                        else None)
                mutable = ctor in _MUTABLE_CONSTRUCTORS
            if mutable:
                self.mutable_globals.update(names)

    # -- suppressions ---------------------------------------------------
    def _collect_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group("rules").split(",")
                     if r.strip()}
            if "ALL" in rules:
                rules = set(RULES)
            if m.group("file"):
                self.file_suppress |= rules
            else:
                self.line_suppress.setdefault(i, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppress:
            return True
        return rule in self.line_suppress.get(line, set())

    # -- imports --------------------------------------------------------
    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    name = al.asname or al.name.split(".")[0]
                    if al.name == "numpy":
                        self.numpy_aliases.add(al.asname or "numpy")
                    elif al.name == "jax.numpy":
                        if al.asname:
                            self.jnp_aliases.add(al.asname)
                        self.jax_aliases.add("jax")
                    elif al.name == "jax":
                        self.jax_aliases.add(al.asname or "jax")
                    elif al.name == "functools":
                        self.functools_aliases.add(al.asname or "functools")
                    elif al.name == "os":
                        self.os_aliases.add(al.asname or "os")
                    else:
                        self.import_map[name] = (al.name, None)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for al in node.names:
                    name = al.asname or al.name
                    if mod in ("numpy", "jax.numpy"):
                        if al.name in ("float64", "complex128"):
                            # `from numpy import float64` — bare-name uses
                            # are flagged by the GL105 Name check
                            self.wide_dtype_names[name] = al.name
                        self.import_map[name] = (mod, al.name)
                    elif mod == "jax" and al.name == "numpy":
                        self.jnp_aliases.add(name)
                    elif mod == "functools" and al.name == "partial":
                        self.partial_names.add(name)
                    elif al.name in _TRACING_TRANSFORMS and (
                            mod == "jax" or mod.startswith("jax.")):
                        self.transform_names[name] = al.name
                    elif mod == "jax" and al.name == "lax":
                        self.lax_aliases.add(name)
                    else:
                        self.import_map[name] = (mod, al.name)

    # -- name classification --------------------------------------------
    def is_numpy(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self.numpy_aliases

    def is_jnp(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name) and node.id in self.jnp_aliases:
            return True
        return (isinstance(node, ast.Attribute) and node.attr == "numpy"
                and self.is_jax(node.value))

    def is_jax(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self.jax_aliases

    def transform_of(self, func: ast.AST) -> str | None:
        """Transform name when ``func`` is a tracing transform, else None.

        Discriminates by the immediate namespace so host-side lookalikes
        (``jax.tree.map``, ``jax.tree_util.tree_map``) are NOT transforms
        while ``jax.lax.map``/``lax.scan``/``pl.pallas_call`` are."""
        if isinstance(func, ast.Name):
            return self.transform_names.get(func.id)
        if not isinstance(func, ast.Attribute) or \
                func.attr not in _TRACING_TRANSFORMS:
            return None
        base = func.value
        # classify the immediate base namespace
        if self.is_jax(base):
            return None if func.attr in _LAX_ONLY_TRANSFORMS else func.attr
        if isinstance(base, ast.Name):
            if base.id in self.lax_aliases:
                return func.attr
            # alias of a jax submodule (e.g. pl -> jax.experimental.pallas,
            # functools excluded): accept non-lax-only transforms
            tgt = self.import_map.get(base.id)
            if tgt is not None and tgt[0].startswith("jax"):
                last = (tgt[1] or tgt[0]).rsplit(".", 1)[-1]
                if last in _JAXY_BASES or func.attr == "pallas_call":
                    return (None if func.attr in _LAX_ONLY_TRANSFORMS
                            and last != "lax" else func.attr)
            return None
        if isinstance(base, ast.Attribute):
            # dotted chain: jax.lax.scan vs jax.tree.map — judge by the
            # component immediately before the transform name
            if base.attr in _JAXY_BASES and (
                    self.is_jax(_attr_root(base))
                    or _attr_root_name(base) in self.lax_aliases
                    or _attr_root_name(base) in self.jax_aliases):
                if func.attr in _LAX_ONLY_TRANSFORMS and base.attr != "lax":
                    return None
                return func.attr
        return None

    def is_partial(self, func: ast.AST) -> bool:
        if isinstance(func, ast.Name) and func.id in self.partial_names:
            return True
        return (isinstance(func, ast.Attribute) and func.attr == "partial"
                and isinstance(func.value, ast.Name)
                and func.value.id in self.functools_aliases)

    def _is_os_environ(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute) and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and node.value.id in self.os_aliases)

    def env_read_name(self, node: ast.AST) -> str | None:
        """The env-var name when ``node`` reads the process environment:
        ``os.environ.get/setdefault(NAME)``, ``os.getenv(NAME)``, or an
        ``os.environ[NAME]`` load.  Writes (``os.environ[k] = v``,
        ``.pop``) are not reads and return None.  The name resolves
        through string literals AND module-level string constants
        (``ENV_VAR = "RAFT_TPU_X"; os.environ.get(ENV_VAR)``)."""

        def resolve(a: ast.AST) -> str | None:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                return a.value
            if isinstance(a, ast.Name):
                return self.str_constants.get(a.id)
            return None

        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and node.args:
                is_read = (
                    (fn.attr in ("get", "setdefault")
                     and self._is_os_environ(fn.value))
                    or (fn.attr == "getenv"
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id in self.os_aliases))
                if is_read:
                    return resolve(node.args[0])
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx,
                                                            ast.Load):
            if self._is_os_environ(node.value):
                return resolve(node.slice)
        return None

    def subprocess_call(self, call: ast.Call) -> str | None:
        """The invoked function name when ``call`` launches a subprocess
        (``subprocess.run/call/check_call/check_output/Popen``, through
        any import spelling), else None."""
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in _SUBPROCESS_FNS:
            base = fn.value
            if isinstance(base, ast.Name):
                if base.id == "subprocess":
                    return fn.attr
                tgt = self.import_map.get(base.id)
                if tgt is not None and tgt[0] == "subprocess":
                    return fn.attr
        elif isinstance(fn, ast.Name):
            tgt = self.import_map.get(fn.id)
            if tgt is not None and tgt[0] == "subprocess" \
                    and (tgt[1] or fn.id) in _SUBPROCESS_FNS:
                return tgt[1] or fn.id
        return None

    def cached_compile_call(self, call: ast.Call) -> bool:
        """True when ``call`` goes through the AOT registry
        (``cached_compile``/``cached_callable``, attribute or bare-name
        spelling)."""
        fn = call.func
        if isinstance(fn, ast.Attribute):
            return fn.attr in _CACHED_COMPILE_FNS
        if isinstance(fn, ast.Name):
            if fn.id in _CACHED_COMPILE_FNS:
                return True
            tgt = self.import_map.get(fn.id)
            return (tgt is not None and tgt[0].startswith("raft_tpu")
                    and (tgt[1] or fn.id) in _CACHED_COMPILE_FNS)
        return False

    # -- GL4xx classification -------------------------------------------
    def collective_call(self, call: ast.Call) -> str | None:
        """The primitive name when ``call`` is a cross-device collective
        (``jax.lax.psum``/``lax.pmax``/bare ``psum`` imported from
        ``jax.lax``), else None."""
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in _COLLECTIVE_FNS:
            base = fn.value
            if isinstance(base, ast.Name) and (
                    base.id in self.lax_aliases
                    or base.id in self.jax_aliases):
                return fn.attr
            if isinstance(base, ast.Attribute) and base.attr == "lax" \
                    and self.is_jax(base.value):
                return fn.attr
            return None
        if isinstance(fn, ast.Name):
            tgt = self.import_map.get(fn.id)
            if tgt is not None and tgt[0].startswith("jax") \
                    and (tgt[1] or fn.id) in _COLLECTIVE_FNS:
                return tgt[1] or fn.id
        return None

    def sharded_dispatch(self, call: ast.Call) -> str | None:
        """A label when ``call`` dispatches an SPMD program — the sites
        every host must reach in lockstep: ``shard_map``/``pmap``, a
        ``jit`` carrying ``in_shardings``/``out_shardings``, a registry
        compile carrying ``mesh=``, or ``with_sharding_constraint``."""
        t = self.transform_of(call.func)
        if t in ("shard_map", "pmap"):
            return t
        kws = {kw.arg for kw in call.keywords}
        if t == "jit" and kws & {"in_shardings", "out_shardings"}:
            return "sharded jit"
        if self.cached_compile_call(call) and "mesh" in kws:
            return "mesh-keyed registry compile"
        fn = call.func
        nm = (fn.attr if isinstance(fn, ast.Attribute)
              else fn.id if isinstance(fn, ast.Name) else None)
        if nm == "with_sharding_constraint":
            return "with_sharding_constraint"
        return None

    def partition_spec_call(self, call: ast.Call) -> bool:
        """True for ``PartitionSpec(...)`` / ``P(...)`` (the conventional
        alias, resolved through the import map)."""
        fn = call.func
        if isinstance(fn, ast.Attribute):
            return fn.attr == "PartitionSpec"
        if isinstance(fn, ast.Name):
            if fn.id == "PartitionSpec":
                return True
            tgt = self.import_map.get(fn.id)
            return tgt is not None and tgt[1] == "PartitionSpec"
        return False


def _attr_root(node: ast.Attribute) -> ast.AST:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node


def _attr_root_name(node: ast.AST) -> str | None:
    root = _attr_root(node) if isinstance(node, ast.Attribute) else node
    return root.id if isinstance(root, ast.Name) else None


def _terminal_name(node: ast.AST) -> str | None:
    """The terminal identifier of a call target: ``f`` for both ``f(...)``
    and ``mod.sub.f(...)``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _param_names(args: ast.arguments) -> list[str]:
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


def _annotation_is_scalar(ann: ast.AST | None) -> bool:
    """True for ``int``/``bool``/``str`` (incl. ``int | None`` unions):
    a scalar-annotated parameter is static configuration, not a tracer."""
    if ann is None:
        return False
    if isinstance(ann, ast.Name):
        return ann.id in _SCALAR_ANNOTATIONS
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        parts = re.split(r"[\[\]|,\s]+", ann.value)
        return any(p in _SCALAR_ANNOTATIONS for p in parts) and not any(
            p in ("Array", "ndarray") for p in parts)
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return (_annotation_is_scalar(ann.left)
                or _annotation_is_scalar(ann.right))
    if isinstance(ann, ast.Subscript):  # Optional[int] etc.
        return _annotation_is_scalar(ann.slice)
    return False


def _annotation_is_array(ann: ast.AST | None) -> bool:
    if ann is None:
        return False
    text = ast.dump(ann)
    return ("Array" in text) or ("ndarray" in text)


def _literal_static_names(call: ast.Call) -> tuple[set[str], list[ast.AST]]:
    """(static_argnames as strings, static_argnums nodes) of a jit call."""
    names: set[str] = set()
    nums: list[ast.AST] = []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
        elif kw.arg == "static_argnums":
            nums.append(kw.value)
    return names, nums


class _FunctionCollector(ast.NodeVisitor):
    """First pass: record every function def with its qualname + params."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.stack: list[FuncInfo] = []

    def _visit_func(self, node):
        parent = self.stack[-1] if self.stack else None
        prefix = parent.qualname + "." if parent else ""
        qualname = prefix + node.name
        fi = FuncInfo(node=node, qualname=qualname, module=self.mod,
                      parent=parent, params=_param_names(node.args))
        # scalar-annotated params are static configuration
        for a in (node.args.posonlyargs + node.args.args
                  + node.args.kwonlyargs):
            if _annotation_is_scalar(a.annotation):
                fi.static_params.add(a.arg)
        self._apply_decorators(fi, node)
        self.mod.functions[qualname] = fi
        self.stack.append(fi)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Lambda(self, node: ast.Lambda):
        parent = self.stack[-1] if self.stack else None
        prefix = parent.qualname + "." if parent else ""
        qualname = f"{prefix}<lambda:{node.lineno}:{node.col_offset}>"
        fi = FuncInfo(node=node, qualname=qualname, module=self.mod,
                      parent=parent, params=_param_names(node.args))
        self.mod.functions[qualname] = fi
        self.mod.lambda_infos[id(node)] = fi
        self.stack.append(fi)
        self.generic_visit(node)
        self.stack.pop()

    def _apply_decorators(self, fi: FuncInfo, node) -> None:
        for dec in node.decorator_list:
            tname = self.mod.transform_of(dec)
            if tname:
                fi.is_root = True
                continue
            if isinstance(dec, ast.Call):
                # @partial(jax.jit, static_argnames=...)
                if self.mod.is_partial(dec.func) and dec.args:
                    inner = self.mod.transform_of(dec.args[0])
                    if inner:
                        fi.is_root = True
                        names, _ = _literal_static_names(dec)
                        fi.static_params |= names
                # @jax.jit(static_argnames=...)
                elif self.mod.transform_of(dec.func):
                    fi.is_root = True
                    names, _ = _literal_static_names(dec)
                    fi.static_params |= names


class Analyzer:
    """Whole-package analysis: reachability propagation + rule checks."""

    def __init__(self, paths: list[str], root: str):
        self.root = root
        self.modules: dict[str, ModuleInfo] = {}     # dotted name -> info
        self.by_relpath: dict[str, ModuleInfo] = {}
        self.violations: list[Violation] = []
        for p in paths:
            rel = os.path.relpath(p, root)
            try:
                with open(p, "r", encoding="utf-8") as f:
                    src = f.read()
                mod = ModuleInfo(p, rel, src)
            except SyntaxError as e:
                self.violations.append(Violation(
                    rule="GL103", path=rel, line=e.lineno or 0, col=0,
                    func="<module>", msg=f"file does not parse: {e.msg}",
                    source=""))
                continue
            _FunctionCollector(mod).visit(mod.tree)
            self.modules[_dotted_name(rel)] = mod
            self.by_relpath[rel] = mod

    # -- cross-module resolution ----------------------------------------
    def resolve_external(self, mod: ModuleInfo, name: str) -> list[FuncInfo]:
        """Resolve ``name`` through ``mod``'s imports to FuncInfos in other
        analyzed modules (package ``__init__`` re-exports are chased by
        searching the package directory)."""
        target = mod.import_map.get(name)
        if target is None:
            return []
        dotted, attr = target
        fname = attr or name
        out: list[FuncInfo] = []
        # exact module
        m = self.modules.get(dotted)
        if m is not None and fname in m.functions:
            out.append(m.functions[fname])
        if not out:
            # package: search every analyzed module under that prefix
            for dn, m2 in self.modules.items():
                if dn == dotted or dn.startswith(dotted + "."):
                    fi = m2.functions.get(fname)
                    if fi is not None:
                        out.append(fi)
        return out

    def resolve_local(self, mod: ModuleInfo, scope: FuncInfo | None,
                      name: str) -> FuncInfo | None:
        """Resolve a bare name to a function visible from ``scope``:
        nested siblings first, then enclosing scopes, then module scope."""
        chain = []
        fi = scope
        while fi is not None:
            chain.append(fi.qualname + ".")
            fi = fi.parent
        chain.append("")
        for prefix in chain:
            hit = mod.functions.get(prefix + name)
            if hit is not None:
                return hit
        return None

    # -- reachability ----------------------------------------------------
    def propagate(self) -> None:
        work: list[FuncInfo] = []

        def mark(fi: FuncInfo | None) -> None:
            if fi is not None and not fi.reachable:
                fi.reachable = True
                work.append(fi)

        for mod in self.modules.values():
            for fi in mod.functions.values():
                if fi.is_root:
                    mark(fi)
            # functions passed to transforms anywhere (incl. inside host
            # orchestrators): jax.jit(f) / vmap(one) / scan(body, ...) —
            # resolved in the call's own lexical scope, so a nested
            # ``def one`` passed to ``jax.vmap`` inside its parent is found
            for scope, call in self._transform_calls(mod):
                for arg in list(call.args) + [k.value
                                              for k in call.keywords]:
                    if isinstance(arg, ast.Lambda):
                        mark(mod.lambda_infos.get(id(arg)))
                    else:
                        for fi in self._funcs_named_in(mod, scope, arg):
                            mark(fi)
            # functions handed to the AOT registry are traced and compiled
            # exactly like jax.jit targets: cached_compile(tag, fn, args)
            # / cached_callable(tag, fn, args) mark ``fn`` jit-reachable
            for scope, call in self._scoped_calls(mod):
                if not mod.cached_compile_call(call) or len(call.args) < 2:
                    continue
                fn_arg = call.args[1]
                if isinstance(fn_arg, ast.Lambda):
                    mark(mod.lambda_infos.get(id(fn_arg)))
                else:
                    for fi in self._funcs_named_in(mod, scope, fn_arg):
                        mark(fi)
            # factory pattern: a nested def returned BY NAME is a closure
            # whose callers typically hand it to a transform
            # (``loss = _make_loss(...); jax.value_and_grad(loss)``) — the
            # alias defeats name resolution, so mark bare-name-returned
            # defs traced.  Only bare names (or tuples of them): a helper
            # merely CALLED inside a return expression stays host-side.
            for fi in list(mod.functions.values()):
                for node in self._own_body_walk(fi):
                    if not isinstance(node, ast.Return) or node.value is \
                            None:
                        continue
                    vals = (node.value.elts
                            if isinstance(node.value, ast.Tuple)
                            else [node.value])
                    for v in vals:
                        if isinstance(v, ast.Name):
                            cand = self.resolve_local(mod, fi, v.id)
                            if cand is not None and cand.parent is fi:
                                mark(cand)
        while work:
            fi = work.pop()
            for callee in self._referenced_functions(fi):
                mark(callee)

    def _scoped_nodes(self, mod: ModuleInfo):
        """(lexically enclosing FuncInfo, node) for every node in the
        module — the scope is the function whose body the node sits in
        (None at module level).  Computed once per module (three
        consumers: transform roots, cached-compile roots, contract
        rules); the AST is immutable for the Analyzer's lifetime."""
        cached = getattr(mod, "_scoped_nodes_cache", None)
        if cached is not None:
            return cached
        out: list[tuple[FuncInfo | None, ast.AST]] = []

        def walk(node: ast.AST, scope: FuncInfo | None) -> None:
            for child in ast.iter_child_nodes(node):
                s = scope
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    prefix = scope.qualname + "." if scope else ""
                    s = mod.functions.get(prefix + child.name, scope)
                elif isinstance(child, ast.Lambda):
                    s = mod.lambda_infos.get(id(child), scope)
                out.append((scope, child))
                walk(child, s)

        walk(mod.tree, None)
        mod._scoped_nodes_cache = out
        return out

    def _scoped_calls(self, mod: ModuleInfo):
        """(lexically enclosing FuncInfo, Call) for every call."""
        return [(scope, n) for scope, n in self._scoped_nodes(mod)
                if isinstance(n, ast.Call)]

    def _transform_calls(self, mod: ModuleInfo):
        """(lexically enclosing FuncInfo, Call) for every tracing-transform
        call in the module."""
        return [(scope, call) for scope, call in self._scoped_calls(mod)
                if mod.transform_of(call.func)]

    def _funcs_named_in(self, mod: ModuleInfo, scope: FuncInfo | None,
                        expr: ast.AST):
        """FuncInfos referenced by bare name within ``expr`` (shallow)."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                fi = self.resolve_local(mod, scope, n.id)
                if fi is not None:
                    yield fi
                else:
                    yield from self.resolve_external(mod, n.id)

    def _referenced_functions(self, fi: FuncInfo):
        """Every function referenced from ``fi``'s own body (nested defs
        excluded — they become reachable only if referenced)."""
        mod = fi.module
        for node in self._own_body_walk(fi):
            if isinstance(node, ast.Lambda):
                hit = mod.lambda_infos.get(id(node))
                if hit is not None:
                    yield hit
            elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                           ast.Load):
                hit = self.resolve_local(mod, fi, node.id)
                if hit is not None and hit is not fi:
                    yield hit
                elif hit is None:
                    yield from self.resolve_external(mod, node.id)

    @staticmethod
    def _own_body_walk(fi: FuncInfo):
        """Walk ``fi``'s body without descending into nested function defs
        or lambdas (each is its own FuncInfo, checked when reachable; the
        Lambda/def node itself is still yielded so references resolve)."""
        stack = list(getattr(fi.node, "body", [])) if not isinstance(
            fi.node, ast.Lambda) else [fi.node.body]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                if isinstance(child, ast.Lambda):
                    yield child      # visible for reference resolution
                    continue
                stack.append(child)

    # -- rule application -------------------------------------------------
    def run(self) -> list[Violation]:
        self.propagate()
        self._propagate_concurrent()
        self._propagate_multihost()
        self._propagate_spmd()
        declared_axes = self._declared_axes()
        for mod in self.modules.values():
            self._check_module_wide(mod)
            self._check_contracts(mod)
            self._check_concurrency(mod)
            self._check_spmd(mod, declared_axes)
            for fi in mod.functions.values():
                if fi.reachable:
                    self._check_traced_function(fi)
        self.violations.sort(key=lambda v: (v.path, v.line, v.rule))
        return self.violations

    def _emit(self, mod: ModuleInfo, rule: str, node: ast.AST, func: str,
              msg: str) -> None:
        line = getattr(node, "lineno", 0)
        if mod.suppressed(rule, line):
            return
        src = mod.lines[line - 1].strip() if 0 < line <= len(mod.lines) else ""
        self.violations.append(Violation(
            rule=rule, path=mod.relpath, line=line,
            col=getattr(node, "col_offset", 0), func=func, msg=msg,
            source=src))

    # ---- module-wide rules: GL104, GL105, GL107 ----
    def _check_module_wide(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                self._gl104_call(mod, node)
                self._gl105_call(mod, node)
                self._gl107_call(mod, node)
            elif isinstance(node, ast.Attribute):
                self._gl105_attr(mod, node)
            elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                           ast.Load) \
                    and node.id in mod.wide_dtype_names:
                self._emit(mod, "GL105", node, "<module>",
                           f"explicit 64-bit dtype {node.id!r} (imported "
                           f"as numpy.{mod.wide_dtype_names[node.id]}) "
                           f"defeats the x32 hot path")
            elif isinstance(node, (ast.For, ast.comprehension)):
                self._gl107_iter(mod, node)

    def _gl104_call(self, mod: ModuleInfo, call: ast.Call) -> None:
        """static_argnames/nums hazards on jit(...) / partial(jit, ...)."""
        is_jit_call = mod.transform_of(call.func) == "jit"
        is_partial_jit = (mod.is_partial(call.func) and call.args
                          and mod.transform_of(call.args[0]) == "jit")
        if not (is_jit_call or is_partial_jit):
            return
        names, nums = _literal_static_names(call)
        if not names and not nums:
            return
        # find the decorated/wrapped function: decorator target, or the
        # first positional function argument of jax.jit(f, ...)
        target: FuncInfo | None = None
        for fi in mod.functions.values():
            for dec in fi.node.decorator_list if not isinstance(
                    fi.node, ast.Lambda) else []:
                if dec is call:
                    target = fi
        if target is None and is_jit_call and call.args:
            t = call.args[0]
            if isinstance(t, ast.Name):
                target = self.resolve_local(mod, None, t.id)
        if target is None or isinstance(target.node, ast.Lambda):
            return
        args = target.node.args
        params = _param_names(args)
        ann = {a.arg: a.annotation for a in
               args.posonlyargs + args.args + args.kwonlyargs}
        pos = [a.arg for a in args.posonlyargs + args.args]
        defaults = dict(zip(pos[len(pos) - len(args.defaults):],
                            args.defaults))
        defaults.update({a.arg: d for a, d in
                         zip(args.kwonlyargs, args.kw_defaults)
                         if d is not None})
        for name in sorted(names):
            if name not in params:
                self._emit(mod, "GL104", call, target.qualname,
                           f"static_argnames names {name!r} which is not a "
                           f"parameter of {target.qualname}() — jit will "
                           f"raise at call time")
            elif _annotation_is_array(ann.get(name)):
                self._emit(mod, "GL104", call, target.qualname,
                           f"static_argnames marks array-typed parameter "
                           f"{name!r} static: every distinct VALUE "
                           f"recompiles (and arrays are unhashable)")
            elif name in defaults and isinstance(
                    defaults[name], (ast.List, ast.Dict, ast.Set)):
                self._emit(mod, "GL104", call, target.qualname,
                           f"static parameter {name!r} has an unhashable "
                           f"default — jit static args must be hashable")
        n_params = len(params)
        for num_node in nums:
            for n in ast.walk(num_node):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    if n.value >= n_params or n.value < -n_params:
                        self._emit(mod, "GL104", call, target.qualname,
                                   f"static_argnums {n.value} out of range "
                                   f"for {target.qualname}() with "
                                   f"{n_params} parameters")

    def _gl105_attr(self, mod: ModuleInfo, node: ast.Attribute) -> None:
        if node.attr in ("float64", "complex128") and (
                mod.is_numpy(node.value) or mod.is_jnp(node.value)):
            self._emit(mod, "GL105", node, "<module>",
                       f"explicit 64-bit dtype "
                       f"`{_attr_root_name(node)}.{node.attr}` defeats the "
                       f"x32 hot path (wrap in a justified "
                       f"`# graftlint: disable=GL105` if host-only)")

    def _gl105_call(self, mod: ModuleInfo, call: ast.Call) -> None:
        for kw in call.keywords:
            if kw.arg == "dtype" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value in ("float64", "complex128"):
                self._emit(mod, "GL105", kw.value, "<module>",
                           f"dtype={kw.value.value!r} string literal "
                           f"defeats the x32 hot path")
        if isinstance(call.func, ast.Attribute) and call.func.attr == \
                "astype":
            for a in call.args:
                if isinstance(a, ast.Constant) and a.value in (
                        "float64", "complex128"):
                    self._emit(mod, "GL105", a, "<module>",
                               f"astype({a.value!r}) promotes to 64-bit")

    @staticmethod
    def _is_set_expr(node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("set", "frozenset"))

    def _gl107_iter(self, mod: ModuleInfo, node) -> None:
        it = node.iter
        if self._is_set_expr(it):
            self._emit(mod, "GL107", it, "<module>",
                       "iteration order over a set is arbitrary — feed it "
                       "through sorted() before it can reach a cache key "
                       "or compiled-program structure")
        elif (isinstance(it, ast.Call)
              and isinstance(it.func, ast.Attribute)
              and it.func.attr == "listdir"
              and _attr_root_name(it.func) in mod.os_aliases):
            self._emit(mod, "GL107", it, "<module>",
                       "os.listdir() order is filesystem-dependent — "
                       "sorted() it before hashing or staging")

    def _gl107_call(self, mod: ModuleInfo, call: ast.Call) -> None:
        # tuple(set(...)) / list(set(...)) / "".join(set(...)) keep the
        # arbitrary order; sorted(set(...)) is the fix and is not flagged
        if isinstance(call.func, ast.Name) and call.func.id in (
                "tuple", "list"):
            if call.args and self._is_set_expr(call.args[0]):
                self._emit(mod, "GL107", call, "<module>",
                           f"{call.func.id}(set(...)) preserves the "
                           f"arbitrary set order — use sorted(...)")
        if isinstance(call.func, ast.Attribute) and call.func.attr == \
                "join" and call.args and self._is_set_expr(call.args[0]):
            self._emit(mod, "GL107", call, "<module>",
                       "join over a set is order-nondeterministic — "
                       "use sorted(...)")

    # ---- cross-cutting contract rules: GL201, GL202, GL203, GL204 ----
    def _check_contracts(self, mod: ModuleInfo) -> None:
        for scope, node in self._scoped_nodes(mod):
            qual = scope.qualname if scope else "<module>"
            self._gl201_env_read(mod, scope, node, qual)
            self._gl303_env_read(mod, scope, node, qual)
            if isinstance(node, ast.Call):
                self._gl203_subprocess(mod, node, qual)
                self._gl204_donation(mod, node, qual)
        # atomic-publish contract: per function scope + module scope
        for fi in mod.functions.values():
            self._gl202_scope(mod, list(self._own_body_walk(fi)),
                              fi.qualname)
        self._gl202_scope(mod, list(self._module_level_nodes(mod)),
                          "<module>")

    def _gl201_env_read(self, mod: ModuleInfo, scope: FuncInfo | None,
                        node: ast.AST, qual: str) -> None:
        name = mod.env_read_name(node)
        if name is None or not _knobs.ENV_READ_RE.match(name):
            return
        knob = _knobs.get(name)
        if knob is None:
            self._emit(mod, "GL201", node, qual,
                       f"env knob {name!r} is not registered in "
                       f"raft_tpu/lint/knobs.py — classify it as "
                       f"key-salted, host-only, or fault-injection before "
                       f"reading it (the docs table and the AOT-salt "
                       f"audit are generated from the registry)")
        elif scope is not None and scope.reachable \
                and knob.classification != _knobs.AOT_KEY:
            self._emit(mod, "GL201", node, qual,
                       f"env knob {name!r} ({knob.classification}) is "
                       f"read inside jit-reachable {qual}(): the value "
                       f"is baked into compiled programs at trace time, "
                       f"invisible to the AOT executable key — classify "
                       f"it 'aot_key' with a salted_via site, or hoist "
                       f"the read out of traced code")

    def _gl203_subprocess(self, mod: ModuleInfo, call: ast.Call,
                          qual: str) -> None:
        fname = mod.subprocess_call(call)
        if fname is None:
            return
        if fname == "Popen":
            self._emit(mod, "GL203", call, qual,
                       "subprocess.Popen carries no hard timeout — a "
                       "hung child wedges the run forever; route through "
                       "resilience.retry.checked_subprocess (or justify "
                       "the raw handle with a suppression)")
            return
        has_timeout = any(
            kw.arg == "timeout" and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is None)
            for kw in call.keywords)
        if not has_timeout:
            self._emit(mod, "GL203", call, qual,
                       f"subprocess.{fname}() without a hard timeout can "
                       f"hang forever (NFS stall, wedged toolchain) — "
                       f"use resilience.retry.checked_subprocess or pass "
                       f"timeout=")

    def _gl204_donation(self, mod: ModuleInfo, call: ast.Call,
                        qual: str) -> None:
        donate_kws = [kw for kw in call.keywords
                      if kw.arg in ("donate_argnums", "donate_argnames")]
        is_jit = mod.transform_of(call.func) == "jit"
        is_partial_jit = (mod.is_partial(call.func) and call.args
                          and mod.transform_of(call.args[0]) == "jit")
        if donate_kws and (is_jit or is_partial_jit):
            self._emit(mod, "GL204", call, qual,
                       "donation on a bare jax.jit is invisible to the "
                       "AOT registry key: a warm process can be served "
                       "an executable compiled under the OTHER aliasing "
                       "contract — route through cache.aot."
                       "cached_compile/cached_callable(jit_kwargs=...), "
                       "whose donation_salt folds the signature into "
                       "every key")
            return
        if not mod.cached_compile_call(call):
            return
        # at a registry call site, literal donate indices must exist in
        # the literal args tuple (JAX validates the same-shape/dtype
        # output alias at compile time; a bad index never gets that far).
        # args may arrive positionally or as a keyword in ANY order
        # relative to jit_kwargs, so resolve it before checking
        args_node = call.args[2] if len(call.args) >= 3 else None
        if args_node is None:
            for kw in call.keywords:
                if kw.arg == "args":
                    args_node = kw.value
                    break
        for kw in call.keywords:
            if kw.arg != "jit_kwargs" or not isinstance(kw.value, ast.Dict):
                continue
            for k, v in zip(kw.value.keys, kw.value.values):
                if not (isinstance(k, ast.Constant)
                        and k.value == "donate_argnums"):
                    continue
                idxs = [n.value for n in ast.walk(v)
                        if isinstance(n, ast.Constant)
                        and isinstance(n.value, int)]
                if isinstance(args_node, ast.Tuple):
                    nargs = len(args_node.elts)
                    for i in idxs:
                        if i >= nargs or i < -nargs:
                            self._emit(
                                mod, "GL204", call, qual,
                                f"donate_argnums {i} is out of range for "
                                f"the {nargs}-argument call site — there "
                                f"is no input buffer to alias")

    # ---- concurrency contract rules: GL301, GL302, GL303 ----
    def _propagate_concurrent(self) -> None:
        """Mark every function host-reachable from a registered concurrent
        entry point (the ROADMAP daemon's request path).  Seeds come from
        ``lint/registry.py``'s ``CONCURRENT_FUNCTIONS`` (dotted names) and
        from in-module ``__graftlint_concurrent__`` declarations; edges
        are the same bare-name references the jit reachability uses PLUS
        module-attribute calls (``_ckpt.store_for(...)``) resolved through
        the import map — a daemon request path crosses modules that way."""
        roots: set = set()
        try:
            from raft_tpu.lint import registry as _registry

            roots.update(getattr(_registry, "CONCURRENT_FUNCTIONS", ()))
        except Exception:       # linting outside the package install
            pass
        work: list[FuncInfo] = []

        def mark(fi: FuncInfo | None) -> None:
            if fi is not None and not fi.concurrent:
                fi.concurrent = True
                work.append(fi)

        for dotted_mod, mod in self.modules.items():
            for fname in mod.concurrent_decls:
                mark(mod.functions.get(fname))
            for r in roots:
                if r.startswith(dotted_mod + "."):
                    mark(mod.functions.get(r[len(dotted_mod) + 1:]))
        while work:
            fi = work.pop()
            for callee in self._referenced_functions(fi):
                mark(callee)
            for callee in self._attr_referenced_functions(fi):
                mark(callee)

    def _attr_referenced_functions(self, fi: FuncInfo):
        """Functions referenced as ``module_alias.func`` from ``fi``'s
        body, resolved through the import map to analyzed modules
        (package ``__init__`` re-exports chased by prefix search)."""
        mod = fi.module
        for node in self._own_body_walk(fi):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and isinstance(node.ctx, ast.Load)):
                continue
            tgt = mod.import_map.get(node.value.id)
            if tgt is None:
                continue
            dotted = tgt[0] if tgt[1] is None else f"{tgt[0]}.{tgt[1]}"
            m2 = self.modules.get(dotted)
            if m2 is not None:
                hit = m2.functions.get(node.attr)
                if hit is not None:
                    yield hit
                    continue
            for dn, m3 in self.modules.items():
                if dn.startswith(dotted + "."):
                    hit = m3.functions.get(node.attr)
                    if hit is not None:
                        yield hit

    # ---- SPMD contract rules: GL401, GL402, GL403, GL404 ----
    def _propagate_multihost(self) -> None:
        """Mark every function host-reachable from a registered multihost
        entry point (the pod-scale sweep path).  Seeds come from
        ``lint/registry.py``'s ``MULTIHOST_FUNCTIONS`` (dotted names) and
        from in-module ``__graftlint_multihost__`` declarations; edges are
        the concurrent propagation's — bare-name references plus
        module-attribute calls resolved through the import map."""
        roots: set = set()
        try:
            from raft_tpu.lint import registry as _registry

            roots.update(getattr(_registry, "MULTIHOST_FUNCTIONS", ()))
        except Exception:       # linting outside the package install
            pass
        work: list[FuncInfo] = []

        def mark(fi: FuncInfo | None) -> None:
            if fi is not None and not fi.multihost:
                fi.multihost = True
                work.append(fi)

        for dotted_mod, mod in self.modules.items():
            for fname in mod.multihost_decls:
                mark(mod.functions.get(fname))
            for r in roots:
                if r.startswith(dotted_mod + "."):
                    mark(mod.functions.get(r[len(dotted_mod) + 1:]))
        while work:
            fi = work.pop()
            for callee in self._referenced_functions(fi):
                mark(callee)
            for callee in self._attr_referenced_functions(fi):
                mark(callee)

    def _propagate_spmd(self) -> None:
        """Mark every function that CONTAINS a collective / SPMD-dispatch
        site, then propagate caller-ward to a fixpoint: a function that
        calls an spmd function is itself a site every host must reach in
        the same order (what GL401's divergent-branch check keys on)."""
        for mod in self.modules.values():
            for fi in mod.functions.values():
                for node in self._own_body_walk(fi):
                    if isinstance(node, ast.Call) and (
                            mod.collective_call(node)
                            or mod.sharded_dispatch(node)):
                        fi.spmd = True
                        break
        all_funcs = [fi for mod in self.modules.values()
                     for fi in mod.functions.values()]
        changed = True
        while changed:
            changed = False
            for fi in all_funcs:
                if fi.spmd:
                    continue
                for callee in self._referenced_functions(fi):
                    if callee.spmd:
                        fi.spmd = changed = True
                        break
                if not fi.spmd:
                    for callee in self._attr_referenced_functions(fi):
                        if callee.spmd:
                            fi.spmd = changed = True
                            break

    def _declared_axes(self) -> set[str]:
        """Every mesh axis name declared ANYWHERE in the linted set:
        ``Mesh(..., axis_names=(...))`` literals plus string defaults of
        ``axis``/``axis_name``/``axis_names`` parameters (the
        ``make_mesh(axis="designs")`` convention).  Repo-wide on purpose —
        meshes are built in one module and consumed in another; the bug
        GL404 exists for is an axis name declared NOWHERE (a typo that
        only fails at dispatch time, on the pod)."""
        axes: set[str] = set()
        for mod in self.modules.values():
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    fn = node.func
                    nm = (fn.attr if isinstance(fn, ast.Attribute)
                          else fn.id if isinstance(fn, ast.Name) else None)
                    if nm not in ("Mesh", "global_mesh", "make_mesh",
                                  "forced_cpu_mesh"):
                        continue
                    for sub in list(node.args) + [k.value
                                                  for k in node.keywords]:
                        for n in ast.walk(sub):
                            if isinstance(n, ast.Constant) and isinstance(
                                    n.value, str):
                                axes.add(n.value)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    args = node.args
                    pos = args.posonlyargs + args.args
                    named = dict(zip(
                        [a.arg for a in pos[len(pos)
                                            - len(args.defaults):]],
                        args.defaults))
                    named.update({a.arg: d for a, d in
                                  zip(args.kwonlyargs, args.kw_defaults)
                                  if d is not None})
                    for pname, d in named.items():
                        if pname in ("axis", "axis_name", "axis_names"):
                            for n in ast.walk(d):
                                if isinstance(n, ast.Constant) and \
                                        isinstance(n.value, str):
                                    axes.add(n.value)
        return axes

    def _divergence_source(self, mod: ModuleInfo, expr: ast.AST,
                           tainted: set[str]) -> str | None:
        """A description when ``expr`` carries a host-divergent value —
        one that can differ BETWEEN the hosts of one pod: an env read
        (``aot_key``-classified knobs pass: key-salted reads move the
        program WITH the value, the GL303 triage precedent), wall clock,
        random, hostname, pid, ``jax.process_index()``, or a name tainted
        by any of those."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in tainted:
                return f"host-divergent value {n.id!r}"
            name = mod.env_read_name(n)
            if name is not None:
                knob = _knobs.get(name)
                if knob is not None and \
                        knob.classification == _knobs.AOT_KEY:
                    continue
                return f"env read {name!r}"
            if not isinstance(n, ast.Call):
                continue
            fn = n.func
            if not isinstance(fn, ast.Attribute):
                continue
            base = _attr_root_name(fn)
            if fn.attr in _DIVERGENT_TIME_FNS and base == "time":
                return f"time.{fn.attr}()"
            if base == "random" or (isinstance(fn.value, ast.Attribute)
                                    and fn.value.attr == "random"):
                return f"random.{fn.attr}()"
            if fn.attr in ("gethostname", "getfqdn") \
                    and base == "socket":
                return f"socket.{fn.attr}()"
            if fn.attr == "node" and base == "platform":
                return "platform.node()"
            if fn.attr == "getpid" and base in mod.os_aliases:
                return "os.getpid()"
            if fn.attr == "process_index" and (
                    mod.is_jax(fn.value) or base in mod.jax_aliases):
                return "jax.process_index()"
        return None

    def _divergent_names(self, mod: ModuleInfo, fi: FuncInfo) -> set[str]:
        """Names in ``fi`` assigned from host-divergent expressions, to a
        fixpoint (mirrors the GL202 durable-taint shape)."""
        tainted: set[str] = set()
        while True:
            changed = False
            for node in self._own_body_walk(fi):
                targets: list = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                if value is None or self._divergence_source(
                        mod, value, tainted) is None:
                    continue
                for t in targets:
                    for nm in _target_names(t):
                        if nm not in tainted:
                            tainted.add(nm)
                            changed = True
            if not changed:
                break
        return tainted

    def _check_spmd(self, mod: ModuleInfo, declared_axes: set[str]) -> None:
        self._gl404_axes(mod, declared_axes)
        for fi in mod.functions.values():
            if isinstance(fi.node, ast.Lambda):
                continue
            self._gl404_divergent_collective(mod, fi)
            if not fi.multihost:
                continue
            self._gl401_function(mod, fi)
            self._gl402_function(mod, fi)
            self._gl403_function(mod, fi)

    def _gl401_function(self, mod: ModuleInfo, fi: FuncInfo) -> None:
        """Host-divergent control flow steering SPMD dispatch: in a
        multihost-reachable function, a branch/loop whose decision can
        differ between hosts, with an SPMD dispatch (or a call into an
        spmd function) somewhere under it.  Lexically-direct collectives
        under a divergent branch are GL404's arm and excluded here."""
        tainted = self._divergent_names(mod, fi)
        qual = fi.qualname
        for node in self._own_body_walk(fi):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                decider = node.test
            elif isinstance(node, ast.For):
                decider = node.iter
            else:
                continue
            src = self._divergence_source(mod, decider, tainted)
            if src is None:
                continue
            target = self._spmd_under(mod, fi, node)
            if target is None:
                continue
            kind = type(node).__name__.lower().replace("exp", " expr")
            self._emit(mod, "GL401", node, qual,
                       f"`{kind}` on {src} steers {target} in {qual}(), "
                       f"which is reachable from a multihost entry "
                       f"point: hosts that disagree on the branch skip "
                       f"or reorder the collective and the pod "
                       f"deadlocks — hoist the decision to staging time "
                       f"(identical on every host), or derive it from "
                       f"key-salted configuration")

    def _spmd_under(self, mod: ModuleInfo, fi: FuncInfo,
                    node: ast.AST) -> str | None:
        """A label when ``node``'s subtree dispatches SPMD work: a
        lexical dispatch site, or a reference to a function marked
        ``spmd`` (reaches a collective through calls)."""
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                d = mod.sharded_dispatch(n)
                if d is not None:
                    return f"an SPMD dispatch ({d})"
                f2 = n.func
                if isinstance(f2, ast.Attribute) and isinstance(
                        f2.value, ast.Name):
                    tgt = mod.import_map.get(f2.value.id)
                    if tgt is not None:
                        dotted = (tgt[0] if tgt[1] is None
                                  else f"{tgt[0]}.{tgt[1]}")
                        for dn, m2 in self.modules.items():
                            if dn == dotted or dn.startswith(dotted + "."):
                                hit = m2.functions.get(f2.attr)
                                if hit is not None and hit.spmd:
                                    return (f"a call into SPMD code "
                                            f"({f2.attr}())")
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                hit = self.resolve_local(mod, fi, n.id)
                cands = [hit] if hit is not None else \
                    self.resolve_external(mod, n.id)
                for c in cands:
                    if c.spmd:
                        return f"a call into SPMD code ({n.id}())"
        return None

    def _gl402_function(self, mod: ModuleInfo, fi: FuncInfo) -> None:
        """Shared-root write collision: in a multihost-reachable
        function, a write whose path derives from a durable root
        (cache/ckpt/obs/ledger — the GL202 taint) and is neither salted
        by ``jax.process_index()`` nor serialized under a lock.  Two
        hosts sharing the root race the same filename; a pid-only suffix
        does NOT pass (pids collide across hosts).  Write sites: ``open``
        in a write mode, ``np.save*``, and atomic-write helpers (the
        tmp+``os.replace`` publishers — atomic per file, but atomicity
        does not serialize two hosts replacing the SAME name)."""
        body = list(self._own_body_walk(fi))
        durable_taint = self._durable_taint(mod, body)
        if not durable_taint["any"]:
            return
        salted = self._salted_names(mod, body)
        qual = fi.qualname

        def durable(expr: ast.AST) -> bool:
            return self._expr_durable(expr, durable_taint["names"])

        def is_salted(expr: ast.AST) -> bool:
            for n in ast.walk(expr):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id in salted:
                    return True
                if isinstance(n, ast.Call) and _terminal_name(n.func) in \
                        _PROCESS_SALT_FNS:
                    return True
            return False

        def flag(call: ast.Call, path_arg: ast.AST, what: str) -> None:
            self._emit(mod, "GL402", call, qual,
                       f"{what} under a durable shared root in {qual}(), "
                       f"reachable from a multihost entry point, with a "
                       f"filename not salted by jax.process_index() and "
                       f"not lock-serialized: two hosts sharing the root "
                       f"clobber each other's artifact (a pid suffix does "
                       f"not help — pids collide across hosts); fold "
                       f"process_index into the name, or serialize under "
                       f"a cross-process lock")

        def check(node: ast.AST, locked: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                held = locked or any(_is_lockish(it.context_expr)
                                     for it in node.items)
                for child in node.body:
                    check(child, held)
                return
            if isinstance(node, ast.Call) and not locked:
                fn = node.func
                nm = _terminal_name(fn)
                if isinstance(fn, ast.Name) and nm == "open" and node.args:
                    mode = None
                    if len(node.args) >= 2 and isinstance(
                            node.args[1], ast.Constant):
                        mode = node.args[1].value
                    for kw in node.keywords:
                        if kw.arg == "mode" and isinstance(
                                kw.value, ast.Constant):
                            mode = kw.value.value
                    if isinstance(mode, str) \
                            and any(c in mode for c in "wax+") \
                            and durable(node.args[0]) \
                            and not is_salted(node.args[0]):
                        flag(node, node.args[0],
                             f"direct {mode!r}-mode open()")
                elif isinstance(fn, ast.Attribute) \
                        and fn.attr in _NP_WRITE_FNS \
                        and mod.is_numpy(_attr_root(fn)) and node.args \
                        and durable(node.args[0]) \
                        and not is_salted(node.args[0]):
                    flag(node, node.args[0], f"np.{fn.attr}()")
                elif nm is not None and "atomic_write" in nm \
                        and node.args and durable(node.args[0]) \
                        and not is_salted(node.args[0]):
                    flag(node, node.args[0], f"{nm}()")
            for child in ast.iter_child_nodes(node):
                check(child, locked)

        for stmt in fi.node.body:
            check(stmt, False)

    def _durable_taint(self, mod: ModuleInfo, body: list) -> dict:
        """The GL202 durable-root taint over one scope: ``names`` tainted
        by a durable-root call, ``any`` whether the scope touches a
        durable root at all (cheap early-out for GL402)."""
        tainted: set[str] = set()
        while True:
            changed = False
            for node in body:
                targets: list = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                if value is None or not self._expr_durable(value, tainted):
                    continue
                for t in targets:
                    for nm in _target_names(t):
                        if nm not in tainted:
                            tainted.add(nm)
                            changed = True
            if not changed:
                break
        any_durable = bool(tainted) or any(
            isinstance(n, ast.Call)
            and _terminal_name(n.func) in _DURABLE_ROOT_FNS
            for node in body for n in ast.walk(node))
        return {"names": tainted, "any": any_durable}

    @staticmethod
    def _expr_durable(expr: ast.AST, tainted: set[str]) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and \
                    _terminal_name(n.func) in _DURABLE_ROOT_FNS:
                return True
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in tainted:
                return True
        return False

    def _salted_names(self, mod: ModuleInfo, body: list) -> set[str]:
        """Names carrying a per-host salt: assigned from an expression
        containing ``jax.process_index()`` / ``process_tag(...)`` (or an
        already-salted name), to a fixpoint."""
        salted: set[str] = set()

        def has_salt(expr: ast.AST) -> bool:
            for n in ast.walk(expr):
                if isinstance(n, ast.Call) and _terminal_name(n.func) in \
                        _PROCESS_SALT_FNS:
                    return True
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id in salted:
                    return True
            return False

        while True:
            changed = False
            for node in body:
                targets: list = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                if value is None or not has_salt(value):
                    continue
                for t in targets:
                    for nm in _target_names(t):
                        if nm not in salted:
                            salted.add(nm)
                            changed = True
            if not changed:
                break
        return salted

    def _gl403_function(self, mod: ModuleInfo, fi: FuncInfo) -> None:
        """Unsharded large operand on a multihost path.  Arm 1: a batched
        dispatch — ``jit(vmap(f))`` or ``cached_*(tag, vmap(f), args)`` —
        with no sharding information (``in_shardings``/``mesh=``): the
        batch-leading operand replicates onto every device instead of
        sharding the batch axis (ROADMAP item 1's discipline).  Arm 2: a
        dispatched function closing over a LARGE module-built constant
        (literal-shape product >= ``_BIG_CONST_ELEMS``) not routed
        through ``consts=`` — it silently replicates per device and
        bypasses the registry key."""
        qual = fi.qualname
        big = self._large_consts(mod, fi)
        for node in self._own_body_walk(fi):
            if not isinstance(node, ast.Call):
                continue
            kws = {kw.arg for kw in node.keywords}
            fn_arg = None
            if mod.cached_compile_call(node) and len(node.args) >= 2:
                fn_arg = node.args[1]
                if isinstance(fn_arg, ast.Call) \
                        and mod.transform_of(fn_arg.func) == "vmap" \
                        and "mesh" not in kws:
                    self._emit(mod, "GL403", node, qual,
                               f"batched registry compile in {qual}() "
                               f"(reachable from a multihost entry "
                               f"point) carries no mesh= — the "
                               f"batch-leading operand replicates onto "
                               f"every device; pass the mesh so the "
                               f"batch axis shards (and the topology "
                               f"salts the AOT key)")
            elif mod.transform_of(node.func) == "jit" and node.args:
                fn_arg = node.args[0]
                if isinstance(fn_arg, ast.Call) \
                        and mod.transform_of(fn_arg.func) == "vmap" \
                        and not (kws & {"in_shardings", "out_shardings"}):
                    self._emit(mod, "GL403", node, qual,
                               f"jit(vmap(...)) in {qual}() (reachable "
                               f"from a multihost entry point) carries "
                               f"no in_shardings — the batch-leading "
                               f"operand replicates onto every device "
                               f"instead of sharding the batch axis")
            if fn_arg is None or not big:
                continue
            consts_decl: set[str] = set()
            for kw in node.keywords:
                if kw.arg == "consts":
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Name):
                            consts_decl.add(n.id)
            for captured in self._closure_refs(mod, fi, fn_arg):
                if captured in big and captured not in consts_decl:
                    self._emit(mod, "GL403", node, qual,
                               f"dispatched function closes over large "
                               f"constant {captured!r} (~{big[captured]} "
                               f"elements) in {qual}() — it replicates "
                               f"per device and bypasses the registry "
                               f"key; pass it through consts= (keyed, "
                               f"explicitly replicated) or shard it as "
                               f"an operand")

    def _large_consts(self, mod: ModuleInfo, fi: FuncInfo) -> dict:
        """Names in ``fi`` bound to a large literal-shaped array
        constructor (``jnp.zeros((64, 64))``-style): name -> element
        count."""
        out: dict = {}
        for node in self._own_body_walk(fi):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if not (isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Attribute)
                    and v.func.attr in _BIG_ARRAY_CTORS
                    and (mod.is_numpy(v.func.value)
                         or mod.is_jnp(v.func.value))):
                continue
            elems = 1
            ints = [n.value for n in ast.walk(v)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, int)]
            for i in ints:
                elems *= max(i, 1)
            if not ints or elems < _BIG_CONST_ELEMS:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = elems
        return out

    def _closure_refs(self, mod: ModuleInfo, fi: FuncInfo, fn_arg: ast.AST):
        """Free names referenced by the function(s) dispatched in
        ``fn_arg``: nested defs / lambdas resolved in ``fi``'s scope;
        their own parameters excluded."""
        seen: set[str] = set()
        funcs: list[FuncInfo] = []
        for n in ast.walk(fn_arg):
            if isinstance(n, ast.Lambda):
                hit = mod.lambda_infos.get(id(n))
                if hit is not None:
                    funcs.append(hit)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                hit = self.resolve_local(mod, fi, n.id)
                if hit is not None and hit.parent is fi:
                    funcs.append(hit)
        for f in funcs:
            params = set(f.params)
            body = ([f.node.body] if isinstance(f.node, ast.Lambda)
                    else list(f.node.body))
            for b in body:
                for n in ast.walk(b):
                    if isinstance(n, ast.Name) and isinstance(
                            n.ctx, ast.Load) and n.id not in params \
                            and n.id not in seen:
                        seen.add(n.id)
                        yield n.id

    def _gl404_axes(self, mod: ModuleInfo, declared: set[str]) -> None:
        """Mesh-axis contract, arm 1: every axis name used in a
        ``PartitionSpec`` or collective must be declared by SOME mesh in
        the linted set — a typo'd axis fails at dispatch time, on the
        pod.  Skipped entirely when no mesh is declared anywhere (a
        library linted standalone cannot know its caller's axes)."""
        if not declared:
            return
        for scope, node in self._scoped_nodes(mod):
            if not isinstance(node, ast.Call):
                continue
            qual = scope.qualname if scope else "<module>"
            used: list[tuple[str, ast.AST]] = []
            if mod.partition_spec_call(node):
                for a in node.args:
                    for n in ast.walk(a):
                        if isinstance(n, ast.Constant) and isinstance(
                                n.value, str):
                            used.append((n.value, n))
            elif mod.collective_call(node):
                for a in list(node.args[1:]) + [
                        kw.value for kw in node.keywords
                        if kw.arg in ("axis_name", "axis_index_groups")]:
                    for n in ast.walk(a):
                        if isinstance(n, ast.Constant) and isinstance(
                                n.value, str):
                            used.append((n.value, n))
            for axis, n in used:
                if axis not in declared:
                    self._emit(mod, "GL404", node, qual,
                               f"axis name {axis!r} is not declared by "
                               f"any Mesh in the linted tree (declared: "
                               f"{sorted(declared)}) — a typo'd axis "
                               f"fails at dispatch time, on the pod")

    def _gl404_divergent_collective(self, mod: ModuleInfo,
                                    fi: FuncInfo) -> None:
        """Mesh-axis contract, arm 2: a collective lexically inside a
        branch whose decision is host-divergent — only SOME hosts enter
        the branch, so the collective's participants never assemble and
        the program deadlocks.  Checked everywhere (not just multihost
        paths): the pattern is wrong in any SPMD program."""
        tainted = self._divergent_names(mod, fi)
        qual = fi.qualname
        for node in self._own_body_walk(fi):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                decider = node.test
            else:
                continue
            src = self._divergence_source(mod, decider, tainted)
            if src is None:
                continue
            for n in ast.walk(node):
                if n is decider or any(n is d for d in ast.walk(decider)):
                    continue
                if isinstance(n, ast.Call):
                    coll = mod.collective_call(n)
                    if coll is not None:
                        self._emit(mod, "GL404", n, qual,
                                   f"collective lax.{coll}() inside a "
                                   f"branch on {src} in {qual}(): hosts "
                                   f"that skip the branch never join the "
                                   f"collective — deadlock; run the "
                                   f"collective unconditionally and mask "
                                   f"the contribution instead")

    def _gl303_env_read(self, mod: ModuleInfo, scope: FuncInfo | None,
                        node: ast.AST, qual: str) -> None:
        if scope is None or not scope.concurrent:
            return
        name = mod.env_read_name(node)
        if name is None or not _knobs.ENV_READ_RE.match(name):
            return
        self._emit(mod, "GL303", node, qual,
                   f"env knob {name!r} is read inside {qual}(), which is "
                   f"reachable from a registered concurrent entry point: "
                   f"a resident process must snapshot knobs at arm time — "
                   f"a mid-process env change silently diverges behavior "
                   f"from the AOT key it was salted into; hoist the read "
                   f"to arm/configuration time, or triage with the "
                   f"single-threaded-by-contract reason")

    def _check_concurrency(self, mod: ModuleInfo) -> None:
        """GL301/GL302 over every function: module-global mutable state
        must be mutated under a lock (``with <lock>:`` lexically
        enclosing), be ``threading.local`` (attribute stores on it are
        not container mutations and pass), or carry a suppression naming
        the single-threaded contract.  Module-scope init is exempt — the
        import lock serializes it."""
        if not mod.mutable_globals:
            return
        for fi in mod.functions.values():
            if isinstance(fi.node, ast.Lambda):
                continue
            self._check_gl30x_function(mod, fi)

    def _check_gl30x_function(self, mod: ModuleInfo, fi: FuncInfo) -> None:
        bound = _locally_bound(fi)
        qual = fi.qualname

        def global_name(n: ast.AST) -> str | None:
            if isinstance(n, ast.Name) and n.id in mod.mutable_globals \
                    and n.id not in bound:
                return n.id
            return None

        # globals this function STORES into (subscript assign / mutator
        # method), at any lock depth — the GL302 ``.get``-then-assign arm
        # only fires when the check-then-act really acts on the dict
        stored: set = set()
        for node in self._own_body_walk(fi):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        g = global_name(t.value)
                        if g:
                            stored.add(g)
            elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr in _MUTATOR_METHODS:
                g = global_name(node.func.value)
                if g:
                    stored.add(g)

        def check(node: ast.AST, locked: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return      # own FuncInfo; a lexical lock does not transfer
            if isinstance(node, (ast.With, ast.AsyncWith)):
                held = locked or any(_is_lockish(it.context_expr)
                                     for it in node.items)
                for it in node.items:
                    check(it.context_expr, locked)
                for child in node.body:
                    check(child, held)
                return
            if not locked:
                self._gl301_mutation(mod, fi, node, global_name, qual)
                self._gl302_check_then_act(mod, node, global_name, stored,
                                           qual)
            for child in ast.iter_child_nodes(node):
                check(child, locked)

        for stmt in fi.node.body:
            check(stmt, False)

    def _gl301_mutation(self, mod: ModuleInfo, fi: FuncInfo, node: ast.AST,
                        global_name, qual: str) -> None:
        g = kind = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript) and global_name(t.value):
                    g, kind = global_name(t.value), "subscript-assign"
                elif isinstance(node, ast.AugAssign) and global_name(t):
                    g, kind = global_name(t), "augmented-assign"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and global_name(t.value):
                    g, kind = global_name(t.value), "del"
        elif isinstance(node, ast.Call) and isinstance(node.func,
                                                       ast.Attribute) \
                and node.func.attr in _MUTATOR_METHODS:
            if global_name(node.func.value):
                g = global_name(node.func.value)
                kind = f".{node.func.attr}()"
        if g is not None:
            self._emit(mod, "GL301", node, qual,
                       f"bare {kind} mutation of module-global {g!r} in "
                       f"{qual}() outside any lock: a multi-threaded "
                       f"resident process interleaves these writes — "
                       f"guard with `with <lock>:`, make the state "
                       f"threading.local, or suppress naming the "
                       f"single-threaded contract")

    def _gl302_check_then_act(self, mod: ModuleInfo, node: ast.AST,
                              global_name, stored: set, qual: str) -> None:
        # form 1: `if k not in d:` with a d[...] = store in the body
        if isinstance(node, ast.If) and isinstance(node.test, ast.Compare) \
                and len(node.test.ops) == 1 \
                and isinstance(node.test.ops[0], ast.NotIn):
            g = global_name(node.test.comparators[0])
            if g:
                acts = any(
                    isinstance(n, ast.Assign) and any(
                        isinstance(t, ast.Subscript)
                        and global_name(t.value) == g
                        for t in n.targets)
                    for b in node.body for n in ast.walk(b))
                if acts:
                    self._emit(mod, "GL302", node, qual,
                               f"check-then-act memoization on "
                               f"module-global {g!r}: `if k not in "
                               f"{g}: {g}[k] = ...` double-computes "
                               f"under concurrent callers — hold one "
                               f"lock across the check AND the insert "
                               f"(single-flight)")
            return
        # form 2: an unlocked `d.get(k)` in a function that also stores
        # into d — the AOT-memo get-or-compute shape
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr == "get":
            g = global_name(node.func.value)
            if g and g in stored:
                self._emit(mod, "GL302", node, qual,
                           f"{g}.get(...) outside a lock in {qual}(), "
                           f"which also stores into {g!r}: the "
                           f"get-or-compute races a concurrent insert "
                           f"(double compile / lost update) — hold one "
                           f"lock across check and act, or single-flight "
                           f"the compute")

    def _module_level_nodes(self, mod: ModuleInfo):
        """Module-scope statements (function/lambda bodies excluded —
        each is checked in its own scope)."""
        stack = list(mod.tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _gl202_scope(self, mod: ModuleInfo, body: list, qual: str) -> None:
        """Atomic-publish contract for one scope: a write-mode ``open``
        (or ``np.save*``) whose path derives from a durable root call
        (``config.subdir``/``cache_dir``/``resolve_dir``/checkpoint
        ``root``/...) is a torn-artifact hazard; the tmp +
        ``os.replace`` idiom (``tempfile.mkstemp`` in the same
        directory) writes through an untainted name and passes."""
        tainted: set[str] = set()

        def durable(expr: ast.AST) -> bool:
            for n in ast.walk(expr):
                if isinstance(n, ast.Call):
                    fn = n.func
                    nm = (fn.id if isinstance(fn, ast.Name)
                          else fn.attr if isinstance(fn, ast.Attribute)
                          else None)
                    if nm in _DURABLE_ROOT_FNS:
                        return True
                elif isinstance(n, ast.Name) and isinstance(n.ctx,
                                                            ast.Load) \
                        and n.id in tainted:
                    return True
            return False

        while True:   # fixpoint over chained assignments: body nodes are
            # in stack-pop (non-source) order, so one pass may propagate
            # only a single link of a join chain — iterate until stable
            # (terminates: taint only grows, bounded by the name count)
            changed = False
            for node in body:
                targets: list = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                if value is None or not durable(value):
                    continue
                for t in targets:
                    for nm in _target_names(t):
                        if nm not in tainted:
                            tainted.add(nm)
                            changed = True
            if not changed:
                break

        for node in body:
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "open" and node.args:
                mode = None
                if len(node.args) >= 2 and isinstance(node.args[1],
                                                      ast.Constant):
                    mode = node.args[1].value
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value,
                                                       ast.Constant):
                        mode = kw.value.value
                if isinstance(mode, str) and any(c in mode for c in "wax+") \
                        and durable(node.args[0]):
                    self._emit(mod, "GL202", node, qual,
                               f"direct {mode!r}-mode open() on a path "
                               f"under a durable cache/checkpoint root — "
                               f"a kill mid-write leaves a truncated "
                               f"artifact; publish via tempfile.mkstemp "
                               f"in the same directory + os.replace")
            elif isinstance(fn, ast.Attribute) and fn.attr in _NP_WRITE_FNS \
                    and mod.is_numpy(_attr_root(fn)) and node.args \
                    and durable(node.args[0]):
                self._emit(mod, "GL202", node, qual,
                           f"np.{fn.attr}() writes directly to a path "
                           f"under a durable cache/checkpoint root — a "
                           f"kill mid-write leaves a truncated artifact "
                           f"a later np.load would crash on; write to a "
                           f"tempfile.mkstemp handle and os.replace into "
                           f"place")

    # ---- traced-function rules: GL101, GL102, GL103, GL106 ----
    def _check_traced_function(self, fi: FuncInfo) -> None:
        mod = fi.module
        traced = self._traced_names(fi)
        qual = fi.qualname
        for node in self._own_body_walk(fi):
            if isinstance(node, ast.Call):
                self._traced_call_rules(mod, fi, node, traced, qual)
            elif isinstance(node, (ast.If, ast.While, ast.Assert,
                                   ast.IfExp)):
                test = node.test
                name = self._first_traced_mention(mod, test, traced)
                if name is not None:
                    kind = type(node).__name__.lower()
                    self._emit(mod, "GL103", node, qual,
                               f"Python `{kind}` on traced value {name!r} "
                               f"inside jit-reachable {qual}() — branch "
                               f"decisions must be jnp.where/lax.cond")
            elif isinstance(node, ast.For):
                name = self._first_traced_mention(mod, node.iter, traced)
                if name is not None:
                    self._emit(mod, "GL103", node, qual,
                               f"Python `for` over traced value {name!r} "
                               f"inside jit-reachable {qual}() — use "
                               f"lax.scan/fori_loop")

    def _traced_call_rules(self, mod, fi, node: ast.Call, traced, qual):
        func = node.func
        arg_name = None
        for a in list(node.args) + [k.value for k in node.keywords]:
            arg_name = self._first_traced_mention(mod, a, traced)
            if arg_name is not None:
                break
        # GL106: host sync primitives
        if isinstance(func, ast.Name) and func.id == "print":
            self._emit(mod, "GL106", node, qual,
                       f"print() inside jit-reachable {qual}() executes at "
                       f"trace time only (or syncs) — use jax.debug.print")
            return
        if isinstance(func, ast.Attribute):
            if func.attr in _HOST_SYNC_METHODS and arg_name is None:
                base_name = self._first_traced_mention(mod, func.value,
                                                      traced)
                if base_name is not None:
                    self._emit(mod, "GL106", node, qual,
                               f".{func.attr}() on traced value "
                               f"{base_name!r} inside {qual}() forces a "
                               f"host<->device sync")
                    return
            if func.attr == "device_get" and self._jaxish(mod, func.value) \
                    and arg_name is not None:
                self._emit(mod, "GL106", node, qual,
                           f"jax.device_get on traced value {arg_name!r} "
                           f"inside {qual}() forces a host sync")
                return
            # numpy calls
            root = _attr_root(func)
            if mod.is_numpy(root):
                if arg_name is None:
                    return
                if func.attr in ("asarray", "array", "copy"):
                    self._emit(mod, "GL106", node, qual,
                               f"np.{func.attr}() on traced value "
                               f"{arg_name!r} inside {qual}() pulls the "
                               f"array to host (TracerArrayConversionError "
                               f"under jit)")
                else:
                    self._emit(mod, "GL101", node, qual,
                               f"numpy call np.{func.attr}() receives "
                               f"traced value {arg_name!r} inside "
                               f"jit-reachable {qual}() — use the jnp "
                               f"equivalent")
                return
        # GL102: python scalar casts
        if isinstance(func, ast.Name) and func.id in ("float", "int",
                                                      "bool", "complex"):
            if arg_name is not None:
                self._emit(mod, "GL102", node, qual,
                           f"{func.id}() on traced value {arg_name!r} "
                           f"inside jit-reachable {qual}() concretizes the "
                           f"tracer (ConcretizationTypeError / host sync)")

    def _jaxish(self, mod: ModuleInfo, node: ast.AST) -> bool:
        return mod.is_jax(node) or mod.is_jnp(node)

    # ---- taint --------------------------------------------------------
    def _traced_names(self, fi: FuncInfo) -> set[str]:
        """Parameters (minus statics) + lexically enclosing traced names +
        names assigned from traced expressions, to a fixpoint."""
        mod = fi.module
        traced: set[str] = set()
        scope = fi
        while scope is not None:
            if scope.reachable:
                if isinstance(scope.node, ast.Lambda):
                    traced |= set(_param_names(scope.node.args))
                else:
                    traced |= (set(scope.params) - scope.static_params)
            scope = scope.parent
        traced -= fi.static_params
        traced -= self._literal_call_statics(fi)
        if isinstance(fi.node, ast.Lambda):
            return traced
        for _ in range(3):  # small fixpoint: handles chained assignments
            changed = False
            for node in self._own_body_walk(fi):
                targets: list[ast.AST] = []
                value: ast.AST | None = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                elif isinstance(node, (ast.For,)):
                    targets, value = [node.target], node.iter
                if value is None:
                    continue
                if self._first_traced_mention(mod, value, traced) is None:
                    continue
                for t in targets:
                    for name in _target_names(t):
                        if name not in traced:
                            traced.add(name)
                            changed = True
            if not changed:
                break
        return traced

    def _literal_call_statics(self, fi: FuncInfo) -> set[str]:
        """For a nested def only ever CALLED directly by its parent (never
        passed around), parameters that receive a literal constant at
        every call site are static Python values, not tracers — e.g.
        ``term(0, 0)`` selectors in an unrolled complex einsum."""
        if fi.parent is None or isinstance(fi.node, ast.Lambda):
            return set()
        name = fi.node.name
        calls: list[ast.Call] = []
        for node in self._own_body_walk(fi.parent):
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Name) \
                    and node.func.id == name:
                calls.append(node)
            elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                           ast.Load) \
                    and node.id == name:
                if not any(node is c.func for c in calls):
                    return set()        # escapes as a value: keep traced
        if not calls:
            return set()
        static: set[str] = set()
        pos_params = [a.arg for a in fi.node.args.posonlyargs
                      + fi.node.args.args]
        for idx, pname in enumerate(pos_params):
            vals = []
            for c in calls:
                if idx < len(c.args):
                    vals.append(c.args[idx])
                else:
                    vals.extend(k.value for k in c.keywords
                                if k.arg == pname)
            if vals and all(isinstance(v, ast.Constant) for v in vals):
                static.add(pname)
        return static

    def _first_traced_mention(self, mod: ModuleInfo, expr: ast.AST,
                              traced: set[str]) -> str | None:
        """First traced name mentioned in ``expr`` outside static-under-
        trace contexts (shape/dtype/ndim reads, len()/isinstance(),
        ``x is None`` checks)."""
        if not traced:
            return None
        skip: set[int] = set()

        def mark_skip(n: ast.AST) -> None:
            for ch in ast.walk(n):
                skip.add(id(ch))

        for n in ast.walk(expr):
            if id(n) in skip:
                continue
            if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                mark_skip(n)
            elif isinstance(n, ast.Call):
                fn = n.func
                fname = None
                if isinstance(fn, ast.Name):
                    fname = fn.id
                elif isinstance(fn, ast.Attribute):
                    fname = fn.attr
                if fname in _STATIC_CALLS:
                    mark_skip(n)
            elif isinstance(n, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in n.ops) and all(
                    isinstance(c, ast.Constant) and c.value is None
                    for c in n.comparators):
                mark_skip(n)
        for n in ast.walk(expr):
            if id(n) in skip:
                continue
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in traced:
                return n.id
        return None


def _target_names(t: ast.AST):
    """Names an assignment target stores into: ``br[j] = x`` stores into
    ``br`` (the index ``j`` is only read, so it must not be tainted)."""
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _target_names(e)
    elif isinstance(t, ast.Starred):
        yield from _target_names(t.value)
    elif isinstance(t, (ast.Subscript, ast.Attribute)):
        yield from _target_names(t.value)


def _is_lockish(expr: ast.AST) -> bool:
    """Is ``with <expr>:`` a lock acquisition?  Judged by the terminal
    identifier (``_lock``, ``self._lock``, ``cv``-style names excluded):
    any name mentioning lock/mutex, plus the threading synchronization
    constructors — the module convention every guarded global in this
    package already follows (``_lock = threading.Lock()``)."""
    node = expr
    if isinstance(node, ast.Call):
        node = node.func
    name = (node.attr if isinstance(node, ast.Attribute)
            else node.id if isinstance(node, ast.Name) else None)
    if name is None:
        return False
    low = name.lower()
    return ("lock" in low or "mutex" in low
            or name in ("Condition", "Semaphore", "BoundedSemaphore"))


def _bound_target_names(t: ast.AST):
    """Names a target BINDS (unlike :func:`_target_names`, a subscript or
    attribute store does not bind — ``d[k] = v`` mutates, not binds)."""
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _bound_target_names(e)
    elif isinstance(t, ast.Starred):
        yield from _bound_target_names(t.value)


def _locally_bound(fi: FuncInfo) -> set:
    """Names shadowing a module global inside ``fi``: parameters plus
    every locally-bound name, minus explicit ``global`` declarations."""
    bound = set(fi.params)
    global_decls: set = set()
    if isinstance(fi.node, ast.Lambda):
        return bound
    for node in Analyzer._own_body_walk(fi):
        if isinstance(node, ast.Global):
            global_decls.update(node.names)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                bound.update(_bound_target_names(t))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                               ast.NamedExpr)):
            bound.update(_bound_target_names(node.target))
        elif isinstance(node, ast.For):
            bound.update(_bound_target_names(node.target))
        elif isinstance(node, ast.withitem) and node.optional_vars:
            bound.update(_bound_target_names(node.optional_vars))
        elif isinstance(node, ast.comprehension):
            bound.update(_bound_target_names(node.target))
    return bound - global_decls


def _dotted_name(relpath: str) -> str:
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace(os.sep, ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def collect_py_files(paths: list[str], root: str) -> list[str]:
    """Expand lint targets to .py files.  A target that does not exist
    raises — a gate that silently lints nothing because of a typo'd path
    would report green forever."""
    out: list[str] = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(ap):
            raise FileNotFoundError(
                f"lint target {p!r} does not exist under {root!r}")
        if os.path.isdir(ap):
            for dirpath, dirnames, filenames in sorted(os.walk(ap)):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in ("__pycache__",))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif ap.endswith(".py"):
            out.append(ap)
        else:
            raise ValueError(f"lint target {p!r} is neither a directory "
                             f"nor a .py file")
    return out


def lint_paths(paths: list[str], root: str) -> list[Violation]:
    """Run every rule over the .py files under ``paths`` (dirs recurse)."""
    files = collect_py_files(paths, root)
    return Analyzer(files, root).run()


def collect_env_reads(paths: list[str], root: str) -> dict:
    """Every ``RAFT_TPU_*``/``JAX_*``/``XLA_FLAGS`` env read under
    ``paths``: ``{knob name: ["relpath:line", ...]}``.  The knob-registry
    drift test uses this to pin "every read is registered AND every
    registered raft knob is actually read" — a registry entry cannot go
    stale in either direction."""
    files = collect_py_files(paths, root)
    a = Analyzer(files, root)
    out: dict = {}
    for mod in a.modules.values():
        for _scope, node in a._scoped_nodes(mod):
            name = mod.env_read_name(node)
            if name is not None and _knobs.ENV_READ_RE.match(name):
                out.setdefault(name, []).append(
                    f"{mod.relpath}:{getattr(node, 'lineno', 0)}")
    return out
