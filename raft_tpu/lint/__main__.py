import sys

from raft_tpu.lint.cli import main

sys.exit(main())
