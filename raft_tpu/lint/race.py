"""Deterministic runtime race harness for the daemon-path shared state.

The GL3xx static rules (:mod:`raft_tpu.lint.rules`) prove the locking
*discipline*; this harness proves the locks actually *work*: N threads
hammer every concurrency-contract surface the ROADMAP resident solver
service will share, with ``sys.setswitchinterval`` cranked tiny so the
GIL hands off every few bytecodes (the preemption schedule is what makes
the pre-fix races reproduce deterministically in seconds instead of
once a week in production), and every assertion is EXACT — counters, not
tolerances:

* **AOT single-flight** — N threads request the same ``cached_compile``
  key concurrently (and pairs of threads contend on distinct keys):
  exactly ONE compile per key (``compile_count``), every caller handed
  the same executable object.  Pre-fix, the ``_mem`` get-or-compute
  double-compiled under contention.
* **compile-event counters** — writer threads record compile events
  while a resetter clears the window: counts never tear (no negative or
  double-counted window), and an uncontended phase counts exactly.
  Pre-fix, ring and counter were cleared non-atomically.
* **metrics / span publish** — N×M counter increments, histogram
  observations and nested spans, with a concurrent snapshot reader:
  final values exact, histogram bucket sums == totals, and the Chrome
  trace / snapshot JSON round-trips (zero-corrupt exports).
* **ChunkStore save/resume** — writer threads checkpoint disjoint chunk
  sets into ONE store: the manifest ends complete (no entry dropped by
  the read-modify-write race the per-store lock closes), every chunk
  resumes content-hash-clean in a fresh store, zero corrupt.
* **fault counters** — ``hang_subprocess:K`` consumed from N threads
  fires exactly K times (the counted-fault check-then-act).
* **serve micro-batcher** — N submitter threads race the serve loop's
  queue (two buckets, capacity closes) while one drainer pops batches
  and a concurrent ``close()`` ends intake: every accepted lane drains
  EXACTLY once (no lane lost at the submit/close boundary, none
  duplicated by a double pop), batches never exceed capacity, and
  per-bucket FIFO order holds within each batch.

``make race-smoke`` wraps ``python -m raft_tpu.lint.race`` (< 60 s CPU;
CI fast job, next to the cache/hetero/obs smokes).  Prints one JSON
line; exit 0/1.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

#: GIL handoff interval during the hammer phases (default is 5 ms; this
#: forces a potential preemption between nearly every pair of bytecodes,
#: the schedule under which the pre-fix races reproduce deterministically)
SWITCH_INTERVAL = 1e-6

THREADS = 8


def _run_threads(n: int, target) -> list:
    """Start ``n`` threads on ``target(i)`` behind one barrier (so the
    hammer really is concurrent, not serialized by startup skew); join
    them and return the raised-exception strings."""
    barrier = threading.Barrier(n)
    errors: list = []

    def wrap(i):
        try:
            barrier.wait(timeout=30)
            target(i)
        except Exception as e:      # noqa: BLE001 - reported, not masked
            errors.append(f"thread {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    errors.extend(f"thread {t.name} did not join" for t in threads
                  if t.is_alive())
    return errors


def _check(out: dict, name: str, cond: bool, detail: str) -> None:
    out.setdefault("checks", {})[name] = bool(cond)
    if not cond:
        out.setdefault("failures", []).append(f"{name}: {detail}")


def scenario_aot_single_flight(cache_dir: str) -> dict:
    """Same-key and distinct-key contention on ``cached_compile``."""
    import jax.numpy as jnp

    from raft_tpu.cache import aot, config

    out: dict = {}
    config.enable(cache_dir)
    aot.clear_memory()
    args = (jnp.arange(8, dtype=jnp.float32),)

    def fn(x):
        return x * 2.0 + 1.0

    # same key from every thread
    results: list = [None] * THREADS
    errors = _run_threads(
        THREADS,
        lambda i: results.__setitem__(
            i, aot.cached_compile("race_same", fn, args)))
    _check(out, "same_key_no_errors", not errors, "; ".join(errors))
    _check(out, "same_key_one_compile",
           aot.compile_count("race_same") == 1,
           f"compile_count={aot.compile_count('race_same')} (want 1)")
    _check(out, "same_key_one_executable",
           len({id(r) for r in results}) == 1,
           "threads received different executable objects")

    # distinct keys, each contended by a pair of threads
    n_keys = THREADS // 2

    def worker(i):
        k = i % n_keys
        aot.cached_compile(f"race_k{k}", fn, args, extra=("k", k))

    errors = _run_threads(THREADS, worker)
    _check(out, "distinct_keys_no_errors", not errors, "; ".join(errors))
    per_key = {k: aot.compile_count(f"race_k{k}") for k in range(n_keys)}
    _check(out, "distinct_keys_one_compile_each",
           all(v == 1 for v in per_key.values()),
           f"per-key compile counts {per_key} (want all 1)")
    out["compile_counts"] = aot.compile_counts()
    aot.clear_memory()
    config.disable()
    return out


def scenario_compile_event_counters() -> dict:
    """Ring + counter consistency under concurrent record/reset."""
    from raft_tpu.cache import aot

    out: dict = {}
    aot.reset_compile_events()
    writers, per_writer = 4, 3000
    stop = threading.Event()
    torn: list = []

    def resetter():
        while not stop.is_set():
            aot.reset_compile_events()
            # tear invariant (this thread is the ONLY resetter, so no
            # clear can land between its two reads): every event visible
            # in the ring carried its counter increment atomically under
            # the events lock, and the counter is monotone between
            # resets — so a count read AFTER the ring read can never be
            # smaller.  Pre-fix, the non-atomic reset orphaned the
            # events appended between ring.clear() and counts.clear()
            # (ring entries whose increments were wiped), making
            # count < len(ring) observable.
            n_ring = len(aot.compile_events("race_evt"))
            c = aot.compile_count("race_evt")
            if c < n_ring:
                torn.append(f"count {c} < ring {n_ring}")

    rt = threading.Thread(target=resetter)
    rt.start()
    errors = _run_threads(
        writers,
        lambda i: [aot._record_compile("race_evt")
                   for _ in range(per_writer)])
    stop.set()
    rt.join(timeout=30)
    _check(out, "reset_phase_no_errors", not errors and not torn,
           "; ".join(errors + torn))
    aot.reset_compile_events()
    _check(out, "reset_zeroes", aot.compile_count() == 0
           and aot.compile_events() == [], "reset left residue")
    # uncontended-by-reset phase: the count must be EXACT
    errors = _run_threads(
        writers,
        lambda i: [aot._record_compile("race_evt")
                   for _ in range(per_writer)])
    total = aot.compile_count("race_evt")
    _check(out, "exact_count", not errors and total == writers * per_writer,
           f"count {total} != {writers * per_writer}; {errors}")
    out["recorded"] = total
    aot.reset_compile_events()
    return out


def scenario_metrics_and_spans() -> dict:
    """Exact counters/histograms/span roll-ups + zero-corrupt exports."""
    from raft_tpu.obs import metrics, trace

    out: dict = {}
    metrics.reset()
    trace.reset()
    per_thread = 2000
    stop = threading.Event()
    corrupt: list = []

    def sampler():
        while not stop.is_set():
            try:
                snap = metrics.snapshot()
                json.dumps(snap)
                for h in snap.get("histograms", {}).values():
                    if sum(n for _, n in h["buckets"]) != h["count"]:
                        corrupt.append("histogram bucket sum != count")
                json.dumps(trace.chrome_trace())
            except Exception as e:  # noqa: BLE001
                corrupt.append(f"{type(e).__name__}: {e}")

    st = threading.Thread(target=sampler)
    st.start()

    def worker(i):
        c = metrics.counter("race.events")
        h = metrics.histogram("race.latency_s")
        for j in range(per_thread):
            c.inc()
            h.observe(1e-4 * ((i + j) % 7 + 1))
            with trace.span("race/outer"):
                with trace.span("inner"):
                    pass

    errors = _run_threads(THREADS, worker)
    stop.set()
    st.join(timeout=30)
    want = THREADS * per_thread
    _check(out, "no_errors", not errors and not corrupt,
           "; ".join(errors + corrupt))
    _check(out, "counter_exact",
           metrics.counter("race.events").value == want,
           f"counter {metrics.counter('race.events').value} != {want}")
    h = metrics.histogram("race.latency_s")
    _check(out, "histogram_exact",
           h.total == want and sum(h.counts) == want,
           f"total {h.total} / bucket sum {sum(h.counts)} != {want}")
    roll = trace.rollup()
    _check(out, "span_rollup_exact",
           roll.get("race/outer", {}).get("count") == want
           and roll.get("race/outer/inner", {}).get("count") == want,
           f"rollup counts {roll.get('race/outer')} / "
           f"{roll.get('race/outer/inner')} != {want}")
    out["observed"] = want
    metrics.reset()
    trace.reset()
    return out


def scenario_chunkstore(tmp: str) -> dict:
    """Concurrent writers into one store: complete manifest, clean resume."""
    import numpy as np

    from raft_tpu.resilience.checkpoint import ChunkStore

    out: dict = {}
    n_chunks, writers = 48, 4
    store = ChunkStore("race_store", n_chunks, tmp)

    def writer(t):
        for k in range(t, n_chunks, writers):
            store.save(k, (np.full(16, k, dtype=np.float32),
                           np.arange(k + 1)))

    errors = _run_threads(writers, writer)
    _check(out, "no_errors", not errors, "; ".join(errors))
    _check(out, "all_saved", store.saved == n_chunks,
           f"saved {store.saved} != {n_chunks}")
    _check(out, "manifest_complete", store.complete(),
           "manifest dropped entries under the concurrent RMW")
    # a FRESH store (new process analog) must resume every chunk clean
    resume = ChunkStore("race_store", n_chunks, tmp)
    loaded = [resume.load(k) for k in range(n_chunks)]
    _check(out, "resume_all", all(r is not None for r in loaded),
           f"{sum(r is None for r in loaded)} chunks missing on resume")
    _check(out, "zero_corrupt", resume.corrupt == 0,
           f"{resume.corrupt} corrupt chunks")
    ok_vals = all(
        r is not None and float(r[0][0]) == float(k)
        for k, r in enumerate(loaded))
    _check(out, "values_roundtrip", ok_vals, "resumed values diverged")
    out["stats"] = resume.to_dict()
    return out


def scenario_fault_counters() -> dict:
    """Every counted fault kind fires exactly K times across N threads —
    ``hang_subprocess:K`` plus the fleet's replica kinds, armed together
    in ONE spec so per-kind counters can't bleed into each other under
    contention (the router consumes kill/stall/refuse from concurrent
    dispatch and probe threads)."""
    from raft_tpu.resilience import faults

    out: dict = {}
    budgets = {"hang_subprocess": 5, "kill_replica": 3,
               "stall_replica": 4, "refuse_connect": 2}
    old = os.environ.get("RAFT_TPU_FAULT_INJECT")
    os.environ["RAFT_TPU_FAULT_INJECT"] = ",".join(
        f"{name}:{k}" for name, k in budgets.items())
    faults.reset_counts()
    fires = [{name: 0 for name in budgets} for _ in range(THREADS)]

    def worker(i):
        for _ in range(200):
            for name in budgets:
                if faults.consume(name):
                    fires[i][name] += 1

    try:
        errors = _run_threads(THREADS, worker)
    finally:
        if old is None:
            os.environ.pop("RAFT_TPU_FAULT_INJECT", None)
        else:
            os.environ["RAFT_TPU_FAULT_INJECT"] = old
        faults.reset_counts()
    _check(out, "no_errors", not errors, "; ".join(errors))
    totals = {name: sum(f[name] for f in fires) for name in budgets}
    for name, k_budget in budgets.items():
        _check(out, f"exact_fires_{name}", totals[name] == k_budget,
               f"{totals[name]} fires != budget {k_budget}")
    out["fires"] = totals
    return out


def scenario_microbatcher() -> dict:
    """Serve-queue contention: concurrent submit / close / drain (the
    daemon's reader-threads-vs-solver-loop-vs-SIGTERM triangle)."""
    from raft_tpu.build.buckets import BucketSig
    from raft_tpu.serve.batcher import Lane, MicroBatcher

    out: dict = {}
    sigs = (BucketSig(16, 64, 32), BucketSig(48, 128, 32))
    cap = 4
    per_thread = 150
    # deadline 0: every non-empty bucket is immediately closeable, so the
    # drainer and the submitters genuinely race the pop/append boundary
    mb = MicroBatcher(batch_deadline_s=0.0, batch_max=cap)
    accepted: list = [0] * THREADS
    batches: list = []
    drained = threading.Event()

    def drain():
        while True:
            item = mb.next_batch()
            if item is None:
                drained.set()
                return
            batches.append(item)

    drainer = threading.Thread(target=drain, name="race-drain", daemon=True)
    drainer.start()

    def submit(i):
        n = 0
        for j in range(per_thread):
            lane = Lane(request_id=(i, j), seq=0, label="x", staged=None)
            try:
                mb.submit(sigs[j % 2], lane)
                n += 1
            except RuntimeError:
                break           # intake closed underneath us: accounted
        accepted[i] = n

    # close() races the tail of the submit storm: a few threads' late
    # submits must either be accepted AND drained, or refused loudly
    closer = threading.Timer(0.05, mb.close)
    closer.start()
    errors = _run_threads(THREADS, submit)
    closer.join()
    mb.close()
    ok_drained = drained.wait(30)
    drainer.join(10)

    lanes = [ln for _sig, lns in batches for ln in lns]
    ids = [ln.request_id for ln in lanes]
    _check(out, "no_errors", not errors, "; ".join(errors))
    _check(out, "drained", ok_drained, "drain loop did not finish")
    _check(out, "every_accepted_lane_drained_once",
           sorted(ids) == sorted(set(ids)) and len(ids) == sum(accepted),
           f"{len(ids)} drained vs {sum(accepted)} accepted "
           f"({len(ids) - len(set(ids))} duplicates)")
    _check(out, "capacity_respected",
           all(len(lns) <= cap for _s, lns in batches),
           f"max batch {max((len(l) for _s, l in batches), default=0)}"
           f" > cap {cap}")
    fifo_ok = True
    for _sig, lns in batches:
        per_src: dict = {}
        for ln in lns:
            src, j = ln.request_id
            if per_src.get(src, -1) >= j:
                fifo_ok = False
            per_src[src] = j
    _check(out, "per_submitter_fifo_within_batch", fifo_ok,
           "a batch reordered one submitter's lanes")
    counters = mb.counters()
    _check(out, "counters_exact",
           counters["submitted"] == sum(accepted)
           and counters["popped"] == len(ids)
           and counters["pending"] == 0,
           f"batcher counters {counters} vs accepted {sum(accepted)}")
    out["accepted"] = sum(accepted)
    out["batches"] = len(batches)
    return out


def main(argv=None) -> int:
    # the harness must never dial a hardware backend: pin CPU before jax
    # init, and keep the warm-start layers inside a scratch root
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.perf_counter()
    old_interval = sys.getswitchinterval()
    sys.setswitchinterval(SWITCH_INTERVAL)
    report: dict = {"tool": "race-smoke", "threads": THREADS,
                    "switch_interval": SWITCH_INTERVAL}
    try:
        with tempfile.TemporaryDirectory(prefix="raft_race_") as tmp:
            report["aot_single_flight"] = scenario_aot_single_flight(
                os.path.join(tmp, "cache"))
            report["compile_event_counters"] = scenario_compile_event_counters()
            report["metrics_spans"] = scenario_metrics_and_spans()
            report["chunkstore"] = scenario_chunkstore(
                os.path.join(tmp, "ckpt"))
            report["fault_counters"] = scenario_fault_counters()
            report["serve_microbatcher"] = scenario_microbatcher()
    finally:
        sys.setswitchinterval(old_interval)
    failures = [f for s in report.values() if isinstance(s, dict)
                for f in s.get("failures", ())]
    report["elapsed_s"] = round(time.perf_counter() - t0, 2)
    report["ok"] = not failures
    if failures:
        report["failures"] = failures
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
