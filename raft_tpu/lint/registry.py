"""Registered public entry points for the trace audit.

Each entry mirrors the *traced core* of one public API — the exact
function shape the public orchestrator hands to ``jax.jit`` — built on a
deliberately tiny OC3-spar model (small ``nw``, few fixed-point
iterations) so the audit traces in milliseconds and the one compile the
retrace check needs stays cheap on CPU.

Why mirrors and not the orchestrators themselves: ``sweep`` /
``sweep_sea_states`` / ``optimize_design`` are host-side functions that
stage arrays, pick shardings, and consult the warm-start cache before
jitting their core — jitting the orchestrator would itself be a lint
violation (host ``np.asarray`` on the inputs).  The registry builds the
same vmapped/shard_mapped core the orchestrator jits, with the same
``n_iter``/``method`` semantics, so a hazard introduced into the traced
pipeline (statics -> Morison -> drag-linearized solve) shows up here.

Every entry returns ``(fn, args, args2)``: two argument pytrees with
IDENTICAL structure/shapes/dtypes but different values.  The audit
asserts that calling ``jit(fn)`` with both causes exactly one trace —
the "repeated same-shape north-star sweep call must not retrace"
acceptance gate.
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Callable

# (nw, x64-mode) -> staged base model; the audit traces under x32 while
# the test suite runs x64, so the cache must key on the mode.  The lock
# makes the get-or-stage single-flight: parallel audit runners (or a
# daemon arming entries concurrently) stage each base exactly once.
_base_cache: dict = {}
_base_lock = threading.Lock()


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    name: str
    public_api: str                      # the API this entry guards
    build: Callable[[], tuple]           # () -> (fn, args, args2)
    #: daemon-facing: the public API this entry mirrors is served to
    #: CONCURRENT callers by the ROADMAP resident solver service, so its
    #: host path falls under the GL3xx concurrency contracts (GL303 seeds
    #: come from :data:`CONCURRENT_FUNCTIONS`, which every
    #: ``concurrent=True`` entry's ``public_api`` must join — pinned by a
    #: drift test, like the knobs table)
    concurrent: bool = False
    #: pod-facing: the public API this entry mirrors runs on the ROADMAP
    #: multi-host sweep path, so its host path falls under the GL4xx SPMD
    #: contracts (GL401/GL402/GL403 seeds come from
    #: :data:`MULTIHOST_FUNCTIONS`)
    multihost: bool = False
    #: the entry's first argument is batch-leading and the sharded-lowering
    #: audit gate must lower it with the batch axis sharded over the forced
    #: 8-device CPU mesh (per-device peak_bytes pinned in budgets.json);
    #: a drift test pins multihost => sharded
    sharded: bool = False


def _small_base(nw: int = 6):
    """Tiny OC3-spar staging shared by all entries (host-side, cheap) —
    the same :func:`raft_tpu.model.stage_design_base` recipe the driver
    entry uses, just on a smaller frequency grid."""
    import jax

    from raft_tpu.model import stage_design_base

    key = (nw, bool(jax.config.jax_enable_x64))
    with _base_lock:
        hit = _base_cache.get(key)
        if hit is not None:
            return hit
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = stage_design_base(os.path.join(pkg, "designs", "OC3spar.yaml"),
                                nw=nw, Hs=6.0, Tp=10.0, w_min=0.3,
                                w_max=2.1)
        _base_cache[key] = out
        return out


_N_ITER = 3     # fixed-point iterations: the audit checks structure, not
#                 convergence, so the cheapest deterministic scan suffices


def _entry_north_star_sweep():
    """Traced core of :func:`raft_tpu.parallel.sweep.sweep` — the
    north-star design-batch RAO sweep (vmapped forward_response over a
    theta batch, ``method='scan'``)."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.parallel.sweep import forward_response, scale_diameters

    _, members, rna, env, wave, C_moor = _small_base()

    def one(theta):
        m = scale_diameters(members, theta)
        out = forward_response(m, rna, env, wave, C_moor, n_iter=_N_ITER,
                               method="scan")
        return out.Xi.abs2(), out.n_iter

    fn = jax.vmap(one)
    args = (1.0 + 0.02 * jnp.arange(2),)
    args2 = (1.0 + 0.03 * jnp.arange(2),)
    return fn, args, args2


def _entry_dlc_solve():
    """Traced core of :func:`raft_tpu.parallel.sweep.sweep_sea_states` —
    the DLC-table evaluation (per-case drag linearization under vmap)."""
    import jax

    from raft_tpu.parallel.optimize import nacelle_accel_std
    from raft_tpu.parallel.sweep import forward_response, make_wave_states

    design, members, rna, env, wave, C_moor = _small_base()
    import numpy as np

    depth = float(design["mooring"]["water_depth"])
    waves = make_wave_states(np.asarray(wave.w), [[6.0, 10.0], [8.0, 12.0]],
                             depth)
    waves2 = make_wave_states(np.asarray(wave.w), [[5.0, 9.0], [9.0, 13.0]],
                              depth)

    def one(wv):
        out = forward_response(members, rna, env, wv, C_moor,
                               n_iter=_N_ITER)
        return out.Xi.abs2(), nacelle_accel_std(out.Xi, wv, rna), out.n_iter

    return jax.vmap(one), (waves,), (waves2,)


def _entry_freq_sharded():
    """Traced core of
    :func:`raft_tpu.parallel.sweep.forward_response_freq_sharded` — the
    sequence-parallel shard_map solve (psum/pmax collectives per
    iteration); audited on a 1-device mesh so the audit runs identically
    under the CLI (1 CPU device) and the test suite (8 virtual devices)."""
    from raft_tpu.parallel.sweep import (
        forward_response_freq_sharded, make_mesh,
    )

    _, members, rna, env, wave, C_moor = _small_base()
    mesh = make_mesh(1, axis="freq")

    def fn(wv):
        out = forward_response_freq_sharded(
            members, rna, env, wv, C_moor, mesh=mesh,
            n_iter=_N_ITER, method="scan")
        return out.Xi.abs2()

    wave2 = wave.replace(zeta=wave.zeta * 1.1)
    return fn, (wave,), (wave2,)


def _entry_val_grad():
    """Traced core of :func:`raft_tpu.parallel.optimize.optimize_design`'s
    per-step executable — ``jax.value_and_grad`` of the nacelle-accel
    objective through the reverse-differentiable scan driver."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.parallel.optimize import nacelle_accel_std
    from raft_tpu.parallel.sweep import forward_response, scale_diameters

    _, members, rna, env, wave, C_moor = _small_base()

    def loss(theta):
        m = scale_diameters(members, theta)
        out = forward_response(m, rna, env, wave, C_moor, n_iter=_N_ITER,
                               method="scan")
        return nacelle_accel_std(out.Xi, wave, rna)

    fn = jax.value_and_grad(loss)
    return fn, (jnp.asarray(1.0),), (jnp.asarray(1.05),)


def _entry_fused_rao_solve():
    """The fused assemble+solve entry (this PR's hot op): BOTH routes —
    the Pallas kernel (interpreter mode off-TPU, the exact kernel the TPU
    runs compiled) and the XLA fallback — traced together, so the audit's
    zero-retrace / zero-f64 / zero-host-callback budgets cover the fused
    path end to end (a ``pallas_call`` is a device op, not a host
    callback; a leak would show here)."""
    import numpy as np
    import jax.numpy as jnp

    from raft_tpu.core.cplx import Cx
    from raft_tpu.core.linalg6 import solve_cx_fused
    from raft_tpu.core.pallas6 import solve_rao_pallas

    def mk(seed):
        rng = np.random.default_rng(seed)
        nw = 8
        Z0 = Cx(jnp.asarray(rng.normal(size=(nw, 6, 6)) + 8.0 * np.eye(6)),
                jnp.asarray(0.3 * rng.normal(size=(nw, 6, 6))))
        w = jnp.asarray(rng.uniform(0.2, 2.5, nw))
        Bd = jnp.asarray(rng.normal(size=(6, 6)))
        F = Cx(jnp.asarray(rng.normal(size=(nw, 6))),
               jnp.asarray(rng.normal(size=(nw, 6))))
        return (Z0, w, Bd, F)

    def fn(Z0, w, Bd, F):
        xp = solve_rao_pallas(Z0, w, Bd, F)
        xx = solve_cx_fused(Z0, w, Bd, F)
        return xp.re + xx.re, xp.im + xx.im

    return fn, mk(0), mk(1)


def _two_design_batch():
    """Shared fixture of the megabatch-shaped entries: TWO genuinely
    different designs (OC3 spar + a station-split variant with different
    exact segment/node counts) staged into ONE bucket, batch-leading."""
    import copy

    import jax
    import numpy as np

    key = ("sweep_designs", bool(jax.config.jax_enable_x64))
    with _base_lock:
        hit = _base_cache.get(key)
        if hit is None:
            from raft_tpu.model import load_design, stage_designs
            from raft_tpu.build import buckets as _buckets

            pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            path = os.path.join(pkg, "designs", "OC3spar.yaml")
            variant = copy.deepcopy(load_design(path))
            # a genuinely different topology in the same bucket: split the
            # spar's station list (more segments/nodes than stock OC3)
            m0 = variant["platform"]["members"][0]
            s0, s1 = float(m0["stations"][0]), float(m0["stations"][-1])
            m0["stations"] = [s0, 0.5 * (s0 + s1), s1]
            m0["d"] = [float(np.atleast_1d(m0["d"])[0])] * 3
            t0 = float(np.atleast_1d(m0["t"])[0])
            m0["t"] = [t0] * 3
            staged = stage_designs([path, variant], nw=6, Hs=6.0, Tp=10.0,
                                   w_min=0.3, w_max=2.1)
            if len(staged) != 1:
                raise AssertionError(
                    f"audit fixture designs landed in {len(staged)} buckets "
                    f"({list(staged)}); they must share one")
            (batch,) = staged.values()
            sig = _buckets.bucketize(load_design(path), nw=6)
            sig_v = _buckets.bucketize(variant, nw=6)
            if sig != sig_v:
                raise AssertionError(
                    f"fixture buckets diverged: {sig} vs {sig_v}")
            hit = _base_cache[key] = batch
    return hit


def _entry_sweep_designs():
    """Traced core of :func:`raft_tpu.parallel.sweep.sweep_designs` — the
    shape-bucketed mixed-design megabatch: the per-design arrays (members,
    RNA, env, wave, mooring) are batch-leading vmapped INPUTS, so one
    executable serves every design of a bucket class.  The two argument
    pytrees stack TWO DIFFERENT designs (OC3 spar + a station-split
    variant with different exact segment/node counts) padded to ONE
    bucket, in swapped lane order — the zero-retrace budget is exactly
    the "two different same-bucket designs never recompile" claim."""
    import jax

    batch = _two_design_batch()

    from raft_tpu.parallel.sweep import forward_response

    def one(members, rna, env, wave, C_moor):
        out = forward_response(members, rna, env, wave, C_moor,
                               n_iter=_N_ITER, method="scan")
        return out.Xi.abs2(), out.n_iter

    fn = jax.vmap(one)
    args = (batch.members, batch.rna, batch.env, batch.wave, batch.C_moor)
    # the SAME two designs in swapped lane order: identical structure and
    # shapes, different values — one trace must serve both
    args2 = jax.tree_util.tree_map(lambda a: a[::-1], args)
    return fn, args, args2


def _entry_serve_solve():
    """Traced core of :func:`raft_tpu.serve.solver.solve_batch` — the
    resident service's per-bucket dispatch: the SAME vmapped
    design-agnostic body as ``sweep_designs``, but padded to the FIXED
    serve lane capacity (unused lanes tile the real ones).  The two
    argument pytrees are two different occupancy mixes of the same two
    same-bucket designs at one capacity — the zero-retrace budget is the
    serving loop's "every occupancy of a bucket shares one executable"
    claim, and ``concurrent=True`` puts the whole request path under the
    GL3xx contracts."""
    import jax

    batch = _two_design_batch()

    from raft_tpu.parallel.sweep import forward_response, response_std

    def one(members, rna, env, wave, C_moor):
        out = forward_response(members, rna, env, wave, C_moor,
                               n_iter=_N_ITER, method="scan")
        return (response_std(out.Xi.abs2(), wave.w), out.n_iter,
                out.converged)

    fn = jax.vmap(one)
    base = (batch.members, batch.rna, batch.env, batch.wave, batch.C_moor)

    import numpy as np

    def pad(args, order):
        idx = np.asarray(order)
        return jax.tree_util.tree_map(lambda a: a[idx], args)

    # occupancy 1 (solo, tiled to capacity) vs occupancy 2 (mixed +
    # one pad lane): identical shapes, different values — one trace
    args = pad(base, (0, 0, 0))
    args2 = pad(base, (1, 0, 1))
    return fn, args, args2


def _bem_entry(assembly: str, nw: int = 2):
    """Shared fixture of the two ``jax_bem`` audit entries: the traced
    core of :func:`raft_tpu.hydro.jax_bem.solve_panels` (influence
    assembly + factor-once refined solve) on a tiny padded deep-water
    mesh, with the assembly route pinned explicitly so each route gets
    its own zero-retrace / zero-f64 / budget gate.  The two argument
    pytrees are two DIFFERENT geometries (radial scales) padded to one
    ``panels`` ladder class."""
    import functools

    import numpy as np
    import jax.numpy as jnp

    from raft_tpu.hydro import jax_bem, wavetable

    def mesh(scale):
        th = np.linspace(0, np.pi, 4 + 1)
        pans = []
        for i in range(4):
            for j in range(8):
                p0, p1 = th[i], th[i + 1]
                a0, a1 = 2 * np.pi * j / 8, 2 * np.pi * (j + 1) / 8
                pt = lambda pp, aa: [scale * np.sin(pp) * np.cos(aa),
                                     scale * np.sin(pp) * np.sin(aa),
                                     -3.0 + scale * np.cos(pp)]
                pans.append([pt(p0, a0), pt(p1, a0), pt(p1, a1),
                             pt(p0, a1)])
        return np.asarray(pans)

    w = np.array([0.9, 1.4])[:nw]
    fd = wavetable.fd_fit_grid(w, -1.0, 9.81)
    tab = jax_bem._stage_table(jnp.float32)

    def args_for(scale):
        padded, pm, lm = jax_bem._pad_mesh(mesh(scale), None)
        return (jnp.asarray(padded, jnp.float32),
                jnp.asarray(pm, jnp.float32), jnp.asarray(lm, jnp.float32),
                jnp.asarray(w, jnp.float32),
                jnp.asarray([0.0], jnp.float32),
                {k: jnp.asarray(v, jnp.float32) for k, v in fd.items()},
                tab)

    fn = functools.partial(jax_bem.solve_panels, rho=1025.0, g=9.81,
                           depth=0.0, finite_depth=False,
                           dtype=jnp.float32, assembly=assembly)

    def wrapped(*a):
        A, B, F, resid = fn(*a)
        return A, B, F.re, F.im, resid

    return wrapped, args_for(1.0), args_for(1.07)


def _entry_jax_bem():
    """Traced core of :func:`raft_tpu.hydro.jax_bem.solve_panels` on the
    XLA assembly route — the zero-retrace budget is exactly the "a novel
    geometry on a warm executable pays only the device solve" claim, and
    the zero-f64 budget pins the f32-blocks-with-refinement contract."""
    return _bem_entry("xla")


def _entry_jax_bem_pallas():
    """The SAME panel solve through the tiled Pallas assembly route
    (:mod:`raft_tpu.core.pallas_bem`; interpreter mode off-TPU — the
    exact kernels the TPU runs compiled), so the zero-retrace /
    zero-f64 / zero-host-callback budgets cover the kernel path end to
    end: a ``pallas_call`` is a device op, not a host callback, and the
    blocked LU downstream of it is shared with the XLA entry.  One
    frequency keeps the interpreter-mode audit cheap — the route is
    frequency-batched by the same ``lax.map(checkpoint(vmap))`` wrapper
    either way, so nw=1 loses no structure."""
    return _bem_entry("pallas", nw=1)


def _entry_eigen():
    """Traced core of :func:`raft_tpu.solve.eigen.solve_eigen` — the
    generalized symmetric eigensolve (Cholesky + Jacobi sweeps)."""
    import jax.numpy as jnp

    from raft_tpu.solve.eigen import solve_eigen
    from raft_tpu.statics import assemble_statics

    _, members, rna, env, _, C_moor = _small_base()
    stat = assemble_statics(members, rna, env)
    M = stat.M_struc
    C = stat.C_struc + stat.C_hydro + C_moor
    # same matrices, different well-posed values for the retrace check
    M2 = M + 0.01 * jnp.eye(6, dtype=M.dtype) * M[0, 0]
    C2 = C + 0.01 * jnp.eye(6, dtype=C.dtype) * jnp.abs(C[2, 2])

    def fn(Mx, Cx_):
        return solve_eigen(Mx, Cx_)

    return fn, (M, C), (M2, C2)


ENTRY_POINTS: tuple[EntryPoint, ...] = (
    EntryPoint("north_star_sweep", "raft_tpu.parallel.sweep.sweep",
               _entry_north_star_sweep, concurrent=True, multihost=True,
               sharded=True),
    EntryPoint("dlc_solve", "raft_tpu.parallel.sweep.sweep_sea_states",
               _entry_dlc_solve, concurrent=True, multihost=True, sharded=True),
    EntryPoint("freq_sharded_forward",
               "raft_tpu.parallel.sweep.forward_response_freq_sharded",
               _entry_freq_sharded),
    EntryPoint("val_grad", "raft_tpu.parallel.optimize.optimize_design",
               _entry_val_grad),
    EntryPoint("eigen", "raft_tpu.solve.eigen.solve_eigen", _entry_eigen),
    # NOT sharded: the fused kernel is the per-shard body — production
    # runs it INSIDE a shard_map shard, never sharded across the
    # frequency batch (a pallas_call forces the partitioner to gather
    # its whole operand, so a batch-sharded lowering of this entry
    # measures an all-gather, not a sharding regression)
    EntryPoint("fused_rao_solve",
               "raft_tpu.core.pallas6.solve_rao_pallas",
               _entry_fused_rao_solve),
    EntryPoint("sweep_designs", "raft_tpu.parallel.sweep.sweep_designs",
               _entry_sweep_designs, concurrent=True, multihost=True,
               sharded=True),
    EntryPoint("serve_solve", "raft_tpu.serve.solver.solve_batch",
               _entry_serve_solve, concurrent=True, multihost=True, sharded=True),
    EntryPoint("jax_bem", "raft_tpu.hydro.jax_bem.solve_panels",
               _entry_jax_bem),
    EntryPoint("jax_bem_pallas", "raft_tpu.hydro.jax_bem.solve_panels",
               _entry_jax_bem_pallas),
)

#: the daemon-facing host functions whose whole call path falls under the
#: GL3xx concurrency contracts — graftlint's GL303 seeds its concurrent
#: reachability here.  Every ``concurrent=True`` audit entry's
#: ``public_api`` is included automatically (the solve/sweep/DLC request
#: handlers of the ROADMAP resident service); the cache registry entry
#: points join explicitly because a daemon also arms executables outside
#: any sweep call.  Names must resolve to real callables AND be listed in
#: the docs "Concurrency contracts" section (``tests/test_lint.py``
#: drift-pins both directions, the knobs table==registry precedent).
CONCURRENT_FUNCTIONS: tuple[str, ...] = tuple(
    e.public_api for e in ENTRY_POINTS if e.concurrent
) + (
    "raft_tpu.cache.aot.cached_compile",
    "raft_tpu.cache.aot.cached_callable",
)

#: the pod-facing host functions whose whole call path falls under the
#: GL4xx SPMD contracts — graftlint seeds its multihost reachability here
#: (GL401 host-agreement, GL402 shared-root writes, GL403 sharding
#: discipline).  Every ``multihost=True`` audit entry's ``public_api`` is
#: included automatically; the explicit extras are the multi-host staging
#: and mesh-sharded forward paths that run on every host of a pod even
#: though no audit entry dispatches them directly.  Names must resolve to
#: real callables AND be listed in the docs "SPMD contracts" section
#: (``tests/test_lint.py`` drift-pins both directions).
MULTIHOST_FUNCTIONS: tuple[str, ...] = tuple(
    e.public_api for e in ENTRY_POINTS if e.multihost
) + (
    "raft_tpu.parallel.multihost.stage_global",
    "raft_tpu.parallel.sweep.forward_response_freq_sharded",
    "raft_tpu.parallel.sweep.forward_response_dp_sp",
)


def get_entries(names=None) -> tuple[EntryPoint, ...]:
    if names is None:
        return ENTRY_POINTS
    by_name = {e.name: e for e in ENTRY_POINTS}
    missing = [n for n in names if n not in by_name]
    if missing:
        raise KeyError(f"unknown audit entries {missing}; have "
                       f"{sorted(by_name)}")
    return tuple(by_name[n] for n in names)
