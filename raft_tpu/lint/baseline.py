"""Committed-baseline triage for graftlint.

The baseline is a JSON map ``fingerprint -> count`` of violations that
existed when the linter landed (or were consciously triaged later).  A
run fails only on violations NOT covered by the baseline, so the gate
can merge with a dirty tree and still stop every regression.

Fingerprints (see :meth:`raft_tpu.lint.rules.Violation.fingerprint`) are
line-number-free — rule + file + enclosing function + stripped source
text — so reformatting elsewhere in a file does not churn the baseline.
``python -m raft_tpu.lint --write-baseline`` regenerates the file;
review the diff like any other code change.

Triage REASONS: the ``_reasons`` map carries a one-line justification
per fingerprint (the GL3xx concurrency contract requires every
single-threaded-by-contract finding to say WHY it is safe today — e.g.
"re-read per call by design; daemon snapshots at arm time").  Reasons
are maintainer state: a ``--write-baseline`` refresh preserves them for
fingerprints that survive and drops the rest.
"""
from __future__ import annotations

import json
import os
from collections import Counter

from raft_tpu.lint.rules import Violation

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def load(path: str | None = None) -> Counter:
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return Counter()
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return Counter({str(k): int(v) for k, v in
                    data.get("violations", {}).items()})


def save(violations: list[Violation], path: str | None = None) -> str:
    path = path or DEFAULT_BASELINE
    counts = Counter(v.fingerprint() for v in violations)
    reasons: dict = {}
    if os.path.exists(path):        # preserve surviving triage reasons
        try:
            with open(path, "r", encoding="utf-8") as f:
                old = json.load(f)
            reasons = {k: str(v) for k, v in old.get("_reasons", {}).items()
                       if k in counts}
        except (OSError, json.JSONDecodeError, ValueError):
            reasons = {}
    payload = {
        "_comment": "graftlint baseline: fingerprint -> count of triaged "
                    "pre-existing violations; regenerate with "
                    "`python -m raft_tpu.lint --write-baseline`. "
                    "_reasons carries the per-fingerprint justification "
                    "(required for GL3xx single-threaded-by-contract "
                    "triage).",
        "_reasons": {k: reasons[k] for k in sorted(reasons)},
        "violations": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return path


def filter_new(violations: list[Violation],
               path: str | None = None) -> tuple[list[Violation], int]:
    """(violations not covered by the baseline, number baselined-out)."""
    budget = Counter(load(path))
    fresh: list[Violation] = []
    absorbed = 0
    for v in violations:
        fp = v.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            absorbed += 1
        else:
            fresh.append(v)
    return fresh, absorbed
