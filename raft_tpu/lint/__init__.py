"""graftlint: JAX-aware static analysis + trace audit for raft_tpu.

Two complementary passes keep the hot path recompile-free and dtype-clean:

* the **static pass** (:mod:`raft_tpu.lint.rules`) — AST rules GL101-GL107
  over the package source: numpy-on-tracer, host casts, traced Python
  branches, ``static_argnames`` hazards, float64 literals, host syncs in
  jitted code, nondeterministic set/listdir iteration near cache keys;
* the **trace audit** (:mod:`raft_tpu.lint.audit`) — abstractly traces
  every registered public entry point (north-star sweep, DLC solve,
  frequency-sharded forward, co-design val_grad, eigen) under
  ``jax.make_jaxpr`` and asserts per-jaxpr budgets: zero retraces for a
  repeated same-shape call, zero float64 leaves under x32, zero host
  callbacks.

CLI: ``python -m raft_tpu.lint [--audit] [--write-baseline] [paths...]``
(exit 0 clean, 1 on new violations / budget breaches).  A committed
baseline (``raft_tpu/lint/baseline.json``) triages pre-existing findings:
only violations NOT in the baseline fail the run.  Suppression syntax and
the rule catalog are documented in ``docs/lint.rst``.
"""
from raft_tpu.lint.rules import (  # noqa: F401
    RULES,
    Violation,
    lint_paths,
)
