"""graftlint: JAX-aware static analysis + trace + budget audit for raft_tpu.

Three complementary passes keep the hot path recompile-free, dtype-clean
and contract-honest:

* the **static pass** (:mod:`raft_tpu.lint.rules`) — AST purity rules
  GL101-GL107 (numpy-on-tracer, host casts, traced Python branches,
  ``static_argnames`` hazards, float64 literals, host syncs in jitted
  code, nondeterministic set/listdir iteration near cache keys) plus the
  contract rules GL201-GL204 (env-knob registration + AOT-key salting
  against :mod:`raft_tpu.lint.knobs`, atomic tmp+``os.replace`` publish
  under durable cache roots, hard subprocess timeouts, donation routed
  through the key-salted AOT registry);
* the **trace audit** (:mod:`raft_tpu.lint.audit`) — abstractly traces
  every registered public entry point (north-star sweep, DLC solve,
  frequency-sharded forward, co-design val_grad, eigen, fused RAO
  solve, bucketed sweep_designs) under ``jax.make_jaxpr`` and asserts
  per-jaxpr budgets: zero retraces for a repeated same-shape call, zero
  float64 leaves under x32, zero host callbacks;
* the **compiled-artifact budget audit** (same module) — AOT-lowers
  each entry and holds its ``cost_analysis()``/``memory_analysis()``
  metrics (flops, bytes accessed, temp/peak bytes, eqn counts) to the
  committed ``raft_tpu/lint/budgets.json`` within tolerance, with
  ``--write-budgets`` as the intentional-change refresh path.

CLI: ``python -m raft_tpu.lint [--audit] [--write-baseline]
[--write-budgets] [paths...]`` (exit 0 clean, 1 on new violations /
budget breaches).  A committed baseline (``raft_tpu/lint/baseline.json``)
triages pre-existing findings: only violations NOT in the baseline fail
the run.  Suppression syntax and the rule catalog are documented in
``docs/lint.rst``.
"""
from raft_tpu.lint.rules import (  # noqa: F401
    RULES,
    Violation,
    lint_paths,
)
