"""raft_tpu — a TPU-native (JAX/XLA) frequency-domain dynamics framework for
floating offshore wind turbines, with the capability surface of dzalkind/RAFT.

Everything between "design parameters" and "response statistics" is a pure,
jittable, vmappable, differentiable function; host-side preprocessing (YAML
parsing, meshing, BEM coefficient generation) emits device arrays.
"""

__version__ = "0.1.0"


def enable_x64():
    """Enable float64 globally (recommended for CPU validation runs)."""
    import jax

    jax.config.update("jax_enable_x64", True)


# lazy type re-exports (PEP 562): importing the package must not pay the
# JAX import — the serving fleet's router/supervisor processes are pure
# socket plumbing and stay JAX-free (see raft_tpu/serve/router.py)
_TYPE_EXPORTS = ("Env", "HydroCoeffs", "MemberSet", "RigidBodyCoeffs",
                 "RNA", "WaveState")


def __getattr__(name: str):
    if name in _TYPE_EXPORTS:
        from raft_tpu.core import types

        return getattr(types, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
