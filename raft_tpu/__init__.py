"""raft_tpu — a TPU-native (JAX/XLA) frequency-domain dynamics framework for
floating offshore wind turbines, with the capability surface of dzalkind/RAFT.

Everything between "design parameters" and "response statistics" is a pure,
jittable, vmappable, differentiable function; host-side preprocessing (YAML
parsing, meshing, BEM coefficient generation) emits device arrays.
"""

__version__ = "0.1.0"


def enable_x64():
    """Enable float64 globally (recommended for CPU validation runs)."""
    import jax

    jax.config.update("jax_enable_x64", True)


from raft_tpu.core.types import Env, HydroCoeffs, MemberSet, RigidBodyCoeffs, RNA, WaveState  # noqa: F401,E402
