"""Multi-process (multi-host) distributed execution.

The single-process path already scales over every device the process can
see (``jax.sharding.Mesh`` + ``shard_map`` with psum/pmax completing the
drag linearization and convergence checks over ICI).  This module is the
multi-HOST layer on top — the capability class the reference would need
MPI/NCCL for, done the JAX way:

* each host process runs the SAME program (SPMD) and contributes its local
  devices to one global mesh (on TPU pods the runtime wires hosts over
  DCN; on CPU/GPU clusters ``jax.distributed`` uses its coordination
  service + Gloo/NCCL),
* arrays that a ``shard_map`` consumes must be GLOBAL jax.Arrays — a host
  numpy array only describes this process's memory — so
  :func:`stage_global` lifts host-replicated pytrees onto the global mesh
  (each process materializes exactly the shards it owns),
* the frequency-sharded and dp x sp solves then run unchanged: XLA
  inserts cross-host collectives for the same psum/pmax that complete the
  physics in-process.

Validated end-to-end by ``tests/test_multihost.py``: two coordinated
processes x 4 virtual CPU devices solve the OC3 RAO on one 8-device
global mesh and reproduce the single-process solve exactly.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding

Array = jax.Array


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> None:
    """Join this process to the distributed runtime.

    On a TPU pod slice every argument autodetects (call with no args —
    the runtime knows the topology).  On CPU/GPU clusters pass the
    coordinator's ``host:port``, the process count, and this process's
    rank.  Must run before the first device operation in the process.
    """
    # the CPU backend ships with collectives DISABLED ("Multiprocess
    # computations aren't implemented on the CPU backend"): arm the Gloo
    # transport before the runtime comes up so a multi-process CPU job
    # (the SPMD smoke, dev boxes) can actually dispatch cross-process
    # programs.  TPU/GPU resolve their own interconnect; only arm when
    # CPU is the explicitly-selected platform, and tolerate builds
    # without the knob (it only matters where the error would occur).
    platforms = str(jax.config.jax_platforms or "")
    if platforms.split(",")[0] == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    if coordinator_address is None:
        if num_processes is not None or process_id is not None:
            raise ValueError(
                "num_processes/process_id were given without a "
                "coordinator_address — autodetect mode would silently "
                "ignore them; pass the coordinator's host:port too"
            )
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)


def global_mesh(axis_names=("freq",), shape=None) -> Mesh:
    """Mesh over ALL processes' devices (``jax.devices()`` is global after
    ``init_multihost``).  ``shape``: optional explicit mesh shape; default
    is 1-D over every device."""
    devs = np.array(jax.devices())
    if shape is not None:
        devs = devs.reshape(shape)
    return Mesh(devs, axis_names=axis_names)


def is_multiprocess(mesh: Mesh) -> bool:
    """True when the mesh spans devices owned by more than one process."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def stage_global(tree, mesh: Mesh, specs):
    """Host-replicated pytree -> globally-sharded jax.Arrays.

    Every process must hold the SAME host values (the usual SPMD staging:
    each rank built or loaded identical inputs).  Each process then
    materializes only the shards the mesh assigns to its own devices —
    the multi-host equivalent of ``jax.device_put(x, NamedSharding)``,
    valid regardless of process count.

    ``specs``: a pytree of PartitionSpec matching ``tree`` (None leaves in
    ``tree`` pass through).
    """

    def put(x, spec):
        if x is None:
            return None
        # host-staging by contract: put() runs OUTSIDE any trace (its whole
        # job is turning host values into global device arrays before a
        # dispatch), so the branch inspects a concrete array's ownership,
        # never a tracer — the idempotence check a re-staged global array
        # needs.  graftlint marks it jit-reachable only because tree.map
        # shares a name with lax.map-style transforms.
        if isinstance(x, jax.Array) and not x.is_fully_addressable:  # graftlint: disable=GL103 — host staging, concrete arrays by contract
            return x        # already a global array — staging is idempotent
        # same contract: materializing the host buffer HERE is the point
        # of staging (each process slices out only its own shards below)
        x = np.asarray(x)  # graftlint: disable=GL106 — host staging, concrete arrays by contract
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx]
        )

    return jax.tree.map(put, tree, specs,
                        is_leaf=lambda v: v is None)
