"""Dispatch-ahead chunk executor: keep the device busy across chunks.

The chunked sweeps (the bench's north-star loop, ``sweep_sea_states`` on
a chunked case table) used to run the blocking pattern

.. code-block:: python

   outs = [np.asarray(compiled(stage(c))) for c in chunks]

which serializes three things that have no ordering dependency: the
host-side staging of chunk ``k+1`` (slicing, heading interpolation,
``device_put``), the device compute of chunk ``k``, and the
device→host fetch of chunk ``k-1``'s results.  :func:`run_pipelined`
overlaps them with a small dispatch-ahead window: at most ``depth``
chunks are in flight at once (bounding live HBM to ``depth`` chunks'
inputs+outputs — unbounded async dispatch would materialize every
chunk's buffers simultaneously), the next chunk is staged and
dispatched BEFORE the oldest in-flight result is fetched, and JAX's
async dispatch does the rest.

Buffer donation rides along naturally: because every chunk is staged
into FRESH device buffers (host → ``device_put`` per dispatch), the
compiled program can take them with ``donate_argnums`` and reuse the
input allocation for the fixed-point carries/outputs in place — the
executor never touches a staged buffer after handing it over.

Knobs:

* ``RAFT_TPU_PIPELINE_DEPTH`` — dispatch-ahead window (default 2,
  minimum 1; 1 degenerates to the blocking loop).
* ``RAFT_TPU_DONATE`` — ``0``/``false``/``off``/``no`` disables input
  donation at the call sites that consult :func:`donation_enabled`
  (default on; the AOT registry keys on the flag, so flipping it can
  never be served a stale executable).
"""
from __future__ import annotations

import dataclasses
import os
import time
from collections import deque

DEFAULT_DEPTH = 2


def dispatch_depth(default: int = DEFAULT_DEPTH) -> int:
    """Dispatch-ahead window from ``RAFT_TPU_PIPELINE_DEPTH`` (min 1)."""
    v = os.environ.get("RAFT_TPU_PIPELINE_DEPTH", "").strip()
    if not v:
        return default
    try:
        return max(1, int(v))
    except ValueError:
        import warnings

        warnings.warn(
            f"RAFT_TPU_PIPELINE_DEPTH={v!r} is not an integer; "
            f"using the default depth {default}", stacklevel=2)
        return default


def donation_enabled() -> bool:
    """True unless ``RAFT_TPU_DONATE`` spells an explicit off."""
    return os.environ.get("RAFT_TPU_DONATE", "").strip().lower() not in (
        "0", "false", "off", "no")


@dataclasses.dataclass
class PipelineStats:
    """Wall-clock accounting of one :func:`run_pipelined` pass.

    ``overlap_fraction`` is the share of host-side work (staging +
    fetching) performed while at least one chunk was in flight on the
    device — the part of the host time the pipeline can hide under
    device compute.  A single chunk (nothing to overlap with) reports 0.
    """

    chunks: int = 0
    depth: int = 0
    max_in_flight: int = 0
    stage_s: float = 0.0
    fetch_s: float = 0.0
    wall_s: float = 0.0
    overlapped_host_s: float = 0.0
    donated_bytes: int = 0
    donated_buffers: int = 0
    invalidated_buffers: int = 0
    # resilience accounting (raft_tpu.resilience): chunks served from the
    # durable checkpoint store vs dispatched to the device, checkpoint
    # writes, corrupt artifacts detected (and recomputed), and injected
    # faults applied by the test harness
    chunks_resumed: int = 0
    chunks_computed: int = 0
    chunks_checkpointed: int = 0
    ckpt_corrupt: int = 0
    faults_injected: int = 0

    @property
    def overlap_fraction(self) -> float:
        host = self.stage_s + self.fetch_s
        return self.overlapped_host_s / host if host > 0 else 0.0

    def to_dict(self) -> dict:
        return {
            "chunks": self.chunks,
            "depth": self.depth,
            "max_in_flight": self.max_in_flight,
            "stage_s": round(self.stage_s, 4),
            "fetch_s": round(self.fetch_s, 4),
            "wall_s": round(self.wall_s, 4),
            "overlap_fraction": round(self.overlap_fraction, 3),
            "donated_bytes": int(self.donated_bytes),
            "donated_buffers": int(self.donated_buffers),
            "invalidated_buffers": int(self.invalidated_buffers),
            "chunks_resumed": int(self.chunks_resumed),
            "chunks_computed": int(self.chunks_computed),
            "chunks_checkpointed": int(self.chunks_checkpointed),
            "ckpt_corrupt": int(self.ckpt_corrupt),
            "faults_injected": int(self.faults_injected),
        }


def run_pipelined(fn, items, *, depth: int | None = None, stage=None,
                  fetch=None, donate_argnums: tuple = (), ckpt=None):
    """Run ``fetch(fn(stage(item)))`` per item with dispatch-ahead overlap.

    ``fn``
        The compiled (or jitted) per-chunk program.  Called with the
        staged value if ``stage`` returns a single object, or splatted
        if it returns a tuple.  Dispatch is asynchronous — ``fn`` must
        not block (no host conversion inside).
    ``items``
        Host-side chunk descriptors, in order.
    ``stage``
        Host staging callback ``item -> staged args`` (slicing, host
        interpolation, ``device_put``).  Runs on the host thread while
        previously dispatched chunks compute.  Default: identity.
    ``fetch``
        Result materialization ``out -> host result`` (e.g. a tree of
        ``np.asarray``).  This is the blocking step; it runs with the
        next chunk(s) already dispatched.  Default: ``jax.device_get``.
    ``depth``
        Max chunks in flight (default :func:`dispatch_depth`).
    ``donate_argnums``
        Positions (into the tuple ``stage`` returns) of the args the
        compiled ``fn`` was built to donate.  The executor accounts
        their bytes and — after fetching each chunk's result — verifies
        the backend really invalidated them (``invalidated_buffers`` in
        the stats; a backend that could not use a donation leaves the
        buffer live, which is visible here rather than silent).
    ``ckpt``
        Optional :class:`raft_tpu.resilience.checkpoint.ChunkStore`.
        Every fetched result is persisted (atomic npz + hashed manifest)
        BEFORE the pass moves on, and a chunk already present in the
        store is served from disk instead of staged/dispatched — the
        resume path of a killed/preempted sweep.  A corrupt artifact is
        detected by content hash and recomputed (``ckpt_corrupt``).
        Chunk indices in the store are POSITIONS in ``items``; the
        store's program key (see ``checkpoint.store_for``) is what makes
        position-keyed results safe to reuse.

    With ``RAFT_TPU_FAULT_INJECT`` armed (:mod:`raft_tpu.resilience.
    faults`), the deterministic injection points live here: ``nan_chunk``
    overwrites a fetched result (before any checkpoint write, exactly
    like a device that produced NaNs) and ``kill_after_chunk`` hard-exits
    after a chunk's fetch+checkpoint completes.  All host-side: arming a
    fault never changes the compiled program.

    Returns ``(results, PipelineStats)`` with results in item order.
    """
    import jax

    from raft_tpu import obs as _obs
    from raft_tpu.resilience import faults as _faults

    if depth is None:
        depth = dispatch_depth()
    depth = max(1, int(depth))
    if stage is None:
        stage = lambda item: item                          # noqa: E731
    if fetch is None:
        fetch = jax.device_get
    items = list(items)
    n = len(items)
    stats = PipelineStats(chunks=n, depth=depth)
    faulty = _faults.active()        # one env read per pass, not per chunk
    results = []
    in_flight: deque = deque()   # (index, dispatched out, donated leaves)
    t_start = time.perf_counter()

    def timed_host(kind, thunk, chunk_idx):
        t0 = time.perf_counter()
        with _obs.trace.span(f"pipeline/{kind}", attrs={"chunk": chunk_idx}):
            out = thunk()
        dt = time.perf_counter() - t0
        if kind == "stage":
            stats.stage_s += dt
        else:
            stats.fetch_s += dt
        _obs.metrics.histogram(f"pipeline.{kind}_s").observe(dt)
        if in_flight:                  # device had work to hide this under
            stats.overlapped_host_s += dt
        return out

    def drain_one():
        k_done, pending, donated = in_flight.popleft()
        res = timed_host("fetch", lambda: fetch(pending), k_done)
        if faulty and _faults.chunk_fault("nan_chunk", k_done):
            res = _faults.nan_results(res)
            stats.faults_injected += 1
        results.append(res)
        for leaf in donated:
            stats.donated_buffers += 1
            if getattr(leaf, "is_deleted", lambda: False)():
                stats.invalidated_buffers += 1
        if ckpt is not None:
            ckpt.save(k_done, res)
            stats.chunks_checkpointed += 1
        if faulty:
            _faults.maybe_kill_after_chunk(k_done)

    for k, item in enumerate(items):
        if ckpt is not None:
            cached = ckpt.load(k)
            if cached is not None:
                # chunks older than k are all in flight or done: drain
                # them first so ``results`` stays in item order (a
                # resume boundary briefly serializes — the durable
                # result is worth the bubble)
                while in_flight:
                    drain_one()
                results.append(cached)
                stats.chunks_resumed += 1
                continue
        staged = timed_host("stage", lambda: stage(item), k)
        donated = []
        if donate_argnums:
            donated = [leaf for i in donate_argnums
                       for leaf in jax.tree_util.tree_leaves(staged[i])]
            stats.donated_bytes += sum(
                getattr(leaf, "nbytes", 0) for leaf in donated)
        t_disp = time.perf_counter()
        with _obs.trace.span("pipeline/dispatch", attrs={"chunk": k}):
            out = fn(*staged) if isinstance(staged, tuple) else fn(staged)
        _obs.metrics.histogram("pipeline.dispatch_s").observe(
            time.perf_counter() - t_disp)
        in_flight.append((k, out, donated))
        stats.chunks_computed += 1
        stats.max_in_flight = max(stats.max_in_flight, len(in_flight))
        # fetch the oldest result only once the window is full (so the
        # youngest chunk's staging+dispatch happened before the oldest
        # chunk's fetch blocks), then drain after the last dispatch;
        # at most ``depth`` chunks are ever in flight
        while len(in_flight) >= depth or (k == n - 1 and in_flight):
            drain_one()
    # the final item may have been resumed from the store with older
    # chunks still pending — the loop's last-item drain never saw them
    while in_flight:
        drain_one()
    if ckpt is not None:
        stats.ckpt_corrupt = ckpt.corrupt
    stats.wall_s = time.perf_counter() - t_start
    # registry mirror of the per-pass stats (the checkpoint store counts
    # its own saved/resumed/corrupt events — not repeated here)
    _obs.metrics.gauge("pipeline.overlap_fraction").set(stats.overlap_fraction)
    _obs.metrics.counter("pipeline.chunks_computed").inc(stats.chunks_computed)
    _obs.metrics.counter("pipeline.chunks_resumed").inc(stats.chunks_resumed)
    if stats.faults_injected:
        _obs.metrics.counter("pipeline.faults_injected").inc(
            stats.faults_injected)
    return results, stats


def _smoke() -> int:
    """``make pipeline-smoke``: CPU proof of the whole PR in < 60 s.

    Runs a tiny OC3 DLC table (4 sea states with per-case headings and a
    synthetic BEM heading grid) through ``sweep_sea_states(chunk=2)`` —
    the dispatch-ahead pipeline with per-chunk host staging and donated
    excitation — with the FUSED solve kernel in interpreter mode
    (``RAFT_TPU_PALLAS=1`` on CPU), then checks bit-level agreement with
    the unchunked call on the fused XLA fallback path, and that the
    donated buffers were really invalidated.  Prints one JSON line;
    rc 0 iff green.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import json

    import numpy as np

    t0 = time.perf_counter()
    from raft_tpu.model import stage_design_base
    from raft_tpu.parallel.sweep import make_wave_states, sweep_sea_states

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    design, members, rna, env, wave, C_moor = stage_design_base(
        os.path.join(pkg, "designs", "OC3spar.yaml"),
        nw=12, Hs=6.0, Tp=10.0, w_min=0.3, w_max=2.1)
    depth = float(design["mooring"]["water_depth"])
    nw = int(wave.w.shape[0])

    # synthetic-but-plausible BEM heading grid (the smoke proves the
    # pipeline/donation machinery, not panel-solve physics): smooth
    # heading-dependent excitation on a 3-heading grid
    rng = np.random.default_rng(7)
    bgrid = np.array([0.0, 0.5, 1.0])
    scale = 1e6
    A_h = np.repeat((rng.normal(size=(6, 6, 1)) * 0.1 + np.eye(6)[..., None])
                    * scale, nw, axis=2)
    B_h = np.repeat((rng.normal(size=(6, 6, 1)) * 0.02) * scale, nw, axis=2)
    F_all = (rng.normal(size=(3, 6, nw)) + 1j * rng.normal(size=(3, 6, nw))
             ) * scale * 0.01
    bem = (bgrid, F_all, A_h, B_h)

    cases = [[6.0, 10.0, 0.1], [7.0, 11.0, 0.4], [8.0, 12.0, 0.6],
             [9.0, 13.0, 0.9]]
    waves = make_wave_states(np.asarray(wave.w), cases, depth)

    os.environ["RAFT_TPU_PALLAS"] = "1"     # interpret-mode fused kernel
    out = sweep_sea_states(members, rna, env, waves, C_moor, bem=bem,
                           n_iter=8, chunk=2, pipeline_depth=2)
    stats = out["pipeline"]

    os.environ["RAFT_TPU_PALLAS"] = "0"     # fused XLA fallback reference
    ref = sweep_sea_states(members, rna, env, waves, C_moor, bem=bem,
                           n_iter=8)

    # cross-PATH bound (pallas-interpret kernel vs XLA fallback, f32
    # rounding accumulated over the fixed point): 1e-4.  Same-path
    # chunked-vs-unchunked bit-parity is pinned in tests/test_pipeline.py.
    denom = np.abs(ref["std dev"]) + 1e-12
    max_rel = float(np.max(np.abs(out["std dev"] - ref["std dev"]) / denom))
    same_iters = bool((out["iterations"] == ref["iterations"]).all())
    donated_ok = (stats["donated_buffers"] > 0
                  and stats["invalidated_buffers"] == stats["donated_buffers"])
    ok = (max_rel < 1e-4 and same_iters and donated_ok
          and stats["max_in_flight"] >= 2 and stats["donated_bytes"] > 0)
    print(json.dumps({
        "ok": ok,
        "max_rel_diff_pallas_chunked_vs_xla": max_rel,
        "same_iteration_counts": same_iters,
        "donated_buffers_invalidated": donated_ok,
        "pipeline": stats,
        "wall_s": round(time.perf_counter() - t0, 2),
    }))
    return 0 if ok else 1


if __name__ == "__main__":                               # pragma: no cover
    import sys

    sys.exit(_smoke())
