"""Deterministic two-process SPMD smoke: ``make spmd-smoke``.

``tests/test_multihost.py`` proves the frequency-sharded RAO solve
crosses a process boundary; this smoke pins the remaining multi-host
claims the GL4xx rules and the sharded-lowering audit reason about,
end to end and in well under 90 s of CPU:

* **sharded == unsharded** — two coordinated processes (2 x 4 virtual
  CPU devices, one global 8-device ``designs`` mesh) run
  :func:`raft_tpu.parallel.sweep.sweep_designs` with ``mesh=`` — the
  design axis sharded over the pod mesh, each process materializing
  only its own lanes — and rank 0 prints the gathered response; the
  parent recomputes the same batch UNSHARDED on a single process and
  requires agreement to float-eps (the "sharding is a layout decision,
  never a numerics decision" contract);
* **one shared cache root, zero collisions** — both workers AND the
  parent's oracle run against one ``RAFT_TPU_CACHE_DIR``: the AOT
  registry, the staging cache, and the obs export sinks all take
  concurrent two-process traffic.  Afterwards the parent asserts every
  observability artifact carries a distinct per-process name
  (``-p<process_index>-<pid>`` — the GL402 salt) and that no torn
  ``*.tmp`` files survive anywhere under the root (the GL202 atomic
  publish contract, now cross-process).

Run modes: no arguments = parent (spawns the two workers, runs the
oracle, checks everything); ``--worker <rank> <port>`` = one SPMD
worker (internal).  Exit code 0 on success.
"""
from __future__ import annotations

import glob
import os
import socket
import subprocess
import sys
import time

#: worker topology: 2 processes x LOCAL_DEVICES virtual CPU devices form
#: the global mesh the sharded-lowering audit also assumes (8 devices)
N_PROCESSES = 2
LOCAL_DEVICES = 4

#: the staged batch: 8 lanes of the stock OC3 spar — one lane per global
#: device, one shape bucket, lane count divisible by the mesh
N_DESIGNS = 8
NW = 6
N_ITER = 4

#: sharded-vs-unsharded agreement bound, relative to the result scale.
#: The lanes run the SAME per-lane program either way (vmap lanes are
#: independent; sharding only places them), so only compilation-level
#: reassociation can differ — float eps territory, not algorithm drift.
PARITY_RTOL = 1e-9


def _design_paths() -> list:
    import raft_tpu

    pkg = os.path.dirname(os.path.abspath(raft_tpu.__file__))
    return [os.path.join(pkg, "designs", "OC3spar.yaml")] * N_DESIGNS


def _solve(mesh=None) -> "object":
    """The exact batch both sides solve: std-dev response of N_DESIGNS
    OC3 lanes (x64, like the multihost test oracle, so parity is pinned
    at 1e-9 instead of f32 noise)."""
    from raft_tpu.parallel.sweep import sweep_designs

    out = sweep_designs(_design_paths(), nw=NW, n_iter=N_ITER,
                        return_xi=False, mesh=mesh)
    return out["std dev"]


def worker(rank: int, port: str) -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from raft_tpu.parallel.multihost import global_mesh, init_multihost

    init_multihost(f"localhost:{port}", num_processes=N_PROCESSES,
                   process_id=rank)
    assert jax.process_count() == N_PROCESSES, jax.process_count()
    assert jax.device_count() == N_PROCESSES * LOCAL_DEVICES, (
        jax.device_count())

    import numpy as np

    std = np.asarray(_solve(mesh=global_mesh(("designs",))))
    # both ranks hold the full gathered result (process_allgather in the
    # mesh path); rank 0 speaks for the job
    if rank == 0:
        print("STD", " ".join(f"{v:.17e}" for v in std.ravel()),
              flush=True)
        print("SHAPE", " ".join(str(s) for s in std.shape), flush=True)
    print(f"WORKER_OK {rank}", flush=True)
    return 0


def _check_exports(obs_dir: str) -> list:
    """Every export artifact must be per-process-salted and whole."""
    problems = []
    jsonl = sorted(glob.glob(os.path.join(obs_dir,
                                          "obs-sweep_designs-*.jsonl")))
    tags = {os.path.basename(p).split("-p", 1)[1].split("-", 1)[0]
            for p in jsonl}
    if len(jsonl) != N_PROCESSES:
        problems.append(f"expected {N_PROCESSES} per-process obs logs, "
                        f"found {len(jsonl)}: {jsonl}")
    if tags != {str(i) for i in range(N_PROCESSES)}:
        problems.append(f"expected process-index salts 0..{N_PROCESSES - 1}"
                        f" in export names, found {sorted(tags)}")
    return problems


def _check_no_torn_files(root: str) -> list:
    tmps = glob.glob(os.path.join(root, "**", "*.tmp"), recursive=True)
    return [f"torn tmp artifacts under the shared root: {tmps}"] if tmps \
        else []


def main() -> int:
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        return worker(int(sys.argv[2]), sys.argv[3])

    import tempfile

    import numpy as np

    t0 = time.perf_counter()
    repo = os.getcwd()
    with tempfile.TemporaryDirectory(prefix="spmd_smoke_") as cache:
        obs_dir = os.path.join(cache, "obs")
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        env = {
            **os.environ,
            "XLA_FLAGS": f"--xla_force_host_platform_device_count="
                         f"{LOCAL_DEVICES}",
            "JAX_PLATFORMS": "cpu",
            "RAFT_TPU_CACHE_DIR": cache,       # ONE root, two writers
            "RAFT_TPU_OBS": obs_dir,
            "PYTHONPATH": repo + os.pathsep + os.environ.get(
                "PYTHONPATH", ""),
        }
        procs = [
            subprocess.Popen(  # graftlint: disable=GL203 — two coordinated workers must run CONCURRENTLY (checked_subprocess is sequential); the communicate(timeout=300) + kill below is the hard timeout
                [sys.executable, "-m", "raft_tpu.parallel.spmd_smoke",
                 "--worker", str(rank), str(port)],
                cwd=repo, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            for rank in range(N_PROCESSES)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
        for p, out in zip(procs, outs):
            if p.returncode != 0:
                print("[spmd-smoke] FAIL: worker died\n"
                      + "\n---\n".join(o[-3000:] for o in outs))
                return 1
        std_line = next(ln for ln in outs[0].splitlines()
                        if ln.startswith("STD "))
        shape = tuple(int(s) for s in next(
            ln for ln in outs[0].splitlines()
            if ln.startswith("SHAPE ")).split()[1:])
        std_sharded = np.array(
            [float(v) for v in std_line.split()[1:]]).reshape(shape)

        # unsharded oracle IN THIS PROCESS, same shared cache root (the
        # worker-compiled sharded executables and this one must coexist
        # under one AOT registry), obs deliberately unarmed so the
        # export-collision census below counts exactly the two workers
        os.environ["RAFT_TPU_CACHE_DIR"] = cache
        os.environ.pop("RAFT_TPU_OBS", None)
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        std_ref = np.asarray(_solve(mesh=None))

        problems = []
        scale = float(np.abs(std_ref).max())
        err = float(np.abs(std_sharded - std_ref).max())
        if not (err <= PARITY_RTOL * scale):
            problems.append(
                f"sharded != unsharded: max err {err:.3e} vs bound "
                f"{PARITY_RTOL * scale:.3e}")
        problems += _check_exports(obs_dir)
        problems += _check_no_torn_files(cache)

        dt = time.perf_counter() - t0
        if problems:
            print("[spmd-smoke] FAIL:")
            for pr in problems:
                print(f"[spmd-smoke]   {pr}")
            return 1
        print(f"[spmd-smoke] ok — {N_PROCESSES} processes x "
              f"{LOCAL_DEVICES} devices, {N_DESIGNS} lanes sharded over "
              f"the global mesh; parity err {err:.3e} "
              f"(bound {PARITY_RTOL * scale:.3e}); "
              f"{N_PROCESSES} salted export logs, no torn files; "
              f"{dt:.1f}s")
        return 0


if __name__ == "__main__":
    sys.exit(main())
