"""Forced virtual-CPU-mesh setup shared by every SPMD consumer.

Three places need "exactly N CPU devices in this process, no hardware":
the driver's multi-chip dry run (``__graft_entry__.dryrun_multichip``),
the sharded-lowering audit gate (``raft_tpu.lint.audit``), and the
two-process SPMD smoke (``raft_tpu.parallel.spmd_smoke``).  Before this
module they carried private copies of the XLA-flag / config-knob dance,
which is exactly the kind of setup that drifts silently — one copy
learns about ``jax_num_cpu_devices`` and the other two keep re-exec'ing.
This module is the single implementation; ``__graft_entry__`` keeps thin
delegating aliases for its historical private names.

Mechanism (newest first): the first-class ``jax_num_cpu_devices`` config
knob (absent on jax <= 0.4.37), falling back to
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` which a process
parses at first backend init.  When both fail — older jax in a process
whose XLA flags were already parsed — :class:`MeshShortfall` tells the
caller a fresh subprocess with the flag preset would succeed.
"""
from __future__ import annotations

import os
import re
import sys


class MeshShortfall(RuntimeError):
    """Raised when the virtual CPU mesh cannot reach the requested device
    count in THIS process but a re-exec with XLA_FLAGS preset would."""


def with_host_device_flag(flags: str, n_devices: int) -> str:
    """XLA_FLAGS with ``--xla_force_host_platform_device_count=N`` set to
    EXACTLY ``n_devices`` — replacing any existing (possibly smaller)
    value rather than keeping it, so a process that inherited count=8 can
    still stage a 16-device dry run."""
    pat = r"--xla_force_host_platform_device_count=\d+"
    new = f"--xla_force_host_platform_device_count={n_devices}"
    if re.search(pat, flags):
        return re.sub(pat, new, flags)
    return (flags + " " + new).strip()


def config_cpu_devices(jax, n_devices: int) -> bool:
    """Set the first-class ``jax_num_cpu_devices`` knob when this jax has
    it.  Returns False on older jax (e.g. 0.4.37 raises AttributeError:
    "Unrecognized config option") — the XLA_FLAGS fallback then has to
    carry the device count on its own."""
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
        return True
    except (AttributeError, KeyError, ValueError, RuntimeError):
        # AttributeError: jax <= 0.4.37 has no such option; RuntimeError:
        # newer jax refuses the knob after backend init — either way the
        # XLA_FLAGS / re-exec fallback must carry the device count
        return False


def cpu_device_plan(knob_ok: bool, n_visible: int, n_needed: int,
                    reexec_blocked: bool) -> str:
    """Decide how to proceed after backend init: ``"ok"`` (mesh big
    enough), ``"reexec"`` (older jax whose XLA_FLAGS were parsed before
    our flag landed — a fresh subprocess with the flag preset will see the
    full mesh), or ``"fail"`` (nothing left to try: the knob took effect
    or a re-exec already happened, yet devices are still short)."""
    if n_visible >= n_needed:
        return "ok"
    if knob_ok or reexec_blocked:
        return "fail"
    return "reexec"


def _backend_initialized() -> bool:
    """True when this process has already created a jax backend (and so
    already spent its one XLA_FLAGS parse).  Probes the registry dict
    directly — calling ``jax.devices()`` to find out would itself
    initialize the backend."""
    try:
        from jax._src import xla_bridge as _xb

        return bool(getattr(_xb, "_backends", None))
    except Exception:
        return False


def force_cpu_devices(n_devices: int, *, cache_dir: str | None = None):
    """Return the jax module with >= ``n_devices`` virtual CPU devices.

    Forces CPU *unconditionally* — SPMD dry runs, audits, and smokes are
    correctness checks of the sharded programs on a virtual mesh; they
    never need (and must never touch) real accelerator hardware.  Only
    ``jax.config.update('jax_platforms', 'cpu')`` reliably overrides a
    ``sitecustomize``-pinned backend, and it must land before backend
    init; ``clear_backends()`` first makes the sequence safe even if some
    earlier code in this process already created a backend.

    ``cache_dir``, when given, arms the persistent compilation cache
    there (SPMD checks are ~95% XLA compile time; a warm on-disk cache
    turns a budget-marginal run into a fast one).

    Raises :class:`MeshShortfall` when this process cannot reach the
    count but a re-exec with the flag preset would; raises AssertionError
    when nothing is left to try.
    """
    jax_live = sys.modules.get("jax")
    if jax_live is not None and _backend_initialized():
        # jax.devices() on an UNinitialized backend would itself trigger
        # backend init — and burn the one XLA_FLAGS parse this function
        # is about to stage — so only probe a backend that already exists
        try:
            devs = jax_live.devices()
            if devs and devs[0].platform == "cpu" and len(devs) >= n_devices:
                # already satisfied (e.g. the test session's 8 virtual
                # devices): resetting live backends here would invalidate
                # every array the process has staged — don't
                return jax_live
        except Exception:
            pass
    # parsed at first backend init; the config knob below covers re-init.
    # Always normalized to n_devices — an inherited smaller count must be
    # replaced, not kept.
    os.environ["XLA_FLAGS"] = with_host_device_flag(
        os.environ.get("XLA_FLAGS", ""), n_devices)
    import jax
    from jax.extend.backend import clear_backends

    clear_backends()  # no-op in a fresh process; resets any earlier backend
    jax.config.update("jax_platforms", "cpu")
    knob_ok = config_cpu_devices(jax, n_devices)
    if cache_dir is not None:
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5)
        except Exception:
            pass  # older jax without the knobs: compile cold, still correct
    devices = jax.devices()
    assert devices[0].platform == "cpu", (
        f"forced jax_platforms=cpu but backend is {devices[0].platform}"
    )
    plan = cpu_device_plan(
        knob_ok, len(devices), n_devices,
        reexec_blocked=bool(os.environ.get("RAFT_TPU_DRYRUN_NO_REEXEC")),
    )
    if plan == "reexec":
        raise MeshShortfall(
            f"need {n_devices} cpu devices, have {len(devices)}; this jax "
            f"lacks jax_num_cpu_devices and XLA_FLAGS were already parsed "
            f"— re-exec with the flag preset"
        )
    assert plan == "ok", (
        f"need {n_devices} cpu devices, have {len(devices)} "
        f"(knob_ok={knob_ok}, XLA_FLAGS={os.environ.get('XLA_FLAGS')!r})"
    )
    return jax


def forced_cpu_mesh(n_devices: int, axis: str = "batch", *,
                    cache_dir: str | None = None):
    """``(jax, Mesh)``: force ``n_devices`` virtual CPU devices and build
    the 1-D mesh every SPMD consumer shards over.  The single construction
    point for audit / smoke meshes, so the axis name and device ordering
    cannot drift between them."""
    import numpy as np

    jax = force_cpu_devices(n_devices, cache_dir=cache_dir)
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:n_devices]), axis_names=(axis,))
    return jax, mesh
