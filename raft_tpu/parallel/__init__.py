"""Multi-device parallelism: design-batch sweeps over a TPU mesh."""
from raft_tpu.parallel.multihost import (  # noqa: F401
    global_mesh,
    init_multihost,
    stage_global,
)
from raft_tpu.parallel.geometry import (  # noqa: F401
    affine_warp,
    make_scale_plan,
    make_stretch_draft,
    substructure_masks,
)
from raft_tpu.parallel.pipeline import (  # noqa: F401
    PipelineStats,
    dispatch_depth,
    donation_enabled,
    run_pipelined,
)
from raft_tpu.parallel.optimize import (  # noqa: F401
    energy_sum,
    grad_nacelle_accel_std,
    nacelle_accel_std,
    optimize_design,
)
from raft_tpu.parallel.sweep import (  # noqa: F401
    directional_response,
    forward_response,
    forward_response_dp_sp,
    forward_response_freq_sharded,
    grad_response_std,
    make_mesh,
    make_wave_states,
    mixed_sea_state,
    response_std,
    scale_diameters,
    spread_sea_state,
    stage_bem,
    sweep,
    sweep_designs,
    sweep_sea_states,
)
