"""Multi-device parallelism: design-batch sweeps over a TPU mesh."""
from raft_tpu.parallel.sweep import (  # noqa: F401
    forward_response,
    forward_response_freq_sharded,
    grad_response_std,
    make_mesh,
    response_std,
    scale_diameters,
    stage_bem,
    sweep,
)
