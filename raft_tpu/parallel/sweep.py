"""Design-batch sweeps sharded over a TPU mesh.

The capability the reference cannot offer (it runs one design per process,
serially): evaluate thousands of geometry variants in one compiled call,
data-parallel over the devices of a ``jax.sharding.Mesh``, and expose exact
gradients of response statistics w.r.t. geometry for co-design optimization
(BASELINE.json north star).

Pattern: ``jit(vmap(forward))`` with the design-parameter batch sharded over
the mesh's ``designs`` axis; XLA inserts the collectives (here only for
reductions the caller requests).  No shard_map is needed because designs are
embarrassingly parallel — the mesh axis is pure data parallelism over ICI.
"""
from __future__ import annotations

import threading
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core.cplx import Cx
from raft_tpu.core.types import Env, MemberSet, RNA, WaveState
from raft_tpu.hydro import node_kinematics, strip_added_mass, strip_excitation
from raft_tpu.parallel.multihost import is_multiprocess, stage_global
from raft_tpu.solve import LinearCoeffs, solve_dynamics
from raft_tpu.statics import assemble_statics

Array = jnp.ndarray


def make_mesh(n_devices: int | None = None, axis: str = "designs") -> Mesh:
    """1-D device mesh for design-batch data parallelism."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), axis_names=(axis,))


def scale_diameters(members: MemberSet, scale: Array) -> MemberSet:
    """Uniformly scale all member cross-sections (a simple geometry knob)."""
    return members.replace(
        seg_dA=members.seg_dA * scale,
        seg_dB=members.seg_dB * scale,
        seg_diA=members.seg_diA * scale,
        seg_diB=members.seg_diB * scale,
        node_ds=members.node_ds * scale,
        node_drs=members.node_drs * scale,
    )


def _bem_device_layout(bem):
    """Host WAMIT-reader layout (A[6,6,nw], B[6,6,nw], F[6,nw] complex) ->
    frequency-leading device arrays (A[nw,6,6], B[nw,6,6], F_re/F_im[nw,6]),
    excitation NOT yet zeta-scaled."""
    A_bem, B_bem, F_bem = bem
    A = jnp.asarray(np.moveaxis(np.asarray(A_bem), -1, 0))
    B = jnp.asarray(np.moveaxis(np.asarray(B_bem), -1, 0))
    Fb = np.moveaxis(np.asarray(F_bem), -1, 0)          # (nw,6) complex, host
    return A, B, jnp.asarray(Fb.real), jnp.asarray(Fb.imag)


def _interp_rows_host(bgrid, F_all, betas_np):
    """Host heading interpolation: (B,) headings -> (B,6,nw) complex
    excitation rows off the staged grid."""
    from raft_tpu.model import interp_heading_excitation

    return np.stack([
        interp_heading_excitation(np.asarray(bgrid), F_all, float(b))
        for b in betas_np
    ])


def _rows_device_layout(F_rows):
    """(B,6,nw) complex host rows -> frequency-leading device pair
    (F_re[B,nw,6], F_im[B,nw,6])."""
    Fb = np.moveaxis(F_rows, -1, 1)          # (B,nw,6)
    return jnp.asarray(Fb.real), jnp.asarray(Fb.imag)


def _stage_heading_rows(bem, betas_eval):
    """Stage a ``Model.calcBEM(headings=...)`` heading GRID for a batch of
    per-case headings: interpolate the excitation to each case's heading on
    the host, then lay out everything frequency-leading on device.

    ``bem``: the staged grid (betas_grid, F_all[nb,6,nw], A[6,6,nw],
    B[6,6,nw]); ``betas_eval``: (B,) evaluation headings [rad].  Returns
    ``(A[nw,6,6], B[nw,6,6], F_re[B,nw,6], F_im[B,nw,6])`` — excitation NOT
    yet zeta-scaled.  The ONE staging convention shared by
    :func:`sweep_sea_states` and the co-design losses
    (:func:`raft_tpu.parallel.optimize.optimize_design`), so the heading
    interpolation rule cannot drift between the two call sites.  (The
    chunked sweep stages its per-chunk rows through the same
    ``_interp_rows_host`` / ``_rows_device_layout`` pair, uncached.)
    """
    from raft_tpu import cache as _cache

    bgrid, F_all, A_h, B_h = bem
    betas_np = np.asarray(betas_eval)

    # content-addressed staging cache: a 1,000-case DLC table re-runs this
    # host loop every process; the heading grid + eval headings key it
    (F_rows,) = _cache.cached_arrays(
        "heading_rows", (np.asarray(bgrid), np.asarray(F_all), betas_np),
        lambda: (_interp_rows_host(bgrid, F_all, betas_np),),
    )
    A_dev, B_dev, _, _ = _bem_device_layout((A_h, B_h, F_rows[0]))
    F_re, F_im = _rows_device_layout(F_rows)
    return A_dev, B_dev, F_re, F_im


def _stage_zeta(staged, zeta):
    """Scale device-layout BEM excitation onto the spectral-amplitude basis
    (zeta = sqrt(S)) used by the Morison path.  Traceable — ``zeta`` may be
    a tracer (per-case staging under vmap in :func:`sweep_sea_states`)."""
    A, B, F_re, F_im = staged
    z = jnp.asarray(zeta)[:, None]
    return A, B, Cx(z * F_re, z * F_im)


def stage_bem(bem, wave: WaveState):
    """Host-layout BEM coefficients -> device arrays for the sweep.

    ``bem`` is the native-solver / WAMIT-reader layout (A[6,6,nw], B[6,6,nw],
    F[6,nw] complex, per unit wave amplitude).  Returns (A[nw,6,6],
    B[nw,6,6], F Cx[nw,6]) with the excitation scaled onto the spectral-
    amplitude basis (zeta = sqrt(S)) used by the Morison path — the
    BASELINE.json "precomputed on host and staged as device arrays" step.
    """
    return _stage_zeta(_bem_device_layout(bem), wave.zeta)


def forward_response(
    members: MemberSet,
    rna: RNA,
    env: Env,
    wave: WaveState,
    C_moor: Array,
    bem=None,
    n_iter: int = 25,
    method: str = "scan",
    remat: bool = False,
    relax: float = 0.8,
    tik: float = 0.0,
):
    """Design -> RAO solve: the pure forward pipeline (statics through Xi).

    A ``wave.beta`` (set per case by :func:`make_wave_states` 3-column
    rows) overrides ``env.beta`` for the node kinematics, so a
    heading-carrying WaveState means the same thing everywhere.
    Strip-theory path by default; pass ``bem`` (the output of
    :func:`stage_bem`) to add potential-flow coefficients — the potMod
    members are then gated out of the Morison added mass/excitation exactly
    as in ``Model._linear_coeffs`` so nothing double-counts.  ``n_iter``
    covers the slowest-converging stock design (the OC4 semi needs ~22
    iterations) with margin; ``method="while"`` early-exits on convergence,
    while ``method="scan"`` (the reverse-differentiable driver) always runs
    ``n_iter`` steps with post-convergence freezing — so keep the cap tight
    for gradient work.
    Returns the :class:`~raft_tpu.solve.RAOResult`.

    ``relax``/``tik`` pass through to :func:`~raft_tpu.solve.solve_dynamics`
    (under-relaxation factor / Tikhonov diagonal loading) — the knobs the
    resilience escalation ladder turns when a quarantined lane is
    re-solved; the defaults trace the exact pre-resilience program.
    """
    if wave.beta is not None:
        if jnp.ndim(wave.beta) != 0:
            raise ValueError(
                f"forward_response expects a scalar wave.beta, got shape "
                f"{jnp.shape(wave.beta)}: batched WaveStates go through "
                f"sweep_sea_states (or vmap forward_response per lane)"
            )
        env = env.replace(beta=wave.beta)
    exclude = bem is not None
    stat = assemble_statics(members, rna, env)
    kin = node_kinematics(members, wave, env)
    A = strip_added_mass(members, env, exclude_potmod=exclude)
    F = strip_excitation(members, kin, env, exclude_potmod=exclude)
    nw = wave.w.shape[0]
    M = jnp.broadcast_to(stat.M_struc + A, (nw, 6, 6))
    B = jnp.zeros((nw, 6, 6), dtype=A.dtype)
    if bem is not None:
        A_bem, B_bem, F_bem = bem
        M = M + A_bem
        B = B + B_bem
        F = F + F_bem
    lin = LinearCoeffs(
        M=M,
        B=B,
        C=stat.C_struc + stat.C_hydro + C_moor,
        F=F,
    )
    return solve_dynamics(members, kin, wave, env, lin, n_iter=n_iter,
                          method=method, remat=remat, relax=relax, tik=tik)


def _sharding_commit(mesh):
    """tree-wise ``device_put`` of arguments onto their shard_map specs
    (AOT executables check input placement strictly, so every process must
    commit identically before lower/call)."""
    def commit(tree, specs):
        if tree is None:
            return None
        return jax.tree_util.tree_map(
            lambda a, p: jax.device_put(a, NamedSharding(mesh, p)),
            tree, specs,
        )
    return commit


def _shard_map():
    try:
        from jax import shard_map                      # jax >= 0.4.35
    except ImportError:                                # pragma: no cover
        from jax.experimental.shard_map import shard_map
    kw = {}
    try:
        import inspect

        if "check_rep" in inspect.signature(shard_map).parameters:
            kw["check_rep"] = False
        elif "check_vma" in inspect.signature(shard_map).parameters:
            kw["check_vma"] = False
    except (ValueError, TypeError):  # pragma: no cover
        pass
    return shard_map, kw


def _local_freq_solve(members, rna, env, wave_l, C_moor, bem_l, exclude,
                      n_iter, method, axis):
    """RAO solve on this device's frequency shard (collectives over ``axis``
    complete the drag linearization's spectral moment and the convergence
    check — see solve_dynamics)."""
    if wave_l.beta is not None:
        env = env.replace(beta=wave_l.beta)
    stat = assemble_statics(members, rna, env)
    kin = node_kinematics(members, wave_l, env)
    A = strip_added_mass(members, env, exclude_potmod=exclude)
    F = strip_excitation(members, kin, env, exclude_potmod=exclude)
    nw_l = wave_l.w.shape[0]
    M = jnp.broadcast_to(stat.M_struc + A, (nw_l, 6, 6))
    B = jnp.zeros((nw_l, 6, 6), dtype=A.dtype)
    if bem_l is not None:
        M = M + bem_l[0]
        B = B + bem_l[1]
        F = F + bem_l[2]
    lin = LinearCoeffs(M=M, B=B, C=stat.C_struc + stat.C_hydro + C_moor, F=F)
    return solve_dynamics(members, kin, wave_l, env, lin,
                          n_iter=n_iter, method=method, axis_name=axis)


def forward_response_freq_sharded(
    members: MemberSet,
    rna: RNA,
    env: Env,
    wave: WaveState,
    C_moor: Array,
    mesh: Mesh,
    bem=None,
    n_iter: int = 40,
    method: str = "while",
):
    """Frequency-axis (sequence-parallel) RAO solve over a device mesh.

    The reference's long axis is the frequency grid (serial loop,
    raft/raft.py:1528); here it shards over the mesh's axis via
    ``shard_map``: every device evaluates its own w-bins' kinematics,
    excitation, and 6x6 impedance solves locally, while the two quantities
    that couple bins — the drag linearization's spectral vRMS moment and
    the convergence error — complete with one ``psum``/``pmax`` over ICI
    per fixed-point iteration.  Bitwise-equivalent to the unsharded
    :func:`forward_response` up to reduction order (sharded == unsharded
    tested on an 8-device mesh).

    Requires ``len(wave.w) % mesh.devices.size == 0``.  For composed
    design x frequency parallelism over a 2-D mesh see
    :func:`forward_response_dp_sp`.
    """
    shard_map, kw = _shard_map()
    axis = mesh.axis_names[0]
    n_dev = int(np.prod(mesh.devices.shape))
    nw = int(wave.w.shape[0])
    if nw % n_dev != 0:
        raise ValueError(f"nw={nw} not divisible by {n_dev} devices")
    exclude = bem is not None
    P_w = P(axis)
    # a heading on the wave is a replicated scalar, not a sharded axis
    wave_specs = WaveState(w=P_w, k=P_w, zeta=P_w,
                           beta=None if wave.beta is None else P())
    bem_specs = (P(axis), P(axis), Cx(P(axis), P(axis))) if bem is not None else None

    from raft_tpu.solve.dynamics import RAOResult

    out_specs = RAOResult(
        Xi=Cx(P(axis), P(axis)),
        n_iter=P(),
        converged=P(),
        B_drag=P(),
        F_drag=Cx(P(axis), P(axis)),
    )

    def run(wave_l, bem_l):
        return _local_freq_solve(members, rna, env, wave_l, C_moor, bem_l,
                                 exclude, n_iter, method, axis)

    sharded = shard_map(
        run, mesh=mesh,
        in_specs=(wave_specs, bem_specs),
        out_specs=out_specs,
        **kw,
    )
    # on a mesh spanning several processes (multi-host), host arrays must
    # first become global jax.Arrays — each process materializes its shards
    if is_multiprocess(mesh):
        wave, bem = stage_global((wave, bem), mesh, (wave_specs, bem_specs))
        return sharded(wave, bem)
    from raft_tpu import cache as _cache

    if _cache.is_enabled():
        # AOT registry over the shard_mapped program (single-process
        # meshes only: a multi-host executable is not portably storable).
        # Inputs are committed to the shard_map specs FIRST so the lowered
        # executable's placement matches the call in every process —
        # whatever placement the caller's arrays arrived with.
        commit = _sharding_commit(mesh)
        wave = commit(wave, wave_specs)
        bem = commit(bem, bem_specs)
        fn = _cache.cached_compile(
            "forward_response_freq_sharded", sharded, (wave, bem),
            consts=(members, rna, env, C_moor), mesh=mesh,
            extra=("n_iter", n_iter, "method", method),
        )
        return fn(wave, bem)
    return sharded(wave, bem)


def forward_response_dp_sp(
    members: MemberSet,
    rna: RNA,
    env: Env,
    wave: WaveState,
    C_moor: Array,
    thetas: Array,
    mesh: Mesh,
    apply_fn=scale_diameters,
    bem=None,
    n_iter: int = 40,
    method: str = "while",
):
    """Composed design x frequency parallelism over a 2-D device mesh.

    The scaling-book layout for this workload: ``mesh.axis_names[0]`` is
    the data-parallel design axis (each device row owns a slice of the
    design batch — embarrassingly parallel, no collectives), and
    ``mesh.axis_names[1]`` is the sequence-parallel frequency axis (each
    device column owns a slice of the w grid; the drag linearization's
    spectral moment and the convergence check complete with ``psum``/
    ``pmax`` over that axis per fixed-point iteration).  One ``shard_map``
    over the 2-D mesh with an inner ``vmap`` over the local design lanes.

    Requires ``len(thetas)`` divisible by the design-axis size and
    ``len(wave.w)`` divisible by the frequency-axis size.  ``bem`` must be
    the STAGED tuple from :func:`stage_bem` — (A[nw,6,6], B[nw,6,6],
    F :class:`Cx` [nw,6], excitation already zeta-scaled) — NOT the raw
    host layout (A[6,6,nw], B, F complex) that the batched sea-state APIs
    take (those re-stage per case; here one sea state is fixed, so staging
    happens once up front).  Returns the RAOResult with a leading
    design-batch axis; agrees with a vmapped :func:`forward_response` up to
    reduction order.
    """
    if bem is not None and not isinstance(bem[2], Cx):
        raise ValueError(
            "forward_response_dp_sp expects the STAGED bem tuple from "
            "stage_bem(bem_raw, wave) — (A[nw,6,6], B[nw,6,6], F Cx[nw,6]) "
            f"— got F of type {type(bem[2]).__name__}; pass the raw "
            "(A[6,6,nw], B, F complex) host tuple through stage_bem first"
        )
    shard_map, kw = _shard_map()
    if mesh.devices.ndim != 2:
        raise ValueError(
            f"forward_response_dp_sp needs a 2-D mesh (design x frequency "
            f"axes); got shape {mesh.devices.shape} with axes {mesh.axis_names}"
        )
    axis_d, axis_f = mesh.axis_names
    n_d, n_f = mesh.devices.shape
    B = int(np.asarray(thetas).shape[0])
    nw = int(wave.w.shape[0])
    if B % n_d != 0:
        raise ValueError(f"design batch {B} not divisible by {n_d} (axis {axis_d!r})")
    if nw % n_f != 0:
        raise ValueError(f"nw={nw} not divisible by {n_f} (axis {axis_f!r})")
    exclude = bem is not None
    P_w = P(axis_f)
    # a heading on the wave is a replicated scalar, not a sharded axis
    wave_specs = WaveState(w=P_w, k=P_w, zeta=P_w,
                           beta=None if wave.beta is None else P())
    bem_specs = (P(axis_f), P(axis_f), Cx(P(axis_f), P(axis_f))) if bem is not None else None

    from raft_tpu.solve.dynamics import RAOResult

    out_specs = RAOResult(
        Xi=Cx(P(axis_d, axis_f), P(axis_d, axis_f)),
        n_iter=P(axis_d),
        converged=P(axis_d),
        B_drag=P(axis_d),
        F_drag=Cx(P(axis_d, axis_f), P(axis_d, axis_f)),
    )

    def run(th_l, wave_l, bem_l):
        return jax.vmap(
            lambda t: _local_freq_solve(
                apply_fn(members, t), rna, env, wave_l, C_moor, bem_l,
                exclude, n_iter, method, axis_f,
            )
        )(th_l)

    sharded = shard_map(
        run, mesh=mesh,
        in_specs=(P(axis_d), wave_specs, bem_specs),
        out_specs=out_specs,
        **kw,
    )
    if is_multiprocess(mesh):
        thetas, wave, bem = stage_global(
            (thetas, wave, bem), mesh, (P(axis_d), wave_specs, bem_specs)
        )
        return sharded(thetas, wave, bem)
    from raft_tpu import cache as _cache

    if _cache.is_enabled():
        commit = _sharding_commit(mesh)
        thetas = commit(jnp.asarray(thetas), P(axis_d))
        wave = commit(wave, wave_specs)
        bem = commit(bem, bem_specs)
        fn = _cache.cached_compile(
            "forward_response_dp_sp", sharded, (thetas, wave, bem),
            consts=(members, rna, env, C_moor), mesh=mesh,
            extra=("n_iter", n_iter, "method", method,
                   *_cache.callable_salt(apply_fn)),
        )
        return fn(thetas, wave, bem)
    return sharded(thetas, wave, bem)


def make_wave_states(w, cases, depth, g: float = 9.81) -> WaveState:
    """Stack sea-state rows into one batched WaveState.

    ``cases``: (B, 2) array-like of [Hs, Tp] rows or (B, 3) of
    [Hs, Tp, beta] rows (heading in rad) — e.g. a design-load-case table
    (the reference's env surface carries beta too, raft/runRAFT.py:68).
    Returns a WaveState whose ``zeta`` (and ``beta``, for 3-column rows)
    has a leading case axis (``w``/``k`` are broadcast), ready for
    :func:`sweep_sea_states`.
    """
    w = jnp.asarray(w, dtype=float)
    cases = np.asarray(cases, dtype=float)
    if cases.ndim == 1:              # one flat row: [Hs, Tp] or [Hs, Tp, beta]
        cases = cases[None, :]
    if cases.ndim != 2 or cases.shape[-1] not in (2, 3):
        raise ValueError(
            f"cases rows must be [Hs, Tp] or [Hs, Tp, beta]; got shape "
            f"{cases.shape}"
        )
    from raft_tpu.core.waves import jonswap, wave_number

    k = wave_number(w, depth, g=g)
    zeta = jnp.stack([jnp.sqrt(jonswap(w, Hs, Tp)) for Hs, Tp in cases[:, :2]])
    B = zeta.shape[0]
    return WaveState(
        w=jnp.broadcast_to(w, (B,) + w.shape),
        k=jnp.broadcast_to(k, (B,) + k.shape),
        zeta=zeta,
        beta=jnp.asarray(cases[:, 2]) if cases.shape[-1] == 3 else None,
    )


def _bem_mode(bem, betas_case) -> str:
    """Classify and validate the ``bem`` argument of the batched
    sea-state APIs: ``"none"``, the raw single-heading ``"raw"`` tuple,
    or the staged heading ``"grid"``.  ONE validation (and one set of
    error messages) shared by the single-call and chunked
    :func:`sweep_sea_states` paths, so they cannot drift."""
    if bem is None:
        return "none"
    if len(bem) == 4:
        return "grid"
    if betas_case is not None:
        raise ValueError(
            "cases vary the wave heading but bem is a single-heading "
            "(A, B, F) tuple; pass the staged heading grid "
            "(betas, F_all, A, B) from Model.calcBEM(headings=...) so "
            "each case gets its own BEM excitation"
        )
    if isinstance(bem[2], Cx):
        raise ValueError(
            "sweep_sea_states expects the raw host (A[6,6,nw], B, "
            "F complex) tuple or the staged heading grid from "
            "Model.calcBEM(headings=...), not the stage_bem output "
            "(F is a Cx): batched sea states re-stage per case, so "
            "pass the pre-staging layout"
        )
    return "raw"


def _make_dlc_case_fn(members, rna, env, C_moor, staged, n_iter,
                      relax: float = 0.8, tik: float = 0.0,
                      health: bool = False):
    """The per-case DLC solve (to be vmapped over the case axis) shared
    by the single-call and chunked :func:`sweep_sea_states` paths — the
    zeta scaling of the staged excitation is the only sea-state-dependent
    part, so it happens per case lane.  The escalation ladder re-uses the
    SAME function unvmapped for its single-lane rungs (``relax``/``tik``
    are the rung knobs), so a salvage solve cannot drift from the batch
    solve.  ``health=True`` additionally returns the lane's device-side
    verdict (converged flag + a finiteness reduction over the full
    response spectra) — static flag, so the default path traces and
    transfers exactly what it always did."""
    from raft_tpu.parallel.optimize import nacelle_accel_std

    def one(wave, F_re, F_im):
        # forward_response folds the lane's wave.beta into env itself
        b = (_stage_zeta((staged[0], staged[1], F_re, F_im), wave.zeta)
             if staged is not None else None)
        out = forward_response(members, rna, env, wave, C_moor, bem=b,
                               n_iter=n_iter, relax=relax, tik=tik)
        abs2 = out.Xi.abs2()
        res = (abs2, nacelle_accel_std(out.Xi, wave, rna), out.n_iter)
        if health:
            return res + (out.converged, jnp.isfinite(abs2).all())
        return res

    return one


def sweep_sea_states(
    members: MemberSet,
    rna: RNA,
    env: Env,
    waves: WaveState,
    C_moor: Array,
    bem=None,
    n_iter: int = 25,
    mesh: Mesh | None = None,
    chunk: int | None = None,
    pipeline_depth: int | None = None,
    health: bool = False,
    escalate: bool = True,
):
    """One design x a batch of sea states in a single compiled call — the
    design-load-case (DLC) table evaluation of a WEIS outer loop.
    ``mesh``: optional 1-D device mesh; the case axis is embarrassingly
    parallel and shards across it (case count divisible by mesh size).

    ``chunk``: split the case table into ``chunk``-sized sub-batches
    (case count divisible by ``chunk``) executed through the
    dispatch-ahead pipeline (:mod:`raft_tpu.parallel.pipeline`): the
    host-side staging of chunk ``k+1`` — the per-case heading
    interpolation and sea-state slicing — overlaps the device compute of
    chunk ``k``, and with a heading-grid ``bem`` the per-chunk staged
    excitation is DONATED to the compiled solve (its buffer is reused in
    place for the ``Xi_abs2`` output; ``RAFT_TPU_DONATE=0`` opts out).
    One chunk-sized executable is compiled and reused for every chunk;
    results match the unchunked call to float eps (same per-lane
    program, but XLA may vectorize the two batch sizes differently —
    pinned at rtol=1e-12 on CPU in tests/test_pipeline.py) and the
    returned dict gains a ``"pipeline"`` stats block.  ``pipeline_depth``
    overrides the dispatch-ahead window (default
    ``RAFT_TPU_PIPELINE_DEPTH`` or 2).  Mutually exclusive with
    ``mesh`` (chunking is a single-device throughput feature).

    ``waves``: batched WaveState from :func:`make_wave_states` — all cases
    must share one uniform frequency grid (checked; the response integral
    uses a single dw).  The wave kinematics, excitation, and the whole
    drag-linearized fixed point (the drag linearization is sea-state-
    dependent) are vmapped over the case axis.  With ``waves.beta`` set
    (3-column DLC rows), each case lane additionally carries its own wave
    heading through the node kinematics.  Note the staged ``bem``
    excitation is zeta-scaled, so it must be staged per case — pass the raw
    coefficient tuple and this function stages it under the vmap.

    ``bem``: either the heading-independent raw tuple (A[6,6,nw], B, F[6,nw]
    complex), or — required when headings vary across cases — the staged
    heading GRID (betas_grid, F_all[nb,6,nw], A[6,6,nw], B[6,6,nw]) that
    ``Model.calcBEM(headings=...)`` stages (``model._bem_headings``): each
    case's excitation is interpolated to its heading on the host before the
    compiled sweep (the solver side of the grid is
    :func:`raft_tpu.model.solve_bem_heading_grid`, the capability of the
    reference's HAMS heading grids, hams/pyhams.py:196-289).

    ``health=True`` turns on the resilience contract
    (:mod:`raft_tpu.resilience`): every case lane gets a device-side
    ``(converged, finite, n_iter)`` verdict, failed lanes are
    QUARANTINED instead of poisoning the batch and — with ``escalate``
    (the default) — re-solved through the escalation ladder (each rung
    its own AOT-cached executable).  The result dict gains per-lane
    ``"converged"``/``"finite"`` arrays and a ``"health"`` summary block
    (quarantined/salvaged/rungs used); salvaged lanes' statistics are
    patched in place, unsalvaged lanes stay NaN but are REPORTED.  Off
    (the default) the call traces, transfers, and returns exactly what
    it always did.
    """
    w_rows = np.asarray(waves.w)
    if not (w_rows == w_rows[0]).all():
        raise ValueError("sweep_sea_states requires one shared frequency "
                         "grid across cases (make_wave_states builds one)")
    B = int(waves.zeta.shape[0])
    betas_case = None if waves.beta is None else np.asarray(waves.beta)

    if chunk is not None:
        if mesh is not None:
            raise ValueError(
                "chunked (pipelined) sweep_sea_states does not compose "
                "with a mesh: chunking bounds single-device HBM while a "
                "mesh shards the case axis — pick one")
        return _sweep_sea_states_chunked(
            members, rna, env, waves, C_moor, bem, n_iter,
            int(chunk), pipeline_depth, B, betas_case,
            health=health, escalate=escalate)

    # pre-convert the coefficient layout once on host so the vmapped body
    # is pure jnp: per-case excitation (heading interpolation) and the zeta
    # scaling (the only sea-state-dependent parts) happen per case lane
    mode = _bem_mode(bem, betas_case)
    staged = None        # (A[nw,6,6], B[nw,6,6]) device coefficient layout
    F_ax = None          # vmap axis of the excitation args (0 = per case)
    if mode == "grid":                       # staged heading grid
        betas_eval = (betas_case if betas_case is not None
                      else np.full(B, float(env.beta)))
        A_dev, B_dev, F_re_h, F_im_h = _stage_heading_rows(bem, betas_eval)
        F_ax = 0                             # (B,nw,6) per-case excitation
        staged = (A_dev, B_dev)
    elif mode == "raw":
        # one shared heading: stage the excitation ONCE, (nw,6), and
        # broadcast it per lane via vmap in_axes=None — not B device
        # copies (only the zeta scaling differs per case)
        A_dev, B_dev, F_re_h, F_im_h = _bem_device_layout(bem)
        staged = (A_dev, B_dev)

    one = _make_dlc_case_fn(members, rna, env, C_moor, staged, n_iter,
                            health=health)

    # dummy excitation keeps one signature when bem is None
    F_re = F_re_h if staged is not None else jnp.zeros(())
    F_im = F_im_h if staged is not None else jnp.zeros(())
    jit_kw = {}
    if mesh is not None:
        if mesh.devices.ndim != 1:
            raise ValueError(f"sweep_sea_states expects a 1-D mesh; got "
                             f"shape {mesh.devices.shape}")
        n_dev = int(mesh.devices.shape[0])
        if B % n_dev != 0:
            raise ValueError(f"{B} sea states not divisible by {n_dev} devices")
        sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
        f_shard = sharding if F_ax == 0 else NamedSharding(mesh, P())
        jit_kw["in_shardings"] = (sharding, f_shard, f_shard)
    from raft_tpu import cache as _cache

    if _cache.is_enabled() and mesh is not None:
        # an AOT executable checks input placement strictly; commit the
        # arguments to the shardings the jit path would have used
        waves = jax.device_put(waves, sharding)
        F_re = jax.device_put(F_re, f_shard)
        F_im = jax.device_put(F_im, f_shard)
    # AOT registry: the compiled DLC-table solve is keyed by the case
    # signature plus everything `one` closes over (plain jit when the
    # cache is off — today's exact dispatch path)
    fn = _cache.cached_callable(
        "sweep_sea_states", jax.vmap(one, in_axes=(0, F_ax, F_ax)),
        (waves, F_re, F_im),
        consts=(members, rna, env, C_moor, staged or ()),
        mesh=mesh, jit_kwargs=jit_kw,
        extra=("n_iter", n_iter, "F_ax", F_ax, "health", bool(health)),
    )
    outs = fn(waves, F_re, F_im)
    abs2, a_nac, iters = outs[:3]
    sigma = response_std(abs2, waves.w[0])
    res = {
        "std dev": np.asarray(sigma),
        "nacelle accel std dev": np.asarray(a_nac),
        "iterations": np.asarray(iters),
        "Xi_abs2": np.asarray(abs2),
    }
    if not health:
        return res
    if mode == "grid":
        lane_F = lambda i: (F_re_h[i], F_im_h[i])          # noqa: E731
    elif mode == "raw":
        lane_F = lambda i: (F_re_h, F_im_h)                # noqa: E731
    else:
        z2 = jnp.zeros(())
        lane_F = lambda i: (z2, z2)                        # noqa: E731
    solve_lane = _dlc_lane_solver(members, rna, env, C_moor, staged,
                                  waves, lane_F)
    return _dlc_health_finish(res, outs[3], outs[4], waves, solve_lane,
                              n_iter, escalate)


def _dlc_lane_solver(members, rna, env, C_moor, staged, waves, lane_F):
    """The escalation ladder's ``solve_lane`` callback over a DLC table:
    ONE case re-solved alone with a rung's knobs, through the SAME
    per-case function as the batch sweep (``_make_dlc_case_fn`` — a
    salvage solve cannot drift from the batch solve) and its own
    AOT-cached executable per rung.  ``lane_F(idx)`` supplies the lane's
    excitation args (staged rows in grid mode, the shared pair in raw
    mode, dummy zeros otherwise)."""
    from raft_tpu import cache as _cache

    # one executable per rung, not per lane: lanes share shapes, so the
    # rung knobs fully determine the program — memoized here so the
    # "a rung used twice compiles once" contract holds even with the
    # warm-start cache disabled (where cached_callable returns a fresh
    # jax.jit per call).  Single-flight under the lock: concurrent lane
    # salvages (a daemon serving requests in threads) build each rung
    # exactly once instead of racing the get-or-compute.
    rung_fns: dict = {}
    rung_lock = threading.Lock()

    def solve_lane(idx, n_iter_r, relax_r, tik_r):
        wv = WaveState(
            w=waves.w[idx], k=waves.k[idx], zeta=waves.zeta[idx],
            beta=None if waves.beta is None else waves.beta[idx])
        F_re_i, F_im_i = lane_F(idx)
        with rung_lock:
            fn1 = rung_fns.get((n_iter_r, relax_r, tik_r))
            if fn1 is None:
                one_r = _make_dlc_case_fn(members, rna, env, C_moor, staged,
                                          n_iter_r, relax=relax_r,
                                          tik=tik_r, health=True)
                fn1 = _cache.cached_callable(
                    "resilience.ladder.dlc", one_r, (wv, F_re_i, F_im_i),
                    consts=(members, rna, env, C_moor, staged or ()),
                    extra=("n_iter", n_iter_r, "relax", relax_r,
                           "tik", tik_r),
                )
                rung_fns[(n_iter_r, relax_r, tik_r)] = fn1
        abs2_i, a_i, it_i, conv_i, fin_i = fn1(wv, F_re_i, F_im_i)
        # host-side by contract: fn1 is the compiled rung executable,
        # this driver fetches its outputs for the quarantine bookkeeping
        return ((np.asarray(abs2_i), np.asarray(a_i), np.asarray(it_i)),  # graftlint: disable=GL106
                bool(np.asarray(conv_i)), bool(np.asarray(fin_i)),  # graftlint: disable=GL102,GL106
                int(np.asarray(it_i)))  # graftlint: disable=GL102,GL106

    return solve_lane


def _health_finish(res, conv, finite, payload_keys, solve_lane, n_iter,
                   escalate, std_from=None, extra=None):
    """Shared host-side health tail for every sweep path (design-theta
    and sea-state, chunked and unchunked — one implementation so the
    quarantine bookkeeping cannot drift between them): salvaged lanes
    are patched in place into the ``payload_keys`` result arrays (the
    ladder payload, in ``solve_lane``'s record order), ``"std dev"`` is
    re-derived from the patched spectra when ``std_from=(key, w)``, and
    per-lane verdict arrays plus the ``health`` summary block are
    attached.  The healthy common case attaches the verdicts and
    returns — no array copies, no std-dev recompute."""
    from raft_tpu.resilience import health as _health
    from raft_tpu.resilience import ladder as _ladder

    conv = np.asarray(conv).astype(bool).reshape(-1)
    finite = np.asarray(finite).astype(bool).reshape(-1)
    host_arrays = [res[k] for k in payload_keys]
    if not len(_health.failed_lanes(conv, finite, host_values=host_arrays)):
        res["converged"] = conv
        res["finite"] = finite
        res["health"] = _health.summarize([], len(conv), extra=extra)
        return res
    payload = [np.array(res[k]) for k in payload_keys]
    iters = payload[payload_keys.index("iterations")]
    records, conv, finite = _ladder.quarantine_and_salvage(
        payload, conv, finite, solve_lane, n_iter,
        escalate=escalate, iters=iters)
    for k, a in zip(payload_keys, payload):
        res[k] = a
    if std_from is not None:
        key, w = std_from
        res["std dev"] = np.asarray(response_std(jnp.asarray(res[key]), w))
    res["converged"] = conv
    res["finite"] = finite
    res["health"] = _health.summarize(records, len(conv), extra=extra)
    return res


def _dlc_health_finish(res, conv, finite, waves, solve_lane, n_iter,
                       escalate, extra=None):
    """Sea-state-sweep instantiation of :func:`_health_finish`."""
    return _health_finish(
        res, conv, finite,
        ["Xi_abs2", "nacelle accel std dev", "iterations"],
        solve_lane, n_iter, escalate,
        std_from=("Xi_abs2", waves.w[0]), extra=extra)


def _sweep_sea_states_chunked(members, rna, env, waves, C_moor, bem,
                              n_iter, chunk, pipeline_depth, B, betas_case,
                              health=False, escalate=True):
    """Pipelined chunk execution of the DLC table (see
    :func:`sweep_sea_states` ``chunk=``): per-chunk host staging
    overlapped with device compute, heading-grid excitation donated.
    With ``RAFT_TPU_CKPT`` armed, every fetched chunk is persisted to the
    durable chunk store (:mod:`raft_tpu.resilience.checkpoint`) and a
    re-run resumes at the first missing chunk."""
    from raft_tpu import cache as _cache
    from raft_tpu.parallel import pipeline as _pipe

    if B % chunk != 0:
        raise ValueError(f"{B} sea states not divisible by chunk={chunk}")

    mode = _bem_mode(bem, betas_case)
    grid_mode = mode == "grid"
    staged = None        # (A[nw,6,6], B[nw,6,6]) loop-invariant layout
    F_ax = None
    F_re_all = F_im_all = None
    betas_eval = None
    if grid_mode:
        F_ax = 0
        betas_eval = (betas_case if betas_case is not None
                      else np.full(B, float(env.beta)))
        # coefficient layout staged ONCE; the per-chunk host work is the
        # heading interpolation of that chunk's excitation rows
        A_dev, B_dev, _, _ = _bem_device_layout(
            (bem[2], bem[3], np.asarray(bem[1])[0]))
        staged = (A_dev, B_dev)
    elif mode == "raw":
        A_dev, B_dev, F_re_all, F_im_all = _bem_device_layout(bem)
        staged = (A_dev, B_dev)

    one = _make_dlc_case_fn(members, rna, env, C_moor, staged, n_iter,
                            health=health)

    def stage(k):
        sl = slice(k * chunk, (k + 1) * chunk)
        wv = WaveState(
            w=waves.w[sl], k=waves.k[sl], zeta=waves.zeta[sl],
            beta=None if waves.beta is None else waves.beta[sl])
        if grid_mode:
            # rows-only per-chunk staging (UNcached: the work is exactly
            # what the pipeline overlaps, and going through the staging
            # cache here would re-content-hash the full heading grid —
            # plus rebuild the already-staged A/B layout — every chunk)
            F_re, F_im = _rows_device_layout(
                _interp_rows_host(bem[0], bem[1], betas_eval[sl]))
            return (wv, F_re, F_im)          # fresh buffers every chunk
        if staged is not None:               # one shared heading: (nw,6)
            return (wv, F_re_all, F_im_all)  # replicated via in_axes=None
        z = jnp.zeros(())
        return (wv, z, z)

    # donation: only the per-case excitation real part has a usable alias
    # (F_re (chunk,nw,6) is reused in place for the Xi_abs2 output, which
    # has exactly that shape/dtype); donating the other staged leaves
    # would find no matching output and only warn.  Freshly staged every
    # chunk above, so the invalidation is safe by construction.
    donate = grid_mode and _pipe.donation_enabled()
    jit_kw = {"donate_argnums": (1,)} if donate else {}
    # chunk 0 is staged once and reused for both the compile-example
    # signature and its own dispatch (staging twice would re-hash the
    # heading grid and re-transfer the excitation for nothing; the
    # buffers are consumed only at dispatch, so the reuse is safe)
    staged0 = stage(0)
    extra = ("n_iter", n_iter, "F_ax", F_ax, "chunk", chunk,
             "health", bool(health))
    fn = _cache.cached_callable(  # graftlint: disable=GL403 — chunked pipeline splits the case axis on the HOST (single-host by construction); sweep_designs(mesh=) is the sharded path
        "sweep_sea_states", jax.vmap(one, in_axes=(0, F_ax, F_ax)),
        staged0,
        consts=(members, rna, env, C_moor, staged or ()),
        jit_kwargs=jit_kw,
        extra=extra,
    )
    # durable per-chunk result store (RAFT_TPU_CKPT): keyed exactly like
    # the executable above, PLUS a content hash of the argument VALUES.
    # The AOT key hashes call arguments abstractly (shape/dtype — right
    # for an executable, which is input-value-agnostic), but stored
    # RESULTS depend on the values: two DLC tables with identical shapes
    # must land in different stores, or a resume would serve table A's
    # responses for table B.  The hashed sources are the full sea-state
    # table and the excitation-bearing bem arrays the per-chunk staging
    # reads (A/B coefficient layouts are value-hashed via consts already).
    from raft_tpu.resilience import checkpoint as _ckpt

    data_leaves = [waves.w, waves.k, waves.zeta]
    if waves.beta is not None:
        data_leaves.append(waves.beta)
    if grid_mode:
        data_leaves += [bem[0], bem[1]]
    elif staged is not None:
        data_leaves += [F_re_all, F_im_all]
    # (donation is NOT in the store key: it changes buffer aliasing, never
    # results, so a resume stays valid across a RAFT_TPU_DONATE flip)
    store = _ckpt.store_for(
        "sweep_sea_states", staged0,
        consts=(members, rna, env, C_moor, staged or ()),
        extra=(*extra, "data_sha", _ckpt.content_hash(data_leaves)),
        n_chunks=B // chunk)
    results, stats = _pipe.run_pipelined(
        fn, range(B // chunk), depth=pipeline_depth,
        stage=lambda k: staged0 if k == 0 else stage(k),
        donate_argnums=(1,) if donate else (),
        ckpt=store,
    )
    abs2 = np.concatenate([r[0] for r in results])
    a_nac = np.concatenate([np.atleast_1d(r[1]) for r in results])
    iters = np.concatenate([np.atleast_1d(r[2]) for r in results])
    sigma = response_std(abs2, waves.w[0])
    res = {
        "std dev": np.asarray(sigma),
        "nacelle accel std dev": a_nac,
        "iterations": iters,
        "Xi_abs2": abs2,
        "pipeline": stats.to_dict(),
    }
    if store is not None:
        res["checkpoint"] = store.to_dict()
    if not health:
        return res
    conv = np.concatenate([np.atleast_1d(r[3]) for r in results])
    finite = np.concatenate([np.atleast_1d(r[4]) for r in results])
    if grid_mode:
        def lane_F(i):
            F_re, F_im = _rows_device_layout(
                _interp_rows_host(bem[0], bem[1], betas_eval[i:i + 1]))
            return F_re[0], F_im[0]
    elif staged is not None:
        lane_F = lambda i: (F_re_all, F_im_all)            # noqa: E731
    else:
        z2 = jnp.zeros(())
        lane_F = lambda i: (z2, z2)                        # noqa: E731
    solve_lane = _dlc_lane_solver(members, rna, env, C_moor, staged,
                                  waves, lane_F)
    return _dlc_health_finish(res, conv, finite, waves, solve_lane,
                              n_iter, escalate)


def spread_sea_state(w, Hs, Tp, depth, beta0: float = 0.0, n_dir: int = 7,
                     s: float = 2.0, g: float = 9.81) -> WaveState:
    """Directionally-spread (short-crested) sea state as a batched WaveState.

    The total JONSWAP energy is split over ``n_dir`` directions by the
    cos^2s spreading function (:func:`raft_tpu.core.waves.spreading_weights`)
    about the mean heading ``beta0``: lane j carries heading
    ``beta0 + offset_j`` and amplitude ``sqrt(w_j) * zeta`` so the lanes'
    variances sum to the long-crested total.  Feed the result to
    :func:`directional_response`.  The reference models long-crested seas
    only; this is the IEC short-crested-sea capability on top of the
    per-case heading axis.
    """
    from raft_tpu.core.waves import jonswap, spreading_weights, wave_number

    offsets, wts = spreading_weights(n_dir=n_dir, s=s)
    w = jnp.asarray(w, dtype=float)
    k = wave_number(w, depth, g=g)
    zeta = jnp.sqrt(jonswap(w, Hs, Tp))
    n = len(offsets)
    return WaveState(
        w=jnp.broadcast_to(w, (n,) + w.shape),
        k=jnp.broadcast_to(k, (n,) + k.shape),
        zeta=jnp.sqrt(jnp.asarray(wts))[:, None] * zeta[None, :],
        beta=beta0 + jnp.asarray(offsets),
    )


def mixed_sea_state(w, components, depth, g: float = 9.81) -> WaveState:
    """Multi-component (e.g. bimodal wind-sea + swell) sea state.

    ``components``: rows of [Hs, Tp, beta] — each an independent JONSWAP
    component with its own heading (a classic North-Sea case: local wind
    sea at one heading plus long-period swell from a storm elsewhere).
    Returns a batched WaveState with one lane per component, for
    :func:`directional_response`: the components are independent linear
    wave systems, so the total response variance is the lane sum — the
    same combination rule as the directional-spreading lanes.  The
    reference carries a single unimodal spectrum only.
    """
    comps = np.asarray(components, dtype=float)
    if comps.ndim != 2 or comps.shape[1] != 3:
        raise ValueError(
            f"components must be rows of [Hs, Tp, beta]; got shape "
            f"{comps.shape}"
        )
    return make_wave_states(w, comps, depth, g=g)


def directional_response(
    members: MemberSet,
    rna: RNA,
    env: Env,
    waves_dir: WaveState,
    C_moor: Array,
    bem=None,
    n_iter: int = 25,
    mesh: Mesh | None = None,
):
    """Response statistics in a directionally-spread sea.

    ``waves_dir``: the batched WaveState from :func:`spread_sea_state` —
    each lane is one direction of the short-crested sea.  The directions
    are independent linear components, so the lanes ride the same batched
    machinery as a DLC table (:func:`sweep_sea_states`, including the
    heading-grid ``bem`` staging and optional mesh sharding) and the total
    variance is the per-direction sum:
    ``sigma_total^2 = sum_j sigma_j^2``.  Approximation to note: the drag
    linearization runs per direction (directions don't couple through the
    linearized drag), consistent with treating components as independent.

    Returns {"std dev": (6,), "nacelle accel std dev": (), "per direction":
    full sweep dict with the (n_dir, ...) breakdown}.
    """
    per = sweep_sea_states(members, rna, env, waves_dir, C_moor, bem=bem,
                           n_iter=n_iter, mesh=mesh)
    return {
        "std dev": np.sqrt((per["std dev"] ** 2).sum(axis=0)),
        "nacelle accel std dev": float(
            np.sqrt((per["nacelle accel std dev"] ** 2).sum())
        ),
        "per direction": per,
    }


def response_std(Xi_abs2: Array, w: Array) -> Array:
    """Std dev of each DOF from spectral amplitudes |Xi| (zeta = sqrt(S)).

    Double-where guard: symmetric designs have exactly-zero response in the
    unexcited DOFs, and d(sqrt)/dx at 0 would turn their zero cotangents
    into NaN for the whole gradient."""
    dw = w[1] - w[0]
    s = jnp.sum(Xi_abs2, axis=-2) * dw
    s_safe = jnp.where(s > 0, s, 1.0)
    return jnp.where(s > 0, jnp.sqrt(s_safe), 0.0)


def sweep(
    members: MemberSet,
    rna: RNA,
    env: Env,
    wave: WaveState,
    C_moor: Array,
    thetas: Array,
    apply_fn=scale_diameters,
    mesh: Mesh | None = None,
    n_iter: int = 25,
    return_xi: bool = True,
    health: bool = False,
    escalate: bool = True,
):
    """Evaluate a batch of design variants, sharded over the mesh.

    ``thetas``: (B, ...) design-parameter batch; ``apply_fn(members, theta)``
    produces each variant.  Returns dict of per-design arrays (std devs,
    convergence iterations) pulled to host.

    ``return_xi=False`` drops the full (B, nw, 6) ``Xi_abs2`` tensor from
    the result: the response std dev is reduced ON DEVICE inside the
    compiled sweep, so only the (B, 6) statistics (plus iteration counts)
    cross the device->host boundary — the mode for throughput paths (the
    bench) that never look at the raw spectra.  The statistics are
    computed from the identical ``Xi`` either way.

    ``health=True``: the resilience contract (see
    :func:`sweep_sea_states`) — per-lane device-side ``(converged,
    finite, n_iter)`` verdicts (``finite`` reduced over the full spectra
    even in ``return_xi=False`` mode, where they never cross to host),
    quarantine of failed lanes, escalation-ladder salvage, and a
    ``"health"`` summary block in the result.  Off by default: the fast
    path is byte-identical to the pre-resilience sweep.
    """

    def one(theta):
        m = apply_fn(members, theta)
        out = forward_response(m, rna, env, wave, C_moor, n_iter=n_iter)
        abs2 = out.Xi.abs2()
        stat = abs2 if return_xi else response_std(abs2, wave.w)
        if health:
            return stat, out.n_iter, out.converged, jnp.isfinite(abs2).all()
        return stat, out.n_iter

    from raft_tpu import cache as _cache

    jit_kw = {}
    if mesh is not None:
        sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
        thetas = jax.device_put(thetas, sharding)
        jit_kw["in_shardings"] = sharding
    # AOT registry: keyed by the theta signature + the closure (geometry,
    # environment, mooring) + the apply_fn identity; plain jit when off
    fn = _cache.cached_callable(
        "sweep", jax.vmap(one), (thetas,),
        consts=(members, rna, env, wave, C_moor),
        mesh=mesh, jit_kwargs=jit_kw,
        extra=("n_iter", n_iter, "return_xi", bool(return_xi),
               "health", bool(health), *_cache.callable_salt(apply_fn)),
    )
    outs = fn(thetas)
    out0, iters = outs[:2]
    if return_xi:
        sigma = response_std(out0, wave.w)
        res = {
            "std dev": np.asarray(sigma),
            "iterations": np.asarray(iters),
            "Xi_abs2": np.asarray(out0),
        }
    else:
        res = {
            "std dev": np.asarray(out0),
            "iterations": np.asarray(iters),
        }
    if not health:
        return res

    thetas_np = np.asarray(thetas)
    # one executable per rung even with cache off; single-flight under
    # the lock against concurrent lane salvages
    rung_fns: dict = {}
    rung_lock = threading.Lock()

    def solve_lane(idx, n_iter_r, relax_r, tik_r):
        th = jnp.asarray(thetas_np[idx])
        with rung_lock:
            fn1 = rung_fns.get((n_iter_r, relax_r, tik_r))
            if fn1 is None:
                def f(theta, _n=n_iter_r, _r=relax_r, _t=tik_r):
                    m = apply_fn(members, theta)
                    out = forward_response(m, rna, env, wave, C_moor,
                                           n_iter=_n, relax=_r, tik=_t)
                    abs2 = out.Xi.abs2()
                    stat = abs2 if return_xi else response_std(abs2, wave.w)
                    return (stat, out.n_iter, out.converged,
                            jnp.isfinite(abs2).all())

                fn1 = _cache.cached_callable(
                    "resilience.ladder.sweep", f, (th,),
                    consts=(members, rna, env, wave, C_moor),
                    extra=("n_iter", n_iter_r, "relax", relax_r,
                           "tik", tik_r, "return_xi", bool(return_xi),
                           *_cache.callable_salt(apply_fn)),
                )
                rung_fns[(n_iter_r, relax_r, tik_r)] = fn1
        stat, it, conv_i, fin_i = fn1(th)
        return ((np.asarray(stat), np.asarray(it)),
                bool(np.asarray(conv_i)), bool(np.asarray(fin_i)),
                int(np.asarray(it)))

    return _health_finish(
        res, outs[2], outs[3],
        ["Xi_abs2", "iterations"] if return_xi else ["std dev", "iterations"],
        solve_lane, n_iter, escalate,
        std_from=("Xi_abs2", wave.w) if return_xi else None)


def _sig_label(sig) -> str:
    """Stable short label of a bucket signature for metric/span names
    ("16x64x128" = segments x nodes x nw)."""
    return f"{sig.segments}x{sig.nodes}x{sig.nw}"


def _record_bucket_metrics(_obs, batch, B, dispatch_s) -> None:
    """Per-bucket registry feed of one :func:`_sweep_designs_bucket`
    dispatch: the latency histogram (one per bucket signature — the
    ladder is a handful of classes, so the name cardinality is bounded
    by construction), the mixed-stream throughput gauge, and the lane
    counter the obs-smoke overhead guard reads."""
    label = _sig_label(batch.sig)
    _obs.metrics.histogram(f"sweep_designs.dispatch_s[{label}]").observe(
        dispatch_s)
    if dispatch_s > 0:
        # physical solves (lanes x physical frequency bins) per second,
        # same accounting as the bench's north-star metric
        _obs.metrics.gauge("sweep_designs.solves_per_s").set(
            B * batch.nw / dispatch_s)
    _obs.metrics.counter("sweep_designs.lanes").inc(B)


def _stage_bucket_global(args, in_axes, mesh):
    """Host-staged bucket args -> globally-sharded jax.Arrays with the
    design (batch-leading) axis split over the mesh's first axis.

    The GL403 contract: a pod-scale design batch must enter the compiled
    call SHARDED, not host-replicated onto every device — each process
    materializes only its own lanes (:func:`stage_global`), and jit
    infers the executable's input shardings from the committed arrays."""
    from jax.sharding import PartitionSpec as P
    from raft_tpu.parallel import multihost as _mh

    axis = mesh.axis_names[0]
    n = int(mesh.devices.shape[0])
    B = len(args[0].seg_l)
    if B % n != 0:
        raise ValueError(
            f"sweep_designs: bucket lane count {B} is not divisible by "
            f"mesh axis {axis!r} size {n} — pad the design batch or use "
            "a divisor-sized mesh")
    return tuple(
        _mh.stage_global(
            a, mesh,
            jax.tree_util.tree_map(
                lambda _, _ax=ax: P(axis) if _ax == 0 else P(), a))
        for a, ax in zip(args, in_axes))


def _gather_bucket_outputs(outs, mesh):
    """Sharded bucket outputs -> host arrays every process fully holds.

    Single-process meshes: the global arrays are already fully
    addressable, pass through.  Multi-process meshes: each host owns only
    its lanes' shards, so the result-scatter (original design order)
    needs an explicit cross-host gather."""
    from raft_tpu.parallel import multihost as _mh

    if not _mh.is_multiprocess(mesh):
        return outs
    from jax.experimental import multihost_utils

    return tuple(multihost_utils.process_allgather(o, tiled=True)
                 for o in outs)


def _dispatch_sharded_bucket(one, args, in_axes, mesh, extra):
    """One bucket's batch dispatch with the design axis sharded over
    ``mesh``'s first axis: ``shard_map`` hands each device its own lane
    block and a local ``vmap`` solves it — pure data parallelism, zero
    collectives (the lanes are independent; only the host-side gather
    crosses shards).  ``shard_map`` rather than bare GSPMD because the
    CPU backend refuses multi-process jit-partitioned computations (the
    freq-sharded precedent), and a shard_mapped program runs identically
    on single- and multi-process meshes.

    Single-process meshes go through the AOT registry (``mesh`` folds
    the topology into the key); multi-process meshes dispatch eagerly —
    a multi-host executable is not portably storable."""
    from raft_tpu import cache as _cache

    shard_map, kw = _shard_map()
    axis = mesh.axis_names[0]
    g_args = _stage_bucket_global(args, in_axes, mesh)
    in_specs = tuple(P(axis) if ax == 0 else P() for ax in in_axes)

    def run(*local_args):
        return jax.vmap(one, in_axes=in_axes)(*local_args)

    sharded = shard_map(run, mesh=mesh, in_specs=in_specs,
                        out_specs=P(axis), **kw)
    if is_multiprocess(mesh):
        outs = jax.block_until_ready(sharded(*g_args))
        return _gather_bucket_outputs(outs, mesh)
    fn = _cache.cached_callable("sweep_designs", sharded, g_args,
                                extra=(*extra, "sharded"), mesh=mesh)
    return jax.block_until_ready(fn(*g_args))


def _sweep_designs_bucket(batch, n_iter, return_xi, health, escalate,
                          chunk, pipeline_depth, mesh=None):
    """Solve ONE shape bucket's stacked design batch as one padded device
    dispatch: the per-design arrays (members, RNA, env, wave, mooring,
    optional BEM) are batch-leading vmapped INPUTS — not closure
    constants like :func:`sweep` — so the compiled executable is
    design-agnostic: any mix of designs in this bucket class (and batch
    size) reuses it, in-process and through the AOT registry.

    ``mesh``: optional 1-D device mesh — the design axis is sharded over
    its first axis (multi-host meshes included; lane salvage and the
    result scatter stay host-side, so ``health`` composes).  The chunked
    pipeline path is mutually exclusive with ``mesh``: chunking splits
    the lane axis on the HOST, sharding splits it on the mesh."""
    from raft_tpu import cache as _cache
    from raft_tpu import obs as _obs
    from raft_tpu.build import buckets as _buckets

    if mesh is not None and chunk is not None:
        raise ValueError(
            "sweep_designs: mesh= and chunk= both split the design axis "
            "(mesh over devices, chunk over pipelined host dispatches) — "
            "pass one or the other")
    B = len(batch.fnames)
    has_bem = batch.bem is not None
    dtype = batch.members.seg_l.dtype
    C_moor = (batch.C_moor if batch.C_moor is not None
              else jnp.zeros((B, 6, 6), dtype=dtype))

    def one(members, rna, env, wave, C_moor_i, bem, *, _n=n_iter,
            _relax=0.8, _tik=0.0):
        out = forward_response(members, rna, env, wave, C_moor_i,
                               bem=bem if has_bem else None,
                               n_iter=_n, relax=_relax, tik=_tik)
        abs2 = out.Xi.abs2()
        stat = abs2 if return_xi else response_std(abs2, wave.w)
        if health:
            return stat, out.n_iter, out.converged, jnp.isfinite(abs2).all()
        return stat, out.n_iter

    bem_arg = batch.bem if has_bem else jnp.zeros((), dtype=dtype)
    bem_ax = 0 if has_bem else None
    args = (batch.members, batch.rna, batch.env, batch.wave, C_moor, bem_arg)
    in_axes = (0, 0, 0, 0, 0, bem_ax)
    extra = ("n_iter", n_iter, "return_xi", bool(return_xi),
             "health", bool(health), "has_bem", has_bem,
             *_buckets.ladder_salt())
    pipe_stats = None
    if chunk is not None:
        from raft_tpu.parallel import pipeline as _pipe

        # bucket sizes are EMERGENT from the design mix, so the caller
        # cannot pick a chunk that divides every bucket: clamp to the
        # largest divisor of this bucket's lane count not exceeding the
        # request (worst case 1 = lane-by-lane; chunking is a pipelining
        # optimization, never a correctness constraint)
        chunk = max(d for d in range(1, min(int(chunk), B) + 1)
                    if B % d == 0)

        def stage(k):
            sl = slice(k * chunk, (k + 1) * chunk)
            lanes = jax.tree_util.tree_map(lambda a: a[sl], args[:5])
            # the BEM batch rides the mapped axis too — slice it with the
            # lanes (the dummy scalar is broadcast via in_axes=None)
            b = (jax.tree_util.tree_map(lambda a: a[sl], batch.bem)
                 if has_bem else bem_arg)
            return (*lanes, b)

        staged0 = stage(0)
        fn = _cache.cached_callable(  # graftlint: disable=GL403 — chunked pipeline splits the lane axis on the HOST (single-host by construction); sweep_designs(mesh=) is the sharded path
            "sweep_designs", jax.vmap(one, in_axes=in_axes), staged0,
            extra=(*extra, "chunk", chunk))
        # durable chunk store (RAFT_TPU_CKPT): the executable's key hashes
        # the designs ABSTRACTLY (they are call arguments), but stored
        # RESULTS depend on their values — fold a content hash of every
        # staged batch array into the store key, or a resume would serve
        # design set A's responses for a same-shaped design set B.  The
        # hash forces a host materialization of the whole stacked batch,
        # so it only runs when the store is actually armed.
        from raft_tpu.resilience import checkpoint as _ckpt

        store = None
        if _ckpt.enabled():
            data_leaves = jax.tree_util.tree_flatten(
                (args[:5], batch.bem if has_bem else ()))[0]
            store = _ckpt.store_for(
                "sweep_designs", staged0,
                extra=(*extra, "chunk", chunk,
                       "data_sha", _ckpt.content_hash(data_leaves)),
                n_chunks=B // chunk)
        with _obs.trace.span("sweep_designs/bucket",
                             attrs={"sig": _sig_label(batch.sig),
                                    "lanes": B, "chunk": chunk}):
            t0 = time.perf_counter()
            results, pipe_stats = _pipe.run_pipelined(
                fn, range(B // chunk), depth=pipeline_depth,
                stage=lambda k: staged0 if k == 0 else stage(k),
                ckpt=store)
            dispatch_s = time.perf_counter() - t0
        outs = tuple(np.concatenate([np.atleast_1d(r[j]) for r in results])
                     for j in range(len(results[0])))
    elif mesh is not None:
        with _obs.trace.span("sweep_designs/bucket",
                             attrs={"sig": _sig_label(batch.sig),
                                    "lanes": B, "sharded": True}):
            t0 = time.perf_counter()
            outs = _dispatch_sharded_bucket(one, args, in_axes, mesh,
                                            extra)
            dispatch_s = time.perf_counter() - t0
        # the ledger is skipped here: on a multi-process mesh there is
        # no storable executable to attribute the dispatch to, and a
        # per-host wall time over a pod dispatch would not be comparable
        # to the single-host rows anyway
    else:
        fn = _cache.cached_callable(
            "sweep_designs", jax.vmap(one, in_axes=in_axes), args,
            extra=extra, mesh=mesh)
        # the span times dispatch THROUGH materialization (the compiled
        # call returns futures; the results are fetched right below
        # anyway, so the barrier moves no work — it only makes the
        # latency histogram honest)
        with _obs.trace.span("sweep_designs/bucket",
                             attrs={"sig": _sig_label(batch.sig),
                                    "lanes": B}):
            t0 = time.perf_counter()
            outs = jax.block_until_ready(fn(*args))
            dispatch_s = time.perf_counter() - t0
        # performance ledger: join this measured dispatch with the
        # executable's own flops/bytes accounting (no-op when the cache
        # is off — a plain jitted fn has no artifact identity).  The
        # chunked path is excluded: its wall time spans a pipeline of
        # dispatches, not one executable run.
        _obs.ledger.record("sweep_designs", _sig_label(batch.sig), fn,
                           dispatch_s)
    _record_bucket_metrics(_obs, batch, B, dispatch_s)
    out0, iters = outs[:2]
    if return_xi:
        res = {
            "std dev": np.asarray(response_std(jnp.asarray(out0),
                                               batch.wave.w[0])),
            "iterations": np.asarray(iters),
            "Xi_abs2": np.asarray(out0),
        }
    else:
        res = {"std dev": np.asarray(out0), "iterations": np.asarray(iters)}
    if pipe_stats is not None:
        res["pipeline"] = pipe_stats.to_dict()
        if store is not None:
            res["checkpoint"] = store.to_dict()
    if not health:
        return res

    # one executable per rung even with cache off; single-flight under
    # the lock against concurrent lane salvages
    rung_fns: dict = {}
    rung_lock = threading.Lock()

    def solve_lane(idx, n_iter_r, relax_r, tik_r):
        lane = jax.tree_util.tree_map(lambda a: a[idx], args[:5])
        lane_bem = (jax.tree_util.tree_map(lambda a: a[idx], batch.bem)
                    if has_bem else bem_arg)
        with rung_lock:
            fn1 = rung_fns.get((n_iter_r, relax_r, tik_r))
            if fn1 is None:
                # the rung re-traces `one` (the batch body) with the
                # rung's knobs, so a salvage solve cannot drift from the
                # batch solve
                def g(m_i, r_i, e_i, w_i, c_i, b_i, _n=n_iter_r,
                      _r=relax_r, _t=tik_r):
                    return one(m_i, r_i, e_i, w_i, c_i, b_i,
                               _n=_n, _relax=_r, _tik=_t)

                fn1 = _cache.cached_callable(
                    "resilience.ladder.designs", g, (*lane, lane_bem),
                    extra=(*extra, "rung_n", n_iter_r, "relax", relax_r,
                           "tik", tik_r))
                rung_fns[(n_iter_r, relax_r, tik_r)] = fn1
        stat, it, conv_i, fin_i = fn1(*lane, lane_bem)
        return ((np.asarray(stat), np.asarray(it)),
                bool(np.asarray(conv_i)), bool(np.asarray(fin_i)),
                int(np.asarray(it)))

    return _health_finish(
        res, outs[2], outs[3],
        ["Xi_abs2", "iterations"] if return_xi else ["std dev", "iterations"],
        solve_lane, n_iter, escalate,
        std_from=("Xi_abs2", batch.wave.w[0]) if return_xi else None)


def sweep_designs(
    fnames=None,
    nw: int = 100,
    Hs: float = 8.0,
    Tp: float = 12.0,
    w_min: float = 0.05,
    w_max: float = 2.95,
    with_mooring: bool = True,
    bems=None,
    staged: dict | None = None,
    n_iter: int = 25,
    return_xi: bool = True,
    health: bool = False,
    escalate: bool = True,
    chunk: int | None = None,
    pipeline_depth: int | None = None,
    mesh=None,
):
    """Solve a MIXED batch of different platform designs — one padded
    device dispatch per shape bucket.

    Where :func:`sweep` vmaps parameter variations of ONE staged design
    (the geometry is a closure constant baked into the executable), this
    lifts the per-design arrays into batch-leading vmapped inputs: the
    designs (YAML paths or dicts) are bucketized into a small ladder of
    padded shape classes (:mod:`raft_tpu.build.buckets`, override via
    ``RAFT_TPU_BUCKETS``), staged batch-leading per bucket
    (:func:`raft_tpu.model.stage_designs` — per-design water depth,
    mooring stiffness, masked member padding, zero-response frequency
    padding), and each bucket solves as ONE compiled call.  Compile count
    is O(buckets), not O(designs): a request stream mixing OC3, OC4,
    VolturnUS and arbitrary user designs reuses a handful of executables
    (the AOT registry key carries the ladder version, so every warm
    process shares them too).

    ``staged``: pass a prebuilt :func:`raft_tpu.model.stage_designs`
    result (the ``fnames``/``nw``/sea-state arguments are then ignored
    for staging).  ``bems``: optional per-design raw BEM tuples, staged
    padded (see ``stage_designs``).  ``chunk``: split each bucket's lane
    axis into ``chunk``-sized sub-batches executed through the
    dispatch-ahead pipeline (:mod:`raft_tpu.parallel.pipeline`).
    ``health=True``: the resilience contract per lane — a bad design's
    lane is quarantined and ladder-salvaged without touching its
    bucket-mates (see :func:`sweep_sea_states`).  ``mesh``: optional 1-D
    device mesh (:func:`make_mesh` /
    :func:`raft_tpu.parallel.multihost.global_mesh`) — each bucket's
    design axis is sharded over the mesh's first axis, with the inputs
    staged globally (:func:`stage_global`) so a multi-host job
    materializes only its own lanes; every bucket's lane count must
    divide the mesh size.  Mutually exclusive with ``chunk``.

    Returns a dict in the ORIGINAL design order: ``"std dev"`` (D, 6),
    ``"iterations"`` (D,), ``"Xi_abs2"`` (D, nw, 6) trimmed to the
    physical bins (``return_xi=True``), a ``"buckets"`` stats block
    (ladder, signatures, lane counts, promotions), plus the per-lane
    ``"converged"``/``"finite"``/``"health"`` verdicts when ``health``.
    """
    from raft_tpu.build import buckets as _buckets
    from raft_tpu.model import stage_designs

    if staged is None:
        if fnames is None:
            raise ValueError("sweep_designs needs a design list (fnames) "
                             "or a prebuilt staged= dict")
        staged = stage_designs(fnames, nw=nw, Hs=Hs, Tp=Tp, w_min=w_min,
                               w_max=w_max, with_mooring=with_mooring,
                               bems=bems)
    elif bems is not None:
        raise ValueError(
            "bems cannot be applied to a prebuilt staged= dict (staging "
            "already fixed each batch's BEM layout): pass bems to "
            "stage_designs (or to sweep_designs with fnames)")
    batches = list(staged.values())
    if not batches:
        raise ValueError("no designs staged")
    D = sum(len(b.fnames) for b in batches)
    nw_phys = batches[0].nw

    per_bucket = [
        _sweep_designs_bucket(b, n_iter, return_xi, health, escalate,
                              chunk, pipeline_depth, mesh=mesh)
        for b in batches
    ]

    def scatter(key, trim_nw=False):
        first = per_bucket[0][key]
        out = np.zeros((D,) + first.shape[1:], dtype=first.dtype)
        for b, res in zip(batches, per_bucket):
            out[np.asarray(b.indices)] = res[key]
        if trim_nw and out.ndim >= 3:
            out = out[:, :nw_phys]
        return out

    # report lanes in the caller's original order, like every array
    names = [None] * D
    for b in batches:
        for i, fn in zip(b.indices, b.fnames):
            names[i] = fn
    result = {
        "designs": names,
        "std dev": scatter("std dev"),
        "iterations": scatter("iterations"),
    }
    if return_xi:
        result["Xi_abs2"] = scatter("Xi_abs2", trim_nw=True)
    result["buckets"] = {
        "ladder": _buckets.ladder_salt()[1],
        "n_designs": D,
        "n_buckets": len(batches),
        "signatures": [
            {"segments": b.sig.segments, "nodes": b.sig.nodes,
             "nw": b.sig.nw, "designs": len(b.fnames)}
            for b in batches
        ],
        # promotions THIS staging performed (per-batch deltas recorded by
        # stage_designs), not the process-wide counter — a sweep must not
        # inherit earlier calls' ladder misfits
        "promotions": sum(getattr(b, "promotions", 0) for b in batches),
    }
    for key in ("pipeline", "checkpoint"):
        blocks = {str(tuple(b.sig)): res[key]
                  for b, res in zip(batches, per_bucket) if key in res}
        if blocks:
            result[key] = blocks
    if health:
        result["converged"] = scatter("converged")
        result["finite"] = scatter("finite")
        merged_rungs: dict = {}
        quarantined, unsalvaged, salvaged = [], [], 0
        for b, res in zip(batches, per_bucket):
            h = res["health"]
            idx = list(b.indices)
            quarantined += [idx[i] for i in h["quarantined"]]
            unsalvaged += [idx[i] for i in h["unsalvaged"]]
            salvaged += h["salvaged"]
            for r, n in h["rungs_used"].items():
                merged_rungs[r] = merged_rungs.get(r, 0) + n
        result["health"] = {
            "lanes": D,
            "n_quarantined": len(quarantined),
            "quarantined": sorted(quarantined),
            "salvaged": salvaged,
            "unsalvaged": sorted(unsalvaged),
            "rungs_used": merged_rungs,
            "per_bucket": {str(tuple(b.sig)): res["health"]
                           for b, res in zip(batches, per_bucket)},
        }
    # with RAFT_TPU_OBS armed, every mixed-design sweep leaves a fresh
    # JSONL log + Chrome trace + Prometheus snapshot behind (no-op, and
    # no import cost on the hot path, when the knob is off)
    from raft_tpu import obs as _obs

    _obs.maybe_publish("sweep_designs")
    return result


def grad_response_std(
    members: MemberSet,
    rna: RNA,
    env: Env,
    wave: WaveState,
    C_moor: Array,
    theta: Array,
    dof: int = 0,
    apply_fn=scale_diameters,
    n_iter: int = 25,
):
    """d sigma_dof / d theta — exact co-design gradient through the whole
    pipeline (statics, Morison, drag-linearized fixed point)."""

    def f(th):
        m = apply_fn(members, th)
        out = forward_response(m, rna, env, wave, C_moor, n_iter=n_iter)
        return response_std(out.Xi.abs2(), wave.w)[dof]

    return jax.grad(f)(theta)
