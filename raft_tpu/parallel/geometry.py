"""Geometry parameterizations for design sweeps and co-design gradients.

The north-star workload (BASELINE.json) sweeps "draft/column-radius
variants" of a platform.  On the stacked :class:`~raft_tpu.core.types.
MemberSet` those are *value-only* transforms — node/segment counts never
change — so one compiled sweep covers every variant and ``jax.grad`` flows
through the knob (the shape-static invariant documented on MemberSet).

All transforms here are anisotropic affine warps ``x' = o + D (x - o)``
with a diagonal scale ``D``, applied to a subset of members (by default the
substructure, never the tower):

* positions (``seg_rA``, ``node_r``) warp directly;
* orientations follow the warp: ``q' = D q / |D q|``, with the transverse
  pair re-orthonormalized so rectangular members keep their twist;
* lengths pick up the member's own stretch factor ``|D q|`` (segment
  length, node lumped length, ballast fill length — the fill *fraction* is
  preserved), while cross-section dims (diameters/side lengths) and end-cap
  thicknesses stay fixed;
* everything else (coefficients, masks, ids) is untouched.

Because member ids live in traced arrays, the member-subset masks are
extracted host-side once by a factory (``make_stretch_draft`` /
``make_scale_plan``) and closed over — the returned ``fn(members, s)`` is
then pure and jit/vmap/grad-safe, slotting straight into
:func:`raft_tpu.parallel.sweep.sweep`'s ``apply_fn``.

Verified relations (tests/test_geometry.py): for a fully-vertical spar a
draft stretch anchored at the waterline scales displaced volume, shell and
ballast mass exactly by ``s`` with the waterplane untouched; a plan-radius
scale moves the OC4 offset columns out by exactly ``s`` and grows the
spacing term of the waterplane inertia by ``s^2``.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from raft_tpu.core.types import MemberSet

Array = jnp.ndarray


def _safe_normalize(v, fallback_axis: int):
    """Normalize v, replacing zero rows (padding) by a fixed unit vector.

    Double-where on the squared norm BEFORE the sqrt: padded members carry
    all-zero frames, and the VJP of ``norm`` at 0 is 0 * (0/0) = NaN even
    though the row's value is discarded downstream — the same guard pattern
    as response_std (sweep.py).
    """
    n2 = jnp.sum(v * v, axis=-1, keepdims=True)
    ok = n2 > 0
    unit = jnp.zeros_like(v).at[..., fallback_axis].set(1.0)
    v_s = jnp.where(ok, v, unit)
    n = jnp.sqrt(jnp.where(ok, n2, 1.0))
    return v_s / n, jnp.where(ok[..., 0], n[..., 0], 1.0)


def _warp_frame(q, p1, D):
    """Transform an orthonormal member frame through the diagonal map D.

    q' is the normalized image of q; p1 is mapped and re-orthonormalized
    against q' (preserving twist continuously); p2' closes the right-handed
    triad.  Shapes: q, p1 (..., 3); D (3,).  Zero (padded) frames pass
    through with stretch 1 and finite gradients.
    """
    qn, f = _safe_normalize(q * D, 2)
    p1D = p1 * D
    p1t = p1D - jnp.sum(p1D * qn, axis=-1, keepdims=True) * qn
    p1n, _ = _safe_normalize(p1t, 0)
    p2n = jnp.cross(qn, p1n)
    return qn, p1n, p2n, f


def affine_warp(
    members: MemberSet,
    scale3,
    origin,
    seg_sel: Array,
    node_sel: Array,
) -> MemberSet:
    """Apply ``x' = o + D (x - o)`` to the selected members' geometry.

    ``seg_sel`` (S,) / ``node_sel`` (N,) are boolean masks of which
    segments/nodes move (concrete arrays from a factory, so the result
    keeps MemberSet's static shapes).  End caps reposition and reorient but
    keep their thickness ``seg_l`` (a stretched plate is not what a cap
    bulkhead means physically).
    """
    D = jnp.asarray(scale3, dtype=members.seg_rA.dtype)
    o = jnp.asarray(origin, dtype=members.seg_rA.dtype)

    def pos(r):
        return o + D * (r - o)

    def pick(sel, new, old):
        return jnp.where(sel[(...,) + (None,) * (new.ndim - sel.ndim)], new, old)

    # segments: R columns are [p1, p2, q] (core/transforms.py:member_orientation)
    p1 = members.seg_R[..., :, 0]
    q_n, p1_n, p2_n, f_seg = _warp_frame(members.seg_q, p1, D)
    R_n = jnp.stack([p1_n, p2_n, q_n], axis=-1)
    stretch = jnp.where(members.seg_is_cap, 1.0, f_seg)
    m = members.replace(
        seg_rA=pick(seg_sel, pos(members.seg_rA), members.seg_rA),
        seg_q=pick(seg_sel, q_n, members.seg_q),
        seg_R=pick(seg_sel, R_n, members.seg_R),
        seg_l=pick(seg_sel, members.seg_l * stretch, members.seg_l),
        seg_l_fill=pick(seg_sel, members.seg_l_fill * stretch, members.seg_l_fill),
    )

    # nodes
    qn_n, p1n_n, p2n_n, f_node = _warp_frame(members.node_q, members.node_p1, D)
    return m.replace(
        node_r=pick(node_sel, pos(members.node_r), members.node_r),
        node_q=pick(node_sel, qn_n, members.node_q),
        node_p1=pick(node_sel, p1n_n, members.node_p1),
        node_p2=pick(node_sel, p2n_n, members.node_p2),
        node_dls=pick(node_sel, members.node_dls * f_node, members.node_dls),
    )


def substructure_masks(members: MemberSet):
    """Concrete (host-side) segment/node masks of the substructure members
    (type code > 1; the tower is type <= 1, raft/raft.py:1898-1912).

    Must be called on an untraced MemberSet (the factory pattern below);
    the masks are then closed over by the pure per-variant transform.
    """
    seg_member = np.asarray(members.seg_member)
    seg_type = np.asarray(members.seg_type)
    seg_mask = np.asarray(members.seg_mask)
    # padded segments carry member id -1 — scatter only the valid ones, or
    # the pad's type 0 lands on the highest member id via negative indexing
    n_mem = int(seg_member[seg_mask].max()) + 1
    mem_type = np.zeros(n_mem, dtype=int)
    mem_type[seg_member[seg_mask]] = seg_type[seg_mask]
    seg_sel = (seg_type > 1) & seg_mask
    node_member = np.clip(np.asarray(members.node_member), 0, n_mem - 1)
    node_sel = (mem_type[node_member] > 1) & np.asarray(members.node_mask)
    return jnp.asarray(seg_sel), jnp.asarray(node_sel)


def make_stretch_draft(members: MemberSet, anchor: float = 0.0):
    """Draft-stretch knob: ``fn(members, s)`` scales the substructure's
    vertical extent about ``z = anchor`` (default: the waterline, so the
    keel deepens while the waterplane is untouched).

    On a fully-vertical hull (e.g. the OC3 spar) this scales displaced
    volume, shell mass and ballast mass exactly by ``s``.
    """
    seg_sel, node_sel = substructure_masks(members)

    def fn(m: MemberSet, s) -> MemberSet:
        s = jnp.asarray(s)
        D = jnp.stack([jnp.ones_like(s), jnp.ones_like(s), s])
        return affine_warp(m, D, jnp.array([0.0, 0.0, anchor]), seg_sel, node_sel)

    return fn


def make_scale_plan(members: MemberSet):
    """Column-radius knob: ``fn(members, s)`` scales the substructure's
    plan (x, y) layout about the platform centerline — offset columns move
    radially in/out by ``s``, horizontal pontoons stretch with them,
    vertical members keep their diameters and drafts.
    """
    seg_sel, node_sel = substructure_masks(members)

    def fn(m: MemberSet, s) -> MemberSet:
        s = jnp.asarray(s)
        D = jnp.stack([s, s, jnp.ones_like(s)])
        return affine_warp(m, D, jnp.zeros(3), seg_sel, node_sel)

    return fn
