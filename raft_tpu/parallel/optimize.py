"""Gradient-based co-design: the WEIS inner loop the framework exists for.

The reference positions RAFT as the "Level 1" model of the WEIS controls
co-design toolset (/root/reference/README.md:3) but offers no derivatives —
every WEIS outer loop around it must finite-difference the whole analysis.
Here the full pipeline (statics -> Morison hydro -> drag-linearized RAO
fixed point -> response statistics) is exactly differentiable, so design
optimization is plain gradient descent on a jitted value-and-grad step
(BASELINE.json configs[4]: "jax.grad of nacelle-accel std-dev w.r.t.
platform geometry params").

Objectives provided:

* :func:`nacelle_accel_std` — std dev of the nacelle fore-aft acceleration
  ``-w^2 (Xi_surge + hHub Xi_pitch)`` (the RAO the reference derives at
  raft/raft.py:1712), integrated over the spectral-amplitude response.
* :func:`response_std` (re-exported from :mod:`raft_tpu.parallel.sweep`) —
  per-DOF motion std devs.

The optimizer drives any scalar ``objective(out, wave, rna)`` through any
``apply_fn(members, theta)`` geometry parameterization; each step is one
compiled ``value_and_grad`` evaluation (reused across steps), with optional
box bounds enforced by projection.
"""
from __future__ import annotations

from typing import Callable, NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.core.cplx import Cx
from raft_tpu.core.types import Env, MemberSet, RNA, WaveState
from raft_tpu.parallel.sweep import (
    _bem_device_layout,
    _stage_heading_rows,
    _stage_zeta,
    forward_response,
    scale_diameters,
)

Array = jnp.ndarray


def nacelle_accel_std(Xi: Cx, wave: WaveState, rna: RNA) -> Array:
    """Std dev of nacelle fore-aft acceleration from the response Xi.

    ``a_nac(w) = -w^2 (Xi_surge + hHub * Xi_pitch)`` (cf. raft/raft.py:1712);
    Xi is on the spectral-amplitude basis (zeta = sqrt(S)), so
    ``sigma^2 = sum |a_nac|^2 dw``.  Double-where sqrt guard so a
    zero-response design (e.g. all-padded test input) has gradient 0, not
    NaN.
    """
    w = wave.w
    a_re = -(w**2) * (Xi.re[..., 0] + rna.hHub * Xi.re[..., 4])
    a_im = -(w**2) * (Xi.im[..., 0] + rna.hHub * Xi.im[..., 4])
    dw = w[..., 1] - w[..., 0]
    s = jnp.sum(a_re**2 + a_im**2, axis=-1) * dw
    s_safe = jnp.where(s > 0, s, 1.0)
    return jnp.where(s > 0, jnp.sqrt(s_safe), 0.0)


def energy_sum(sigmas):
    """``case_reduce`` for directionally-spread lanes
    (:func:`~raft_tpu.parallel.sweep.spread_sea_state`): the lanes are
    independent linear components of ONE short-crested sea, so their std
    devs combine as a root-sum-of-squares — unlike a DLC table, where the
    default worst-case ``max`` is the robust choice."""
    return jnp.sqrt(jnp.sum(sigmas ** 2))


def _make_loss(members, rna, env, wave, C_moor, objective, apply_fn, bem,
               n_iter, remat, case_reduce=None, moor=None,
               moor_apply_fn=None, r6_moor=None, bem_fn=None):
    """theta -> objective(Xi) through the reverse-differentiable pipeline.

    With ``moor`` (a :class:`~raft_tpu.mooring.MooringSystem`) and
    ``moor_apply_fn(moor, theta)`` given, the mooring stiffness is
    recomputed INSIDE the loss from the theta-modified system —
    ``C = mooring_stiffness(moor_apply_fn(moor, theta), r6_moor)`` — so
    line length / anchor radius / EA become differentiable design
    variables alongside the hull geometry (``C_moor`` is then ignored).

    ``wave`` may be a single sea state or a batched WaveState from
    :func:`~raft_tpu.parallel.sweep.make_wave_states` (leading case axis on
    every leaf): in the batched case each sea state gets its own drag-
    linearization fixed point under ``vmap`` and the per-case objectives
    reduce with ``case_reduce`` (default ``jnp.max`` — robust worst-case
    design over the DLC table).

    ``bem_fn`` (exclusive with ``bem``) closes the co-design loop through
    the panel solve itself: ``theta -> (A[nw,6,6], B[nw,6,6], F Cx[nw,6])``
    re-solved differentiably INSIDE the loss
    (:func:`raft_tpu.hydro.jax_bem.make_bem_fn`), so the gradient carries
    the potential-flow coefficients' dependence on the hull geometry —
    with a static ``bem`` they are frozen at the nominal hull (the
    linearized-sweep convention).

    ``bem`` is detected by layout: :func:`~raft_tpu.parallel.sweep.
    stage_bem` output (excitation already zeta-scaled to ONE sea state,
    valid for a single wave only), the raw host coefficient tuple
    (A[6,6,nw], B[6,6,nw], F[6,nw]) — valid when all lanes share one
    heading; the case-dependent zeta scaling then happens per case — or,
    when the lanes carry their own headings (``wave.beta`` set, e.g. a
    :func:`~raft_tpu.parallel.sweep.spread_sea_state`), the staged heading
    GRID (betas, F_all[nb,6,nw], A, B) from ``Model.calcBEM(headings=...)``
    so each lane's excitation is interpolated to its heading, exactly as
    in :func:`~raft_tpu.parallel.sweep.sweep_sea_states`.
    """
    import numpy as np

    batched = wave.zeta.ndim == 2
    if case_reduce is None:
        case_reduce = jnp.max
    if bem_fn is not None and bem is not None:
        raise ValueError("pass bem (frozen coefficients) OR bem_fn "
                         "(differentiable re-solve), not both")
    if bem_fn is not None and batched and wave.beta is not None:
        raise ValueError(
            "bem_fn solves one heading; lanes carrying their own wave "
            "headings need the staged heading-grid bem instead")
    staged = None       # per-case zeta staging of one shared-heading layout
    staged_F = None     # per-lane heading-interpolated excitation
    if bem is not None:
        if len(bem) == 4:                     # staged heading grid
            if batched:
                B_case = int(wave.zeta.shape[0])
                betas_eval = (np.asarray(wave.beta) if wave.beta is not None
                              else np.full(B_case, float(env.beta)))
            else:
                betas_eval = np.asarray([
                    float(env.beta) if wave.beta is None else float(wave.beta)
                ])
            A_dev, B_dev, F_re, F_im = _stage_heading_rows(bem, betas_eval)
            if batched:
                staged_F = (A_dev, B_dev, F_re, F_im)
            else:
                bem = _stage_zeta((A_dev, B_dev, F_re[0], F_im[0]),
                                  wave.zeta)
        elif isinstance(bem[2], Cx):          # stage_bem output
            if batched:
                raise ValueError(
                    "batched sea states need the raw (A[6,6,nw], B[6,6,nw], "
                    "F[6,nw]) coefficient tuple, not stage_bem output: the "
                    "zeta scaling is per-case"
                )
        else:                                 # raw host layout: stage here
            if batched and wave.beta is not None:
                raise ValueError(
                    "lanes vary the wave heading but bem is a single-heading "
                    "(A, B, F) tuple; pass the staged heading grid "
                    "(betas, F_all, A, B) from Model.calcBEM(headings=...) "
                    "so each lane's excitation matches its heading"
                )
            staged = _bem_device_layout(bem)
            if not batched:
                bem = _stage_zeta(staged, wave.zeta)
                staged = None

    def solve_one(m, C, wv, F_re=None, F_im=None, staged_dyn=None):
        if staged_dyn is not None:
            b = _stage_zeta(staged_dyn, wv.zeta)
        elif F_re is not None:
            b = _stage_zeta((staged_F[0], staged_F[1], F_re, F_im), wv.zeta)
        elif staged is not None:
            b = _stage_zeta(staged, wv.zeta)
        else:
            b = bem
        out = forward_response(
            members=m, rna=rna, env=env, wave=wv, C_moor=C,
            bem=b, n_iter=n_iter, method="scan", remat=remat,
        )
        return objective(out.Xi, wv, rna)

    def loss(theta):
        m = apply_fn(members, theta)
        staged_dyn = None
        if bem_fn is not None:
            # the differentiable panel re-solve: coefficients become a
            # function of theta INSIDE the loss (one solve per theta, the
            # sea states share it — A/B/F are sea-state independent)
            A_d, B_d, F_cx = bem_fn(theta)
            staged_dyn = (A_d, B_d, F_cx.re, F_cx.im)
        if moor is not None:
            from raft_tpu.mooring import mooring_stiffness

            sys_t = moor_apply_fn(moor, theta)
            r0 = (jnp.zeros(6, dtype=sys_t.r_anchor.dtype)
                  if r6_moor is None else r6_moor)
            C = mooring_stiffness(sys_t, r0)
        else:
            C = C_moor
        if batched:
            if staged_F is not None:
                per = jax.vmap(
                    lambda wv, fr, fi: solve_one(m, C, wv, fr, fi)
                )(wave, staged_F[2], staged_F[3])
            else:
                per = jax.vmap(
                    lambda wv: solve_one(m, C, wv, staged_dyn=staged_dyn)
                )(wave)
            return case_reduce(per)
        return solve_one(m, C, wave, staged_dyn=staged_dyn)

    return loss


class OptResult(NamedTuple):
    theta: np.ndarray        # optimized parameters
    objective: float         # objective at theta
    history: np.ndarray      # (steps+1,) objective trajectory
    thetas: np.ndarray       # (steps+1, ...) parameter trajectory
    grad_norm: float         # |grad| at the last evaluated step


def optimize_design(
    members: MemberSet,
    rna: RNA,
    env: Env,
    wave: WaveState,
    C_moor: Array,
    theta0,
    objective: Callable = nacelle_accel_std,
    apply_fn: Callable = scale_diameters,
    steps: int = 30,
    learning_rate: float = 0.02,
    optimizer=None,
    bounds: tuple | None = None,
    bem=None,
    n_iter: int = 25,
    remat: bool = False,
    case_reduce=None,
    moor=None,
    moor_apply_fn=None,
    r6_moor=None,
    bem_fn=None,
) -> OptResult:
    """Minimize a response statistic over a geometry parameterization.

    Co-design over hull AND mooring: pass ``moor`` (the MooringSystem) and
    ``moor_apply_fn(moor, theta) -> MooringSystem`` (e.g.
    :func:`raft_tpu.mooring.scale_mooring`, reading its own components of
    theta) and the mooring stiffness is recomputed differentiably inside
    the loss at linearization point ``r6_moor`` (default zeros) — line
    length, anchor radius and EA become gradient knobs next to the
    geometry scales, closing the WEIS co-design loop over the reference
    mooring schema (raft/OC3spar.yaml:80-147).

    ``wave`` may be a batched WaveState (``make_wave_states``): the
    objective then evaluates per sea-state case and reduces with
    ``case_reduce`` (default max) — robust design over a DLC table; with
    batched waves pass ``bem`` as the raw coefficient tuple (see
    ``_make_loss``).

    ``objective(Xi, wave, rna) -> scalar`` is evaluated on the RAO solve of
    ``apply_fn(members, theta)``; the step is ``optax`` gradient descent
    (Adam by default) on one jitted ``value_and_grad``, compiled once and
    reused every iteration.  The fixed point runs ``method="scan"`` with
    post-convergence freezing — the reverse-differentiable driver
    (solve/dynamics.py) — with ``remat=True`` rematerializing each
    iteration on the backward pass for large node counts.

    ``bounds=(lo, hi)`` projects theta back into the box after each update
    (clipped gradient descent), keeping geometry scales physical.

    With ``bem`` staged, the potential-flow coefficients are those of the
    nominal hull and are held constant under differentiation — the gradient
    carries the statics/Morison/drag dependence on theta (the linearized-
    sweep convention; re-solving the panel method per step is what staging
    avoids).  With ``bem_fn``
    (:func:`raft_tpu.hydro.jax_bem.make_bem_fn`) the panel solve runs
    differentiably INSIDE each step instead: the gradient then carries
    the full geometry -> A/B/F -> RAO chain — true potential-flow
    co-design, at the cost of one on-device panel solve per step.

    Returns the parameter/objective trajectory so callers can inspect
    convergence rather than trust a single terminal value.
    """
    import optax

    if optimizer is None:
        optimizer = optax.adam(learning_rate)

    loss = _make_loss(members, rna, env, wave, C_moor, objective, apply_fn,
                      bem, n_iter, remat, case_reduce=case_reduce,
                      moor=moor, moor_apply_fn=moor_apply_fn, r6_moor=r6_moor,
                      bem_fn=bem_fn)
    theta = jnp.asarray(theta0, dtype=float)
    # AOT registry: the value-and-grad step is ONE large executable reused
    # for every optimizer iteration AND across processes (warm co-design
    # restarts skip the whole backward-pass compile); plain jit when the
    # cache is off — today's exact path
    from raft_tpu import cache as _cache

    val_grad = _cache.cached_callable(
        "optimize_design/val_grad", jax.value_and_grad(loss), (theta,),
        consts=(members, rna, env, wave, C_moor,
                bem if bem is not None else (),
                moor if moor is not None else (),
                r6_moor if r6_moor is not None else ()),
        extra=("n_iter", n_iter, "remat", remat,
               *_cache.callable_salt(objective),
               *_cache.callable_salt(apply_fn),
               *(_cache.callable_salt(case_reduce)
                 if case_reduce is not None else ("case_reduce=max",)),
               *(_cache.callable_salt(moor_apply_fn)
                 if moor_apply_fn is not None else ("moor_apply=none",)),
               *(_cache.callable_salt(bem_fn)
                 if bem_fn is not None else ("bem_fn=none",))),
    )
    opt_state = optimizer.init(theta)
    history, thetas = [], [theta]
    g_norm = 0.0
    for _ in range(steps):
        val, g = val_grad(theta)
        history.append(float(val))
        g_norm = float(jnp.linalg.norm(jnp.atleast_1d(g)))
        updates, opt_state = optimizer.update(g, opt_state, theta)
        theta = optax.apply_updates(theta, updates)
        if bounds is not None:
            theta = jnp.clip(theta, bounds[0], bounds[1])
        thetas.append(theta)
    # terminal value reuses the compiled val_grad: one extra backward pass
    # is far cheaper than compiling a forward-only variant
    history.append(float(val_grad(theta)[0]))
    return OptResult(
        theta=np.asarray(theta),
        objective=history[-1],
        history=np.asarray(history),
        thetas=np.stack([np.asarray(t) for t in thetas]),
        grad_norm=g_norm,
    )


def grad_nacelle_accel_std(
    members: MemberSet,
    rna: RNA,
    env: Env,
    wave: WaveState,
    C_moor: Array,
    theta,
    apply_fn: Callable = scale_diameters,
    bem=None,
    n_iter: int = 25,
    remat: bool = False,
    case_reduce=None,
) -> Array:
    """d sigma_nacelle / d theta: the headline co-design derivative
    (BASELINE.json configs[4]) as a single call.  Batched ``wave`` -> the
    derivative of the ``case_reduce`` (default worst-case) statistic."""
    loss = _make_loss(members, rna, env, wave, C_moor, nacelle_accel_std,
                      apply_fn, bem, n_iter, remat, case_reduce=case_reduce)
    from raft_tpu import cache as _cache

    theta = jnp.asarray(theta, dtype=float)
    if _cache.is_enabled():
        # with the cache armed the gradient runs as ONE registered
        # executable; off, it keeps today's un-jitted eager-grad path
        g = _cache.cached_compile(
            "grad_nacelle_accel_std", jax.grad(loss), (theta,),
            consts=(members, rna, env, wave, C_moor,
                    bem if bem is not None else ()),
            extra=("n_iter", n_iter, "remat", remat,
                   *_cache.callable_salt(apply_fn),
                   *(_cache.callable_salt(case_reduce)
                     if case_reduce is not None else ("case_reduce=max",))),
        )
        return g(theta)
    return jax.grad(loss)(theta)
