"""Back-compat phase timers over the unified span API.

Historically this module WAS the instrumentation: module-global
wall-clock phase timers.  It is now a thin shim over
:mod:`raft_tpu.obs.trace` — ``phase`` opens a real span (so every
``prof.phase`` call site shows up in the Chrome trace and the span
roll-up for free), and ``totals``/``summary`` read the span aggregates.
Kept because ~30 call sites (bench.py, cache/, model.py, array.py, the
smokes) speak this vocabulary; new code should use ``obs.trace.span``
directly.

Two long-standing ``phase`` bugs die in the migration:

* the nesting stack was a module-global list — two threads timing
  concurrently (the ROADMAP solver daemon) would interleave pushes and
  corrupt each other's nested names; the span API keeps one stack per
  thread (``threading.local``);
* the exit sync blocked on **every live device array** in the process,
  charging unrelated buffers' pending compute to whatever phase happened
  to close first.  The sync is now SCOPED: only arrays that became live
  during the block are waited on (a liveness-delta of ``id()``s —
  blast radius: an array allocated in the block that reuses the id of
  one freed mid-block is missed, a rare under-sync that can only shift
  a timing, never a result; pass ``sync="all"`` for the old
  whole-process barrier when a phase must absorb everything).
"""
from __future__ import annotations

import contextlib

from raft_tpu.obs import trace as _trace


def _live_ids() -> set:
    import jax

    return {id(a) for a in jax.live_arrays()}


def _sync(before: set | None) -> None:
    """Block until the arrays produced since ``before`` (or all live
    arrays, when ``before`` is None) are ready."""
    import jax

    (jax.effects_barrier if hasattr(jax, "effects_barrier") else _noop)()
    for d in jax.live_arrays():
        if before is None or id(d) not in before:
            d.block_until_ready()


@contextlib.contextmanager
def phase(name: str, jax_trace: bool = False, sync=True):
    """Time a named phase (nested names join with '/', per thread).

    JAX dispatch is asynchronous: without a device sync, a block would be
    charged only its trace/dispatch time and the compute would bleed into
    a later phase.  ``sync=True`` (default) blocks at phase exit on the
    arrays the block PRODUCED (liveness delta — unrelated in-flight work
    is no longer charged here); ``sync="all"`` restores the historical
    whole-process barrier; ``sync=False`` skips the barrier entirely
    (hot loops where it would serialize useful overlap).

    With ``jax_trace=True`` the block is also annotated in the JAX
    profiler timeline (requires an active ``start_trace``).
    """
    with _trace.span(name, jax_trace=jax_trace):
        before = _live_ids() if sync is True else None
        yield
        if sync:
            _sync(before)


def _noop():
    pass


@contextlib.contextmanager
def xla_trace(log_dir: str):
    """Capture a JAX/XLA profiler trace for the enclosed block
    (open with TensorBoard or Perfetto)."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def summary() -> str:
    """Formatted table of accumulated span/phase timings."""
    lines = ["phase                                    calls   total [s]   mean [ms]"]
    for name, agg in _trace.rollup().items():
        n, tot = agg["count"], agg["total_s"]
        lines.append(f"{name:<40} {n:>5} {tot:>11.3f} {tot / n * 1e3:>11.2f}")
    return "\n".join(lines)


def totals() -> dict:
    """Accumulated {phase: seconds} — e.g. for embedding in a bench JSON.
    (Exact past the span ring bound: backed by the roll-up aggregates,
    not the ring.)"""
    return {k: v["total_s"] for k, v in _trace.rollup().items()}


def reset():
    """Clear accumulated span history (the shim's totals with it)."""
    _trace.reset()
