"""Tracing / profiling hooks.

The reference has no instrumentation at all (SURVEY.md §5); this module is
the greenfield equivalent: lightweight wall-clock phase timers that nest,
a summary table, and an optional bridge into ``jax.profiler`` traces for
XLA-level timelines viewable in TensorBoard/Perfetto.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

_totals: dict = defaultdict(float)
_counts: dict = defaultdict(int)
_stack: list = []


@contextlib.contextmanager
def phase(name: str, jax_trace: bool = False, sync: bool = True):
    """Time a named phase (nested names join with '/').

    JAX dispatch is asynchronous: without a device sync, a block would be
    charged only its trace/dispatch time and the compute would bleed into a
    later phase.  ``sync=True`` (default) blocks on all live device arrays
    at phase exit so wall-clock numbers are honest; pass ``sync=False``
    inside hot loops where the barrier would serialize useful overlap.

    With ``jax_trace=True`` the block is also annotated in the JAX profiler
    timeline (requires an active ``start_trace``)."""
    full = "/".join([*_stack, name])
    _stack.append(name)
    ctx = contextlib.nullcontext()
    if jax_trace:
        import jax.profiler

        ctx = jax.profiler.TraceAnnotation(full)
    t0 = time.perf_counter()
    try:
        with ctx:
            yield
            if sync:
                import jax

                (jax.effects_barrier if hasattr(jax, "effects_barrier") else _noop)()
                for d in jax.live_arrays():
                    d.block_until_ready()
    finally:
        dt = time.perf_counter() - t0
        _stack.pop()
        _totals[full] += dt
        _counts[full] += 1


def _noop():
    pass


@contextlib.contextmanager
def xla_trace(log_dir: str):
    """Capture a JAX/XLA profiler trace for the enclosed block
    (open with TensorBoard or Perfetto)."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def summary() -> str:
    """Formatted table of accumulated phase timings."""
    lines = ["phase                                    calls   total [s]   mean [ms]"]
    for name in sorted(_totals):
        n = _counts[name]
        tot = _totals[name]
        lines.append(f"{name:<40} {n:>5} {tot:>11.3f} {tot / n * 1e3:>11.2f}")
    return "\n".join(lines)


def totals() -> dict:
    """Accumulated {phase: seconds} — e.g. for embedding in a bench JSON."""
    return dict(_totals)


def reset():
    _totals.clear()
    _counts.clear()
