"""Utilities: profiling/tracing hooks and shared helpers."""
from raft_tpu.utils.profiling import phase, reset, summary, xla_trace  # noqa: F401
