"""Frequency-domain RAO solve: the framework's north-star kernel.

TPU-native re-design of the reference ``Model.solveDynamics``
(raft/raft.py:1469-1592): the per-frequency Python loop forming
``Z = -w^2 M + i w B + C`` and inverting it (raft/raft.py:1528-1533) becomes
one batched 6x6 complex solve over the whole frequency grid (and, under
``vmap``, over a design batch), and the drag-linearization fixed point
(raft/raft.py:1497-1552) becomes a ``lax.scan``/``lax.while_loop`` with the
same under-relaxation and convergence rule.

Two iteration drivers share one step function:

* ``method="while"`` — ``lax.while_loop`` with early exit, the fast path for
  inference/benchmarks (not reverse-differentiable).
* ``method="scan"``  — fixed ``n_iter`` ``lax.scan`` whose updates freeze
  once converged: identical results, deterministic cost, and fully
  reverse-differentiable (the route for ``jax.grad`` co-design studies).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from raft_tpu.core import cplx
from raft_tpu.core.cplx import Cx
from raft_tpu.core.linalg6 import solve_cx_fused
from raft_tpu.core.types import Env, MemberSet, WaveState
from raft_tpu.hydro.strip import StripKin, linearized_drag

Array = jnp.ndarray


@struct.dataclass
class LinearCoeffs:
    """Response-independent linear terms of the equation of motion.

    Precomputed once per design+sea-state, mirroring the stacking at
    raft/raft.py:1490-1493:
      M = M_struc + A_bem(w) + A_morison   (nw,6,6)
      B = B_struc + B_bem(w)               (nw,6,6)
      C = C_struc + C_moor + C_hydro       (6,6)
      F = F_bem(w) + F_hydro_iner(w)       (nw,6) complex
    """

    M: Array
    B: Array
    C: Array
    F: Cx


@struct.dataclass
class RAOResult:
    Xi: Cx            # (nw,6) complex response amplitudes (per unit wave amp basis)
    n_iter: Array     # () iterations actually used
    converged: Array  # () bool
    B_drag: Array     # (6,6) linearized drag damping at the solution
    F_drag: Cx        # (nw,6) drag excitation at the solution
    # (n_iter,) per-iteration convergence error when solve_dynamics ran with
    # history=True (NaN past the exit iteration); None otherwise.  The
    # convergence-inspection capability of the reference's per-iterate RAO
    # plots (raft/raft.py:1536-1539) as data instead of figures.
    err_hist: Array | None = None


def impedance(w: Array, M: Array, B: Array, C: Array) -> Cx:
    """Z(w) = -w^2 M + i w B + C as a (..., nw, 6, 6) Cx (raft/raft.py:1530)."""
    w2 = (w * w)[..., None, None]
    return Cx(-w2 * M + C, w[..., None, None] * B)


def _solve_once(Z0: Cx, w: Array, B_drag: Array, F: Cx,
                use_pallas: bool = False, differentiable: bool = False) -> Cx:
    """One FUSED impedance assemble+solve with the current drag damping.

    The per-iteration ``Z = Z0 + i w B_drag`` is never materialized as a
    standalone (..., nw, 6, 6) complex tensor: the Pallas route assembles
    it inside the VMEM-resident kernel block
    (:func:`~raft_tpu.core.pallas6.solve_rao_pallas`), and the XLA route
    fuses the elementwise assembly into the elimination
    (:func:`~raft_tpu.core.linalg6.solve_cx_fused`) — bit-comparable
    expressions, so flipping the kernel knob cannot change convergence.

    ``differentiable`` picks the kernel variant with the analytic adjoint
    rule (``solve_rao_pallas_ad``: the adjoint system ``A^H lam = xbar``
    re-uses the SAME fused forward kernel on ``(Z0^H, w, -B_drag^T)``)
    so reverse-mode AD works through the scan driver; the while driver
    keeps the plain kernel (a while_loop is not reverse-differentiable
    anyway, and the plain variant still admits whatever forward
    transforms the underlying pallas_call does).
    """
    if use_pallas:
        from raft_tpu.core.pallas6 import solve_rao_pallas, solve_rao_pallas_ad

        return (solve_rao_pallas_ad if differentiable
                else solve_rao_pallas)(Z0, w, B_drag, F)
    return solve_cx_fused(Z0, w, B_drag, F)


def _error(Xi: Cx, Xi_last: Cx, tol: float) -> Array:
    """Relative change metric, reduced over (nw, 6) (raft/raft.py:1542)."""
    num = (Xi - Xi_last).abs()
    den = Xi.abs() + tol
    return jnp.max(num / den)


def solve_dynamics(
    m: MemberSet,
    kin: StripKin,
    wave: WaveState,
    env: Env,
    lin: LinearCoeffs,
    n_iter: int = 15,
    tol: float = 0.01,
    relax: float = 0.8,
    method: str = "scan",
    axis_name: str | None = None,
    remat: bool = False,
    history: bool = False,
    tik: float = 0.0,
) -> RAOResult:
    """Solve Xi(w) by fixed-point drag linearization (raft/raft.py:1469-1552).

    Per iteration: linearize Morison drag about the current iterate
    (``linearized_drag``), assemble Z, solve all frequencies at once, check
    the relative-change tolerance, then under-relax
    ``Xi_last <- (1-relax) Xi_last + relax Xi`` (raft/raft.py:1547).
    The returned ``Xi`` is the raw solve of the final iteration, matching the
    reference's loop-exit semantics.

    Operates on one (design, sea state); batch with ``jax.vmap`` — each lane
    then gets its own convergence state for free.

    ``remat=True`` (scan path) rematerializes each fixed-point step on the
    backward pass (``jax.checkpoint``): reverse-mode memory drops from
    O(n_iter x drag-linearization residuals) to O(n_iter x Xi) at ~1
    extra forward step per iteration — the trade for large design batches
    against HBM.

    ``axis_name``: set when the frequency grid is SHARDED over a mesh axis
    (sequence parallelism via ``shard_map``): the drag linearization's
    spectral moment completes with a ``psum`` and the convergence error
    with a ``pmax`` over that axis, so every shard takes the same number
    of iterations and reproduces the unsharded fixed point exactly.

    ``history=True`` additionally records the convergence error of every
    iteration into ``RAOResult.err_hist`` (shape ``(n_iter,)``, NaN past
    the exit iteration) — the diagnostic for a non-converging design lane
    that the reference serves with per-iterate RAO plots
    (raft/raft.py:1536-1539).  Static flag, so the default hot path carries
    no history buffer.

    ``tik`` > 0 applies Tikhonov-style diagonal loading to the response-
    independent impedance: each frequency's ``Z0`` diagonal is lifted by
    ``tik`` times that frequency's largest diagonal magnitude before the
    fused assemble+solve.  This is the escalation ladder's last rung
    (:mod:`raft_tpu.resilience.ladder`) — it trades a bounded, REPORTED
    bias for solvability when the impedance is near-singular at some
    bin.  Static knob: ``tik=0.0`` (the default and every healthy path)
    traces the exact unregularized program.
    """
    # Pallas kernel for the batched 6x6 solves (auto-on on TPU, where it
    # is measured 18x faster end-to-end — core/pallas6.py), both drivers:
    # the while route uses the plain kernel, the scan route the
    # custom_vjp variant whose analytic adjoint re-uses the same kernel
    # (forward-mode jvp/jacfwd through scan needs RAFT_TPU_PALLAS=0).
    # Read OUTSIDE the jitted core so the flag participates in the jit
    # cache key — toggling the env var between DIRECT solve_dynamics
    # calls really switches paths.  Callers that wrap this in their own
    # jit/vmap/shard_map (sweep_sea_states, forward_response_freq_sharded,
    # ArrayModel.solveDynamics) capture the flag at their first outer
    # trace; a later toggle does not retrace those pipelines.
    from raft_tpu.core import pallas6

    use_pallas = pallas6.enabled()
    return _solve_dynamics_impl(
        m, kin, wave, env, lin, n_iter=n_iter, tol=tol, relax=relax,
        method=method, axis_name=axis_name, remat=remat, history=history,
        use_pallas=use_pallas, tik=tik,
    )


@partial(jax.jit, static_argnames=("n_iter", "tol", "relax", "method",
                                   "axis_name", "remat", "history",
                                   "use_pallas", "tik"))
def _solve_dynamics_impl(
    m: MemberSet,
    kin: StripKin,
    wave: WaveState,
    env: Env,
    lin: LinearCoeffs,
    n_iter: int,
    tol: float,
    relax: float,
    method: str,
    axis_name: str | None,
    remat: bool,
    history: bool,
    use_pallas: bool,
    tik: float = 0.0,
) -> RAOResult:
    nw = wave.w.shape[-1]
    dtype = lin.C.dtype

    Xi0 = Cx(jnp.full((nw, 6), 0.1, dtype=dtype), jnp.zeros((nw, 6), dtype=dtype))
    if wave.freq_mask is not None:
        # bucket-padded bins (freq_mask False) start at exactly zero: with
        # zeta = 0 there (zero excitation) a zero iterate is a fixed point
        # of the padded bin — F_drag and the vRMS spectral moment see
        # vrel = 0 — so the padded bins carry zeros through EVERY
        # iteration and the physical bins reproduce the unpadded solve
        # (a 0.1 seed at a padded bin would pollute the early iterations'
        # drag linearization instead).  None (every unbucketed caller)
        # traces the exact historical program.
        Xi0 = Cx(Xi0.re * wave.freq_mask[..., None].astype(dtype), Xi0.im)
    Z0 = impedance(wave.w, lin.M, lin.B, lin.C)
    if tik:
        # Tikhonov-style diagonal loading (ladder rung): lift each
        # frequency's diagonal by tik x its own largest diagonal
        # magnitude, scale-free across hulls.  The shift follows the
        # sign of each real diagonal entry — Re(Z_jj) = C_jj - w^2 M_jj
        # is negative above that DOF's resonance, where an unconditional
        # +lam would move the entry TOWARD zero and worsen conditioning.
        # Python-level branch on a static knob — the tik=0 hot path
        # traces zero extra ops.
        d_re = jnp.diagonal(Z0.re, axis1=-2, axis2=-1)
        dmag = jnp.sqrt(
            jnp.square(d_re)
            + jnp.square(jnp.diagonal(Z0.im, axis1=-2, axis2=-1)))
        lam = tik * jnp.max(dmag, axis=-1)
        shift = jnp.where(d_re >= 0, 1.0, -1.0) * lam[..., None]
        Z0 = Cx(Z0.re + shift[..., None] * jnp.eye(6, dtype=dtype),
                Z0.im)

    def step(Xi_last):
        B_drag, F_drag = linearized_drag(m, kin, Xi_last, wave, env,
                                         axis_name=axis_name)
        F = lin.F + F_drag
        Xi = _solve_once(Z0, wave.w, B_drag, F, use_pallas=use_pallas,
                         differentiable=(method == "scan"))
        err = _error(Xi, Xi_last, tol)
        if axis_name is not None:
            err = jax.lax.pmax(err, axis_name)      # global convergence
        return Xi, err

    def advance(carry):
        """One fixed-point step with post-convergence freeze."""
        Xi_last, Xi_out, done, count, hist = carry
        Xi, err = step(Xi_last)
        conv = err < tol
        Xi_out = cplx.where(done, Xi_out, Xi)
        Xi_next = cplx.where(done, Xi_last, Xi_last * (1.0 - relax) + Xi * relax)
        if hist is not None:
            # frozen lanes keep their buffer; live lanes log this iterate
            hist = hist.at[count].set(jnp.where(done, hist[count], err))
        count = count + (~done).astype(count.dtype)
        return Xi_next, Xi_out, done | conv, count, hist

    hist0 = jnp.full((n_iter,), jnp.nan, dtype=dtype) if history else None
    init = (Xi0, Xi0, jnp.asarray(False), jnp.asarray(0, dtype=jnp.int32), hist0)

    if method == "while":
        _, Xi_out, done, count, hist = jax.lax.while_loop(
            lambda c: (~c[2]) & (c[3] < n_iter), advance, init
        )
    elif method == "scan":
        step_fn = jax.checkpoint(advance) if remat else advance
        (_, Xi_out, done, count, hist), _ = jax.lax.scan(
            lambda c, _: (step_fn(c), None), init, None, length=n_iter
        )
    else:
        raise ValueError(f"unknown method {method!r}")

    B_drag, F_drag = linearized_drag(m, kin, Xi_out, wave, env,
                                     axis_name=axis_name)
    return RAOResult(Xi=Xi_out, n_iter=count, converged=done, B_drag=B_drag,
                     F_drag=F_drag, err_hist=hist)
