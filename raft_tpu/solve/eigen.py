"""Natural frequencies and mode shapes.

TPU-native equivalent of the reference ``Model.solveEigen``
(raft/raft.py:1370-1452).  The reference computes
``np.linalg.eig(inv(M_tot) @ C_tot)`` (raft/raft.py:1394); since both
matrices are symmetric (M SPD), the numerically sound equivalent is the
generalized symmetric problem ``C x = lambda M x`` solved by Cholesky
reduction + Jacobi rotations — which, unlike LAPACK ``eig``, runs on TPU
and batches/vmaps/differentiates cleanly.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from raft_tpu.core.linalg6 import generalized_eigh

Array = jnp.ndarray

_TWO_PI = 2.0 * jnp.pi


@struct.dataclass
class EigenResult:
    fns: Array     # (...,6) natural frequencies [Hz], ordered by dominant DOF
    wns: Array     # (...,6) natural frequencies [rad/s]
    modes: Array   # (...,6,6) mode shapes, column i dominated by DOF i
    order: Array   # (...,6) index of the raw eigenpair assigned to each DOF


def dominance_order(modes: Array) -> Array:
    """Assign each DOF the eigenvector most dominated by it.

    Re-design of the reference's greedy eigenvector sort
    (raft/raft.py:1396-1414): normalize each eigenvector by its largest
    component magnitude, then walk the DOFs in order, each taking the
    not-yet-assigned column whose normalized component is largest — a
    greedy matching, guaranteed injective (each eigenpair used once).
    Static 6-step loop, so it stays jit/vmap friendly.
    """
    mag = jnp.abs(modes)
    norm = jnp.max(mag, axis=-2, keepdims=True)
    rel = mag / jnp.where(norm > 0, norm, 1.0)
    n = modes.shape[-1]
    avail = jnp.ones(rel.shape[:-2] + (n,), dtype=rel.dtype)
    picks = []
    for dof in range(n):
        score = jnp.where(avail > 0, rel[..., dof, :], -1.0)
        pick = jnp.argmax(score, axis=-1)
        picks.append(pick)
        avail = avail * (1.0 - jax.nn.one_hot(pick, n, dtype=rel.dtype))
    return jnp.stack(picks, axis=-1)


@jax.jit
def diagonal_estimates(M_tot: Array, C_tot: Array) -> Array:
    """Per-DOF natural-frequency estimates from the diagonal entries [Hz].

    The reference's engineering cross-check on the full eigen solve
    (raft/raft.py:1422-1446): translational DOFs and yaw use
    ``sqrt(C_ii/M_ii)`` directly; roll and pitch are corrected to rotation
    about the effective center of mass instead of the PRP, using the
    off-diagonal coupling terms as levers —
    ``z_CM = M[0,4]/M[0,0]`` (mass + added mass) and
    ``z_moor = C[0,4]/C[0,0]`` (mooring reaction elevation) — rather than a
    parallel-axis shift, because added mass moves the rotation point off the
    CG.  Batched/vmappable; divisions are guarded for free DOFs.
    """
    M_tot = jnp.asarray(M_tot)
    C_tot = jnp.asarray(C_tot)

    def safe_div(a, b):
        return jnp.where(jnp.abs(b) > 0, a / jnp.where(jnp.abs(b) > 0, b, 1.0), 0.0)

    zMoorx = safe_div(C_tot[..., 0, 4], C_tot[..., 0, 0])
    zMoory = safe_div(C_tot[..., 1, 3], C_tot[..., 1, 1])
    zCMx = safe_div(M_tot[..., 0, 4], M_tot[..., 0, 0])
    zCMy = safe_div(M_tot[..., 1, 3], M_tot[..., 1, 1])

    def wn2(c, m):
        return jnp.where(m > 0, jnp.clip(safe_div(c, m), 0.0, None), 0.0)

    diagC = jnp.diagonal(C_tot, axis1=-2, axis2=-1)
    diagM = jnp.diagonal(M_tot, axis1=-2, axis2=-1)
    w2 = [wn2(diagC[..., i], diagM[..., i]) for i in range(6)]
    # roll/pitch about the effective CM: stiffness gains the translational
    # lever term, inertia loses the transfer term M_11 z_CM^2
    c_roll = diagC[..., 3] + diagC[..., 1] * ((zCMy - zMoory) ** 2 - zMoory**2)
    m_roll = diagM[..., 3] - diagM[..., 1] * zCMy**2
    c_pitch = diagC[..., 4] + diagC[..., 0] * ((zCMx - zMoorx) ** 2 - zMoorx**2)
    m_pitch = diagM[..., 4] - diagM[..., 0] * zCMx**2
    w2[3] = wn2(c_roll, m_roll)
    w2[4] = wn2(c_pitch, m_pitch)
    return jnp.sqrt(jnp.stack(w2, axis=-1)) / _TWO_PI


def eigen_with_bem(M_base, C_tot, A_w, w_grid, n_pass: int = 3):
    """Eigen solve with frequency-dependent BEM added mass, evaluated *at
    each mode's own natural frequency* by a small host-driven fixed point:
    solve with A(w_n) interpolated per mode, update w_n, repeat ``n_pass``
    times (converges in 2-3 passes — A(w) varies slowly near the rigid-body
    modes).  The reference cannot do this: its BEM arrays in the eigen
    assembly are always zero (raft/raft.py:1380,1797-1800).

    ``M_base``: (6,6) structural + Morison mass (potMod members excluded);
    ``A_w``: (nw,6,6) frequency-leading BEM added mass on the host;
    ``w_grid``: (nw,) the BEM frequency grid [rad/s].
    Returns ``(EigenResult with flat per-DOF fields, estimates[6] in Hz)``
    — shared by ``Model.solveEigen`` and ``ArrayModel.solveEigen``.
    """
    import numpy as np

    res, est = eigen_with_bem_batched(
        jnp.asarray(M_base)[None], jnp.asarray(C_tot)[None],
        jnp.asarray(A_w), jnp.asarray(w_grid), n_pass=n_pass,
    )
    return jax.tree.map(lambda a: a[0], res), np.asarray(est)[0]


@partial(jax.jit, static_argnames=("n_pass",))
def eigen_with_bem_batched(M_base: Array, C_tot: Array, A_w: Array,
                           w_grid: Array, n_pass: int = 3):
    """Pure-jax, turbine-batched :func:`eigen_with_bem`.

    Same per-mode fixed point (interpolate A(w) at each mode's own natural
    frequency, re-solve, repeat), but compiled end to end and vmapped over
    a leading turbine axis — one jit call eigen-solves a whole farm instead
    of ``nT`` sequential host round-trips (the ArrayModel analog of the
    reference's single 6N block assembly, raft/raft.py:1292-1298).

    ``M_base``/``C_tot``: (nT,6,6); ``A_w``: (nw,6,6) shared BEM added-mass
    table (one hull design serves the farm); ``w_grid``: (nw,).
    Returns ``(EigenResult with (nT,6)-shaped fields, estimates (nT,6))``.
    """
    if n_pass < 1:
        raise ValueError(f"eigen_with_bem_batched needs n_pass >= 1, got {n_pass}")
    A_flat = A_w.reshape(A_w.shape[0], 36)              # (nw, 36)

    def interp_A(wns):                                   # (6,) -> (6,6,6)
        vals = jax.vmap(lambda col: jnp.interp(wns, w_grid, col),
                        in_axes=1, out_axes=1)(A_flat)   # (6, 36)
        return vals.reshape(6, 6, 6)

    def one(M1, C1):                                     # (6,6),(6,6)
        wns = jnp.full(6, w_grid[0])
        for _ in range(n_pass):                          # static 2-3 passes
            A_modes = interp_A(wns)                      # (6,6,6)
            eigs = jax.vmap(solve_eigen, in_axes=(0, None))(M1 + A_modes, C1)
            wns = jnp.diagonal(eigs.wns)                 # mode i at assembly i
        res = EigenResult(
            fns=wns / _TWO_PI,
            wns=wns,
            modes=jnp.stack([eigs.modes[i, :, i] for i in range(6)], axis=1),
            order=jnp.stack([eigs.order[i, i] for i in range(6)]),
        )
        est = jnp.diagonal(
            jax.vmap(diagonal_estimates, in_axes=(0, None))(M1 + A_modes, C1)
        )
        return res, est

    return jax.vmap(one)(M_base, C_tot)


@partial(jax.jit, static_argnames=("sweeps",))
def solve_eigen(M_tot: Array, C_tot: Array, sweeps: int = 12) -> EigenResult:
    """Natural frequencies of the undamped 6-DOF system.

    M_tot = M_struc + A_morison (+ A_bem at w_n if staged);
    C_tot = C_struc + C_moor + C_hydro  (cf. raft/raft.py:1380-1391).
    """
    lam, X = generalized_eigh(C_tot, M_tot, sweeps=sweeps)
    wns_raw = jnp.sqrt(jnp.clip(lam, 0.0, None))
    order = dominance_order(X)
    wns = jnp.take_along_axis(wns_raw, order, axis=-1)
    modes = jnp.take_along_axis(X, order[..., None, :], axis=-1)
    # normalize modes to unit max-magnitude component
    norm = jnp.max(jnp.abs(modes), axis=-2, keepdims=True)
    modes = modes / jnp.where(norm > 0, norm, 1.0)
    return EigenResult(fns=wns / _TWO_PI, wns=wns, modes=modes, order=order)
