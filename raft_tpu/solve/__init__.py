"""Frequency-domain solve engine: RAO fixed point + eigen analysis."""
from raft_tpu.solve.dynamics import (  # noqa: F401
    LinearCoeffs,
    RAOResult,
    impedance,
    solve_dynamics,
)
from raft_tpu.solve.eigen import (  # noqa: F401
    EigenResult,
    diagonal_estimates,
    dominance_order,
    eigen_with_bem,
    eigen_with_bem_batched,
    solve_eigen,
)
