"""WEIS/OpenMDAO integration adapter.

The reference sketches this coupling in ``runRAFTfromWEIS``
(raft/runRAFT.py:86-208) — dead code referencing undefined globals, kept
only as documentation of the intended data flow.  This module is the
working equivalent: translate the array-style turbine/platform description
a WEIS optimization loop carries (member joint coordinates, outer
diameters, wall thickness, RNA scalars, mooring node/line tables) into the
raft_tpu design dict, so `Model`/`sweep` can serve as the Level-1 dynamics
inner loop of a co-design study.
"""
from __future__ import annotations

import numpy as np


def member_from_arrays(
    name: str,
    joint1,
    joint2,
    diameters,
    thicknesses,
    stations=None,
    shape: str = "circ",
    mtype: int = 2,
    **kwargs,
) -> dict:
    """One member dict from WEIS-style arrays (cf. raft/runRAFT.py:118-160).

    ``stations`` defaults to a normalized grid over the member span;
    extra Morison coefficients / ballast fields pass through ``kwargs``.
    """
    joint1 = np.asarray(joint1, dtype=float)
    joint2 = np.asarray(joint2, dtype=float)
    d = np.atleast_1d(np.asarray(diameters, dtype=float))
    t = np.atleast_1d(np.asarray(thicknesses, dtype=float))
    if stations is not None:
        n = len(stations)
    else:
        n = max(len(d), len(t), 2)
        stations = np.linspace(0.0, 1.0, n)
    if len(d) == 1:
        d = np.full(n, d[0])
    if len(t) == 1:
        t = np.full(n, t[0])
    if len(d) != n or len(t) != n:
        raise ValueError(
            f"member {name!r}: d (len {len(d)}) and t (len {len(t)}) must be "
            f"scalar or match the {n} stations"
        )
    member = {
        "name": name,
        "type": mtype,
        "rA": joint1.tolist(),
        "rB": joint2.tolist(),
        "shape": shape,
        "stations": np.asarray(stations, dtype=float).tolist(),
        "d": d.tolist(),
        "t": t.tolist(),
    }
    member.update(kwargs)
    return member


def mooring_from_arrays(
    water_depth: float,
    anchor_xyz,
    fairlead_xyz,
    line_lengths,
    diameter: float,
    mass_density: float,
    stiffness: float,
    line_type: str = "main",
) -> dict:
    """Mooring dict from node/line tables (cf. raft/runRAFT.py:163-208)."""
    anchor_xyz = np.atleast_2d(np.asarray(anchor_xyz, dtype=float))
    fairlead_xyz = np.atleast_2d(np.asarray(fairlead_xyz, dtype=float))
    nl = len(anchor_xyz)
    if len(fairlead_xyz) != nl:
        raise ValueError(f"{nl} anchors but {len(fairlead_xyz)} fairleads")
    lengths = np.broadcast_to(
        np.atleast_1d(np.asarray(line_lengths, dtype=float)), (nl,)
    )
    points, lines = [], []
    for i, (a, f, L) in enumerate(zip(anchor_xyz, fairlead_xyz, lengths), 1):
        points.append(
            {"name": f"anchor{i}", "type": "fixed", "location": a.tolist(),
             "anchor_type": "default"}
        )
        points.append(
            {"name": f"fairlead{i}", "type": "vessel", "location": f.tolist()}
        )
        lines.append(
            {"name": f"line{i}", "endA": f"anchor{i}", "endB": f"fairlead{i}",
             "type": line_type, "length": float(L)}
        )
    return {
        "water_depth": float(water_depth),
        "points": points,
        "lines": lines,
        "line_types": [
            {
                "name": line_type,
                "diameter": float(diameter),
                "mass_density": float(mass_density),
                "stiffness": float(stiffness),
                "breaking_load": 1e8,
                "cost": 100.0,
                "transverse_added_mass": 1.0,
                "tangential_added_mass": 0.0,
                "transverse_drag": 1.6,
                "tangential_drag": 0.1,
            }
        ],
        "anchor_types": [
            {"name": "default", "mass": 1e3, "cost": 1e4,
             "max_vertical_load": 0.0, "max_lateral_load": 1e5}
        ],
    }


def design_from_weis(
    platform_members: list,
    tower: dict,
    rna: dict,
    mooring: dict,
    name: str = "weis design",
) -> dict:
    """Assemble the full design dict consumed by :class:`raft_tpu.model.Model`.

    ``rna`` keys: mRNA, IxRNA, IrRNA, xCG_RNA, hHub, Fthrust,
    yaw_stiffness (all scalars; cf. raft/raft.py:1790-1794).
    """
    turbine = dict(rna)
    turbine["tower"] = tower
    return {
        "type": "input file for RAFT",
        "name": name,
        "turbine": turbine,
        "platform": {"members": list(platform_members)},
        "mooring": mooring,
    }
