"""Host-side IO: YAML design parsing, validation, results serialization."""
from raft_tpu.io.schema import get_from_dict  # noqa: F401
