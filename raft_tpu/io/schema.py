"""Typed, shape-checked, defaulted access to YAML design dictionaries.

Behavioral equivalent of the reference's ``getFromDict`` accessor
(raft/raft.py:1164-1224): every field read from a design dict goes through
one function that coerces dtype, validates/broadcasts shape, and applies
defaults — so malformed design files fail loudly at load time, before any
device computation.
"""
from __future__ import annotations

import numpy as np


def get_from_dict(d: dict, key: str, shape=0, dtype=float, default=None):
    """Read ``d[key]`` with dtype coercion, shape validation and defaults.

    Parameters
    ----------
    shape : 0 for a scalar, -1 for "scalar or any-length 1D", an int n for a
        length-n 1D array (scalars broadcast), or a sequence like [n, 2] for
        a 2D array (rows of scalars broadcast along the last axis).
    default : value used when ``key`` is absent; ``None`` makes the field
        required.  Scalar defaults broadcast to the requested shape.
    """
    if key in d:
        val = d[key]
        if shape == 0:
            if np.isscalar(val):
                return dtype(val)
            raise ValueError(f"design field '{key}' must be a scalar")
        if shape == -1:
            if np.isscalar(val):
                return dtype(val)
            return np.array(val, dtype=dtype)
        # fixed shapes
        if np.isscalar(shape):
            if np.isscalar(val):
                return np.tile(dtype(val), int(shape))
            arr = np.array(val, dtype=dtype)
            if arr.shape != (int(shape),):
                raise ValueError(
                    f"design field '{key}' has length {arr.shape}, expected {int(shape)}"
                )
            return arr
        # 2D shape spec like [n, 2]
        n, m = int(shape[0]), int(shape[1])
        if np.isscalar(val):
            return np.tile(dtype(val), (n, m))
        arr = np.array(val, dtype=dtype)
        if arr.ndim == 1:
            if n == -1:
                return np.tile(arr, (1, 1)) if arr.shape[0] == m else _fail(key, arr, (n, m))
            if arr.shape[0] == m:
                return np.tile(arr, (n, 1))
            return _fail(key, arr, (n, m))
        if n != -1 and arr.shape != (n, m):
            return _fail(key, arr, (n, m))
        if n == -1 and arr.shape[1] != m:
            return _fail(key, arr, (n, m))
        return arr

    if default is None:
        raise ValueError(f"design field '{key}' is required but missing")
    if shape == 0 or shape == -1:
        return dtype(default) if np.isscalar(default) else np.array(default, dtype=dtype)
    if np.isscalar(shape):
        if np.isscalar(default):
            return np.tile(dtype(default), int(shape))
        arr = np.array(default, dtype=dtype)
        if arr.shape != (int(shape),):
            return _fail(key, arr, (int(shape),))
        return arr
    n, m = int(shape[0]), int(shape[1])
    if np.isscalar(default):
        return np.tile(dtype(default), (n, m))
    return np.array(default, dtype=dtype)


def _fail(key, arr, want):
    raise ValueError(f"design field '{key}' has shape {arr.shape}, expected {want}")
