"""Escalation ladder: quarantined lanes get progressively tougher solves.

A lane that fails the batch solve is not thrown away — it is re-solved
alone, walking a ladder of increasingly conservative solver settings
until one converges finite (or the ladder is exhausted and the lane is
reported unsalvaged):

1. ``n_iter_x4`` — same solver, 4x the iteration budget: the common case
   of a slow-but-convergent fixed point that simply hit the batch cap.
2. ``relax_0.5`` — halve the under-relaxation (and keep the larger
   budget): damps the oscillatory divergence mode of the drag
   linearization on resonant/extreme cases.
3. ``relax_0.25`` — quarter relaxation, 6x budget: the heavily damped
   crawl for stiffly coupled lanes.
4. ``tikhonov`` — diagonal-loaded (Tikhonov-regularized) fused solve
   (``solve_dynamics(tik=1e-6)``) at half relaxation: trades a bounded,
   reported bias for solvability when the impedance itself is nearly
   singular at some frequency.

(The reference tree this grew from has a single fixed-point scheme; an
alternative-accelerator rung slots in here if one lands — the ladder is
data, not control flow.)

Every rung is a SEPARATE compiled program: the per-lane solve goes
through the AOT registry (``cache.cached_callable``) keyed by the rung's
static knobs, so the healthy fast path — whose executable never sees a
rung — stays recompile-free, and a rung used twice compiles once.
Rungs run single-lane (batch-1-free shapes): quarantine is rare by
construction, and a fixed per-lane signature means no padded-batch
recompiles.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from raft_tpu.resilience.health import LaneHealth


@dataclasses.dataclass(frozen=True)
class Rung:
    name: str
    n_iter_mul: int          # multiplier on the sweep's iteration budget
    relax: float | None      # None = keep the caller's relaxation
    tik: float = 0.0         # diagonal-loading strength (0 = plain solve)


#: the default ladder, mildest first (see module docstring)
RUNGS: tuple = (
    Rung("n_iter_x4", 4, None),
    Rung("relax_0.5", 4, 0.5),
    Rung("relax_0.25", 6, 0.25),
    Rung("tikhonov", 6, 0.5, 1e-6),
)

DEFAULT_RELAX = 0.8          # solve_dynamics' own default


def rung_knobs(rung: Rung, base_n_iter: int,
               default_relax: float = DEFAULT_RELAX) -> tuple:
    """(n_iter, relax, tik) a rung resolves to for a given base budget."""
    n_iter = max(int(base_n_iter) * rung.n_iter_mul, int(base_n_iter) + 1)
    relax = default_relax if rung.relax is None else rung.relax
    return n_iter, relax, rung.tik


def escalate_lanes(lanes, solve_lane, base_n_iter: int,
                   rungs=RUNGS, default_relax: float = DEFAULT_RELAX):
    """Walk each quarantined lane up the ladder.

    ``solve_lane(index, n_iter, relax, tik)`` re-solves ONE lane with the
    given knobs and returns ``(payload, converged, finite, n_iter_used)``
    — payload a tuple of host arrays in the sweep's own result layout,
    the flags/count host scalars.  A lane is salvaged by the first rung
    whose result is converged and finite (device flags AND a host
    finiteness sweep over the payload — a rung may converge to NaN on
    NaN inputs, which must not count as salvage).

    Returns ``(records, salvaged)``: one :class:`LaneHealth` per lane in
    input order, and ``{index: payload}`` for the lanes a rung rescued.
    """
    from raft_tpu import obs as _obs

    records = []
    salvaged = {}
    for idx in np.asarray(lanes).reshape(-1):
        idx = int(idx)
        rec = LaneHealth(index=idx, converged=False, finite=False,
                         n_iter=0, quarantined=True)
        for rung in rungs:
            n_iter, relax, tik = rung_knobs(rung, base_n_iter, default_relax)
            _obs.metrics.counter(f"resilience.rung[{rung.name}]").inc()
            payload, conv, fin, used = solve_lane(idx, n_iter, relax, tik)
            rec.converged = bool(conv)
            rec.finite = bool(fin)
            rec.n_iter = int(used)
            host_ok = all(np.isfinite(np.asarray(p)).all() for p in payload)
            if rec.converged and rec.finite and host_ok:
                rec.salvaged = True
                rec.rung = rung.name
                salvaged[idx] = payload
                break
        records.append(rec)
    return records, salvaged


def quarantine_and_salvage(arrays, conv, finite, solve_lane,
                           base_n_iter: int, escalate: bool = True,
                           iters=None):
    """The host-side quarantine step every resilient sweep shares.

    ``arrays``: writable host arrays (leading axis = lane), in the SAME
    order as the payload tuples ``solve_lane`` returns — salvaged
    payloads are patched into them in place.  ``conv``/``finite``: the
    device-side verdict arrays (``finite`` may be None when the sweep
    had no device finite flag); copies are returned with salvaged lanes
    flipped healthy.  ``iters`` (optional, per-lane) stamps the records
    of lanes that were quarantined but not escalated.

    Returns ``(records, conv, finite)`` — one :class:`LaneHealth` per
    quarantined lane (empty when the batch was healthy).
    """
    from raft_tpu import obs as _obs
    from raft_tpu.resilience.health import failed_lanes

    conv = np.array(conv).astype(bool).reshape(-1)
    finite = (np.ones_like(conv) if finite is None
              else np.array(finite).astype(bool).reshape(-1))
    bad = failed_lanes(conv, finite, host_values=arrays)
    if not len(bad):
        return [], conv, finite
    _obs.metrics.counter("resilience.quarantined").inc(len(bad))
    if not escalate:
        it = np.zeros(len(conv), dtype=int) if iters is None else np.asarray(iters)
        # the record's finite verdict folds the host sweep in: a lane
        # whose fetched arrays are NaN must not read finite=True just
        # because the device flag (or a finite=None caller) said so
        host_fin = [all(np.isfinite(np.asarray(a[i])).all() for a in arrays)
                    for i in bad]
        records = [LaneHealth(index=int(i), converged=bool(conv[i]),
                              finite=bool(finite[i]) and bool(hf),
                              n_iter=int(it[i]), quarantined=True)
                   for i, hf in zip(bad, host_fin)]
        return records, conv, finite
    records, salvaged = escalate_lanes(bad, solve_lane, base_n_iter)
    _obs.metrics.counter("resilience.salvaged").inc(len(salvaged))
    _obs.metrics.counter("resilience.unsalvaged").inc(
        len(bad) - len(salvaged))
    for idx, payload in salvaged.items():
        for arr, val in zip(arrays, payload):
            arr[idx] = val
        conv[idx] = True
        finite[idx] = True
    return records, conv, finite
