"""Deterministic fault injection for the resilience test harness.

A long-running chunked sweep fails in a handful of stereotyped ways — a
lane of the batch diverges or goes NaN, the process is preempted between
chunks, a checkpoint artifact is corrupted on disk, a native toolchain
subprocess hangs.  Reproducing any of these against real hardware is
flaky by definition, so the resilience machinery carries its own
injection points, armed by one environment variable:

``RAFT_TPU_FAULT_INJECT``
    Comma-separated fault specs, each ``name`` or ``name:arg``:

    * ``nan_chunk:K`` — the fetched host results of chunk ``K`` are
      overwritten with NaN (float leaves only; convergence flags are left
      alone, mimicking a device that silently produced NaNs).  Applied in
      :func:`raft_tpu.parallel.pipeline.run_pipelined` at fetch time,
      BEFORE any checkpoint write — downstream quarantine must catch it
      exactly as it would a real one.
    * ``kill_after_chunk:K`` — the process exits hard
      (``os._exit(KILL_EXIT)``) right after chunk ``K``'s result is
      fetched (and checkpointed, when a store is active): the
      preemption/OOM-kill simulation for the resume path.
    * ``corrupt_ckpt:K`` — chunk ``K``'s checkpoint npz gets one byte
      flipped immediately after its atomic write
      (:meth:`raft_tpu.resilience.checkpoint.ChunkStore.save`): the
      bit-rot simulation for the content-hash detection path.
    * ``hang_subprocess[:N]`` — subprocess launches through
      :func:`raft_tpu.resilience.retry.checked_subprocess` sleep past
      their timeout instead of running; with ``:N`` only the first ``N``
      launches in this process hang (so a bounded retry can be seen to
      salvage the call).
    * ``kill_replica[:K]`` — the serving fleet's router
      (:mod:`raft_tpu.serve.router`) SIGKILLs the replica it just picked
      for the next ``K`` dispatches, BEFORE forwarding the request: the
      replica-death simulation for the failover-resubmission path (the
      request must still be answered, by a survivor).
    * ``stall_replica[:K]`` — the router registers the next ``K``
      forwarded requests but silently withholds the frames (the replica
      never sees them): the wedged-replica simulation for the
      forward-deadline / resubmission path.
    * ``refuse_connect[:K]`` — the router's next ``K`` replica connection
      attempts raise ``ConnectionRefusedError`` before touching the
      socket: the crash-during-restart simulation for the bounded
      reconnect ladder and the re-admission probe.

All injection points are HOST-side (fetch results, file writes,
subprocess spawns, router-side socket plumbing): arming a fault never
changes any traced/compiled program, so the AOT cache keys and the
trace-audit budgets are untouched by the harness.
"""
from __future__ import annotations

import os
import threading

import numpy as np

#: exit code of a ``kill_after_chunk`` hard exit (distinct from common
#: shells/python codes so the smoke can assert the kill really fired)
KILL_EXIT = 77

#: every fault kind an armed spec may name (the docstring above is the
#: contract; a misspelled kind must warn as loudly as a malformed arg —
#: a harness silently arming nothing "passes" every resilience check)
KINDS = frozenset({
    "nan_chunk", "kill_after_chunk", "corrupt_ckpt", "hang_subprocess",
    "kill_replica", "stall_replica", "refuse_connect",
})

# per-process consumption counters for counted faults (hang_subprocess:N);
# locked so ``name:N`` fires exactly N times even under concurrent
# subprocess launches (`make race-smoke` pins the exact count)
_counts: dict = {}
_counts_lock = threading.Lock()


def specs() -> dict:
    """Parse ``RAFT_TPU_FAULT_INJECT`` fresh (tests flip it in-process).

    Returns ``{name: [arg, ...]}`` with ``arg`` an int or None.  Malformed
    entries (non-integer arg) are ignored with a warning rather than
    killing the run a fault harness exists to protect.
    """
    raw = os.environ.get("RAFT_TPU_FAULT_INJECT", "").strip()
    out: dict = {}
    if not raw:
        return out
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, arg = part.partition(":")
        if name not in KINDS:
            import warnings

            warnings.warn(
                f"RAFT_TPU_FAULT_INJECT spec {part!r} names an unknown "
                f"fault kind (have {sorted(KINDS)}); ignoring it",
                stacklevel=2)
            continue
        if arg:
            try:
                arg_i = int(arg)
            except ValueError:
                import warnings

                warnings.warn(
                    f"RAFT_TPU_FAULT_INJECT spec {part!r} has a "
                    f"non-integer argument; ignoring it", stacklevel=2)
                continue
            out.setdefault(name, []).append(arg_i)
        else:
            out.setdefault(name, []).append(None)
    return out


def active() -> bool:
    """True when any fault spec is armed (one env read; the pipeline
    checks this once per pass so an unarmed process pays nothing)."""
    return bool(os.environ.get("RAFT_TPU_FAULT_INJECT", "").strip())


def chunk_fault(name: str, k: int) -> bool:
    """Does an armed ``name`` spec target chunk ``k``?  An argument-less
    spec targets every chunk."""
    args = specs().get(name)
    if not args:
        return False
    return any(a is None or a == int(k) for a in args)


def consume(name: str) -> bool:
    """Counted fault check: ``name`` fires always, ``name:N`` fires for
    the first ``N`` calls in this process (then stays quiet)."""
    args = specs().get(name)
    if not args:
        return False
    n = args[0]
    if n is None:
        return True
    with _counts_lock:          # check-then-act atomically: exactly N fires
        used = _counts.get(name, 0)
        if used < n:
            _counts[name] = used + 1
            return True
    return False


def reset_counts() -> None:
    """Forget counted-fault consumption (tests)."""
    with _counts_lock:
        _counts.clear()


def nan_results(result):
    """NaN-out the float leaves of a fetched chunk result (ints/bools —
    iteration counts, convergence flags — pass through untouched, the
    signature of a device that silently produced NaNs)."""
    def one(leaf):
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating):
            return np.full_like(a, np.nan)
        return leaf

    if isinstance(result, tuple):
        return tuple(one(x) for x in result)
    return one(result)


def maybe_kill_after_chunk(k: int) -> None:
    """Hard-exit the process if ``kill_after_chunk:k`` is armed.  Called
    after chunk ``k``'s fetch (and checkpoint write) completes —
    ``os._exit`` skips interpreter teardown, exactly like a preemption."""
    if chunk_fault("kill_after_chunk", k):
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(KILL_EXIT)


def maybe_corrupt_file(name: str, k: int, path: str) -> bool:
    """Flip one mid-file byte of ``path`` if ``name:k`` is armed (the
    checkpoint store calls this right after its atomic write).  Returns
    True when the corruption was applied."""
    if not chunk_fault(name, k):
        return False
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size == 0:
            return False
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    return True
