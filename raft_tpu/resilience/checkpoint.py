"""Durable per-chunk result store: a killed sweep resumes, not restarts.

The north-star workload is a chunked sweep over thousands of cases; a
preemption at chunk 37/40 used to throw away 36 chunks of finished
results.  This store makes partial progress durable, the same contract
RAFT's reference encodes with its compute-once WAMIT-file pattern
(SURVEY.md §5): each fetched chunk result is written as an atomic npz
(tmp + ``os.replace``; a kill mid-write can never leave a truncated
artifact that a later run would trust), indexed by a ``manifest.json``
(also atomically replaced) that records a content hash per chunk.

Keying: a store directory is named by the PROGRAM key — the same
:func:`raft_tpu.cache.aot.aot_key` digest that names the compiled
executable (argument signature + closure-consts hash + code fingerprint
+ topology + solver salts) plus the chunk count.  Any change to the
code, the inputs, or the knobs lands in a different directory, so a
resume can only ever be served results the CURRENT program would have
computed — float-eps-identical by construction (bitwise, in fact: npz
round-trips array bytes exactly).

Corruption tolerance is absolute (the staging-cache rule): a missing,
unreadable, truncated, or hash-mismatched chunk artifact counts as a
miss — logged, counted, deleted, recomputed — never served.

Armed by ``RAFT_TPU_CKPT``: unset/``off`` disables (the default — the
fast path stages and writes NOTHING new); ``1``/``on`` roots the store
under the cache root's ``ckpt/``; any other value is the root directory
itself.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading

import numpy as np

_OFF = ("off", "0", "none", "disabled", "false", "no")


def root() -> str | None:
    """The checkpoint root this process would use, or None when disabled."""
    v = os.environ.get("RAFT_TPU_CKPT", "").strip()
    if not v or v.lower() in _OFF:
        return None
    if v.lower() in ("1", "on", "true", "yes"):
        from raft_tpu.cache import config

        base = config.cache_dir() or config.resolve_dir() or config.default_dir()
        return os.path.join(base, "ckpt")
    return os.path.abspath(os.path.expanduser(v))


def enabled() -> bool:
    return root() is not None


def _leaf_hash(leaves) -> str:
    h = hashlib.sha256()
    for a in leaves:
        a = np.ascontiguousarray(np.asarray(a))
        h.update(f"{a.dtype.str}:{a.shape}:".encode())
        h.update(a.tobytes())
    return h.hexdigest()


def content_hash(leaves) -> str:
    """Value hash of a list of arrays, for folding input VALUES into a
    store key.  The AOT key a store derives from hashes call arguments
    abstractly (shape/dtype) — correct for executables, insufficient for
    stored results, which depend on the values; callers fold this hash
    of the value-bearing inputs into ``store_for``'s ``extra``."""
    return _leaf_hash(leaves)[:16]


class ChunkStore:
    """Per-chunk result store for one (program, chunk-count) identity.

    Results are flat tuples of host arrays (what the pipeline's fetch
    step produces); a non-tuple result is stored and restored as the
    bare array.  Construct via :func:`store_for` (which resolves the
    root and derives the program key) rather than directly.
    """

    def __init__(self, key: str, n_chunks: int, base: str):
        self.key = key
        self.n_chunks = int(n_chunks)
        self.dir = os.path.join(base, key)
        os.makedirs(self.dir, exist_ok=True)
        self._manifest_path = os.path.join(self.dir, "manifest.json")
        # per-store lock around every manifest read-modify-write (entry
        # update + atomic replace as one critical section): two threads
        # checkpointing chunks concurrently can never drop each other's
        # entries by racing the whole-file rewrite
        self._lock = threading.Lock()
        self.saved = 0
        self.resumed = 0
        self.corrupt = 0
        m = None
        try:
            with open(self._manifest_path) as f:
                m = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            m = None
        if (not isinstance(m, dict) or m.get("key") != key
                or m.get("n_chunks") != self.n_chunks):
            # unreadable manifest, or a stale store from a different
            # program/chunking under a colliding path: start fresh
            m = {"key": key, "n_chunks": self.n_chunks, "chunks": {}}
        self._manifest = m

    # ------------------------------------------------------------- paths

    def _chunk_path(self, k: int) -> str:
        return os.path.join(self.dir, f"chunk_{int(k)}.npz")

    def _write_manifest(self) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._manifest, f)
            os.replace(tmp, self._manifest_path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    # --------------------------------------------------------------- api

    def save(self, k: int, result) -> None:
        """Persist chunk ``k``: atomic npz first, manifest second — a
        kill between the two leaves an orphan file the manifest ignores
        (recomputed next run), never a manifest entry without data."""
        scalar = not isinstance(result, tuple)
        leaves = [result] if scalar else list(result)
        path = self._chunk_path(k)
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **{f"arr_{i}": np.asarray(a)
                               for i, a in enumerate(leaves)})
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        from raft_tpu.resilience import faults

        faults.maybe_corrupt_file("corrupt_ckpt", k, path)
        with self._lock:
            self._manifest["chunks"][str(int(k))] = {
                "sha": _leaf_hash(leaves), "n": len(leaves),
                "scalar": scalar,
            }
            self._write_manifest()
            self.saved += 1
        from raft_tpu import obs as _obs

        _obs.metrics.counter("ckpt.saved").inc()

    def _drop(self, k: int, why: str) -> None:
        import warnings

        warnings.warn(
            f"checkpoint chunk {k} of {self.key} is unusable ({why}); "
            f"it will be recomputed", stacklevel=3)
        from raft_tpu import obs as _obs

        _obs.metrics.counter("ckpt.corrupt").inc()
        with self._lock:
            self.corrupt += 1
            self._manifest["chunks"].pop(str(int(k)), None)
            try:
                os.unlink(self._chunk_path(k))
            except OSError:
                pass
            self._write_manifest()

    def load(self, k: int):
        """Chunk ``k``'s stored result, or None (missing or corrupt —
        a corrupt artifact is detected by content hash, logged, deleted,
        and counted; it is NEVER returned)."""
        with self._lock:
            entry = self._manifest["chunks"].get(str(int(k)))
        if entry is None:
            return None
        try:
            with np.load(self._chunk_path(k), allow_pickle=False) as z:
                leaves = [z[f"arr_{i}"] for i in range(int(entry["n"]))]
        except Exception:
            self._drop(k, "unreadable/truncated npz")
            return None
        if _leaf_hash(leaves) != entry["sha"]:
            self._drop(k, "content hash mismatch")
            return None
        with self._lock:
            self.resumed += 1
        from raft_tpu import obs as _obs

        _obs.metrics.counter("ckpt.resumed").inc()
        return leaves[0] if entry.get("scalar") else tuple(leaves)

    def complete(self) -> bool:
        return len(self._manifest["chunks"]) >= self.n_chunks

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "dir": self.dir,
            "n_chunks": self.n_chunks,
            "saved": self.saved,
            "resumed": self.resumed,
            "corrupt": self.corrupt,
        }


def store_for(tag: str, args, *, consts=(), extra=(), n_chunks: int,
              mesh=None) -> ChunkStore | None:
    """A :class:`ChunkStore` for the program identified exactly as the
    AOT registry would key its executable, or None when ``RAFT_TPU_CKPT``
    is off.  ``tag``/``args``/``consts``/``extra`` must mirror the
    ``cached_callable``/``cached_compile`` call the chunks run through —
    that is what makes resumed results program-identical."""
    base = root()
    if base is None:
        return None
    from raft_tpu.cache import aot

    key = aot.aot_key(tag, args, consts=consts, mesh=mesh,
                      extra=(*tuple(extra), "n_chunks", int(n_chunks)))
    return ChunkStore(key[:24], n_chunks, base)
