"""Bounded, backoff-aware retry for host-side fallible operations.

One retry discipline for every flaky host boundary the framework crosses
— backend probes, native-toolchain builds, device-child benches — instead
of a bespoke loop per call site:

* attempts are BOUNDED (``retries``), never unbounded spin;
* waits between attempts grow exponentially (``backoff_s * growth**n``,
  capped at ``max_backoff_s``) — a transient wedge gets room to clear
  without a tight retry hammering it;
* an optional ``deadline_s`` makes the whole ladder wall-clock-aware:
  no attempt starts (and no sleep happens) past the deadline, so a
  caller with a driver budget can hand the budget down instead of
  multiplying worst cases.

:func:`checked_subprocess` is the companion primitive for child
processes: a hard timeout on every launch (a native build or backend
init can hang forever — ISSUE 5's ``g++`` case), non-zero exit turned
into a typed exception carrying a REDACTED stderr tail (these
diagnostics land verbatim in committed bench artifacts), and the
``hang_subprocess`` fault-injection hook for the resilience harness.
"""
from __future__ import annotations

import os
import re
import subprocess
import sys
import time


class SubprocessFailed(RuntimeError):
    """A checked subprocess timed out, failed to spawn, or exited non-zero.

    ``kind``: ``"timeout"`` / ``"nonzero"`` / ``"spawn"``;
    ``stderr_tail``: redacted tail of the child's stderr (may be "").
    """

    def __init__(self, describe: str, kind: str, detail: str = "",
                 returncode: int | None = None, stderr_tail: str = ""):
        self.describe = describe
        self.kind = kind
        self.detail = detail
        self.returncode = returncode
        self.stderr_tail = stderr_tail
        msg = f"{describe}: {kind}"
        if returncode is not None:
            msg += f" (rc={returncode})"
        if detail:
            msg += f": {detail}"
        if stderr_tail:
            msg += f"\nstderr tail: {stderr_tail}"
        super().__init__(msg)


class RetryExhausted(RuntimeError):
    """Every attempt of a :func:`retry_call` ladder failed.

    ``last`` is the final attempt's exception; ``attempts`` how many ran;
    ``elapsed_s`` total wall-clock including backoff sleeps.
    """

    def __init__(self, describe: str, attempts: int, elapsed_s: float, last):
        self.describe = describe
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.last = last
        super().__init__(
            f"{describe}: {attempts} attempt(s) failed in {elapsed_s:.1f}s; "
            f"last error: {last}")


def redacted_tail(text, n: int = 300) -> str:
    """Last ~n chars of subprocess output with credential-looking tokens
    masked — the shared redaction rule for every diagnostic that lands in
    a committed artifact (bench error dicts, build failures, retry logs).

    Redacts BEFORE truncating: slicing first could cut the key prefix
    ('Bearer ', 'api_key=') off a credential that straddles the cut,
    leaving the bare token with nothing for the patterns to anchor on.
    """
    if not text:
        return ""
    if isinstance(text, bytes):
        text = text.decode("utf-8", "replace")
    # header form first ("Authorization: Bearer <tok>" / bare
    # "Bearer <tok>" — the credential follows the word, no = or :
    # between them), then key=value / key: value forms, then bare
    # sk-style keys
    text = re.sub(r"(?i)(bearer\s+)\S+", r"\1[redacted]", text.strip())
    text = re.sub(
        r"(?i)((?:api[_-]?key|token|secret|password|authorization)"
        r"\S*\s*[=:]\s*)\S+",
        r"\1[redacted]", text,
    )
    return re.sub(r"\bsk-[A-Za-z0-9_-]{8,}", "[redacted]", text)[-n:]


def retry_call(fn, *, retries: int = 3, backoff_s: float = 1.0,
               growth: float = 2.0, max_backoff_s: float = 60.0,
               deadline_s: float | None = None,
               retry_on: tuple = (Exception,), describe: str = "call",
               on_retry=None, sleep=time.sleep):
    """Call ``fn(attempt)`` up to ``retries`` times with exponential
    backoff between attempts; return its value, or raise
    :class:`RetryExhausted` wrapping the last failure.

    ``fn`` receives the 0-based attempt index.  Only exceptions matching
    ``retry_on`` are retried — anything else propagates immediately
    (a deterministic failure should not burn the backoff budget).
    ``deadline_s`` bounds the TOTAL wall-clock from the first attempt:
    when the next backoff sleep (or next attempt) would start past the
    deadline, the ladder stops early and raises with whatever the last
    error was.  ``on_retry(attempt, exc)`` observes each failure (logging
    hooks); ``sleep`` is injectable for tests.
    """
    retries = max(1, int(retries))
    t0 = time.monotonic()
    last = None
    attempts = 0
    for attempt in range(retries):
        if attempt:
            delay = min(backoff_s * growth ** (attempt - 1), max_backoff_s)
            if deadline_s is not None:
                remaining = deadline_s - (time.monotonic() - t0)
                if remaining <= delay:
                    break          # deadline-aware: no pointless sleep
            sleep(delay)
        attempts += 1
        try:
            return fn(attempt)
        except retry_on as e:      # noqa: PERF203 - the point of the loop
            last = e
            if on_retry is not None:
                on_retry(attempt, e)
            if (deadline_s is not None
                    and time.monotonic() - t0 >= deadline_s):
                break
    raise RetryExhausted(describe, attempts, time.monotonic() - t0, last)


def checked_subprocess(cmd, *, timeout_s: float, env=None,
                       describe: str = "subprocess",
                       require_stdout: bool = False):
    """``subprocess.run`` with a HARD timeout and typed failure.

    Returns the ``CompletedProcess`` on rc == 0 (and, with
    ``require_stdout``, non-empty stdout); raises
    :class:`SubprocessFailed` otherwise, with a redacted stderr tail so
    the caller's diagnostics are safe to commit.  The
    ``hang_subprocess`` fault spec (:mod:`raft_tpu.resilience.faults`)
    substitutes a sleep-forever child so timeout/retry paths can be
    exercised deterministically.
    """
    from raft_tpu.resilience import faults

    if faults.consume("hang_subprocess"):
        cmd = [sys.executable, "-c", "import time; time.sleep(3600)"]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired as e:
        raise SubprocessFailed(
            describe, "timeout",
            detail=f"did not complete within {timeout_s:.0f}s",
            stderr_tail=redacted_tail(getattr(e, "stderr", None)))
    except OSError as e:
        raise SubprocessFailed(describe, "spawn", detail=str(e)[-300:])
    if r.returncode != 0:
        raise SubprocessFailed(
            describe, "nonzero", returncode=r.returncode,
            stderr_tail=redacted_tail(r.stderr or r.stdout))
    if require_stdout and not r.stdout.strip():
        raise SubprocessFailed(
            describe, "nonzero", returncode=r.returncode,
            detail="exited 0 with empty stdout",
            stderr_tail=redacted_tail(r.stderr))
    return r


def build_timeout_s(default: float = 300.0) -> float:
    """Native-toolchain build timeout from ``RAFT_TPU_BUILD_TIMEOUT``
    (seconds; the ``g++`` BEM build must never hang a sweep forever)."""
    v = os.environ.get("RAFT_TPU_BUILD_TIMEOUT", "").strip()
    if not v:
        return default
    try:
        return max(1.0, float(v))
    except ValueError:
        import warnings

        warnings.warn(
            f"RAFT_TPU_BUILD_TIMEOUT={v!r} is not a number; using the "
            f"default {default:.0f}s", stacklevel=2)
        return default
