"""``make resilience-smoke``: CPU proof of the whole resilience PR in < 60 s.

Drives one small chunked OC3 DLC sweep through every resilience path,
with REAL process boundaries (the properties being proven — durability
across a kill, cross-process resume — cannot be faked in-process):

1. **Reference** — the uninterrupted sweep, in-process, checkpointing
   off.  Also warms the shared AOT disk cache so the child runs below
   pay no repeat compiles.
2. **Kill** — the same sweep in a child with
   ``RAFT_TPU_FAULT_INJECT=kill_after_chunk:0`` and a checkpoint store
   armed: the child must die with the harness's kill exit code AFTER
   persisting chunk 0.
3. **Resume** — the same child command without the fault: it must
   resume chunk 0 from the manifest, recompute ONLY the missing chunk,
   and its final results must match the uninterrupted reference to
   float eps (bitwise in practice: same executable, npz round-trips
   bytes exactly).
4. **NaN quarantine + ladder** — the sweep with
   ``RAFT_TPU_FAULT_INJECT=nan_chunk:1``: the poisoned chunk's lanes
   must be quarantined (never silently dropped), salvaged through the
   escalation ladder, reported in the health block, and land within
   convergence tolerance of the reference.

Prints one JSON line; rc 0 iff every check is green.
"""
# graftlint: disable-file=GL105 — host-side verification arithmetic only:
# the f64 upcasts here are deliberate (a 1e-300 epsilon in the relative
# error would underflow in the sweeps' f32), nothing in this module is
# ever traced
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

_CASES = [[6.0, 10.0], [7.0, 11.0], [8.0, 12.0], [9.0, 13.0]]
_NW = 8
_N_ITER = 8
_CHUNK = 2


def _smoke_case():
    """The one tiny OC3 DLC workload every smoke step runs (4 sea
    states, 2 chunks, strip theory only — the machinery under proof is
    quarantine/checkpoint/ladder, not panel-solve physics)."""
    from raft_tpu.model import stage_design_base
    from raft_tpu.parallel.sweep import make_wave_states

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    design, members, rna, env, wave, C_moor = stage_design_base(
        os.path.join(pkg, "designs", "OC3spar.yaml"),
        nw=_NW, Hs=6.0, Tp=10.0, w_min=0.3, w_max=2.1)
    depth = float(design["mooring"]["water_depth"])
    waves = make_wave_states(np.asarray(wave.w), _CASES, depth)
    return members, rna, env, waves, C_moor


def _run_case():
    from raft_tpu.parallel.sweep import sweep_sea_states

    members, rna, env, waves, C_moor = _smoke_case()
    return sweep_sea_states(members, rna, env, waves, C_moor,
                            n_iter=_N_ITER, chunk=_CHUNK, health=True)


def _smoke_child(out_path: str) -> int:
    """Child body: run the smoke sweep under whatever RAFT_TPU_CKPT /
    RAFT_TPU_FAULT_INJECT the parent armed, persist the results, print
    one JSON stats line."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from raft_tpu import cache

    cache.enable()        # share the parent's AOT disk (RAFT_TPU_CACHE_DIR)
    res = _run_case()
    np.savez(out_path, std=res["std dev"],
             a_nac=res["nacelle accel std dev"],
             iters=res["iterations"], xi=res["Xi_abs2"],
             conv=res["converged"], finite=res["finite"])
    print(json.dumps({
        "pipeline": res["pipeline"],
        "checkpoint": res.get("checkpoint"),
        "health": res["health"],
    }))
    return 0


def _child_cmd(out_path: str):
    return [sys.executable, "-m", "raft_tpu.resilience", "--child", out_path]


def _smoke() -> int:
    import shutil

    tmp = tempfile.mkdtemp(prefix="raft_resilience_smoke_")
    try:
        return _smoke_body(tmp)
    finally:
        # the workspace holds multi-MB AOT/XLA caches + checkpoint npz
        # per run — CI runs this on every build (cache smoke precedent)
        shutil.rmtree(tmp, ignore_errors=True)


def _smoke_body(tmp: str) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.perf_counter()
    from raft_tpu import cache
    from raft_tpu.resilience import faults

    cache_dir = os.path.join(tmp, "cache")
    ckpt_dir = os.path.join(tmp, "ckpt")
    base_env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "RAFT_TPU_CACHE_DIR": cache_dir,
        "RAFT_TPU_STRICT": "0",
        "RAFT_TPU_CKPT": "off",
    }
    base_env.pop("RAFT_TPU_FAULT_INJECT", None)

    # 1. uninterrupted reference, in-process (warms the shared AOT disk)
    os.environ.pop("RAFT_TPU_CKPT", None)
    os.environ.pop("RAFT_TPU_FAULT_INJECT", None)
    cache.enable(cache_dir)
    ref = _run_case()
    ref_healthy = bool(ref["health"]["n_quarantined"] == 0
                       and np.isfinite(ref["std dev"]).all())
    # f64 for the relative-error checks: the sweep's f32 results + a
    # 1e-300 epsilon would underflow (numpy 2 weak-scalar promotion
    # keeps f32), turning exact-zero columns into 0/0
    ref_std = np.asarray(ref["std dev"], dtype=np.float64)
    denom = np.abs(ref_std) + 1e-300

    def run_child(tag, **env_over):
        out_path = os.path.join(tmp, f"{tag}.npz")
        r = subprocess.run(
            _child_cmd(out_path), capture_output=True, text=True,
            timeout=300, env={**base_env, **env_over},
        )
        line = (r.stdout.strip().splitlines() or ["{}"])[-1]
        try:
            stats = json.loads(line)
        except json.JSONDecodeError:
            stats = {}
        return r.returncode, stats, out_path, r.stderr[-800:]

    # 2. kill after chunk 0 (checkpoint store armed)
    rc_kill, _, _, err_kill = run_child(
        "kill", RAFT_TPU_CKPT=ckpt_dir,
        RAFT_TPU_FAULT_INJECT="kill_after_chunk:0")
    killed_ok = rc_kill == faults.KILL_EXIT
    # the manifest must already hold chunk 0 — that is what the kill
    # fault is timed to prove (persist first, die second)
    n_ckpt_files = sum(
        f.startswith("chunk_") for d, _, fs in os.walk(ckpt_dir) for f in fs)
    persisted_ok = n_ckpt_files >= 1

    # 3. resume: only the missing chunk recomputes; float-eps parity
    rc_res, st_res, out_res, err_res = run_child(
        "resume", RAFT_TPU_CKPT=ckpt_dir)
    resumed = st_res.get("pipeline", {}).get("chunks_resumed", -1)
    computed = st_res.get("pipeline", {}).get("chunks_computed", -1)
    resume_ok = (rc_res == 0 and resumed == 1 and computed == 1)
    parity = None
    if rc_res == 0:
        z = np.load(out_res)
        parity = float(np.max(
            np.abs(np.asarray(z["std"], np.float64) - ref_std) / denom))
        resume_ok = bool(resume_ok and parity < 1e-12
                         and bool(z["conv"].all()))

    # 4. NaN chunk -> quarantine -> ladder salvage (no lane dropped)
    rc_nan, st_nan, out_nan, err_nan = run_child(
        "nan", RAFT_TPU_FAULT_INJECT="nan_chunk:1")
    h = st_nan.get("health", {})
    nan_lanes = list(range(_CHUNK, 2 * _CHUNK))      # chunk 1's lanes
    nan_ok = (rc_nan == 0
              and h.get("quarantined") == nan_lanes
              and h.get("salvaged") == _CHUNK
              and not h.get("unsalvaged"))
    salvage_rel = None
    if rc_nan == 0:
        z = np.load(out_nan)
        # zero lanes silently dropped: every lane finite, every lane
        # within convergence tolerance of the uninterrupted reference
        # (salvaged lanes ran more iterations — tol-level, not bitwise)
        salvage_rel = float(np.max(
            np.abs(np.asarray(z["std"], np.float64) - ref_std) / denom))
        nan_ok = bool(nan_ok and np.isfinite(z["std"]).all()
                      and np.isfinite(z["xi"]).all() and salvage_rel < 2e-2)

    ok = bool(ref_healthy and killed_ok and persisted_ok and resume_ok
              and nan_ok)
    print(json.dumps({
        "ok": ok,
        "reference_healthy": ref_healthy,
        "killed_with_expected_rc": killed_ok,
        "chunk0_persisted_before_kill": persisted_ok,
        "resume": {"ok": resume_ok, "chunks_resumed": resumed,
                   "chunks_recomputed": computed,
                   "max_rel_vs_uninterrupted": parity},
        "nan_quarantine": {"ok": nan_ok, "health": h,
                           "max_rel_vs_uninterrupted": salvage_rel},
        "wall_s": round(time.perf_counter() - t0, 2),
        **({} if ok else {"stderr_tails": {
            "kill": err_kill[-300:], "resume": err_res[-300:],
            "nan": err_nan[-300:]}}),
    }))
    return 0 if ok else 1
