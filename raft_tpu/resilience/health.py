"""Per-lane health verdicts: no lane fails silently, no lane poisons a batch.

A batched sweep used to have exactly two outcomes: every lane converged
finite, or one ``assert`` threw the whole batch away.  The resilience
contract replaces that with a per-lane verdict — ``(converged, finite,
n_iter)``, the first two computed DEVICE-side inside the compiled sweep
(``finite`` over the full response spectra, which in ``return_xi=False``
mode never cross to host) — and a host-side quarantine step that
separates failed lanes from healthy ones instead of aborting.

Quarantined lanes go through the escalation ladder
(:mod:`raft_tpu.resilience.ladder`); whatever the outcome, every lane
ends with a :class:`LaneHealth` record and the batch-level
:func:`summarize` block that the bench embeds as its ``resilience``
key — degradation is visible, never silent.

``RAFT_TPU_STRICT`` (default ON — unset means strict) preserves the old
all-or-nothing contract at the call sites that had it (bench asserts):
strict mode reports the same structured block, then fails loudly.
"""
from __future__ import annotations

import dataclasses
import os

import numpy as np


def strict() -> bool:
    """The all-or-nothing gate: True unless ``RAFT_TPU_STRICT`` spells an
    explicit off.  Strict is the DEFAULT (and stays the default in CI):
    degradation-tolerant behavior is an opt-in, never a surprise."""
    v = os.environ.get("RAFT_TPU_STRICT", "").strip().lower()
    if not v:
        return True
    return v not in ("0", "false", "off", "no")


@dataclasses.dataclass
class LaneHealth:
    """Final verdict for one batch lane.

    ``converged``/``finite``/``n_iter`` reflect the lane's LAST solve —
    the original batch solve for healthy lanes, the successful (or final
    failed) ladder rung for quarantined ones.  ``rung`` names the ladder
    rung that salvaged the lane (None when the lane never needed one, or
    nothing salvaged it)."""

    index: int
    converged: bool
    finite: bool
    n_iter: int
    quarantined: bool = False
    salvaged: bool = False
    rung: str | None = None


def failed_lanes(converged, finite=None, host_values=()) -> np.ndarray:
    """Indices of lanes whose verdict is bad: not converged, device-side
    non-finite, or non-finite in any of the fetched ``host_values``
    arrays (leading axis = lane) — the last check catches anything that
    went bad AFTER the device verdict (fetch-path corruption, injected
    faults), so quarantine can never be talked out of by a stale flag."""
    ok = np.asarray(converged).astype(bool).reshape(-1).copy()
    if finite is not None:
        ok &= np.asarray(finite).astype(bool).reshape(-1)
    for v in host_values:
        a = np.asarray(v)
        a = a.reshape(a.shape[0], -1) if a.ndim > 1 else a.reshape(-1, 1)
        ok &= np.isfinite(a).all(axis=1)
    return np.where(~ok)[0]


def summarize(records, n_lanes: int, extra: dict | None = None) -> dict:
    """The batch-level ``resilience`` block (bench JSON / sweep result):
    who was quarantined, who was salvaged and by which rung, who stayed
    bad — plus any caller extras (checkpoint counters, strictness)."""
    records = list(records)
    rungs_used: dict = {}
    for r in records:
        if r.salvaged and r.rung:
            rungs_used[r.rung] = rungs_used.get(r.rung, 0) + 1
    out = {
        "lanes": int(n_lanes),
        "n_quarantined": len(records),
        "quarantined": [int(r.index) for r in records],
        "salvaged": sum(1 for r in records if r.salvaged),
        "unsalvaged": [int(r.index) for r in records if not r.salvaged],
        "rungs_used": rungs_used,
    }
    if extra:
        out.update(extra)
    return out
