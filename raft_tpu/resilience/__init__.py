"""Resilient sweeps: lane quarantine, escalation retry, chunk
checkpoint/resume, and a deterministic fault-injection harness.

The north-star workload is a long-running chunked sweep over thousands
of designs/sea states.  Before this subsystem, one diverged or NaN lane
aborted the whole batch, a preempted run threw away every finished
chunk, and a hung native-toolchain subprocess could stall a sweep
forever.  The production contract is the opposite — partial progress is
durable, bad cases are quarantined and REPORTED, the fleet keeps moving:

* :mod:`~raft_tpu.resilience.health` — per-lane ``(converged, finite,
  n_iter)`` verdicts computed device-side inside the compiled sweeps;
  quarantine instead of batch abort; the ``RAFT_TPU_STRICT`` gate
  (default ON) preserving the old all-or-nothing behavior where it
  existed.
* :mod:`~raft_tpu.resilience.ladder` — quarantined lanes re-solved
  through an escalation ladder (bigger iteration budget → reduced
  relaxation → Tikhonov-regularized fused solve), each rung its own
  AOT-cached executable so the healthy path never recompiles.
* :mod:`~raft_tpu.resilience.checkpoint` — durable per-chunk result
  store (atomic npz + content-hashed manifest, keyed by the program's
  AOT key) under ``RAFT_TPU_CKPT``; a killed sweep resumes at the first
  missing chunk with bit-identical results.
* :mod:`~raft_tpu.resilience.retry` — bounded, exponential-backoff,
  deadline-aware retry + hard-timeout subprocess wrapper (the ``g++``
  BEM build, the bench's backend probes) with shared stderr redaction.
* :mod:`~raft_tpu.resilience.faults` — ``RAFT_TPU_FAULT_INJECT``
  deterministic fault points (NaN chunk, kill-after-chunk, checkpoint
  corruption, hanging subprocess), all host-side: arming a fault never
  changes a traced program.

``python -m raft_tpu.resilience`` runs the CPU smoke proving the
kill-and-resume and NaN-quarantine-and-salvage paths end to end
(``make resilience-smoke``, wired into the CI fast job).
"""
from raft_tpu.resilience.health import (  # noqa: F401
    LaneHealth,
    failed_lanes,
    strict,
    summarize,
)
from raft_tpu.resilience.ladder import (  # noqa: F401
    RUNGS,
    Rung,
    escalate_lanes,
    quarantine_and_salvage,
    rung_knobs,
)
from raft_tpu.resilience.checkpoint import ChunkStore, store_for  # noqa: F401
from raft_tpu.resilience.retry import (  # noqa: F401
    RetryExhausted,
    SubprocessFailed,
    checked_subprocess,
    redacted_tail,
    retry_call,
)
