"""``python -m raft_tpu.resilience``: the resilience smoke
(:mod:`raft_tpu.resilience.smoke`).  ``--child <out.npz>`` is the
internal entry the smoke's subprocess steps re-invoke."""
from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv[:1] == ["--child"]:
        if len(argv) != 2:
            print("usage: python -m raft_tpu.resilience [--child OUT.npz]",
                  file=sys.stderr)
            return 2
        from raft_tpu.resilience.smoke import _smoke_child

        return _smoke_child(argv[1])
    if argv:
        print("usage: python -m raft_tpu.resilience [--child OUT.npz]",
              file=sys.stderr)
        return 2
    from raft_tpu.resilience.smoke import _smoke

    return _smoke()


if __name__ == "__main__":
    sys.exit(main())
