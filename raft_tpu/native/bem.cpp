// Native frequency-domain panel-method BEM solver (HAMS-equivalent).
//
// Role in raft_tpu: the reference drives an external Fortran BEM executable
// (HAMS, hams/pyhams.py:361-373) to produce potential-flow radiation and
// diffraction coefficients A(w), B(w), X(w) from a hull panel mesh.  This
// file is the first-class native replacement: a constant-strength source
// (Hess & Smith) panel method with the deep-water free-surface Green
// function, OpenMP-threaded over panel pairs, exposed through a C API for
// the ctypes wrapper in raft_tpu/hydro/native_bem.py.  Results are staged
// to the JAX pipeline as device arrays (Model(BEM=(A, B, F))).
//
// Method
// ------
// Green function, infinite depth, e^{i w t} time convention
// (Wehausen & Laitone eq. 13.17):
//   G(P,Q) = 1/r + 1/r1 + Gf,
//   Gf     = 2k * [ I0(X, Y) - i pi e^Y J0(X) ],
// with r the direct distance, r1 the distance to the free-surface image of
// Q, k = w^2/g, X = k*R (horizontal), Y = k*(z+zeta) <= 0, and
//   I0(X,Y) = PV Int_0^inf e^{uY} J0(uX) / (u-1) du,
// the dimensionless principal-value wave integral (u = kappa/k).  I0 and
// its J1 counterpart I1 are precomputed once on a 2-D table over
// (X, log(1-Y)) and bilinearly interpolated -- the Delhommeau-table
// strategy used by established BEM codes; direct evaluation uses pole
// subtraction on [0,2] plus Bessel-zero-segmented tail quadrature.
//
// Derivatives (for the source boundary condition) use the identities
//   dI0/dY' = 1/sqrt(X^2+Y^2)_scaled + I0           (no new integral)
//   dI0/dX  = -[ C1(X,Y) + I1(X,Y) ],  C1 = (1/X)(1 - (-Y)/sqrt(X^2+Y^2))
//
// Radiation problem k=1..6:  (2 pi I + D) sigma = n_k    (source strengths)
// Diffraction:               (2 pi I + D) sigma = -d(phi_I)/dn
// with D_ij the normal-derivative influence of panel j at collocation i
// (Rankine parts integrated with Gauss subdivision near the singularity and
// the exact flat-polygon formula for the self term), then
//   phi = S sigma,   A - iB/w = rho Int phi_k n_j dS   (radiation)
//   X_j = -i w rho Int (phi_I + phi_S) n_j dS          (excitation)
//
// Validation: reference HAMS outputs for the 1008-panel cylinder
// (raft/data/cylinder/Output/Wamit_format/Buoy.1/.3) and Hulme's analytic
// hemisphere coefficients -- see tests/test_native_bem.py.
#include <cmath>
#include <complex>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

using cdouble = std::complex<double>;
static const double PI = 3.14159265358979323846;

// ----------------------------------------------------------------- tables
//
// The tables store the SMOOTH parts of the wave integrals: near X=Y=0 the
// integrals behave like  I0 ~ -ln(rho) ,  I1 ~ -C1 + X/rho^2  with
// rho = sqrt(X^2+Y^2), C1 = (1/X)(1 - (-Y)/rho); subtracting those closed
// forms makes bilinear interpolation accurate everywhere.

static inline double sing_I0(double X, double Y) {
    return -0.5 * log(X * X + Y * Y);
}
static inline double sing_I1(double X, double Y) {
    double r2 = X * X + Y * Y;
    double C1 = X > 1e-12 ? (1.0 / X) * (1.0 - (-Y) / sqrt(r2)) : 0.0;
    return -C1 + X / r2;
}

struct WaveTable {
    // X grid: uniform [0, XMAX]; Y grid: s = log(1 - Y) uniform [0, SMAX]
    static constexpr double XMAX = 60.0;
    static constexpr double SMAX = 4.1108738641733;   // log(1+60)
    static constexpr int NX = 1600;
    static constexpr int NS = 320;
    std::vector<double> I0, I1;                        // smooth parts, NX*NS
    bool built = false;

    static double direct_I(double X, double Y, int order);
    void build();
    void eval(double X, double Y, double* i0, double* i1) const;
};

static double gauss_x64[32], gauss_w64[32];            // 64-pt GL half nodes
static void init_gauss64() {
    // 64-point Gauss-Legendre nodes/weights on [-1,1] via Newton iteration
    static bool done = false;
    if (done) return;
    int n = 64;
    for (int i = 0; i < n / 2; i++) {
        double x = cos(PI * (i + 0.75) / (n + 0.5));
        for (int it = 0; it < 100; it++) {
            double p0 = 1.0, p1 = 0.0;
            for (int j = 0; j < n; j++) {
                double p2 = p1; p1 = p0;
                p0 = ((2.0 * j + 1.0) * x * p1 - j * p2) / (j + 1.0);
            }
            double dp = n * (x * p0 - p1) / (x * x - 1.0);
            double dx = -p0 / dp;
            x += dx;
            if (fabs(dx) < 1e-15) break;
        }
        double p0 = 1.0, p1 = 0.0;
        for (int j = 0; j < n; j++) {
            double p2 = p1; p1 = p0;
            p0 = ((2.0 * j + 1.0) * x * p1 - j * p2) / (j + 1.0);
        }
        double dp = n * (x * p0 - p1) / (x * x - 1.0);
        gauss_x64[i] = x;
        gauss_w64[i] = 2.0 / ((1.0 - x * x) * dp * dp);
    }
    done = true;
}

static inline double bess(int order, double x) {
    return order == 0 ? j0(x) : j1(x);
}

// ---------------------------------------------------- complex E1 and Phi
//
// Phi(zeta) = PV Int_0^inf e^{u zeta} / (u-1) du      (Re zeta <= 0)
//           = e^zeta [ 2 Shi(zeta) + E1(-zeta) ]
// derivation: shift t = u-1; the odd part over [-1,1] is 2 Shi(zeta), the
// tail over [1,inf) is E1(-zeta).  All wave integrals reduce to Phi via
// J0(x) = (1/pi) Int_0^pi cos(x sin th) dth  ->  zeta = Y + i X sin th.

// Phi(zeta) = e^zeta [ E1(zeta) + i pi ]   for Im zeta >= 0
// (from 2 Shi(z) = E1(z) - E1(-z) + i pi, Im z > 0; verified against the
// PV definition with mpmath).  E1 uses the power series for |z| <= 22
// (principal log gives the limit-from-above on the negative-real cut,
// exactly the PV convention needed) and the asymptotic e^{-z}/z series
// beyond.
static cdouble phi_pv(cdouble z) {
    double az = std::abs(z);
    const double EULER = 0.5772156649015329;
    if (az < 1e-14) z = cdouble(-1e-14, 0.0);
    if (az <= 22.0) {
        cdouble sum = 0.0, term = 1.0;
        for (int n = 1; n <= 220; n++) {
            term *= -z / (double)n;
            cdouble add = -term / (double)n;
            sum += add;
            if (std::abs(add) < 1e-17 * (1.0 + std::abs(sum)) && n > 4) break;
        }
        cdouble E1 = -EULER - std::log(z) + sum;
        return std::exp(z) * (E1 + cdouble(0.0, PI));
    }
    // e^z E1(z) ~ (1/z) sum (-1)^n n! / z^n  (truncate at smallest term)
    cdouble acc = 0.0, zp = 1.0 / z;
    double fact = 1.0;
    double prev = 1e300;
    for (int n = 0; n < 20; n++) {
        double mag = fact / pow(az, n + 1);
        if (mag > prev) break;                        // series turned
        prev = mag;
        acc += ((n % 2) ? -fact : fact) * zp;
        zp /= z;
        fact *= (double)(n + 1);
    }
    return acc + std::exp(z) * cdouble(0.0, PI);
}

// exact I0, I1 via the theta reduction (any X >= 0, Y <= 0, not both ~0)
static void analytic_I(double X, double Y, double* i0, double* i1);

static void analytic_I(double X, double Y, double* i0, double* i1) {
    init_gauss64();
    double acc0 = 0.0, accX = 0.0;
    int m = 1 + (int)(X / 20.0);                      // resolve cos(X sin th)
    for (int p = 0; p < m; p++) {
        double a = PI * p / m, b = PI * (p + 1) / m;
        for (int i = 0; i < 32; i++) {
            for (int sgn = -1; sgn <= 1; sgn += 2) {
                double x = sgn * gauss_x64[i];
                double th = 0.5 * (a + b) + 0.5 * (b - a) * x;
                double wgt = gauss_w64[i] * 0.5 * (b - a);
                double s = sin(th);
                cdouble zeta(Y, X * s);
                if (std::abs(zeta) < 1e-14) zeta = cdouble(-1e-14, 0.0);
                cdouble Phi = phi_pv(zeta);
                acc0 += wgt * Phi.real();
                cdouble dPhi = -1.0 / zeta + Phi;     // dPhi/dzeta
                accX += wgt * (dPhi * cdouble(0.0, s)).real();
            }
        }
    }
    *i0 = acc0 / PI;
    double dI0_dX = accX / PI;
    double rr = sqrt(X * X + Y * Y);
    double C1 = X > 1e-9 ? (1.0 / X) * (1.0 - (-Y) / rr) : 0.0;
    *i1 = X > 1e-9 ? (-C1 - dI0_dX) : 0.0;
}

// E1(x) for x > 0 (Abramowitz & Stegun 5.1.53/5.1.56)
static double expint_e1(double x) {
    if (x <= 0) return 0.0;
    if (x < 1.0) {
        double a0 = -0.57721566, a1 = 0.99999193, a2 = -0.24991055,
               a3 = 0.05519968, a4 = -0.00976004, a5 = 0.00107857;
        return -log(x) + a0 + x * (a1 + x * (a2 + x * (a3 + x * (a4 + x * a5))));
    }
    double b1 = 8.5733287401, b2 = 18.0590169730, b3 = 8.6347608925, b4 = 0.2677737343;
    double c1 = 9.5733223454, c2 = 25.6329561486, c3 = 21.0996530827, c4 = 3.9584969228;
    double num = x * x * x * x + b1 * x * x * x + b2 * x * x + b3 * x + b4;
    double den = x * x * x * x + c1 * x * x * x + c2 * x * x + c3 * x + c4;
    return exp(-x) / x * num / den;
}

// PV Int_0^inf e^{uY} J_ord(uX) / (u-1) du, Y <= 0.
double WaveTable::direct_I(double X, double Y, int order) {
    init_gauss64();
    auto f = [&](double u) { return exp(u * Y) * bess(order, u * X); };
    double f1 = f(1.0);
    // [0,2]: pole-subtracted (the PV of 1/(u-1) over [0,2] is zero)
    double core = 0.0;
    for (int i = 0; i < 32; i++) {
        for (int sgn = -1; sgn <= 1; sgn += 2) {
            double x = sgn * gauss_x64[i];           // node in [-1,1]
            double u = 1.0 + x;                      // map to [0,2]
            double g;
            if (fabs(x) < 1e-8) {
                // limit (f(u)-f(1))/(u-1) -> f'(1)
                double h = 1e-5;
                g = (f(1.0 + h) - f(1.0 - h)) / (2 * h);
            } else {
                g = (f(u) - f1) / (u - 1.0);
            }
            core += gauss_w64[i] * g;
        }
    }
    // tail [2, inf)
    double tail = 0.0;
    if (X < 1e-9) {
        // J0 -> 1 (order 0) or J1 -> 0 (order 1)
        if (order == 0) {
            if (Y < -1e-12) tail = exp(Y) * expint_e1(-Y);
            else tail = 0.0;                          // X=0,Y=0 excluded
        }
    } else {
        // integrate between Bessel zeros (approx period pi/X), 16-pt GL per
        // segment, stop when negligible
        init_gauss64();
        double u0 = 2.0;
        double du = PI / X;
        double prev = 1e30;
        for (int seg = 0; seg < 4000; seg++) {
            double u1 = u0 + du;
            double s = 0.0;
            for (int i = 0; i < 32; i++) {
                for (int sgn = -1; sgn <= 1; sgn += 2) {
                    double x = sgn * gauss_x64[i];
                    double u = 0.5 * (u0 + u1) + 0.5 * (u1 - u0) * x;
                    s += gauss_w64[i] * f(u) / (u - 1.0);
                }
            }
            s *= 0.5 * (u1 - u0);
            // alternating-series averaging for the oscillatory part
            tail += s;
            if (fabs(s) < 1e-13 && fabs(prev) < 1e-13) break;
            if (u0 * (-Y) > 35.0) break;              // exponential cutoff
            prev = s;
            u0 = u1;
        }
    }
    return core + tail;
}

static const char* table_cache_path() {
    static char path[4096] = {0};
    if (!path[0]) {
        const char* home = getenv("HOME");
        snprintf(path, sizeof(path), "%s/.cache/raft_tpu/wavetable_v1.bin",
                 home ? home : "/tmp");
    }
    return path;
}

void WaveTable::build() {
    if (built) return;
    I0.assign((size_t)NX * NS, 0.0);
    I1.assign((size_t)NX * NS, 0.0);
    // disk cache: the table is design-independent, build once per machine
    FILE* f = fopen(table_cache_path(), "rb");
    if (f) {
        int hdr[2] = {0, 0};
        bool ok = fread(hdr, sizeof(int), 2, f) == 2 && hdr[0] == NX && hdr[1] == NS;
        ok = ok && fread(I0.data(), sizeof(double), I0.size(), f) == I0.size();
        ok = ok && fread(I1.data(), sizeof(double), I1.size(), f) == I1.size();
        fclose(f);
        if (ok) { built = true; return; }
    }
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic)
#endif
    for (int ix = 0; ix < NX; ix++) {
        double X = XMAX * ix / (NX - 1);
        for (int is = 0; is < NS; is++) {
            double s = SMAX * is / (NS - 1);
            double Y = 1.0 - exp(s);                 // 0 .. -60
            if (ix == 0 && is == 0) Y = -1e-6;       // avoid the X=Y=0 corner
            double a0, a1;
            analytic_I(X, Y, &a0, &a1);
            I0[(size_t)ix * NS + is] = a0 - sing_I0(X, Y);
            I1[(size_t)ix * NS + is] = a1 - sing_I1(X, Y);
        }
    }
    {
        char dir[4096];
        snprintf(dir, sizeof(dir), "%s", table_cache_path());
        char* slash = strrchr(dir, '/');
        if (slash) { *slash = 0; char cmd[4200]; snprintf(cmd, sizeof(cmd), "mkdir -p '%s'", dir); int rc = system(cmd); (void)rc; }
        FILE* f = fopen(table_cache_path(), "wb");
        if (f) {
            int hdr[2] = {NX, NS};
            fwrite(hdr, sizeof(int), 2, f);
            fwrite(I0.data(), sizeof(double), I0.size(), f);
            fwrite(I1.data(), sizeof(double), I1.size(), f);
            fclose(f);
        }
    }
    built = true;
}

void WaveTable::eval(double X, double Y, double* i0, double* i1) const {
    // near the origin the smooth parts still carry directional (X/Y-angle)
    // structure the first bilinear cells cannot represent (errors up to
    // ~0.3 absolute at rho ~ 0.02, which bias every distant panel pair at
    // low frequency); evaluate exactly there instead.  Only nu*R, nu*|z+z'|
    // both small lands here, so the extra quadrature cost is confined to
    // the cheap low-frequency end of the sweep.
    double rho0 = sqrt(X * X + Y * Y);
    if (rho0 < 0.25 && rho0 > 1e-13) {
        analytic_I(X, Y, i0, i1);
        return;
    }
    // beyond XMAX use the far-field asymptotics; beyond Y range the
    // integrand is dead (e^{uY} kills everything except the 1/r1-type part)
    if (X >= XMAX - 1e-9) {
        // I0 -> -pi e^Y Y0(X), I1 -> -pi e^Y Y1(X) (pole-dominated far field)
        *i0 = -PI * exp(Y) * y0(X);
        *i1 = -PI * exp(Y) * y1(X);
        return;
    }
    double s = log(1.0 - Y);
    if (s >= SMAX - 1e-12) {
        // very deep: leading term of the 1/k expansion
        double rr = sqrt(X * X + Y * Y);
        *i0 = -1.0 / rr;
        *i1 = X > 1e-9 ? -(1.0 / X) * (1.0 - (-Y) / rr) : 0.0;
        return;
    }
    double fx = X / (XMAX / (NX - 1));
    int ix = (int)fx; double tx = fx - ix;
    double fs = s / (SMAX / (NS - 1));
    int is = (int)fs; double ts = fs - is;
    if (ix >= NX - 1) { ix = NX - 2; tx = 1.0; }
    if (is >= NS - 1) { is = NS - 2; ts = 1.0; }
    auto lerp = [&](const std::vector<double>& T) {
        double a = T[(size_t)ix * NS + is], b = T[(size_t)(ix + 1) * NS + is];
        double c = T[(size_t)ix * NS + is + 1], d = T[(size_t)(ix + 1) * NS + is + 1];
        return (1 - tx) * ((1 - ts) * a + ts * c) + tx * ((1 - ts) * b + ts * d);
    };
    *i0 = lerp(I0) + sing_I0(X, Y);
    *i1 = lerp(I1) + sing_I1(X, Y);
}

static WaveTable g_table;

// -------------------------------------------------------- finite depth
//
// Finite-depth free-surface Green function (e^{i w t}, depth h):
//   G = 1/r + 1/r2 + Gw,   r2 = seabed image of Q (vertical z+zeta+2h),
//   Gw = 2 PV Int_0^inf F(mu) sum_i e^{-mu d_i} J0(mu R) dmu
//        - 2 pi i A0 sum_i e^{-k0 d_i} J0(k0 R),
//   F(mu) = (mu+nu) / (2[(mu-nu) - (mu+nu) e^{-2 mu h}]),   nu = w^2/g,
//   d1 = -(z+zeta), d2 = 2h-(z-zeta), d3 = 2h+(z-zeta), d4 = 4h+(z+zeta),
//   k0: positive root of k tanh(kh) = nu,  A0 = Res_{mu=k0} F.
// (Derived by expanding cosh mu(z+h) cosh mu(zeta+h) into four
// exponentials in Wehausen & Laitone eq. 13.19; cross-validated to 8
// digits against John's eigenfunction series and, in the kh -> inf limit,
// against the deep-water form above.)
//
// Evaluation strategy (Delhommeau-style, per frequency):
//   2F(mu) = 1 + 2A0/(mu-k0) + rho(mu),  rho smooth and decaying ->
//   per image i:  "1"    -> 1/sqrt(R^2+d_i^2)        (closed form)
//                 pole   -> 2 A0 I0(k0 R, -k0 d_i)   (deep-water PV table)
//                 rho    -> sum_j a_j/sqrt(R^2+(d_i+lam_j)^2)
// with rho(mu) ~= sum_j a_j e^{-lam_j mu} least-squares fit on a fixed
// geometric lambda grid (46 terms; fit residual ~1e-6, overall Green
// function error vs the eigenfunction series ~1e-4 relative for k0h <= 6).
// For k0 h > 10 the finite-depth corrections are O(e^{-2 k0 h}) < 1e-8 and
// the deep-water path is used instead.
//
// The i=1 "1" term is exactly the free-surface image 1/r1, which the
// assembly integrates over the panel (Rankine) rather than at centroids;
// eval() therefore EXCLUDES it, and includes 1/r2 (smooth for floating
// bodies: vertical distance >= 2(h - draft)).

struct FDGreen {
    double h = 0, nu = 0, k0 = 0, A0 = 0;
    bool active = false;
    static constexpr int NL = 46;
    double lam[NL], a[NL];

    static double dispersion(double nu, double h) {
        double k = nu * h < 1.0 ? sqrt(nu / h) : nu;
        for (int it = 0; it < 100; it++) {
            double t = tanh(k * h);
            double c = cosh(k * h);
            double f = k * t - nu;
            double df = t + k * h / (c * c);
            double dk = f / df;
            k -= dk;
            if (fabs(dk) < 1e-15 * (k + 1e-300)) break;
        }
        return k;
    }

    void setup(double nu_, double h_) {
        nu = nu_; h = h_;
        active = false;
        if (h <= 0 || nu <= 0) return;
        k0 = dispersion(nu, h);
        if (k0 * h >= 10.0) return;                   // deep water regime
        active = true;
        double e2 = exp(-2.0 * k0 * h);
        A0 = (k0 + nu) / (2.0 * (1.0 - e2 + 2.0 * h * (k0 + nu) * e2));
        // sample rho(mu) = 2F(mu) - 1 - 2A0/(mu-k0) on [0, mumax]
        const int NS = 1200;
        double mumax = 20.0 * (k0 > 1.0 / h ? k0 : 1.0 / h);
        std::vector<double> mu(NS), y(NS);
        for (int i = 0; i < NS; i++) {
            double t = (double)i / (NS - 1);
            double m = mumax * t * t;                 // denser near 0
            double ref = k0 > 1.0 ? k0 : 1.0;
            if (fabs(m - k0) < 1e-9 * ref) m += 1e-6 * ref;
            mu[i] = m;
            double F = (m + nu) /
                       (2.0 * ((m - nu) - (m + nu) * exp(-2.0 * m * h)));
            y[i] = 2.0 * F - 1.0 - 2.0 * A0 / (m - k0);
        }
        // geometric lambda grid spanning the decay scales of rho
        double lmin = (h < 1.0 / k0 ? h : 1.0 / k0) / 50.0;
        double lmax = 50.0 / (mumax / 20.0);
        for (int j = 0; j < NL; j++)
            lam[j] = lmin * pow(lmax / lmin, (double)j / (NL - 1));
        // least squares via scaled normal equations + tiny ridge
        std::vector<double> B((size_t)NS * NL);
        double coln[NL];
        for (int j = 0; j < NL; j++) {
            double s2 = 0.0;
            for (int i = 0; i < NS; i++) {
                double v = exp(-mu[i] * lam[j]);
                B[(size_t)i * NL + j] = v;
                s2 += v * v;
            }
            coln[j] = sqrt(s2);
        }
        double M[NL][NL], rhs[NL];
        for (int j = 0; j < NL; j++) {
            rhs[j] = 0.0;
            for (int i = 0; i < NS; i++)
                rhs[j] += B[(size_t)i * NL + j] / coln[j] * y[i];
            for (int l = 0; l < NL; l++) {
                double s = 0.0;
                for (int i = 0; i < NS; i++)
                    s += B[(size_t)i * NL + j] * B[(size_t)i * NL + l];
                M[j][l] = s / (coln[j] * coln[l]);
            }
            M[j][j] += 1e-10;
        }
        // Gaussian elimination with partial pivoting (NL x NL)
        int piv[NL];
        for (int j = 0; j < NL; j++) piv[j] = j;
        for (int c = 0; c < NL; c++) {
            int p = c; double best = fabs(M[c][c]);
            for (int i = c + 1; i < NL; i++)
                if (fabs(M[i][c]) > best) { best = fabs(M[i][c]); p = i; }
            if (p != c) {
                for (int j = 0; j < NL; j++) std::swap(M[c][j], M[p][j]);
                std::swap(rhs[c], rhs[p]);
            }
            for (int i = c + 1; i < NL; i++) {
                double f = M[i][c] / M[c][c];
                for (int j = c; j < NL; j++) M[i][j] -= f * M[c][j];
                rhs[i] -= f * rhs[c];
            }
        }
        for (int i = NL - 1; i >= 0; i--) {
            double s = rhs[i];
            for (int j = i + 1; j < NL; j++) s -= M[i][j] * a[j];
            a[i] = s / M[i][i];
        }
        for (int j = 0; j < NL; j++) a[j] /= coln[j];
    }

    // Wave part at field point P=(R horizontal, zP) vs source zQ,
    // EXCLUDING 1/r and the free-surface image 1/r1, INCLUDING the seabed
    // image 1/r2.  Returns G and its derivatives w.r.t. R and zP.
    void eval(double R, double zP, double zQ,
              cdouble* G, cdouble* dG_dR, cdouble* dG_dz) const {
        double d[4] = { -(zP + zQ), 2.0 * h - (zP - zQ),
                        2.0 * h + (zP - zQ), 4.0 * h + (zP + zQ) };
        static const double sgn[4] = { -1.0, -1.0, 1.0, 1.0 };
        double gre = 0.0, gre_R = 0.0, gre_z = 0.0;
        double gim = 0.0, gim_R = 0.0, gim_z = 0.0;
        double X = k0 * R;
        double J0 = j0(X), J1 = j1(X);
        for (int i = 0; i < 4; i++) {
            double di = d[i], si = sgn[i];
            // "1" part (skip i=0: that is 1/r1, Rankine-integrated outside)
            if (i > 0) {
                double rr2 = R * R + di * di;
                double rr = sqrt(rr2);
                double t3 = 1.0 / (rr2 * rr);
                gre += 1.0 / rr;
                gre_R += -R * t3;
                gre_z += -di * t3 * si;
            }
            // pole part: 2 A0 I0(k0 R, -k0 d_i)
            {
                double Y = -k0 * di;
                double i0, i1;
                g_table.eval(X, Y, &i0, &i1);
                double rxy = sqrt(X * X + Y * Y);
                if (rxy < 1e-12) rxy = 1e-12;
                double C1 = X > 1e-12 ? (1.0 / X) * (1.0 - (-Y) / rxy) : 0.0;
                gre += 2.0 * A0 * i0;
                gre_R += 2.0 * A0 * k0 * (-(C1 + i1));
                gre_z += 2.0 * A0 * (-k0 * si) * (1.0 / rxy + i0);
            }
            // exp-fit part
            for (int j = 0; j < NL; j++) {
                double c = di + lam[j];
                double rr2 = R * R + c * c;
                double rr = sqrt(rr2);
                double t3 = a[j] / (rr2 * rr);
                gre += a[j] / rr;
                gre_R += -R * t3;
                gre_z += -c * t3 * si;
            }
            // imaginary (radiated-wave) part
            double e = exp(-k0 * di);
            gim += -2.0 * PI * A0 * e * J0;
            gim_R += 2.0 * PI * A0 * k0 * e * J1;
            gim_z += 2.0 * PI * A0 * k0 * si * e * J0;
        }
        // seabed image 1/r2 (vertical zP + zQ + 2h; d(v2)/dzP = +1)
        {
            double v2 = zP + zQ + 2.0 * h;
            double rr2 = R * R + v2 * v2;
            double rr = sqrt(rr2);
            double t3 = 1.0 / (rr2 * rr);
            gre += 1.0 / rr;
            gre_R += -R * t3;
            gre_z += -v2 * t3;
        }
        *G = cdouble(gre, gim);
        *dG_dR = cdouble(gre_R, gim_R);
        *dG_dz = cdouble(gre_z, gim_z);
    }
};

// ------------------------------------------------------------- geometry

struct Panel {
    double v[4][3];
    double c[3];        // centroid
    double n[3];        // unit normal (outward from body, into fluid)
    double area;
    double diag;
};

static void panel_setup(Panel& p) {
    double d1[3], d2[3];
    for (int i = 0; i < 3; i++) {
        d1[i] = p.v[2][i] - p.v[0][i];
        d2[i] = p.v[3][i] - p.v[1][i];
        p.c[i] = 0.25 * (p.v[0][i] + p.v[1][i] + p.v[2][i] + p.v[3][i]);
    }
    double nx = 0.5 * (d1[1] * d2[2] - d1[2] * d2[1]);
    double ny = 0.5 * (d1[2] * d2[0] - d1[0] * d2[2]);
    double nz = 0.5 * (d1[0] * d2[1] - d1[1] * d2[0]);
    p.area = sqrt(nx * nx + ny * ny + nz * nz);
    double inv = p.area > 1e-14 ? 1.0 / p.area : 0.0;
    p.n[0] = nx * inv; p.n[1] = ny * inv; p.n[2] = nz * inv;
    double l1 = sqrt(d1[0]*d1[0] + d1[1]*d1[1] + d1[2]*d1[2]);
    double l2 = sqrt(d2[0]*d2[0] + d2[1]*d2[1] + d2[2]*d2[2]);
    p.diag = l1 > l2 ? l1 : l2;
}

// exact Int 1/r dS over the flat polygon, field point at its centroid
// (in-plane): sum over edges of d*ln((ra+rb+s)/(ra+rb-s))
static double self_rankine_potential(const Panel& p) {
    double tot = 0.0;
    for (int e = 0; e < 4; e++) {
        const double* a = p.v[e];
        const double* b = p.v[(e + 1) % 4];
        double ab[3] = {b[0]-a[0], b[1]-a[1], b[2]-a[2]};
        double s = sqrt(ab[0]*ab[0] + ab[1]*ab[1] + ab[2]*ab[2]);
        if (s < 1e-12) continue;                      // degenerate (triangle)
        double ca[3] = {a[0]-p.c[0], a[1]-p.c[1], a[2]-p.c[2]};
        double cb[3] = {b[0]-p.c[0], b[1]-p.c[1], b[2]-p.c[2]};
        double ra = sqrt(ca[0]*ca[0] + ca[1]*ca[1] + ca[2]*ca[2]);
        double rb = sqrt(cb[0]*cb[0] + cb[1]*cb[1] + cb[2]*cb[2]);
        // signed perpendicular distance from centroid to edge (in plane):
        // d = |(a-c) x (b-a)| / s  with sign via normal -- area convention
        double cr[3] = {ca[1]*ab[2]-ca[2]*ab[1], ca[2]*ab[0]-ca[0]*ab[2], ca[0]*ab[1]-ca[1]*ab[0]};
        double dsign = cr[0]*p.n[0] + cr[1]*p.n[1] + cr[2]*p.n[2];
        double d = dsign / s;
        double num = ra + rb + s, den = ra + rb - s;
        if (den < 1e-14) den = 1e-14;
        tot += d * log(num / den);
    }
    return fabs(tot);
}

// Rankine 1/r potential+gradient of panel q integrated at point P, with
// ns x ns Gauss subdivision (bilinear quad map)
static void rankine_integral(const Panel& q, const double* P, int ns,
                             double* pot, double grad[3]) {
    *pot = 0.0; grad[0] = grad[1] = grad[2] = 0.0;
    for (int iu = 0; iu < ns; iu++) {
        for (int iv = 0; iv < ns; iv++) {
            double u = (iu + 0.5) / ns, v = (iv + 0.5) / ns;
            // bilinear interior point and Jacobian-weighted area element
            double pt[3];
            for (int d = 0; d < 3; d++) {
                pt[d] = (1-u)*(1-v)*q.v[0][d] + u*(1-v)*q.v[1][d]
                      + u*v*q.v[2][d] + (1-u)*v*q.v[3][d];
            }
            double dA = q.area / (ns * ns);          // flat-panel approx
            double dx = P[0]-pt[0], dy = P[1]-pt[1], dz = P[2]-pt[2];
            double r2 = dx*dx + dy*dy + dz*dz;
            double r = sqrt(r2);
            if (r < 1e-12) continue;
            double ir = 1.0 / r, ir3 = ir / r2;
            *pot += dA * ir;
            grad[0] -= dA * dx * ir3;                // d(1/r)/dPx = -dx/r^3
            grad[1] -= dA * dy * ir3;
            grad[2] -= dA * dz * ir3;
        }
    }
}

// --------------------------------------------------------------- solver

struct Influence {
    // S phi and D normal-derivative matrices (complex)
    std::vector<cdouble> S, D;
};

static void wave_part(double k, const double* P, const double* Q,
                      cdouble* G, cdouble gradP[3]) {
    // image of Q above the surface enters via v = z_P + z_Q
    double dx = P[0]-Q[0], dy = P[1]-Q[1];
    double R = sqrt(dx*dx + dy*dy);
    double v = P[2] + Q[2];                           // <= 0
    double X = k * R, Y = k * v;
    double i0, i1;
    g_table.eval(X, Y, &i0, &i1);
    double eY = exp(Y);
    double J0 = j0(X), J1v = j1(X);
    *G = 2.0 * k * cdouble(i0, -PI * eY * J0);
    // d/dv = 2k [ k/sqrt(R^2+v^2)_dim... ]: dI0/dv = k(1/sqrt(X^2+Y^2)) ...
    double rr = sqrt(R*R + v*v);
    if (rr < 1e-12) rr = 1e-12;
    double dI0_dv = 1.0 / rr + k * i0;                // identity: no new integral
    double dIm_dv = -PI * k * eY * J0;                // d(e^Y J0)/dv * -pi ... times k
    cdouble dG_dv = 2.0 * k * cdouble(dI0_dv, dIm_dv);
    // d/dR: dI0/dR = -k [ C1 + I1 ],  C1 = (1/X)(1 - (-Y)/sqrt(X^2+Y^2))
    double C1 = 0.0;
    if (R > 1e-12) C1 = (1.0 / R) * (1.0 - (-v) / rr);
    double dI0_dR = -(C1 + k * i1);
    double dIm_dR = PI * k * eY * J1v;                // d(-pi e^Y J0(kR))/dR
    cdouble dG_dR = 2.0 * k * cdouble(dI0_dR, dIm_dR);
    double ux = R > 1e-12 ? dx / R : 0.0;
    double uy = R > 1e-12 ? dy / R : 0.0;
    gradP[0] = dG_dR * ux;
    gradP[1] = dG_dR * uy;
    gradP[2] = dG_dv;
}

static void assemble(const std::vector<Panel>& pan, double k,
                     const FDGreen* fd, Influence& inf) {
    int n = (int)pan.size();
    inf.S.assign((size_t)n * n, 0.0);
    inf.D.assign((size_t)n * n, 0.0);
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic)
#endif
    for (int i = 0; i < n; i++) {
        const double* P = pan[i].c;
        for (int j = 0; j < n; j++) {
            const Panel& q = pan[j];
            double dx = P[0]-q.c[0], dy = P[1]-q.c[1], dz = P[2]-q.c[2];
            double dist = sqrt(dx*dx + dy*dy + dz*dz);
            double pot = 0.0, grad[3] = {0, 0, 0};
            if (i == j) {
                pot = self_rankine_potential(q);
                // PV of flat-panel 1/r normal derivative at centroid = 0
            } else {
                double rel = dist / q.diag;
                int ns = rel < 1.0 ? 12 : rel < 2.0 ? 6 : rel < 6.0 ? 3 : 1;
                rankine_integral(q, P, ns, &pot, grad);
            }
            // image (1/r1): field point vs image panel (z -> -z of Q).
            // panels at the waterline nearly coincide with their own image,
            // so the subdivision must go much finer than for body pairs
            double potI, gradI[3] = {0, 0, 0};
            Panel qi = q;
            for (int vv = 0; vv < 4; vv++) qi.v[vv][2] = -q.v[vv][2];
            qi.c[2] = -q.c[2];
            {
                double dzI = P[2] - qi.c[2];
                double distI = sqrt(dx*dx + dy*dy + dzI*dzI);
                if (i == j && distI < 1e-9 * q.diag) {
                    // lid panel AT z=0: the image coincides with the panel
                    // itself -- exact self potential, PV gradient 0
                    potI = self_rankine_potential(qi);
                } else {
                    double rel = distI / q.diag;
                    int ns = rel < 0.5 ? 24 : rel < 1.0 ? 12 : rel < 2.0 ? 6
                           : rel < 6.0 ? 3 : 1;
                    rankine_integral(qi, P, ns, &potI, gradI);
                }
            }
            // wave part at centroids (smooth); finite depth adds the
            // seabed image and evanescent-mode corrections.  A lid panel's
            // self term sits exactly at the R=0, z=z'=0 log singularity of
            // the wave kernel: use the panel's log-average radius as the
            // effective evaluation point (panel-mean of the ln term)
            cdouble Gw, gw[3];
            double R_eff = sqrt(dx * dx + dy * dy);
            if (i == j && R_eff < 1e-12 && fabs(P[2]) < 1e-9 * q.diag) {
                R_eff = 0.4 * sqrt(q.area);
            }
            if (fd && fd->active) {
                double R = R_eff;
                cdouble G, dGdR, dGdz;
                fd->eval(R, P[2], q.c[2], &G, &dGdR, &dGdz);
                double ux = R > 1e-12 ? dx / R : 0.0;
                double uy = R > 1e-12 ? dy / R : 0.0;
                Gw = G;
                gw[0] = dGdR * ux;
                gw[1] = dGdR * uy;
                gw[2] = dGdz;
            } else {
                double Pe[3] = { P[0], P[1], P[2] };
                if (i == j && R_eff > 0 && sqrt(dx * dx + dy * dy) < 1e-12) {
                    Pe[0] = q.c[0] + R_eff;   // lid self: log-average offset
                }
                wave_part(k, Pe, q.c, &Gw, gw);
            }
            cdouble S = pot + potI + Gw * q.area;
            cdouble Dn = (grad[0] + gradI[0] + gw[0] * q.area) * pan[i].n[0]
                       + (grad[1] + gradI[1] + gw[1] * q.area) * pan[i].n[1]
                       + (grad[2] + gradI[2] + gw[2] * q.area) * pan[i].n[2];
            // fold the Gauss-subdivided gradients' area in: rankine_integral
            // already integrates dS, wave part multiplies area explicitly
            inf.S[(size_t)i * n + j] = S;
            inf.D[(size_t)i * n + j] = Dn;
        }
    }
}

// complex LU with partial pivoting, in place; b: n x m RHS
static int lu_solve(std::vector<cdouble>& A, std::vector<cdouble>& B, int n, int m) {
    std::vector<int> piv(n);
    for (int kcol = 0; kcol < n; kcol++) {
        int p = kcol; double best = std::abs(A[(size_t)kcol * n + kcol]);
        for (int i = kcol + 1; i < n; i++) {
            double v = std::abs(A[(size_t)i * n + kcol]);
            if (v > best) { best = v; p = i; }
        }
        if (best < 1e-30) return -1;
        if (p != kcol) {
            for (int j = 0; j < n; j++) std::swap(A[(size_t)kcol*n+j], A[(size_t)p*n+j]);
            for (int j = 0; j < m; j++) std::swap(B[(size_t)kcol*m+j], B[(size_t)p*m+j]);
        }
        cdouble inv = 1.0 / A[(size_t)kcol * n + kcol];
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
        for (int i = kcol + 1; i < n; i++) {
            cdouble f = A[(size_t)i * n + kcol] * inv;
            A[(size_t)i * n + kcol] = f;
            for (int j = kcol + 1; j < n; j++)
                A[(size_t)i * n + j] -= f * A[(size_t)kcol * n + j];
            for (int j = 0; j < m; j++)
                B[(size_t)i * m + j] -= f * B[(size_t)kcol * m + j];
        }
    }
    // back substitution
    for (int i = n - 1; i >= 0; i--) {
        for (int j = 0; j < m; j++) {
            cdouble s = B[(size_t)i * m + j];
            for (int kk = i + 1; kk < n; kk++)
                s -= A[(size_t)i * n + kk] * B[(size_t)kk * m + j];
            B[(size_t)i * m + j] = s / A[(size_t)i * n + i];
        }
    }
    return 0;
}

extern "C" {

// panels: np x 4 x 3 (row-major); w: nw angular frequencies; depth <= 0
// means infinite depth (deep water).  Outputs (row-major): A, Bo:
// nw x 6 x 6; Fre, Fim: nw x 6.  Returns 0 on success.
static int solve_core(const double* panels, int np,
                      const double* w, int nw, double depth,
                      double rho, double g,
                      const double* betas, int nb,
                      double* A, double* Bo, double* Fre, double* Fim,
                      double* Fhre, double* Fhim,
                      int nthreads, int nlid) {
    // nlid > 0: the LAST nlid panels are an interior waterplane lid.  The
    // extended boundary integral equation forces the interior extension of
    // the potential to vanish on the lid (sigma rows: S sigma = phi target,
    // no jump term for the continuous single layer), which removes the
    // irregular frequencies of the plain source formulation -- the
    // capability behind the reference's HAMS `irr` flag
    // (hams/pyhams.py:200,284), which its missing Fortran binary never
    // actually exercised.
#ifdef _OPENMP
    if (nthreads > 0) omp_set_num_threads(nthreads);
#endif
    g_table.build();
    std::vector<Panel> pan(np);
    for (int i = 0; i < np; i++) {
        for (int vv = 0; vv < 4; vv++)
            for (int d = 0; d < 3; d++)
                pan[i].v[vv][d] = panels[((size_t)i * 4 + vv) * 3 + d];
        panel_setup(pan[i]);
    }
    int n = np;
    int nh = np - nlid;                           // hull panels (wetted)
    for (int iw = 0; iw < nw; iw++) {
        double om = w[iw];
        double k = om * om / g;                       // nu (deep wavenumber)
        FDGreen fd;
        fd.setup(k, depth);
        // incident wave number and stable depth-profile factors:
        //   Zr = cosh(kw(z+h))/cosh(kw h),  Zs = sinh(kw(z+h))/cosh(kw h)
        double kw = fd.active ? fd.k0 : k;
        auto Zr = [&](double z) {
            if (!fd.active) return exp(kw * z);
            double e = exp(-2.0 * kw * (z + depth));
            return exp(kw * z) * (1.0 + e) / (1.0 + exp(-2.0 * kw * depth));
        };
        auto Zs = [&](double z) {
            if (!fd.active) return exp(kw * z);
            double e = exp(-2.0 * kw * (z + depth));
            return exp(kw * z) * (1.0 - e) / (1.0 + exp(-2.0 * kw * depth));
        };
        Influence inf;
        assemble(pan, k, fd.active ? &fd : nullptr, inf);
        // system: (-2 pi I + D) sigma = rhs, 6 + nb RHS (6 radiation + one
        // diffraction column per heading -- the LU is factored once and
        // every extra heading is just another back-substitution)
        // -- exterior limit with the collocation normal pointing INTO the
        // fluid gives the jump  d(phi)/dn -> -2 pi sigma + PV D sigma
        // (verified against the sphere single-layer harmonics: S Y_n =
        // 4 pi a/(2n+1) Y_n, D Y_n = -2 pi/(2n+1) Y_n).
        std::vector<cdouble> M = inf.D;
        for (int i = 0; i < n; i++) M[(size_t)i * n + i] += -2.0 * PI;
        // lid rows: Dirichlet condition on the interior free surface
        for (int i = nh; i < n; i++)
            for (int j = 0; j < n; j++)
                M[(size_t)i * n + j] = inf.S[(size_t)i * n + j];
        int m = 6 + nb;
        std::vector<cdouble> rhs((size_t)n * m);
        std::vector<cdouble> dphiI_dn((size_t)n * nb);   // saved for Haskind
        for (int i = 0; i < n; i++) {
            const Panel& p = pan[i];
            double rx = p.c[0], ry = p.c[1], rz = p.c[2];
            double nvec[6] = {
                p.n[0], p.n[1], p.n[2],
                ry * p.n[2] - rz * p.n[1],
                rz * p.n[0] - rx * p.n[2],
                rx * p.n[1] - ry * p.n[0],
            };
            bool lid = i >= nh;
            for (int kk = 0; kk < 6; kk++)
                rhs[(size_t)i * m + kk] = lid ? 0.0 : nvec[kk];
            for (int ib = 0; ib < nb; ib++) {
                double cb = cos(betas[ib]), sb = sin(betas[ib]);
                // incident wave (unit amplitude, e^{iwt}):
                //   phi_I = (g/om) i Zr(z) e^{-i kw (x cos b + y sin b)}
                // deep water: Zr = Zs = e^{kw z}; finite depth: cosh/sinh
                // profile over the water column (kw = k0)
                cdouble phase = std::exp(cdouble(0.0, -kw * (rx * cb + ry * sb)));
                cdouble ph = cdouble(0.0, g / om) * Zr(rz) * phase;
                // grad phi_I
                cdouble ddx = ph * cdouble(0.0, -kw * cb);
                cdouble ddy = ph * cdouble(0.0, -kw * sb);
                cdouble ddz = cdouble(0.0, g / om) * kw * Zs(rz) * phase;
                cdouble dn = ddx * p.n[0] + ddy * p.n[1] + ddz * p.n[2];
                dphiI_dn[(size_t)i * nb + ib] = dn;
                // hull: Neumann  dphi_S/dn = -dphi_I/dn
                // lid:  Dirichlet phi_S = -phi_I  (zero interior total)
                rhs[(size_t)i * m + 6 + ib] = lid ? -ph : -dn;
            }
        }
        if (lu_solve(M, rhs, n, m) != 0) return -1;
        // panel potentials phi = S sigma for ALL columns at once (one n^2 m
        // pass instead of re-accumulating per coefficient pair)
        std::vector<cdouble> phi((size_t)n * m, cdouble(0.0, 0.0));
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
        for (int i = 0; i < n; i++)
            for (int q = 0; q < n; q++) {
                cdouble s = inf.S[(size_t)i * n + q];
                for (int kk = 0; kk < m; kk++)
                    phi[(size_t)i * m + kk] += s * rhs[(size_t)q * m + kk];
            }
        // radiation coefficients: A - i B/om = rho Int phi_k n_j dS
        for (int kk = 0; kk < 6; kk++) {
            for (int j = 0; j < 6; j++) {
                cdouble acc = 0.0;
                for (int i = 0; i < nh; i++) {    // wetted hull only
                    const Panel& p = pan[i];
                    double nvec[6] = {
                        p.n[0], p.n[1], p.n[2],
                        p.c[1] * p.n[2] - p.c[2] * p.n[1],
                        p.c[2] * p.n[0] - p.c[0] * p.n[2],
                        p.c[0] * p.n[1] - p.c[1] * p.n[0],
                    };
                    acc += phi[(size_t)i * m + kk] * nvec[j] * p.area;
                }
                // from -i w A - B = i w rho Int phi n dS (unit velocity):
                //   A = -rho Re I,  B = +w rho Im I
                cdouble val = rho * acc;
                A[((size_t)iw * 6 + j) * 6 + kk] = -val.real();
                Bo[((size_t)iw * 6 + j) * 6 + kk] = val.imag() * om;
            }
        }
        // excitation per heading:
        //   direct:  X_j = i om rho Int (phi_I + phi_S) n_j dS
        //   Haskind: X_j = i om rho Int (phi_I n_j - phi_j dphi_I/dn) dS
        // (Green's identity on the radiation/scattering pair turns
        //  Int phi_S n_j dS into -Int phi_j dphi_I/dn dS; agreement of the
        //  two is a solver self-consistency check in amplitude AND phase)
        for (int ib = 0; ib < nb; ib++) {
            double cb = cos(betas[ib]), sb = sin(betas[ib]);
            for (int j = 0; j < 6; j++) {
                cdouble acc = 0.0, acch = 0.0;
                for (int i = 0; i < nh; i++) {    // wetted hull only
                    const Panel& p = pan[i];
                    cdouble phiS = phi[(size_t)i * m + 6 + ib];
                    cdouble phiI = cdouble(0.0, g / om) * Zr(p.c[2])
                                 * std::exp(cdouble(0.0, -kw * (p.c[0] * cb + p.c[1] * sb)));
                    double nvec[6] = {
                        p.n[0], p.n[1], p.n[2],
                        p.c[1] * p.n[2] - p.c[2] * p.n[1],
                        p.c[2] * p.n[0] - p.c[0] * p.n[2],
                        p.c[0] * p.n[1] - p.c[1] * p.n[0],
                    };
                    acc += (phiI + phiS) * nvec[j] * p.area;
                    acch += (phiI * nvec[j]
                             - phi[(size_t)i * m + j] * dphiI_dn[(size_t)i * nb + ib])
                            * p.area;
                }
                cdouble X = cdouble(0.0, om) * rho * acc;
                Fre[((size_t)iw * nb + ib) * 6 + j] = X.real();
                Fim[((size_t)iw * nb + ib) * 6 + j] = X.imag();
                if (Fhre && Fhim) {
                    cdouble Xh = cdouble(0.0, om) * rho * acch;
                    Fhre[((size_t)iw * nb + ib) * 6 + j] = Xh.real();
                    Fhim[((size_t)iw * nb + ib) * 6 + j] = Xh.imag();
                }
            }
        }
    }
    return 0;
}

int bem_solve_mh(const double* panels, int np,
                 const double* w, int nw, double depth,
                 double rho, double g,
                 const double* betas, int nb,
                 double* A, double* Bo, double* Fre, double* Fim,
                 double* Fhre, double* Fhim,
                 int nthreads, int nlid) {
    return solve_core(panels, np, w, nw, depth, rho, g, betas, nb,
                      A, Bo, Fre, Fim, Fhre, Fhim, nthreads, nlid);
}

int bem_solve(const double* panels, int np,
              const double* w, int nw, double depth,
              double rho, double g, double beta,
              double* A, double* Bo, double* Fre, double* Fim,
              int nthreads) {
    return solve_core(panels, np, w, nw, depth, rho, g, &beta, 1,
                      A, Bo, Fre, Fim, nullptr, nullptr, nthreads, 0);
}

// backward-compatible deep-water entry
int bem_solve_deep(const double* panels, int np,
                   const double* w, int nw,
                   double rho, double g, double beta,
                   double* A, double* Bo, double* Fre, double* Fim,
                   int nthreads) {
    return bem_solve(panels, np, w, nw, -1.0, rho, g, beta,
                     A, Bo, Fre, Fim, nthreads);
}

// finite-depth Green function probe for unit tests: returns the FULL
// G = 1/r + 1/r1 + (wave part incl. 1/r2) and its gradient w.r.t. the
// field point (dR, dz).  out = [Gre, Gim, dGdR_re, dGdR_im, dGdz_re,
// dGdz_im].  Falls back to the deep-water form when k0*depth >= 10.
void bem_green_fd(double nu, double depth, double R, double zP, double zQ,
                  double* out) {
    g_table.build();
    FDGreen fd;
    fd.setup(nu, depth);
    cdouble G, dGdR, dGdz;
    if (fd.active) {
        fd.eval(R, zP, zQ, &G, &dGdR, &dGdz);
        // add the direct and free-surface-image Rankine terms
        double dz_d = zP - zQ, dz_i = zP + zQ;
        double r2d = R * R + dz_d * dz_d, r2i = R * R + dz_i * dz_i;
        double rd = sqrt(r2d), ri = sqrt(r2i);
        G += 1.0 / rd + 1.0 / ri;
        dGdR += -R / (r2d * rd) - R / (r2i * ri);
        dGdz += -dz_d / (r2d * rd) - dz_i / (r2i * ri);
    } else {
        double P[3] = { R, 0.0, zP }, Q[3] = { 0.0, 0.0, zQ };
        cdouble gw[3];
        wave_part(nu, P, Q, &G, gw);
        dGdR = gw[0];
        dGdz = gw[2];
        double dz_d = zP - zQ, dz_i = zP + zQ;
        double r2d = R * R + dz_d * dz_d, r2i = R * R + dz_i * dz_i;
        double rd = sqrt(r2d), ri = sqrt(r2i);
        G += 1.0 / rd + 1.0 / ri;
        dGdR += -R / (r2d * rd) - R / (r2i * ri);
        dGdz += -dz_d / (r2d * rd) - dz_i / (r2i * ri);
    }
    out[0] = G.real(); out[1] = G.imag();
    out[2] = dGdR.real(); out[3] = dGdR.imag();
    out[4] = dGdz.real(); out[5] = dGdz.imag();
}

// dispersion probe: k0 with k0 tanh(k0 h) = nu
double bem_dispersion(double nu, double depth) {
    return FDGreen::dispersion(nu, depth);
}

// probe Phi(zeta) for unit tests
void bem_phi_probe(double re, double im, double* pre, double* pim) {
    cdouble p = phi_pv(cdouble(re, im));
    *pre = p.real();
    *pim = p.imag();
}

// quick probe of the wave-integral table for unit tests
void bem_wave_integral(double X, double Y, double* i0, double* i1) {
    g_table.build();
    g_table.eval(X, Y, i0, i1);
}

void bem_wave_integral_direct(double X, double Y, double* i0, double* i1) {
    *i0 = WaveTable::direct_I(X, Y, 0);
    *i1 = WaveTable::direct_I(X, Y, 1);
}

}  // extern "C"
