"""AOT executable registry: compile once per (program, signature, topology),
reuse across calls AND across processes.

The hot entry points (``parallel/sweep.py``'s batched/sharded forwards,
``parallel/optimize.py``'s value-and-grad step, ``bench.py``'s north-star
chunk solve) are each ONE large XLA program recompiled identically by every
process.  This registry keys the compiled executable by

* a **function tag** (stable call-site name, e.g. ``"sweep_sea_states"``),
* the **abstract signature** of the call arguments (pytree structure +
  shape/dtype of every leaf),
* a **consts fingerprint** — a content hash of every array the traced
  function closes over (member geometry, staged BEM coefficients, mooring
  stiffness, ...).  Closure constants are baked into the HLO, so two
  designs with identical shapes still need distinct executables; the call
  site passes everything its closure captures and the registry hashes it,
* the **device topology** (backend platform, device kind, device count,
  mesh axis names/shape when sharded) — an executable is loadable only on
  the topology it was built for,
* **version salts** (jax / jaxlib / raft_tpu versions) so an upgrade
  invalidates rather than deserializes garbage.

Storage layers, tried in order:

1. in-process memo (dict) — repeat calls in one process never re-lower;
2. on-disk serialized executable (``jax.experimental.serialize_executable``,
   the PJRT executable bytes) — a warm process skips BOTH tracing and XLA
   compilation.  Any deserialize failure (corrupt file, incompatible
   runtime) silently falls through to layer 3;
3. trace + compile — which itself hits JAX's persistent compilation cache
   (wired by :func:`raft_tpu.cache.config.enable`), so even when the
   executable artifact is unusable the warm process pays tracing only.

With the cache disabled the registry vanishes: :func:`cached_callable`
returns a plain ``jax.jit`` (today's exact dispatch path, bit-identical),
and :func:`cached_compile` performs a plain ``lower().compile()``.
"""
from __future__ import annotations

import hashlib
import inspect
import os
import tempfile
import threading
import time
from collections import Counter, deque

import numpy as np

from raft_tpu.cache import config, stats
from raft_tpu.cache.staging import _update

# in-process executable memo + the single-flight table of in-progress
# builds.  ONE lock guards both: under concurrent requests (the ROADMAP
# resident solver service) every key is compiled by exactly one thread —
# followers wait on the leader's event instead of re-lowering the same
# program (`make race-smoke` pins one compile per contended key).
_mem: dict = {}
_mem_lock = threading.Lock()
_inflight: dict = {}            # key -> threading.Event of the build
_mem_tags: dict = {}            # key -> tag (scoped eviction, see below)

# tags of executables that were ACTUALLY lowered+compiled in this process
# (every reuse layer missed) — the evidence stream behind compile-count
# claims like "a mixed design stream compiles once per shape bucket":
# bench.py's buckets block and `make hetero-smoke` read it.  BOUNDED: a
# long-lived process (the ROADMAP solver daemon) or a multi-phase bench
# run must not grow it without limit, so the ordered log is a ring of
# the most recent _COMPILE_EVENTS_MAX tags while exact per-tag totals
# since process start (or the last reset) live in _compile_counts —
# count deltas stay correct even after the ring has wrapped.
_COMPILE_EVENTS_MAX = 4096
# ring + counters move together under ONE lock: a reset concurrent with
# an append can never tear them apart (count without event, or vice
# versa), so per-window compile counts stay exact in a threaded daemon
_events_lock = threading.Lock()
_compile_events: deque = deque(maxlen=_COMPILE_EVENTS_MAX)
_compile_counts: Counter = Counter()


def _record_compile(tag: str) -> None:
    """Count one real compile (every warm layer missed): the ordered ring
    and the exact counter update atomically under the events lock."""
    with _events_lock:
        _compile_events.append(tag)
        _compile_counts[tag] += 1


def compile_events(tag: str | None = None) -> list:
    """Tags compiled (not served from any warm layer) in this process, in
    order; filtered to one ``tag`` when given.  The log is a bounded ring
    (:data:`_COMPILE_EVENTS_MAX` most recent events); for counting across
    long windows prefer :func:`compile_count`, which never saturates."""
    with _events_lock:
        events = list(_compile_events)
    if tag is None:
        return events
    return [t for t in events if t == tag]


def compile_count(tag: str | None = None) -> int:
    """Exact number of real compiles since process start (or the last
    :func:`reset_compile_events`): per ``tag``, or total.  Unlike
    ``len(compile_events(tag))`` this stays exact after the bounded
    event ring wraps."""
    with _events_lock:
        if tag is None:
            return sum(_compile_counts.values())
        return _compile_counts.get(tag, 0)


def compile_counts() -> dict:
    """Exact {tag: real compiles} since process start (or the last
    :func:`reset_compile_events`) — the per-tag form of
    :func:`compile_count`, e.g. for the ``obs`` bench block."""
    with _events_lock:
        return dict(_compile_counts)


def reset_compile_events() -> None:
    """Zero the compile-event log AND counters — phase boundaries of
    long-lived processes (bench passes, a resident solver service)
    measure per-window compile counts without unbounded growth.  Atomic
    with respect to concurrent :func:`_record_compile` calls: a window
    can never observe a negative or double-counted delta."""
    with _events_lock:
        _compile_events.clear()
        _compile_counts.clear()


# compiler-side accounting (flops / bytes / memory) of resolved
# executables, memoized per live object: the performance ledger joins
# these numbers with measured dispatch times, and the cost_analysis walk
# should run once per executable, not once per dispatch.  Bounded like
# every other process-lifetime buffer.
_COST_MEMO_MAX = 512
_cost_lock = threading.Lock()
_cost_memo: dict = {}            # id(compiled) -> (weakref, metrics)


def artifact_cost(compiled) -> dict | None:
    """The budget-gate extraction (``lint.audit.compiled_metrics``),
    live: ``cost_analysis``/``memory_analysis`` metrics of one resolved
    executable — works on freshly-compiled AND deserialized-from-disk
    artifacts.  Returns None for a plain jitted function (cache
    disabled) or when the backend reports nothing usable.  Memoized by
    object identity (weakref-checked, so a recycled ``id`` can never
    serve another executable's numbers)."""
    import weakref

    if not hasattr(compiled, "cost_analysis"):
        return None
    key = id(compiled)
    with _cost_lock:
        hit = _cost_memo.get(key)
        if hit is not None and hit[0]() is compiled:
            return hit[1]
    from raft_tpu.lint.audit import compiled_metrics

    try:
        m = compiled_metrics(compiled, 0, 0)
    except Exception:                # pragma: no cover - backend quirk
        return None
    m.pop("n_eqns", None)
    m.pop("n_jaxprs", None)
    if not m:
        return None
    try:
        ref = weakref.ref(compiled)
    except TypeError:                # pragma: no cover - unweakrefable
        return m
    with _cost_lock:
        if len(_cost_memo) >= _COST_MEMO_MAX:
            _cost_memo.pop(next(iter(_cost_memo)))
        _cost_memo[key] = (ref, m)
    return m


def _version_salts() -> tuple:
    import jax

    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
    except Exception:  # pragma: no cover
        jl = "?"
    import raft_tpu

    return ("jax=" + jax.__version__, "jaxlib=" + jl,
            "raft_tpu=" + raft_tpu.__version__,
            # any in-repo source edit invalidates: the traced program
            # depends on library code that shapes/consts cannot see
            "code=" + config.code_fingerprint())


def _topology(mesh=None) -> tuple:
    import jax

    devs = jax.devices()
    topo = (jax.default_backend(), devs[0].device_kind, len(devs))
    if mesh is not None:
        topo += (tuple(mesh.axis_names), tuple(int(s) for s in mesh.devices.shape))
    return topo


def _update_code_consts(h, consts, _depth: int = 0) -> None:
    """Hash a code object's literal constants STRUCTURALLY: nested code
    objects (lambdas, comprehensions, inner defs) hash by their own
    bytecode + constants, never by ``repr`` — a code object's repr embeds
    its memory address, which would make the salt process-unique and
    silently defeat the cross-process disk layer for any hook containing
    a lambda."""
    import types

    for c in consts:
        if isinstance(c, types.CodeType):
            if _depth < 8:
                h.update(c.co_code)
                _update_code_consts(h, c.co_consts, _depth + 1)
            else:  # pragma: no cover - pathological nesting
                h.update(b"<code:deep>")
        elif isinstance(c, (frozenset, set)):
            # unordered: iteration (and so repr) order follows the
            # per-process PYTHONHASHSEED — canonicalize or the salt is
            # process-unique for any hook containing `x in {"a", "b"}`
            h.update(("{%s}" % ",".join(sorted(map(repr, c)))).encode())
        else:
            h.update(repr(c).encode())


def callable_salt(fn, _depth: int = 0) -> tuple:
    """Best-effort identity of a user-supplied callable for the key:
    qualified name + source hash + a fingerprint of its closure cells.
    The closure matters: ``make_apply(0.5)`` and ``make_apply(2.0)`` share
    name and source, and only the captured value distinguishes the traced
    programs — missing it would let a warm process reuse an executable
    with the WRONG constant baked in.  Cells holding arrays/scalars hash
    by content; nested callables recurse (bounded); anything opaque hashes
    by ``repr``, which over-invalidates the disk layer (a new process
    recompiles) rather than aliasing.  Source-less definitions (REPL /
    ``exec``) are covered by the bytecode + literal-constants hash.  The
    salt is best-effort, not a proof: a hook whose behavior hides behind
    an opaque object with a stable ``repr`` defeats it — pass such state
    via ``consts``.  In-repo call
    sites additionally cover their array state via ``consts``; this salt
    guards the user-pluggable hooks (``apply_fn`` / ``objective``)."""
    name = f"{getattr(fn, '__module__', '?')}.{getattr(fn, '__qualname__', repr(fn))}"
    h = hashlib.sha256()
    try:
        h.update(inspect.getsource(fn).encode())
    except (OSError, TypeError):
        h.update(name.encode())
    code = getattr(fn, "__code__", None)
    if code is not None:
        # bytecode + literal constants: distinguishes two same-named hooks
        # even when no source is retrievable (REPL / exec-defined lambdas,
        # where getsource raises for both)
        h.update(code.co_code)
        _update_code_consts(h, code.co_consts)
    cells = getattr(fn, "__closure__", None) or ()
    for cell in cells:
        try:
            v = cell.cell_contents
        except ValueError:             # empty cell
            h.update(b"<empty>")
            continue
        if callable(v) and _depth < 3:
            _update(h, callable_salt(v, _depth + 1))
        elif hasattr(v, "shape") or isinstance(
                v, (int, float, bool, str, bytes, np.generic, type(None))):
            _update(h, np.asarray(v) if hasattr(v, "shape") else v)
        elif isinstance(v, (list, tuple)) and all(
                callable(x) or isinstance(x, (int, float, bool, str))
                or hasattr(x, "shape") for x in v):
            for x in v:
                _update(h, callable_salt(x, _depth + 1) if callable(x)
                        else (np.asarray(x) if hasattr(x, "shape") else x))
        else:
            h.update(repr(v).encode())
    return (name, h.hexdigest()[:16])


def _abstract_signature(args) -> tuple:
    """Pytree structure + per-leaf (shape, dtype) of the call arguments."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = [str(treedef)]
    for leaf in leaves:
        a = np.asarray(leaf) if not hasattr(leaf, "shape") else leaf
        sig.append(f"{getattr(a, 'dtype', type(a).__name__)}:{getattr(a, 'shape', ())}")
    return tuple(sig)


def _consts_fingerprint(consts) -> str:
    """Content hash of the closure-captured pytree (arrays by bytes)."""
    import jax

    h = hashlib.sha256()
    leaves, treedef = jax.tree_util.tree_flatten(consts)
    h.update(str(treedef).encode())
    for leaf in leaves:
        _update(h, np.asarray(leaf) if hasattr(leaf, "shape") or isinstance(
            leaf, (int, float, bool, np.generic)) else leaf)
    return h.hexdigest()[:32]


def _solver_salts() -> tuple:
    """Runtime knobs that change the traced/compiled program without
    appearing in any argument: the Pallas kernel routing, the BEM solver
    routing (mode, assembly route, assembly precision), x64 mode, matmul
    precision, and raw XLA flags.  Keyed
    centrally so no call site can forget them — JAX's persistent compile
    cache keys on its compile options, and the AOT layer must not bypass
    that protection.  (RAFT_TPU_BEM changes which solver produced the
    STAGED coefficient values feeding downstream executables — the jax
    and native paths agree only to the documented parity tolerance, not
    bitwise — so a mode flip must invalidate rather than alias.)"""
    import jax

    from raft_tpu.core import pallas6
    from raft_tpu.hydro import jax_bem

    return ("pallas", bool(pallas6.enabled()),
            "bem_mode", jax_bem.resolved_mode(),
            "bem_assembly", jax_bem.resolved_assembly(),
            "bem_precision", jax_bem.bem_precision(),
            "x64", bool(jax.config.jax_enable_x64),
            "matmul", str(getattr(jax.config, "jax_default_matmul_precision",
                                  None)),
            "xla_flags", os.environ.get("XLA_FLAGS", ""))


def donation_salt(jit_kwargs: dict | None) -> tuple:
    """Key component for the buffer-donation signature of a jit call.

    Donation is baked into the compiled executable (donated parameters
    alias their output buffers), so an executable compiled with
    ``donate_argnums=(0,)`` must NEVER be served to a call site compiled
    without it (and vice versa): the donating executable invalidates
    input buffers the non-donating caller still holds live.  Folded into
    every :func:`cached_compile` key alongside the solver salts.
    """
    kw = jit_kwargs or {}

    def norm(v):
        if v is None:
            return ()
        return tuple(v) if isinstance(v, (tuple, list)) else (v,)

    return ("donate", norm(kw.get("donate_argnums")),
            norm(kw.get("donate_argnames")))


def aot_key(tag: str, args, consts=(), mesh=None, extra=()) -> str:
    """Hex digest naming one executable in the registry."""
    h = hashlib.sha256()
    for part in (("tag", tag), _version_salts(), _topology(mesh),
                 _solver_salts(), _abstract_signature(args),
                 ("consts", _consts_fingerprint(consts)), tuple(extra)):
        _update(h, part)
    return h.hexdigest()[:32]


def _disk_path(key: str) -> str:
    return os.path.join(config.subdir("aot"), f"{key}.pjrt")


def _try_load(key: str):
    """Deserialize a stored executable; None on any failure (the corrupt
    artifact is removed so it cannot fail every future run; a cache root
    disabled by a concurrent thread is just a miss)."""
    try:
        path = _disk_path(key)
    except config.CacheDisabledError:
        return None
    if not os.path.exists(path):
        return None
    from raft_tpu.utils import profiling as prof

    t0 = time.perf_counter()
    try:
        from jax.experimental import serialize_executable as se

        with prof.phase("cache/aot_load", sync=False):
            with open(path, "rb") as f:
                import pickle

                payload, in_tree, out_tree, cold_s = pickle.load(f)
            loaded = se.deserialize_and_load(payload, in_tree, out_tree)
        load_s = time.perf_counter() - t0
        stats.record("aot", "disk_hit", saved_s=max(0.0, cold_s - load_s))
        from raft_tpu import obs as _obs

        _obs.metrics.histogram("aot.deserialize_s").observe(load_s)
        return loaded
    except Exception:
        stats.record("aot", "error")
        try:
            os.unlink(path)
        except OSError:
            pass
        return None


def _try_store(key: str, compiled, cold_s: float) -> None:
    """Best-effort serialize; never fails the run (e.g. executables with
    host callbacks are unserializable — the persistent XLA cache still
    covers their recompile)."""
    from raft_tpu.utils import profiling as prof

    try:
        from jax.experimental import serialize_executable as se

        with prof.phase("cache/aot_save", sync=False):
            payload, in_tree, out_tree = se.serialize(compiled)
            import pickle

            d = os.path.dirname(_disk_path(key))
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump((payload, in_tree, out_tree, cold_s), f)
                os.replace(tmp, _disk_path(key))
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
    except Exception:
        stats.record("aot", "error")


def cached_compile(tag: str, fn, args, *, consts=(), mesh=None,
                   jit_kwargs: dict | None = None, extra=()):
    """``jax.jit(fn, **jit_kwargs).lower(*args).compile()`` through the
    registry.  Always returns an executable for EXACTLY this argument
    signature; reuse layers apply only when the cache is enabled.

    ``consts`` MUST cover every array/scalar the traced ``fn`` closes over
    (it is part of the key — see module docstring); ``extra`` folds in any
    additional statics (e.g. hyperparameters already baked into the trace
    but not arrays, or :func:`callable_salt` of user hooks).  The
    donation signature in ``jit_kwargs`` (``donate_argnums`` /
    ``donate_argnames``) is folded into the key automatically
    (:func:`donation_salt`) — flipping the donation flag can never be
    served a stale executable compiled under the other aliasing contract.
    """
    import jax

    kw = jit_kwargs or {}
    if not config.is_enabled():
        return jax.jit(fn, **kw).lower(*args).compile()
    from raft_tpu.utils import profiling as prof

    key = aot_key(tag, args, consts=consts, mesh=mesh,
                  extra=(*tuple(extra), donation_salt(kw)))
    # single-flight get-or-compute: the first thread to claim a key
    # becomes its leader (registers an in-flight event and builds);
    # followers wait on the event and re-check the memo, so N concurrent
    # requests for one program cost exactly one lowering+compile.  A
    # leader that fails sets the event without publishing, and a waiter
    # retries as the new leader rather than caching the failure.
    while True:
        with _mem_lock:
            hit = _mem.get(key)
            if hit is not None:
                stats.record("aot", "mem_hit")
                return hit
            ev = _inflight.get(key)
            if ev is None:
                ev = _inflight[key] = threading.Event()
                break
        ev.wait()
    try:
        compiled = _try_load(key)
        if compiled is None:
            t0 = time.perf_counter()
            with prof.phase("cache/aot_compile", sync=False):
                compiled = jax.jit(fn, **kw).lower(*args).compile()
            cold_s = time.perf_counter() - t0
            stats.record("aot", "miss")
            from raft_tpu import obs as _obs

            _obs.metrics.histogram("aot.compile_s").observe(cold_s)
            _record_compile(tag)
            _try_store(key, compiled, cold_s)
        with _mem_lock:
            _mem[key] = compiled
            _mem_tags[key] = tag
        return compiled
    finally:
        with _mem_lock:
            _inflight.pop(key, None)
        ev.set()


def cached_callable(tag: str, fn, args, *, consts=(), mesh=None,
                    jit_kwargs: dict | None = None, extra=()):
    """Registry-backed replacement for ``jax.jit(fn, **jit_kwargs)`` at a
    call site that immediately calls it with ``args``.

    Cache disabled: returns the plain jitted function — the EXACT dispatch
    path (and numerics) of an uncached build.  Cache enabled: returns the
    AOT executable for this signature via :func:`cached_compile` (same
    trace, same HLO, same results; the warm layers only skip work).
    """
    import jax

    if not config.is_enabled():
        return jax.jit(fn, **(jit_kwargs or {}))
    return cached_compile(tag, fn, args, consts=consts, mesh=mesh,
                          jit_kwargs=jit_kwargs, extra=extra)


def clear_memory() -> None:
    """Drop the in-process memo (tests).  In-flight builds keep their
    single-flight entries — the leader publishes into the fresh memo."""
    with _mem_lock:
        _mem.clear()
        _mem_tags.clear()
    reset_compile_events()


def evict_memory(tag: str | None = None) -> int:
    """Graceful executor refresh for long-lived processes: drop memoized
    executables (all, or only those registered under ``tag``) WITHOUT
    touching compile counters or in-flight builds.  The next call per
    evicted key re-resolves bottom-up — in-process miss, AOT disk load
    when the program is unchanged, fresh compile when a ladder/knob
    change re-keyed it — which is exactly the resident solver service's
    ``refresh`` op: executables turn over without restarting the daemon,
    and nothing an in-flight batch still references is invalidated (the
    memo holds plain Python references; eviction only unpins them).
    Returns the number of entries dropped."""
    with _mem_lock:
        if tag is None:
            n = len(_mem)
            _mem.clear()
            _mem_tags.clear()
            return n
        keys = [k for k, t in _mem_tags.items() if t == tag]
        for k in keys:
            _mem.pop(k, None)
            _mem_tags.pop(k, None)
        return len(keys)
