"""Warm-start subsystem: persistent compile cache, AOT executable reuse,
and on-disk staging of host-precomputed BEM coefficients.

A cold north-star process is >94% warm-up (XLA compilation 11.45 s +
host-side BEM staging 3.08 s against a 0.82 s compiled sweep, BENCH_r05);
this package makes the second process start hot:

* :func:`enable` — the one switch.  Wires JAX's persistent compilation
  cache under the cache root and arms the two layers below.  Honors
  ``RAFT_TPU_CACHE_DIR`` (``off`` disables everything, keeping runs
  bit-identical to an uncached build).  Called by the CLI and the bench
  at startup; library users opt in explicitly.
* :mod:`raft_tpu.cache.aot` — compiled-executable registry keyed by
  (function tag, abstract arg shapes/dtypes, closure-consts content hash,
  device topology, version salts), serialized across processes.
* :mod:`raft_tpu.cache.staging` — content-addressed npz cache for
  host-side staging (WAMIT parses, BEM grid solves + interpolation,
  heading-row interpolation).
* :mod:`raft_tpu.cache.stats` — hit/miss/saved-seconds ledger; its
  :func:`~raft_tpu.cache.stats.report` is the bench JSON's ``warm_start``
  block.
"""
from raft_tpu.cache.config import (  # noqa: F401
    cache_dir,
    default_dir,
    disable,
    enable,
    is_enabled,
    resolve_dir,
)
from raft_tpu.cache.aot import (  # noqa: F401
    aot_key,
    cached_callable,
    cached_compile,
    callable_salt,
    compile_count,
    compile_events,
    donation_salt,
    evict_memory,
    reset_compile_events,
)
from raft_tpu.cache.staging import FileKey, cached_arrays, staging_key  # noqa: F401
from raft_tpu.cache.stats import report  # noqa: F401
