"""Content-addressed on-disk cache for host-side array staging.

The RAO sweep's host-side warm-up — panel meshing + BEM solve + grid
interpolation in ``bench._volturn_setup``, WAMIT file parsing in
``hydro.bem_io.load_wamit_coeffs``, the per-case heading interpolation in
``parallel.sweep._stage_heading_rows`` — costs seconds per process
(BENCH_r05 ``setup_bem_stage``: 3.08 s) and is a pure function of its
file/array inputs.  This module memoizes such functions as npz artifacts
keyed by a hash of everything they read: file CONTENTS (not paths/mtimes,
so a rewritten WAMIT file invalidates and an identical copy hits), array
bytes, and scalar/string parameters.

Corruption tolerance is absolute: any failure to read or parse an artifact
counts as a miss and falls through to the real computation (the bad file is
replaced by the fresh store).  Writes are atomic (tmp + rename) so a killed
process cannot leave a truncated artifact that a later run would trust.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time

import numpy as np

from raft_tpu.cache import config, stats

_FORMAT_SALT = "staging-v1"       # bump to invalidate every artifact


def _update(h, part) -> None:
    """Fold one key part into the hash: arrays by dtype/shape/bytes,
    file markers by content hash, scalars/strings canonically."""
    if isinstance(part, FileKey):
        h.update(b"file:")
        h.update(part.digest.encode())
    elif isinstance(part, np.ndarray) or hasattr(part, "__array__"):
        a = np.asarray(part)
        h.update(f"arr:{a.dtype.str}:{a.shape}:".encode())
        h.update(np.ascontiguousarray(a).tobytes())
    elif isinstance(part, (list, tuple)):
        h.update(f"seq{len(part)}:".encode())
        for p in part:
            _update(h, p)
    elif isinstance(part, float):
        # canonical 8-byte key encoding, never device data
        h.update(np.float64(part).tobytes())  # graftlint: disable=GL105
    elif isinstance(part, (int, bool, np.integer)):
        h.update(f"int:{int(part)}:".encode())
    elif part is None:
        h.update(b"none:")
    else:
        h.update(f"str:{part}:".encode())


class FileKey:
    """Content identity of an input file: sha256 of its bytes.

    Hashing contents (not mtime) means touching a WAMIT file without
    changing it still hits, while any edit — including an in-place rewrite
    that preserves size — invalidates."""

    def __init__(self, path: str):
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        self.path = path
        self.digest = h.hexdigest()


def staging_key(category: str, *parts) -> str:
    """Hex digest addressing one staged artifact.  The raft_tpu version
    AND the package source fingerprint are part of the key (same
    staleness rule as the AOT registry): an upgrade or in-repo edit that
    changes staging semantics — interpolation, dimensionalization — must
    recompute, not serve pre-edit arrays."""
    import raft_tpu

    h = hashlib.sha256()
    h.update(f"{_FORMAT_SALT}:{raft_tpu.__version__}:"
             f"{config.code_fingerprint()}:{category}:".encode())
    for p in parts:
        _update(h, p)
    return h.hexdigest()[:32]


def cached_arrays(category: str, parts, compute, meta: dict | None = None):
    """Memoize ``compute() -> tuple of arrays`` on disk, content-addressed.

    ``parts``: everything the computation reads (arrays, scalars, strings,
    :class:`FileKey` markers for files).  Returns the tuple (complex dtypes
    round-trip).  With the cache disabled this is exactly ``compute()``.

    A hit reports the seconds it saved — the cold run stores its own
    compute time in the artifact, so ``saved = stored_cold_s - load_s``.
    """
    if not config.is_enabled():
        return tuple(compute())
    from raft_tpu.utils import profiling as prof

    key = staging_key(category, *parts)
    path = os.path.join(config.subdir("staging"), f"{category}-{key}.npz")
    if os.path.exists(path):
        t0 = time.perf_counter()
        try:
            with prof.phase("cache/staging_load", sync=False):
                with np.load(path, allow_pickle=False) as z:
                    n = int(z["__n__"])
                    cold_s = float(z["__cold_s__"])
                    out = tuple(z[f"arr{i}"] for i in range(n))
            load_s = time.perf_counter() - t0
            stats.record("staging", "disk_hit",
                         saved_s=max(0.0, cold_s - load_s))
            return out
        except Exception:
            # truncated/corrupt/foreign artifact: silently recompute (the
            # store below overwrites it atomically)
            stats.record("staging", "error")
    t0 = time.perf_counter()
    out = tuple(compute())
    cold_s = time.perf_counter() - t0
    stats.record("staging", "miss")
    try:
        with prof.phase("cache/staging_save", sync=False):
            payload = {f"arr{i}": np.asarray(a) for i, a in enumerate(out)}
            payload["__n__"] = np.int64(len(out))
            # npz metadata scalar (host artifact, never staged to device)
            payload["__cold_s__"] = np.float64(cold_s)  # graftlint: disable=GL105
            if meta:
                payload["__meta__"] = np.frombuffer(
                    json.dumps(meta).encode(), dtype=np.uint8
                )
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                       suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez_compressed(f, **payload)
                os.replace(tmp, path)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
    except Exception:
        stats.record("staging", "error")   # a failed store never fails the run
    return out
