"""Warm-start instrumentation: hit/miss counters and saved-seconds ledger.

Every cache layer (persistent XLA compile cache wiring, AOT executable
registry, BEM staging cache) records its events here so a bench run can
report the cold/warm split separately from solve throughput — the
``warm_start`` block of the bench JSON.  Wall-clock spent inside cache
machinery itself goes through :mod:`raft_tpu.utils.profiling` phases named
``cache/...`` and therefore shows up in ``phases_s`` alongside the physics
phases.
"""
from __future__ import annotations

import threading
from collections import defaultdict

_lock = threading.Lock()


def _zero() -> dict:
    return {"mem_hits": 0, "disk_hits": 0, "misses": 0, "errors": 0,
            "saved_s": 0.0}


_layers: dict = defaultdict(_zero)

_EVENT_KEY = {"mem_hit": "mem_hits", "disk_hit": "disk_hits",
              "miss": "misses", "error": "errors"}


def record(layer: str, event: str, saved_s: float = 0.0) -> None:
    """Count one cache event.

    ``layer``: "aot" / "staging" / ...; ``event``: "mem_hit" / "disk_hit" /
    "miss" / "error".  ``saved_s``: estimated wall-clock the hit avoided
    (cold compute time recorded at store time minus the load time) — the
    number that lets the perf trajectory show warm-start value directly.
    """
    with _lock:
        c = _layers[layer]
        c[_EVENT_KEY[event]] += 1
        c["saved_s"] += float(saved_s)
    # mirror into the unified metric registry (raft_tpu.obs): one central
    # site covers every cache layer's hit/miss/error counters
    from raft_tpu import obs as _obs

    _obs.metrics.counter(f"cache.{layer}.{event}").inc()


def report() -> dict:
    """The ``warm_start`` block: per-layer counters plus the enablement
    state, ready to embed in a bench JSON."""
    from raft_tpu.cache import config

    with _lock:
        layers = {k: dict(v) for k, v in _layers.items()}
    for c in layers.values():
        c["saved_s"] = round(c["saved_s"], 3)
    return {
        "enabled": config.is_enabled(),
        "dir": config.cache_dir(),
        **layers,
    }


def reset() -> None:
    with _lock:
        _layers.clear()
