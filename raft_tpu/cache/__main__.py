"""Cache smoke check: prove the warm-start subsystem works on this machine.

``python -m raft_tpu.cache smoke`` runs a tiny OC3 design sweep TWICE in
separate processes sharing one fresh cache dir and asserts the second
process's compile wall-clock (AOT load + any residual compile) is below a
threshold fraction of the first's — the cross-process warm-start claim,
verified end-to-end in ~a minute on CPU.  Exit code 0/1; prints one JSON
line with both processes' numbers.  ``make cache-smoke`` wraps it; a
smaller variant runs inside the test suite (tests/test_cache.py).

``python -m raft_tpu.cache child`` is the per-process payload (internal).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile


def _child(argv) -> None:
    p = argparse.ArgumentParser(prog="raft_tpu.cache child")
    p.add_argument("--n", type=int, default=8)
    p.add_argument("--nw", type=int, default=30)
    args = p.parse_args(argv)

    # the smoke must never dial a hardware backend: pin CPU before jax init
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from raft_tpu import cache
    from raft_tpu.utils import profiling as prof

    cache.enable()                      # RAFT_TPU_CACHE_DIR from the parent

    import jax.numpy as jnp

    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.parallel import sweep

    design, members, rna, env, wave = ge._base(nw=args.nw)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    thetas = jnp.linspace(0.95, 1.05, args.n)
    out = sweep(members, rna, env, wave, C_moor, thetas, n_iter=25)
    print(json.dumps({
        "phases_s": {k: round(v, 4) for k, v in prof.totals().items()},
        "warm_start": cache.report(),
        "std0": float(out["std dev"][0, 0]),   # cold/warm must agree
    }))


def _run_child(cache_dir: str, n: int, nw: int) -> dict:
    env = dict(os.environ)
    env["RAFT_TPU_CACHE_DIR"] = cache_dir
    env["JAX_PLATFORMS"] = "cpu"
    # the smoke must be deterministic whatever environment launches it: a
    # caller's virtual-device mesh (e.g. the test suite's 8-CPU XLA_FLAGS)
    # changes XLA-CPU compile times enough to swamp the tiny workload
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-m", "raft_tpu.cache", "child",
         "--n", str(n), "--nw", str(nw)],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
    )
    if r.returncode != 0:
        raise SystemExit(
            f"cache-smoke child failed (rc={r.returncode}):\n"
            + (r.stderr or r.stdout)[-2000:]
        )
    return json.loads(r.stdout.strip().splitlines()[-1])


def compile_seconds(phases: dict) -> float:
    """Wall-clock a process spent producing executables: trace+compile plus
    the warm path's artifact loads."""
    return sum(v for k, v in phases.items()
               if k.endswith(("cache/aot_compile", "cache/aot_load")))


def smoke(argv) -> int:
    p = argparse.ArgumentParser(prog="raft_tpu.cache smoke")
    p.add_argument("--n", type=int, default=8, help="design variants")
    p.add_argument("--nw", type=int, default=30, help="frequency bins")
    p.add_argument("--threshold", type=float, default=0.5,
                   help="warm compile must be below this fraction of cold")
    p.add_argument("--dir", default=None,
                   help="cache dir (default: fresh temp dir, removed after)")
    args = p.parse_args(argv)

    d = args.dir or tempfile.mkdtemp(prefix="raft_tpu_cache_smoke_")
    try:
        cold = _run_child(d, args.n, args.nw)
        warm = _run_child(d, args.n, args.nw)
        cold_s = compile_seconds(cold["phases_s"])
        warm_s = compile_seconds(warm["phases_s"])
        hits = warm["warm_start"].get("aot", {}).get("disk_hits", 0)
        ok = (hits >= 1 and warm_s < args.threshold * cold_s
              and warm["std0"] == cold["std0"])
        print(json.dumps({
            "ok": ok,
            "cold_compile_s": round(cold_s, 3),
            "warm_compile_s": round(warm_s, 3),
            "speedup": round(cold_s / warm_s, 1) if warm_s > 0 else None,
            "warm_aot_disk_hits": hits,
            "results_identical": warm["std0"] == cold["std0"],
            "cache_dir": d,
        }))
        return 0 if ok else 1
    finally:
        if args.dir is None:
            shutil.rmtree(d, ignore_errors=True)


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] == "child":
        _child(argv[1:])
        return 0
    if argv and argv[0] == "smoke":
        return smoke(argv[1:])
    print("usage: python -m raft_tpu.cache smoke [--n N] [--nw NW] "
          "[--threshold R] [--dir D]", file=sys.stderr)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
