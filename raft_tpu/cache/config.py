"""Warm-start enablement: one entry point wiring JAX's persistent
compilation cache plus the on-disk layers of this package.

The north-star workload spends >94% of a cold process in warm-up (XLA
compilation + host-side BEM staging, BENCH_r05 ``phases_s``), so the
service-shaped deployments the ROADMAP targets need compiled executables
and staged coefficients to survive process boundaries.  ``enable()`` is the
single switch: it points ``jax_compilation_cache_dir`` at the cache root
and drops the min-entry-size / min-compile-time thresholds so even the
CPU-fallback bench populates it, and it fixes the directory the AOT
registry (:mod:`raft_tpu.cache.aot`) and the staging cache
(:mod:`raft_tpu.cache.staging`) write under.

Resolution order for the cache root:

1. the ``cache_dir=`` argument;
2. the ``RAFT_TPU_CACHE_DIR`` environment variable — the spellings
   ``off`` / ``0`` / ``none`` / ``disabled`` (case-insensitive) disable
   every layer, keeping the run bit-identical to an uncached one; an
   EMPTY value means unset (fall through to the default);
3. the default ``~/.cache/raft_tpu``.

Layout under the root::

    <root>/xla/       persistent XLA compilation cache (managed by jax)
    <root>/aot/       serialized AOT executables + JSON key sidecars
    <root>/staging/   content-addressed npz staging artifacts
    <root>/bem/       native panel-solver results (hydro/native_bem.py)
"""
from __future__ import annotations

import os
import threading

_OFF_SPELLINGS = ("off", "0", "none", "disabled", "false", "no")

# one lock guards the enablement state AND the code-salt memo: a daemon
# thread toggling the cache while another reads/arms can never observe a
# half-updated state, and the fingerprint is computed exactly once
_state_lock = threading.Lock()
_state = {"enabled": False, "dir": None, "wired": None}
_code_salt: list = []


class CacheDisabledError(RuntimeError):
    """The cache root vanished between an ``is_enabled()`` check and the
    path derivation (a concurrent ``disable()``) — callers on the warm
    path treat it as a cache miss."""


def code_fingerprint() -> str:
    """Content hash of every .py file in the raft_tpu package — the
    in-repo analog of the user-hook ``callable_salt``: editing ANY library
    source (physics, staging, solver driver) invalidates every AOT and
    staging artifact, so a developer iterating on the code can never be
    served a pre-edit executable or pre-edit staged arrays.  (The same
    rule the native panel solver has always applied to its own source,
    hydro/native_bem.py.)  Conservative on purpose: a docstring edit
    recompiles too — correctness over cache lifetime.  Computed once per
    process (~1 ms for this package size; the lock makes the compute
    single-flight, so concurrent first readers share one walk)."""
    with _state_lock:
        if not _code_salt:
            import hashlib

            import raft_tpu

            h = hashlib.sha256()
            try:
                pkg = os.path.dirname(os.path.abspath(raft_tpu.__file__))
                # sorted() consumes the whole walk, so ordering is already
                # deterministic regardless of dirent order
                for dirpath, _dirnames, filenames in sorted(os.walk(pkg)):
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            p = os.path.join(dirpath, fn)
                            h.update(os.path.relpath(p, pkg).encode())
                            with open(p, "rb") as f:
                                h.update(f.read())
                _code_salt.append(h.hexdigest()[:16])
            except OSError:  # pragma: no cover - unreadable install
                _code_salt.append("nosalt")
        return _code_salt[0]


def default_dir() -> str:
    return os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu")


def resolve_dir(cache_dir: str | None = None) -> str | None:
    """The cache root this process would use, or None when disabled.

    Pure resolution — does not create directories or touch jax config."""
    if cache_dir is None:
        env = os.environ.get("RAFT_TPU_CACHE_DIR")
        if env is not None and env.strip():
            cache_dir = env.strip()
        else:
            cache_dir = default_dir()
    if cache_dir.strip().lower() in _OFF_SPELLINGS:
        return None
    return os.path.abspath(os.path.expanduser(cache_dir))


def enable(cache_dir: str | None = None,
           min_entry_size_bytes: int = -1,
           min_compile_time_secs: float = 0.0) -> str | None:
    """Turn the warm-start subsystem on.  Idempotent; safe to call before
    or after jax backend init (the compilation-cache config applies to any
    compile that happens after the call).

    Returns the cache root, or None when disabled (``RAFT_TPU_CACHE_DIR``
    set to one of the off spellings) — in which case NOTHING is configured
    and every cached entry point takes its plain uncached path.

    ``min_entry_size_bytes=-1`` / ``min_compile_time_secs=0`` cache every
    executable regardless of size or compile time: the north-star sweep is
    a handful of large programs, so there is no small-entry churn to guard
    against, and the CPU-fallback bench (fast compiles) must populate the
    cache too for the warm-start acceptance check to be measurable
    off-TPU.
    """
    root = resolve_dir(cache_dir)
    if root is None:
        disable()       # also un-wires a previously-enabled compile cache
        return None
    with _state_lock:
        _state.update(enabled=True, dir=root)
        if _state["wired"] != root:    # first call, or a new root (tests)
            import jax

            xla_dir = os.path.join(root, "xla")
            os.makedirs(xla_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", xla_dir)
            try:
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes",
                    min_entry_size_bytes)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    min_compile_time_secs)
            except AttributeError:  # pragma: no cover - older jax spelling
                pass
            _state["wired"] = root
    return root


def disable() -> None:
    """Turn every layer off for this process (tests): no AOT/staging
    artifact is read or written, and the persistent compilation cache is
    un-wired (``jax_compilation_cache_dir=None`` restores jax's
    default-off state) so later compiles are plain uncached ones."""
    with _state_lock:
        if _state["wired"] is not None:
            import jax

            jax.config.update("jax_compilation_cache_dir", None)
            _state["wired"] = None
        _state.update(enabled=False, dir=None)


def is_enabled() -> bool:
    with _state_lock:
        return bool(_state["enabled"])


def cache_dir() -> str | None:
    with _state_lock:
        return _state["dir"]


def subdir(name: str) -> str:
    """<root>/<name>, created on demand.  Caller checked ``is_enabled()``
    — but in a threaded process the cache can be disabled BETWEEN that
    check and this call, so a vanished root raises a typed
    :class:`CacheDisabledError` (which the AOT disk layers degrade to a
    miss) rather than a ``TypeError`` out of ``os.path.join(None, ...)``."""
    with _state_lock:
        root = _state["dir"]
    if root is None:
        raise CacheDisabledError(
            f"cache disabled concurrently; no root for subdir {name!r}")
    d = os.path.join(root, name)
    os.makedirs(d, exist_ok=True)
    return d
