"""Capytaine BEM-dataset ingestion.

The reference's test suite documents a ``read_capy_nc``/``call_capy``
contract against Capytaine NetCDF datasets with 1e-12 golden regression
(/root/reference/tests/test_capytaine_integration.py:10-134); the functions
themselves are absent from the reference snapshot (referenced only in the
commented import at raft/runRAFT.py:14 and the commented preprocessing path
at raft/runRAFT.py:44-61).  This module implements that contract for real:

* :func:`read_capy_nc` — read a Capytaine NetCDF (classic CDF) dataset into
  ``(w, addedMass[6,6,nw], damping[6,6,nw], fEx[6,nw])`` with optional
  interpolation onto a design frequency grid, raising ``ValueError`` when
  the requested grid extends beyond the data (the contract pinned at
  tests/test_capytaine_integration.py:31-34).
* :func:`call_capy` — run a live Capytaine radiation/diffraction solve for
  a mesh + frequency grid (requires the optional ``capytaine`` package).
* :func:`load_capytaine_nc` — read + reorder to the ``Model(BEM=...)``
  staging layout shared with the WAMIT readers.
"""
from __future__ import annotations

import numpy as np

_DOF_ORDER = ("Surge", "Sway", "Heave", "Roll", "Pitch", "Yaw")


def _decode(char_rows) -> list[str]:
    return ["".join(c.decode() for c in row).strip("\x00 ") for row in char_rows]


def read_capy_nc(path: str, wDes=None, heading_idx: int = 0,
                 include_froude_krylov: bool = True):
    """Read a Capytaine NetCDF dataset.

    Returns ``(w, addedMass, damping, fEx)`` with shapes ``(nw,)``,
    ``(6,6,nw)``, ``(6,6,nw)``, ``(6,nw)`` (``fEx`` complex128, per unit
    wave amplitude; excitation = diffraction + Froude-Krylov).  With
    ``wDes`` given, all outputs are linearly interpolated onto it and
    ``wDes`` is returned as the first element.

    ``include_froude_krylov=False`` reproduces the reference's golden data
    exactly (tests/ref_data/capytaine_integration pins fEx to the
    ``diffraction_force`` variable alone — the incident-wave Froude-Krylov
    part is missing from the intended upstream reader, DEVIATIONS.md #19);
    the default includes it, which is the physically complete excitation.
    """
    from scipy.io import netcdf_file

    with netcdf_file(path, "r", mmap=False) as f:
        w = np.array(f.variables["omega"][:], dtype=float)
        A = np.array(f.variables["added_mass"][:], dtype=float)
        B = np.array(f.variables["radiation_damping"][:], dtype=float)
        D = np.array(f.variables["diffraction_force"][:], dtype=float)
        FK = np.array(f.variables["Froude_Krylov_force"][:], dtype=float)
        rad_dofs = _decode(f.variables["radiating_dof"][:])
        inf_dofs = _decode(f.variables["influenced_dof"][:])

    # reorder DOFs into (surge..yaw) in case the dataset permutes them
    ri = [rad_dofs.index(d) for d in _DOF_ORDER]
    ii = [inf_dofs.index(d) for d in _DOF_ORDER]
    # (omega, radiating, influenced) -> (radiating, influenced, omega)
    A = A[:, ri, :][:, :, ii].transpose(1, 2, 0)
    B = B[:, ri, :][:, :, ii].transpose(1, 2, 0)
    # (complex, omega, heading, dof) -> complex (dof, omega)
    if include_froude_krylov:
        fEx_all = (D[0] + FK[0]) + 1j * (D[1] + FK[1])
    else:
        fEx_all = D[0] + 1j * D[1]
    # Capytaine hands back float64/complex128; keep the HOST staging layout
    # canonical — the device layout downcasts at jnp.asarray time (x32)
    fEx = fEx_all[:, heading_idx, :][:, ii].T.astype(np.complex128)  # graftlint: disable=GL105

    if wDes is not None:
        wDes = np.asarray(wDes, dtype=float)
        if wDes.min() < w.min() - 1e-12 or wDes.max() > w.max() + 1e-12:
            raise ValueError(
                f"requested frequency range [{wDes.min():.3f}, "
                f"{wDes.max():.3f}] outside capytaine data range "
                f"[{w.min():.3f}, {w.max():.3f}]"
            )
        A = _interp_last(w, A, wDes)
        B = _interp_last(w, B, wDes)
        fEx = _interp_last(w, fEx, wDes)
        return wDes, A, B, fEx
    return w, A, B, fEx


def _interp_last(w_src, arr, w_dst):
    out = np.empty(arr.shape[:-1] + (len(w_dst),), dtype=arr.dtype)
    flat = arr.reshape(-1, arr.shape[-1])
    oflat = out.reshape(-1, len(w_dst))
    for i in range(flat.shape[0]):
        # complex arrays interpolate in one call (bit-identical to the
        # reference's golden interpolation data)
        oflat[i] = np.interp(w_dst, w_src, flat[i])
    return out


def call_capy(meshFName: str, wCapy, CoG=(0.0, 0.0, 0.0), headings=(0.0,),
              depth=None, ncFName: str | None = None, density: float = 1025.0):
    """Run a live Capytaine radiation + diffraction solve
    (cf. the commented recipe at raft/runRAFT.py:44-61).

    Requires the optional ``capytaine`` package; raises ImportError with a
    pointer to :func:`read_capy_nc` when absent.  Returns the same tuple as
    :func:`read_capy_nc` and optionally exports the dataset to ``ncFName``.
    """
    try:
        import capytaine as cpt
    except ImportError as e:
        raise ImportError(
            "capytaine is not installed; precompute a NetCDF dataset and "
            "load it with read_capy_nc(), or use the native solver "
            "(raft_tpu.hydro.native_bem.solve_bem)"
        ) from e

    body = cpt.FloatingBody.from_file(meshFName)
    body.center_of_mass = np.asarray(CoG)
    body.keep_immersed_part()
    body.add_all_rigid_body_dofs()
    problems = [
        cpt.RadiationProblem(body=body, radiating_dof=dof, omega=w,
                             sea_bottom=-abs(depth) if depth else -np.inf,
                             rho=density)
        for dof in body.dofs for w in wCapy
    ] + [
        cpt.DiffractionProblem(body=body, omega=w, wave_direction=b,
                               sea_bottom=-abs(depth) if depth else -np.inf,
                               rho=density)
        for b in headings for w in wCapy
    ]
    solver = cpt.BEMSolver()
    results = solver.solve_all(problems)
    ds = cpt.assemble_dataset(results)
    if ncFName is not None:
        cpt.io.xarray.separate_complex_values(ds).to_netcdf(ncFName)
    A = ds["added_mass"].values.transpose(1, 2, 0)
    B = ds["radiation_damping"].values.transpose(1, 2, 0)
    fEx = (ds["diffraction_force"] + ds["Froude_Krylov_force"]).values
    # host staging layout (see run_capytaine above): canonical c128 on host
    fEx = fEx[:, 0, :].T.astype(np.complex128)  # graftlint: disable=GL105
    return np.asarray(wCapy), A, B, fEx


def load_capytaine_nc(path: str, w_grid=None):
    """Read a Capytaine dataset and return ``(A, B, F)`` ready for
    ``Model(design, BEM=(A, B, F))`` (same staging layout as
    :func:`raft_tpu.hydro.bem_io.load_wamit_coeffs`)."""
    w, A, B, F = read_capy_nc(path, wDes=w_grid)
    return A, B, F
