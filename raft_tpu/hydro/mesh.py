"""Axisymmetric panel mesher for potential-flow (BEM) members.

Host-side preprocessing, the capability of the reference's ``member2pnl``
(raft/member2pnl.py:8-509) re-designed around plain (n,4,3) numpy panel
arrays instead of growing Python lists: build each ``potMod`` member's
wetted surface as a revolved station profile (sides + end caps), transform
by member pose, clip at the waterline, and emit HAMS ``.pnl`` / WAMIT
``.gdf`` files or hand the panels straight to the native BEM solver.

Panels are quads with vertices ordered so the normal points INTO the fluid
(outward from the body); triangles are stored as degenerate quads (last
vertex repeated), the convention both HAMS and WAMIT accept.
"""
from __future__ import annotations

import numpy as np


def _profile(stations: np.ndarray, radii: np.ndarray, dz_max: float):
    """Refine a station profile so no axial span exceeds dz_max."""
    zs, rs = [float(stations[0])], [float(radii[0])]
    for i in range(1, len(stations)):
        dz = stations[i] - stations[i - 1]
        if dz <= 0:
            # radius jump at equal station: keep both points (vertical flange)
            zs.append(float(stations[i]))
            rs.append(float(radii[i]))
            continue
        n = max(1, int(np.ceil(dz / dz_max)))
        for j in range(1, n + 1):
            f = j / n
            zs.append(float(stations[i - 1] + f * dz))
            rs.append(float(radii[i - 1] + f * (radii[i] - radii[i - 1])))
    return np.array(zs), np.array(rs)


def _cap_rings(r_outer: float, da_max: float):
    """Radii for end-cap rings from r_outer down toward the axis."""
    if r_outer <= 0:
        return np.array([0.0])
    n = max(1, int(np.ceil(r_outer / da_max)))
    return np.linspace(r_outer, 0.0, n + 1)


def _naz_levels(radii, da_max: float, naz_min: int = 4, naz_max: int = 512):
    """Adaptive azimuthal sector counts, one per ring radius.

    The capability of the reference mesher's azimuthal doubling/halving
    (raft/member2pnl.py:177-242), designed as a per-member power-of-two
    family: every ring gets the smallest count ``base * 2^k`` satisfying
    the arc-length bound ``2 pi r / naz <= da_max``, with ``base`` chosen
    from {4..7} to minimize the member's total sector count.  Adjacent
    rings then differ by exactly 1:2 (or equal), so bands stitch with
    watertight transition triangles — and large end caps coarsen toward
    the axis instead of inheriting the outer ring's count.
    """
    radii = np.asarray(radii, dtype=float)
    targets = 2.0 * np.pi * np.clip(radii, 0.0, None) / da_max

    def level(base, t):
        n = base
        while n < t and n < naz_max:
            n *= 2
        return n

    best, best_cost = None, None
    for base in (4, 5, 6, 7):
        ns = np.array([level(base, max(t, naz_min)) for t in targets])
        cost = ns.sum()
        if best_cost is None or cost < best_cost:
            best, best_cost = ns, cost
    # clamp jumps to one level between consecutive rings so every band is
    # either conforming (1:1) or a single 1:2 transition
    ns = best.astype(int)
    for i in range(1, len(ns)):
        ns[i] = min(ns[i], ns[i - 1] * 2)
    for i in range(len(ns) - 2, -1, -1):
        ns[i] = min(ns[i], ns[i + 1] * 2)
    return ns


def _band_panels(ring_a, ring_b):
    """Panels between two rings with naz_a, naz_b in {equal, 1:2, 2:1}.

    Vertex order (a_j, a_j+1, b_j+1, b_j) — the same cyclic sense as a
    conforming quad strip — so outward orientation is preserved; 1:2
    transitions emit three triangles per coarse sector (stored as
    degenerate quads), keeping the surface watertight.
    """
    na, nb = len(ring_a) - 1, len(ring_b) - 1
    out = []
    if na == nb:
        a0, a1 = ring_a[:-1], ring_a[1:]
        b0, b1 = ring_b[:-1], ring_b[1:]
        out.append(np.stack([a0, a1, b1, b0], axis=1))
    elif nb == 2 * na:
        for j in range(na):
            aj, aj1 = ring_a[j], ring_a[j + 1]
            f0, f1, f2 = ring_b[2 * j], ring_b[2 * j + 1], ring_b[2 * j + 2]
            out.append(np.stack([
                np.stack([aj, aj1, f1, f1]),
                np.stack([aj, f1, f0, f0]),
                np.stack([aj1, f2, f1, f1]),
            ]))
    elif na == 2 * nb:
        for j in range(nb):
            bj, bj1 = ring_b[j], ring_b[j + 1]
            c0, c1, c2 = ring_a[2 * j], ring_a[2 * j + 1], ring_a[2 * j + 2]
            out.append(np.stack([
                np.stack([c0, c1, bj, bj]),
                np.stack([c1, bj1, bj, bj]),
                np.stack([c1, c2, bj1, bj1]),
            ]))
    else:
        raise ValueError(f"non-stitchable ring counts {na}:{nb}")
    return out


def mesh_member(
    stations,
    diameters,
    rA,
    rB,
    dz_max: float = 3.0,
    da_max: float = 2.0,
    endA: bool = True,
    endB: bool = True,
) -> np.ndarray:
    """Mesh one circular member: returns (np, 4, 3) panel vertex array.

    ``stations`` are along-axis positions (member frame, 0 at end A),
    ``diameters`` the matching outer diameters; ``rA``/``rB`` the global end
    positions.  Sides are revolved bands with adaptive azimuthal counts
    (see :func:`_naz_levels`); flat end caps are ring fans coarsening
    toward the axis (cf. the reference's radial end fill + azimuthal
    refinement, raft/member2pnl.py:149-242).
    """
    stations = np.asarray(stations, dtype=float)
    diameters = np.asarray(diameters, dtype=float)
    rA = np.asarray(rA, dtype=float)
    rB = np.asarray(rB, dtype=float)

    zs, rs = _profile(stations, 0.5 * diameters, dz_max)

    # assemble the full ring sequence: cap A (axis -> rim), sides, cap B
    # (rim -> axis), so adaptive counts are consistent across the seams
    ring_r, ring_z = [], []
    if endA and rs[0] > 0:
        rrA = _cap_rings(rs[0], da_max)[::-1]          # axis ... rim
        ring_r.extend(rrA[:-1])
        ring_z.extend([zs[0]] * (len(rrA) - 1))
    ring_r.extend(rs)
    ring_z.extend(zs)
    if endB and rs[-1] > 0:
        rrB = _cap_rings(rs[-1], da_max)
        ring_r.extend(rrB[1:])
        ring_z.extend([zs[-1]] * (len(rrB) - 1))
    ring_r = np.array(ring_r)
    ring_z = np.array(ring_z)
    naz = _naz_levels(ring_r, da_max)

    def ring(i):
        n = naz[i]
        th = np.linspace(0.0, 2.0 * np.pi, n + 1)
        return np.stack(
            [ring_r[i] * np.cos(th), ring_r[i] * np.sin(th),
             np.full(n + 1, ring_z[i])], axis=-1,
        )

    # orientation falls out of the ring ordering: lower-z ring (or the
    # inner ring of a same-z annulus pair ordered inner->outer) in the
    # first slot gives outward normals for sides, caps, and flange
    # shoulders alike (cross-diagonal rule on [a_j, a_j+1, b_j+1, b_j])
    panels = []
    for i in range(len(ring_r) - 1):
        same_z = abs(ring_z[i + 1] - ring_z[i]) < 1e-12
        if same_z and ring_r[i + 1] == ring_r[i]:
            continue
        panels.extend(_band_panels(ring(i), ring(i + 1)))

    pans = np.concatenate(panels, axis=0)

    # pose: local +z axis -> member axis q
    axis = rB - rA
    L = np.linalg.norm(axis)
    q = axis / L
    # scale local z from profile coordinate (already along-axis length)
    z_hat = np.array([0.0, 0.0, 1.0])
    v = np.cross(z_hat, q)
    c = float(np.dot(z_hat, q))
    if np.linalg.norm(v) < 1e-12:
        R = np.eye(3) if c > 0 else np.diag([1.0, -1.0, -1.0])
    else:
        vx = np.array([[0, -v[2], v[1]], [v[2], 0, -v[0]], [-v[1], v[0], 0]])
        R = np.eye(3) + vx + vx @ vx * ((1 - c) / (np.linalg.norm(v) ** 2))
    pans = pans @ R.T + rA

    return clip_waterline(pans)


def clip_waterline(panels: np.ndarray, z_surface: float = 0.0) -> np.ndarray:
    """Drop panels fully above the surface; clamp crossing vertices to z=0
    (the reference's makePanel clip, raft/member2pnl.py:8-35).  Panels left
    with zero area (all vertices clamped) are removed."""
    z = panels[..., 2]
    keep = (z < z_surface - 1e-9).any(axis=1)
    pans = panels[keep].copy()
    pans[..., 2] = np.minimum(pans[..., 2], z_surface)
    area = panel_areas(pans)
    return pans[area > 1e-10]


def panel_centroids(panels: np.ndarray) -> np.ndarray:
    return panels.mean(axis=1)


def panel_normals_areas(panels: np.ndarray):
    """Normals (unit) and areas of quad panels via the cross-diagonal rule."""
    d1 = panels[:, 2] - panels[:, 0]
    d2 = panels[:, 3] - panels[:, 1]
    n = 0.5 * np.cross(d1, d2)
    area = np.linalg.norm(n, axis=-1)
    unit = n / np.where(area > 1e-12, area, 1.0)[:, None]
    return unit, area


def panel_areas(panels: np.ndarray) -> np.ndarray:
    return panel_normals_areas(panels)[1]


def mesh_volume(panels: np.ndarray) -> float:
    """Enclosed volume by the divergence theorem, outward normals (the
    z=0 waterplane lid contributes zero): V = sum(z * n_z * dA)."""
    n, a = panel_normals_areas(panels)
    zc = panel_centroids(panels)[:, 2]
    return float((zc * n[:, 2] * a).sum())



def _iter_potmod_members(design: dict):
    """Yield (stations, diameters, rA, rB) for every heading-replicated
    potMod circular member — the shared selection/pose logic of
    :func:`mesh_design` and :func:`mesh_lid`."""
    from raft_tpu.io.schema import get_from_dict

    for mi in design["platform"]["members"]:
        if not mi.get("potMod", False):
            continue
        if str(mi["shape"])[0].lower() != "c":
            continue                      # rect members stay on the Morison path
        stations = np.asarray(mi["stations"], dtype=float)
        stations = stations - stations[0]
        d = np.asarray(mi["d"], dtype=float)
        if d.ndim == 0:
            d = np.full(len(stations), float(d))
        headings = np.atleast_1d(get_from_dict(mi, "heading", shape=-1, default=0.0))
        for h in headings:
            rA = np.asarray(mi["rA"], dtype=float)
            rB = np.asarray(mi["rB"], dtype=float)
            if h != 0.0:
                c, s = np.cos(np.deg2rad(h)), np.sin(np.deg2rad(h))
                rot = np.array([[c, s, 0.0], [-s, c, 0.0], [0.0, 0.0, 1.0]])
                rA, rB = rot @ rA, rot @ rB
            yield stations, d, rA, rB


def disk_panels(center, r_outer: float, da_max: float = 2.0, z: float = 0.0):
    """Horizontal disk fan at height ``z`` (adaptive ring counts) — used for
    interior waterplane lids in irregular-frequency removal."""
    rr = _cap_rings(r_outer, da_max)[::-1]             # axis -> rim
    naz = _naz_levels(rr, da_max)
    cx, cy = float(center[0]), float(center[1])

    def ring(i):
        n = naz[i]
        th = np.linspace(0.0, 2.0 * np.pi, n + 1)
        return np.stack(
            [cx + rr[i] * np.cos(th), cy + rr[i] * np.sin(th),
             np.full(n + 1, z)], axis=-1,
        )

    panels = []
    for i in range(len(rr) - 1):
        if rr[i + 1] == rr[i]:
            continue
        panels.extend(_band_panels(ring(i), ring(i + 1)))
    return np.concatenate(panels, axis=0)


def mesh_lid(design: dict, da_max: float = 2.0) -> np.ndarray:
    """Interior waterplane lid for every surface-piercing potMod circular
    member: the extended-boundary-integral surface that removes irregular
    frequencies from the native BEM solve (the reference's HAMS `irr`
    option, hams/pyhams.py:200,284).  Returns (n,4,3) panels at z=0."""
    lids = []
    for stations, d, rA, rB in _iter_potmod_members(design):
        if not (min(rA[2], rB[2]) < 0.0 <= max(rA[2], rB[2])):
            continue                                 # not surface-piercing
        t = (0.0 - rA[2]) / (rB[2] - rA[2])
        L = np.linalg.norm(rB - rA)
        r_wl = float(np.interp(t * L, stations, 0.5 * d))
        if r_wl <= 0:
            continue
        center = rA + t * (rB - rA)
        lids.append(disk_panels(center, r_wl, da_max=da_max))
    if not lids:
        return np.zeros((0, 4, 3))
    return np.concatenate(lids, axis=0)


class _MemberSolid:
    """Implicit solid of one circular member for interior-panel tests."""

    def __init__(self, stations, radii, rA, rB):
        self.rA = np.asarray(rA, dtype=float)
        axis = np.asarray(rB, dtype=float) - self.rA
        self.L = float(np.linalg.norm(axis))
        self.q = axis / self.L
        self.ts = np.asarray(stations, dtype=float)
        self.rs = np.asarray(radii, dtype=float)

    def contains(self, pts: np.ndarray, tol: float = 1e-3) -> np.ndarray:
        """True for points inside or on the member surface (within tol)."""
        rel = pts - self.rA
        t = rel @ self.q
        radial = np.linalg.norm(rel - t[:, None] * self.q[None, :], axis=-1)
        r_at = np.interp(t, self.ts, self.rs)
        return (t >= -tol) & (t <= self.L + tol) & (radial <= r_at + tol)


def trim_interior_panels(panel_groups, solids, tol: float = 1e-3) -> np.ndarray:
    """Drop panels lying inside (or on) ANOTHER member's solid.

    Members meshed independently overlap where they join (e.g. an upper
    column seated flush on a base column leaves two coincident interior
    disks at the interface).  Such interior surfaces are not wetted hull;
    left in, they pollute the radiation solve.  The reference mesher has no
    equivalent (it meshes members independently and never trims,
    raft/member2pnl.py:73-275) — interior trimming is required the moment
    the BEM actually runs, which the reference never does.
    """
    kept = []
    for gi, pans in enumerate(panel_groups):
        if len(pans) == 0:
            continue
        cent = panel_centroids(pans)
        interior = np.zeros(len(pans), dtype=bool)
        for si, solid in enumerate(solids):
            if si == gi:
                continue
            interior |= solid.contains(cent, tol=tol)
        kept.append(pans[~interior])
    if not kept:
        return np.zeros((0, 4, 3))
    return np.concatenate(kept, axis=0)


def mesh_design(design: dict, dz_max: float = 3.0, da_max: float = 2.0,
                trim: bool = True) -> np.ndarray:
    """Mesh every ``potMod`` circular member of a design dict
    (cf. FOWT.calcBEM, raft/raft.py:2016-2047).  Heading replication matches
    the member builder; panels interior to adjoining members are trimmed."""
    groups, solids = [], []
    for stations, d, rA, rB in _iter_potmod_members(design):
        groups.append(
            mesh_member(stations, d, rA, rB, dz_max=dz_max, da_max=da_max)
        )
        solids.append(_MemberSolid(stations, 0.5 * d, rA, rB))
    if not groups:
        return np.zeros((0, 4, 3))
    if trim:
        return trim_interior_panels(groups, solids)
    return np.concatenate(groups, axis=0)


# ------------------------------------------------------------- file output


def write_pnl(path: str, panels: np.ndarray, x_sym: int = 0, y_sym: int = 0):
    """HAMS hull-mesh file (cf. writeMesh, raft/member2pnl.py:279-305)."""
    verts = panels.reshape(-1, 3)
    # deduplicate vertices
    uniq, inv = np.unique(np.round(verts, 6), axis=0, return_inverse=True)
    conn = inv.reshape(-1, 4)
    with open(path, "w") as f:
        f.write("    --------------Hull Mesh File---------------\n\n")
        f.write("    # Number of Panels, Nodes, X-Symmetry and Y-Symmetry\n")
        f.write(f"    {len(conn):>8}    {len(uniq):>8}    {x_sym:>8}    {y_sym:>8}\n\n")
        f.write("    # Start Definition of Node Coordinates     ! node_number   x   y   z\n")
        for i, v in enumerate(uniq, 1):
            f.write(f"    {i:<8}{v[0]:>14.6f}{v[1]:>18.6f}{v[2]:>18.6f}\n")
        f.write("    # Start Definition of Node Relations   ! panel_number  number_of_vertices   Vertex1_ID   Vertex2_ID   Vertex3_ID   (Vertex4_ID)\n")
        for i, c in enumerate(conn, 1):
            ids = [int(x) + 1 for x in c]
            # drop any duplicated consecutive vertex (axis fans degenerate on
            # the first edge for cap A, the last for cap B)
            uniq_ids = [v for j, v in enumerate(ids) if v != ids[j - 1]]
            if len(uniq_ids) == 3:
                f.write(
                    f"    {i:<8}3    {uniq_ids[0]:>8}{uniq_ids[1]:>8}{uniq_ids[2]:>8}\n"
                )
            else:
                f.write(f"    {i:<8}4    {ids[0]:>8}{ids[1]:>8}{ids[2]:>8}{ids[3]:>8}\n")
        f.write("    --------------End Hull Mesh File---------------\n")


def write_gdf(path: str, panels: np.ndarray, ulen: float = 1.0, g: float = 9.80665):
    """WAMIT low-order .gdf file (cf. writeMeshToGDF, raft/member2pnl.py:496-509)."""
    with open(path, "w") as f:
        f.write("gdf mesh written by raft_tpu\n")
        f.write(f"{ulen:>10.4f}{g:>10.5f}\n")
        f.write("0  0\n")
        f.write(f"{len(panels)}\n")
        for p in panels:
            for v in p:
                f.write(f"{v[0]:>14.6f}{v[1]:>14.6f}{v[2]:>14.6f}\n")


def read_pnl(path: str) -> np.ndarray:
    """Read a HAMS .pnl mesh back into an (np,4,3) panel array."""
    with open(path) as f:
        lines = [ln.strip() for ln in f.readlines()]
    counts = None
    i = 0
    for i, ln in enumerate(lines):
        if ln.startswith("#") and "Number of Panels" in ln:
            counts = [int(x) for x in lines[i + 1].split()]
            break
    if counts is None:
        raise ValueError(f"{path}: no panel-count header found")
    n_pan, n_node = counts[0], counts[1]
    nodes = np.zeros((n_node, 3))
    j = i + 2
    seen = 0
    while seen < n_node:
        parts = lines[j].split()
        j += 1
        if len(parts) == 4 and not lines[j - 1].startswith("#"):
            nodes[int(parts[0]) - 1] = [float(parts[1]), float(parts[2]), float(parts[3])]
            seen += 1
    panels = np.zeros((n_pan, 4, 3))
    seen = 0
    while seen < n_pan:
        parts = lines[j].split()
        j += 1
        if not parts or lines[j - 1].startswith("#") or lines[j - 1].startswith("-"):
            continue
        nv = int(parts[1])
        ids = [int(x) - 1 for x in parts[2 : 2 + nv]]
        if nv == 3:
            ids.append(ids[2])
        panels[int(parts[0]) - 1] = nodes[ids]
        seen += 1
    return panels
