"""Axisymmetric panel mesher for potential-flow (BEM) members.

Host-side preprocessing, the capability of the reference's ``member2pnl``
(raft/member2pnl.py:8-509) re-designed around plain (n,4,3) numpy panel
arrays instead of growing Python lists: build each ``potMod`` member's
wetted surface as a revolved station profile (sides + end caps), transform
by member pose, clip at the waterline, and emit HAMS ``.pnl`` / WAMIT
``.gdf`` files or hand the panels straight to the native BEM solver.

Panels are quads with vertices ordered so the normal points INTO the fluid
(outward from the body); triangles are stored as degenerate quads (last
vertex repeated), the convention both HAMS and WAMIT accept.
"""
from __future__ import annotations

import numpy as np


def _profile(stations: np.ndarray, radii: np.ndarray, dz_max: float):
    """Refine a station profile so no axial span exceeds dz_max."""
    zs, rs = [float(stations[0])], [float(radii[0])]
    for i in range(1, len(stations)):
        dz = stations[i] - stations[i - 1]
        if dz <= 0:
            # radius jump at equal station: keep both points (vertical flange)
            zs.append(float(stations[i]))
            rs.append(float(radii[i]))
            continue
        n = max(1, int(np.ceil(dz / dz_max)))
        for j in range(1, n + 1):
            f = j / n
            zs.append(float(stations[i - 1] + f * dz))
            rs.append(float(radii[i - 1] + f * (radii[i] - radii[i - 1])))
    return np.array(zs), np.array(rs)


def _cap_rings(r_outer: float, da_max: float):
    """Radii for end-cap rings from r_outer down toward the axis."""
    if r_outer <= 0:
        return np.array([0.0])
    n = max(1, int(np.ceil(r_outer / da_max)))
    return np.linspace(r_outer, 0.0, n + 1)


def mesh_member(
    stations,
    diameters,
    rA,
    rB,
    dz_max: float = 3.0,
    da_max: float = 2.0,
    endA: bool = True,
    endB: bool = True,
) -> np.ndarray:
    """Mesh one circular member: returns (np, 4, 3) panel vertex array.

    ``stations`` are along-axis positions (member frame, 0 at end A),
    ``diameters`` the matching outer diameters; ``rA``/``rB`` the global end
    positions.  Sides are revolved quads; flat end caps are ring/triangle
    fans (cf. the reference's radial end fill, raft/member2pnl.py:149-165).
    """
    stations = np.asarray(stations, dtype=float)
    diameters = np.asarray(diameters, dtype=float)
    rA = np.asarray(rA, dtype=float)
    rB = np.asarray(rB, dtype=float)

    zs, rs = _profile(stations, 0.5 * diameters, dz_max)
    r_max = rs.max()
    naz = max(8, int(np.ceil(2.0 * np.pi * r_max / da_max)))
    th = np.linspace(0.0, 2.0 * np.pi, naz + 1)
    cos, sin = np.cos(th), np.sin(th)

    panels = []

    def ring(r, z):
        return np.stack([r * cos, r * sin, np.full(naz + 1, z)], axis=-1)  # (naz+1,3)

    def band(ringA, ringB, flip=False):
        """Quads between two rings; vertex order sets the normal."""
        a0, a1 = ringA[:-1], ringA[1:]
        b0, b1 = ringB[:-1], ringB[1:]
        quad = np.stack([a0, a1, b1, b0], axis=1)          # (naz,4,3)
        if flip:
            quad = quad[:, ::-1, :]
        panels.append(quad)

    # sides: outward normal for increasing z profile (A low, B high in local
    # frame; the pose rotation below handles the rest)
    for i in range(len(zs) - 1):
        if zs[i + 1] <= zs[i] and rs[i + 1] == rs[i]:
            continue
        rA_ring = ring(rs[i], zs[i])
        rB_ring = ring(rs[i + 1], zs[i + 1])
        band(rA_ring, rB_ring, flip=False)

    # end caps: A faces -z (local), B faces +z
    if endA and rs[0] > 0:
        rr = _cap_rings(rs[0], da_max)
        for i in range(len(rr) - 1):
            band(ring(rr[i + 1], zs[0]), ring(rr[i], zs[0]), flip=False)
    if endB and rs[-1] > 0:
        rr = _cap_rings(rs[-1], da_max)
        for i in range(len(rr) - 1):
            band(ring(rr[i], zs[-1]), ring(rr[i + 1], zs[-1]), flip=False)

    pans = np.concatenate(panels, axis=0)

    # pose: local +z axis -> member axis q
    axis = rB - rA
    L = np.linalg.norm(axis)
    q = axis / L
    # scale local z from profile coordinate (already along-axis length)
    z_hat = np.array([0.0, 0.0, 1.0])
    v = np.cross(z_hat, q)
    c = float(np.dot(z_hat, q))
    if np.linalg.norm(v) < 1e-12:
        R = np.eye(3) if c > 0 else np.diag([1.0, -1.0, -1.0])
    else:
        vx = np.array([[0, -v[2], v[1]], [v[2], 0, -v[0]], [-v[1], v[0], 0]])
        R = np.eye(3) + vx + vx @ vx * ((1 - c) / (np.linalg.norm(v) ** 2))
    pans = pans @ R.T + rA

    return clip_waterline(pans)


def clip_waterline(panels: np.ndarray, z_surface: float = 0.0) -> np.ndarray:
    """Drop panels fully above the surface; clamp crossing vertices to z=0
    (the reference's makePanel clip, raft/member2pnl.py:8-35).  Panels left
    with zero area (all vertices clamped) are removed."""
    z = panels[..., 2]
    keep = (z < z_surface - 1e-9).any(axis=1)
    pans = panels[keep].copy()
    pans[..., 2] = np.minimum(pans[..., 2], z_surface)
    area = panel_areas(pans)
    return pans[area > 1e-10]


def panel_centroids(panels: np.ndarray) -> np.ndarray:
    return panels.mean(axis=1)


def panel_normals_areas(panels: np.ndarray):
    """Normals (unit) and areas of quad panels via the cross-diagonal rule."""
    d1 = panels[:, 2] - panels[:, 0]
    d2 = panels[:, 3] - panels[:, 1]
    n = 0.5 * np.cross(d1, d2)
    area = np.linalg.norm(n, axis=-1)
    unit = n / np.where(area > 1e-12, area, 1.0)[:, None]
    return unit, area


def panel_areas(panels: np.ndarray) -> np.ndarray:
    return panel_normals_areas(panels)[1]


def mesh_volume(panels: np.ndarray) -> float:
    """Enclosed volume by the divergence theorem, outward normals (the
    z=0 waterplane lid contributes zero): V = sum(z * n_z * dA)."""
    n, a = panel_normals_areas(panels)
    zc = panel_centroids(panels)[:, 2]
    return float((zc * n[:, 2] * a).sum())


def mesh_design(design: dict, dz_max: float = 3.0, da_max: float = 2.0) -> np.ndarray:
    """Mesh every ``potMod`` circular member of a design dict
    (cf. FOWT.calcBEM, raft/raft.py:2016-2047).  Heading replication matches
    the member builder."""
    from raft_tpu.io.schema import get_from_dict

    allp = []
    for mi in design["platform"]["members"]:
        if not mi.get("potMod", False):
            continue
        if str(mi["shape"])[0].lower() != "c":
            continue                      # rect members stay on the Morison path
        stations = np.asarray(mi["stations"], dtype=float)
        stations = stations - stations[0]
        d = np.asarray(mi["d"], dtype=float)
        if d.ndim == 0:
            d = np.full(len(stations), float(d))
        headings = np.atleast_1d(get_from_dict(mi, "heading", shape=-1, default=0.0))
        for h in headings:
            rA = np.asarray(mi["rA"], dtype=float)
            rB = np.asarray(mi["rB"], dtype=float)
            if h != 0.0:
                c, s = np.cos(np.deg2rad(h)), np.sin(np.deg2rad(h))
                rot = np.array([[c, s, 0.0], [-s, c, 0.0], [0.0, 0.0, 1.0]])
                rA, rB = rot @ rA, rot @ rB
            allp.append(
                mesh_member(stations, d, rA, rB, dz_max=dz_max, da_max=da_max)
            )
    if not allp:
        return np.zeros((0, 4, 3))
    return np.concatenate(allp, axis=0)


# ------------------------------------------------------------- file output


def write_pnl(path: str, panels: np.ndarray, x_sym: int = 0, y_sym: int = 0):
    """HAMS hull-mesh file (cf. writeMesh, raft/member2pnl.py:279-305)."""
    verts = panels.reshape(-1, 3)
    # deduplicate vertices
    uniq, inv = np.unique(np.round(verts, 6), axis=0, return_inverse=True)
    conn = inv.reshape(-1, 4)
    with open(path, "w") as f:
        f.write("    --------------Hull Mesh File---------------\n\n")
        f.write("    # Number of Panels, Nodes, X-Symmetry and Y-Symmetry\n")
        f.write(f"    {len(conn):>8}    {len(uniq):>8}    {x_sym:>8}    {y_sym:>8}\n\n")
        f.write("    # Start Definition of Node Coordinates     ! node_number   x   y   z\n")
        for i, v in enumerate(uniq, 1):
            f.write(f"    {i:<8}{v[0]:>14.6f}{v[1]:>18.6f}{v[2]:>18.6f}\n")
        f.write("    # Start Definition of Node Relations   ! panel_number  number_of_vertices   Vertex1_ID   Vertex2_ID   Vertex3_ID   (Vertex4_ID)\n")
        for i, c in enumerate(conn, 1):
            ids = [int(x) + 1 for x in c]
            # drop any duplicated consecutive vertex (axis fans degenerate on
            # the first edge for cap A, the last for cap B)
            uniq_ids = [v for j, v in enumerate(ids) if v != ids[j - 1]]
            if len(uniq_ids) == 3:
                f.write(
                    f"    {i:<8}3    {uniq_ids[0]:>8}{uniq_ids[1]:>8}{uniq_ids[2]:>8}\n"
                )
            else:
                f.write(f"    {i:<8}4    {ids[0]:>8}{ids[1]:>8}{ids[2]:>8}{ids[3]:>8}\n")
        f.write("    --------------End Hull Mesh File---------------\n")


def write_gdf(path: str, panels: np.ndarray, ulen: float = 1.0, g: float = 9.80665):
    """WAMIT low-order .gdf file (cf. writeMeshToGDF, raft/member2pnl.py:496-509)."""
    with open(path, "w") as f:
        f.write("gdf mesh written by raft_tpu\n")
        f.write(f"{ulen:>10.4f}{g:>10.5f}\n")
        f.write("0  0\n")
        f.write(f"{len(panels)}\n")
        for p in panels:
            for v in p:
                f.write(f"{v[0]:>14.6f}{v[1]:>14.6f}{v[2]:>14.6f}\n")


def read_pnl(path: str) -> np.ndarray:
    """Read a HAMS .pnl mesh back into an (np,4,3) panel array."""
    with open(path) as f:
        lines = [ln.strip() for ln in f.readlines()]
    counts = None
    i = 0
    for i, ln in enumerate(lines):
        if ln.startswith("#") and "Number of Panels" in ln:
            counts = [int(x) for x in lines[i + 1].split()]
            break
    if counts is None:
        raise ValueError(f"{path}: no panel-count header found")
    n_pan, n_node = counts[0], counts[1]
    nodes = np.zeros((n_node, 3))
    j = i + 2
    seen = 0
    while seen < n_node:
        parts = lines[j].split()
        j += 1
        if len(parts) == 4 and not lines[j - 1].startswith("#"):
            nodes[int(parts[0]) - 1] = [float(parts[1]), float(parts[2]), float(parts[3])]
            seen += 1
    panels = np.zeros((n_pan, 4, 3))
    seen = 0
    while seen < n_pan:
        parts = lines[j].split()
        j += 1
        if not parts or lines[j - 1].startswith("#") or lines[j - 1].startswith("-"):
            continue
        nv = int(parts[1])
        ids = [int(x) - 1 for x in parts[2 : 2 + nv]]
        if nv == 3:
            ids.append(ids[2])
        panels[int(parts[0]) - 1] = nodes[ids]
        seen += 1
    return panels
