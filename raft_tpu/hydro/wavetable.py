"""Host-side f64 build of the wave-integral smooth-part tables.

The on-device BEM (:mod:`raft_tpu.hydro.jax_bem`) needs the dimensionless
principal-value wave integrals

    I0(X, Y) = PV Int_0^inf e^{uY} J0(uX) / (u-1) du        (Y <= 0)

and its J1 counterpart I1 at every panel pair — the free-surface part of
the deep-water Green function (native/bem.cpp's ``WaveTable``).  Direct
evaluation reduces to Phi(zeta) = e^zeta [E1(zeta) + i pi] on zeta =
Y + i X sin(theta), but the E1 power series suffers catastrophic
cancellation for |zeta| beyond a few — fine in the native solver's f64,
numerically unusable in the f32 blocks the TPU kernel runs in.  So the
device kernel follows the native solver's own Delhommeau-table strategy:
this module evaluates the integrals ONCE, on host, in f64 numpy, over a
2-D grid of (X, log(1-Y)), stores the SMOOTH parts (the -ln rho / 1/rho
singular closed forms subtracted, exactly as the native table does), and
the device interpolates bilinearly in f32 — the table values are O(1) and
smooth, so f32 interpolation costs ~1e-6, not the ~all of it the raw
series would.

The table is design- and frequency-independent (one artifact per machine,
like the native solver's ``wavetable_v1.bin``): it is content-keyed by
the build parameters and cached as an npz next to the other cache layers
(atomic publish, corruption-tolerant load — the ChunkStore rules).
"""
# graftlint: disable-file=GL105 — deliberate f64: this is the host-side
# oracle-precision precompute; nothing here is jit-reachable, and the
# arrays are downcast at the device staging boundary (jax_bem._stage_table).
from __future__ import annotations

import os
import threading

import numpy as np

#: version tag folded into the cache key AND into every jax_bem AOT key —
#: bump on any change to the build math or the grid semantics
TABLE_VERSION = "jaxwt-v1"

XMAX = 60.0                      # X grid: uniform [0, XMAX]
SMAX = float(np.log(1.0 + 60.0))  # s = log(1 - Y) grid: uniform [0, SMAX]
NX = 900
NS = 200

_EULER = 0.5772156649015329

_lock = threading.Lock()
_memo: dict = {}


# ------------------------------------------------------------ closed forms

def sing_i0(X, Y):
    """Singular part of I0 near the origin: -ln(rho)."""
    return -0.5 * np.log(X * X + Y * Y)


def sing_i1(X, Y):
    """Singular part of I1: -C1 + X/rho^2, C1 = (1/X)(1 - (-Y)/rho)."""
    r2 = X * X + Y * Y
    with np.errstate(divide="ignore", invalid="ignore"):
        C1 = np.where(X > 1e-12, (1.0 / np.where(X > 1e-12, X, 1.0))
                      * (1.0 - (-Y) / np.sqrt(r2)), 0.0)
    return -C1 + X / r2


# -------------------------------------------------------------- Phi(zeta)

def phi_pv(z: np.ndarray) -> np.ndarray:
    """Vectorized Phi(zeta) = e^zeta [E1(zeta) + i pi], Im zeta >= 0.

    Power series for |z| <= 22 (principal log = the PV convention on the
    negative-real cut), asymptotic e^{-z}/z series beyond — the exact
    branch structure of native/bem.cpp::phi_pv, vectorized.
    """
    z = np.asarray(z, dtype=np.complex128)
    az = np.abs(z)
    z = np.where(az < 1e-14, -1e-14 + 0.0j, z)
    az = np.abs(z)
    out = np.empty_like(z)

    small = az <= 22.0
    if small.any():
        zs = z[small]
        term = np.ones_like(zs)
        ssum = np.zeros_like(zs)
        for n in range(1, 221):
            term = term * (-zs) / n
            add = -term / n
            ssum += add
            if n > 4 and np.all(np.abs(add) < 1e-17 * (1.0 + np.abs(ssum))):
                break
        E1 = -_EULER - np.log(zs) + ssum
        out[small] = np.exp(zs) * (E1 + 1j * np.pi)

    big = ~small
    if big.any():
        zb = z[big]
        # e^z E1(z) ~ (1/z) sum (-1)^n n! / z^n; for |z| > 22 the first 20
        # terms are strictly decreasing, so the truncate-at-smallest-term
        # rule of the native code reduces to a plain 20-term sum
        acc = np.zeros_like(zb)
        zp = 1.0 / zb
        fact = 1.0
        for n in range(20):
            acc += (fact if n % 2 == 0 else -fact) * zp
            zp = zp / zb
            fact *= n + 1
        out[big] = acc + np.exp(zb) * (1j * np.pi)
    return out


def analytic_i(X, Y):
    """Exact (I0, I1) via the theta reduction — vectorized f64 port of
    native/bem.cpp::analytic_I (64-pt Gauss-Legendre per pi/m segment,
    m = 1 + int(X/20) segments to resolve cos(X sin theta))."""
    X = np.asarray(X, dtype=np.float64).ravel()
    Y = np.asarray(Y, dtype=np.float64).ravel()
    gx, gw = np.polynomial.legendre.leggauss(64)
    i0 = np.zeros_like(X)
    dI0_dX = np.zeros_like(X)
    m_all = 1 + (X / 20.0).astype(int)
    for m in np.unique(m_all):
        sel = m_all == m
        Xs, Ys = X[sel], Y[sel]
        acc0 = np.zeros_like(Xs)
        accX = np.zeros_like(Xs)
        for p in range(m):
            a = np.pi * p / m
            b = np.pi * (p + 1) / m
            th = 0.5 * (a + b) + 0.5 * (b - a) * gx          # (64,)
            wgt = gw * 0.5 * (b - a)
            s = np.sin(th)
            zeta = Ys[:, None] + 1j * Xs[:, None] * s[None, :]
            Phi = phi_pv(zeta)
            acc0 += (wgt[None, :] * Phi.real).sum(axis=1)
            dPhi = -1.0 / np.where(np.abs(zeta) < 1e-14, -1e-14 + 0j,
                                   zeta) + Phi
            accX += (wgt[None, :] * (dPhi * (1j * s[None, :])).real
                     ).sum(axis=1)
        i0[sel] = acc0 / np.pi
        dI0_dX[sel] = accX / np.pi
    rr = np.sqrt(X * X + Y * Y)
    with np.errstate(divide="ignore", invalid="ignore"):
        C1 = np.where(X > 1e-9, (1.0 / np.where(X > 1e-9, X, 1.0))
                      * (1.0 - (-Y) / rr), 0.0)
    i1 = np.where(X > 1e-9, -C1 - dI0_dX, 0.0)
    return i0, i1


# ----------------------------------------------------------------- tables

def _params_key() -> str:
    return f"{TABLE_VERSION}-{NX}x{NS}-{XMAX:g}-{SMAX:.6f}"


def _cache_path() -> str:
    # same root-resolution contract as the native result cache: follow a
    # RAFT_TPU_CACHE_DIR relocation, fall back to the per-user default
    # even when the warm-start layers are off (the table is exact solver
    # input, so reuse is bit-identical)
    from raft_tpu.cache import config as _cfg

    root = _cfg.cache_dir() or _cfg.resolve_dir()
    base = (os.path.join(root, "wavetable") if root is not None
            else os.path.expanduser("~/.cache/raft_tpu/wavetable"))
    return os.path.join(base, _params_key() + ".npz")


def _build() -> dict:
    """Evaluate the smooth parts over the full (X, s) grid — a one-time
    ~20 s f64 numpy pass on one core, chunked to bound memory."""
    X1 = XMAX * np.arange(NX) / (NX - 1)
    s1 = SMAX * np.arange(NS) / (NS - 1)
    Y1 = 1.0 - np.exp(s1)                              # 0 .. -60
    Xg, Yg = np.meshgrid(X1, Y1, indexing="ij")        # (NX, NS)
    Xf, Yf = Xg.ravel().copy(), Yg.ravel().copy()
    Yf[0] = -1e-6                                      # avoid X=Y=0 corner
    t0 = np.empty_like(Xf)
    t1 = np.empty_like(Xf)
    chunk = 4096
    for lo in range(0, len(Xf), chunk):
        hi = min(lo + chunk, len(Xf))
        a0, a1 = analytic_i(Xf[lo:hi], Yf[lo:hi])
        t0[lo:hi] = a0 - sing_i0(Xf[lo:hi], Yf[lo:hi])
        t1[lo:hi] = a1 - sing_i1(Xf[lo:hi], Yf[lo:hi])
    return {
        "I0": t0.reshape(NX, NS), "I1": t1.reshape(NX, NS),
        "meta": np.array([NX, NS, XMAX, SMAX], dtype=np.float64),
    }


def load_tables() -> dict:
    """The smooth-part tables, from the in-process memo, the disk cache,
    or a fresh build — through the SHARED corruption-tolerant result
    cache (:func:`raft_tpu.hydro.native_bem.result_cache_load` /
    ``result_cache_store``: atomic tmp+os.replace publish, and a torn or
    garbage artifact counts ``bem.cache_corrupt`` and is deleted and
    rebuilt, never served)."""
    from raft_tpu.hydro.native_bem import (result_cache_load,
                                           result_cache_store)

    key = _params_key()
    with _lock:
        hit = _memo.get(key)
        if hit is not None:
            return hit
        path = _cache_path()
        tab = result_cache_load(path, ("I0", "I1", "meta"))
        if tab is not None and (int(tab["meta"][0]),
                                int(tab["meta"][1])) != (NX, NS):
            tab = None          # params key collision: rebuild in place
        if tab is None:
            from raft_tpu.utils.profiling import phase

            with phase("bem/wavetable_build"):
                tab = _build()
            result_cache_store(path, tab)
        _memo[key] = tab
        return tab


# ------------------------------------------------- finite-depth fit (host)

def dispersion(nu: float, h: float) -> float:
    """k0 with k0 tanh(k0 h) = nu (Newton, the native iteration)."""
    k = np.sqrt(nu / h) if nu * h < 1.0 else nu
    for _ in range(100):
        t = np.tanh(k * h)
        c = np.cosh(k * h)
        f = k * t - nu
        df = t + k * h / (c * c)
        dk = f / df
        k -= dk
        if abs(dk) < 1e-15 * (k + 1e-300):
            break
    return float(k)


FD_NL = 46          # exponential-fit terms (native FDGreen::NL)


def fd_fit(nu: float, h: float) -> dict | None:
    """Per-frequency finite-depth Green-function fit — the f64 host port
    of native/bem.cpp::FDGreen::setup.  Returns None outside the active
    regime (h <= 0, nu <= 0, or k0 h >= 10: deep water).

    The fit depends only on (nu, h) — never on geometry — so it stays on
    host at oracle precision and feeds the device kernel as plain input
    arrays (lam/a/k0/A0 per frequency)."""
    if h <= 0 or nu <= 0:
        return None
    k0 = dispersion(nu, h)
    if k0 * h >= 10.0:
        return None
    e2 = np.exp(-2.0 * k0 * h)
    A0 = (k0 + nu) / (2.0 * (1.0 - e2 + 2.0 * h * (k0 + nu) * e2))
    NSs = 1200
    mumax = 20.0 * max(k0, 1.0 / h)
    t = np.arange(NSs) / (NSs - 1)
    mu = mumax * t * t
    ref = max(k0, 1.0)
    mu = np.where(np.abs(mu - k0) < 1e-9 * ref, mu + 1e-6 * ref, mu)
    F = (mu + nu) / (2.0 * ((mu - nu) - (mu + nu) * np.exp(-2.0 * mu * h)))
    y = 2.0 * F - 1.0 - 2.0 * A0 / (mu - k0)
    lmin = min(h, 1.0 / k0) / 50.0
    lmax = 50.0 / (mumax / 20.0)
    lam = lmin * (lmax / lmin) ** (np.arange(FD_NL) / (FD_NL - 1))
    B = np.exp(-mu[:, None] * lam[None, :])            # (NS, NL)
    coln = np.sqrt((B * B).sum(axis=0))
    Bs = B / coln[None, :]
    M = Bs.T @ Bs + 1e-10 * np.eye(FD_NL)
    rhs = Bs.T @ y
    a = np.linalg.solve(M, rhs) / coln
    return {"k0": float(k0), "A0": float(A0), "lam": lam, "a": a}


def fd_fit_grid(w: np.ndarray, depth: float, g: float) -> dict:
    """Stack per-frequency fits into kernel input arrays.

    Returns dict of (nw,)-leading f64 arrays: ``active`` (1.0 where the
    finite-depth path applies), ``k0``/``A0``/``kw`` and the (nw, NL)
    ``lam``/``a`` fit (zeros where inactive — the kernel selects per
    frequency).  ``kw`` is the incident wavenumber: k0 when active, the
    deep nu = w^2/g otherwise."""
    w = np.asarray(w, dtype=np.float64)
    nw = len(w)
    out = {
        "active": np.zeros(nw), "k0": np.zeros(nw), "A0": np.zeros(nw),
        "lam": np.ones((nw, FD_NL)), "a": np.zeros((nw, FD_NL)),
        "kw": np.zeros(nw),
    }
    for i, om in enumerate(w):
        nu = float(om * om / g)
        fit = fd_fit(nu, depth) if depth and depth > 0 else None
        if fit is None:
            out["kw"][i] = nu
        else:
            out["active"][i] = 1.0
            out["k0"][i] = fit["k0"]
            out["A0"][i] = fit["A0"]
            out["lam"][i] = fit["lam"]
            out["a"][i] = fit["a"]
            out["kw"][i] = fit["k0"]
    return out
