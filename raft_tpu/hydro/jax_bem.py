"""On-device differentiable BEM: the batched JAX port of native/bem.cpp.

The native C++ panel solver (the f64 oracle, 1072 lines) is the last big
host-side island: every *novel* geometry pays a serial host solve
(~10.7 s ``setup_bem_stage`` on the captured TPU bench) while the warm
device path runs in half a second.  This module is the same Hess & Smith
constant-source panel method as batched JAX ops over (panels x panels),
mapped over frequencies, so BEM throughput scales with chips instead of
host cores — and, because every step is plain ``jnp``, ``jax.grad`` flows
from panel geometry through A/B/F into the fused RAO solve (true geometry
-> response co-design, which the staged-coefficient boundary in
:mod:`raft_tpu.parallel.optimize` could never offer).

Method (the native solver's, restructured for a vector machine):

* **Green function.** Deep water: G = 1/r + 1/r1 + 2k[I0 - i pi e^Y J0]
  with the PV wave integrals I0/I1 read from the host-built smooth-part
  tables (:mod:`raft_tpu.hydro.wavetable`, bilinear in f32) plus the
  singular closed forms; pairs with rho = |(X, Y)| < ``R_NEAR`` use a
  direct 16-node theta quadrature with a short (cancellation-free, so
  f32-safe) E1 series instead — the same near/table split as the native
  ``WaveTable::eval``.  Finite depth: the 4-image Delhommeau
  decomposition with the per-frequency exponential fit done ON HOST in
  f64 (:func:`wavetable.fd_fit_grid` — it depends only on (w, depth),
  never on geometry) and fed to the kernel as plain arrays.
* **Rankine parts.** The 1/r (and free-surface-image) panel integrals
  use the native midpoint-subdivision rule (ns in {1,3,6,12,24} by
  distance/diagonal ratio) evaluated as a masked scan over the union of
  all subdivision points: each scan step is one (n, n) broadcast op, so
  the working set stays O(n^2) regardless of subdivision depth.  The
  self term is the exact flat-polygon formula.
* **Solve.** One complex system per frequency with 6 + n_headings RHS
  columns (factor once, back-substitute per heading — the native
  heading-grid contract), carried as the real 2n x 2n block form (the
  TPU backend has no complex dtype) and LU-factored ONCE in f32 with
  ``N_REFINE`` iterative-refinement steps; the refinement residual is
  returned per frequency so the f32-vs-f64-oracle parity claim is
  measured, not assumed.  The solve carries a ``custom_vjp`` (implicit
  function theorem: the adjoint re-uses the same refined solver on the
  transposed system), so gradients never differentiate through the LU
  internals.
* **Padding.** Panel counts round UP to the ``panels`` axis of the
  bucket ladder (:mod:`raft_tpu.build.buckets`): padded slots are
  degenerate zero-area panels with explicit mask columns/rows, so any
  mesh of a size class shares one compiled executable — mesh shapes
  cannot explode the executable count, and a *novel* geometry on a warm
  executable pays only the device solve.

Parity contract: on every shipped design mesh (deep + finite depth,
scalar heading + heading grid, with and without an irregular-frequency
lid) the f32 device solve matches the native f64 oracle within
``PARITY_RTOL`` scale-relative (tests/test_jax_bem.py pins it; the
``bem-smoke`` CI job proves it cross-process with g++ poisoned).

Mode selection: the key-salted ``RAFT_TPU_BEM`` knob (``native`` |
``jax`` | ``auto``; auto = jax exactly when the default backend is a
TPU), folded into every AOT key via ``cache.aot._solver_salts`` so a
mode flip can never be served stale staged artifacts.
"""
from __future__ import annotations

import functools
import logging
import os
import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core import bessel
from raft_tpu.core.cplx import Cx
from raft_tpu.core.linalg6 import (
    LU_BLOCK,
    lu_factor_blocked,
    lu_factor_unblocked,
    lu_solve_blocked,
    lu_solve_unblocked,
)
from raft_tpu.hydro import wavetable

log = logging.getLogger(__name__)

Array = jnp.ndarray

ENV_VAR = "RAFT_TPU_BEM"

#: assembly-route knob: ``xla`` | ``pallas`` | ``auto`` (pallas iff TPU)
ASSEMBLY_ENV = "RAFT_TPU_BEM_ASSEMBLY"

#: assembly-precision knob: ``f32`` (default) | ``bf16`` (bf16 assembly
#: feeding the f32 factor+refine; the f64 host oracle is untouched)
PRECISION_ENV = "RAFT_TPU_BEM_PRECISION"

#: kernel version, folded into AOT keys and the result-cache key — bump on
#: any numerical change so warm artifacts can never go stale silently
#: (v2: fused Rankine collapse, blocked panel LU, chunked frequency vmap —
#: same math, different summation association, so results move at roundoff)
KERNEL_VERSION = "jaxbem-v2"

#: f32 LU refinement steps (the "f32 blocks with iterative refinement"
#: contract); 2 steps bring the solve residual to f32 roundoff for the
#: diagonally-dominant (-2 pi I + D) panel systems
N_REFINE = 2

#: below this rho = |(X, Y)| the wave integrals use the direct quadrature
#: (short-series Phi, f32-safe) instead of the bilinear table
R_NEAR = 0.6

#: documented parity tolerance vs the native f64 oracle: max |jax - native|
#: over max |native|, per output (A, B, F), on the shipped design meshes
PARITY_RTOL = 3e-3


def parity_err(got, ref) -> float:
    """The ``PARITY_RTOL`` metric: max |got - ref| / max |ref|,
    scale-relative per output — componentwise ratios would compare noise
    to noise in the unexcited symmetric DOFs.  THE definition shared by
    the tests, the smoke, and the bench (it must not drift)."""
    got, ref = np.asarray(got), np.asarray(ref)
    return float(np.abs(got - ref).max() / max(np.abs(ref).max(), 1e-30))

_PI = float(np.pi)
_TWO_PI = float(2.0 * np.pi)

#: subdivision levels of the native Rankine integration (ns x ns midpoint)
_LEVELS = (1, 3, 6, 12, 24)


# ------------------------------------------------------------- mode knob

_mode_lock = threading.Lock()
_mode_warned = False

# cache-off jit memo: without the warm-start registry every call would
# re-wrap (and so retrace) a fresh functools.partial; one jitted callable
# per static signature keeps the seed-semantics path honest AND cheap.
# Single-flight under the lock (GL302).
_jit_lock = threading.Lock()
_jit_memo: dict = {}


def _jit_for(key, make):
    with _jit_lock:
        f = _jit_memo.get(key)
        if f is None:
            f = _jit_memo[key] = jax.jit(make())
        return f


def bem_mode(env: str | None = None) -> str:
    """The ``RAFT_TPU_BEM`` knob: ``native`` | ``jax`` | ``auto``.

    Unset or empty -> ``auto``; a malformed value degrades to ``auto``
    with a one-time warning (the ``RAFT_TPU_PALLAS`` empty-knob rule).
    """
    global _mode_warned
    raw = os.environ.get(ENV_VAR, "") if env is None else env
    val = raw.strip().lower()
    if val in ("", "auto"):
        return "auto"
    if val in ("native", "jax"):
        return val
    with _mode_lock:
        if not _mode_warned:
            _mode_warned = True
            log.warning(
                "%s=%r is not one of native|jax|auto; using auto",
                ENV_VAR, raw)
    return "auto"


def resolved_mode(mode: str | None = None) -> str:
    """``native`` or ``jax`` after resolving ``auto`` (jax exactly when
    the default backend is a TPU — the on-device path is what the chip
    buys; on CPU the OpenMP f64 native solver stays the measured
    default).

    An explicit ``mode`` of ``native``/``jax`` forces the route; an
    explicit ``auto`` (``Model(BEM="auto")``) DEFERS to the
    ``RAFT_TPU_BEM`` env knob first — so the registered, key-salted
    operator override works on every Model, not only those built with
    ``mode=None`` — and only then falls back to the backend rule."""
    m = bem_mode() if mode is None else bem_mode(env=mode)
    if m == "auto" and mode is not None:
        m = bem_mode()          # explicit 'auto': the env knob decides
    if m != "auto":
        return m
    try:
        backend = jax.default_backend()
    except Exception:       # backend not initializable: host-only context
        backend = "cpu"
    return "jax" if backend == "tpu" else "native"


# ------------------------------------------- assembly route + precision

_assembly_warned = False
_precision_warned = False


def assembly_mode(env: str | None = None) -> str:
    """The ``RAFT_TPU_BEM_ASSEMBLY`` knob: ``xla`` | ``pallas`` |
    ``auto`` (unset/empty; malformed degrades to auto with a one-time
    warning — the ``RAFT_TPU_BEM`` empty-knob rule)."""
    global _assembly_warned
    raw = os.environ.get(ASSEMBLY_ENV, "") if env is None else env
    val = raw.strip().lower()
    if val in ("", "auto"):
        return "auto"
    if val in ("xla", "pallas"):
        return val
    with _mode_lock:
        if not _assembly_warned:
            _assembly_warned = True
            log.warning("%s=%r is not one of xla|pallas|auto; using auto",
                        ASSEMBLY_ENV, raw)
    return "auto"


def resolved_assembly(mode: str | None = None) -> str:
    """``xla`` or ``pallas`` after resolving ``auto`` (pallas exactly
    when the default backend is a TPU — on CPU the tiled kernels would
    run in interpreter mode, slower than XLA; tests/smoke opt in
    explicitly).  An explicit ``mode`` forces the route; an explicit
    ``auto`` defers to the env knob first (the :func:`resolved_mode`
    override contract)."""
    m = assembly_mode() if mode is None else assembly_mode(env=mode)
    if m == "auto" and mode is not None:
        m = assembly_mode()
    if m != "auto":
        return m
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    return "pallas" if backend == "tpu" else "xla"


def bem_precision(env: str | None = None) -> str:
    """The ``RAFT_TPU_BEM_PRECISION`` knob: ``f32`` (default) | ``bf16``.

    ``bf16`` runs the influence-matrix ASSEMBLY (Rankine quadrature +
    wave part, either route) in bfloat16 while the 2n x 2n factor, the
    refinement loop and the RHS stay f32 — the iterative-refinement
    residual histogram (``bem.refine_resid``) is the live guardrail on
    what the cheaper assembly costs.  The f64 host oracle never sees
    this knob.  Malformed values degrade to ``f32`` with a one-time
    warning."""
    global _precision_warned
    raw = os.environ.get(PRECISION_ENV, "") if env is None else env
    val = raw.strip().lower()
    if val in ("", "f32", "float32"):
        return "f32"
    if val in ("bf16", "bfloat16"):
        return "bf16"
    with _mode_lock:
        if not _precision_warned:
            _precision_warned = True
            log.warning("%s=%r is not one of f32|bf16; using f32",
                        PRECISION_ENV, raw)
    return "f32"


# -------------------------------------------------------- panel bucketing

def pad_panel_count(n_total: int) -> int:
    """Smallest ``panels`` ladder class admitting ``n_total`` — the
    bucket-ladder idiom (:mod:`raft_tpu.build.buckets`) applied to the
    BEM matrix dimension, so mesh sizes collapse to a handful of padded
    signatures and one warm executable serves any mesh of its class."""
    from raft_tpu.build import buckets

    return buckets.round_up(int(n_total), "panels")


# ----------------------------------------------------------- device: table

def _stage_table(dtype):
    """Host tables -> device arrays at the kernel dtype."""
    tab = wavetable.load_tables()
    return {"I0": jnp.asarray(tab["I0"], dtype),
            "I1": jnp.asarray(tab["I1"], dtype)}


# stored f32: these close over jit-traced code as jaxpr consts, and the
# zero-f64 budget (rightly) counts captured f64 arrays; the kernel dtype
# cast upcasts them for f64 oracle runs (coordinate rounding ~1e-8 is far
# below every quadrature tolerance here)
_GL16_X, _GL16_W = (a.astype(np.float32)
                    for a in np.polynomial.legendre.leggauss(16))
_N_SERIES = 12           # E1 terms: |zeta| <= R_NEAR -> < 1e-9 truncation


def _phi_near(zr, zi):
    """Phi(zeta) = e^zeta [E1(zeta) + i pi] and dPhi = -1/zeta + Phi for
    SMALL |zeta| (cancellation-free short series; callers clamp zeta to
    the near region first, double-where style)."""
    az2 = zr * zr + zi * zi
    az2 = jnp.maximum(az2, 1e-14)            # zeta ~ 0: native's -1e-14 nudge
    log_re = 0.5 * jnp.log(az2)
    log_im = jnp.arctan2(zi, zr)
    # series sum_{n>=1} -(-z)^n / (n n!)
    tr, ti = -zr, -zi                        # term = (-z)
    sr, si = -tr, -ti
    for n in range(2, _N_SERIES + 1):
        tr, ti = (tr * (-zr) - ti * (-zi)) / n, (tr * (-zi) + ti * (-zr)) / n
        sr = sr - tr / n
        si = si - ti / n
    e1r = -0.5772156649015329 - log_re + sr
    e1i = -log_im + si
    ez = jnp.exp(zr)
    cr, ci = jnp.cos(zi), jnp.sin(zi)
    phr = ez * (cr * e1r - ci * (e1i + _PI))
    phi = ez * (cr * (e1i + _PI) + ci * e1r)
    inv = 1.0 / az2
    dphr = phr - zr * inv                    # -1/z = -conj(z)/|z|^2
    dphi = phi + zi * inv
    return phr, phi, dphr, dphi


def _near_integrals(X, Y, nodes=None):
    """(I0, I1) by direct theta quadrature — valid (and f32-safe) for
    rho = |(X, Y)| <= R_NEAR; callers select with the near mask.

    ``nodes``: optional (x, w) Gauss-Legendre node arrays — the Pallas
    kernels thread them through as operands (a kernel may not capture
    constant arrays); default is the module-level 16-point rule."""
    def body(carry, node):
        acc0, accX = carry
        x, wgt = node
        th = 0.5 * _PI + 0.5 * _PI * x
        s = jnp.sin(th)
        w = wgt * 0.5 * _PI
        phr, _phi, dphr, dphi = _phi_near(Y, X * s)
        acc0 = acc0 + w * phr
        # Re(dPhi * i s) = -s * Im(dPhi)
        accX = accX - w * s * dphi
        return (acc0, accX), None

    if nodes is None:
        nodes = (jnp.asarray(_GL16_X, X.dtype), jnp.asarray(_GL16_W, X.dtype))
    (acc0, accX), _ = lax.scan(body, (jnp.zeros_like(X), jnp.zeros_like(X)),
                               nodes)
    i0 = acc0 / _PI
    dI0_dX = accX / _PI
    rr = jnp.sqrt(jnp.maximum(X * X + Y * Y, 1e-14))
    xs = jnp.where(X > 1e-9, X, 1.0)
    C1 = jnp.where(X > 1e-9, (1.0 / xs) * (1.0 - (-Y) / rr), 0.0)
    i1 = jnp.where(X > 1e-9, -C1 - dI0_dX, 0.0)
    return i0, i1


def _sing_i0(X, Y):
    return -0.5 * jnp.log(jnp.maximum(X * X + Y * Y, 1e-30))


def _sing_i1(X, Y):
    r2 = jnp.maximum(X * X + Y * Y, 1e-30)
    xs = jnp.where(X > 1e-9, X, 1.0)
    C1 = jnp.where(X > 1e-9, (1.0 / xs) * (1.0 - (-Y) / jnp.sqrt(r2)), 0.0)
    return -C1 + X / r2


def eval_wave_integrals(X, Y, tab):
    """(I0, I1) at any X >= 0, Y <= 0 — near quadrature / bilinear table /
    far-field Bessel / deep closed form, the native ``WaveTable::eval``
    region split, fully differentiable."""
    dtype = X.dtype
    NXm1, NSm1 = wavetable.NX - 1, wavetable.NS - 1
    rho = jnp.sqrt(X * X + Y * Y + 1e-18)
    near = rho < R_NEAR
    # near branch (evaluated densely; clamped to a harmless point outside
    # the region so the series/log stay finite — double-where)
    Xn = jnp.where(near, X, 0.1)
    Yn = jnp.where(near, Y, -0.1)
    i0_near, i1_near = _near_integrals(Xn, Yn, nodes=tab.get("nodes"))
    # table branch
    s = jnp.log1p(-Y)
    fx = jnp.clip(X, 0.0, wavetable.XMAX) / (wavetable.XMAX / NXm1)
    ix = jnp.clip(fx.astype(jnp.int32), 0, NXm1 - 1)
    tx = fx - ix.astype(dtype)
    fs = jnp.clip(s, 0.0, wavetable.SMAX) / (wavetable.SMAX / NSm1)
    is_ = jnp.clip(fs.astype(jnp.int32), 0, NSm1 - 1)
    ts = fs - is_.astype(dtype)

    def lerp(T):
        a = T[ix, is_]
        b = T[ix + 1, is_]
        c = T[ix, is_ + 1]
        d = T[ix + 1, is_ + 1]
        return (1 - tx) * ((1 - ts) * a + ts * c) + tx * ((1 - ts) * b
                                                         + ts * d)

    i0_tab = lerp(tab["I0"]) + _sing_i0(X, Y)
    i1_tab = lerp(tab["I1"]) + _sing_i1(X, Y)
    # far-field X >= XMAX: pole-dominated asymptotics
    eY = jnp.exp(Y)
    Xf = jnp.maximum(X, 1.0)
    i0_far = -_PI * eY * bessel.y0(Xf)
    i1_far = -_PI * eY * bessel.y1(Xf)
    # very deep (s >= SMAX): leading 1/k term
    rr = jnp.maximum(rho, 1e-30)
    i0_deep = -1.0 / rr
    xs = jnp.where(X > 1e-9, X, 1.0)
    i1_deep = jnp.where(X > 1e-9, -(1.0 / xs) * (1.0 - (-Y) / rr), 0.0)

    far = X >= wavetable.XMAX * (1.0 - 1e-7)
    deep = s >= wavetable.SMAX * (1.0 - 1e-7)
    i0 = jnp.where(near, i0_near,
                   jnp.where(far, i0_far,
                             jnp.where(deep, i0_deep, i0_tab)))
    i1 = jnp.where(near, i1_near,
                   jnp.where(far, i1_far,
                             jnp.where(deep, i1_deep, i1_tab)))
    return i0, i1


# -------------------------------------------------------- device: geometry

def _safe_norm(x, axis=-1):
    """sqrt(sum x^2 + tiny): NaN-free gradients at the zero vectors the
    degenerate padding panels (and pair diagonals) produce — d|x|/dx at 0
    is 0 here instead of 0/0.  The +1e-20 floor (|x| >= 1e-10) is far
    below any physical panel scale and above f32 subnormals."""
    return jnp.sqrt(jnp.sum(x * x, axis=axis) + 1e-20)


def panel_geometry(pans):
    """Centroids, unit normals, areas, characteristic diagonals of an
    (n, 4, 3) panel array — the native ``panel_setup`` (cross-diagonal
    rule; degenerate zero-area padding panels get zero normals, which
    makes their matrix rows/columns inert by construction)."""
    d1 = pans[:, 2] - pans[:, 0]
    d2 = pans[:, 3] - pans[:, 1]
    c = pans.mean(axis=1)
    nvec = 0.5 * jnp.cross(d1, d2)
    area = _safe_norm(nvec)
    inv = jnp.where(area > 1e-9, 1.0 / jnp.where(area > 1e-9, area, 1.0),
                    0.0)
    nrm = nvec * inv[:, None]
    diag = jnp.maximum(_safe_norm(d1), _safe_norm(d2))
    return c, nrm, area, diag


def self_potential(pans, c, nrm):
    """Exact Int 1/r dS over each flat panel, field point at its centroid
    (native ``self_rankine_potential``)."""
    tot = jnp.zeros(pans.shape[0], pans.dtype)
    for e in range(4):
        a = pans[:, e]
        b = pans[:, (e + 1) % 4]
        ab = b - a
        s = _safe_norm(ab)
        ok = s > 1e-9
        s_safe = jnp.where(ok, s, 1.0)
        ca = a - c
        cb = b - c
        ra = _safe_norm(ca)
        rb = _safe_norm(cb)
        cr = jnp.cross(ca, ab)
        d = jnp.einsum("nk,nk->n", cr, nrm) / s_safe
        num = ra + rb + s
        den = jnp.maximum(ra + rb - s, 1e-12)
        tot = tot + jnp.where(ok, d * jnp.log(num / den), 0.0)
    return jnp.abs(tot)


def _quad_points(levels):
    """Host constants: the union of all ns x ns midpoint subdivision
    points for the given levels — (u, v, weight-fraction, level-id)."""
    us, vs, wf, lev = [], [], [], []
    for ns in levels:
        lid = _LEVELS.index(ns)
        for iu in range(ns):
            for iv in range(ns):
                us.append((iu + 0.5) / ns)
                vs.append((iv + 0.5) / ns)
                wf.append(1.0 / (ns * ns))
                lev.append(lid)
    return (np.asarray(us, dtype=np.float32), np.asarray(vs, dtype=np.float32),
            np.asarray(wf, dtype=np.float32), np.asarray(lev, dtype=np.int32))


_QUAD_MAIN = _quad_points((1, 3, 6, 12))       # direct + image levels
_QUAD_FINE = _quad_points((24,))               # image-only near-surface level


def _level_select_direct(rel):
    """Native direct-integral subdivision choice: rel < 1 -> ns=12,
    < 2 -> 6, < 6 -> 3, else 1 (as level ids into ``_LEVELS``)."""
    out = jnp.where(rel < 6.0, jnp.int32(1), jnp.int32(0))
    out = jnp.where(rel < 2.0, jnp.int32(2), out)
    return jnp.where(rel < 1.0, jnp.int32(3), out)


def _level_select_image(rel):
    """Native image-integral choice: an extra ns=24 level below 0.5
    (waterline panels nearly coincide with their own images)."""
    out = jnp.where(rel < 6.0, jnp.int32(1), jnp.int32(0))
    out = jnp.where(rel < 2.0, jnp.int32(2), out)
    out = jnp.where(rel < 1.0, jnp.int32(3), out)
    return jnp.where(rel < 0.5, jnp.int32(4), out)


def rankine_parts(pans, c, nrm, area, diag, panel_mask, lid_surface):
    """Direct + free-surface-image Rankine integrals for every pair:
    returns (pot_d, grad_d, pot_i, grad_i) with pot (n, n) and grad
    (n, n, 3) w.r.t. the field point; diagonals carry the exact self
    potential (direct always, image only for lid panels at z = 0)."""
    n = pans.shape[0]
    dtype = pans.dtype
    dist = _safe_norm(c[:, None, :] - c[None, :, :])
    cI = c * jnp.asarray([1.0, 1.0, -1.0], dtype)
    distI = _safe_norm(c[:, None, :] - cI[None, :, :])
    diag_safe = jnp.where(diag > 1e-9, diag, 1.0)
    rel = jnp.where(diag > 1e-9, dist / diag_safe[None, :], 1e9)
    relI = jnp.where(diag > 1e-9, distI / diag_safe[None, :], 1e9)
    # native ns choice: direct rel<1 -> 12, <2 -> 6, <6 -> 3, else 1;
    # image relI<0.5 -> 24, <1 -> 12, <2 -> 6, <6 -> 3, else 1
    sel_d = _level_select_direct(rel)
    sel_i = _level_select_image(relI)
    eye = jnp.eye(n, dtype=bool)
    # diagonal: direct self term is exact (sentinel -1 drops it from the
    # scan); the image diagonal stays numeric EXCEPT for lid panels at
    # z=0, whose image coincides with the panel itself
    sel_d = jnp.where(eye, -1, sel_d)
    sel_i = jnp.where(eye & lid_surface[None, :], -1, sel_i)

    def accumulate(quad, want_direct: bool):
        us, vs, wf, lev = (jnp.asarray(a) for a in quad)

        def body(carry, x):
            pot_d, grad_d, pot_i, grad_i = carry
            u, v, w_frac, lv = x
            pt = ((1 - u) * (1 - v) * pans[:, 0] + u * (1 - v) * pans[:, 1]
                  + u * v * pans[:, 2] + (1 - u) * v * pans[:, 3])
            dA = area * w_frac

            def contrib(ptz, sel):
                d = c[:, None, :] - ptz[None, :, :]
                r2 = jnp.einsum("ijk,ijk->ij", d, d)
                ok = (sel == lv) & (r2 > 1e-12)
                r2s = jnp.where(ok, r2, 1.0)
                ir = 1.0 / jnp.sqrt(r2s)
                ir3 = ir / r2s
                pot = jnp.where(ok, dA[None, :] * ir, 0.0)
                g = jnp.where(ok, -dA[None, :] * ir3, 0.0)[:, :, None] * d
                return pot, g

            if want_direct:
                p, gq = contrib(pt, sel_d)
                pot_d = pot_d + p
                grad_d = grad_d + gq
            ptI = pt * jnp.asarray([1.0, 1.0, -1.0], dtype)
            p, gq = contrib(ptI, sel_i)
            pot_i = pot_i + p
            grad_i = grad_i + gq
            return (pot_d, grad_d, pot_i, grad_i), None

        return body, (us.astype(dtype), vs.astype(dtype),
                      wf.astype(dtype), lev)

    zero2 = jnp.zeros((n, n), dtype)
    zero3 = jnp.zeros((n, n, 3), dtype)
    body_m, xs_m = accumulate(_QUAD_MAIN, want_direct=True)
    carry, _ = lax.scan(jax.checkpoint(body_m),
                        (zero2, zero3, zero2, zero3), xs_m)
    body_f, xs_f = accumulate(_QUAD_FINE, want_direct=False)
    carry, _ = lax.scan(jax.checkpoint(body_f), carry, xs_f)
    pot_d, grad_d, pot_i, grad_i = carry

    self_pot = self_potential(pans, c, nrm)
    eyef = jnp.eye(n, dtype=dtype)
    pot_d = pot_d + eyef * self_pot[None, :]
    pot_i = pot_i + eyef * jnp.where(lid_surface, self_pot, 0.0)[None, :]
    # padded (masked-out) source columns contribute nothing
    colm = panel_mask[None, :].astype(dtype)
    return (pot_d * colm, grad_d * colm[:, :, None],
            pot_i * colm, grad_i * colm[:, :, None])


# ------------------------------------------------------- device: wave part

def _wave_deep(k, R, dx, dy, v, area_j, diag_lid, tab):
    """Deep-water free-surface wave part at centroids (native
    ``wave_part``): G (Cx) and its gradient components (Cx each) w.r.t.
    the field point.  ``diag_lid`` marks lid self pairs, which evaluate
    at the log-average radius R_eff = 0.4 sqrt(area)."""
    R_eff = 0.4 * jnp.sqrt(jnp.maximum(area_j, 1e-14))[None, :]
    R_use = jnp.where(diag_lid, R_eff, R)
    X = k * R_use
    Y = k * v
    i0, i1 = eval_wave_integrals(X, Y, tab)
    eY = jnp.exp(Y)
    J0 = bessel.j0(X)
    J1 = bessel.j1(X)
    G = Cx(2.0 * k * i0, 2.0 * k * (-_PI * eY * J0))
    rr = jnp.sqrt(R_use * R_use + v * v + 1e-20)
    dG_dv = Cx(2.0 * k * (1.0 / rr + k * i0), 2.0 * k * (-_PI * k * eY * J0))
    Rs = jnp.where(R_use > 1e-12, R_use, 1.0)
    C1 = jnp.where(R_use > 1e-12, (1.0 / Rs) * (1.0 - (-v) / rr), 0.0)
    dG_dR = Cx(2.0 * k * (-(C1 + k * i1)), 2.0 * k * (_PI * k * eY * J1))
    ux = jnp.where(diag_lid, 1.0, jnp.where(R > 1e-12, dx / jnp.where(
        R > 1e-12, R, 1.0), 0.0))
    uy = jnp.where(diag_lid, 0.0, jnp.where(R > 1e-12, dy / jnp.where(
        R > 1e-12, R, 1.0), 0.0))
    return G, dG_dR * ux, dG_dR * uy, dG_dv


def _wave_fd(k0, A0, lam, a_fit, h, R, dx, dy, zP, zQ, area_j, diag_lid,
             tab):
    """Finite-depth wave part (native ``FDGreen::eval``): the 4-image
    pole/exp-fit/radiated decomposition plus the seabed image, EXCLUDING
    1/r and 1/r1 (Rankine-integrated outside).  ``lam``/``a_fit`` are the
    host-f64 per-frequency exponential fit."""
    dtype = R.dtype
    R_eff = 0.4 * jnp.sqrt(jnp.maximum(area_j, 1e-14))[None, :]
    R_use = jnp.where(diag_lid, R_eff, R)
    d4 = jnp.stack([
        -(zP + zQ), 2.0 * h - (zP - zQ), 2.0 * h + (zP - zQ),
        4.0 * h + (zP + zQ),
    ])                                                     # (4, n, n)
    # image sign/gating vectors built from iota, not literal arrays —
    # this function also runs inside the Pallas wave kernel, and a
    # kernel may not capture constant arrays
    i4 = lax.broadcasted_iota(dtype, (4, 1, 1), 0)
    sgn = jnp.where(i4 < 2.0, -1.0, 1.0)
    img1 = jnp.where(i4 < 1.0, 0.0, 1.0)
    X = k0 * R_use
    J0 = bessel.j0(X)
    J1 = bessel.j1(X)
    # "1" parts (images 2..4) + seabed image
    rr2 = R_use[None] * R_use[None] + d4 * d4
    rr = jnp.sqrt(jnp.maximum(rr2, 1e-12))
    t3 = 1.0 / (jnp.maximum(rr2, 1e-12) * rr)
    gre = (img1 / rr).sum(0)
    gre_R = (img1 * (-R_use[None]) * t3).sum(0)
    gre_z = (img1 * (-d4) * t3 * sgn).sum(0)
    # pole parts: 2 A0 I0(k0 R, -k0 d_i) per image
    Y4 = -k0 * d4
    i0_4, i1_4 = eval_wave_integrals(jnp.broadcast_to(X, d4.shape), Y4, tab)
    rxy = jnp.sqrt(X * X + Y4 * Y4 + 1e-20)
    Xs = jnp.where(X > 1e-12, X, 1.0)
    C1 = jnp.where(X > 1e-12, (1.0 / Xs) * (1.0 - (-Y4) / rxy), 0.0)
    gre = gre + (2.0 * A0 * i0_4).sum(0)
    gre_R = gre_R + (2.0 * A0 * k0 * (-(C1 + i1_4))).sum(0)
    gre_z = gre_z + (2.0 * A0 * (-k0 * sgn) * (1.0 / rxy + i0_4)).sum(0)

    # exponential-fit part: scan over the 46 lambda terms
    def body(carry, x):
        g0, gR, gz = carry
        lam_j, a_j = x
        cc = d4 + lam_j
        rr2 = R_use[None] * R_use[None] + cc * cc
        rr = jnp.sqrt(jnp.maximum(rr2, 1e-12))
        t3 = a_j / (jnp.maximum(rr2, 1e-12) * rr)
        g0 = g0 + (a_j / rr).sum(0)
        gR = gR + (-R_use[None] * t3).sum(0)
        gz = gz + (-cc * t3 * sgn).sum(0)
        return (g0, gR, gz), None

    zero = jnp.zeros_like(R)
    (g0, gR, gz), _ = lax.scan(body, (zero, zero, zero), (lam, a_fit))
    gre, gre_R, gre_z = gre + g0, gre_R + gR, gre_z + gz
    # radiated-wave (imaginary) part
    e4 = jnp.exp(-k0 * d4)
    gim = (-_TWO_PI * A0 * e4 * J0[None]).sum(0)
    gim_R = (_TWO_PI * A0 * k0 * e4 * J1[None]).sum(0)
    gim_z = (_TWO_PI * A0 * k0 * sgn * e4 * J0[None]).sum(0)
    # seabed image 1/r2
    v2 = zP + zQ + 2.0 * h
    rr2 = R_use * R_use + v2 * v2
    rr = jnp.sqrt(jnp.maximum(rr2, 1e-12))
    t3 = 1.0 / (jnp.maximum(rr2, 1e-12) * rr)
    gre = gre + 1.0 / rr
    gre_R = gre_R - R_use * t3
    gre_z = gre_z - v2 * t3
    G = Cx(gre, gim)
    dG_dR = Cx(gre_R, gim_R)
    dG_dz = Cx(gre_z, gim_z)
    Rs = jnp.where(R_use > 1e-12, R_use, 1.0)
    ux = jnp.where(R_use > 1e-12, dx / Rs, 0.0)
    uy = jnp.where(R_use > 1e-12, dy / Rs, 0.0)
    return G, dG_dR * ux, dG_dR * uy, dG_dz


# ---------------------------------------------------- device: refined solve
#
# Pure-jnp partially-pivoted LU, NOT jax.scipy's lu_factor: on the CPU
# backend LAPACK lowers to a custom call whose serialized executable
# embeds a process-local function pointer — a warm process deserializing
# it from the AOT registry segfaults on first execution (measured on
# jaxlib 0.4.37; the same reason linalg6/eigen hand-roll their solves).
# Pure HLO serializes and round-trips on every backend.  The hot path is
# the BLOCKED right-looking factorization (raft_tpu.core.linalg6): the
# 2n-step rank-1 chain of the v1 row-by-row scan collapses to 2n / b
# panel+GEMM steps, which is what lets the 2n x 2n solve keep up with
# the tiled assembly instead of becoming the new serial bottleneck.  The
# row-by-row variant stays importable as the bit-level reference
# (tests/test_bem_tiles.py pins blocked == unblocked through pivoting).

# legacy aliases (v1 names, kept for external callers/tests)
_lu_factor_jnp = lu_factor_unblocked
_lu_solve_jnp = lu_solve_unblocked


@jax.custom_vjp
def _solve_refined(M2, B2):
    """f32 LU factor-once solve of M2 @ X = B2 (all RHS columns share the
    factorization) with N_REFINE iterative-refinement steps."""
    return _solve_refined_impl(M2, B2)


def _solve_refined_impl(M2, B2):
    LU, perm = lu_factor_blocked(M2, block=LU_BLOCK)
    x = lu_solve_blocked(LU, perm, B2, block=LU_BLOCK)
    for _ in range(N_REFINE):
        r = B2 - M2 @ x
        x = x + lu_solve_blocked(LU, perm, r, block=LU_BLOCK)
    return x


def _solve_refined_fwd(M2, B2):
    x = _solve_refined_impl(M2, B2)
    return x, (M2, x)


def _solve_refined_bwd(res, xbar):
    # implicit function theorem: M2 x = b  =>  lam = M2^-T xbar,
    # bbar = lam, Mbar = -lam x^T — the adjoint re-uses the SAME refined
    # solver, so backward accuracy matches forward
    M2, x = res
    lam = _solve_refined_impl(M2.T, xbar)
    return (-lam @ x.T, lam)


_solve_refined.defvjp(_solve_refined_fwd, _solve_refined_bwd)


# --------------------------------------------------------- the panel solve

def _freq_chunk(n: int, nw: int) -> int:
    """Static frequency-batch width of the chunked ``vmap``: how many
    2n x 2n systems (plus their assembly intermediates) ride one device
    dispatch.  Shrinks with the padded panel class so the per-chunk
    working set stays roughly constant (~a few hundred MB at f32); a
    deterministic function of static shapes, so it can never retrace a
    warm executable."""
    return max(1, min(nw, 8, 2048 // max(n, 1)))


def _rankine_fused(pans, c, nrm, area, diag, panel_mask, lid_surface):
    """XLA-route Rankine collapse: the eight pot/grad outputs of
    :func:`rankine_parts` reduced to the two matrices the combine
    consumes — ``R_pot = pot_d + pot_i`` and
    ``R_dn = (grad_d + grad_i) . n_i`` (the Pallas kernel emits the
    same pair straight from VMEM)."""
    pot_d, grad_d, pot_i, grad_i = rankine_parts(
        pans, c, nrm, area, diag, panel_mask, lid_surface)
    R_pot = pot_d + pot_i
    R_dn = ((grad_d[..., 0] + grad_i[..., 0]) * nrm[:, 0][:, None]
            + (grad_d[..., 1] + grad_i[..., 1]) * nrm[:, 1][:, None]
            + (grad_d[..., 2] + grad_i[..., 2]) * nrm[:, 2][:, None])
    return R_pot, R_dn


def solve_panels(pans, panel_mask, lid_mask, w, betas, fd, tab, *,
                 rho: float = 1025.0, g: float = 9.81, depth: float = 0.0,
                 finite_depth: bool = False, dtype=jnp.float32,
                 assembly: str | None = None, precision: str | None = None):
    """The traced core: padded panels -> (A, B, F, residual).

    Args (arrays; everything is cast to ``dtype``):
      pans        (n, 4, 3) padded panel vertices (hull, then lid, then
                  degenerate zero-area padding)
      panel_mask  (n,) 1.0 for real panels (hull + lid)
      lid_mask    (n,) 1.0 for interior-waterplane lid panels
      w           (nw,) angular frequencies
      betas       (nb,) wave headings [rad]
      fd          dict of per-frequency finite-depth fit arrays
                  (:func:`wavetable.fd_fit_grid`)
      tab         dict of wave-integral tables (:func:`_stage_table`)

    Static: ``rho``/``g``/``depth`` (baked scalars), ``finite_depth``
    (routes the per-frequency ``lax.cond`` between the deep and 4-image
    kernels), ``dtype``, plus the two route knobs — ``assembly``
    (``xla`` | ``pallas`` | ``auto``/None, resolved via the key-salted
    ``RAFT_TPU_BEM_ASSEMBLY``; non-tile-aligned panel counts always take
    the XLA route) and ``precision`` (``f32`` | ``bf16`` | None =
    ``RAFT_TPU_BEM_PRECISION``; bf16 runs the assembly stage only — the
    factor, refinement and RHS stay at ``dtype``).

    Frequencies are batched: ``one_freq`` is ``vmap``-ed over chunks of
    :func:`_freq_chunk` frequencies and ``lax.map``-ed over chunks (the
    v1 code mapped frequencies one at a time, leaving the device under-
    occupied at small panel counts).

    Returns ``(A, B, F, resid)``: A/B (nw, 6, 6) with [j, k] = force j
    per unit mode-k motion, F a :class:`Cx` (nw, nb, 6), and resid (nw,)
    the max relative linear-system residual after refinement (the
    measured f32-vs-oracle quality signal — and the live bf16 guardrail,
    exported as the ``bem.refine_resid`` histogram).
    """
    pans = jnp.asarray(pans, dtype)
    panel_mask = jnp.asarray(panel_mask, dtype)
    lid_mask = jnp.asarray(lid_mask, dtype)
    w = jnp.asarray(w, dtype)
    betas = jnp.asarray(betas, dtype)
    fd = {k: jnp.asarray(v, dtype) for k, v in fd.items()}
    tab = {k: jnp.asarray(v, dtype) for k, v in tab.items()}
    n = pans.shape[0]
    nb = betas.shape[0]

    from raft_tpu.core import pallas_bem

    route = resolved_assembly(assembly)
    if route == "pallas" and not pallas_bem.tile_ok(n):
        route = "xla"           # custom non-tile-aligned ladder class
    prec = bem_precision() if precision is None else bem_precision(
        env=precision)
    # assembly-stage dtype: bf16 applies only to the f32 device solve
    # (the f64 oracle path ignores the knob by construction).  Dict
    # lookup, not a ternary: `dtype` is a static Python dtype here, but
    # it is also a parameter of this jit-reachable function, and the
    # GL103 branch rule cannot tell those apart
    a_dtype = {True: jnp.bfloat16, False: dtype}[
        prec == "bf16" and dtype == jnp.float32]

    c, nrm, area, diag = panel_geometry(pans)
    hull_mask = panel_mask * (1.0 - lid_mask)
    # lid panels sitting AT z = 0 (their free-surface image is themselves)
    lid_surface = (lid_mask > 0.5) & (jnp.abs(c[:, 2]) < 1e-6
                                      * jnp.maximum(diag, 1e-9))

    c_a = c.astype(a_dtype)
    nrm_a = nrm.astype(a_dtype)
    area_a = area.astype(a_dtype)
    mask_a = panel_mask.astype(a_dtype)
    tab_a = {kk: v_.astype(a_dtype) for kk, v_ in tab.items()}

    if route == "pallas":
        self_pot = self_potential(pans, c, nrm)       # O(n), stays XLA
        R_pot, R_dn = pallas_bem.rankine_assembly(
            pans.astype(a_dtype), c_a, nrm_a, area_a,
            diag.astype(a_dtype), mask_a, lid_surface,
            self_pot.astype(a_dtype))
    else:
        R_pot, R_dn = _rankine_fused(
            pans.astype(a_dtype), c_a, nrm_a, area_a,
            diag.astype(a_dtype), mask_a, lid_surface)
        # pairwise wave-part geometry (the Pallas kernel derives these
        # per tile in VMEM; the XLA route materializes them once)
        dx = c_a[:, None, 0] - c_a[None, :, 0]
        dy = c_a[:, None, 1] - c_a[None, :, 1]
        R = jnp.sqrt(dx * dx + dy * dy + 1e-20)
        zP = jnp.broadcast_to(c_a[:, 2][:, None], (n, n))
        zQ = jnp.broadcast_to(c_a[:, 2][None, :], (n, n))
        v = zP + zQ
        eye = jnp.eye(n, dtype=bool)
        diag_lid = eye & lid_surface[None, :]

    nvec6 = jnp.concatenate([nrm, jnp.cross(c, nrm)], axis=1)   # (n, 6)
    dtyp = pans.dtype

    def one_freq(xs):
        om = xs["w"]
        k = om * om / g
        if route == "pallas":
            fd_scal = ({"k0": xs["k0"], "A0": xs["A0"],
                        "active": xs["active"], "lam": xs["lam"],
                        "a": xs["a"]} if finite_depth else None)
            S_re_a, S_im_a, Dn_re_a, Dn_im_a = pallas_bem.wave_assembly(
                R_pot, R_dn, c_a, nrm_a, area_a, mask_a, lid_surface,
                tab_a, k, fd_scal, finite_depth=finite_depth, depth=depth)
        else:
            k_a = k.astype(a_dtype)
            if finite_depth:
                def fd_branch(_):
                    return _wave_fd(
                        xs["k0"].astype(a_dtype), xs["A0"].astype(a_dtype),
                        xs["lam"].astype(a_dtype), xs["a"].astype(a_dtype),
                        depth, R, dx, dy, zP, zQ, area_a, diag_lid, tab_a)

                def deep_branch(_):
                    return _wave_deep(k_a, R, dx, dy, v, area_a, diag_lid,
                                      tab_a)

                G, gx, gy, gz = lax.cond(xs["active"] > 0.5, fd_branch,
                                         deep_branch, operand=None)
            else:
                G, gx, gy, gz = _wave_deep(k_a, R, dx, dy, v, area_a,
                                           diag_lid, tab_a)
            area_row = area_a[None, :]
            colm = mask_a[None, :]
            S_re_a = (R_pot + G.re * area_row) * colm
            S_im_a = (G.im * area_row) * colm
            proj_re = (gx.re * nrm_a[:, 0][:, None]
                       + gy.re * nrm_a[:, 1][:, None]
                       + gz.re * nrm_a[:, 2][:, None])
            proj_im = (gx.im * nrm_a[:, 0][:, None]
                       + gy.im * nrm_a[:, 1][:, None]
                       + gz.im * nrm_a[:, 2][:, None])
            Dn_re_a = (R_dn + proj_re * area_row) * colm
            Dn_im_a = (proj_im * area_row) * colm
        # assembly -> solve dtype boundary (bf16 mode upcasts HERE: the
        # factor + refinement always run at the solve dtype)
        S = Cx(S_re_a.astype(dtyp), S_im_a.astype(dtyp))
        Dn_re = Dn_re_a.astype(dtyp)
        Dn_im = Dn_im_a.astype(dtyp)
        eyef = jnp.eye(n, dtype=dtyp)
        M_re = Dn_re - _TWO_PI * eyef
        M_im = Dn_im
        lid_row = (lid_mask > 0.5)[:, None]
        M_re = jnp.where(lid_row, S.re, M_re)
        M_im = jnp.where(lid_row, S.im, M_im)

        # incident wave at centroids, per heading (nb, n)
        kw = xs["kw"]
        if finite_depth:
            zph = jnp.minimum(c[:, 2] + depth, depth)   # clamp padding
            e2h = jnp.exp(-2.0 * kw * depth)
            ez = jnp.exp(kw * jnp.minimum(c[:, 2], 0.0))
            ee = jnp.exp(-2.0 * kw * jnp.maximum(zph, 0.0))
            Zr = jnp.where(xs["active"] > 0.5,
                           ez * (1.0 + ee) / (1.0 + e2h), ez)
            Zs = jnp.where(xs["active"] > 0.5,
                           ez * (1.0 - ee) / (1.0 + e2h), ez)
        else:
            Zr = Zs = jnp.exp(kw * jnp.minimum(c[:, 2], 0.0))
        cb = jnp.cos(betas)[:, None]
        sb = jnp.sin(betas)[:, None]
        ang = -kw * (c[None, :, 0] * cb + c[None, :, 1] * sb)
        amp = (g / om) * Zr[None, :]
        ph = Cx(jnp.zeros_like(ang), amp) * Cx.expi(ang)      # (nb, n)
        ddx = ph * Cx(jnp.zeros_like(ang), -kw * jnp.broadcast_to(
            cb, ang.shape))
        ddy = ph * Cx(jnp.zeros_like(ang), -kw * jnp.broadcast_to(
            sb, ang.shape))
        ddz = Cx(jnp.zeros_like(ang), (g / om) * kw
                 * Zs[None, :]) * Cx.expi(ang)
        dn = (ddx * nrm[None, :, 0] + ddy * nrm[None, :, 1]
              + ddz * nrm[None, :, 2])                        # (nb, n)

        # RHS: 6 radiation columns + nb diffraction columns
        rad = nvec6 * hull_mask[:, None]                      # (n, 6)
        lid_col = lid_mask[None, :] > 0.5
        diff_re = jnp.where(lid_col, -ph.re, -dn.re) * panel_mask[None, :]
        diff_im = jnp.where(lid_col, -ph.im, -dn.im) * panel_mask[None, :]
        B_re = jnp.concatenate([rad, diff_re.T], axis=1)      # (n, m)
        B_im = jnp.concatenate([jnp.zeros_like(rad), diff_im.T], axis=1)

        M2 = jnp.block([[M_re, -M_im], [M_im, M_re]])
        B2 = jnp.concatenate([B_re, B_im], axis=0)
        x2 = _solve_refined(M2, B2)
        r2 = B2 - M2 @ x2
        resid = jnp.max(jnp.abs(r2)) / jnp.maximum(
            jnp.max(jnp.abs(B2)), 1e-30)
        xr, xi = x2[:n], x2[n:]

        # panel potentials phi = S sigma (all columns at once)
        P_re = S.re @ xr - S.im @ xi                          # (n, m)
        P_im = S.re @ xi + S.im @ xr
        Wn = nvec6 * (hull_mask * area)[:, None]              # (n, 6)
        acc_re = P_re[:, :6].T @ Wn                           # (kk, j)
        acc_im = P_im[:, :6].T @ Wn
        A6 = -rho * acc_re.T                                  # [j, kk]
        B6 = rho * om * acc_im.T
        phiS = Cx(P_re[:, 6:].T, P_im[:, 6:].T)               # (nb, n)
        tot = ph + phiS
        exc_re = tot.re @ Wn                                  # (nb, j)
        exc_im = tot.im @ Wn
        F_re = -rho * om * exc_im
        F_im = rho * om * exc_re
        return A6, B6, F_re, F_im, resid

    xs = {"w": w, "active": fd["active"], "k0": fd["k0"], "A0": fd["A0"],
          "lam": fd["lam"], "a": fd["a"], "kw": fd["kw"]}
    # chunked frequency batching: vmap one_freq over a VMEM-sized chunk,
    # lax.map over chunks (padded by repeating the last frequency — the
    # padded lanes are sliced off below, they just keep chunks uniform)
    nw = w.shape[0]
    chunk = _freq_chunk(n, nw)
    nck = -(-nw // chunk)
    pad = nck * chunk - nw
    if pad:
        xs = {kk: jnp.concatenate([v_, jnp.repeat(v_[-1:], pad, axis=0)])
              for kk, v_ in xs.items()}
    xs = {kk: v_.reshape((nck, chunk) + v_.shape[1:])
          for kk, v_ in xs.items()}
    outs = lax.map(jax.checkpoint(jax.vmap(one_freq)), xs)
    A6, B6, F_re, F_im, resid = (
        o.reshape((nck * chunk,) + o.shape[2:])[:nw] for o in outs)
    return A6, B6, Cx(F_re, F_im), resid


# ----------------------------------------------------------- host wrapper

def _pad_mesh(panels: np.ndarray, lid: np.ndarray | None):
    """Pad (hull, lid) to the ``panels`` ladder class with degenerate
    zero-area panels (all four vertices at the first hull centroid —
    zero normal/area makes every row and column inert; masks make it
    explicit).  Returns (padded, panel_mask, lid_mask)."""
    panels = np.asarray(panels, dtype=np.float64)  # graftlint: disable=GL105 — host staging, downcast at the device boundary
    n_h = len(panels)
    n_l = 0 if lid is None else len(lid)
    n_tot = n_h + n_l
    if n_tot == 0:
        raise ValueError("empty mesh")
    n_pad = pad_panel_count(n_tot)
    out = np.zeros((n_pad, 4, 3))
    out[:n_h] = panels
    if n_l:
        out[n_h:n_tot] = np.asarray(lid, dtype=np.float64)  # graftlint: disable=GL105 — host staging
    if n_pad > n_tot:
        out[n_tot:] = panels[0].mean(axis=0)[None, None, :]
    idx = np.arange(n_pad)
    panel_mask = (idx < n_tot).astype(float)
    lid_mask = ((idx >= n_h) & (idx < n_tot)).astype(float)
    return out, panel_mask, lid_mask


def solve_bem_jax(
    panels: np.ndarray,
    w: np.ndarray,
    rho: float = 1025.0,
    g: float = 9.81,
    beta=0.0,
    depth: float = 0.0,
    cache: bool = True,
    lid: np.ndarray | None = None,
    dtype=None,
    return_diagnostics: bool = False,
    assembly: str | None = None,
    precision: str | None = None,
):
    """On-device panel solve with the native ``solve_bem`` contract:
    returns (A[6, 6, nw], B[6, 6, nw], F) with F[6, nw] complex for a
    scalar heading or F[nb, 6, nw] for a grid — drop-in for the host
    solver at every staging site.

    The compiled executable is keyed ONLY by the padded shapes (+ salts),
    so a *novel* geometry on a warm process pays the device solve alone —
    no host C++ solve, no g++, no recompile.  With ``cache=True`` exact
    results are also content-cached on disk (same corruption-tolerant
    atomic-npz contract as the native result cache, shared helpers).
    """
    from raft_tpu import obs as _obs
    from raft_tpu.hydro import native_bem as _nat

    # host staging is deliberately f64 (the oracle contract of the native
    # wrapper); every array is downcast at the jnp.asarray(·, dtype) edge
    panels = np.ascontiguousarray(panels, dtype=np.float64)  # graftlint: disable=GL105 — host staging
    w_np = np.ascontiguousarray(np.atleast_1d(w), dtype=np.float64)  # graftlint: disable=GL105 — host staging
    scalar_beta = np.ndim(beta) == 0
    betas = np.ascontiguousarray(np.atleast_1d(beta), dtype=np.float64)  # graftlint: disable=GL105 — host staging
    depth_f = float(depth) if depth and depth > 0 else -1.0
    dtype = jnp.float32 if dtype is None else dtype
    # resolve the route knobs ONCE here so the result-cache key, the AOT
    # statics and the traced program all see the same values
    route = resolved_assembly(assembly)
    prec = bem_precision() if precision is None else bem_precision(
        env=precision)

    key = None
    if cache:
        key = _nat.result_cache_key(
            "bem-jax", panels, w_np, betas,
            (rho, g, depth_f, 0.0, float(0 if lid is None else len(lid))),
            salt=(KERNEL_VERSION, wavetable.TABLE_VERSION, N_REFINE,
                  str(jnp.dtype(dtype)), route, prec),
            extra_bytes=(np.asarray(lid, dtype=np.float64).tobytes()  # graftlint: disable=GL105 — content hashing
                         if lid is not None and len(lid) else b""),
        )
        hit = _nat.result_cache_load(key, ("A", "B", "F", "resid"))
        if hit is not None:
            A, B, F = hit["A"], hit["B"], hit["F"]
            out = (A, B, F[0] if scalar_beta else F)
            if not return_diagnostics:
                return out
            # same diagnostics contract as the miss path (callers index
            # unconditionally); the residual was measured at store time
            return out + (_diagnostics(
                cached=True, panels=panels, w_np=w_np, betas=betas,
                lid=lid, padded=pad_panel_count(
                    len(panels) + (0 if lid is None else len(lid))),
                resid_max=float(np.max(hit["resid"])),
                finite_depth=depth_f > 0, dtype=dtype),)

    padded, panel_mask, lid_mask = _pad_mesh(panels, lid)
    finite_depth = depth_f > 0
    fd = wavetable.fd_fit_grid(w_np, depth_f, g)
    tab = _stage_table(dtype)

    fn = functools.partial(
        solve_panels, rho=float(rho), g=float(g),
        depth=float(depth_f if finite_depth else 0.0),
        finite_depth=finite_depth, dtype=dtype,
        assembly=route, precision=prec)
    args = (
        jnp.asarray(padded, dtype), jnp.asarray(panel_mask, dtype),
        jnp.asarray(lid_mask, dtype), jnp.asarray(w_np, dtype),
        jnp.asarray(betas, dtype),
        {k: jnp.asarray(v_, dtype) for k, v_ in fd.items()}, tab,
    )
    from raft_tpu.cache import config as _cfg
    from raft_tpu.cache.aot import cached_callable
    from raft_tpu.obs import trace as _trace

    statics = (("kernel", KERNEL_VERSION),
               ("table", wavetable.TABLE_VERSION),
               ("refine", N_REFINE), ("rho", float(rho)), ("g", float(g)),
               ("depth", float(depth_f)), ("fd", bool(finite_depth)),
               ("dtype", str(jnp.dtype(dtype))),
               ("assembly", route), ("precision", prec))
    if _cfg.is_enabled():
        exe = cached_callable("jax_bem", fn, args, extra=statics)
    else:
        exe = _jit_for(
            (statics, len(padded), len(w_np), len(betas)), lambda: fn)
    import time as _time

    t0 = _time.perf_counter()
    with _trace.span("bem/jax_solve", attrs={"panels": int(len(panels)),
                                             "padded": int(len(padded)),
                                             "nw": int(len(w_np)),
                                             "headings": int(len(betas))}):
        A6, B6, F_cx, resid = exe(*args)
        A6, B6 = np.asarray(A6, float), np.asarray(B6, float)
        F = np.asarray(F_cx.re, float) + 1j * np.asarray(F_cx.im, float)
        resid = np.asarray(resid, float)
    dt = _time.perf_counter() - t0
    _obs.metrics.histogram("bem.jax_solve_s").observe(dt)
    # per-panel-bucket latency: one histogram per padded class, so the
    # ledger's per-(entry, bucket) rooflines have a live counterpart
    _obs.metrics.histogram(f"bem.solve_s[{len(padded)}]").observe(dt)
    _obs.metrics.histogram("bem.jax_residual").observe(float(resid.max()))
    # the refinement residual per frequency — the mixed-precision
    # (RAFT_TPU_BEM_PRECISION) guardrail as a live metric, not just a
    # bench scalar
    refine_h = _obs.metrics.histogram("bem.refine_resid")
    for r_ in resid:
        refine_h.observe(float(r_))

    A = A6.transpose(1, 2, 0)                       # (6, 6, nw)
    B = B6.transpose(1, 2, 0)
    F = F.transpose(1, 2, 0)                        # (nb, 6, nw)
    if cache and key is not None:
        _nat.result_cache_store(key, dict(A=A, B=B, F=F, resid=resid))
    out = (A, B, F[0] if scalar_beta else F)
    if return_diagnostics:
        return out + (_diagnostics(
            cached=False, panels=panels, w_np=w_np, betas=betas, lid=lid,
            padded=len(padded), resid_max=float(resid.max()),
            finite_depth=finite_depth, dtype=dtype),)
    return out


def _diagnostics(*, cached, panels, w_np, betas, lid, padded, resid_max,
                 finite_depth, dtype):
    """One diagnostics shape for BOTH the fresh-solve and cache-hit paths
    of :func:`solve_bem_jax` — callers index the keys unconditionally."""
    return {
        "cached": bool(cached),
        "panels": int(len(panels)),
        "padded": int(padded),
        "lid": int(0 if lid is None else len(lid)),
        "nw": int(len(w_np)),
        "headings": int(len(betas)),
        "refine_iters": int(N_REFINE),
        "max_residual": float(resid_max),
        "finite_depth": bool(finite_depth),
        "dtype": str(jnp.dtype(dtype)),
    }


def solve_bem_any(panels, w, rho=1025.0, g=9.81, beta=0.0, depth=0.0,
                  cache=True, lid=None, mode: str | None = None,
                  nthreads: int = 0):
    """The one BEM staging entry: routes to the native host solver or the
    on-device JAX solve per the (key-salted) ``RAFT_TPU_BEM`` knob.

    ``mode``: explicit override (``native`` | ``jax`` | ``auto``); None
    reads the environment.  Identical return contract either way."""
    m = resolved_mode(mode)
    if m == "jax":
        return solve_bem_jax(panels, w, rho=rho, g=g, beta=beta,
                             depth=depth, cache=cache, lid=lid)
    from raft_tpu.hydro.native_bem import solve_bem

    return solve_bem(panels, w, rho=rho, g=g, beta=beta, depth=depth,
                     cache=cache, lid=lid, nthreads=nthreads)


# -------------------------------------------- differentiable geometry hook

def make_bem_fn(panels, w, *, rho=1025.0, g=9.81, depth=0.0, beta=0.0,
                lid=None, warp_fn=None, dtype=None):
    """Build ``theta -> (A[nw,6,6], B[nw,6,6], F Cx[nw,6])`` — the
    differentiable geometry->coefficients hook for
    :func:`raft_tpu.parallel.optimize.optimize_design` (``bem_fn=``).

    ``warp_fn(padded_panels, theta) -> padded_panels`` is the (traceable)
    geometry parameterization; the default scales the hull radially about
    the z axis, the panel-mesh analog of ``scale_diameters``.  Degenerate
    padding panels stay degenerate under any pointwise warp, so the
    padding contract survives warping.  Gradients flow through panel
    geometry, influence assembly, and the refined solve into whatever
    objective consumes the staged coefficients — the co-design loop the
    staged-coefficient boundary could never close.
    """
    dtype = jnp.float32 if dtype is None else dtype
    padded, panel_mask, lid_mask = _pad_mesh(panels, lid)
    w_np = np.ascontiguousarray(np.atleast_1d(w), dtype=np.float64)  # graftlint: disable=GL105 — host staging
    depth_f = float(depth) if depth and depth > 0 else -1.0
    finite_depth = depth_f > 0
    fd = wavetable.fd_fit_grid(w_np, depth_f, g)
    tab = _stage_table(dtype)
    pans0 = jnp.asarray(padded, dtype)
    masks = (jnp.asarray(panel_mask, dtype), jnp.asarray(lid_mask, dtype))
    w_dev = jnp.asarray(w_np, dtype)
    betas = jnp.asarray([float(beta)], dtype)
    fd_dev = {k: jnp.asarray(v_, dtype) for k, v_ in fd.items()}

    if warp_fn is None:
        def warp_fn(p, theta):
            scale = jnp.concatenate([jnp.broadcast_to(theta, (2,)),
                                     jnp.ones((1,), p.dtype)])
            return p * scale[None, None, :]

    def bem_fn(theta):
        p = warp_fn(pans0, theta)
        # assembly pinned to the XLA route: the Pallas tiles carry no AD
        # rules, and this hook exists to be differentiated — the solve
        # adjoint (custom_vjp) is route-independent either way
        A6, B6, F_cx, _resid = solve_panels(
            p, masks[0], masks[1], w_dev, betas, fd_dev, tab,
            rho=float(rho), g=float(g),
            depth=float(depth_f if finite_depth else 0.0),
            finite_depth=finite_depth, dtype=dtype, assembly="xla")
        return A6, B6, F_cx[:, 0, :]

    return bem_fn
