"""BEM coefficient file IO: WAMIT-format readers, HAMS-format project files.

Host-side equivalents of the reference's ``hams/pyhams.py`` surface —
``read_wamit1``/``read_wamit3`` parsers (pyhams.py:292-359), project
scaffolding (pyhams.py:89-129), ``Hydrostatic.in``/``ControlFile.in``
writers (pyhams.py:131-289) and the Nemoh mesh converter (pyhams.py:7-86) —
plus what the reference leaves implicit: dimensionalization of the WAMIT
coefficients and interpolation onto the model's frequency grid, returning
arrays ready to stage as the ``Model(BEM=...)`` input.
"""
from __future__ import annotations

import os

import numpy as np


# ----------------------------------------------------------- WAMIT readers


def read_wamit1(path: str):
    """Read a WAMIT .1 file: returns (w, addedMass[6,6,nw], damping[6,6,nw]).

    Coefficients are WAMIT-nondimensional (A' = A/(rho L^k),
    B' = B/(rho w L^k)); see :func:`dimensionalize`.
    """
    data = np.loadtxt(path)
    w = np.unique(data[:, 0])
    nw = len(w)
    A = data[:, 3].reshape(nw, 6, 6).transpose(1, 2, 0)
    B = data[:, 4].reshape(nw, 6, 6).transpose(1, 2, 0)
    return w, A, B


def read_wamit3(path: str, heading: float | None = None):
    """Read a WAMIT .3 excitation file, ALL headings.

    Returns (w, headings, mod, phase_deg, re, im).  With one heading in the
    file (or ``heading=`` selecting one) the arrays are [6, nw] — the
    reference reader's layout (hams/pyhams.py:325-359, which always keeps a
    single heading).  Multi-heading files return [nh, 6, nw] stacked in
    ``headings`` order.
    """
    data = np.loadtxt(path)
    w = np.unique(data[:, 0])
    headings = np.unique(data[:, 1])
    if heading is not None:
        i = int(np.argmin(np.abs(headings - heading)))
        if not np.isclose(headings[i], heading):
            raise ValueError(
                f"heading {heading} not in file (has {headings})"
            )
        data = data[np.isclose(data[:, 1], headings[i])]
        headings = headings[i : i + 1]
    nw, nh = len(w), len(headings)

    def grab(col):
        out = np.empty((nh, 6, nw))
        for ih, hd in enumerate(headings):
            rows = data[np.isclose(data[:, 1], hd)]
            out[ih] = rows[:, col].reshape(nw, 6).T
        return out[0] if nh == 1 else out

    return w, headings, grab(3), grab(4), grab(5), grab(6)


def read_wamit_hst(path: str):
    """Read a WAMIT .hst hydrostatic-stiffness file -> C'[6,6] (nondim)."""
    C = np.zeros((6, 6))
    for row in np.loadtxt(path):
        C[int(row[0]) - 1, int(row[1]) - 1] = row[2]
    return C


def dimensionalize(w, A_bar, B_bar, X_re_bar, X_im_bar, rho=1025.0, g=9.81, ulen=1.0):
    """WAMIT nondimensional -> SI, for ULEN=ulen.

    A_ij = rho ulen^k A'_ij ; B_ij = rho w ulen^k B'_ij ;
    X_i = rho g A ulen^m X'_i  (per unit wave amplitude).
    k = 3 for translation-translation, 4 cross, 5 rotation-rotation;
    m = 2 translation, 3 rotation.
    """
    k = np.zeros((6, 6))
    for i in range(6):
        for j in range(6):
            k[i, j] = 3 + (i >= 3) + (j >= 3)
    m = np.where(np.arange(6) < 3, 2.0, 3.0)
    A = rho * (ulen ** k)[:, :, None] * A_bar
    B = rho * (ulen ** k)[:, :, None] * B_bar * np.asarray(w)[None, None, :]
    scale = rho * g * (ulen ** m)[:, None]
    F = scale * (X_re_bar + 1j * X_im_bar)
    return A, B, F


def interp_to_grid(w_src, arr, w_dst):
    """Interpolate coefficient arrays (..., nw_src) onto w_dst.

    Raises ValueError if w_dst extends beyond the source grid (matching the
    contract pinned by the reference's Capytaine test,
    tests/test_capytaine_integration.py:31-34)."""
    w_src = np.asarray(w_src)
    w_dst = np.asarray(w_dst)
    if w_dst.min() < w_src.min() - 1e-9 or w_dst.max() > w_src.max() + 1e-9:
        raise ValueError(
            f"requested grid [{w_dst.min():.3f}, {w_dst.max():.3f}] outside "
            f"source data range [{w_src.min():.3f}, {w_src.max():.3f}]"
        )
    out = np.empty(arr.shape[:-1] + (len(w_dst),), dtype=arr.dtype)
    flat = arr.reshape(-1, arr.shape[-1])
    oflat = out.reshape(-1, len(w_dst))
    for i in range(flat.shape[0]):
        if np.iscomplexobj(arr):
            oflat[i] = np.interp(w_dst, w_src, flat[i].real) + 1j * np.interp(
                w_dst, w_src, flat[i].imag
            )
        else:
            oflat[i] = np.interp(w_dst, w_src, flat[i])
    return out


def load_wamit_coeffs(path1: str, path3: str, w_grid, rho=1025.0, g=9.81,
                      heading: float | None = None):
    """Read + dimensionalize + interpolate: returns (A, B, F) on w_grid,
    ready for ``Model(design, BEM=(A, B, F))``.  Multi-heading .3 files:
    pass ``heading`` (deg) to select one; default takes the first heading
    (the reference reader's behavior, hams/pyhams.py:325-359).

    When the warm-start cache is enabled (:func:`raft_tpu.cache.enable`)
    the staged (A, B, F) arrays are memoized on disk keyed by the WAMIT
    file CONTENTS + grid + heading, so a repeat process skips the parse
    and interpolation; editing either source file invalidates the entry.
    """
    from raft_tpu import cache as _cache

    def _compute():
        w1, A_bar, B_bar = read_wamit1(path1)
        w3, hds, _, _, re, im = read_wamit3(path3, heading=heading)
        if re.ndim == 3:                   # multi-heading, none selected
            re, im = re[0], im[0]
        A, B, F = dimensionalize(w1, A_bar, B_bar, re, im, rho=rho, g=g)
        if len(w1) != len(w3) or not np.allclose(w1, w3):
            F = interp_to_grid(w3, F, w1)
        return (
            interp_to_grid(w1, A, w_grid),
            interp_to_grid(w1, B, w_grid),
            interp_to_grid(w1, F, w_grid),
        )

    if not _cache.is_enabled():
        return _compute()
    return _cache.cached_arrays(
        "wamit_coeffs",
        (_cache.FileKey(path1), _cache.FileKey(path3),
         np.asarray(w_grid, dtype=float), float(rho), float(g),
         None if heading is None else float(heading)),
        _compute,
    )


def nondimensionalize(w, A, B, F, rho=1025.0, g=9.81, ulen=1.0):
    """SI -> WAMIT nondimensional (inverse of :func:`dimensionalize`)."""
    k = np.zeros((6, 6))
    for i in range(6):
        for j in range(6):
            k[i, j] = 3 + (i >= 3) + (j >= 3)
    m = np.where(np.arange(6) < 3, 2.0, 3.0)
    A_bar = np.asarray(A) / (rho * (ulen ** k)[:, :, None])
    B_bar = np.asarray(B) / (rho * (ulen ** k)[:, :, None] * np.asarray(w)[None, None, :])
    X_bar = np.asarray(F) / (rho * g * (ulen ** m)[:, None])
    return A_bar, B_bar, X_bar


def write_wamit1(path: str, w, A, B, rho=1025.0, g=9.81, ulen=1.0):
    """Write a WAMIT .1 added-mass/damping file from SI arrays
    (A[6,6,nw], B[6,6,nw]) — the format HAMS emits to
    Output/Wamit_format (cf. read_wamit1)."""
    A_bar, B_bar, _ = nondimensionalize(w, A, B, np.zeros((6, len(w))),
                                        rho=rho, g=g, ulen=ulen)
    with open(path, "w") as f:
        for iw, wv in enumerate(np.asarray(w)):
            for i in range(6):
                for j in range(6):
                    f.write(f" {wv:13.6E} {i+1:5d} {j+1:5d} "
                            f"{A_bar[i, j, iw]:13.6E} {B_bar[i, j, iw]:13.6E}\n")
    return path


def write_wamit3(path: str, w, F, rho=1025.0, g=9.81, ulen=1.0, heading=0.0):
    """Write a WAMIT .3 excitation file from SI excitation (complex, per
    unit wave amplitude): F[6,nw] with a scalar ``heading`` [deg], or
    F[nh,6,nw] with ``heading`` a matching grid of degrees."""
    F = np.asarray(F)
    if F.ndim == 2:
        F = F[None]
        headings = [float(heading)]
    else:
        headings = list(np.atleast_1d(heading).astype(float))
        if len(headings) != F.shape[0]:
            raise ValueError(f"{F.shape[0]} heading blocks, {len(headings)} headings")
    X_bars = [
        nondimensionalize(w, np.zeros((6, 6, len(w))), np.ones((6, 6, len(w))),
                          F[ih], rho=rho, g=g, ulen=ulen)[2]
        for ih in range(len(headings))
    ]
    with open(path, "w") as f:
        for iw, wv in enumerate(np.asarray(w)):
            for ih, hd in enumerate(headings):
                for i in range(6):
                    x = X_bars[ih][i, iw]
                    f.write(f" {wv:13.6E} {hd:10.3f} {i+1:5d} "
                            f"{abs(x):13.6E} {np.degrees(np.angle(x)):13.6E} "
                            f"{x.real:13.6E} {x.imag:13.6E}\n")
    return path


# ------------------------------------------------------ HAMS project files


def create_project_dirs(project_dir: str):
    """HAMS-compatible project scaffolding (cf. pyhams.py:89-129)."""
    for sub in (
        "Input",
        "Output",
        "Output/Hams_format",
        "Output/Hydrostar_format",
        "Output/Wamit_format",
    ):
        os.makedirs(os.path.join(project_dir, sub), exist_ok=True)


def write_hydrostatic_file(
    project_dir: str, cog=(0.0, 0.0, 0.0), mass=None, damping=None,
    kHydro=None, kExt=None,
):
    """Write Input/Hydrostatic.in (cf. pyhams.py:131-194)."""
    mass = np.zeros((6, 6)) if mass is None else np.asarray(mass)
    damping = np.zeros((6, 6)) if damping is None else np.asarray(damping)
    kHydro = np.zeros((6, 6)) if kHydro is None else np.asarray(kHydro)
    kExt = np.zeros((6, 6)) if kExt is None else np.asarray(kExt)
    path = os.path.join(project_dir, "Input", "Hydrostatic.in")
    with open(path, "w") as f:
        f.write(" Center of Gravity:\n")
        f.write(f"  {cog[0]:>12.6E}  {cog[1]:>12.6E}  {cog[2]:>12.6E}\n")
        for name, M in (
            ("Body Mass Matrix:", mass),
            ("External Linear Damping Matrix:", damping),
            ("Hydrostatic Restoring Matrix:", kHydro),
            ("External Restoring Matrix:", kExt),
        ):
            f.write(f" {name}\n")
            for row in M:
                f.write("".join(f"  {x:>12.6E}" for x in row) + "\n")
    return path


def write_control_file(
    project_dir: str,
    water_depth: float = 50.0,
    num_freqs: int = 30,
    min_freq: float = 0.2,
    d_freq: float = 0.2,
    num_headings: int = 1,
    min_heading: float = 0.0,
    d_heading: float = 0.0,
    num_threads: int = 8,
    irr: int = 0,
):
    """Write Input/ControlFile.in (cf. pyhams.py:196-289).

    ``num_freqs`` negative means the list is angular frequencies (the HAMS
    convention the reference uses at raft/raft.py:2062)."""
    path = os.path.join(project_dir, "Input", "ControlFile.in")
    with open(path, "w") as f:
        f.write("   --------------HAMS Control file---------------\n\n")
        f.write(f"   Waterdepth  {water_depth}D0\n\n")
        f.write("   #Start Definition of Wave Frequencies\n")
        f.write(f"    0_inf_frequency_limits      {irr}\n")
        f.write(f"    Input_frequency_type        3\n")
        f.write(f"    Output_frequency_type       3\n")
        f.write(f"    Number_of_frequencies      -{abs(num_freqs)}\n")
        f.write(f"    Minimum_frequency_Wmin      {min_freq}D0\n")
        f.write(f"    Frequency_step              {d_freq}D0\n")
        f.write("   #End Definition of Wave Frequencies\n\n")
        f.write("   #Start Definition of Wave Headings\n")
        f.write(f"    Number_of_headings          {num_headings}\n")
        f.write(f"    Minimum_heading             {min_heading}D0\n")
        f.write(f"    Heading_step                {d_heading}D0\n")
        f.write("   #End Definition of Wave Headings\n\n")
        f.write(f"    Reference_body_center   0.000000  0.000000  0.000000\n")
        f.write(f"    Reference_body_length   1.0D0\n")
        f.write(f"    Wave-diffrac-solution   2\n")
        f.write(f"    If_remove_irr_freq      {irr}\n")
        f.write(f"    Number of threads       {num_threads}\n\n")
        f.write("   #Start Definition of Pressure and/or Elevation\n")
        f.write("    Number_of_field_points     0\n")
        f.write("   #End Definition of Pressure and/or Elevation\n\n")
        f.write("   ----------End HAMS Control file---------------\n")
    return path


def read_nemoh_mesh(path: str) -> np.ndarray:
    """Read a Nemoh .nemoh/.dat mesh into an (np,4,3) panel array
    (cf. nemohmesh_to_pnl, pyhams.py:7-86)."""
    nodes = {}
    panels = []
    mode = "nodes"
    with open(path) as f:
        first = f.readline()          # header: "2 0" etc.
        for ln in f:
            parts = ln.split()
            if not parts:
                continue
            if mode == "nodes":
                if len(parts) >= 4:
                    idx = int(parts[0])
                    if idx == 0:
                        mode = "panels"
                        continue
                    nodes[idx] = [float(parts[1]), float(parts[2]), float(parts[3])]
            else:
                ids = [int(p) for p in parts[:4]]
                if all(i == 0 for i in ids):
                    break
                panels.append([nodes[i] for i in ids])
    return np.asarray(panels)


def nemoh_to_pnl(nemoh_path: str, pnl_path: str):
    """Convert a Nemoh mesh file to HAMS .pnl format."""
    from raft_tpu.hydro.mesh import write_pnl

    write_pnl(pnl_path, read_nemoh_mesh(nemoh_path))
    return pnl_path
