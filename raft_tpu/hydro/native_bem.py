"""ctypes wrapper for the native C++ BEM solver (raft_tpu/native/bem.cpp).

The native solver is the framework's HAMS equivalent (the reference's only
native component, hams/pyhams.py:361-373 + hams/bin/HAMS_x64.exe): given a
hull panel mesh and a frequency grid it returns potential-flow added mass
A(w), radiation damping B(w) and wave excitation X(w), which are staged to
the JAX pipeline via ``Model(design, BEM=(A, B, F))``.

The shared library is compiled on demand with g++ -O3 -fopenmp and cached
next to the source; results are cached content-addressed (mesh + grid hash)
under ``~/.cache/raft_tpu/bem`` — the formalization of the reference's
compute-once/reuse WAMIT-file pattern (SURVEY.md §5 checkpoint/resume).
"""
# graftlint: disable-file=GL105 — the C++ ABI is `double*`: every array
# crossing the ctypes boundary MUST be float64; nothing here reaches the
# device without a jnp.asarray downcast on the staging side.
from __future__ import annotations

import ctypes
import hashlib
import os
import time

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "bem.cpp")
_LIB_DIR = os.path.join(os.path.dirname(_SRC), "_build")

_lib = None


def _src_digest() -> str:
    h = hashlib.sha256()
    with open(_SRC, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


def _lib_path() -> str:
    """The shared-library path, keyed by a CONTENT hash of ``bem.cpp`` —
    the same contract the result cache already uses.  The old freshness
    check compared mtimes (``getmtime(_LIB) >= src_mtime``), which a git
    checkout can regress (checkout rewrites the source with an older
    mtime than the built artifact), silently serving a stale solver; a
    content key cannot go stale, and editing the source simply lands on
    a new path."""
    return os.path.join(_LIB_DIR, f"libraft_bem-{_src_digest()[:16]}.so")


def _build_lib() -> str:
    """Compile the shared library on demand — through the resilience
    retry discipline: a HARD timeout on the ``g++`` child (a hung
    toolchain — NFS stall, OOM-thrashing box — must never wedge a sweep
    forever; ``RAFT_TPU_BUILD_TIMEOUT``, default 300 s), one bounded
    retry with backoff for transient failures, and on final failure a
    RuntimeError carrying a REDACTED tail of the compiler's stderr (the
    diagnostic, safe for committed artifacts) instead of the full spew.
    """
    os.makedirs(_LIB_DIR, exist_ok=True)
    lib = _lib_path()
    if os.path.exists(lib):
        return lib
    from raft_tpu.resilience import retry as _retry

    # compile to a tmp path and publish atomically: a timeout-KILLED g++
    # can leave a partial object under an existence-checked key
    tmp = lib + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
        _SRC, "-o", tmp, "-lm",
    ]
    timeout_s = _retry.build_timeout_s()
    from raft_tpu.obs import trace as _trace

    try:
        with _trace.span("bem/build_lib"):
            _retry.retry_call(
                lambda attempt: _retry.checked_subprocess(
                    cmd, timeout_s=timeout_s, describe="BEM solver g++ build"),
                retries=2, backoff_s=2.0,
                retry_on=(_retry.SubprocessFailed,),
                describe="BEM solver build",
            )
        os.replace(tmp, lib)
    except _retry.RetryExhausted as e:
        last = e.last
        tail = getattr(last, "stderr_tail", "") or str(last)[-300:]
        raise RuntimeError(
            f"BEM solver build failed after {e.attempts} attempt(s) "
            f"({getattr(last, 'kind', 'error')}, timeout {timeout_s:.0f}s "
            f"per attempt):\n{tail}") from e
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return lib


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(_build_lib())
        dptr = ctypes.POINTER(ctypes.c_double)
        lib.bem_solve_mh.restype = ctypes.c_int
        lib.bem_solve_mh.argtypes = [
            dptr, ctypes.c_int,                                 # panels, np
            dptr, ctypes.c_int,                                 # w, nw
            ctypes.c_double,                                    # depth
            ctypes.c_double, ctypes.c_double,                   # rho, g
            dptr, ctypes.c_int,                                 # betas, nb
            dptr, dptr, dptr, dptr,                             # A, B, Fre, Fim
            dptr, dptr,                                         # Fhre, Fhim (Haskind, may be NULL)
            ctypes.c_int, ctypes.c_int,                         # nthreads, nlid
        ]
        lib.bem_green_fd.restype = None
        lib.bem_green_fd.argtypes = [ctypes.c_double] * 5 + [
            ctypes.POINTER(ctypes.c_double)
        ]
        lib.bem_dispersion.restype = ctypes.c_double
        lib.bem_dispersion.argtypes = [ctypes.c_double, ctypes.c_double]
        lib.bem_wave_integral.restype = None
        lib.bem_wave_integral.argtypes = [ctypes.c_double, ctypes.c_double,
                                          ctypes.POINTER(ctypes.c_double),
                                          ctypes.POINTER(ctypes.c_double)]
        lib.bem_wave_integral_direct.restype = None
        lib.bem_wave_integral_direct.argtypes = lib.bem_wave_integral.argtypes
        _lib = lib
    return _lib


# ------------------------------------------------- shared result cache --
#
# Content-addressed npz result cache shared by the native and the JAX
# (hydro/jax_bem.py) panel solvers: atomic tmp+os.replace publish, and a
# corrupt artifact (torn write, bit rot, missing keys) is a *counted*
# MISS — deleted and recomputed, never served, never silent.  The
# ``bem.cache_corrupt`` counter (ChunkStore's ckpt.corrupt precedent)
# makes corruption observable instead of a quiet unlink.


def _cache_base(namespace: str) -> str:
    # the solver result caches predate the warm-start subsystem and are
    # governed by the callers' ``cache`` flag, but they follow a
    # RAFT_TPU_CACHE_DIR relocation so one root holds every layer
    # (``off`` only disables the warm-start layers, not these: the
    # artifacts are exact solver output, so hits are bit-identical)
    from raft_tpu.cache import config as _cache_config

    root = _cache_config.cache_dir() or _cache_config.resolve_dir()
    return (os.path.join(root, namespace) if root is not None
            else os.path.expanduser(f"~/.cache/raft_tpu/{namespace}"))


def result_cache_key(namespace: str, panels, w, betas, scalars,
                     salt=(), extra_bytes: bytes = b"") -> str:
    """Content-addressed artifact path for one solve's inputs."""
    import numpy as _np

    h = hashlib.sha256()
    for part in salt:
        h.update(repr(part).encode())
    h.update(_np.ascontiguousarray(panels).tobytes())
    h.update(_np.ascontiguousarray(w).tobytes())
    h.update(_np.ascontiguousarray(betas).tobytes())
    h.update(_np.asarray(scalars, dtype=_np.float64).tobytes())
    h.update(extra_bytes)
    return os.path.join(_cache_base(namespace), h.hexdigest()[:24] + ".npz")


def result_cache_load(key: str, needed) -> dict | None:
    """Load a cached solve result; corrupt/incomplete artifacts count
    ``bem.cache_corrupt`` and are deleted (a MISS)."""
    from raft_tpu import obs as _obs

    if not os.path.exists(key):
        _obs.metrics.counter("bem.cache_miss").inc()
        return None
    try:
        with np.load(key) as z:
            names = set(z.files)
            needed = set(needed)
            if not needed <= names:
                raise KeyError(sorted(needed - names))
            out = {k: z[k].copy() for k in needed}
        _obs.metrics.counter("bem.cache_hit").inc()
        return out
    except Exception:
        _obs.metrics.counter("bem.cache_corrupt").inc()
        _obs.metrics.counter("bem.cache_miss").inc()
        try:
            os.unlink(key)
        except OSError:
            pass
        return None


def result_cache_store(key: str, payload: dict) -> None:
    """Atomic tmp + os.replace publish under the content-addressed key
    (GL202: a kill mid-write must never leave a torn npz that an
    existence freshness check would serve)."""
    os.makedirs(os.path.dirname(key), exist_ok=True)
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(key), suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **payload)
        os.replace(tmp, key)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def wave_integral(X: float, Y: float, direct: bool = False):
    """Probe the dimensionless PV wave integrals I0/I1 (unit tests)."""
    lib = _load()
    i0 = ctypes.c_double()
    i1 = ctypes.c_double()
    fn = lib.bem_wave_integral_direct if direct else lib.bem_wave_integral
    fn(X, Y, ctypes.byref(i0), ctypes.byref(i1))
    return i0.value, i1.value


def green_fd(nu: float, depth: float, R: float, zP: float, zQ: float):
    """Probe the finite-depth Green function (unit tests).

    Returns (G, dG/dR, dG/dz) as complex scalars — the full G including
    the 1/r and free-surface-image singular parts."""
    lib = _load()
    out = (ctypes.c_double * 6)()
    lib.bem_green_fd(nu, depth, R, zP, zQ, out)
    return (
        complex(out[0], out[1]),
        complex(out[2], out[3]),
        complex(out[4], out[5]),
    )


def dispersion(nu: float, depth: float) -> float:
    """k0 with k0 tanh(k0 depth) = nu (native dispersion probe)."""
    return float(_load().bem_dispersion(nu, depth))


def solve_bem(
    panels: np.ndarray,
    w: np.ndarray,
    rho: float = 1025.0,
    g: float = 9.81,
    beta=0.0,
    depth: float = 0.0,
    nthreads: int = 0,
    cache: bool = True,
    haskind: bool = False,
    lid: np.ndarray | None = None,
):
    """Run the native BEM solve (finite depth when ``depth`` > 0, else deep).

    panels: (np, 4, 3) hull mesh (outward normals); w: (nw,) rad/s;
    ``beta``: one heading [rad] or a heading grid — the influence matrix is
    factored once per frequency and each extra heading is one extra
    back-substitution (the capability of the reference's HAMS heading grid,
    hams/pyhams.py:196-289 num_headings/d_heading).

    Returns (A[6,6,nw], B[6,6,nw], F) with F[6,nw] complex for a scalar
    heading (reference WAMIT-reader layout) or F[nb,6,nw] for a grid.
    With ``haskind=True`` returns (A, B, F, Fh) where Fh is the excitation
    from the Haskind relation X_j = i w rho Int(phi_I n_j - phi_j
    dphi_I/dn) dS — an independent check of F in amplitude and phase.

    ``lid``: optional (nl, 4, 3) interior waterplane panels at z=0
    (:func:`raft_tpu.hydro.mesh.mesh_lid`).  Activates the extended
    boundary integral equation (zero interior potential on the lid),
    removing the irregular frequencies of the plain source formulation —
    the reference's HAMS `irr` capability (hams/pyhams.py:200,284).
    """
    panels = np.ascontiguousarray(panels, dtype=np.float64)
    n_lid = 0
    if lid is not None and len(lid) > 0:
        panels = np.ascontiguousarray(
            np.concatenate([panels, np.asarray(lid, dtype=np.float64)]), dtype=np.float64
        )
        n_lid = len(lid)
    w = np.ascontiguousarray(np.atleast_1d(w), dtype=np.float64)
    scalar_beta = np.ndim(beta) == 0
    betas = np.ascontiguousarray(np.atleast_1d(beta), dtype=np.float64)
    n_p, n_w, n_b = len(panels), len(w), len(betas)
    depth = float(depth) if depth and depth > 0 else -1.0

    from raft_tpu import obs as _obs

    key = None
    if cache:
        # solver edits invalidate the cache: key on the source content
        key = result_cache_key(
            "bem", panels, w, betas,
            (rho, g, depth, float(haskind), float(n_lid)),
            salt=(_src_digest(),))
        needed = ("A", "B", "F", "Fh") if haskind else ("A", "B", "F")
        hit = result_cache_load(key, needed)
        if hit is not None:
            out = (hit["A"], hit["B"],
                   hit["F"][0] if scalar_beta else hit["F"])
            if haskind:
                out = out + ((hit["Fh"][0] if scalar_beta
                              else hit["Fh"]),)
            return out
    lib = _load()
    A = np.zeros((n_w, 6, 6))
    B = np.zeros((n_w, 6, 6))
    Fre = np.zeros((n_w, n_b, 6))
    Fim = np.zeros((n_w, n_b, 6))
    Fhre = np.zeros((n_w, n_b, 6)) if haskind else None
    Fhim = np.zeros((n_w, n_b, 6)) if haskind else None
    dptr = lambda a: (
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) if a is not None else None
    )
    t0 = time.perf_counter()
    with _obs.trace.span("bem/solve", attrs={"panels": n_p, "nw": n_w,
                                             "headings": n_b}):
        ret = lib.bem_solve_mh(
            dptr(panels), n_p, dptr(w), n_w, depth, rho, g,
            dptr(betas), n_b,
            dptr(A), dptr(B), dptr(Fre), dptr(Fim),
            dptr(Fhre), dptr(Fhim), nthreads, n_lid,
        )
    _obs.metrics.histogram("bem.solve_s").observe(time.perf_counter() - t0)
    if ret != 0:
        raise RuntimeError(f"bem_solve failed with code {ret}")
    A = A.transpose(1, 2, 0)
    B = B.transpose(1, 2, 0)
    # (nw, nb, 6) -> (nb, 6, nw)
    F = (Fre + 1j * Fim).transpose(1, 2, 0)
    Fh = (Fhre + 1j * Fhim).transpose(1, 2, 0) if haskind else None

    if cache and key is not None:
        payload = dict(A=A, B=B, F=F)
        if haskind:
            payload["Fh"] = Fh
        result_cache_store(key, payload)
    if scalar_beta:
        F = F[0]
        Fh = Fh[0] if haskind else None
    return (A, B, F, Fh) if haskind else (A, B, F)
