"""Hydrodynamics: strip-theory (Morison) kernels and BEM coefficient providers."""
from raft_tpu.hydro.strip import (  # noqa: F401
    StripKin,
    linearized_drag,
    node_kinematics,
    strip_added_mass,
    strip_excitation,
)
