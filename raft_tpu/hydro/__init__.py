"""Hydrodynamics: strip-theory (Morison) kernels and BEM coefficient providers."""
from raft_tpu.hydro.bem_io import (  # noqa: F401
    dimensionalize,
    interp_to_grid,
    load_wamit_coeffs,
    read_wamit1,
    read_wamit3,
)
from raft_tpu.hydro.mesh import (  # noqa: F401
    mesh_design,
    mesh_member,
    mesh_volume,
    write_gdf,
    write_pnl,
)
from raft_tpu.hydro.strip import (  # noqa: F401
    StripKin,
    current_mean_force,
    linearized_drag,
    node_current,
    node_kinematics,
    strip_added_mass,
    strip_excitation,
)
