"""Cross-process proof of the on-device BEM (`make bem-smoke`).

The claim (ROADMAP item 2 / the jax_bem tentpole): a *novel* (uncached)
geometry solves ON DEVICE — no g++ invocation, no host C++ solver — with
parity against the native f64 oracle, and a warm process pays ZERO
compiles for a second novel geometry of the same panel size class.

Protocol (real process boundaries, the cache-/hetero-/serve-smoke rule):

1. The PARENT builds the native oracle (real toolchain allowed here) and
   pre-warms the design-independent wave-integral table into a fresh
   workspace cache root (pure numpy — no g++ involved).
2. CHILD 1 runs with ``RAFT_TPU_BEM=jax``, the fresh cache root, and a
   POISONED ``g++`` on PATH (a stub that drops a marker file and exits
   1): it solves novel geometry A cold (compile + solve) and writes
   A/B/F + diagnostics.  Any attempt to reach the toolchain either
   fails the child loudly or leaves the marker — both are detected.
3. CHILD 2 repeats geometry A warm: ZERO compiles (AOT disk hit).
4. CHILD 3 solves novel geometry B (different dimensions, same ``panels``
   ladder class, cache-cold content): ZERO compiles — a novel geometry
   on a warm executable pays only the device solve.
5. The parent solves both geometries through the native oracle
   (``cache=False``) and pins max scale-relative |jax - native| on A, B
   and F within :data:`raft_tpu.hydro.jax_bem.PARITY_RTOL`.
6. CHILDREN 4-5 repeat the cold + novel legs with
   ``RAFT_TPU_BEM_ASSEMBLY=pallas`` (the tiled assembly kernels of
   :mod:`raft_tpu.core.pallas_bem`; interpreter mode off-TPU): cold
   compiles under its own key-salted AOT key, the novel geometry is
   again ZERO compiles, oracle parity holds, and the pallas A/B/F agree
   with the XLA route within
   :data:`raft_tpu.core.pallas_bem.INTERP_PARITY_RTOL` — still with
   g++ poisoned.

Prints exactly ONE JSON line; exits 0 iff every check passed.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np


def novel_mesh(r: float, draft: float, spacing: float,
               dz_max: float = 1.6, da_max: float = 1.3) -> np.ndarray:
    """A deterministic 'novel' two-column platform mesh — dimensions are
    deliberately unlike any shipped design, so nothing content-cached can
    match.  Shared by this smoke and the bench ``bem`` block (one mesh
    recipe, two measurements)."""
    from raft_tpu.hydro.mesh import mesh_member

    cols = []
    for sx in (-0.5, 0.5):
        cols.append(mesh_member(
            stations=[0.0, draft + 2.0], diameters=[2 * r, 2 * r],
            rA=[sx * spacing, 0.0, -draft], rB=[sx * spacing, 0.0, 2.0],
            dz_max=dz_max, da_max=da_max))
    return np.concatenate(cols, axis=0)


def smoke_mesh(variant: str) -> np.ndarray:
    """Variants A/B differ in radius/draft/spacing but land in the same
    ``panels`` ladder class (the novel-geometry-zero-compile claim)."""
    if variant == "a":
        return novel_mesh(1.13, 5.7, 7.9)
    if variant == "b":
        return novel_mesh(1.19, 5.9, 8.3)
    raise ValueError(variant)


_W = np.linspace(0.4, 1.6, 4)
_RHO, _G, _DEPTH, _BETA = 1025.0, 9.81, 40.0, 0.3


def _child(variant: str, out_path: str) -> int:
    from raft_tpu import cache
    from raft_tpu.hydro.jax_bem import solve_bem_jax

    cache.enable()                     # root from RAFT_TPU_CACHE_DIR
    panels = smoke_mesh(variant)
    t0 = time.perf_counter()
    A, B, F, diag = solve_bem_jax(
        panels, _W, rho=_RHO, g=_G, beta=_BETA, depth=_DEPTH,
        cache=False, return_diagnostics=True)
    wall = time.perf_counter() - t0
    from raft_tpu.cache.aot import compile_count

    np.savez(out_path, A=A, B=B, F_re=F.real, F_im=F.imag,
             wall_s=wall, compiles=compile_count("jax_bem"),
             max_residual=diag["max_residual"], padded=diag["padded"])
    return 0


def main() -> int:
    t_start = time.perf_counter()
    ws = tempfile.mkdtemp(prefix="raft-bem-smoke-")
    result: dict = {"ok": False}
    try:
        root = os.path.join(ws, "cache")
        os.makedirs(root, exist_ok=True)
        # poisoned toolchain for the children
        poison = os.path.join(ws, "bin")
        os.makedirs(poison, exist_ok=True)
        marker = os.path.join(ws, "gxx-invoked")
        for tool in ("g++", "gcc", "c++"):
            path = os.path.join(poison, tool)
            with open(path, "w") as f:
                f.write("#!/bin/sh\n"
                        f"touch {marker}\n"
                        "echo 'bem-smoke: toolchain poisoned' >&2\n"
                        "exit 1\n")
            os.chmod(path, 0o755)

        # parent: native oracle (real toolchain) + table pre-warm
        from raft_tpu.hydro import jax_bem, wavetable
        from raft_tpu.hydro.native_bem import solve_bem

        oracle = {v: solve_bem(smoke_mesh(v), _W, rho=_RHO, g=_G,
                               beta=_BETA, depth=_DEPTH, cache=False)
                  for v in ("a", "b")}
        wavetable.load_tables()        # build once under the default root
        tab_src = wavetable._cache_path()
        tab_dst = os.path.join(root, "wavetable",
                               os.path.basename(tab_src))
        os.makedirs(os.path.dirname(tab_dst), exist_ok=True)
        shutil.copy(tab_src, tab_dst)

        env = dict(os.environ)
        env["PATH"] = poison + os.pathsep + env.get("PATH", "")
        env["RAFT_TPU_BEM"] = "jax"
        env["RAFT_TPU_CACHE_DIR"] = root
        env.setdefault("JAX_PLATFORMS", "cpu")

        def run_child(variant, tag, extra_env=None):
            out = os.path.join(ws, f"{tag}.npz")
            t0 = time.perf_counter()
            proc = subprocess.run(
                [sys.executable, "-m", "raft_tpu.hydro.bem_smoke",
                 "--child", variant, out],
                env=env if not extra_env else env | extra_env,
                timeout=600, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"child {tag} rc={proc.returncode}: "
                    f"{proc.stderr[-800:]}")
            with np.load(out) as z:
                return {k: z[k] for k in z.files} | {
                    "child_wall_s": time.perf_counter() - t0}

        cold = run_child("a", "cold")
        warm = run_child("a", "warm")
        novel = run_child("b", "novel")
        # the tiled-assembly leg: same protocol, pallas route pinned
        # (interpreter mode off-TPU), its own key-salted executable
        pal = {"RAFT_TPU_BEM_ASSEMBLY": "pallas"}
        pallas_cold = run_child("a", "pallas_cold", pal)
        pallas_novel = run_child("b", "pallas_novel", pal)

        def parity(got, variant):
            An, Bn, Fn = oracle[variant]
            F = got["F_re"] + 1j * got["F_im"]
            err = jax_bem.parity_err
            return {"A": err(got["A"], An), "B": err(got["B"], Bn),
                    "F": err(F, Fn)}

        par_a = parity(cold, "a")
        par_b = parity(novel, "b")
        par_pa = parity(pallas_cold, "a")
        par_pb = parity(pallas_novel, "b")
        from raft_tpu.core.pallas_bem import INTERP_PARITY_RTOL

        err = jax_bem.parity_err
        cross = {"A": err(pallas_cold["A"], cold["A"]),
                 "B": err(pallas_cold["B"], cold["B"]),
                 "F": err(pallas_cold["F_re"] + 1j * pallas_cold["F_im"],
                          cold["F_re"] + 1j * cold["F_im"])}
        tol = jax_bem.PARITY_RTOL
        checks = {
            "gxx_never_invoked": not os.path.exists(marker),
            "cold_compiled": int(cold["compiles"]) >= 1,
            "warm_zero_compiles": int(warm["compiles"]) == 0,
            "novel_zero_compiles": int(novel["compiles"]) == 0,
            "warm_faster_than_cold":
                float(warm["wall_s"]) < float(cold["wall_s"]),
            "parity_a": all(v <= tol for v in par_a.values()),
            "parity_b": all(v <= tol for v in par_b.values()),
            "residual_small":
                max(float(cold["max_residual"]),
                    float(novel["max_residual"])) < 1e-4,
            # the pallas-interpret leg: own cold compile (route is
            # key-salted), novel-geometry zero compiles, oracle parity,
            # and cross-route agreement with the XLA leg
            "pallas_cold_compiled": int(pallas_cold["compiles"]) >= 1,
            "pallas_novel_zero_compiles":
                int(pallas_novel["compiles"]) == 0,
            "pallas_parity_a": all(v <= tol for v in par_pa.values()),
            "pallas_parity_b": all(v <= tol for v in par_pb.values()),
            "pallas_xla_agree": all(v <= INTERP_PARITY_RTOL
                                    for v in cross.values()),
        }
        result = {
            "ok": all(checks.values()),
            "checks": checks,
            "parity": {"a": par_a, "b": par_b, "rtol": tol,
                       "pallas_a": par_pa, "pallas_b": par_pb,
                       "cross_route": cross,
                       "cross_rtol": INTERP_PARITY_RTOL},
            "cold_solve_s": float(cold["wall_s"]),
            "warm_solve_s": float(warm["wall_s"]),
            "novel_solve_s": float(novel["wall_s"]),
            "pallas_cold_solve_s": float(pallas_cold["wall_s"]),
            "pallas_novel_solve_s": float(pallas_novel["wall_s"]),
            "compiles": {"cold": int(cold["compiles"]),
                         "warm": int(warm["compiles"]),
                         "novel": int(novel["compiles"]),
                         "pallas_cold": int(pallas_cold["compiles"]),
                         "pallas_novel": int(pallas_novel["compiles"])},
            "padded_panels": int(cold["padded"]),
            "max_residual": float(max(cold["max_residual"],
                                      novel["max_residual"],
                                      pallas_cold["max_residual"],
                                      pallas_novel["max_residual"])),
            "wall_s": time.perf_counter() - t_start,
        }
    except Exception as e:                       # noqa: BLE001
        result["error"] = f"{type(e).__name__}: {e}"
    finally:
        shutil.rmtree(ws, ignore_errors=True)
    print(json.dumps(result))
    return 0 if result.get("ok") else 1


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--child":
        sys.exit(_child(sys.argv[2], sys.argv[3]))
    sys.exit(main())
