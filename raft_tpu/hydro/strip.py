"""Strip-theory (Morison) hydrodynamics over the stacked node axis.

Vectorized, jittable, differentiable equivalent of the reference's
``FOWT.calcHydroConstants`` (raft/raft.py:2076-2157) and
``FOWT.calcLinearizedTerms`` (raft/raft.py:2160-2264): the member/node/
frequency triple loop becomes batched einsums over the (N nodes, nw
frequencies) axes.  A design batch is the same call under ``vmap``.

Conventions:
  * All complex amplitudes are :class:`~raft_tpu.core.cplx.Cx` (re, im)
    pairs; frequency is the *leading* data axis of assembled outputs,
    i.e. excitation vectors are (nw, 6) and frequency-dependent matrices
    (nw, 6, 6) — the layout the batched impedance solve consumes directly.
  * A node contributes only while submerged (z < 0), matching the
    reference's node gate at raft/raft.py:2097; here it is a mask so the
    computation stays shape-static under jit/vmap.

Deviations from the reference (correct physics kept; see DEVIATIONS.md):
  * Drag coefficients: the reference interpolates the *added-mass* profiles
    for use as drag coefficients (``mem.Ca_*`` at raft/raft.py:2194-2197);
    here the actual Cd profiles are used.
  * Rectangular axial skin-drag area: the reference computes
    ``2*(ds[0]+ds[0])*dls`` (raft/raft.py:2207); here the perimeter uses
    both side lengths, ``2*(ds[0]+ds[1])*dls``.
  * Axial Froude-Krylov: the reference includes BOTH the volume form
    (``(1+Ca_q)`` on the side qq term, raft/raft.py:2122) AND the surface
    form (dynamic pressure on ends/tapers, raft/raft.py:2156) of the same
    axial FK force — Gauss's theorem makes them equal, so it is counted
    twice (~2x heave excitation on a spar).  Here the side qq term carries
    only the axial added-mass correction ``Ca_q``; the FK part comes from
    the end/taper pressure terms alone.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from raft_tpu.core import cplx
from raft_tpu.core.cplx import Cx
from raft_tpu.core.transforms import translate_force_3to6, translate_matrix_3to6, vec_outer
from raft_tpu.core.types import Env, MemberSet, WaveState
from raft_tpu.core.waves import wave_kinematics

Array = jnp.ndarray

_SQRT_8_PI = (8.0 / jnp.pi) ** 0.5


def node_current(m: MemberSet, env: Env) -> Array:
    """Per-node steady-current velocity vectors (N,3).

    Power-law shear profile over the water column,
    ``u_c(z) = current * ((depth + z)/depth)^current_exp`` (clipped to
    [0, 1] so above-surface nodes see the surface speed — they are masked
    out of every force downstream), heading in the horizontal plane.
    Beyond-reference: the reference has no current model at all (its Env
    carries wind + waves only, raft/raft.py:22-30)."""
    z = m.node_r[..., 2]
    frac = jnp.clip((env.depth + z) / env.depth, 0.0, 1.0)
    u = env.current * frac ** env.current_exp                   # (N,)
    ch = jnp.asarray(env.current_heading)
    dirv = jnp.stack([jnp.cos(ch), jnp.sin(ch), jnp.zeros_like(ch)], axis=-1)
    return u[..., None] * dirv


def _gauss_drag_slope(U: Array, sigma: Array) -> Array:
    """MMSE linearization slope of the quadratic drag ``|X| X`` for
    ``X ~ N(U, sigma^2)``: ``Cov(|X|X, X)/sigma^2 =
    2 U erf(U/(sigma sqrt2)) + sqrt(8/pi) sigma exp(-U^2/(2 sigma^2))``.

    The exact Gaussian-closure generalization of the Borgman factor:
    reduces to ``sqrt(8/pi) sigma`` at U=0 (the reference's stochastic
    linearization, raft/raft.py:2219-2227) and to ``2|U|`` (steady-flow
    drag slope) as sigma -> 0.  Double-where guards keep sigma=0 lanes
    (padded nodes) finite in both passes."""
    s_safe = jnp.where(sigma > 0, sigma, 1.0)
    r = U / (s_safe * jnp.sqrt(2.0))
    slope = (2.0 * U * jax.scipy.special.erf(r)
             + _SQRT_8_PI * sigma * jnp.exp(-(r**2)))
    return jnp.where(sigma > 0, slope, 2.0 * jnp.abs(U))


def _drag_areas(m: MemberSet):
    """Per-node drag reference areas (axial-skin, p1, p2, end disk)."""
    d0, d1 = m.node_ds[..., 0], m.node_ds[..., 1]
    dls = m.node_dls
    a_q = jnp.where(m.node_circ, jnp.pi * d0 * dls, 2.0 * (d0 + d1) * dls)
    a_p1 = d0 * dls
    a_p2 = jnp.where(m.node_circ, d0 * dls, d1 * dls)
    a_end = jnp.abs(_end_area_signed(m))
    return a_q, a_p1, a_p2, a_end


def current_mean_force(m: MemberSet, env: Env) -> Array:
    """Mean 6-DOF drag load of the steady current about the PRP.

    Per submerged node and drag direction d in {axial q, transverse p1,
    p2, end disk}: ``0.5 rho a_d Cd_d |U_d| U_d`` along the direction
    unit vector — the sigma=0 closed form of the Gaussian drag moment
    (the oscillatory part enters the response solve through
    :func:`linearized_drag`'s mean-flow-aware slope instead).  Feeds the
    mean-offset equilibrium exactly like wind thrust does."""
    uc = node_current(m, env)                                   # (N,3)
    U_q = (uc * m.node_q).sum(-1)
    U_p1 = (uc * m.node_p1).sum(-1)
    U_p2 = (uc * m.node_p2).sum(-1)
    a_q, a_p1, a_p2, a_end = _drag_areas(m)
    half_rho = 0.5 * env.rho

    def mean_drag(U, a, Cd):
        return half_rho * a * Cd * jnp.abs(U) * U               # (N,)

    F3 = (
        (mean_drag(U_q, a_q, m.node_Cd_q)
         + mean_drag(U_q, a_end, m.node_Cd_end))[..., None] * m.node_q
        + mean_drag(U_p1, a_p1, m.node_Cd_p1)[..., None] * m.node_p1
        + mean_drag(U_p2, a_p2, m.node_Cd_p2)[..., None] * m.node_p2
    )
    F3 = F3 * _submerged(m).astype(F3.dtype)[..., None]
    return translate_force_3to6(m.node_r, F3).sum(axis=-2)


@struct.dataclass
class StripKin:
    """Wave kinematics at the strip nodes (precomputed once per sea state)."""

    u: Cx      # (N,nw,3) water particle velocity amplitudes
    ud: Cx     # (N,nw,3) acceleration amplitudes
    pDyn: Cx   # (N,nw)   dynamic pressure amplitudes


@jax.jit
def node_kinematics(m: MemberSet, wave: WaveState, env: Env) -> StripKin:
    """Evaluate wave kinematics at every strip node (cf. raft/raft.py:2100)."""
    u, ud, pDyn = wave_kinematics(
        wave.zeta, wave.w, wave.k, env.depth, m.node_r, env.beta, env.rho, env.g
    )
    # wave_kinematics returns (...,3,nw); put frequency before the xyz axis
    return StripKin(u=u.swapaxes(-1, -2), ud=ud.swapaxes(-1, -2), pDyn=pDyn)


def _submerged(m: MemberSet) -> Array:
    return (m.node_r[..., 2] < 0.0) & m.node_mask


def _morison_active(m: MemberSet) -> Array:
    """Submerged nodes whose inertial hydro comes from strip theory.

    potMod members are served by the BEM provider instead — their strip
    added mass / FK excitation is gated off here, while drag (which no
    potential-flow solver provides) stays on for all members.  Only
    CIRCULAR potMod members are gated: the mesher routes rectangular
    members to the Morison path regardless of their potMod flag
    (hydro/mesh.py _iter_potmod_members), so gating them here would drop
    them from both providers — e.g. the VolturnUS-S rectangular pontoons,
    which carry ~25e6 kg of heave added mass.
    """
    act = _submerged(m)
    if m.node_potmod is not None:
        act = act & ~(m.node_potmod & m.node_circ)
    return act


def _side_volume(m: MemberSet) -> Array:
    """Member volume assigned to each node (cf. raft/raft.py:2111-2114)."""
    d0, d1 = m.node_ds[..., 0], m.node_ds[..., 1]
    return jnp.where(
        m.node_circ,
        0.25 * jnp.pi * d0 * d0 * m.node_dls,
        d0 * d1 * m.node_dls,
    )


def _end_volume(m: MemberSet) -> Array:
    """Volume assigned to each node's end surface (cf. raft/raft.py:2135-2139)."""
    d_c = m.node_ds[..., 0]
    dr_c = m.node_drs[..., 0]
    v_circ = jnp.pi / 6.0 * ((d_c + dr_c) ** 3 - (d_c - dr_c) ** 3)
    dm = 0.5 * (m.node_ds[..., 0] + m.node_ds[..., 1])
    drm = 0.5 * (m.node_drs[..., 0] + m.node_drs[..., 1])
    v_rect = jnp.pi / 6.0 * ((dm + drm) ** 3 - (dm - drm) ** 3)
    return jnp.where(m.node_circ, v_circ, v_rect)


def _end_area_signed(m: MemberSet) -> Array:
    """Signed end area, positive facing -q (cf. raft/raft.py:2136-2140)."""
    a_circ = jnp.pi * m.node_ds[..., 0] * m.node_drs[..., 0]
    a_rect = (m.node_ds[..., 0] + m.node_drs[..., 0]) * (m.node_ds[..., 1] + m.node_drs[..., 1]) - (
        m.node_ds[..., 0] - m.node_drs[..., 0]
    ) * (m.node_ds[..., 1] - m.node_drs[..., 1])
    return jnp.where(m.node_circ, a_circ, a_rect)


def _direction_mats(m: MemberSet):
    """Outer-product direction matrices qq/p1p1/p2p2 per node: (N,3,3)."""
    return vec_outer(m.node_q), vec_outer(m.node_p1), vec_outer(m.node_p2)


@partial(jax.jit, static_argnames=("exclude_potmod",))
def strip_added_mass(m: MemberSet, env: Env, exclude_potmod: bool = False) -> Array:
    """Morison added-mass matrix A (6,6) about the PRP.

    Side (transverse + axial) plus end effects, summed over submerged nodes
    (cf. raft/raft.py:2110-2148).  With ``exclude_potmod`` (used when a BEM
    provider supplies the potential-flow coefficients), potMod members are
    gated out.
    """
    qq, p1p1, p2p2 = _direction_mats(m)
    v_side = _side_volume(m)
    v_end = _end_volume(m)
    Amat = env.rho * (
        v_side[..., None, None]
        * (
            m.node_Ca_q[..., None, None] * qq
            + m.node_Ca_p1[..., None, None] * p1p1
            + m.node_Ca_p2[..., None, None] * p2p2
        )
        + (v_end * m.node_Ca_end)[..., None, None] * qq
    )
    act = _morison_active(m) if exclude_potmod else _submerged(m)
    w = act.astype(Amat.dtype)
    A6 = translate_matrix_3to6(m.node_r, Amat) * w[..., None, None]
    return A6.sum(axis=-3)


def _translate_force_cx(r: Array, F: Cx) -> Cx:
    """Complex force at points r -> 6-DOF force about origin.

    r: (N,3); F: Cx (N,nw,3) -> Cx (N,nw,6).
    """
    rb = r[..., None, :]
    return Cx(translate_force_3to6(rb, F.re), translate_force_3to6(rb, F.im))


@partial(jax.jit, static_argnames=("exclude_potmod",))
def strip_excitation(
    m: MemberSet, kin: StripKin, env: Env, exclude_potmod: bool = False
) -> Cx:
    """Froude-Krylov + dynamic-pressure excitation F (nw,6), complex.

    Side inertial term Imat @ ud plus end inertial + dynamic-pressure terms
    (cf. raft/raft.py:2120-2161).  Above-water nodes contribute zero because
    the wave kinematics are masked there.
    """
    qq, p1p1, p2p2 = _direction_mats(m)
    v_side = _side_volume(m)
    v_end = _end_volume(m)
    Imat = env.rho * (
        v_side[..., None, None]
        * (
            m.node_Ca_q[..., None, None] * qq
            + (1.0 + m.node_Ca_p1)[..., None, None] * p1p1
            + (1.0 + m.node_Ca_p2)[..., None, None] * p2p2
        )
        + (v_end * (1.0 + m.node_Ca_end))[..., None, None] * qq
    )
    F3 = cplx.einsum("...nij,...nwj->...nwi", Imat, kin.ud)
    # dynamic-pressure end load: pDyn * a_end * q (cf. raft/raft.py:2156; our
    # pDyn already includes rho, the reference's getWaveKin pDyn does not)
    pa = _end_area_signed(m)[..., None]                        # (N,1)
    Fp = Cx(
        kin.pDyn.re * pa, kin.pDyn.im * pa
    )                                                           # (N,nw)
    F3 = F3 + Cx(
        Fp.re[..., None] * m.node_q[..., None, :],
        Fp.im[..., None] * m.node_q[..., None, :],
    )
    act = _morison_active(m) if exclude_potmod else _submerged(m)
    w = act.astype(F3.re.dtype)[..., None, None]
    F6 = _translate_force_cx(m.node_r, F3)
    F6 = Cx(F6.re * w, F6.im * w)
    return F6.sum(axis=-3)                                      # (nw,6)


def node_motion(m: MemberSet, Xi: Cx, w: Array) -> Cx:
    """Node velocity amplitudes from rigid-body response Xi.

    Xi: Cx (nw,6) platform response; returns Cx (N,nw,3) velocities
    v = i w (Xi_t + Xi_r x r)  (cf. getVelocity, raft/raft.py:903-919).
    """
    r = m.node_r[..., None, :]                                  # (N,1,3)

    def disp(x):
        xt = x[..., :3]                                         # (nw,3)
        xr = x[..., 3:]
        return xt + jnp.cross(jnp.broadcast_to(xr, jnp.broadcast_shapes(xr.shape, r.shape)), r)

    dr = Cx(disp(Xi.re), disp(Xi.im))                           # (N,nw,3)
    return Cx(dr.re * w[:, None], dr.im * w[:, None]).mul_i()


def linearized_drag(
    m: MemberSet, kin: StripKin, Xi: Cx, wave: WaveState, env: Env,
    axis_name: str | None = None,
) -> tuple[Array, Cx]:
    """Stochastically linearized Morison drag about the response iterate Xi.

    Borgman linearization: B' = sqrt(8/pi) * vRMS * 0.5 rho a Cd per node
    per direction (cf. raft/raft.py:2160-2264).  The per-direction vRMS uses
    the reference's component-weighted convention: the relative-velocity
    spectrum is multiplied elementwise by the direction unit vector and the
    Frobenius norm is taken over (xyz, frequency) (raft/raft.py:2219-2227).
    With a steady current set (``env.current``), the factor becomes the
    exact Gaussian MMSE slope about the mean flow
    (:func:`_gauss_drag_slope`) — identical to Borgman at zero current,
    ``2|U|`` in the steady-flow limit; the current's MEAN load goes
    through :func:`current_mean_force` into the offset equilibrium, not
    into the oscillatory excitation.

    ``axis_name``: when the frequency grid is sharded over a mesh axis
    (sequence parallelism inside ``shard_map``), the vRMS spectral moment is
    the ONLY frequency reduction in the fixed point — it completes across
    devices with a ``psum`` over that axis.

    Returns (B_drag (6,6) real damping, F_drag Cx (nw,6) drag excitation).
    """
    import jax

    vnode = node_motion(m, Xi, wave.w)                          # (N,nw,3)
    vrel = kin.u - vnode

    def vrms(unit):                                             # unit: (N,3)
        w2 = unit[..., None, :] ** 2                            # (N,1,3)
        s = ((vrel.re**2 + vrel.im**2) * w2).sum(axis=(-1, -2))
        if axis_name is not None:
            s = jax.lax.psum(s, axis_name)          # complete over w shards
        # double-where so padded nodes (s == 0 exactly) don't poison the
        # backward pass with d(sqrt)/ds = inf at 0
        s_safe = jnp.where(s > 0, s, 1.0)
        return jnp.where(s > 0, jnp.sqrt(s_safe), 0.0)          # (N,)

    vRMS_q = vrms(m.node_q)
    vRMS_p1 = vrms(m.node_p1)
    vRMS_p2 = vrms(m.node_p2)

    # steady current shifts the linearization point: the Borgman factor
    # sqrt(8/pi)*sigma generalizes to the exact Gaussian MMSE slope about
    # the mean flow (identical when env.current == 0)
    uc = node_current(m, env)
    U_q = (uc * m.node_q).sum(-1)
    U_p1 = (uc * m.node_p1).sum(-1)
    U_p2 = (uc * m.node_p2).sum(-1)

    a_q, a_p1, a_p2, a_end = _drag_areas(m)

    half_rho = 0.5 * env.rho
    Bq = _gauss_drag_slope(U_q, vRMS_q) * half_rho * a_q * m.node_Cd_q
    Bp1 = _gauss_drag_slope(U_p1, vRMS_p1) * half_rho * a_p1 * m.node_Cd_p1
    Bp2 = _gauss_drag_slope(U_p2, vRMS_p2) * half_rho * a_p2 * m.node_Cd_p2
    Bend = _gauss_drag_slope(U_q, vRMS_q) * half_rho * a_end * m.node_Cd_end

    qq, p1p1, p2p2 = _direction_mats(m)
    Bmat = (
        (Bq + Bend)[..., None, None] * qq
        + Bp1[..., None, None] * p1p1
        + Bp2[..., None, None] * p2p2
    )
    Bmat = Bmat * _submerged(m).astype(Bmat.dtype)[..., None, None]

    B6 = translate_matrix_3to6(m.node_r, Bmat).sum(axis=-3)

    # drag excitation uses the undisturbed wave velocity (raft/raft.py:2238)
    F3 = cplx.einsum("...nij,...nwj->...nwi", Bmat, kin.u)
    F6 = _translate_force_cx(m.node_r, F3).sum(axis=-3)         # (nw,6)
    return B6, F6
