"""Analytic elastic catenary with seabed contact — the quasi-static line model.

TPU-native replacement for the catenary kernel of MoorPy (external dep of the
reference, used via ``ms.solveEquilibrium3``/``getCoupledStiffness`` at
raft/raft.py:1343-1355).  Solves for the fairlead tension components (H, V)
of a single mooring line given its horizontal/vertical end-to-end spans, by a
fixed-iteration damped Newton on the closed-form elastic catenary equations
(the MAP/Jonkman formulation):

Fully suspended (vertical anchor tension V - wL >= 0):
  xf = (H/w)[asinh(V/H) - asinh((V-wL)/H)] + H L/EA
  zf = (H/w)[sqrt(1+(V/H)^2) - sqrt(1+((V-wL)/H)^2)] + (V L - w L^2/2)/EA

Seabed contact (V < wL; resting length LB = L - V/w):
  xf = LB + (H/w) asinh(V/H) + H L/EA + friction term
  zf = (H/w)[sqrt(1+(V/H)^2) - 1] + V^2/(2 EA w)

Seabed friction (coefficient CB, per MAP/MoorPy): along the grounded
portion the horizontal tension decays from H at touchdown at rate CB*w per
unit length, so the anchor-end tension is Ha = max(H - CB*w*LB, 0); if it
reaches zero at x0 = LB - H/(CB*w) > 0 the segment [0, x0] is slack.  Only
the elastic stretch of the grounded portion changes: integral of T ds is
  H*LB - CB*w*LB^2/2          (tension positive all along, x0 <= 0)
  H^2/(2*CB*w)                (slack segment present,    x0 > 0)
which folds into the xf residual as
  (CB*w/(2*EA)) * (x0*max(x0,0) - LB^2)
added to the frictionless H*L/EA term (exactly 0 as CB -> 0).

The branch is selected per-iteration with ``jnp.where`` so the whole solve is
shape-static, vmappable over a line batch, and differentiable (fixed Newton
iteration count; gradients flow through the converged iterates).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

Array = jnp.ndarray

_H_MIN = 1e-6


@struct.dataclass
class LineProps:
    """Per-line scalar properties (batch with a leading axis)."""

    L: Array      # unstretched length [m]
    w: Array      # submerged weight per unit length [N/m]
    EA: Array     # axial stiffness [N]
    CB: Array = 0.0  # seabed friction coefficient [-] (MAP/MoorPy convention)


@struct.dataclass
class CatenaryState:
    H: Array          # horizontal fairlead tension [N]
    V: Array          # vertical fairlead tension [N]
    Ta: Array         # anchor tension magnitude [N]
    Tf: Array         # fairlead tension magnitude [N]
    LB: Array         # line length resting on the seabed [m]
    residual: Array   # max |residual| of the catenary equations [m]


def _profile_residual(H: Array, V: Array, xf: Array, zf: Array, p: LineProps):
    """Residuals (x_model - xf, z_model - zf) with the seabed/suspended branch
    chosen by the current V."""
    w, L, EA = p.w, p.L, p.EA
    Va = V - w * L                      # vertical tension at the anchor
    touchdown = Va < 0.0

    s_f = V / H
    s_a = Va / H
    sq_f = jnp.sqrt(1.0 + s_f * s_f)
    sq_a = jnp.sqrt(1.0 + s_a * s_a)

    x_susp = (H / w) * (jnp.arcsinh(s_f) - jnp.arcsinh(s_a)) + H * L / EA
    z_susp = (H / w) * (sq_f - sq_a) + (V * L - 0.5 * w * L * L) / EA

    LB = jnp.clip(L - V / w, 0.0, None)
    # seabed friction: stretch of the grounded portion under linearly
    # decaying tension (double-where so CB=0 stays NaN-free under grad)
    cbw = p.CB * w
    cbw_safe = jnp.where(cbw > 0, cbw, 1.0)
    x0 = LB - H / cbw_safe
    fric = jnp.where(
        cbw > 0,
        (cbw / (2.0 * EA)) * (x0 * jnp.clip(x0, 0.0, None) - LB * LB),
        0.0,
    )
    x_td = LB + (H / w) * jnp.arcsinh(s_f) + H * L / EA + fric
    z_td = (H / w) * (sq_f - 1.0) + V * V / (2.0 * EA * w)

    rx = jnp.where(touchdown, x_td, x_susp) - xf
    rz = jnp.where(touchdown, z_td, z_susp) - zf
    return rx, rz


def initial_guess(xf: Array, zf: Array, p: LineProps):
    """MAP-style starting point for (H, V) (Jonkman 2009, App. B)."""
    L, w = p.L, p.w
    slack = L * L > xf * xf + zf * zf
    arg = jnp.clip((L * L - zf * zf) / jnp.clip(xf * xf, 1e-12, None) - 1.0, 1e-6, None)
    lam = jnp.where(slack, jnp.sqrt(3.0 * arg), 0.2)
    lam = jnp.where(xf <= 1e-6, 1000.0, lam)
    H0 = jnp.clip(jnp.abs(0.5 * w * xf / lam), 10.0, None)
    V0 = 0.5 * w * (zf / jnp.tanh(lam) + L)
    return H0, V0


def solve_catenary(
    xf: Array, zf: Array, p: LineProps, iters: int = 60
) -> CatenaryState:
    """Solve the catenary equations for (H, V) by damped Newton.

    All arguments broadcast; a batch of lines is solved in one fused kernel.
    The 2x2 Newton system is inverted in closed form; steps are clamped to a
    trust factor of the current iterate to keep early iterations stable.
    """
    H0, V0 = initial_guess(xf, zf, p)

    def body(carry, _):
        H, V = carry
        rx, rz = _profile_residual(H, V, xf, zf, p)
        (drx_dH, drx_dV), (drz_dH, drz_dV) = _jac(H, V, xf, zf, p)
        det = drx_dH * drz_dV - drx_dV * drz_dH
        det = jnp.where(jnp.abs(det) > 1e-30, det, 1e-30)
        # closed-form 2x2 solve: [dH dV] = -J^-1 r
        dH = (-rx * drz_dV + rz * drx_dV) / det
        dV = (-rz * drx_dH + rx * drz_dH) / det
        # damp: limit the step to 50% of the current magnitude (+ floor)
        capH = 0.5 * jnp.abs(H) + 1.0
        capV = 0.5 * jnp.abs(V) + 1.0
        dH = jnp.clip(dH, -capH, capH)
        dV = jnp.clip(dV, -capV, capV)
        H_new = jnp.clip(H + dH, _H_MIN, None)
        V_new = V + dV
        return (H_new, V_new), None

    (H, V), _ = jax.lax.scan(body, (H0, V0), None, length=iters)
    rx, rz = _profile_residual(H, V, xf, zf, p)
    Va = jnp.clip(V - p.w * p.L, 0.0, None)
    LB = jnp.clip(p.L - V / p.w, 0.0, None)
    # anchor-end horizontal tension is reduced by seabed friction over LB
    Ha = jnp.where(
        V < p.w * p.L, jnp.clip(H - p.CB * p.w * LB, 0.0, None), H
    )
    # double-where sqrt guard: a fully slack anchor (Ha = Va = 0, possible
    # with friction) must give Ta = 0 with zero — not NaN — gradient
    Ta2 = Ha * Ha + Va * Va
    Ta = jnp.where(Ta2 > 0, jnp.sqrt(jnp.where(Ta2 > 0, Ta2, 1.0)), 0.0)
    return CatenaryState(
        H=H,
        V=V,
        Ta=Ta,
        Tf=jnp.sqrt(H * H + V * V),
        LB=LB,
        residual=jnp.maximum(jnp.abs(rx), jnp.abs(rz)),
    )


def _jac(H, V, xf, zf, p):
    """Analytic-free Jacobian of the residuals via forward-mode autodiff."""
    fH = lambda h: jnp.stack(_profile_residual(h, V, xf, zf, p))
    fV = lambda v: jnp.stack(_profile_residual(H, v, xf, zf, p))
    dH = jax.jvp(fH, (H,), (jnp.ones_like(H),))[1]
    dV = jax.jvp(fV, (V,), (jnp.ones_like(V),))[1]
    return (dH[0], dV[0]), (dH[1], dV[1])
