"""Quasi-static mooring: catenary lines, system equilibrium, stiffness."""
from raft_tpu.mooring.catenary import (  # noqa: F401
    CatenaryState,
    LineProps,
    solve_catenary,
)
from raft_tpu.mooring.system import (  # noqa: F401
    MooringSystem,
    fairlead_positions,
    fairlead_tensions,
    line_states,
    mooring_force,
    mooring_stiffness,
    parse_mooring,
    scale_mooring,
    solve_equilibrium,
    tension_jacobian,
)
