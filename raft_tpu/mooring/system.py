"""Quasi-static mooring system: parse, equilibrium, linearized stiffness.

TPU-native replacement for the MoorPy surface the reference consumes
(raft/raft.py:1256-1355): ``System.parseYAML`` -> :func:`parse_mooring`;
``solveEquilibrium3`` -> :func:`solve_equilibrium`; ``getCoupledStiffness`` /
``getForces(lines_only=True)`` -> :func:`mooring_stiffness` /
:func:`mooring_force`.

Design: the mooring system is a pytree of stacked line arrays
(:class:`MooringSystem`).  Every quantity is a pure function of the 6-DOF
platform displacement ``r6``; the linearized stiffness is simply
``-jax.jacfwd`` of the restoring force — strictly more capable than the
reference's finite-difference-free MoorPy call because it is exact and
differentiable end-to-end (the route to `jax.grad` co-design through the
mooring system).

The body restoring (hydrostatics + gravity) used during equilibrium is the
linearized set assembled by :mod:`raft_tpu.statics`, matching the data the
reference pushes into the MoorPy body at raft/raft.py:2007-2011.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from raft_tpu.core.linalg6 import solve_re
from raft_tpu.mooring.catenary import CatenaryState, LineProps, solve_catenary

Array = jnp.ndarray


@struct.dataclass
class MooringSystem:
    """Stacked single-body mooring system (nl lines, vessel<->anchor)."""

    r_anchor: Array      # (nl,3) anchor positions, global frame
    r_fair_body: Array   # (nl,3) fairlead positions in the body frame
    props: LineProps     # per-line L/w/EA, each (nl,)
    depth: Array         # () water depth [m]
    yaw_stiffness: Array = struct.field(default=0.0)  # additive C[5,5] (raft/raft.py:1264-1268)


def scale_mooring(sys: MooringSystem, theta) -> MooringSystem:
    """Differentiable mooring design knobs: ``theta = (L, R, EA)`` scales.

    * ``theta[0]`` — unstretched line length
    * ``theta[1]`` — anchor radius (horizontal anchor distance from the
      platform centerline; water depth unchanged)
    * ``theta[2]`` — axial stiffness EA

    The standard co-design parameterization over the reference mooring
    schema (raft/OC3spar.yaml:80-147: line ``length``, anchor point
    coordinates, line-type ``stiffness``).  All three enter the catenary
    Newton solve, so responses and stiffnesses differentiate exactly
    w.r.t. theta (mooring/system.py jacfwd stack).
    """
    theta = jnp.asarray(theta)
    props = sys.props.replace(L=sys.props.L * theta[0],
                              EA=sys.props.EA * theta[2])
    r_anchor = jnp.concatenate(
        [sys.r_anchor[:, :2] * theta[1], sys.r_anchor[:, 2:]], axis=1
    )
    return sys.replace(props=props, r_anchor=r_anchor)


def parse_mooring(mooring: dict, rho: float = 1025.0, g: float = 9.81,
                  yaw_stiffness: float = 0.0) -> MooringSystem:
    """Build a :class:`MooringSystem` from the design-YAML ``mooring`` dict.

    Schema (cf. the reference design files, e.g. raft/OC3spar.yaml:80-147):
    ``points`` (type fixed|vessel), ``lines`` (endA/endB point names, type,
    length), ``line_types`` (diameter, mass_density, stiffness).  The
    submerged weight uses the volume-equivalent diameter convention:
    w = g (m_lin - rho pi/4 d^2).
    """
    pts = {p["name"]: p for p in mooring["points"]}
    types = {t["name"]: t for t in mooring["line_types"]}
    anchors, fairs, Ls, ws, EAs, CBs = [], [], [], [], [], []
    for ln in mooring["lines"]:
        a, b = pts[ln["endA"]], pts[ln["endB"]]
        if a["type"] == "vessel":                 # normalize: A = anchor side
            a, b = b, a
        if a["type"] != "fixed" or b["type"] != "vessel":
            raise ValueError(
                f"line {ln['name']}: only fixed<->vessel lines are supported"
            )
        t = types[ln["type"]]
        anchors.append(a["location"])
        fairs.append(b["location"])
        Ls.append(ln["length"])
        m_lin = float(t["mass_density"])
        d = float(t["diameter"])
        ws.append(g * (m_lin - rho * np.pi / 4.0 * d * d))
        EAs.append(float(t["stiffness"]))
        CBs.append(float(t.get("seabed_friction", t.get("cb", 0.0))))
    return MooringSystem(
        r_anchor=jnp.asarray(np.array(anchors, dtype=float)),
        r_fair_body=jnp.asarray(np.array(fairs, dtype=float)),
        props=LineProps(
            L=jnp.asarray(Ls, dtype=float),
            w=jnp.asarray(ws, dtype=float),
            EA=jnp.asarray(EAs, dtype=float),
            CB=jnp.asarray(CBs, dtype=float),
        ),
        depth=jnp.asarray(float(mooring.get("water_depth", 300.0))),
        yaw_stiffness=jnp.asarray(float(yaw_stiffness)),
    )


def _rotation(r6: Array) -> Array:
    """Roll-pitch-yaw rotation matrix R = Rz(yaw) Ry(pitch) Rx(roll)."""
    cr, sr = jnp.cos(r6[3]), jnp.sin(r6[3])
    cp, sp = jnp.cos(r6[4]), jnp.sin(r6[4])
    cy, sy = jnp.cos(r6[5]), jnp.sin(r6[5])
    Rx = jnp.array([[1.0, 0.0, 0.0], [0.0, cr, -sr], [0.0, sr, cr]])
    Ry = jnp.array([[cp, 0.0, sp], [0.0, 1.0, 0.0], [-sp, 0.0, cp]])
    Rz = jnp.array([[cy, -sy, 0.0], [sy, cy, 0.0], [0.0, 0.0, 1.0]])
    return Rz @ Ry @ Rx


def fairlead_positions(sys: MooringSystem, r6: Array) -> Array:
    """Global fairlead positions for platform displacement r6 (nl,3)."""
    R = _rotation(r6)
    return r6[:3] + sys.r_fair_body @ R.T


def line_states(sys: MooringSystem, r6: Array) -> CatenaryState:
    """Solve every line's catenary at the given platform displacement."""
    rf = fairlead_positions(sys, r6)
    dxy = rf[:, :2] - sys.r_anchor[:, :2]
    xf = jnp.sqrt(jnp.sum(dxy * dxy, axis=-1) + 1e-12)
    zf = rf[:, 2] - sys.r_anchor[:, 2]
    return solve_catenary(xf, zf, sys.props)


@jax.jit
def mooring_force(sys: MooringSystem, r6: Array) -> Array:
    """Net 6-DOF mooring load on the platform at displacement r6.

    Equivalent of MoorPy ``getForces(DOFtype='coupled', lines_only=True)``
    (raft/raft.py:1326).  Per line: horizontal pull H toward the anchor,
    vertical pull V downward, applied at the fairlead.
    """
    rf = fairlead_positions(sys, r6)
    dxy = sys.r_anchor[:, :2] - rf[:, :2]
    dist = jnp.sqrt(jnp.sum(dxy * dxy, axis=-1) + 1e-12)
    u = dxy / dist[:, None]                        # unit vector toward anchor
    st = line_states(sys, r6)
    F3 = jnp.concatenate([st.H[:, None] * u, -st.V[:, None]], axis=-1)  # (nl,3)
    # moments about the displaced platform reference point (PRP at r6[:3])
    M3 = jnp.cross(rf - r6[:3], F3)
    return jnp.concatenate([F3.sum(axis=0), M3.sum(axis=0)])


@jax.jit
def mooring_stiffness(sys: MooringSystem, r6: Array) -> Array:
    """Linearized 6x6 mooring stiffness about r6: C = -d F_moor / d r6.

    Equivalent of MoorPy ``getCoupledStiffness(lines_only=True)``
    (raft/raft.py:1325,1354), computed exactly by forward-mode autodiff
    through the catenary Newton solve.  The manual yaw-spring addition of the
    reference (raft/raft.py:1359) is folded in here.
    """
    C = -jax.jacfwd(lambda x: mooring_force(sys, x))(r6)
    return C.at[5, 5].add(sys.yaw_stiffness)


@jax.jit
def fairlead_tensions(sys: MooringSystem, r6: Array) -> Array:
    """Fairlead tension magnitude per line at platform displacement r6 (nl,)."""
    return line_states(sys, r6).Tf


@jax.jit
def tension_jacobian(sys: MooringSystem, r6: Array) -> Array:
    """d T_fairlead / d r6 — (nl, 6), exact via forward-mode autodiff.

    The reference documents fairlead-tension RAOs as an intended output in
    a commented MATLAB-heritage block (raft/raft.py:1655-1708); combined
    with the platform response this linearization delivers them:
    ``T_RAO(w) = J @ Xi(w)``.  Jitted so facade callers (calcOutputs,
    incl. the per-turbine array loop) hit one cached compilation per
    mooring structure instead of an eager trace per call.
    """
    return jax.jacfwd(lambda x: fairlead_tensions(sys, x))(r6)


@partial(jax.jit, static_argnames=("iters",))
def solve_equilibrium(
    sys: MooringSystem,
    F_const: Array,
    C_body: Array,
    r6_init: Array | None = None,
    iters: int = 40,
) -> tuple[Array, Array]:
    """Mean-offset equilibrium of the moored platform.

    Equivalent of MoorPy ``solveEquilibrium3(DOFtype='both')``
    (raft/raft.py:1343): find r6 with
    ``F_const - C_body r6 + F_moor(r6) = 0`` where ``F_const`` collects
    weight + buoyancy + thrust (the reference's body.f6Ext) and ``C_body``
    is the linearized hydrostatic + gravitational stiffness from statics.

    Damped Newton with a fixed iteration count (shape-static, vmappable,
    differentiable).  Returns (r6_eq, residual_norm).
    """
    if r6_init is None:
        r6_init = jnp.zeros(6, dtype=sys.r_anchor.dtype)

    def residual(r6):
        return F_const - C_body @ r6 + mooring_force(sys, r6)

    def body(r6, _):
        r = residual(r6)
        J = jax.jacfwd(residual)(r6)
        dx = solve_re(J, -r)
        # clamp translation steps to 10 m and rotation steps to 0.1 rad
        cap = jnp.array([10.0, 10.0, 10.0, 0.1, 0.1, 0.1], dtype=r6.dtype)
        dx = jnp.clip(dx, -cap, cap)
        return r6 + dx, None

    r6, _ = jax.lax.scan(body, r6_init, None, length=iters)
    res = residual(r6)
    return r6, jnp.sqrt(jnp.sum(res * res))
