"""Command-line driver: the runRAFT recipe as a console entry point.

Equivalent of the reference's ``python runRAFT.py`` flow
(raft/runRAFT.py:23-82, :212-216), with the design selectable by path or by
the bundled names (oc3 / oc4 / volturn) and the environment configurable
from the command line (the reference accepts an env file argument but never
reads it; here the knobs are real).
"""
from __future__ import annotations

import argparse
import json
import os

import numpy as np

_BUNDLED = {
    "oc3": "OC3spar.yaml",
    "oc4": "OC4semi.yaml",
    "oc4_2": "OC4semi_2.yaml",
    "volturn": "VolturnUS-S.yaml",
}


def main(argv=None):
    p = argparse.ArgumentParser(description="raft_tpu frequency-domain analysis")
    p.add_argument("design", help="design YAML path or bundled name: "
                                  + "/".join(_BUNDLED))
    p.add_argument("--hs", type=float, default=8.0, help="significant wave height [m]")
    p.add_argument("--tp", type=float, default=12.0, help="peak period [s]")
    p.add_argument("--wind", type=float, default=10.0, help="wind speed [m/s]")
    p.add_argument("--beta", type=float, default=0.0, help="wave heading [deg]")
    p.add_argument("--thrust", type=float, default=None,
                   help="rotor thrust [N] (default: design Fthrust)")
    p.add_argument("--wmin", type=float, default=0.05)
    p.add_argument("--wmax", type=float, default=3.0)
    p.add_argument("--dw", type=float, default=0.05)
    p.add_argument("--bem", action="store_true",
                   help="run the native BEM solver for potMod members")
    p.add_argument("--irr", action="store_true",
                   help="irregular-frequency removal (waterplane lid) in the BEM solve")
    p.add_argument("--n-turbines", type=int, default=1,
                   help="analyze N identical turbines as an array (nDOF = 6N)")
    p.add_argument("--plot", action="store_true")
    p.add_argument("--json", action="store_true", help="print results as JSON")
    args = p.parse_args(argv)

    from raft_tpu.model import Model, load_design

    path = args.design
    if path in _BUNDLED:
        path = os.path.join(os.path.dirname(__file__), "designs", _BUNDLED[path])
    design = load_design(path)
    thrust = args.thrust
    if thrust is None:
        thrust = float(design.get("turbine", {}).get("Fthrust", 0.0))

    model = Model(design, w=np.arange(args.wmin, args.wmax, args.dw),
                  BEM="native" if args.bem else None,
                  nTurbines=args.n_turbines)
    model.setEnv(Hs=args.hs, Tp=args.tp, V=args.wind,
                 beta=np.deg2rad(args.beta), Fthrust=thrust)
    if args.bem and args.irr:
        model.calcBEM(irr=True)
    model.calcSystemProps()
    model.solveEigen()
    model.calcMooringAndOffsets()
    model.solveDynamics()
    results = model.calcOutputs()

    if args.json:
        def clean(o):
            if isinstance(o, dict):
                return {k: clean(v) for k, v in o.items()}
            if isinstance(o, np.ndarray):
                return o.tolist() if not np.iscomplexobj(o) else np.abs(o).tolist()
            return o

        print(json.dumps(clean(results), default=str))
    else:
        model.print_report()
    if args.plot:
        import matplotlib.pyplot as plt

        model.plot()
        plt.savefig("raft_tpu_platform.png", dpi=120)
        print("wrote raft_tpu_platform.png")
    return results


def entry_point():
    """Console-script wrapper: setuptools calls sys.exit(return value), so
    swallow main()'s results dict and return a clean 0."""
    main()
    return 0


if __name__ == "__main__":
    entry_point()
