"""Command-line driver: the runRAFT recipe as a console entry point.

Equivalent of the reference's ``python runRAFT.py`` flow
(raft/runRAFT.py:23-82, :212-216), with the design selectable by path or by
the bundled names (oc3 / oc4 / volturn) and the environment configurable
from the command line (the reference accepts an env file argument but never
reads it; here the knobs are real).

Two additional subcommands expose the capabilities the reference has no
analog for:

* ``raft-tpu sweep <design> --param draft --lo 0.9 --hi 1.1 -n 100`` —
  batched design-variant sweep (one compiled vmapped solve).
* ``raft-tpu optimize <design> --params diameter draft --steps 20`` —
  gradient-based co-design minimizing the nacelle-acceleration std dev.
"""
from __future__ import annotations

import argparse
import json
import os

import numpy as np

_BUNDLED = {
    "oc3": "OC3spar.yaml",
    "oc4": "OC4semi.yaml",
    "oc4_2": "OC4semi_2.yaml",
    "volturn": "VolturnUS-S.yaml",
}


def _design_path(name: str) -> str:
    if name in _BUNDLED:
        return os.path.join(os.path.dirname(__file__), "designs", _BUNDLED[name])
    return name


def _add_env_args(p):
    p.add_argument("--hs", type=float, default=8.0, help="significant wave height [m]")
    p.add_argument("--tp", type=float, default=12.0, help="peak period [s]")
    p.add_argument("--thrust", type=float, default=None,
                   help="rotor thrust [N] (default: design Fthrust)")
    p.add_argument("--wmin", type=float, default=0.05)
    p.add_argument("--wmax", type=float, default=3.0)
    p.add_argument("--dw", type=float, default=0.05)
    p.add_argument("--current", type=float, default=0.0,
                   help="surface current speed [m/s]")
    p.add_argument("--current-heading", type=float, default=0.0,
                   help="current direction [deg]")
    p.add_argument("--current-exp", type=float, default=0.0,
                   help="power-law shear exponent (1/7 typical; 0 uniform)")


def _build_pipeline_inputs(args, headings=None):
    """Shared sweep/optimize/dlc setup: design ->
    (members, rna, env, wave, C_moor, model).

    Goes through the Model facade so the staged inputs match the analyze
    path exactly: thrust applied, mean equilibrium solved, mooring
    stiffness linearized about that offset (model.py calcMooringAndOffsets)
    — the nominal design's C_moor is then staged across all variants.
    With ``args.bem`` set the native BEM solve runs too; ``headings``
    (rad) stages a heading grid in that one solve (model._bem_headings)."""
    from raft_tpu.model import Model, load_design

    design = load_design(_design_path(args.design))
    thrust = args.thrust
    if thrust is None:
        thrust = float(design.get("turbine", {}).get("Fthrust", 0.0))
    use_bem = bool(getattr(args, "bem", False))
    model = Model(design, w=np.arange(args.wmin, args.wmax, args.dw),
                  BEM="native" if use_bem else None)
    env_kw = {}
    if headings is not None:
        # env.beta must sit inside the staged grid (calcBEM re-stages the
        # current heading's excitation by interpolation)
        env_kw["beta"] = float(np.asarray(headings, dtype=float)[0])
    if getattr(args, "current", 0.0):
        env_kw.update(
            current=args.current,
            current_heading=np.deg2rad(args.current_heading),
            current_exp=args.current_exp,
        )
    model.setEnv(Hs=args.hs, Tp=args.tp, Fthrust=thrust, **env_kw)
    if use_bem:
        # explicit call so the mesh knobs apply with OR without a heading
        # grid (calcSystemProps' implicit calcBEM would use its defaults)
        model.calcBEM(dz_max=getattr(args, "dz_max", 3.0),
                      da_max=getattr(args, "da_max", 2.0),
                      headings=(np.asarray(headings, dtype=float)
                                if headings is not None else None))
    model.calcSystemProps()
    model.calcMooringAndOffsets()
    return model.members, model.rna, model.env, model.wave, model.C_moor, model


def _param_fn(members, names):
    """Composite apply_fn over the named geometry knobs (theta per knob)."""
    from raft_tpu.parallel import (
        make_scale_plan, make_stretch_draft, scale_diameters,
    )

    fns = []
    for n in names:
        if n == "diameter":
            fns.append(scale_diameters)
        elif n == "draft":
            fns.append(make_stretch_draft(members))
        elif n == "plan":
            fns.append(make_scale_plan(members))
        else:
            raise SystemExit(f"unknown parameter {n!r} (diameter/draft/plan)")

    def apply(m, theta):
        import jax.numpy as jnp

        theta = jnp.atleast_1d(theta)
        for i, f in enumerate(fns):
            m = f(m, theta[i])
        return m

    return apply


def main_sweep(argv):
    p = argparse.ArgumentParser(prog="raft-tpu sweep",
                                description="batched design-variant sweep")
    p.add_argument("design")
    p.add_argument("--param", default="diameter",
                   choices=["diameter", "draft", "plan"])
    p.add_argument("--lo", type=float, default=0.9)
    p.add_argument("--hi", type=float, default=1.1)
    p.add_argument("-n", type=int, default=64, help="number of variants")
    _add_env_args(p)
    args = p.parse_args(argv)

    import jax.numpy as jnp

    from raft_tpu.parallel import sweep

    members, rna, env, wave, C_moor, _ = _build_pipeline_inputs(args)
    apply = _param_fn(members, [args.param])
    thetas = jnp.linspace(args.lo, args.hi, args.n)
    out = sweep(members, rna, env, wave, C_moor, thetas, apply_fn=apply)
    rows = {
        "param": args.param,
        "theta": np.linspace(args.lo, args.hi, args.n).tolist(),
        "std dev": out["std dev"].tolist(),
        "iterations": out["iterations"].tolist(),
    }
    print(json.dumps(rows))
    return rows


def main_dlc(argv):
    p = argparse.ArgumentParser(
        prog="raft-tpu dlc",
        description="design-load-case table: one design x many sea states "
                    "(Hs, Tp[, heading]) in one compiled batched solve",
    )
    p.add_argument("design")
    p.add_argument("--cases", required=True,
                   help="CSV file of rows 'Hs,Tp[,beta_deg]' (lines starting "
                        "with # and non-numeric header lines are skipped)")
    p.add_argument("--bem", action="store_true",
                   help="run the native BEM solver once, staging a heading "
                        "grid over the table's unique headings (per-case "
                        "excitation interpolated to its own heading)")
    p.add_argument("--thrust", type=float, default=None,
                   help="rotor thrust [N] (default: design Fthrust)")
    p.add_argument("--dz-max", type=float, default=3.0,
                   help="BEM mesh: max panel height [m]")
    p.add_argument("--da-max", type=float, default=2.0,
                   help="BEM mesh: max panel azimuthal width [m]")
    p.add_argument("--wmin", type=float, default=0.05)
    p.add_argument("--wmax", type=float, default=3.0)
    p.add_argument("--dw", type=float, default=0.05)
    args = p.parse_args(argv)

    rows = []
    with open(args.cases) as f:
        for lineno, ln in enumerate(f, 1):
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                continue
            try:
                rows.append([float(x) for x in ln.replace(",", " ").split()])
            except ValueError:
                if not rows:              # spreadsheet header line(s) before
                    continue              # the first numeric row
                raise SystemExit(
                    f"{args.cases}:{lineno}: non-numeric case row {ln!r} "
                    f"(rows are 'Hs,Tp' or 'Hs,Tp,beta_deg')"
                )
    if not rows:
        raise SystemExit(f"{args.cases}: no numeric case rows found")
    ncol = {len(r) for r in rows}
    if ncol not in ({2}, {3}):
        raise SystemExit(
            f"--cases rows must all be 'Hs,Tp' or all 'Hs,Tp,beta_deg'; "
            f"got column counts {sorted(ncol)}"
        )
    cases = np.asarray(rows, dtype=float)
    if cases.shape[1] == 3:
        cases[:, 2] = np.deg2rad(cases[:, 2])

    from raft_tpu.parallel import make_wave_states, sweep_sea_states

    # reuse the shared pipeline setup, staging the nominal mooring/statics
    # at the table's most severe case
    ea = argparse.Namespace(
        design=args.design, thrust=args.thrust, bem=args.bem,
        dz_max=args.dz_max, da_max=args.da_max,
        hs=float(cases[:, 0].max()),
        tp=float(cases[cases[:, 0].argmax(), 1]),
        wmin=args.wmin, wmax=args.wmax, dw=args.dw,
    )
    headings = np.unique(cases[:, 2]) if cases.shape[1] == 3 else None
    members, rna, env, wave, C_moor, model = _build_pipeline_inputs(
        ea, headings=headings if args.bem else None
    )
    bem = None
    if args.bem:
        # heading grid staged when the table carries headings, else the
        # single-heading solve from calcSystemProps
        bem = model._bem_headings if headings is not None else model.bem
    waves = make_wave_states(np.asarray(wave.w), cases, float(env.depth))
    out = sweep_sea_states(members, rna, env, waves, C_moor, bem=bem)
    result = {
        "cases": cases.tolist(),
        "columns": ["Hs", "Tp"] + (["beta_rad"] if cases.shape[1] == 3 else []),
        "std dev": out["std dev"].tolist(),
        "nacelle accel std dev": out["nacelle accel std dev"].tolist(),
        "iterations": out["iterations"].tolist(),
    }
    print(json.dumps(result))
    return result


def main_optimize(argv):
    p = argparse.ArgumentParser(prog="raft-tpu optimize",
                                description="gradient co-design: minimize "
                                            "nacelle-acceleration std dev")
    p.add_argument("design")
    p.add_argument("--params", nargs="+", default=["diameter"],
                   help="geometry knobs: diameter / draft / plan")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--lo", type=float, default=0.85)
    p.add_argument("--hi", type=float, default=1.2)
    _add_env_args(p)
    args = p.parse_args(argv)

    import jax.numpy as jnp

    from raft_tpu.parallel import optimize_design

    members, rna, env, wave, C_moor, _ = _build_pipeline_inputs(args)
    apply = _param_fn(members, args.params)
    res = optimize_design(
        members, rna, env, wave, C_moor,
        theta0=jnp.ones(len(args.params)), apply_fn=apply,
        steps=args.steps, learning_rate=args.lr, bounds=(args.lo, args.hi),
    )
    out = {
        "params": args.params,
        "theta": np.atleast_1d(res.theta).tolist(),
        "objective": res.objective,
        "history": res.history.tolist(),
    }
    print(json.dumps(out))
    return out


def main(argv=None):
    import sys

    # warm-start subsystem: persistent XLA compile cache + AOT executable
    # registry + BEM staging cache (RAFT_TPU_CACHE_DIR=off opts out; see
    # docs/usage.rst "Warm starts & caching")
    from raft_tpu import cache

    cache.enable()

    argv = list(sys.argv[1:] if argv is None else argv)
    # subcommand dispatch; a design file literally named like a subcommand
    # still wins (analyze ./sweep by path) because existing paths short-circuit
    if argv and argv[0] in ("sweep", "optimize", "dlc") and not os.path.isfile(argv[0]):
        return {"sweep": main_sweep, "optimize": main_optimize,
                "dlc": main_dlc}[argv[0]](argv[1:])
    p = argparse.ArgumentParser(
        description="raft_tpu frequency-domain analysis",
        epilog="subcommands: 'raft-tpu sweep ...' (batched design-variant "
               "sweep), 'raft-tpu dlc ...' (sea-state/heading case table), "
               "and 'raft-tpu optimize ...' (gradient co-design); see "
               "'raft-tpu <subcommand> --help'.",
    )
    p.add_argument("design", help="design YAML path or bundled name: "
                                  + "/".join(_BUNDLED))
    p.add_argument("--hs", type=float, default=8.0, help="significant wave height [m]")
    p.add_argument("--tp", type=float, default=12.0, help="peak period [s]")
    p.add_argument("--wind", type=float, default=10.0, help="wind speed [m/s]")
    p.add_argument("--beta", type=float, default=0.0, help="wave heading [deg]")
    p.add_argument("--thrust", type=float, default=None,
                   help="rotor thrust [N] (default: design Fthrust)")
    p.add_argument("--current", type=float, default=0.0,
                   help="surface current speed [m/s]")
    p.add_argument("--current-heading", type=float, default=0.0,
                   help="current direction [deg]")
    p.add_argument("--current-exp", type=float, default=0.0,
                   help="power-law shear exponent (1/7 typical; 0 uniform)")
    p.add_argument("--wmin", type=float, default=0.05)
    p.add_argument("--wmax", type=float, default=3.0)
    p.add_argument("--dw", type=float, default=0.05)
    p.add_argument("--bem", action="store_true",
                   help="run the native BEM solver for potMod members")
    p.add_argument("--irr", action="store_true",
                   help="irregular-frequency removal (waterplane lid) in the BEM solve")
    p.add_argument("--n-turbines", type=int, default=1,
                   help="analyze N identical turbines as an array (nDOF = 6N)")
    p.add_argument("--plot", action="store_true")
    p.add_argument("--json", action="store_true", help="print results as JSON")
    args = p.parse_args(argv)

    from raft_tpu.model import Model, load_design

    design = load_design(_design_path(args.design))
    thrust = args.thrust
    if thrust is None:
        thrust = float(design.get("turbine", {}).get("Fthrust", 0.0))

    model = Model(design, w=np.arange(args.wmin, args.wmax, args.dw),
                  BEM="native" if args.bem else None,
                  nTurbines=args.n_turbines)
    model.setEnv(Hs=args.hs, Tp=args.tp, V=args.wind,
                 beta=np.deg2rad(args.beta), Fthrust=thrust,
                 current=args.current,
                 current_heading=np.deg2rad(args.current_heading),
                 current_exp=args.current_exp)
    if args.bem and args.irr:
        model.calcBEM(irr=True)
    model.calcSystemProps()
    model.solveEigen()
    model.calcMooringAndOffsets()
    model.solveDynamics()
    results = model.calcOutputs()

    if args.json:
        def clean(o):
            if isinstance(o, dict):
                return {k: clean(v) for k, v in o.items()}
            if isinstance(o, np.ndarray):
                return o.tolist() if not np.iscomplexobj(o) else np.abs(o).tolist()
            return o

        print(json.dumps(clean(results), default=str))
    else:
        model.print_report()
    if args.plot:
        import sys

        import matplotlib

        # the CLI only ever savefig()s, so Agg is right — but a notebook
        # calling main() programmatically already has pyplot (and its
        # interactive backend) loaded, and an explicit MPLBACKEND is the
        # user's choice either way: clobber neither
        if ("matplotlib.pyplot" not in sys.modules
                and "MPLBACKEND" not in os.environ):
            matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        model.plot()
        plt.savefig("raft_tpu_platform.png", dpi=120)
        print("wrote raft_tpu_platform.png")
        model.plot_raos()
        plt.savefig("raft_tpu_raos.png", dpi=120)
        print("wrote raft_tpu_raos.png")
    return results


def entry_point():
    """Console-script wrapper: setuptools calls sys.exit(return value), so
    swallow main()'s results dict and return a clean 0."""
    main()
    return 0


if __name__ == "__main__":
    entry_point()
