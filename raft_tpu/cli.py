"""Command-line driver: the runRAFT recipe as a console entry point.

Equivalent of the reference's ``python runRAFT.py`` flow
(raft/runRAFT.py:23-82, :212-216), with the design selectable by path or by
the bundled names (oc3 / oc4 / volturn) and the environment configurable
from the command line (the reference accepts an env file argument but never
reads it; here the knobs are real).

Two additional subcommands expose the capabilities the reference has no
analog for:

* ``raft-tpu sweep <design> --param draft --lo 0.9 --hi 1.1 -n 100`` —
  batched design-variant sweep (one compiled vmapped solve).
* ``raft-tpu optimize <design> --params diameter draft --steps 20`` —
  gradient-based co-design minimizing the nacelle-acceleration std dev.
"""
from __future__ import annotations

import argparse
import json
import os

import numpy as np

_BUNDLED = {
    "oc3": "OC3spar.yaml",
    "oc4": "OC4semi.yaml",
    "oc4_2": "OC4semi_2.yaml",
    "volturn": "VolturnUS-S.yaml",
}


def _design_path(name: str) -> str:
    if name in _BUNDLED:
        return os.path.join(os.path.dirname(__file__), "designs", _BUNDLED[name])
    return name


def _add_env_args(p):
    p.add_argument("--hs", type=float, default=8.0, help="significant wave height [m]")
    p.add_argument("--tp", type=float, default=12.0, help="peak period [s]")
    p.add_argument("--thrust", type=float, default=None,
                   help="rotor thrust [N] (default: design Fthrust)")
    p.add_argument("--wmin", type=float, default=0.05)
    p.add_argument("--wmax", type=float, default=3.0)
    p.add_argument("--dw", type=float, default=0.05)


def _build_pipeline_inputs(args):
    """Shared sweep/optimize setup: design -> (members, rna, env, wave, C_moor).

    Goes through the Model facade so the staged inputs match the analyze
    path exactly: thrust applied, mean equilibrium solved, mooring
    stiffness linearized about that offset (model.py calcMooringAndOffsets)
    — the nominal design's C_moor is then staged across all variants."""
    from raft_tpu.model import Model, load_design

    design = load_design(_design_path(args.design))
    thrust = args.thrust
    if thrust is None:
        thrust = float(design.get("turbine", {}).get("Fthrust", 0.0))
    model = Model(design, w=np.arange(args.wmin, args.wmax, args.dw))
    model.setEnv(Hs=args.hs, Tp=args.tp, Fthrust=thrust)
    model.calcSystemProps()
    model.calcMooringAndOffsets()
    return model.members, model.rna, model.env, model.wave, model.C_moor


def _param_fn(members, names):
    """Composite apply_fn over the named geometry knobs (theta per knob)."""
    from raft_tpu.parallel import (
        make_scale_plan, make_stretch_draft, scale_diameters,
    )

    fns = []
    for n in names:
        if n == "diameter":
            fns.append(scale_diameters)
        elif n == "draft":
            fns.append(make_stretch_draft(members))
        elif n == "plan":
            fns.append(make_scale_plan(members))
        else:
            raise SystemExit(f"unknown parameter {n!r} (diameter/draft/plan)")

    def apply(m, theta):
        import jax.numpy as jnp

        theta = jnp.atleast_1d(theta)
        for i, f in enumerate(fns):
            m = f(m, theta[i])
        return m

    return apply


def main_sweep(argv):
    p = argparse.ArgumentParser(prog="raft-tpu sweep",
                                description="batched design-variant sweep")
    p.add_argument("design")
    p.add_argument("--param", default="diameter",
                   choices=["diameter", "draft", "plan"])
    p.add_argument("--lo", type=float, default=0.9)
    p.add_argument("--hi", type=float, default=1.1)
    p.add_argument("-n", type=int, default=64, help="number of variants")
    _add_env_args(p)
    args = p.parse_args(argv)

    import jax.numpy as jnp

    from raft_tpu.parallel import sweep

    members, rna, env, wave, C_moor = _build_pipeline_inputs(args)
    apply = _param_fn(members, [args.param])
    thetas = jnp.linspace(args.lo, args.hi, args.n)
    out = sweep(members, rna, env, wave, C_moor, thetas, apply_fn=apply)
    rows = {
        "param": args.param,
        "theta": np.linspace(args.lo, args.hi, args.n).tolist(),
        "std dev": out["std dev"].tolist(),
        "iterations": out["iterations"].tolist(),
    }
    print(json.dumps(rows))
    return rows


def main_optimize(argv):
    p = argparse.ArgumentParser(prog="raft-tpu optimize",
                                description="gradient co-design: minimize "
                                            "nacelle-acceleration std dev")
    p.add_argument("design")
    p.add_argument("--params", nargs="+", default=["diameter"],
                   help="geometry knobs: diameter / draft / plan")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--lo", type=float, default=0.85)
    p.add_argument("--hi", type=float, default=1.2)
    _add_env_args(p)
    args = p.parse_args(argv)

    import jax.numpy as jnp

    from raft_tpu.parallel import optimize_design

    members, rna, env, wave, C_moor = _build_pipeline_inputs(args)
    apply = _param_fn(members, args.params)
    res = optimize_design(
        members, rna, env, wave, C_moor,
        theta0=jnp.ones(len(args.params)), apply_fn=apply,
        steps=args.steps, learning_rate=args.lr, bounds=(args.lo, args.hi),
    )
    out = {
        "params": args.params,
        "theta": np.atleast_1d(res.theta).tolist(),
        "objective": res.objective,
        "history": res.history.tolist(),
    }
    print(json.dumps(out))
    return out


def main(argv=None):
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    # subcommand dispatch; a design file literally named like a subcommand
    # still wins (analyze ./sweep by path) because existing paths short-circuit
    if argv and argv[0] in ("sweep", "optimize") and not os.path.isfile(argv[0]):
        return {"sweep": main_sweep, "optimize": main_optimize}[argv[0]](argv[1:])
    p = argparse.ArgumentParser(
        description="raft_tpu frequency-domain analysis",
        epilog="subcommands: 'raft-tpu sweep ...' (batched design-variant "
               "sweep) and 'raft-tpu optimize ...' (gradient co-design); "
               "see 'raft-tpu sweep --help' / 'raft-tpu optimize --help'.",
    )
    p.add_argument("design", help="design YAML path or bundled name: "
                                  + "/".join(_BUNDLED))
    p.add_argument("--hs", type=float, default=8.0, help="significant wave height [m]")
    p.add_argument("--tp", type=float, default=12.0, help="peak period [s]")
    p.add_argument("--wind", type=float, default=10.0, help="wind speed [m/s]")
    p.add_argument("--beta", type=float, default=0.0, help="wave heading [deg]")
    p.add_argument("--thrust", type=float, default=None,
                   help="rotor thrust [N] (default: design Fthrust)")
    p.add_argument("--wmin", type=float, default=0.05)
    p.add_argument("--wmax", type=float, default=3.0)
    p.add_argument("--dw", type=float, default=0.05)
    p.add_argument("--bem", action="store_true",
                   help="run the native BEM solver for potMod members")
    p.add_argument("--irr", action="store_true",
                   help="irregular-frequency removal (waterplane lid) in the BEM solve")
    p.add_argument("--n-turbines", type=int, default=1,
                   help="analyze N identical turbines as an array (nDOF = 6N)")
    p.add_argument("--plot", action="store_true")
    p.add_argument("--json", action="store_true", help="print results as JSON")
    args = p.parse_args(argv)

    from raft_tpu.model import Model, load_design

    design = load_design(_design_path(args.design))
    thrust = args.thrust
    if thrust is None:
        thrust = float(design.get("turbine", {}).get("Fthrust", 0.0))

    model = Model(design, w=np.arange(args.wmin, args.wmax, args.dw),
                  BEM="native" if args.bem else None,
                  nTurbines=args.n_turbines)
    model.setEnv(Hs=args.hs, Tp=args.tp, V=args.wind,
                 beta=np.deg2rad(args.beta), Fthrust=thrust)
    if args.bem and args.irr:
        model.calcBEM(irr=True)
    model.calcSystemProps()
    model.solveEigen()
    model.calcMooringAndOffsets()
    model.solveDynamics()
    results = model.calcOutputs()

    if args.json:
        def clean(o):
            if isinstance(o, dict):
                return {k: clean(v) for k, v in o.items()}
            if isinstance(o, np.ndarray):
                return o.tolist() if not np.iscomplexobj(o) else np.abs(o).tolist()
            return o

        print(json.dumps(clean(results), default=str))
    else:
        model.print_report()
    if args.plot:
        import matplotlib.pyplot as plt

        model.plot()
        plt.savefig("raft_tpu_platform.png", dpi=120)
        print("wrote raft_tpu_platform.png")
    return results


def entry_point():
    """Console-script wrapper: setuptools calls sys.exit(return value), so
    swallow main()'s results dict and return a clean 0."""
    main()
    return 0


if __name__ == "__main__":
    entry_point()
