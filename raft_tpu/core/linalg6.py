"""Unrolled small-matrix linear algebra for the 6-DOF hot path.

The RAO solve is thousands of *independent* 6x6 complex solves (one per
frequency bin per design — cf. the reference's per-frequency loop
``Xi = inv(Z) @ F`` at raft/raft.py:1528-1533).  Generic batched linalg is
unavailable on this TPU backend (LU/Cholesky/eigh lower to UNIMPLEMENTED
custom calls), and would be a poor fit anyway: for n=6, fully unrolled
elimination compiles to a single fused elementwise kernel over the batch,
with no dynamic control flow.

Everything here is batch-broadcast over leading axes and differentiable.

Kernels:
  * :func:`solve_cx`   — complex 6x6 solve (Gaussian elimination, partial
                         pivoting) on :class:`~raft_tpu.core.cplx.Cx` pairs.
  * :func:`solve_cx_fused` — the same solve with the RAO impedance
                         assembly ``Z = Z0 + i w B_drag`` fused into the
                         solve expression (XLA fuses the elementwise
                         assembly into the elimination's first consumer,
                         so the complex ``Z`` is never a standalone HBM
                         tensor) — the CPU/interpret twin of the Pallas
                         fused kernel (:func:`raft_tpu.core.pallas6.
                         solve_rao_pallas`).
  * :func:`solve_re`   — same for real systems.
  * :func:`eigh_jacobi`— symmetric eigendecomposition by fixed-sweep cyclic
                         Jacobi rotations (replaces np.linalg.eig of the
                         reference solveEigen, raft/raft.py:1394).
  * :func:`cholesky`   — unrolled Cholesky for SPD mass matrices.
  * :func:`generalized_eigh` — K x = lambda M x via Cholesky + Jacobi.

Large-matrix pure-jnp LU (the BEM 2n x 2n real panel systems — see the
pointer-portability note in :mod:`raft_tpu.hydro.jax_bem`: LAPACK custom
calls embed process-local pointers, so AOT-portable factorization must be
plain HLO):

  * :func:`lu_factor_unblocked` / :func:`lu_solve_unblocked` — the
    row-by-row scan (one rank-1 update per row), the bit-level reference.
  * :func:`lu_factor_blocked` / :func:`lu_solve_blocked` — blocked
    right-looking LU with partial pivoting: panel factorization with the
    pivot search over the FULL trailing column (so the pivot sequence
    matches the unblocked factorization up to roundoff ties), then one
    (m x b) @ (b x m) GEMM trailing update per panel — the O(m) rank-1
    latency chain collapses to O(m / b) GEMMs the MXU can saturate.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from raft_tpu.core.cplx import Cx

Array = jnp.ndarray

#: default panel width of the blocked LU: wide enough that the trailing
#: GEMM dominates, small enough that the unrolled in-panel elimination
#: keeps trace size bounded (b unrolled steps per scanned panel)
LU_BLOCK = 32


def _pivot_rows(col_mag: Array, k: int, n: int):
    """Row-permutation indices swapping row k with the max-magnitude row >= k.

    col_mag: (..., n) magnitudes of column k (entries < k should be masked
    by the caller).  Returns (..., n) int32 gather indices.
    """
    rows = jnp.arange(n)
    masked = jnp.where(rows >= k, col_mag, -1.0)
    piv = jnp.argmax(masked, axis=-1)  # (...,)
    pivb = piv[..., None]
    idx = jnp.broadcast_to(rows, masked.shape)
    idx = jnp.where(idx == k, pivb, jnp.where(idx == pivb, k, idx))
    return idx


def _gather_rows(A: Array, idx: Array) -> Array:
    """Gather rows of (...,n,m) by (...,n) indices."""
    return jnp.take_along_axis(A, idx[..., None], axis=-2)


def solve_cx(A: Cx, b: Cx, n: int = 6) -> Cx:
    """Solve complex A x = b, A: (...,n,n) Cx, b: (...,n) or (...,n,m) Cx.

    Unrolled Gaussian elimination with partial pivoting; all ops elementwise
    or gathers, so the whole batch compiles to one fused kernel.
    """
    vec = b.re.ndim == A.re.ndim - 1
    if vec:
        b = Cx(b.re[..., None], b.im[..., None])
    Ar, Ai = A.re, A.im
    br, bi = b.re, b.im
    for k in range(n):
        mag = Ar[..., :, k] ** 2 + Ai[..., :, k] ** 2  # (...,n)
        idx = _pivot_rows(mag, k, n)
        Ar = _gather_rows(Ar, idx)
        Ai = _gather_rows(Ai, idx)
        br = _gather_rows(br, idx)
        bi = _gather_rows(bi, idx)
        # eliminate rows below k
        den = Ar[..., k, k] ** 2 + Ai[..., k, k] ** 2
        den = jnp.where(den != 0, den, 1.0)
        fr = (Ar[..., :, k] * Ar[..., k : k + 1, k] + Ai[..., :, k] * Ai[..., k : k + 1, k]) / den[..., None]
        fi = (Ai[..., :, k] * Ar[..., k : k + 1, k] - Ar[..., :, k] * Ai[..., k : k + 1, k]) / den[..., None]
        below = jnp.arange(n) > k
        fr = jnp.where(below, fr, 0.0)
        fi = jnp.where(below, fi, 0.0)
        Ar, Ai = (
            Ar - (fr[..., None] * Ar[..., k : k + 1, :] - fi[..., None] * Ai[..., k : k + 1, :]),
            Ai - (fr[..., None] * Ai[..., k : k + 1, :] + fi[..., None] * Ar[..., k : k + 1, :]),
        )
        br, bi = (
            br - (fr[..., None] * br[..., k : k + 1, :] - fi[..., None] * bi[..., k : k + 1, :]),
            bi - (fr[..., None] * bi[..., k : k + 1, :] + fi[..., None] * br[..., k : k + 1, :]),
        )
    # back substitution
    xr = jnp.zeros_like(br)
    xi = jnp.zeros_like(bi)
    for k in range(n - 1, -1, -1):
        sr = br[..., k, :] - (
            jnp.einsum("...j,...jm->...m", Ar[..., k, k + 1 :], xr[..., k + 1 :, :])
            - jnp.einsum("...j,...jm->...m", Ai[..., k, k + 1 :], xi[..., k + 1 :, :])
        )
        si = bi[..., k, :] - (
            jnp.einsum("...j,...jm->...m", Ar[..., k, k + 1 :], xi[..., k + 1 :, :])
            + jnp.einsum("...j,...jm->...m", Ai[..., k, k + 1 :], xr[..., k + 1 :, :])
        )
        den = Ar[..., k, k] ** 2 + Ai[..., k, k] ** 2
        den = jnp.where(den != 0, den, 1.0)[..., None]
        xk_r = (sr * Ar[..., k, k][..., None] + si * Ai[..., k, k][..., None]) / den
        xk_i = (si * Ar[..., k, k][..., None] - sr * Ai[..., k, k][..., None]) / den
        xr = xr.at[..., k, :].set(xk_r)
        xi = xi.at[..., k, :].set(xk_i)
    x = Cx(xr, xi)
    if vec:
        x = Cx(x.re[..., 0], x.im[..., 0])
    return x


def assemble_impedance(Z0: Cx, w: Array, B_drag: Array) -> Cx:
    """``Z = Z0 + i w B_drag``: fold the per-iteration drag damping into a
    precomputed loop-invariant impedance ``Z0 = -w^2 M + i w B + C``.

    ``Z0``: (..., nw, 6, 6) Cx; ``w``: broadcastable to (..., nw);
    ``B_drag``: (..., 6, 6) real — one drag matrix per design, broadcast
    over the frequency axis.  Only the imaginary part changes, so the
    real part is passed through untouched (exactly bit-preserving).
    """
    return Cx(Z0.re, Z0.im + w[..., None, None] * B_drag[..., None, :, :])


def solve_cx_fused(Z0: Cx, w: Array, B_drag: Array, F: Cx, n: int = 6) -> Cx:
    """Fused RAO assemble+solve: ``x = (Z0 + i w B_drag)^-1 F``.

    The XLA fallback of the Pallas fused kernel
    (:func:`raft_tpu.core.pallas6.solve_rao_pallas`): the assembly is an
    elementwise expression feeding straight into :func:`solve_cx`, so XLA
    fuses it into the elimination and the assembled complex ``Z`` never
    round-trips through HBM inside the fixed point.  Fully transformable
    (vmap/jvp/grad/shard_map) — this is also the path the ``custom_vjp``
    adjoint falls back to for bit-comparability checks.
    """
    return solve_cx(assemble_impedance(Z0, w, B_drag), F, n=n)


def solve_re(A: Array, b: Array, n: int = 6) -> Array:
    """Real A x = b via the complex kernel (zero imaginary part)."""
    out = solve_cx(Cx(A, jnp.zeros_like(A)), Cx(b, jnp.zeros_like(b)), n=n)
    return out.re


def cholesky(M: Array, n: int = 6) -> Array:
    """Unrolled Cholesky factor L (lower) of SPD M: (...,n,n)."""
    L = jnp.zeros_like(M)
    for j in range(n):
        s = M[..., j, j] - jnp.einsum("...k,...k->...", L[..., j, :j], L[..., j, :j])
        ljj = jnp.sqrt(jnp.maximum(s, 1e-30))
        L = L.at[..., j, j].set(ljj)
        for i in range(j + 1, n):
            s = M[..., i, j] - jnp.einsum("...k,...k->...", L[..., i, :j], L[..., j, :j])
            L = L.at[..., i, j].set(s / ljj)
    return L


def solve_lower(L: Array, b: Array, n: int = 6) -> Array:
    """Solve L y = b with L lower-triangular, b: (...,n) or (...,n,m)."""
    vec = b.ndim == L.ndim - 1
    if vec:
        b = b[..., None]
    y = jnp.zeros_like(b)
    for i in range(n):
        s = b[..., i, :] - jnp.einsum("...k,...km->...m", L[..., i, :i], y[..., :i, :])
        y = y.at[..., i, :].set(s / L[..., i, i][..., None])
    return y[..., 0] if vec else y


def solve_upper(U: Array, b: Array, n: int = 6) -> Array:
    """Solve U y = b with U upper-triangular."""
    vec = b.ndim == U.ndim - 1
    if vec:
        b = b[..., None]
    y = jnp.zeros_like(b)
    for i in range(n - 1, -1, -1):
        s = b[..., i, :] - jnp.einsum("...k,...km->...m", U[..., i, i + 1 :], y[..., i + 1 :, :])
        y = y.at[..., i, :].set(s / U[..., i, i][..., None])
    return y[..., 0] if vec else y


def eigh_jacobi(M: Array, n: int = 6, sweeps: int = 12):
    """Eigendecomposition of symmetric M by cyclic Jacobi rotations.

    Returns (eigvals (...,n), eigvecs (...,n,n) with columns as vectors).
    Fixed sweep count -> static control flow; 12 sweeps is far past
    convergence for n=6 (quadratic convergence after ~3).
    """
    A = M
    V = jnp.zeros_like(M) + jnp.eye(n, dtype=M.dtype)
    for _ in range(sweeps):
        for p in range(n - 1):
            for q in range(p + 1, n):
                app = A[..., p, p]
                aqq = A[..., q, q]
                apq = A[..., p, q]
                # rotation angle: theta = 0.5 atan2(2 apq, aqq - app)
                theta = 0.5 * jnp.arctan2(2.0 * apq, aqq - app)
                c = jnp.cos(theta)[..., None]
                s = jnp.sin(theta)[..., None]
                # apply rotation on rows/cols p and q
                rowp = A[..., p, :]
                rowq = A[..., q, :]
                A = A.at[..., p, :].set(c * rowp - s * rowq)
                A = A.at[..., q, :].set(s * rowp + c * rowq)
                colp = A[..., :, p]
                colq = A[..., :, q]
                A = A.at[..., :, p].set(c * colp - s * colq)
                A = A.at[..., :, q].set(s * colp + c * colq)
                vp = V[..., :, p]
                vq = V[..., :, q]
                V = V.at[..., :, p].set(c * vp - s * vq)
                V = V.at[..., :, q].set(s * vp + c * vq)
    return jnp.diagonal(A, axis1=-2, axis2=-1), V


def generalized_eigh(K: Array, M: Array, n: int = 6, sweeps: int = 12):
    """Solve K x = lambda M x for symmetric K, SPD M.

    Used for natural frequencies (reference solveEigen uses eig(inv(M) C),
    raft/raft.py:1394; the symmetric reduction here is the numerically sound
    equivalent).  Returns (lambda (...,n), modes (...,n,n) columns).
    """
    L = cholesky(M, n=n)
    # A = L^-1 K L^-T
    Y = solve_lower(L, K, n=n)                       # L Y = K
    # Solve L Z^T = Y^T  => Z = Y L^-T: apply lower solve on transposed
    Z = solve_lower(L, jnp.swapaxes(Y, -1, -2), n=n)
    A = 0.5 * (Z + jnp.swapaxes(Z, -1, -2))          # symmetrize roundoff
    lam, V = eigh_jacobi(A, n=n, sweeps=sweeps)
    # modes: x = L^-T v
    X = solve_upper(jnp.swapaxes(L, -1, -2), V, n=n)
    return lam, X


# ------------------------------------------------- large-matrix pure-jnp LU
#
# The BEM panel systems (2n x 2n real, n up to 2048) need a factorization
# that serializes as plain HLO (no LAPACK custom calls — those embed
# process-local pointers and segfault on warm AOT deserialization) and
# stays vmap-able for frequency batching.  The unblocked scan is the
# reference; the blocked variant is the hot path.


def _ceil_to(m: int, b: int) -> int:
    return -(-m // b) * b


def _pad_identity(A: Array, mp: int) -> Array:
    """Embed (m, m) A in an (mp, mp) matrix with 1s on the padded diagonal.

    Padded rows/columns never interact with the real block under partially
    pivoted elimination: a padded row is all-zero in every real column (so
    it never wins a pivot search — argmax ties resolve to the first, i.e.
    real, candidate), and each padded column's only nonzero is its unit
    diagonal (so its pivot is itself and its multipliers are zero).
    """
    m = A.shape[0]
    out = jnp.zeros((mp, mp), A.dtype).at[:m, :m].set(A)
    pad = jnp.arange(m, mp)
    return out.at[pad, pad].set(1.0)


def lu_factor_unblocked(A: Array):
    """Row-by-row LU with partial pivoting: (LU, perm) in the LAPACK
    getrf layout (unit-L strictly below the diagonal, U on/above).

    One pivot search + rank-1 update per row — an O(m) sequential chain
    of O(m^2) updates.  Kept as the bit-level reference the blocked
    factorization is pinned against (tests/test_bem_tiles.py)."""
    m = A.shape[0]
    idx = jnp.arange(m)

    def step(carry, k):
        A, perm = carry
        col = A[:, k]
        mag = jnp.where(idx >= k, jnp.abs(col), -1.0)
        p = jnp.argmax(mag)
        rowk, rowp = A[k], A[p]
        A = A.at[k].set(rowp).at[p].set(rowk)
        pk, pp = perm[k], perm[p]
        perm = perm.at[k].set(pp).at[p].set(pk)
        piv = A[k, k]
        piv = jnp.where(jnp.abs(piv) > 1e-30, piv, 1e-30)
        f = jnp.where(idx > k, A[:, k] / piv, 0.0)
        rowk_u = jnp.where(idx >= k, A[k], 0.0)     # U part of the pivot row
        A = A - jnp.outer(f, rowk_u)
        A = A.at[:, k].set(jnp.where(idx > k, f, A[:, k]))
        return (A, perm), None

    (LU, perm), _ = lax.scan(step, (A, idx), jnp.arange(m))
    return LU, perm


def lu_solve_unblocked(LU: Array, perm: Array, B: Array) -> Array:
    """Forward/back substitution for all RHS columns at once (reference
    twin of :func:`lu_solve_blocked`)."""
    m = LU.shape[0]
    idx = jnp.arange(m)
    X = B[perm]

    def fwd(k, X):
        lk = jnp.where(idx < k, LU[k], 0.0)
        return X.at[k].add(-(lk @ X))

    X = lax.fori_loop(0, m, fwd, X)

    def bwd(i, X):
        k = m - 1 - i
        uk = jnp.where(idx > k, LU[k], 0.0)
        dk = LU[k, k]
        dk = jnp.where(jnp.abs(dk) > 1e-30, dk, 1e-30)
        return X.at[k].set((X[k] - uk @ X) / dk)

    return lax.fori_loop(0, m, bwd, X)


def lu_factor_blocked(A: Array, block: int = LU_BLOCK):
    """Blocked right-looking LU with partial pivoting, pure jnp.

    Same layout and (up to roundoff ties) same pivot sequence as
    :func:`lu_factor_unblocked`: each b-column panel is factored with the
    pivot search over the full trailing column height, the recorded swaps
    are replayed on the rest of the matrix, the U12 block-row is solved
    with the panel's unit-lower L11, and the trailing submatrix takes ONE
    (m x b) @ (b x m) masked GEMM update — so the sequential chain is
    m / b GEMM steps instead of m rank-1 updates.  Shapes not divisible
    by ``block`` are identity-padded internally (see
    :func:`_pad_identity`) and sliced back, so any m is accepted.
    """
    m = A.shape[0]
    mp = _ceil_to(m, block)
    if mp != m:
        A = _pad_identity(A, mp)
    idx = jnp.arange(mp)
    nb = mp // block
    cols = jnp.arange(block)

    def factor_panel(carry, kb):
        A, perm = carry
        k0 = kb * block
        P = lax.dynamic_slice(A, (0, k0), (mp, block))
        swaps = []
        for j in range(block):                      # static unroll: b steps
            kg = k0 + j
            mag = jnp.where(idx >= kg, jnp.abs(P[:, j]), -1.0)
            p = jnp.argmax(mag)
            rowk, rowp = P[kg], P[p]
            P = P.at[kg].set(rowp).at[p].set(rowk)
            swaps.append((kg, p))
            piv = P[kg, j]
            piv = jnp.where(jnp.abs(piv) > 1e-30, piv, 1e-30)
            f = jnp.where(idx > kg, P[:, j] / piv, 0.0)
            rowu = jnp.where(cols >= j, P[kg], 0.0)
            P = P - jnp.outer(f, rowu)
            P = P.at[:, j].set(jnp.where(idx > kg, f, P[:, j]))
        # replay the panel's swaps on the full matrix (previous L columns
        # AND trailing columns; the panel columns are overwritten below)
        for kg, p in swaps:
            rowk, rowp = A[kg], A[p]
            A = A.at[kg].set(rowp).at[p].set(rowk)
            pk, pp = perm[kg], perm[p]
            perm = perm.at[kg].set(pp).at[p].set(pk)
        A = lax.dynamic_update_slice(A, P, (0, k0))
        # U12 block-row: L11 U12 = A12 (unit-lower solve across the full
        # width, committed only on the trailing columns)
        L11 = lax.dynamic_slice(A, (k0, k0), (block, block))
        row = lax.dynamic_slice(A, (k0, 0), (block, mp))
        solved = row
        for r in range(1, block):
            solved = solved.at[r].add(-(L11[r, :r] @ solved[:r]))
        trail = idx >= k0 + block                   # (mp,) column mask
        row = jnp.where(trail[None, :], solved, row)
        A = lax.dynamic_update_slice(A, row, (k0, 0))
        # trailing GEMM update: A22 -= L21 @ U12 (masks make rows above
        # the panel and columns left of the trailing block no-ops)
        Lcol = lax.dynamic_slice(A, (0, k0), (mp, block))
        Lcol = jnp.where(trail[:, None], Lcol, 0.0)
        Urow = jnp.where(trail[None, :], row, 0.0)
        A = A - Lcol @ Urow
        return (A, perm), None

    (LU, perm), _ = lax.scan(factor_panel, (A, idx), jnp.arange(nb))
    return LU[:m, :m], perm[:m]


def lu_solve_blocked(LU: Array, perm: Array, B: Array,
                     block: int = LU_BLOCK) -> Array:
    """Blocked forward/back substitution for all RHS columns at once:
    per b-row block, an unrolled in-block triangular solve plus one
    (m x b) @ (b x nrhs) masked GEMM propagating it to the remaining
    rows.  Accepts any m (identity-padded internally like the factor)."""
    m = LU.shape[0]
    vec = B.ndim == 1
    if vec:
        B = B[:, None]
    mp = _ceil_to(m, block)
    if mp != m:
        LU = _pad_identity(LU, mp)
        perm = jnp.concatenate([perm, jnp.arange(m, mp)])
        B = jnp.concatenate(
            [B, jnp.zeros((mp - m, B.shape[1]), B.dtype)], axis=0)
    nrhs = B.shape[1]
    idx = jnp.arange(mp)
    nb = mp // block
    X = B[perm]

    def fwd(X, kb):
        k0 = kb * block
        Lb = lax.dynamic_slice(LU, (k0, k0), (block, block))
        Xb = lax.dynamic_slice(X, (k0, 0), (block, nrhs))
        for r in range(1, block):
            Xb = Xb.at[r].add(-(Lb[r, :r] @ Xb[:r]))
        X = lax.dynamic_update_slice(X, Xb, (k0, 0))
        Lcol = lax.dynamic_slice(LU, (0, k0), (mp, block))
        Lcol = jnp.where((idx >= k0 + block)[:, None], Lcol, 0.0)
        return X - Lcol @ Xb, None

    X, _ = lax.scan(fwd, X, jnp.arange(nb))

    def bwd(X, i):
        k0 = (nb - 1 - i) * block
        Ub = lax.dynamic_slice(LU, (k0, k0), (block, block))
        Xb = lax.dynamic_slice(X, (k0, 0), (block, nrhs))
        for r in range(block - 1, -1, -1):
            d = Ub[r, r]
            d = jnp.where(jnp.abs(d) > 1e-30, d, 1e-30)
            Xb = Xb.at[r].set((Xb[r] - Ub[r, r + 1:] @ Xb[r + 1:]) / d)
        X = lax.dynamic_update_slice(X, Xb, (k0, 0))
        Ucol = lax.dynamic_slice(LU, (0, k0), (mp, block))
        Ucol = jnp.where((idx < k0)[:, None], Ucol, 0.0)
        return X - Ucol @ Xb, None

    X, _ = lax.scan(bwd, X, jnp.arange(nb))
    X = X[:m]
    return X[:, 0] if vec else X
