"""Unrolled small-matrix linear algebra for the 6-DOF hot path.

The RAO solve is thousands of *independent* 6x6 complex solves (one per
frequency bin per design — cf. the reference's per-frequency loop
``Xi = inv(Z) @ F`` at raft/raft.py:1528-1533).  Generic batched linalg is
unavailable on this TPU backend (LU/Cholesky/eigh lower to UNIMPLEMENTED
custom calls), and would be a poor fit anyway: for n=6, fully unrolled
elimination compiles to a single fused elementwise kernel over the batch,
with no dynamic control flow.

Everything here is batch-broadcast over leading axes and differentiable.

Kernels:
  * :func:`solve_cx`   — complex 6x6 solve (Gaussian elimination, partial
                         pivoting) on :class:`~raft_tpu.core.cplx.Cx` pairs.
  * :func:`solve_cx_fused` — the same solve with the RAO impedance
                         assembly ``Z = Z0 + i w B_drag`` fused into the
                         solve expression (XLA fuses the elementwise
                         assembly into the elimination's first consumer,
                         so the complex ``Z`` is never a standalone HBM
                         tensor) — the CPU/interpret twin of the Pallas
                         fused kernel (:func:`raft_tpu.core.pallas6.
                         solve_rao_pallas`).
  * :func:`solve_re`   — same for real systems.
  * :func:`eigh_jacobi`— symmetric eigendecomposition by fixed-sweep cyclic
                         Jacobi rotations (replaces np.linalg.eig of the
                         reference solveEigen, raft/raft.py:1394).
  * :func:`cholesky`   — unrolled Cholesky for SPD mass matrices.
  * :func:`generalized_eigh` — K x = lambda M x via Cholesky + Jacobi.
"""
from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.core.cplx import Cx

Array = jnp.ndarray


def _pivot_rows(col_mag: Array, k: int, n: int):
    """Row-permutation indices swapping row k with the max-magnitude row >= k.

    col_mag: (..., n) magnitudes of column k (entries < k should be masked
    by the caller).  Returns (..., n) int32 gather indices.
    """
    rows = jnp.arange(n)
    masked = jnp.where(rows >= k, col_mag, -1.0)
    piv = jnp.argmax(masked, axis=-1)  # (...,)
    pivb = piv[..., None]
    idx = jnp.broadcast_to(rows, masked.shape)
    idx = jnp.where(idx == k, pivb, jnp.where(idx == pivb, k, idx))
    return idx


def _gather_rows(A: Array, idx: Array) -> Array:
    """Gather rows of (...,n,m) by (...,n) indices."""
    return jnp.take_along_axis(A, idx[..., None], axis=-2)


def solve_cx(A: Cx, b: Cx, n: int = 6) -> Cx:
    """Solve complex A x = b, A: (...,n,n) Cx, b: (...,n) or (...,n,m) Cx.

    Unrolled Gaussian elimination with partial pivoting; all ops elementwise
    or gathers, so the whole batch compiles to one fused kernel.
    """
    vec = b.re.ndim == A.re.ndim - 1
    if vec:
        b = Cx(b.re[..., None], b.im[..., None])
    Ar, Ai = A.re, A.im
    br, bi = b.re, b.im
    for k in range(n):
        mag = Ar[..., :, k] ** 2 + Ai[..., :, k] ** 2  # (...,n)
        idx = _pivot_rows(mag, k, n)
        Ar = _gather_rows(Ar, idx)
        Ai = _gather_rows(Ai, idx)
        br = _gather_rows(br, idx)
        bi = _gather_rows(bi, idx)
        # eliminate rows below k
        den = Ar[..., k, k] ** 2 + Ai[..., k, k] ** 2
        den = jnp.where(den != 0, den, 1.0)
        fr = (Ar[..., :, k] * Ar[..., k : k + 1, k] + Ai[..., :, k] * Ai[..., k : k + 1, k]) / den[..., None]
        fi = (Ai[..., :, k] * Ar[..., k : k + 1, k] - Ar[..., :, k] * Ai[..., k : k + 1, k]) / den[..., None]
        below = jnp.arange(n) > k
        fr = jnp.where(below, fr, 0.0)
        fi = jnp.where(below, fi, 0.0)
        Ar, Ai = (
            Ar - (fr[..., None] * Ar[..., k : k + 1, :] - fi[..., None] * Ai[..., k : k + 1, :]),
            Ai - (fr[..., None] * Ai[..., k : k + 1, :] + fi[..., None] * Ar[..., k : k + 1, :]),
        )
        br, bi = (
            br - (fr[..., None] * br[..., k : k + 1, :] - fi[..., None] * bi[..., k : k + 1, :]),
            bi - (fr[..., None] * bi[..., k : k + 1, :] + fi[..., None] * br[..., k : k + 1, :]),
        )
    # back substitution
    xr = jnp.zeros_like(br)
    xi = jnp.zeros_like(bi)
    for k in range(n - 1, -1, -1):
        sr = br[..., k, :] - (
            jnp.einsum("...j,...jm->...m", Ar[..., k, k + 1 :], xr[..., k + 1 :, :])
            - jnp.einsum("...j,...jm->...m", Ai[..., k, k + 1 :], xi[..., k + 1 :, :])
        )
        si = bi[..., k, :] - (
            jnp.einsum("...j,...jm->...m", Ar[..., k, k + 1 :], xi[..., k + 1 :, :])
            + jnp.einsum("...j,...jm->...m", Ai[..., k, k + 1 :], xr[..., k + 1 :, :])
        )
        den = Ar[..., k, k] ** 2 + Ai[..., k, k] ** 2
        den = jnp.where(den != 0, den, 1.0)[..., None]
        xk_r = (sr * Ar[..., k, k][..., None] + si * Ai[..., k, k][..., None]) / den
        xk_i = (si * Ar[..., k, k][..., None] - sr * Ai[..., k, k][..., None]) / den
        xr = xr.at[..., k, :].set(xk_r)
        xi = xi.at[..., k, :].set(xk_i)
    x = Cx(xr, xi)
    if vec:
        x = Cx(x.re[..., 0], x.im[..., 0])
    return x


def assemble_impedance(Z0: Cx, w: Array, B_drag: Array) -> Cx:
    """``Z = Z0 + i w B_drag``: fold the per-iteration drag damping into a
    precomputed loop-invariant impedance ``Z0 = -w^2 M + i w B + C``.

    ``Z0``: (..., nw, 6, 6) Cx; ``w``: broadcastable to (..., nw);
    ``B_drag``: (..., 6, 6) real — one drag matrix per design, broadcast
    over the frequency axis.  Only the imaginary part changes, so the
    real part is passed through untouched (exactly bit-preserving).
    """
    return Cx(Z0.re, Z0.im + w[..., None, None] * B_drag[..., None, :, :])


def solve_cx_fused(Z0: Cx, w: Array, B_drag: Array, F: Cx, n: int = 6) -> Cx:
    """Fused RAO assemble+solve: ``x = (Z0 + i w B_drag)^-1 F``.

    The XLA fallback of the Pallas fused kernel
    (:func:`raft_tpu.core.pallas6.solve_rao_pallas`): the assembly is an
    elementwise expression feeding straight into :func:`solve_cx`, so XLA
    fuses it into the elimination and the assembled complex ``Z`` never
    round-trips through HBM inside the fixed point.  Fully transformable
    (vmap/jvp/grad/shard_map) — this is also the path the ``custom_vjp``
    adjoint falls back to for bit-comparability checks.
    """
    return solve_cx(assemble_impedance(Z0, w, B_drag), F, n=n)


def solve_re(A: Array, b: Array, n: int = 6) -> Array:
    """Real A x = b via the complex kernel (zero imaginary part)."""
    out = solve_cx(Cx(A, jnp.zeros_like(A)), Cx(b, jnp.zeros_like(b)), n=n)
    return out.re


def cholesky(M: Array, n: int = 6) -> Array:
    """Unrolled Cholesky factor L (lower) of SPD M: (...,n,n)."""
    L = jnp.zeros_like(M)
    for j in range(n):
        s = M[..., j, j] - jnp.einsum("...k,...k->...", L[..., j, :j], L[..., j, :j])
        ljj = jnp.sqrt(jnp.maximum(s, 1e-30))
        L = L.at[..., j, j].set(ljj)
        for i in range(j + 1, n):
            s = M[..., i, j] - jnp.einsum("...k,...k->...", L[..., i, :j], L[..., j, :j])
            L = L.at[..., i, j].set(s / ljj)
    return L


def solve_lower(L: Array, b: Array, n: int = 6) -> Array:
    """Solve L y = b with L lower-triangular, b: (...,n) or (...,n,m)."""
    vec = b.ndim == L.ndim - 1
    if vec:
        b = b[..., None]
    y = jnp.zeros_like(b)
    for i in range(n):
        s = b[..., i, :] - jnp.einsum("...k,...km->...m", L[..., i, :i], y[..., :i, :])
        y = y.at[..., i, :].set(s / L[..., i, i][..., None])
    return y[..., 0] if vec else y


def solve_upper(U: Array, b: Array, n: int = 6) -> Array:
    """Solve U y = b with U upper-triangular."""
    vec = b.ndim == U.ndim - 1
    if vec:
        b = b[..., None]
    y = jnp.zeros_like(b)
    for i in range(n - 1, -1, -1):
        s = b[..., i, :] - jnp.einsum("...k,...km->...m", U[..., i, i + 1 :], y[..., i + 1 :, :])
        y = y.at[..., i, :].set(s / U[..., i, i][..., None])
    return y[..., 0] if vec else y


def eigh_jacobi(M: Array, n: int = 6, sweeps: int = 12):
    """Eigendecomposition of symmetric M by cyclic Jacobi rotations.

    Returns (eigvals (...,n), eigvecs (...,n,n) with columns as vectors).
    Fixed sweep count -> static control flow; 12 sweeps is far past
    convergence for n=6 (quadratic convergence after ~3).
    """
    A = M
    V = jnp.zeros_like(M) + jnp.eye(n, dtype=M.dtype)
    for _ in range(sweeps):
        for p in range(n - 1):
            for q in range(p + 1, n):
                app = A[..., p, p]
                aqq = A[..., q, q]
                apq = A[..., p, q]
                # rotation angle: theta = 0.5 atan2(2 apq, aqq - app)
                theta = 0.5 * jnp.arctan2(2.0 * apq, aqq - app)
                c = jnp.cos(theta)[..., None]
                s = jnp.sin(theta)[..., None]
                # apply rotation on rows/cols p and q
                rowp = A[..., p, :]
                rowq = A[..., q, :]
                A = A.at[..., p, :].set(c * rowp - s * rowq)
                A = A.at[..., q, :].set(s * rowp + c * rowq)
                colp = A[..., :, p]
                colq = A[..., :, q]
                A = A.at[..., :, p].set(c * colp - s * colq)
                A = A.at[..., :, q].set(s * colp + c * colq)
                vp = V[..., :, p]
                vq = V[..., :, q]
                V = V.at[..., :, p].set(c * vp - s * vq)
                V = V.at[..., :, q].set(s * vp + c * vq)
    return jnp.diagonal(A, axis1=-2, axis2=-1), V


def generalized_eigh(K: Array, M: Array, n: int = 6, sweeps: int = 12):
    """Solve K x = lambda M x for symmetric K, SPD M.

    Used for natural frequencies (reference solveEigen uses eig(inv(M) C),
    raft/raft.py:1394; the symmetric reduction here is the numerically sound
    equivalent).  Returns (lambda (...,n), modes (...,n,n) columns).
    """
    L = cholesky(M, n=n)
    # A = L^-1 K L^-T
    Y = solve_lower(L, K, n=n)                       # L Y = K
    # Solve L Z^T = Y^T  => Z = Y L^-T: apply lower solve on transposed
    Z = solve_lower(L, jnp.swapaxes(Y, -1, -2), n=n)
    A = 0.5 * (Z + jnp.swapaxes(Z, -1, -2))          # symmetrize roundoff
    lam, V = eigh_jacobi(A, n=n, sweeps=sweeps)
    # modes: x = L^-T v
    X = solve_upper(jnp.swapaxes(L, -1, -2), V, n=n)
    return lam, X
