"""Pallas TPU kernel for the batched 6x6 complex solve (the hot op).

The RAO engine's inner operation is thousands of independent 6x6 complex
solves per fixed-point iteration (:mod:`raft_tpu.core.linalg6`'s unrolled
elimination, vectorized over the batch by XLA).  This module is the same
algorithm as ONE hand-written Pallas kernel: the batch lies along the TPU
lane axis, every elimination/back-substitution step is an elementwise VPU
operation over a VMEM-resident block, and partial pivoting is a lane-wise
one-hot blend (no gathers).  One kernel invocation per block replaces the
~200-op XLA fusion — the payoff is explicit control of the memory layout
(matrix entries live in sublanes, systems in lanes) so a block's whole
working set stays in VMEM across all 6 elimination steps.

Status — the decided position, taken from hardware measurements:

* **On by default on TPU** (``RAFT_TPU_PALLAS=0`` opts out; ``=1``
  forces it on any backend — see :func:`enabled`).  Measured on a TPU
  v5e (2026-07-31, ``BENCH_TPU_CAPTURED.json``): **1.41x** over XLA on
  the isolated hot op (``pallas6_microbench``, batch 16,384, max |diff|
  2.1e-7; 1.34x in an earlier same-day run) and **18x**
  end-to-end on the 1,000-design north star (0.16 s vs 2.9 s, same
  iteration counts, |dXi| ~ 5e-7) — inside the while-loop driver the
  XLA lowering's per-step pivot argmax/one-hot becomes gather traffic
  that dominates the whole solve, which the kernel's lane-wise blends
  avoid entirely.  The kernel is additionally bit-validated against
  ``linalg6.solve_cx`` in interpreter mode (``tests/test_pallas6.py``).
* **Fused assemble+solve for the fixed point.** The RAO fixed point's
  per-iteration work is ``solve(Z0 + i w B_drag, F)`` with only the
  small real drag update changing between iterations; the plain kernel
  forces the caller to materialize the full (..., nw, 6, 6) complex
  impedance in HBM every iteration just to hand it over.
  :func:`solve_rao_pallas` moves the assembly INSIDE the VMEM-resident
  block: per iteration the kernel reads the loop-invariant ``Z0`` pair
  plus the per-lane ``w`` and broadcast ``B_drag`` (half the dynamic
  HBM traffic of write+read of the assembled ``Z``) and the assembled
  impedance never exists outside VMEM.  Both fixed-point drivers in
  :mod:`raft_tpu.solve.dynamics` route through it; the XLA twin is
  :func:`raft_tpu.core.linalg6.solve_cx_fused`.
* **Analytic adjoint, not a differentiated kernel.** The
  differentiable route (``method="scan"``, used by every
  gradient/co-design path) goes through :func:`solve_cx_pallas_ad`,
  whose ``custom_vjp`` solves the adjoint system ``A^H lam = xbar``
  with the SAME forward kernel — one extra kernel call plus an outer
  product per backward step, no hand-differentiated elimination.  (The
  earlier rounds' "no VJP" position was premised on the XLA path being
  fast; the measured 18x reversed that premise.)  Forward-mode
  ``jvp``/``jacfwd`` is the one transform the wrapper cannot carry —
  ``RAFT_TPU_PALLAS=0`` keeps the fully transformable XLA path for it.
"""
from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.core.cplx import Cx

Array = jnp.ndarray

_N = 6
_BLOCK = 512          # systems per kernel invocation (lanes: 4 x 128)


def enabled() -> bool:
    """True when the Pallas solve path should be used.

    Accepted spellings of ``RAFT_TPU_PALLAS`` (case-insensitive,
    whitespace-stripped):

    * force ON, any backend: ``1`` / ``true`` / ``on`` / ``yes``
    * force OFF: ``0`` / ``false`` / ``off`` / ``no``
    * unset -> **auto**: on exactly when the default backend is a TPU
    * empty string or any other value -> auto, with a warning — an
      explicitly-set-but-malformed knob degrades to the measured default
      instead of silently opting out of the 18x TPU path.  (Before
      round 5 the legacy rule was "anything but ``1`` means off", so a
      deployment script exporting ``RAFT_TPU_PALLAS=""`` used to force
      the kernel off; the warning makes that silent behavior flip
      visible.)

    The auto-on default is a measured decision, not a guess: on
    a TPU v5e the kernel ran the full 1,000-design north star 18x
    faster than the XLA lowering of the same unrolled solve (0.16 s vs
    2.9 s end-to-end, identical iteration counts, |dXi| ~ 5e-7 — the
    XLA path's per-iteration pivot argmax/one-hot lowers to gathers,
    which TPUs execute catastrophically slowly inside a while loop).
    On CPU the kernel would need interpreter mode (slower than XLA), so
    auto stays off there and the tests' pinned-CPU runs are unaffected.
    """
    knob = os.environ.get("RAFT_TPU_PALLAS")
    if knob is not None:
        k = knob.strip().lower()
        if k in ("1", "true", "on", "yes"):
            return True
        if k in ("0", "false", "off", "no"):
            return False
        import warnings

        warnings.warn(
            (f"RAFT_TPU_PALLAS is set but empty; treating as unset "
             f"(auto: on iff the default backend is TPU).  The pre-round-5 "
             f"rule forced the kernel OFF for this value — set "
             f"RAFT_TPU_PALLAS=0 if that is what you want"
             if not k else
             f"RAFT_TPU_PALLAS={knob!r} not recognized "
             f"(use 1/true/on/yes or 0/false/off/no); "
             f"falling back to auto (on iff the default backend is TPU)"),
            stacklevel=2,
        )
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend init failure: the XLA path always works
        return False


def _eliminate(Ar, Ai, br, bi, xr_ref, xi_ref):
    """Unrolled 6x6 complex Gaussian elimination over a lane block.

    ``Ar``/``Ai``: row-major lists of the 36 matrix-entry rows, ``br``/
    ``bi``: lists of the 6 RHS rows — each a (1, B) VMEM-resident vector.
    All arithmetic is elementwise (VPU), and the per-lane pivot
    permutation is a one-hot blend.  Shared by the plain kernel (entries
    loaded straight from HBM) and the fused assemble+solve kernel
    (imaginary entries assembled in VMEM from ``Z0`` + ``w B_drag``).
    """

    def at(i, j):
        return i * _N + j

    for k in range(_N):
        # lane-wise partial pivot: one-hot over candidate rows >= k
        mags = [Ar[at(j, k)] ** 2 + Ai[at(j, k)] ** 2 for j in range(_N)]
        best = mags[k]
        onehot = [jnp.ones_like(best) if j == k else jnp.zeros_like(best)
                  for j in range(_N)]
        for j in range(k + 1, _N):
            better = mags[j] > best
            for l in range(_N):
                onehot[l] = jnp.where(better, 0.0, onehot[l])
            onehot[j] = jnp.where(better, 1.0, onehot[j])
            best = jnp.where(better, mags[j], best)

        def swap(rows):
            """rows: list over row index of (1,B); swap row k <-> pivot."""
            piv = rows[k] * onehot[k]
            for j in range(k + 1, _N):
                piv = piv + rows[j] * onehot[j]
            old_k = rows[k]
            out = list(rows)
            out[k] = piv
            for j in range(k + 1, _N):
                out[j] = jnp.where(onehot[j] > 0, old_k, rows[j])
            return out

        # swap the (still-relevant) trailing columns of A and the RHS
        for col in range(k, _N):
            rowsr = swap([Ar[at(j, col)] for j in range(_N)])
            rowsi = swap([Ai[at(j, col)] for j in range(_N)])
            for j in range(_N):
                Ar[at(j, col)] = rowsr[j]
                Ai[at(j, col)] = rowsi[j]
        br = swap(br)
        bi = swap(bi)

        # eliminate rows below k
        den = Ar[at(k, k)] ** 2 + Ai[at(k, k)] ** 2
        den = jnp.where(den != 0.0, den, 1.0)
        for j in range(k + 1, _N):
            fr = (Ar[at(j, k)] * Ar[at(k, k)] + Ai[at(j, k)] * Ai[at(k, k)]) / den
            fi = (Ai[at(j, k)] * Ar[at(k, k)] - Ar[at(j, k)] * Ai[at(k, k)]) / den
            for col in range(k, _N):
                Ar[at(j, col)], Ai[at(j, col)] = (
                    Ar[at(j, col)] - (fr * Ar[at(k, col)] - fi * Ai[at(k, col)]),
                    Ai[at(j, col)] - (fr * Ai[at(k, col)] + fi * Ar[at(k, col)]),
                )
            br[j], bi[j] = (
                br[j] - (fr * br[k] - fi * bi[k]),
                bi[j] - (fr * bi[k] + fi * br[k]),
            )

    # back substitution
    xr = [None] * _N
    xi = [None] * _N
    for k in range(_N - 1, -1, -1):
        sr, si = br[k], bi[k]
        for j in range(k + 1, _N):
            sr = sr - (Ar[at(k, j)] * xr[j] - Ai[at(k, j)] * xi[j])
            si = si - (Ar[at(k, j)] * xi[j] + Ai[at(k, j)] * xr[j])
        den = Ar[at(k, k)] ** 2 + Ai[at(k, k)] ** 2
        den = jnp.where(den != 0.0, den, 1.0)
        xr[k] = (sr * Ar[at(k, k)] + si * Ai[at(k, k)]) / den
        xi[k] = (si * Ar[at(k, k)] - sr * Ai[at(k, k)]) / den

    for i in range(_N):
        xr_ref[i:i + 1, :] = xr[i]
        xi_ref[i:i + 1, :] = xi[i]


def _kernel(zr_ref, zi_ref, br_ref, bi_ref, xr_ref, xi_ref):
    """Plain solve kernel: matrix entries read directly from the refs."""
    _eliminate(
        [zr_ref[i:i + 1, :] for i in range(_N * _N)],
        [zi_ref[i:i + 1, :] for i in range(_N * _N)],
        [br_ref[i:i + 1, :] for i in range(_N)],
        [bi_ref[i:i + 1, :] for i in range(_N)],
        xr_ref, xi_ref,
    )


def _fused_kernel(z0r_ref, z0i_ref, w_ref, bd_ref, br_ref, bi_ref,
                  xr_ref, xi_ref):
    """Fused assemble+solve kernel: ``Z = Z0 + i w B_drag`` is formed in
    VMEM registers — the per-iteration complex impedance never exists as
    an HBM tensor.  ``z0r``/``z0i``/``bd`` are (36, B) row-major entry
    refs, ``w`` is (1, B); the imaginary entries are assembled lane-wise
    right at load time and flow straight into the elimination."""
    w = w_ref[0:1, :]
    _eliminate(
        [z0r_ref[i:i + 1, :] for i in range(_N * _N)],
        [z0i_ref[i:i + 1, :] + w * bd_ref[i:i + 1, :]
         for i in range(_N * _N)],
        [br_ref[i:i + 1, :] for i in range(_N)],
        [bi_ref[i:i + 1, :] for i in range(_N)],
        xr_ref, xi_ref,
    )


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def _solve_blocked(Zr, Zi, Fr, Fi, block: int, interpret: bool):
    """(Np, 6, 6)/(Np, 6) padded inputs -> (Np, 6) solution, via the
    Pallas kernel on (36, block)/(6, block) lane-major tiles."""
    from jax.experimental import pallas as pl

    Np = Zr.shape[0]
    grid = Np // block
    # lane-major layouts: matrix entries in sublanes, systems in lanes
    zr = Zr.reshape(Np, _N * _N).T           # (36, Np)
    zi = Zi.reshape(Np, _N * _N).T
    fr = Fr.T                                 # (6, Np)
    fi = Fi.T
    spec_z = pl.BlockSpec((_N * _N, block), lambda g: (0, g))
    spec_f = pl.BlockSpec((_N, block), lambda g: (0, g))
    xr, xi = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[spec_z, spec_z, spec_f, spec_f],
        out_specs=[spec_f, spec_f],
        out_shape=[
            jax.ShapeDtypeStruct(fr.shape, fr.dtype),
            jax.ShapeDtypeStruct(fi.shape, fi.dtype),
        ],
        interpret=interpret,
    )(zr, zi, fr, fi)
    return xr.T, xi.T


def solve_cx_pallas(A: Cx, b: Cx, block: int = _BLOCK,
                    interpret: bool | None = None) -> Cx:
    """Drop-in for :func:`raft_tpu.core.linalg6.solve_cx` (vector RHS).

    ``A``: (..., 6, 6) Cx, ``b``: (..., 6) Cx — leading axes flatten to
    the lane dimension and pad to a multiple of ``block``.  ``interpret``
    defaults to True off-TPU (the Mosaic compiler is TPU-only).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = A.re.shape[:-2]
    n_sys = int(np.prod(lead)) if lead else 1
    if n_sys == 0:
        return Cx(jnp.zeros(lead + (_N,), dtype=A.re.dtype),
                  jnp.zeros(lead + (_N,), dtype=A.re.dtype))
    # shrink the block to the batch (128-lane granularity) so small local
    # shards — e.g. a frequency-sharded solve's per-device bins — don't
    # pad up to the full default block
    block = min(block, -(-n_sys // 128) * 128)
    pad = (-n_sys) % block
    Np = n_sys + pad

    def prep(x, shape):
        x = x.reshape((n_sys,) + shape)
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + shape, dtype=x.dtype)], axis=0)
        return x

    Zr = prep(A.re, (_N, _N))
    Zi = prep(A.im, (_N, _N))
    # padded lanes solve the identity so no 0/0 enters the pipeline
    if pad:
        eye = jnp.broadcast_to(jnp.eye(_N, dtype=Zr.dtype), (pad, _N, _N))
        Zr = Zr.at[n_sys:].set(eye)
    Fr = prep(b.re, (_N,))
    Fi = prep(b.im, (_N,))
    xr, xi = _solve_blocked(Zr, Zi, Fr, Fi, block, interpret)
    return Cx(xr[:n_sys].reshape(lead + (_N,)),
              xi[:n_sys].reshape(lead + (_N,)))


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def _solve_rao_blocked(Z0r, Z0i, W, Bd, Fr, Fi, block: int, interpret: bool):
    """(Np, 6, 6)/(Np,)/(Np, 6, 6)/(Np, 6) padded inputs -> (Np, 6)
    solution via the fused assemble+solve kernel on lane-major tiles."""
    from jax.experimental import pallas as pl

    Np = Z0r.shape[0]
    grid = Np // block
    z0r = Z0r.reshape(Np, _N * _N).T          # (36, Np)
    z0i = Z0i.reshape(Np, _N * _N).T
    bd = Bd.reshape(Np, _N * _N).T
    w = W.reshape(Np, 1).T                    # (1, Np)
    fr = Fr.T                                 # (6, Np)
    fi = Fi.T
    spec_z = pl.BlockSpec((_N * _N, block), lambda g: (0, g))
    spec_w = pl.BlockSpec((1, block), lambda g: (0, g))
    spec_f = pl.BlockSpec((_N, block), lambda g: (0, g))
    xr, xi = pl.pallas_call(
        _fused_kernel,
        grid=(grid,),
        in_specs=[spec_z, spec_z, spec_w, spec_z, spec_f, spec_f],
        out_specs=[spec_f, spec_f],
        out_shape=[
            jax.ShapeDtypeStruct(fr.shape, fr.dtype),
            jax.ShapeDtypeStruct(fi.shape, fi.dtype),
        ],
        interpret=interpret,
    )(z0r, z0i, w, bd, fr, fi)
    return xr.T, xi.T


def solve_rao_pallas(Z0: Cx, w, B_drag, F: Cx, block: int = _BLOCK,
                     interpret: bool | None = None) -> Cx:
    """Fused RAO assemble+solve: ``x = (Z0 + i w B_drag)^-1 F``.

    Kernel twin of :func:`raft_tpu.core.linalg6.solve_cx_fused` — the
    per-iteration impedance assembly happens INSIDE the VMEM-resident
    block, so the fixed point never writes or re-reads the full
    (..., nw, 6, 6) complex ``Z`` in HBM: per iteration the kernel reads
    the loop-invariant ``Z0`` pair, the scalar-per-lane ``w`` and the
    (broadcast) real drag update, and writes only the (..., 6) solution.

    ``Z0``: (..., nw, 6, 6) Cx; ``w``: broadcastable to the lead shape
    (..., nw); ``B_drag``: (..., 6, 6) real, broadcast over the frequency
    axis; ``F``: (..., nw, 6) Cx.  ``interpret`` defaults to True off-TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = Z0.re.shape[:-2]
    n_sys = int(np.prod(lead)) if lead else 1
    if n_sys == 0:
        return Cx(jnp.zeros(lead + (_N,), dtype=Z0.re.dtype),
                  jnp.zeros(lead + (_N,), dtype=Z0.re.dtype))
    wb = jnp.broadcast_to(w, lead)
    bd = jnp.broadcast_to(B_drag[..., None, :, :], lead + (_N, _N))
    block = min(block, -(-n_sys // 128) * 128)
    pad = (-n_sys) % block
    Np = n_sys + pad

    def prep(x, shape):
        x = x.reshape((n_sys,) + shape)
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + shape, dtype=x.dtype)], axis=0)
        return x

    Z0r = prep(Z0.re, (_N, _N))
    Z0i = prep(Z0.im, (_N, _N))
    # padded lanes solve the identity (w and B_drag pad as zeros, so the
    # assembled pad matrix stays exactly the identity)
    if pad:
        eye = jnp.broadcast_to(jnp.eye(_N, dtype=Z0r.dtype), (pad, _N, _N))
        Z0r = Z0r.at[n_sys:].set(eye)
    W = prep(wb, ())
    Bd = prep(bd, (_N, _N))
    Fr = prep(F.re, (_N,))
    Fi = prep(F.im, (_N,))
    xr, xi = _solve_rao_blocked(Z0r, Z0i, W, Bd, Fr, Fi, block, interpret)
    return Cx(xr[:n_sys].reshape(lead + (_N,)),
              xi[:n_sys].reshape(lead + (_N,)))


def _unbroadcast(x, shape):
    """Reduce a cotangent produced at broadcast shape back onto the
    primal's shape (sum over the broadcast axes)."""
    while x.ndim > len(shape):
        x = x.sum(axis=0)
    for ax, (have, want) in enumerate(zip(x.shape, shape)):
        if want == 1 and have != 1:
            x = x.sum(axis=ax, keepdims=True)
    return x


@jax.custom_vjp
def solve_rao_pallas_ad(Z0: Cx, w, B_drag, F: Cx) -> Cx:
    """:func:`solve_rao_pallas` with an analytic reverse-mode rule.

    Same adjoint structure as :func:`solve_cx_pallas_ad` — solve
    ``A^H lam = xbar`` with ONE more call of the SAME fused kernel —
    except the conjugate transpose is taken in the fused representation:
    ``A = Z0 + i w B_drag`` gives ``A^H = Z0^H + i w (-B_drag^T)``, so
    the adjoint solve is just the fused kernel on ``(Z0^H, w, -B_drag^T,
    xbar)`` and the assembled adjoint impedance stays in VMEM too.  The
    extra primals' cotangents follow from ``Z.im = Z0.im + w B_drag``:
    ``B_dragbar = sum_w w * Abar.im`` (reduced over the frequency axis)
    and ``wbar = sum_jk B_drag * Abar.im``.

    Forward-mode (``jvp``/``jacfwd``) is NOT supported through this
    wrapper (a ``custom_vjp`` limitation) — ``RAFT_TPU_PALLAS=0`` keeps
    the fully transformable XLA path (``linalg6.solve_cx_fused``).
    """
    return solve_rao_pallas(Z0, w, B_drag, F)


def _rao_ad_fwd(Z0: Cx, w, B_drag, F: Cx):
    x = solve_rao_pallas(Z0, w, B_drag, F)
    return x, (Z0, w, B_drag, x)


def _rao_ad_bwd(res, xbar: Cx):
    Z0, w, B_drag, x = res
    Z0H = Cx(jnp.swapaxes(Z0.re, -1, -2), -jnp.swapaxes(Z0.im, -1, -2))
    lam = solve_rao_pallas(Z0H, w, -jnp.swapaxes(B_drag, -1, -2), xbar)
    # Abar = -conj(lam) x^T in the (re, im) pair algebra (see
    # _solve_ad_bwd); Z = Z0 + i w B_drag then splits Abar onto the
    # fused-representation primals.
    lr, li = lam.re[..., :, None], lam.im[..., :, None]
    xr, xi = x.re[..., None, :], x.im[..., None, :]
    Abar = Cx(-(lr * xr + li * xi), lr * xi - li * xr)
    lead = Z0.re.shape[:-2]
    wb = jnp.broadcast_to(w, lead)
    w_shape = jnp.shape(w)
    wbar = _unbroadcast(
        jnp.sum(B_drag[..., None, :, :] * Abar.im, axis=(-2, -1)), w_shape)
    bdbar = _unbroadcast(
        jnp.sum(wb[..., None, None] * Abar.im, axis=-3),
        jnp.shape(B_drag))
    return Abar, wbar, bdbar, Cx(lam.re, lam.im)


solve_rao_pallas_ad.defvjp(_rao_ad_fwd, _rao_ad_bwd)


@jax.custom_vjp
def solve_cx_pallas_ad(A: Cx, b: Cx) -> Cx:
    """:func:`solve_cx_pallas` with an analytic reverse-mode rule.

    The VJP of a linear solve ``x = A^-1 b`` needs no differentiation of
    the elimination itself: given the cotangent ``xbar``, solve the
    adjoint system ``A^H lam = xbar`` (ONE more call of the same kernel
    on the conjugate transpose), then ``bbar = lam`` and
    ``Abar = -conj(lam) x^T`` (an outer product).  This is what makes the
    kernel usable on the differentiable ``method="scan"`` fixed point —
    the backward pass costs one extra kernel call per iteration instead
    of falling back to the gather-bound XLA lowering that motivated the
    kernel in the first place.

    In the (re, im)-pair representation the real-valued cotangent algebra
    works out to (derivation: ``<xbar, dx>_R = Re(xbar^H A^-1 (db - dA x))``):

    * ``lam = (A^H)^-1 xbar``, carried as the pair ``(Re lam, Im lam)``;
    * ``bbar = (Re lam, Im lam)``;
    * ``Abar_ij = (-Re(conj(lam_i) x_j), +Im(conj(lam_i) x_j))``.

    Forward-mode (``jvp``/``jacfwd``) is NOT supported through this
    wrapper (a ``custom_vjp`` limitation) — ``RAFT_TPU_PALLAS=0`` keeps
    the fully transformable XLA path for that.
    """
    return solve_cx_pallas(A, b)


def _solve_ad_fwd(A: Cx, b: Cx):
    x = solve_cx_pallas(A, b)
    return x, (A, x)


def _solve_ad_bwd(res, xbar: Cx):
    A, x = res
    AH = Cx(jnp.swapaxes(A.re, -1, -2), -jnp.swapaxes(A.im, -1, -2))
    lam = solve_cx_pallas(AH, xbar)
    # conj(lam_i) * x_j, expanded over the trailing (6, 6) matrix axes
    lr, li = lam.re[..., :, None], lam.im[..., :, None]
    xr, xi = x.re[..., None, :], x.im[..., None, :]
    Abar = Cx(-(lr * xr + li * xi), lr * xi - li * xr)
    return Abar, Cx(lam.re, lam.im)


solve_cx_pallas_ad.defvjp(_solve_ad_fwd, _solve_ad_bwd)
