"""Linear wave theory kernels: spectrum, dispersion, kinematics.

Functional equivalents of the reference's JONSWAP (raft/raft.py:1105-1151),
waveNumber (raft/raft.py:979-994) and getWaveKin (raft/raft.py:923-974),
re-designed as fully-vectorized jnp functions: all frequencies and all field
points are evaluated in one broadcasted call (the reference loops over
frequencies per node).

Deviations from the reference (documented, intentional):
  * getWaveKin upstream defaults g=9.91 (raft/raft.py:923) and contains a
    live ``breakpoint()`` for k==0 (raft/raft.py:950); here g is an explicit
    argument and k<=0 entries yield zero kinematics.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from raft_tpu.core import cplx
from raft_tpu.core.cplx import Cx

Array = jnp.ndarray

# kh beyond which the finite-depth ratios overflow; switch to the deep-water
# form (same guard value as the reference, raft/raft.py:953).
_KH_DEEP = 89.4


@jax.jit
def jonswap(w: Array, Hs, Tp, gamma=1.0) -> Array:
    """One-sided JONSWAP wave power spectral density S(w) [m^2/(rad/s)].

    IEC 61400-3 / FAST v7 form (cf. raft/raft.py:1105-1151).  gamma=1
    reduces to Pierson-Moskowitz.  Broadcasts over w.
    """
    f = 0.5 / jnp.pi * w
    fpOvrf4 = (Tp * f) ** (-4.0)
    C = 1.0 - 0.287 * jnp.log(gamma)
    sigma = jnp.where(f <= 1.0 / Tp, 0.07, 0.09)
    alpha = jnp.exp(-0.5 * ((f * Tp - 1.0) / sigma) ** 2)
    return (
        0.5 / jnp.pi * C * 0.3125 * Hs * Hs * fpOvrf4 / f
        * jnp.exp(-1.25 * fpOvrf4) * gamma**alpha
    )


@partial(jax.jit, static_argnames=("iters",))
def wave_number(w: Array, depth, g: float = 9.81, iters: int = 30) -> Array:
    """Wave number k(w, h) from the linear dispersion relation w^2 = g k tanh(k h).

    The reference iterates a fixed-point to a 1e-3 relative tolerance
    (raft/raft.py:979-994); here a fixed-iteration Newton solve from the
    deep-water guess converges to machine precision, is vmappable over w and
    over batched designs, and is differentiable.
    """
    w = jnp.asarray(w)
    w2g = w * w / g

    def body(k, _):
        kh = k * depth
        t = jnp.tanh(kh)
        f = k * t - w2g
        fp = t + kh * (1.0 - t * t)
        k_new = k - f / jnp.where(fp != 0, fp, 1.0)
        return jnp.maximum(k_new, 1e-12), None

    k0 = jnp.maximum(w2g, 1e-12)
    k, _ = jax.lax.scan(body, k0, None, length=iters)
    return k


def depth_ratios(k: Array, z: Array, depth) -> tuple[Array, Array, Array]:
    """Stable evaluation of the three depth-attenuation ratios.

    sinh(k(z+h))/sinh(kh), cosh(k(z+h))/sinh(kh), cosh(k(z+h))/cosh(kh)
    with the deep-water overflow guard at kh > 89.4 (cf. raft/raft.py:946-960).
    Broadcasts k against z -> all outputs share the broadcast shape.
    """
    # ratios are only defined below the free surface; clamp so above-water
    # query points can't overflow sinh/cosh into 0*inf=NaN before masking
    z = jnp.minimum(z, 0.0)
    kh = k * depth
    kz = k * z
    deep = kh > _KH_DEEP
    kh_safe = jnp.where(deep, 1.0, kh)
    kzh = jnp.where(deep, 0.0, k * (z + depth))
    shallow_s = jnp.sinh(kzh) / jnp.sinh(kh_safe)
    shallow_c = jnp.cosh(kzh) / jnp.sinh(kh_safe)
    shallow_cc = jnp.cosh(kzh) / jnp.cosh(kh_safe)
    deep_e = jnp.exp(kz)
    s = jnp.where(deep, deep_e, shallow_s)
    c = jnp.where(deep, deep_e, shallow_c)
    cc = jnp.where(deep, deep_e + jnp.exp(-k * (z + 2.0 * depth)), shallow_cc)
    ok = k > 0
    return jnp.where(ok, s, 0.0), jnp.where(ok, c, 0.0), jnp.where(ok, cc, 0.0)


def wave_kinematics(
    zeta0: Array,
    w: Array,
    k: Array,
    depth,
    r: Array,
    beta=0.0,
    rho: float = 1025.0,
    g: float = 9.81,
):
    """Complex wave velocity/acceleration/dynamic-pressure amplitudes at points.

    Vectorized equivalent of getWaveKin (raft/raft.py:923-974): evaluates all
    field points x all frequencies at once.

    Parameters
    ----------
    zeta0 : (nw,) wave elevation amplitude per frequency bin
    w, k : (nw,) frequency grid and wave numbers
    r : (...,3) field point positions (z<0 submerged)
    beta : wave heading [rad]

    Complex amplitudes are returned as :class:`~raft_tpu.core.cplx.Cx`
    (re, im) pairs — the TPU backend has no complex dtype support, and the
    pair representation fuses better anyway.

    Returns
    -------
    u : Cx (...,3,nw) velocity amplitudes
    ud : Cx (...,3,nw) acceleration amplitudes
    pDyn : Cx (...,nw) dynamic pressure amplitudes
    """
    cb, sb = jnp.cos(beta), jnp.sin(beta)
    x = r[..., 0:1]  # (...,1) broadcast against (nw,)
    y = r[..., 1:2]
    z = r[..., 2:3]
    phase = Cx.expi(-(k * (cb * x + sb * y)))                       # (...,nw)
    s, c, cc = depth_ratios(k, z, depth)                            # (...,nw)
    submerged = (z < 0).astype(phase.re.dtype)
    zeta = phase * (zeta0 * submerged)
    ux = zeta * (w * c * cb)
    uy = zeta * (w * c * sb)
    uz = (zeta * (w * s)).mul_i()
    u = cplx.stack([ux, uy, uz], axis=-2)                           # (...,3,nw)
    ud = (u * w).mul_i()
    pDyn = zeta * (rho * g * cc)
    return u, ud, pDyn


def spreading_weights(n_dir: int = 7, s: float = 2.0,
                      max_offset: "float | None" = None):
    """Discrete cos^2s directional spreading: (offsets [rad], weights).

    D(theta) ∝ cos^2s(theta) over (-pi/2, pi/2) about the mean heading —
    the standard offshore short-crested-sea spreading function (the
    reference is strictly long-crested; this is a beyond-reference
    capability).  Midpoint discretization at ``n_dir`` equally spaced
    offsets, numerically normalized so the weights sum to 1 (each
    direction carries the fraction ``w_j`` of the total wave energy).
    ``n_dir=1`` or ``s=inf`` degenerate to a single long-crested lane.

    Host/NumPy on purpose: this runs once at sea-state staging time, not
    inside the compiled solve.
    """
    import numpy as np

    if n_dir < 1:
        raise ValueError(f"n_dir must be >= 1, got {n_dir}")
    if n_dir == 1 or not np.isfinite(s):
        return np.zeros(1), np.ones(1)
    half = 0.5 * np.pi if max_offset is None else float(max_offset)
    if not 0.0 < half <= 0.5 * np.pi:
        # beyond pi/2 the cos weight goes negative (or NaN for fractional
        # s) — that is outside the spreading function's support
        raise ValueError(f"max_offset must be in (0, pi/2], got {half}")
    # midpoints of n_dir equal bins spanning (-half, half): the open
    # interval endpoints (where D=0 for the pi/2 span) are never sampled
    edges = np.linspace(-half, half, n_dir + 1)
    offsets = 0.5 * (edges[:-1] + edges[1:])
    D = np.cos(offsets) ** (2.0 * s)
    w = D / D.sum()
    return offsets, w
