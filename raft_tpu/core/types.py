"""Core pytree container types.

Everything between "design parameters" and "response statistics" in raft_tpu
is a pure function over these containers, so they are all registered JAX
pytrees (via ``flax.struct.dataclass``): they can be passed through ``jit``,
``vmap``, ``grad`` and sharded over device meshes.

Capability map to the reference (dzalkind/RAFT):
  * ``Env``         <- environment container, raft/raft.py:22-30
  * ``MemberSet``   <- the list of per-object ``Member`` instances built at
                       raft/raft.py:1770-1783, re-designed as flat, stacked,
                       masked arrays (segments + strip nodes) so a single
                       platform is one pytree and a batch of designs is the
                       same pytree with a leading axis.
  * ``RigidBodyCoeffs`` <- the M/B/C/W matrices assembled by
                       FOWT.calcStatics, raft/raft.py:1836-2012
  * ``HydroCoeffs`` <- A_BEM/B_BEM/F_BEM arrays, raft/raft.py:1797-1800
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import struct

Array = jnp.ndarray


@struct.dataclass
class Env:
    """Environmental conditions (sea state + wind + constants)."""

    rho: Array = struct.field(default=1025.0)    # water density [kg/m^3]
    g: Array = struct.field(default=9.81)        # gravity [m/s^2]
    Hs: Array = struct.field(default=1.0)        # significant wave height [m]
    Tp: Array = struct.field(default=10.0)       # peak period [s]
    V: Array = struct.field(default=10.0)        # wind speed [m/s]
    beta: Array = struct.field(default=0.0)      # wave heading [rad]
    depth: Array = struct.field(default=300.0)   # water depth [m]
    # steady current (beyond the reference, which has no current model):
    # u_c(z) = current * ((depth + z)/depth)^current_exp, clipped to the
    # water column — power-law profile, current_exp=0 gives uniform flow,
    # 1/7 the usual open-ocean shear profile
    current: Array = struct.field(default=0.0)          # surface speed [m/s]
    current_heading: Array = struct.field(default=0.0)  # direction [rad]
    current_exp: Array = struct.field(default=0.0)      # profile exponent [-]


@struct.dataclass
class MemberSet:
    """All platform + tower members of one design as flat stacked arrays.

    Two flat axes:

    * ``S`` — one entry per *segment* (a station-to-station span of some
      member).  Drives inertia + hydrostatics (reference ``Member.getInertia``
      raft/raft.py:246-641 and ``Member.getHydrostatics`` raft/raft.py:646-796
      loop over exactly these spans).  End caps/bulkheads are folded into this
      axis as extra "cap segments" flagged by ``seg_is_cap``.

    * ``N`` — one entry per strip-theory *node* (reference discretization at
      raft/raft.py:147-191).  Drives Morison added mass / excitation / drag.

    All per-segment and per-node quantities carry the member's orientation
    (q, p1, p2 unit vectors and rotation matrix R) so no object lookup is ever
    needed; a design batch is simply this pytree with a leading batch axis.

    Shape-static invariant: for a fixed design *topology* (member count,
    station counts, node counts) all arrays have fixed shapes; continuous
    geometry changes (diameters, drafts, ballast, coefficients) only change
    values.  That is what makes 1000-design ``vmap`` sweeps and ``jax.grad``
    w.r.t. geometry possible.
    """

    # ---- per-segment arrays (axis S) ----
    # Unified representation: a segment is a linear frustum shell — outer
    # dims minus inner dims gives the shell; the inner frustum can carry a
    # ballast fill over its first ``seg_l_fill`` of length.  End caps and
    # bulkheads are extra segments whose "inner" dims describe the center
    # hole (0 for a solid plate), so one code path computes everything
    # (reference treats these as two separate loops, raft/raft.py:346-477
    # and :484-633).
    seg_rA: Array          # (S,3) lower end of segment in global frame [m]
    seg_q: Array           # (S,3) member axial unit vector
    seg_R: Array           # (S,3,3) member rotation matrix (Z1Y2Z3)
    seg_l: Array           # (S,)  segment length [m]
    seg_dA: Array          # (S,2) outer side lengths (circular: [d,d]) at lower end
    seg_dB: Array          # (S,2) outer side lengths at upper end
    seg_diA: Array         # (S,2) inner side lengths at lower end (cap: hole dims)
    seg_diB: Array         # (S,2) inner side lengths at upper end
    seg_l_fill: Array      # (S,)  ballast fill length within segment [m]
    seg_rho_fill: Array    # (S,)  ballast density [kg/m^3]
    seg_rho_shell: Array   # (S,)  shell material density [kg/m^3]
    seg_circ: Array        # (S,)  bool: circular (True) vs rectangular
    seg_is_cap: Array      # (S,)  bool: this segment is an end cap / bulkhead
    #                        (caps contribute inertia but no hydrostatics,
    #                         matching the reference's separate cap loop)
    seg_member: Array      # (S,)  int: owning member id
    seg_type: Array        # (S,)  int: member type code (<=1 tower, >1 substructure)
    seg_mask: Array        # (S,)  bool: valid segment (False = padding)

    # ---- per-node arrays (axis N) ----
    node_r: Array          # (N,3) node position in global frame [m]
    node_q: Array          # (N,3) axial unit vector of owning member
    node_p1: Array         # (N,3) transverse unit vector 1
    node_p2: Array         # (N,3) transverse unit vector 2
    node_ds: Array         # (N,2) mean side lengths of strip (circular: [d,d]) [m]
    node_drs: Array        # (N,2) change in radius/half-side over strip [m]
    node_dls: Array        # (N,)  lumped strip length [m]
    node_Cd_q: Array       # (N,)  axial drag coefficient
    node_Cd_p1: Array      # (N,)  transverse drag coefficient 1
    node_Cd_p2: Array      # (N,)  transverse drag coefficient 2
    node_Cd_end: Array     # (N,)  end/axial drag coefficient
    node_Ca_q: Array       # (N,)  axial added-mass coefficient
    node_Ca_p1: Array      # (N,)  transverse added-mass coefficient 1
    node_Ca_p2: Array      # (N,)  transverse added-mass coefficient 2
    node_Ca_end: Array     # (N,)  end/axial added-mass coefficient
    node_circ: Array       # (N,)  bool circular
    node_member: Array     # (N,)  int owning member id
    node_mask: Array       # (N,)  bool valid node (False = padding)
    # potMod=True members take their inertial hydrodynamics from the BEM
    # provider; their strip-theory added mass / FK excitation is gated off
    # (drag stays strip-theory).  Optional for backward compatibility:
    # None means "no potential-flow members".
    node_potmod: Optional[Array] = struct.field(default=None)  # (N,) bool


@struct.dataclass
class RNA:
    """Lumped rotor-nacelle-assembly properties.

    Mirrors the turbine scalars consumed by the reference FOWT
    (raft/raft.py:1790-1794) plus thrust/yaw-stiffness knobs
    (raft/raft.py:1264-1268, runRAFT.py:68).
    """

    mRNA: Array = struct.field(default=0.0)       # [kg]
    IxRNA: Array = struct.field(default=0.0)      # [kg m^2] about rotor axis
    IrRNA: Array = struct.field(default=0.0)      # [kg m^2] about lateral axes
    xCG_RNA: Array = struct.field(default=0.0)    # [m]
    hHub: Array = struct.field(default=100.0)     # [m]
    Fthrust: Array = struct.field(default=0.0)    # [N]
    yaw_stiffness: Array = struct.field(default=0.0)  # [N m/rad]


@struct.dataclass
class RigidBodyCoeffs:
    """6-DOF rigid-body coefficient set about the PRP.

    The output of the statics assembly (reference FOWT.calcStatics,
    raft/raft.py:1836-2012), plus bookkeeping totals used for reporting and
    for the mooring body model.
    """

    M_struc: Array         # (6,6) structural mass/inertia
    C_struc: Array         # (6,6) structural stiffness (CG gravity terms)
    W_struc: Array         # (6,)  weight force/moment vector
    C_hydro: Array         # (6,6) hydrostatic stiffness
    W_hydro: Array         # (6,)  buoyancy force/moment vector
    # report totals
    mass: Array            # () total mass [kg]
    rCG: Array             # (3,) total center of gravity [m]
    V: Array               # () displaced volume [m^3]
    rCB: Array             # (3,) center of buoyancy [m]
    AWP: Array             # () total waterplane area [m^2]
    IWPx: Array            # () waterplane inertia about x (incl. spacing) [m^4]
    IWPy: Array            # () waterplane inertia about y [m^4]
    zMeta: Array           # () metacenter elevation [m]
    # substructure/tower split (reference raft/raft.py:1898-1912)
    m_tower: Array         # () tower mass
    rCG_tower: Array       # (3,)
    m_sub: Array           # () substructure mass
    rCG_sub: Array         # (3,)
    m_shell: Array         # () substructure shell mass
    m_ballast: Array       # () total ballast mass
    I44: Array             # () roll inertia of substructure about its CG
    I55: Array             # () pitch inertia of substructure about its CG
    I66: Array             # () yaw inertia of substructure about its centerline
    I44B: Array            # () roll inertia of substructure about the PRP
    I55B: Array            # () pitch inertia about PRP


@struct.dataclass
class HydroCoeffs:
    """Frequency-dependent hydrodynamic coefficient set.

    Holds the BEM (potential-flow) arrays — zero if no BEM data is staged,
    matching reference behavior at raft/raft.py:1797-1800 — and the Morison
    strip-theory terms from FOWT.calcHydroConstants (raft/raft.py:2076-2157).
    """

    A_bem: Array           # (6,6,nw) added mass
    B_bem: Array           # (6,6,nw) radiation damping
    F_bem: Array           # (6,nw) complex excitation
    A_morison: Array       # (6,6)  strip-theory added mass
    F_morison: Array       # (6,nw) complex Froude-Krylov + dynamic pressure excitation


@struct.dataclass
class WaveState:
    """Discretized sea state on the frequency grid."""

    w: Array               # (nw,) angular frequencies [rad/s]
    k: Array               # (nw,) wave numbers [1/m]
    zeta: Array            # (nw,) wave amplitude spectrum sqrt(S(w)) [m] —
    #                        matches the reference convention raft/raft.py:1825
    # wave heading [rad] — optional so existing (w, k, zeta) construction
    # sites are untouched.  None means "use env.beta" (the single-case
    # path); batched sea-state sweeps set it per case so a DLC table can
    # vary heading alongside (Hs, Tp) (reference env surface carries beta,
    # raft/runRAFT.py:68).
    beta: Optional[Array] = struct.field(default=None)
    # (nw,) bool: True = physical frequency bin, False = bucket padding
    # (raft_tpu.build.buckets): padded bins extend the grid past w_max
    # with zeta = 0 AND a zeroed fixed-point seed (solve_dynamics), which
    # together pin their response to exactly zero every iteration — the
    # invariant that makes a padded grid's solution bit-for-bit the
    # unpadded one (up to reduction order).  None (the default) means
    # every bin is physical: the pre-bucketing program, untouched.
    freq_mask: Optional[Array] = struct.field(default=None)
