"""Complex arithmetic as explicit (re, im) real-array pairs.

The TPU backend in this environment implements no complex dtypes (every
complex op, even ``complex add``, is UNIMPLEMENTED at the XLA level).  All
frequency-domain quantities in raft_tpu — wave kinematics, excitation
amplitudes, impedance matrices, response amplitudes — are therefore carried
as a :class:`Cx` pytree of two real arrays.  This is also the faster design
on TPU hardware that *does* support complex: elementwise re/im ops fuse
freely, and complex matmuls lower to real MXU matmuls.

``Cx`` is a registered pytree (flax.struct), so it passes transparently
through jit / vmap / grad / scan / shard_map.
"""
from __future__ import annotations

import jax.numpy as jnp
from flax import struct

Array = jnp.ndarray


@struct.dataclass
class Cx:
    """A complex tensor as a (re, im) pair of equally-shaped real arrays."""

    re: Array
    im: Array

    # ---- constructors ----
    @staticmethod
    def of(z) -> "Cx":
        """From a numpy/jnp complex (or real) array — host-side staging."""
        z = jnp.asarray(z)
        return Cx(jnp.real(z), jnp.imag(z) if jnp.iscomplexobj(z) else jnp.zeros_like(jnp.real(z)))

    @staticmethod
    def zeros(shape, dtype=jnp.float32) -> "Cx":
        return Cx(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    @staticmethod
    def expi(theta: Array) -> "Cx":
        """e^{i theta} for real theta."""
        return Cx(jnp.cos(theta), jnp.sin(theta))

    # ---- views ----
    @property
    def shape(self):
        return self.re.shape

    @property
    def dtype(self):
        return self.re.dtype

    def to_complex(self) -> Array:
        """Materialize as a complex array ON HOST (numpy).

        The TPU backend has no complex dtype support, so the combine always
        happens host-side; use ``.re``/``.im`` to stay on device.
        """
        import numpy as np

        return np.asarray(self.re) + 1j * np.asarray(self.im)

    # ---- arithmetic ----
    def __add__(self, o):
        if isinstance(o, Cx):
            return Cx(self.re + o.re, self.im + o.im)
        return Cx(self.re + o, self.im + jnp.zeros_like(self.im))

    __radd__ = __add__

    def __sub__(self, o):
        if isinstance(o, Cx):
            return Cx(self.re - o.re, self.im - o.im)
        return Cx(self.re - o, self.im)

    def __rsub__(self, o):
        return Cx(o - self.re, -self.im)

    def __neg__(self):
        return Cx(-self.re, -self.im)

    def __mul__(self, o):
        if isinstance(o, Cx):
            return Cx(self.re * o.re - self.im * o.im, self.re * o.im + self.im * o.re)
        return Cx(self.re * o, self.im * o)  # o real scalar/array

    __rmul__ = __mul__

    def __truediv__(self, o):
        # division by zero propagates inf/NaN like numpy complex would;
        # solver kernels that divide by possibly-padded lanes carry their
        # own explicit guards instead.
        if isinstance(o, Cx):
            d = o.abs2()
            return Cx(
                (self.re * o.re + self.im * o.im) / d,
                (self.im * o.re - self.re * o.im) / d,
            )
        return Cx(self.re / o, self.im / o)

    def __rtruediv__(self, o):
        d = self.abs2()
        return Cx(o * self.re / d, -o * self.im / d)

    def mul_i(self) -> "Cx":
        """Multiply by i (e.g. differentiation in frequency domain)."""
        return Cx(-self.im, self.re)

    def conj(self) -> "Cx":
        return Cx(self.re, -self.im)

    def abs2(self) -> Array:
        return self.re * self.re + self.im * self.im

    def abs(self) -> Array:
        return jnp.sqrt(self.abs2())

    # ---- structural ops (mirror jnp API on both parts) ----
    def __getitem__(self, idx):
        return Cx(self.re[idx], self.im[idx])

    def reshape(self, *shape):
        return Cx(self.re.reshape(*shape), self.im.reshape(*shape))

    def sum(self, axis=None):
        return Cx(self.re.sum(axis=axis), self.im.sum(axis=axis))

    def swapaxes(self, a, b):
        return Cx(jnp.swapaxes(self.re, a, b), jnp.swapaxes(self.im, a, b))

    def astype(self, dtype):
        return Cx(self.re.astype(dtype), self.im.astype(dtype))


def where(cond: Array, a: Cx, b: Cx) -> Cx:
    return Cx(jnp.where(cond, a.re, b.re), jnp.where(cond, a.im, b.im))


def stack(xs, axis=0) -> Cx:
    return Cx(
        jnp.stack([x.re for x in xs], axis=axis),
        jnp.stack([x.im for x in xs], axis=axis),
    )


def concatenate(xs, axis=0) -> Cx:
    return Cx(
        jnp.concatenate([x.re for x in xs], axis=axis),
        jnp.concatenate([x.im for x in xs], axis=axis),
    )


def einsum(eq: str, *ops) -> Cx:
    """einsum over a mix of Cx and real operands (expands re/im products)."""
    cxs = [isinstance(o, Cx) for o in ops]
    n_cx = sum(cxs)
    if n_cx == 0:
        r = jnp.einsum(eq, *ops)
        return Cx(r, jnp.zeros_like(r))
    if n_cx == 1:
        i = cxs.index(True)
        re_ops = [o.re if j == i else o for j, o in enumerate(ops)]
        im_ops = [o.im if j == i else o for j, o in enumerate(ops)]
        return Cx(jnp.einsum(eq, *re_ops), jnp.einsum(eq, *im_ops))
    if n_cx == 2:
        i = cxs.index(True)
        j = cxs.index(True, i + 1)

        def term(pi, pj):
            arrs = []
            for k, o in enumerate(ops):
                if k == i:
                    arrs.append(o.re if pi == 0 else o.im)
                elif k == j:
                    arrs.append(o.re if pj == 0 else o.im)
                else:
                    arrs.append(o)
            return jnp.einsum(eq, *arrs)

        return Cx(term(0, 0) - term(1, 1), term(0, 1) + term(1, 0))
    raise NotImplementedError("einsum with >2 complex operands")


def matmul(A, B) -> Cx:
    """Complex matmul via real matmuls (4 real MXU matmuls, or 2 if one is real)."""
    if isinstance(A, Cx) and isinstance(B, Cx):
        return Cx(A.re @ B.re - A.im @ B.im, A.re @ B.im + A.im @ B.re)
    if isinstance(A, Cx):
        return Cx(A.re @ B, A.im @ B)
    return Cx(A @ B.re, A @ B.im)
