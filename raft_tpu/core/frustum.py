"""Frustum volume / centroid / moment-of-inertia kernels.

The reference computes these with per-case closed forms (``FrustumVCV``
raft/raft.py:873-900, ``FrustumMOI`` raft/raft.py:251-269,
``RectangularFrustumMOI`` raft/raft.py:271-332 — the latter with four
branches, one of which is broken upstream).  Here a single vectorized
implementation covers every case: all the integrands are polynomials of
degree <= 4 in the axial coordinate (cross-section dimensions vary linearly),
so a fixed 3-point Gauss-Legendre rule is *exact* — no branches, no special
cases, fully batch-broadcastable and differentiable.

Conventions: a "section pair" is (dA, dB) with shape (...,2) holding the two
side lengths of a rectangular section or [d, d] for a circular one; ``circ``
is a boolean selecting the circular area/inertia formulas.

Deviations from the reference (documented, intentional):
  * Rectangular frusta whose two side lengths taper non-proportionally use
    the exact integral here; the reference applies the pyramidal-frustum
    formula with a geometric-mean mid-area (raft/raft.py:888), which is only
    exact for proportional taper.
  * The reference's general rectangular-taper MOI branch raises a TypeError
    upstream (``H(...)`` called as a function, raft/raft.py:295-298); here it
    is simply the same quadrature.
"""
from __future__ import annotations

import jax.numpy as jnp

Array = jnp.ndarray

import math

import numpy as np

# 3-point Gauss-Legendre nodes/weights on [0, 1]: exact for degree <= 5.
# Plain numpy (weakly typed) so the working dtype follows the inputs — baking
# jnp arrays at import time would freeze them at the then-current default.
_GL_X = np.array([0.5 - math.sqrt(3.0 / 20.0), 0.5, 0.5 + math.sqrt(3.0 / 20.0)])
_GL_W = np.array([5.0 / 18.0, 8.0 / 18.0, 5.0 / 18.0])


def _sections(dA: Array, dB: Array):
    """Linear side lengths at the 3 quadrature points: (..., 3, 2)."""
    xi = _GL_X  # (3,)
    return dA[..., None, :] + (dB - dA)[..., None, :] * xi[:, None]


def _areas(s: Array, circ: Array) -> Array:
    """Cross-section areas at quadrature points: (..., 3)."""
    a_circ = 0.25 * jnp.pi * s[..., 0] * s[..., 1]   # pi/4 d^2 (with s=[d,d])
    a_rect = s[..., 0] * s[..., 1]
    return jnp.where(circ[..., None], a_circ, a_rect)


def frustum_vcv(dA: Array, dB: Array, H: Array, circ: Array):
    """Volume and axial center-of-volume height of a linear frustum.

    Equivalent of FrustumVCV (raft/raft.py:873-900).
    dA, dB: (...,2) side-length pairs; H: (...,); circ: (...,) bool.
    Returns (V, hc): volume and centroid height above the lower face.
    """
    s = _sections(dA, dB)
    A = _areas(s, circ)                       # (...,3)
    V = H * jnp.einsum("q,...q->...", _GL_W, A)
    Mz = H * H * jnp.einsum("q,q,...q->...", _GL_W, _GL_X, A)
    hc = Mz / jnp.where(V != 0, V, 1.0)
    return V, hc


def frustum_moi(dA: Array, dB: Array, H: Array, rho: Array, circ: Array):
    """Moments of inertia of a solid linear frustum about its lower end node.

    Equivalent of FrustumMOI / RectangularFrustumMOI
    (raft/raft.py:251-269, 271-332) with local axes: x,y transverse at the
    lower end node on the member axis, z axial.

    Returns (Ixx_end, Iyy_end, Izz): Ixx/Iyy about the end node, Izz about
    the axis (same at any axial position).
    """
    s = _sections(dA, dB)                     # (...,3,2)
    L, W = s[..., 0], s[..., 1]
    xi = _GL_X
    z2 = (H[..., None] * xi) ** 2             # (...,3)

    # circular: section inertias pi/64 d^4 about both transverse axes, pi/32 d^4 polar
    d4 = (L * L) * (W * W)                    # d^4 for circular ([d,d])
    ixx_c = jnp.pi / 64.0 * d4
    izz_c = jnp.pi / 32.0 * d4
    A_c = 0.25 * jnp.pi * L * W
    # rectangular: (1/12) L W^3 about x, (1/12) L^3 W about y
    ixx_r = (L * W**3) / 12.0
    iyy_r = (L**3 * W) / 12.0
    A_r = L * W

    c = circ[..., None]
    ixx = jnp.where(c, ixx_c, ixx_r)
    iyy = jnp.where(c, ixx_c, iyy_r)
    izz = jnp.where(c, izz_c, ixx_r + iyy_r)
    A = jnp.where(c, A_c, A_r)

    w = _GL_W
    Ixx_end = rho * H * jnp.einsum("q,...q->...", w, ixx + A * z2)
    Iyy_end = rho * H * jnp.einsum("q,...q->...", w, iyy + A * z2)
    Izz = rho * H * jnp.einsum("q,...q->...", w, izz)
    return Ixx_end, Iyy_end, Izz
