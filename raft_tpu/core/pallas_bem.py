"""Pallas-tiled BEM influence-matrix assembly (the panel-solve hot path).

The JAX BEM port (:mod:`raft_tpu.hydro.jax_bem`) assembles two dense
(panels x panels) interaction stages per solve: the frequency-independent
Rankine direct+image quadrature (a scan over ~760 subdivision points,
each step one (n, n) broadcast op) and the per-frequency wave part (the
tabulated PV integrals I0/I1, bilinear in f32, plus Bessel asymptotics).
Under XLA each scan step round-trips its (n, n) working set through HBM;
at n = 2048 that is ~16 MB per step, hundreds of times.

This module is the same math as two hand-tiled Pallas kernels over
(panel_i, panel_j) tiles of edge :data:`TILE` (= ``buckets.BEM_TILE``,
the built-in panels-ladder alignment):

* :func:`rankine_assembly` — the full subdivision-point loop runs per
  tile with the (TILE, TILE) accumulators VMEM-resident, and the eight
  (n, n[, 3]) direct/image potential+gradient outputs of the XLA path
  collapse to the TWO matrices the solve actually consumes:
  ``R_pot = pot_d + pot_i`` and ``R_dn = (grad_d + grad_i) . n_i``.
* :func:`wave_assembly` — one frequency's wave part + combine: the
  wave-integral tables (~720 KB f32 each) are resident in VMEM for
  every tile, and the tile emits the assembled ``S``/``Dn`` blocks
  directly, so no wave-part intermediate ever exists in HBM.  Batched
  over a frequency chunk via ``jax.vmap`` (the ``pallas_call`` batching
  rule turns the batch into a leading grid axis; per-frequency scalars
  ride as (1, 1) operands, so the finite-depth ``lax.cond`` stays a
  real branch per grid step instead of vmap's both-sides ``select``).

Both kernels call the SAME region-split helpers as the XLA route
(``eval_wave_integrals`` / ``_wave_deep`` / ``_wave_fd`` / the level
selectors), imported lazily from :mod:`raft_tpu.hydro.jax_bem` — the
routes share one numerical definition and differ only in tiling, which
is what makes the interpret-mode cross-path parity pin
(tests/test_bem_tiles.py, 1e-4 — the PR 3 precedent) meaningful.

Route selection lives in :func:`jax_bem.resolved_assembly` (the
key-salted ``RAFT_TPU_BEM_ASSEMBLY`` knob, auto = pallas iff TPU); the
XLA path remains the fallback for non-``TILE``-aligned custom ladders
and for every differentiated trace (these kernels carry no AD rules —
the geometry co-design hook pins ``assembly="xla"``).  On non-TPU
backends the kernels run in interpreter mode (CPU tests/smoke); the
table bilinear gather is the documented Mosaic caveat to re-validate on
hardware, per the honest-reporting precedent.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from raft_tpu.build.buckets import BEM_TILE as TILE

Array = jnp.ndarray

#: documented XLA-vs-pallas cross-route agreement bound (scale-relative
#: max |pallas - xla|, the PR 3 interpret-parity precedent): the routes
#: share one numerical definition, so only summation association and
#: fused-multiply contraction differ.  Pinned by tests/test_bem_tiles.py
#: and the bem-smoke pallas leg.
INTERP_PARITY_RTOL = 1e-4


def tile_ok(n: int) -> bool:
    """True when an n-panel padded mesh divides into whole tiles (every
    built-in panels-ladder class does; custom ladders may not — those
    classes use the XLA assembly route)."""
    return n >= TILE and n % TILE == 0


def _interpret_default() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _gl_rows(dtype):
    """The 16-point Gauss-Legendre nodes of the near quadrature as
    (1, 16) operand rows (kernels may not capture constant arrays)."""
    from raft_tpu.hydro import jax_bem as _jb

    return (jnp.asarray(_jb._GL16_X, dtype)[None, :],
            jnp.asarray(_jb._GL16_W, dtype)[None, :])


def _quad_stack(quads):
    """Host quad constants -> (1, NQ) device rows (u, v, weight, level)."""
    import numpy as np

    us = np.concatenate([q[0] for q in quads])[None, :]
    vs = np.concatenate([q[1] for q in quads])[None, :]
    wf = np.concatenate([q[2] for q in quads])[None, :]
    lv = np.concatenate([q[3] for q in quads])[None, :]
    return us, vs, wf, lv


# ------------------------------------------------------- Rankine kernel


def _rankine_kernel(nq_main: int, nq_fine: int,
                    pans_ref, ci_ref, ni_ref, cj_ref, area_ref, diag_ref,
                    mask_ref, lids_ref, spot_ref, rid_ref, cid_ref,
                    us_ref, vs_ref, wf_ref, lv_ref,
                    pot_ref, dn_ref):
    """One (TILE, TILE) tile of the Rankine direct+image quadrature.

    Field side (i): centroids + unit normals.  Source side (j): panel
    vertices, centroid, area, diagonal, masks, exact self potential.
    The subdivision-point loop is two ``fori_loop``s (main levels carry
    direct + image, the fine ns=24 level is image-only — the native
    level split), with global row/column ids supplied as data so the
    kernel is insensitive to grid-axis numbering (vmap prepends one).
    """
    from raft_tpu.hydro import jax_bem as _jb

    dtype = ci_ref.dtype
    ci = ci_ref[...]                       # (T, 3)
    ni = ni_ref[...]                       # (T, 3)
    cj = cj_ref[...]                       # (T, 3)
    pans = pans_ref[...]                   # (T, 4, 3)
    area = area_ref[0, :]                  # (T,)
    diag = diag_ref[0, :]
    mask = mask_ref[0, :]
    lids = lids_ref[0, :] > 0.5            # lid-at-surface flag (source)
    spot = spot_ref[0, :]                  # exact self potential
    eye = rid_ref[0, :][:, None] == cid_ref[0, :][None, :]

    def zflip(p):
        # free-surface image: negate z (built by stacking — a (3,) sign
        # vector would be a captured constant, which kernels reject)
        return jnp.stack([p[:, 0], p[:, 1], -p[:, 2]], axis=-1)

    d0 = ci[:, None, :] - cj[None, :, :]
    dist = jnp.sqrt(jnp.sum(d0 * d0, axis=-1) + 1e-20)
    dI = ci[:, None, :] - zflip(cj)[None, :, :]
    distI = jnp.sqrt(jnp.sum(dI * dI, axis=-1) + 1e-20)
    diag_safe = jnp.where(diag > 1e-9, diag, 1.0)
    rel = jnp.where(diag > 1e-9, dist / diag_safe[None, :], 1e9)
    relI = jnp.where(diag > 1e-9, distI / diag_safe[None, :], 1e9)
    sel_d = _jb._level_select_direct(rel)
    sel_i = _jb._level_select_image(relI)
    # diagonal: exact direct self term (sentinel -1 drops the numeric
    # one); image diagonal stays numeric except lid panels AT z = 0
    sel_d = jnp.where(eye, -1, sel_d)
    sel_i = jnp.where(eye & lids[None, :], -1, sel_i)

    def contrib(pt, dA, sel, lv):
        d = ci[:, None, :] - pt[None, :, :]
        r2 = jnp.sum(d * d, axis=-1)
        ok = (sel == lv) & (r2 > 1e-12)
        r2s = jnp.where(ok, r2, 1.0)
        ir = 1.0 / jnp.sqrt(r2s)
        ir3 = ir / r2s
        pot = jnp.where(ok, dA[None, :] * ir, 0.0)
        dsn = (d[:, :, 0] * ni[:, 0][:, None] + d[:, :, 1]
               * ni[:, 1][:, None] + d[:, :, 2] * ni[:, 2][:, None])
        return pot, jnp.where(ok, -dA[None, :] * ir3, 0.0) * dsn

    def point(q):
        u = us_ref[0, q]
        v = vs_ref[0, q]
        pt = ((1 - u) * (1 - v) * pans[:, 0] + u * (1 - v) * pans[:, 1]
              + u * v * pans[:, 2] + (1 - u) * v * pans[:, 3])
        return pt, area * wf_ref[0, q], lv_ref[0, q]

    def body_main(q, carry):
        pot, dn = carry
        pt, dA, lv = point(q)
        p, g = contrib(pt, dA, sel_d, lv)
        pot, dn = pot + p, dn + g
        p, g = contrib(zflip(pt), dA, sel_i, lv)
        return pot + p, dn + g

    def body_fine(q, carry):
        pot, dn = carry
        pt, dA, lv = point(q)
        p, g = contrib(zflip(pt), dA, sel_i, lv)
        return pot + p, dn + g

    zero = jnp.zeros((ci.shape[0], cj.shape[0]), dtype)
    pot, dn = lax.fori_loop(0, nq_main, body_main, (zero, zero))
    pot, dn = lax.fori_loop(nq_main, nq_main + nq_fine, body_fine,
                            (pot, dn))
    # exact self potential on the diagonal (doubled for a lid panel at
    # z = 0, whose free-surface image is itself)
    pot = pot + jnp.where(eye, spot[None, :]
                          * (1.0 + jnp.where(lids, 1.0, 0.0))[None, :], 0.0)
    colm = mask[None, :]
    pot_ref[...] = pot * colm
    dn_ref[...] = dn * colm


def rankine_assembly(pans, c, nrm, area, diag, panel_mask, lid_surface,
                     self_pot, *, interpret: bool | None = None):
    """Tiled Rankine assembly: ``(R_pot, R_dn)`` with
    ``R_pot = pot_d + pot_i`` and ``R_dn = (grad_d + grad_i) . n_i`` —
    exactly the two (n, n) matrices the per-frequency combine consumes
    (the XLA route's eight pot/grad outputs, pre-collapsed in VMEM)."""
    from raft_tpu.hydro import jax_bem as _jb

    n = pans.shape[0]
    if not tile_ok(n):
        raise ValueError(f"panel count {n} not a {TILE} multiple; "
                         f"use the XLA assembly route")
    dtype = c.dtype
    interpret = _interpret_default() if interpret is None else interpret
    g = n // TILE

    usm, vsm, wfm, lvm = _quad_stack((_jb._QUAD_MAIN, _jb._QUAD_FINE))
    nq_main = _jb._QUAD_MAIN[0].shape[0]
    nq_fine = _jb._QUAD_FINE[0].shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)[None, :]
    row1 = lambda x: jnp.asarray(x, dtype).reshape(1, n)

    full = lambda shape: pl.BlockSpec(shape, lambda i, j: (0,) * len(shape))
    irow = pl.BlockSpec((1, TILE), lambda i, j: (0, i))
    jrow = pl.BlockSpec((1, TILE), lambda i, j: (0, j))
    out = pl.BlockSpec((TILE, TILE), lambda i, j: (i, j))

    kernel = functools.partial(_rankine_kernel, nq_main, nq_fine)
    nq = nq_main + nq_fine
    R_pot, R_dn = pl.pallas_call(
        kernel,
        grid=(g, g),
        in_specs=[
            pl.BlockSpec((TILE, 4, 3), lambda i, j: (j, 0, 0)),   # pans_j
            pl.BlockSpec((TILE, 3), lambda i, j: (i, 0)),         # c_i
            pl.BlockSpec((TILE, 3), lambda i, j: (i, 0)),         # nrm_i
            pl.BlockSpec((TILE, 3), lambda i, j: (j, 0)),         # c_j
            jrow, jrow, jrow, jrow, jrow,      # area, diag, mask, lids, spot
            irow, jrow,                        # row ids, col ids
            full((1, nq)), full((1, nq)), full((1, nq)), full((1, nq)),
        ],
        out_specs=(out, out),
        out_shape=(jax.ShapeDtypeStruct((n, n), dtype),
                   jax.ShapeDtypeStruct((n, n), dtype)),
        interpret=interpret,
    )(
        pans, c, nrm, c,
        row1(area), row1(diag), row1(panel_mask),
        row1(jnp.where(lid_surface, 1.0, 0.0)), row1(self_pot),
        ids, ids,
        jnp.asarray(usm, dtype), jnp.asarray(vsm, dtype),
        jnp.asarray(wfm, dtype), jnp.asarray(lvm),
    )
    return R_pot, R_dn


# --------------------------------------------------------- wave kernel


def _wave_kernel(finite_depth: bool, depth: float,
                 Rp_ref, Rdn_ref, ci_ref, ni_ref, cj_ref, area_ref,
                 mask_ref, lids_ref, rid_ref, cid_ref, i0_ref, i1_ref,
                 glx_ref, glw_ref, k_ref, k0_ref, A0_ref, act_ref,
                 lam_ref, a_ref,
                 sre_ref, sim_ref, dre_ref, dim_ref):
    """One (TILE, TILE) tile of one frequency's wave part + combine.

    Emits the assembled S (source-potential) and Dn (normal-derivative)
    blocks; the -2 pi diagonal shift and the lid-row equation swap are
    O(n^2) elementwise and stay outside (shared with the XLA route).
    The wave-integral tables are whole-array VMEM residents; for finite
    depth the deep-vs-4-image choice is a real scalar ``lax.cond`` per
    grid step (``active`` rides in as a (1, 1) operand).
    """
    from raft_tpu.hydro import jax_bem as _jb

    ci = ci_ref[...]
    ni = ni_ref[...]
    cj = cj_ref[...]
    area = area_ref[0, :]                  # (T,)
    colm = mask_ref[0, :][None, :]
    # the near-quadrature GL nodes ride in as operands ("nodes" key —
    # see eval_wave_integrals), since kernels may not capture constants
    tab = {"I0": i0_ref[...], "I1": i1_ref[...],
           "nodes": (glx_ref[0, :], glw_ref[0, :])}
    eye = rid_ref[0, :][:, None] == cid_ref[0, :][None, :]
    diag_lid = eye & (lids_ref[0, :] > 0.5)[None, :]

    dx = ci[:, 0][:, None] - cj[:, 0][None, :]
    dy = ci[:, 1][:, None] - cj[:, 1][None, :]
    R = jnp.sqrt(dx * dx + dy * dy + 1e-20)
    zP = jnp.broadcast_to(ci[:, 2][:, None], R.shape)
    zQ = jnp.broadcast_to(cj[:, 2][None, :], R.shape)

    k = k_ref[0, 0]
    if finite_depth:
        k0 = k0_ref[0, 0]
        A0 = A0_ref[0, 0]
        lam = lam_ref[0, :]
        a_fit = a_ref[0, :]

        def fd_branch(_):
            return _jb._wave_fd(k0, A0, lam, a_fit, depth, R, dx, dy,
                                zP, zQ, area, diag_lid, tab)

        def deep_branch(_):
            return _jb._wave_deep(k, R, dx, dy, zP + zQ, area, diag_lid,
                                  tab)

        G, gx, gy, gz = lax.cond(act_ref[0, 0] > 0.5, fd_branch,
                                 deep_branch, operand=None)
    else:
        G, gx, gy, gz = _jb._wave_deep(k, R, dx, dy, zP + zQ, area,
                                       diag_lid, tab)

    area_row = area[None, :]
    sre_ref[...] = (Rp_ref[...] + G.re * area_row) * colm
    sim_ref[...] = (G.im * area_row) * colm
    proj_re = (gx.re * ni[:, 0][:, None] + gy.re * ni[:, 1][:, None]
               + gz.re * ni[:, 2][:, None])
    proj_im = (gx.im * ni[:, 0][:, None] + gy.im * ni[:, 1][:, None]
               + gz.im * ni[:, 2][:, None])
    dre_ref[...] = (Rdn_ref[...] + proj_re * area_row) * colm
    dim_ref[...] = (proj_im * area_row) * colm


def wave_assembly(R_pot, R_dn, c, nrm, area, panel_mask, lid_surface,
                  tab, k, fd_scal, *, finite_depth: bool, depth: float,
                  interpret: bool | None = None):
    """Tiled wave part + combine for ONE frequency: returns the
    assembled ``(S_re, S_im, Dn_re, Dn_im)`` (n, n) matrices.

    ``k`` is the deep-water wavenumber scalar; ``fd_scal`` the
    per-frequency finite-depth fit ``{"k0", "A0", "active", "lam", "a"}``
    (ignored when ``finite_depth`` is False — zero placeholders are
    staged so the operand list is route-static).  Safe under ``vmap``
    over a frequency chunk: every per-frequency value is an operand.
    """
    n = R_pot.shape[0]
    if not tile_ok(n):
        raise ValueError(f"panel count {n} not a {TILE} multiple; "
                         f"use the XLA assembly route")
    dtype = R_pot.dtype
    interpret = _interpret_default() if interpret is None else interpret
    g = n // TILE
    nlam = fd_scal["lam"].shape[-1] if finite_depth else 1

    def s11(x):
        return jnp.asarray(x, dtype).reshape(1, 1)

    if finite_depth:
        k0 = s11(fd_scal["k0"])
        A0 = s11(fd_scal["A0"])
        act = s11(fd_scal["active"])
        lam = jnp.asarray(fd_scal["lam"], dtype).reshape(1, nlam)
        a_f = jnp.asarray(fd_scal["a"], dtype).reshape(1, nlam)
    else:
        k0 = A0 = act = s11(0.0)
        lam = a_f = jnp.zeros((1, nlam), dtype)

    ids = jnp.arange(n, dtype=jnp.int32)[None, :]
    row1 = lambda x: jnp.asarray(x, dtype).reshape(1, n)
    full = lambda shape: pl.BlockSpec(shape, lambda i, j: (0,) * len(shape))
    tile = pl.BlockSpec((TILE, TILE), lambda i, j: (i, j))
    irow = pl.BlockSpec((1, TILE), lambda i, j: (0, i))
    jrow = pl.BlockSpec((1, TILE), lambda i, j: (0, j))

    kernel = functools.partial(_wave_kernel, finite_depth, float(depth))
    outs = pl.pallas_call(
        kernel,
        grid=(g, g),
        in_specs=[
            tile, tile,                                        # R_pot, R_dn
            pl.BlockSpec((TILE, 3), lambda i, j: (i, 0)),      # c_i
            pl.BlockSpec((TILE, 3), lambda i, j: (i, 0)),      # nrm_i
            pl.BlockSpec((TILE, 3), lambda i, j: (j, 0)),      # c_j
            jrow, jrow, jrow,                  # area, mask, lid-surface
            irow, jrow,                        # row ids, col ids
            full(tab["I0"].shape), full(tab["I1"].shape),
            full((1, 16)), full((1, 16)),      # near-quadrature GL nodes
            full((1, 1)), full((1, 1)), full((1, 1)), full((1, 1)),
            full((1, nlam)), full((1, nlam)),
        ],
        out_specs=(tile, tile, tile, tile),
        out_shape=tuple(jax.ShapeDtypeStruct((n, n), dtype)
                        for _ in range(4)),
        interpret=interpret,
    )(
        R_pot, R_dn, c, nrm, c,
        row1(area), row1(panel_mask), row1(jnp.where(lid_surface, 1.0, 0.0)),
        ids, ids, tab["I0"], tab["I1"],
        _gl_rows(dtype)[0], _gl_rows(dtype)[1],
        s11(k), k0, A0, act, lam, a_f,
    )
    return outs
