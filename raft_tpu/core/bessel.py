"""Device-evaluable Bessel functions J0, J1, Y0, Y1.

The free-surface Green function of the on-device BEM
(:mod:`raft_tpu.hydro.jax_bem`) needs J0/J1 (radiated-wave part) at every
panel pair and Y0/Y1 in the large-X far field, but ``jax.scipy.special``
ships neither Y_n nor an f32-friendly J_n.  These are the standard
Abramowitz & Stegun rational/asymptotic approximations (the Numerical
Recipes coefficients): absolute error < 2e-7 over the real line — below
f32 resolution, which is all the f32 BEM blocks can use anyway.  Pure
``jnp`` elementwise ops: vmappable, differentiable, TPU-native.
"""
from __future__ import annotations

import jax.numpy as jnp

_2_OVER_PI = 0.636619772367581343


def _poly(y, coeffs):
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = c + y * acc
    return acc


def _j0_small(y):
    num = _poly(y, (57568490574.0, -13362590354.0, 651619640.7,
                    -11214424.18, 77392.33017, -184.9052456))
    den = _poly(y, (57568490411.0, 1029532985.0, 9494680.718,
                    59272.64853, 267.8532712, 1.0))
    return num / den


def _j0_large(ax):
    z = 8.0 / ax
    y = z * z
    xx = ax - 0.785398164
    p = _poly(y, (1.0, -0.1098628627e-2, 0.2734510407e-4,
                  -0.2073370639e-5, 0.2093887211e-6))
    q = _poly(y, (-0.1562499995e-1, 0.1430488765e-3, -0.6911147651e-5,
                  0.7621095161e-6, -0.934935152e-7))
    return jnp.sqrt(_2_OVER_PI / ax) * (jnp.cos(xx) * p
                                        - z * jnp.sin(xx) * q)


def j0(x):
    ax = jnp.abs(x)
    small = ax < 8.0
    ax_l = jnp.where(small, 8.0, ax)            # double-where: keep the
    y = jnp.where(small, ax * ax, 0.0)          # untaken branch finite
    return jnp.where(small, _j0_small(y), _j0_large(ax_l))


def _j1_small(x, y):
    num = x * _poly(y, (72362614232.0, -7895059235.0, 242396853.1,
                        -2972611.439, 15704.48260, -30.16036606))
    den = _poly(y, (144725228442.0, 2300535178.0, 18583304.74,
                    99447.43394, 376.9991397, 1.0))
    return num / den


def _j1_large(ax):
    z = 8.0 / ax
    y = z * z
    xx = ax - 2.356194491
    p = _poly(y, (1.0, 0.183105e-2, -0.3516396496e-4, 0.2457520174e-5,
                  -0.240337019e-6))
    q = _poly(y, (0.04687499995, -0.2002690873e-3, 0.8449199096e-5,
                  -0.88228987e-6, 0.105787412e-6))
    return jnp.sqrt(_2_OVER_PI / ax) * (jnp.cos(xx) * p
                                        - z * jnp.sin(xx) * q)


def j1(x):
    ax = jnp.abs(x)
    small = ax < 8.0
    ax_l = jnp.where(small, 8.0, ax)
    y = jnp.where(small, ax * ax, 0.0)
    out = jnp.where(small, _j1_small(ax, y), _j1_large(ax_l))
    return jnp.sign(x) * jnp.where(x == 0, 0.0, out)


def y0(x):
    """Y0 for x > 0 (guarded at 0: returns the value at a tiny clamp)."""
    x = jnp.maximum(x, 1e-30)
    small = x < 8.0
    x_s = jnp.where(small, x, 1.0)
    y = x_s * x_s
    num = _poly(y, (-2957821389.0, 7062834065.0, -512359803.6,
                    10879881.29, -86327.92757, 228.4622733))
    den = _poly(y, (40076544269.0, 745249964.8, 7189466.438,
                    47447.26470, 226.1030244, 1.0))
    small_val = num / den + _2_OVER_PI * j0(x_s) * jnp.log(x_s)
    x_l = jnp.where(small, 8.0, x)
    z = 8.0 / x_l
    yl = z * z
    xx = x_l - 0.785398164
    p = _poly(yl, (1.0, -0.1098628627e-2, 0.2734510407e-4,
                   -0.2073370639e-5, 0.2093887211e-6))
    q = _poly(yl, (-0.1562499995e-1, 0.1430488765e-3, -0.6911147651e-5,
                   0.7621095161e-6, -0.934935152e-7))
    large_val = jnp.sqrt(_2_OVER_PI / x_l) * (jnp.sin(xx) * p
                                              + z * jnp.cos(xx) * q)
    return jnp.where(small, small_val, large_val)


def y1(x):
    """Y1 for x > 0 (guarded at 0)."""
    x = jnp.maximum(x, 1e-30)
    small = x < 8.0
    x_s = jnp.where(small, x, 1.0)
    y = x_s * x_s
    num = x_s * _poly(y, (-0.4900604943e13, 0.1275274390e13,
                          -0.5153438139e11, 0.7349264551e9,
                          -0.4237922726e7, 0.8511937935e4))
    den = _poly(y, (0.2499580570e14, 0.4244419664e12, 0.3733650367e10,
                    0.2245904002e8, 0.1020426050e6, 0.3549632885e3, 1.0))
    small_val = num / den + _2_OVER_PI * (j1(x_s) * jnp.log(x_s)
                                          - 1.0 / x_s)
    x_l = jnp.where(small, 8.0, x)
    z = 8.0 / x_l
    yl = z * z
    xx = x_l - 2.356194491
    p = _poly(yl, (1.0, 0.183105e-2, -0.3516396496e-4, 0.2457520174e-5,
                   -0.240337019e-6))
    q = _poly(yl, (0.04687499995, -0.2002690873e-3, 0.8449199096e-5,
                   -0.88228987e-6, 0.105787412e-6))
    large_val = jnp.sqrt(_2_OVER_PI / x_l) * (jnp.sin(xx) * p
                                              + z * jnp.cos(xx) * q)
    return jnp.where(small, small_val, large_val)
