"""Rigid-body transform kernels (pure jnp, batch-friendly).

Functional equivalents of the reference's module-level helpers
(``getH``/``translateForce3to6DOF``/``translateMatrix3to6DOF``/
``translateMatrix6to6DOF``/``VecVecTrans``/``SmallRotate`` at
raft/raft.py:998-1102), re-designed so that every function broadcasts over
arbitrary leading batch axes — one call handles all segments/nodes of a
platform, or a whole batch of designs, without Python loops.

Deviation from the reference: ``SmallRotate`` in the reference overwrites all
three components into element 0 (raft/raft.py:1002-1005, acknowledged broken
in-code); ``small_rotation_displacement`` here implements the intended
cross-product form.
"""
from __future__ import annotations

import jax.numpy as jnp

Array = jnp.ndarray


def alternator(r: Array) -> Array:
    """H(r) matrix with H[0,1]=z, H[0,2]=-y, H[1,2]=x (antisymmetric).

    This is the "alternator" layout used by the 6-DOF translation identities
    (cf. reference getH, raft/raft.py:1022-1032).  Note ``H(r) @ f = f x r``
    and ``H(r).T @ f = r x f``.

    r: (..., 3) -> (..., 3, 3)
    """
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    zero = jnp.zeros_like(x)
    return jnp.stack(
        [
            jnp.stack([zero, z, -y], axis=-1),
            jnp.stack([-z, zero, x], axis=-1),
            jnp.stack([y, -x, zero], axis=-1),
        ],
        axis=-2,
    )


def vec_outer(v: Array) -> Array:
    """Outer product v v^T, (...,3) -> (...,3,3) (cf. VecVecTrans raft/raft.py:1010)."""
    return v[..., :, None] * v[..., None, :]


def translate_force_3to6(r: Array, f: Array) -> Array:
    """Force applied at point r -> 6-DOF force/moment about the origin.

    (cf. translateForce3to6DOF raft/raft.py:1036-1051)
    r: (...,3), f: (...,3) -> (...,6). Complex-safe.
    """
    return jnp.concatenate([f, jnp.cross(r, f)], axis=-1)


def translate_matrix_3to6(r: Array, M: Array) -> Array:
    """3x3 mass-like matrix at point r -> 6x6 about the origin.

    (cf. translateMatrix3to6DOF raft/raft.py:1056-1079)
    r: (...,3), M: (...,3,3) -> (...,6,6)
    """
    H = alternator(r)
    MH = M @ H
    top = jnp.concatenate([M, MH], axis=-1)
    HT = jnp.swapaxes(H, -1, -2)
    bot = jnp.concatenate([jnp.swapaxes(MH, -1, -2), H @ M @ HT], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def translate_matrix_6to6(r: Array, M: Array) -> Array:
    """6x6 matrix about a point at r -> 6x6 about the origin.

    (cf. translateMatrix6to6DOF raft/raft.py:1082-1102)
    r: (...,3), M: (...,6,6) -> (...,6,6)
    """
    H = alternator(r)
    HT = jnp.swapaxes(H, -1, -2)
    m = M[..., :3, :3]
    J = M[..., :3, 3:]
    I = M[..., 3:, 3:]
    JT = jnp.swapaxes(J, -1, -2)
    Jp = m @ H + J
    Ip = H @ m @ HT + JT @ H + HT @ J + I
    top = jnp.concatenate([m, Jp], axis=-1)
    bot = jnp.concatenate([jnp.swapaxes(Jp, -1, -2), Ip], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def rotate_diag_tensor(R: Array, Ixx: Array, Iyy: Array, Izz: Array) -> Array:
    """Rotate a diagonal rank-2 tensor into global axes: R diag(I) R^T.

    R: (...,3,3); Ixx/Iyy/Izz: (...) -> (...,3,3).  Used for member-local
    inertia and waterplane-inertia tensors.
    """
    zeros = jnp.zeros_like(Ixx)
    I_loc = jnp.stack(
        [
            jnp.stack([Ixx, zeros, zeros], axis=-1),
            jnp.stack([zeros, Iyy, zeros], axis=-1),
            jnp.stack([zeros, zeros, Izz], axis=-1),
        ],
        axis=-2,
    )
    return R @ I_loc @ jnp.swapaxes(R, -1, -2)


def small_rotation_displacement(r: Array, th: Array) -> Array:
    """Displacement of a point at r under small rotations th: th x r.

    Intended behavior of the reference SmallRotate (raft/raft.py:998-1006,
    which has an acknowledged indexing bug); used for platform-motion node
    kinematics (getVelocity, raft/raft.py:903-919).
    Broadcasts; complex-safe (th may be a complex amplitude).
    """
    return jnp.cross(th, jnp.broadcast_to(r, jnp.broadcast_shapes(r.shape, th.shape)))


def euler_z1y2z3(beta: Array, phi: Array, gamma: Array) -> Array:
    """Z1Y2Z3 Euler rotation matrix (cf. Member.calcOrientation raft/raft.py:205-242).

    beta: heading from x axis, phi: incline from vertical, gamma: twist [rad].
    Broadcasts over leading axes -> (...,3,3).
    """
    s1, c1 = jnp.sin(beta), jnp.cos(beta)
    s2, c2 = jnp.sin(phi), jnp.cos(phi)
    s3, c3 = jnp.sin(gamma), jnp.cos(gamma)
    z = jnp.zeros_like(s1 + s2 + s3)
    r00 = c1 * c2 * c3 - s1 * s3
    r01 = -c3 * s1 - c1 * c2 * s3
    r02 = c1 * s2
    r10 = c1 * s3 + c2 * c3 * s1
    r11 = c1 * c3 - c2 * s1 * s3
    r12 = s1 * s2
    r20 = -c3 * s2 + z
    r21 = s2 * s3 + z
    r22 = c2 + z
    return jnp.stack(
        [
            jnp.stack([r00, r01, r02], axis=-1),
            jnp.stack([r10, r11, r12], axis=-1),
            jnp.stack([r20, r21, r22], axis=-1),
        ],
        axis=-2,
    )


def member_orientation(rA: Array, rB: Array, gamma: Array):
    """Axial/transverse unit vectors and rotation matrix of a member.

    Equivalent of Member.calcOrientation (raft/raft.py:205-242): q along the
    member axis, p1/p2 transverse, R the Z1Y2Z3 matrix built from the member's
    heading (beta), incline (phi) and twist (gamma).

    rA,rB: (...,3); gamma: (...) [rad] -> (q, p1, p2, R)
    """
    rAB = rB - rA
    l = jnp.linalg.norm(rAB, axis=-1, keepdims=True)
    q = rAB / jnp.where(l > 0, l, 1.0)
    beta = jnp.arctan2(q[..., 1], q[..., 0])
    phi = jnp.arctan2(jnp.sqrt(q[..., 0] ** 2 + q[..., 1] ** 2), q[..., 2])
    R = euler_z1y2z3(beta, phi, gamma)
    e1 = jnp.zeros_like(q).at[..., 0].set(1.0)
    p1 = jnp.einsum("...ij,...j->...i", R, e1)
    p2 = jnp.cross(q, p1)
    return q, p1, p2, R


def heading_rotation(heading_deg: Array) -> Array:
    """Member-pattern heading rotation about z.

    Matches the reference convention for replicated member patterns
    (raft/raft.py:71-77): rotMat = [[c, s, 0], [-s, c, 0], [0, 0, 1]] with
    c/s of +heading — i.e. a clockwise rotation of coordinates.  Kept
    identical so replicated geometries (e.g. OC4 offset columns) land at the
    reference's positions.
    """
    a = jnp.deg2rad(heading_deg)
    c, s = jnp.cos(a), jnp.sin(a)
    z = jnp.zeros_like(c)
    o = jnp.ones_like(c)
    return jnp.stack(
        [
            jnp.stack([c, s, z], axis=-1),
            jnp.stack([-s, c, z], axis=-1),
            jnp.stack([z, z, o], axis=-1),
        ],
        axis=-2,
    )
