"""Physical constants and environment defaults.

Mirrors the capability of the reference environment container
(``raft/raft.py:22-30`` in dzalkind/RAFT): seawater density, gravity, and
default sea-state / wind parameters.
"""

RHO_SEAWATER = 1025.0   # [kg/m^3] default water density
GRAVITY = 9.81          # [m/s^2]  gravitational acceleration

DEFAULT_HS = 1.0        # [m]   significant wave height
DEFAULT_TP = 10.0       # [s]   peak spectral period
DEFAULT_V = 10.0        # [m/s] mean wind speed
DEFAULT_BETA = 0.0      # [rad] wave heading
