"""Core math kernels and pytree schemas for raft_tpu."""
from raft_tpu.core import constants, frustum, transforms, types, waves  # noqa: F401
from raft_tpu.core.types import Env, HydroCoeffs, MemberSet, RigidBodyCoeffs, RNA, WaveState  # noqa: F401
