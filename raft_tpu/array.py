"""Multi-turbine arrays: FOWTs stacked on a leading device axis.

The reference is architecturally N-turbine — ``Model.fowtList`` grows by
``addFOWT`` and ``nDOF += 6`` per FOWT (raft/raft.py:1292-1298) — but every
solve method hard-wires ``fowtList[0]``, so arrays never actually run there.
Here the array is a first-class batched axis: each turbine's padded
:class:`~raft_tpu.core.types.MemberSet`/RNA is stacked on a leading axis and
the whole device pipeline (statics, strip hydro, drag-linearized RAO fixed
point) runs under one ``jax.vmap`` — N turbines cost one fused kernel, and
the same leading axis shards over a TPU mesh for large wind farms.

Physics scope matches the reference architecture: turbines are
hydrodynamically independent (no wave-interaction coupling between hulls —
the reference has none either), each with its own mooring system, sharing
one incident wave field.  A turbine at plan position (x, y) sees the
incident wave with phase lag ``exp(-i k (x cos beta + y sin beta))``; the
phase multiplies the wave kinematics at its strip nodes so excitation AND
drag linearization inherit it consistently.  The coupled system matrices are
therefore block-diagonal and the 6N-DOF response is the stacked per-turbine
response — which the block-diagonality test in tests/test_array.py verifies
against single-turbine runs.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.build.members import build_member_set, build_rna
from raft_tpu.core.cplx import Cx
from raft_tpu.core.types import Env, WaveState
from raft_tpu.core.waves import jonswap, wave_number
from raft_tpu.hydro import node_kinematics, strip_added_mass, strip_excitation
from raft_tpu.hydro.strip import StripKin
from raft_tpu.mooring import (
    fairlead_tensions,
    mooring_stiffness,
    parse_mooring,
    solve_equilibrium,
    tension_jacobian,
)
from raft_tpu.solve import LinearCoeffs, diagonal_estimates, solve_dynamics, solve_eigen
from raft_tpu.statics import assemble_statics
from raft_tpu.utils.profiling import phase

Array = jnp.ndarray


def stack_fowts(designs: list[dict]):
    """Build each design's member set with shared pad dims and stack them.

    Returns (members_stacked, rna_stacked) — every leaf gains a leading
    turbine axis, so the single-FOWT kernels run under ``jax.vmap``.
    """
    base = [build_member_set(d) for d in designs]
    S = max(int(m.seg_mask.shape[0]) for m in base)
    N = max(int(m.node_mask.shape[0]) for m in base)
    sets = [build_member_set(d, pad_segments=S, pad_nodes=N) for d in designs]
    members = jax.tree.map(lambda *xs: jnp.stack(xs), *sets)
    rnas = [build_rna(d) for d in designs]
    rna = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *rnas)
    return members, rna


@jax.jit
def _moor_solve_batch(sys_b, F_b, C_b):
    """Equilibrium + stiffness + tensions for a stacked MooringSystem batch:
    (r6_eq (nT,6), residuals (nT,), C_moor (nT,6,6), tensions (nT,nl))."""
    r6, res = jax.vmap(solve_equilibrium)(sys_b, F_b, C_b)
    C = jax.vmap(mooring_stiffness)(sys_b, r6)
    T = jax.vmap(fairlead_tensions)(sys_b, r6)
    return r6, res, C, T


def _phase_kin(kin: StripKin, ph: Cx) -> StripKin:
    """Multiply node wave kinematics by a per-frequency phase factor (nw,)."""
    ph3 = Cx(ph.re[None, :, None], ph.im[None, :, None])
    ph2 = Cx(ph.re[None, :], ph.im[None, :])
    return StripKin(u=kin.u * ph3, ud=kin.ud * ph3, pDyn=kin.pDyn * ph2)


class ArrayModel:
    """N mooring-coupled FOWTs analyzed as one stacked batch (nDOF = 6N).

    ``designs``: one design dict (replicated ``nT`` times) or a list of
    design dicts.  ``positions``: (nT, 2) plan coordinates of each turbine's
    PRP; defaults to all-zero (co-located, useful for verification).
    """

    def __init__(self, designs, positions=None, w=None, depth: float | None = None,
                 nT: int | None = None, BEM=None):
        if isinstance(designs, dict):
            if nT is None:
                nT = len(positions) if positions is not None else 1
            designs = [designs] * nT
        self.designs = list(designs)
        # BEM: None (pure Morison), a mode string ('native' | 'jax' |
        # 'auto' — mesh + solve once, shared across turbines, requires
        # identical designs; routing per Model.calcBEM), or precomputed
        # (A[6,6,nw], B[6,6,nw], F[6,nw]) host arrays.  Per-turbine incident
        # phase is applied to the staged excitation at solve time.
        if BEM is not None and any(d is not self.designs[0] for d in self.designs):
            raise NotImplementedError(
                "BEM in arrays requires identical turbine designs (shared "
                "coefficients); mixed-design arrays run strip-theory only"
            )
        if isinstance(BEM, str) and BEM not in ("native", "jax", "auto"):
            raise ValueError(
                f"BEM={BEM!r}: expected 'native', 'jax', 'auto', or a "
                "precomputed (A, B, F) tuple")
        self.bem_mode = BEM if isinstance(BEM, str) else None
        self.bem = BEM if not isinstance(BEM, str) else None
        self._bem_staged = None
        self.nT = len(self.designs)
        if positions is None:
            positions = np.zeros((self.nT, 2))
        self.positions = np.asarray(positions, dtype=float).reshape(self.nT, 2)
        self._bem_headings = None        # staged heading grid (calcBEM)
        self.members, self.rna = stack_fowts(self.designs)
        self.moor = []
        for d in self.designs:
            mo = d.get("mooring")
            ys = float(d.get("turbine", {}).get("yaw_stiffness", 0.0))
            self.moor.append(parse_mooring(mo, yaw_stiffness=ys) if mo else None)
        if depth is None:
            m0 = self.designs[0].get("mooring")
            depth = float(m0.get("water_depth", 300.0)) if m0 else 300.0
        self.depth = float(depth)
        if w is None:
            w = np.arange(0.05, 3.0, 0.05)
        self.w = jnp.asarray(np.asarray(w, dtype=float))
        self.env = Env(depth=self.depth)
        self.wave: WaveState | None = None
        self.statics = None
        self.kin = None
        self.A_morison = None
        self.F_morison = None
        self.C_moor0 = None
        self.C_moor = None
        self.r6_eq = None
        self.rao = None
        self.results: dict = {}

    def addFOWT(self, design: dict, position=(0.0, 0.0)):
        """Append one turbine to the array (cf. Model.addFOWT,
        raft/raft.py:1292-1298 — where the reference grows ``fowtList`` and
        ``nDOF`` but never solves the extra turbines, this rebuilds the
        stacked axes so the whole array actually solves as 6(N+1) DOF).
        Invalidates computed state; call ``setEnv``/``calcSystemProps``
        again."""
        if self.bem is not None and design is not self.designs[0]:
            raise NotImplementedError(
                "BEM arrays require identical turbine designs"
            )
        self.designs.append(design)
        self.nT = len(self.designs)
        self.positions = np.vstack([self.positions,
                                    np.asarray(position, dtype=float)])
        self.members, self.rna = stack_fowts(self.designs)
        mo = design.get("mooring")
        ys = float(design.get("turbine", {}).get("yaw_stiffness", 0.0))
        self.moor.append(parse_mooring(mo, yaw_stiffness=ys) if mo else None)
        self.wave = None
        self.statics = None
        self.kin = None
        self.rao = None
        self._bem_staged = None
        self.results = {}
        return self

    # ---------------------------------------------------------------- env

    def setEnv(self, Hs=8.0, Tp=12.0, V=10.0, beta=0.0, Fthrust=0.0,
               current=0.0, current_heading=0.0, current_exp=0.0):
        # validate BEFORE mutating any state: a heading outside the staged
        # grid must leave the model exactly as it was (cf. Model.setEnv)
        F_beta = None
        if self._bem_headings is not None and self.bem is not None:
            from raft_tpu.model import interp_heading_excitation

            betas_g, F_all_g = self._bem_headings[0], self._bem_headings[1]
            F_beta = interp_heading_excitation(betas_g, F_all_g, float(beta))
        self.env = Env(Hs=float(Hs), Tp=float(Tp), V=float(V), beta=float(beta),
                       depth=self.depth, current=float(current),
                       current_heading=float(current_heading),
                       current_exp=float(current_exp))
        S = jonswap(self.w, Hs, Tp)
        k = wave_number(self.w, self.depth)
        self.wave = WaveState(w=self.w, k=k, zeta=jnp.sqrt(S))
        # incident-wave phase lag at each turbine's PRP
        d_along = (self.positions[:, 0] * np.cos(beta)
                   + self.positions[:, 1] * np.sin(beta))
        theta = -jnp.asarray(d_along)[:, None] * k[None, :]     # (nT, nw)
        self.phases = Cx.expi(theta)
        self.Fthrust = float(Fthrust)
        hubs = np.asarray(self.rna.hHub).reshape(self.nT)
        self.f6Ext = jnp.stack([
            jnp.array([self.Fthrust, 0, 0, 0, self.Fthrust * h, 0]) for h in hubs
        ])
        # environment changed: kinematics, excitation and the phased BEM
        # staging are stale (cf. Model.setEnv); statics are not
        self.kin = None
        self.F_morison = None
        self._bem_staged = None
        if F_beta is not None:
            # re-stage the excitation for the new heading from the grid —
            # no BEM re-solve (A, B are heading-independent)
            self.bem = (self._bem_headings[2], self._bem_headings[3], F_beta)
        return self

    # ------------------------------------------------------------- statics

    def calcBEM(self, dz_max: float = 3.0, da_max: float = 2.0, irr: bool = False,
                headings=None):
        """One native BEM solve for the shared design, staged to every
        turbine (cf. Model.calcBEM).  ``headings``: optional heading grid
        [rad] — the excitation solves for every heading in one pass
        (influence matrix factored once per frequency) and later
        ``setEnv(beta=...)`` calls re-stage by interpolation without
        re-running the solver."""
        from raft_tpu.hydro.mesh import mesh_design, mesh_lid
        from raft_tpu.hydro.jax_bem import solve_bem_any

        with phase("array-calcBEM"):
            panels = mesh_design(self.designs[0], dz_max=dz_max, da_max=da_max)
            if len(panels) == 0:
                return None
            lid = mesh_lid(self.designs[0], da_max=da_max) if irr else None
            if headings is not None:
                from raft_tpu.model import solve_bem_heading_grid

                self._bem_headings, self.bem = solve_bem_heading_grid(
                    panels, self.w, float(self.env.rho), float(self.env.g),
                    self.depth, lid, headings, float(self.env.beta),
                    mode=self.bem_mode,
                )
            else:
                self.bem = solve_bem_any(
                    panels, np.asarray(self.w),
                    rho=float(self.env.rho), g=float(self.env.g),
                    beta=float(self.env.beta), depth=self.depth, lid=lid,
                    mode=self.bem_mode,
                )
                # only after a SUCCESSFUL solve (cf. Model.calcBEM)
                self._bem_headings = None
        return self.bem

    def calcSystemProps(self):
        if self.wave is None:
            self.setEnv()
        if self.bem_mode is not None and self.bem is None:
            self.calcBEM()
        exclude = self.bem is not None
        env, wave = self.env, self.wave
        with phase("array-statics"):
            self.statics = jax.vmap(lambda m, r: assemble_statics(m, r, env))(
                self.members, self.rna
            )
        with phase("array-hydro-strip"):
            kin0 = jax.vmap(lambda m: node_kinematics(m, wave, env))(self.members)
            self.kin = jax.vmap(_phase_kin)(kin0, self.phases)
            self.A_morison = jax.vmap(
                lambda m: strip_added_mass(m, env, exclude_potmod=exclude)
            )(self.members)
            self.F_morison = jax.vmap(
                lambda m, k: strip_excitation(m, k, env, exclude_potmod=exclude)
            )(self.members, self.kin)
        if self.bem is not None:
            from raft_tpu.parallel import stage_bem

            A_b, B_b, F_cx = stage_bem(self.bem, wave)       # F zeta-scaled
            ph = self.phases                                  # (nT, nw) Cx
            F_t = Cx(
                ph.re[:, :, None] * F_cx.re[None] - ph.im[:, :, None] * F_cx.im[None],
                ph.re[:, :, None] * F_cx.im[None] + ph.im[:, :, None] * F_cx.re[None],
            )                                                 # (nT, nw, 6)
            self._bem_staged = (A_b, B_b, F_t)
        with phase("array-mooring-stiffness"):
            z6 = jnp.zeros(6)
            C0 = [
                mooring_stiffness(mo, z6) if mo is not None else jnp.zeros((6, 6))
                for mo in self.moor
            ]
            self.C_moor0 = jnp.stack(C0)
        self.C_moor = self.C_moor0
        self.results["properties"] = {
            "n turbines": self.nT,
            "nDOF": 6 * self.nT,
            "total mass": np.asarray(self.statics.mass),
            "displacement": np.asarray(self.statics.V),
            "total CG": np.asarray(self.statics.rCG),
        }
        return self

    # --------------------------------------------------------------- eigen

    def solveEigen(self, n_pass: int = 3):
        """Block-diagonal 6N eigenproblem = N independent 6x6 problems.

        With BEM staged, the potMod members' strip added mass is gated out
        of ``A_morison``, so each turbine's eigen assembly must fold in the
        staged ``A_bem`` — evaluated at each mode's own natural frequency by
        the same per-mode fixed point as ``Model.solveEigen``
        (:func:`raft_tpu.solve.eigen_with_bem`; the shared hull means one
        A(w) table serves all turbines, while M/C stay per-turbine).
        """
        if self.statics is None:
            self.calcSystemProps()
        M_tot = self.statics.M_struc + self.A_morison
        C_tot = self.statics.C_struc + self.statics.C_hydro + self.C_moor0
        with phase("array-eigen"):
            if self.bem is None:
                eig = jax.vmap(solve_eigen)(M_tot, C_tot)
                est = jax.vmap(diagonal_estimates)(M_tot, C_tot)
            else:
                from raft_tpu.solve import eigen_with_bem_batched

                A_w = np.moveaxis(np.asarray(self.bem[0]), -1, 0)  # (nw,6,6)
                # one compiled call for the whole farm (nT-batched fixed
                # point) instead of nT sequential host round-trips
                eig, est = eigen_with_bem_batched(
                    M_tot, C_tot, jnp.asarray(A_w), jnp.asarray(self.w),
                    n_pass=n_pass,
                )
        self.eigen = eig
        fns = np.asarray(eig.fns)                          # (nT, 6)
        self.results["eigen"] = {
            "frequencies": fns,
            "periods": 1.0 / np.maximum(fns, 1e-12),
            "modes": np.asarray(eig.modes),
            "estimates": np.asarray(est),
        }
        return self

    # ------------------------------------------------------------- mooring

    def calcMooringAndOffsets(self):
        if self.statics is None:
            self.calcSystemProps()
        s = self.statics
        f6Ext = self.f6Ext
        if float(jnp.abs(self.env.current)) > 0:
            from raft_tpu.hydro import current_mean_force

            # per-turbine mean current drag (stacked members -> vmap)
            f6Ext = f6Ext + jax.vmap(current_mean_force, in_axes=(0, None))(
                self.members, self.env
            )
        with phase("array-mooring-equilibrium"):
            if self._moor_batchable():
                # one compiled call solves every turbine's equilibrium:
                # stack the per-turbine MooringSystems (identical structure
                # in a farm) and vmap the Newton solve + stiffness +
                # tensions over the turbine axis
                sys_b = jax.tree.map(lambda *xs: jnp.stack(xs), *self.moor)
                F_b = s.W_struc + s.W_hydro + f6Ext
                C_b = s.C_struc + s.C_hydro
                r6s, res, Cs, Ts = _moor_solve_batch(sys_b, F_b, C_b)
                Ts = list(Ts)
            else:
                r6s, Cs, Ts, res = [], [], [], []
                for i, mo in enumerate(self.moor):
                    if mo is None:
                        r6s.append(jnp.zeros(6))
                        Cs.append(jnp.zeros((6, 6)))
                        Ts.append(jnp.zeros(0))
                        res.append(0.0)
                        continue
                    F_const = s.W_struc[i] + s.W_hydro[i] + f6Ext[i]
                    C_body = s.C_struc[i] + s.C_hydro[i]
                    r6, r = solve_equilibrium(mo, F_const, C_body)
                    r6s.append(r6)
                    Cs.append(mooring_stiffness(mo, r6))
                    Ts.append(fairlead_tensions(mo, r6))
                    res.append(float(r))
                r6s = jnp.stack(r6s)
                Cs = jnp.stack(Cs)
        self.r6_eq = r6s
        self.C_moor = Cs
        self.results["means"] = {
            "platform offset": np.asarray(self.r6_eq),        # (nT, 6)
            "equilibrium residual": np.asarray(res),
            "fairlead tensions": [np.asarray(t) for t in Ts],
        }
        return self

    def _moor_batchable(self) -> bool:
        """True when every turbine has a mooring system of one shared
        structure (same line count / treedef), so the equilibrium solve can
        batch over the turbine axis in a single compiled call."""
        if not self.moor or any(mo is None for mo in self.moor):
            return False
        t0 = jax.tree.structure(self.moor[0])
        n0 = np.shape(self.moor[0].r_anchor)
        return all(
            jax.tree.structure(mo) == t0 and np.shape(mo.r_anchor) == n0
            for mo in self.moor[1:]
        )

    # ------------------------------------------------------------ dynamics

    def solveDynamics(self, nIter: int = 40, tol: float = 0.01, method="while",
                      mesh=None, history: bool = False):
        """RAO solve for every turbine in one vmapped call.

        ``mesh``: optional 1-D ``jax.sharding.Mesh`` — the turbine axis is
        pure data parallelism, so a wind farm shards across TPU chips by
        placing each turbine's stacked inputs on its device (nT must be a
        multiple of the mesh size); XLA keeps the whole solve local per
        device with no collectives.  ``history=True`` records each
        turbine's per-iteration convergence error (cf. Model.solveDynamics)."""
        if mesh is not None:
            n_dev = int(np.prod(mesh.devices.shape))
            if self.nT % n_dev != 0:
                raise ValueError(
                    f"nT={self.nT} not a multiple of the {n_dev}-device mesh"
                )
        if self.statics is None or self.kin is None:
            self.calcSystemProps()
        if self.C_moor is None:
            self.C_moor = self.C_moor0
        env, wave = self.env, self.wave
        nw = self.w.shape[0]
        s = self.statics

        staged = self._bem_staged

        def lane(members, kin, A_mor, F_mor, M_struc, C_struc, C_hydro, C_moor,
                 F_bem):
            M = jnp.broadcast_to(M_struc + A_mor, (nw, 6, 6))
            B = jnp.zeros((nw, 6, 6), dtype=A_mor.dtype)
            F = F_mor
            if staged is not None:
                M = M + staged[0]                 # shared A_bem(w)
                B = B + staged[1]                 # shared B_bem(w)
                F = F + F_bem                     # per-turbine phased F_bem
            lin = LinearCoeffs(
                M=M, B=B, C=C_struc + C_hydro + C_moor, F=F,
            )
            return solve_dynamics(members, kin, wave, env, lin,
                                  n_iter=nIter, tol=tol, method=method,
                                  history=history)

        F_bem_t = (
            staged[2] if staged is not None
            else Cx(jnp.zeros((self.nT, nw, 6)), jnp.zeros((self.nT, nw, 6)))
        )
        with phase("array-rao-solve"):
            lane_args = (
                self.members, self.kin, self.A_morison, self.F_morison,
                s.M_struc, s.C_struc, s.C_hydro, self.C_moor, F_bem_t,
            )
            if mesh is None:
                self.rao = jax.vmap(lane)(*lane_args)
            else:
                from jax.sharding import NamedSharding, PartitionSpec as P

                sh = NamedSharding(mesh, P(mesh.axis_names[0]))
                lane_args = jax.device_put(lane_args, sh)
                self.rao = jax.jit(jax.vmap(lane), in_shardings=sh)(*lane_args)
        Xi = self.rao.Xi                                     # (nT, nw, 6)
        amp = np.asarray(Xi.abs())
        zeta = np.maximum(np.asarray(wave.zeta), 1e-12)
        dw = float(self.w[1] - self.w[0]) if nw > 1 else 1.0
        sigma = np.sqrt((amp**2).sum(axis=1) * dw)           # (nT, 6)
        Xi_c = np.asarray(Xi.to_complex())                   # (nT, nw, 6)
        self.results["response"] = {
            "w": np.asarray(self.w),
            "Xi": np.transpose(Xi_c, (1, 0, 2)).reshape(nw, 6 * self.nT),
            "Xi per turbine": Xi_c,
            "RAO magnitude": amp / zeta[None, :, None],
            "std dev": sigma,
            "converged": np.asarray(self.rao.converged),
            "iterations": np.asarray(self.rao.n_iter),
        }
        if self.rao.err_hist is not None:
            self.results["response"]["iteration error history"] = np.asarray(
                self.rao.err_hist                            # (nT, nIter)
            )
        return self

    def print_report(self):
        """Per-turbine summary report (cf. Model.print_report)."""
        print(f"=== raft_tpu array report: {self.nT} turbines, "
              f"nDOF {6 * self.nT} ===")
        p = self.results.get("properties", {})
        for t in range(self.nT):
            x, y = self.positions[t]
            print(f"  turbine {t}: position ({x:.1f}, {y:.1f}) m")
            if "total mass" in p:
                print(f"    mass {p['total mass'][t]:14.4g} kg   "
                      f"displacement {p['displacement'][t]:12.4g} m^3")
            if "eigen" in self.results:
                T = self.results["eigen"]["periods"][t]
                print("    periods [s]:", " ".join(f"{x:8.2f}" for x in T))
            if "means" in self.results:
                r6 = self.results["means"]["platform offset"][t]
                print(f"    mean offset: surge {r6[0]:.2f} m, heave {r6[2]:.2f} m, "
                      f"pitch {np.rad2deg(r6[4]):.2f} deg")
            if "response" in self.results:
                s = self.results["response"]["std dev"][t]
                print("    response std dev:", " ".join(f"{x:9.4g}" for x in s))
        print("=" * 40)

    def plot(self, ax=None, hideGrid: bool = False, n_ring: int = 24):
        """Wireframes of every turbine at its plan position."""
        import matplotlib.pyplot as plt

        from raft_tpu.model import plot_member_wireframe

        if ax is None:
            fig = plt.figure(figsize=(9, 9))
            ax = fig.add_subplot(projection="3d")
        for t in range(self.nT):
            m_t = jax.tree.map(lambda x: x[t], self.members)
            plot_member_wireframe(ax, m_t, offset=self.positions[t],
                                  n_ring=n_ring)
        if hideGrid:
            ax.set_axis_off()
        return ax

    def plot_raos(self, axes=None):
        """2x3 grid of per-DOF RAO magnitude curves, one line per turbine
        (the layout is shared with :meth:`raft_tpu.model.Model.plot_raos`
        via :func:`raft_tpu.model.plot_rao_grid`)."""
        from raft_tpu.model import plot_rao_grid

        if "response" not in self.results:
            raise RuntimeError("run solveDynamics() before plot_raos()")
        resp = self.results["response"]
        return plot_rao_grid(np.asarray(resp["w"]),
                             np.asarray(resp["RAO magnitude"]), axes=axes)

    def calcOutputs(self):
        if self.rao is None:
            raise RuntimeError("run solveDynamics first")
        w = np.asarray(self.w)
        Xi = self.results["response"]["Xi per turbine"]      # (nT, nw, 6)
        hubs = np.asarray(self.rna.hHub).reshape(self.nT)
        a_nac = -(w[None, :] ** 2) * (Xi[:, :, 0] + Xi[:, :, 4] * hubs[:, None])
        zeta = np.maximum(np.asarray(self.wave.zeta), 1e-12)
        self.results["response"]["nacelle acceleration"] = a_nac
        self.results["response"]["nacelle acceleration RAO"] = np.abs(a_nac) / zeta
        # per-turbine design-constraint margins (cf. Model.calcOutputs; the
        # reference carries these only as commented-out legacy code,
        # raft/raft.py:1655-1698)
        dw = float(w[1] - w[0]) if len(w) > 1 else 1.0
        cons = {}
        if self.r6_eq is not None and "means" in self.results:
            margins = []
            for t, mo in enumerate(self.moor):
                if mo is None:
                    margins.append(np.nan)   # no lines -> no slack constraint
                    continue
                J = np.asarray(tension_jacobian(mo, self.r6_eq[t]))  # (nl,6)
                T_amp = Xi[t] @ J.T                                  # (nw,nl)
                sig_T = np.sqrt((np.abs(T_amp) ** 2).sum(axis=0) * dw)
                T_mean = np.asarray(
                    self.results["means"]["fairlead tensions"][t])
                margins.append(float((T_mean - 3.0 * sig_T).min()))
            cons["slack line margin"] = np.asarray(margins)          # (nT,)
        sig_p = np.asarray(self.results["response"]["std dev"])[:, 4]
        static_p = (np.abs(np.asarray(self.r6_eq)[:, 4])
                    if self.r6_eq is not None else np.zeros(self.nT))
        cons["dynamic pitch"] = np.rad2deg(static_p + 3.0 * sig_p)   # (nT,)
        cons["dynamic pitch limit"] = 10.0
        self.results["constraints"] = cons
        return self.results
