"""Statics assembly: MemberSet + RNA + Env -> 6-DOF rigid-body coefficients.

Vectorized, jittable, differentiable equivalent of the reference's
``Member.getInertia`` (raft/raft.py:246-641), ``Member.getHydrostatics``
(raft/raft.py:646-796) and ``FOWT.calcStatics`` (raft/raft.py:1836-2012):
one masked computation over the stacked segment axis replaces all three
nested Python loops.  A batch of designs is the same call under ``vmap``.

Deviations from the reference (correct physics kept; see DEVIATIONS.md):
  * waterplane crossing coordinates: the reference overwrites ``xWP`` with
    the y coordinate and leaves ``yWP`` = 0 (raft/raft.py:692-693); here both
    are computed properly.
  * rectangular waterplane inertia: reference's ``IyWP`` uses ``slWP[0]**4``
    (raft/raft.py:704); here (1/12) a^3 b.
  * waterplane dims are interpolated with the station diameters in the
    correct A->B order (reference reverses them, raft/raft.py:695).
  * cap inertia is translated by the cap's own center (reference uses a
    stale variable, raft/raft.py:633).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.core.frustum import frustum_moi, frustum_vcv
from raft_tpu.core.transforms import (
    rotate_diag_tensor,
    translate_force_3to6,
    translate_matrix_6to6,
)
from raft_tpu.core.types import Env, MemberSet, RigidBodyCoeffs, RNA

Array = jnp.ndarray

_EPS = 1e-12


def _safe_div(a, b):
    return a / jnp.where(jnp.abs(b) > _EPS, b, 1.0) * (jnp.abs(b) > _EPS)


def segment_inertia(m: MemberSet):
    """Per-segment mass, center, and 6x6 inertia about the PRP.

    Shell = outer frustum - inner frustum; ballast = inner frustum filled to
    ``seg_l_fill``; caps use the same path (hole as inner dims, no fill).
    Returns (mass (S,), center (S,3), M6 (S,6,6), m_shell (S,), m_fill (S,)).
    """
    l = m.seg_l
    V_o, hc_o = frustum_vcv(m.seg_dA, m.seg_dB, l, m.seg_circ)
    V_i, hc_i = frustum_vcv(m.seg_diA, m.seg_diB, l, m.seg_circ)
    v_shell = V_o - V_i
    m_shell = v_shell * m.seg_rho_shell
    hc_shell = _safe_div(hc_o * V_o - hc_i * V_i, v_shell)

    frac = _safe_div(m.seg_l_fill, l)
    diB_fill = m.seg_diA + (m.seg_diB - m.seg_diA) * frac[..., None]
    v_fill, hc_fill = frustum_vcv(m.seg_diA, diB_fill, m.seg_l_fill, m.seg_circ)
    m_fill = v_fill * m.seg_rho_fill

    mass = m_shell + m_fill
    hc = _safe_div(hc_fill * m_fill + hc_shell * m_shell, mass)
    center = m.seg_rA + m.seg_q * hc[..., None]

    # moments of inertia about the segment's lower end node, local axes
    Ixx_o, Iyy_o, Izz_o = frustum_moi(m.seg_dA, m.seg_dB, l, m.seg_rho_shell, m.seg_circ)
    Ixx_i, Iyy_i, Izz_i = frustum_moi(m.seg_diA, m.seg_diB, l, m.seg_rho_shell, m.seg_circ)
    Ixx_f, Iyy_f, Izz_f = frustum_moi(m.seg_diA, diB_fill, m.seg_l_fill, m.seg_rho_fill, m.seg_circ)
    mh2 = mass * hc * hc  # parallel-axis shift from end node to segment CG
    Ixx = Ixx_o - Ixx_i + Ixx_f - mh2
    Iyy = Iyy_o - Iyy_i + Iyy_f - mh2
    Izz = Izz_o - Izz_i + Izz_f

    # rotate the local MOI tensor into global axes: I' = R I R^T
    I_rot = rotate_diag_tensor(m.seg_R, Ixx, Iyy, Izz)

    M6 = jnp.zeros((*mass.shape, 6, 6), dtype=mass.dtype)
    eye3 = jnp.eye(3, dtype=mass.dtype)
    M6 = M6.at[..., :3, :3].set(mass[..., None, None] * eye3)
    M6 = M6.at[..., 3:, 3:].set(I_rot)
    M6_prp = translate_matrix_6to6(center, M6)
    return mass, center, M6_prp, m_shell, m_fill


def segment_hydrostatics(m: MemberSet, env: Env):
    """Per-segment buoyancy force, hydrostatic stiffness and waterplane props.

    Masked three-way branch (crossing / submerged / dry) replacing the
    reference's if/elif (raft/raft.py:673-789).  Cap segments contribute
    nothing (the reference's hydrostatics loop only covers station spans).

    Returns dict of per-segment arrays: F6 (S,6), C6 (S,6,6), V (S,),
    r_center (S,3), AWP, IxWP, IyWP, xWP, yWP (S,).
    """
    rho, g = env.rho, env.g
    # canonicalize each segment so end A is the lower (more submerged) end;
    # the crossing-case formulas below assume the axis points upward, and
    # nothing upstream forbids listing a member deck-down.
    rA0 = m.seg_rA
    rB0 = m.seg_rA + m.seg_q * m.seg_l[..., None]
    flip = rA0[..., 2] > rB0[..., 2]
    rA_s = jnp.where(flip[..., None], rB0, rA0)
    rB_s = jnp.where(flip[..., None], rA0, rB0)
    qv = jnp.where(flip[..., None], -m.seg_q, m.seg_q)
    dA = jnp.where(flip[..., None], m.seg_dB, m.seg_dA)
    dB = jnp.where(flip[..., None], m.seg_dA, m.seg_dB)

    zA = rA_s[..., 2]
    zB = rB_s[..., 2]
    live = m.seg_mask & ~m.seg_is_cap
    # strict zA < 0 so a station exactly at the waterline assigns the plane
    # crossing to the lower segment only — summing per-segment waterplane
    # terms would otherwise double-count AWP/C33 when a design places a
    # station at z=0 (the reference overwrites member-level AWP instead of
    # summing, so it cannot hit this)
    crossing = (zA < 0.0) & (zB >= 0.0) & live
    submerged = (zA <= 0.0) & (zB <= 0.0) & ~crossing & live

    cosPhi = jnp.clip(qv[..., 2], _EPS, None)
    sinPhi = jnp.sqrt(jnp.clip(qv[..., 0] ** 2 + qv[..., 1] ** 2, 0.0, 1.0))
    tanPhi = sinPhi / cosPhi
    beta = jnp.arctan2(qv[..., 1], qv[..., 0])

    # ---- crossing-segment waterplane quantities ----
    frac = _safe_div(0.0 - zA, zB - zA)
    dWP = dA + (dB - dA) * frac[..., None]                      # dims at z=0
    xWP = rA_s[..., 0] + (rB_s[..., 0] - rA_s[..., 0]) * frac
    yWP = rA_s[..., 1] + (rB_s[..., 1] - rA_s[..., 1]) * frac
    AWP_c = jnp.where(
        m.seg_circ, 0.25 * jnp.pi * dWP[..., 0] * dWP[..., 1], dWP[..., 0] * dWP[..., 1]
    )
    IxWP_rect = dWP[..., 0] * dWP[..., 1] ** 3 / 12.0
    IyWP_rect = dWP[..., 0] ** 3 * dWP[..., 1] / 12.0
    # rotate the rectangle's local waterplane-inertia tensor into global axes
    # (cf. raft/raft.py:705-709); circular sections are isotropic, and the
    # reference's vertical-waterplane assumption (raft/raft.py:713) applies,
    # so they are left unrotated.
    I_rot = rotate_diag_tensor(m.seg_R, IxWP_rect, IyWP_rect, jnp.zeros_like(IxWP_rect))
    IWP_circ = jnp.pi / 64.0 * (dWP[..., 0] * dWP[..., 1]) ** 2
    IxWP = jnp.where(m.seg_circ, IWP_circ, I_rot[..., 0, 0])
    IyWP = jnp.where(m.seg_circ, IWP_circ, I_rot[..., 1, 1])

    LWP = jnp.abs(zA) / cosPhi
    V_c, hc_c = frustum_vcv(dA, dWP, LWP, m.seg_circ)
    r_center_c = rA_s + qv * hc_c[..., None]

    Fz_c = rho * g * V_c
    dWPm = 0.5 * (dWP[..., 0] + dWP[..., 1])
    M_incline = (
        -rho * g * jnp.pi
        * (dWPm**2 / 32.0 * (2.0 + tanPhi**2) + 0.5 * (zA / cosPhi) ** 2)
        * sinPhi
    )
    Mx_c = M_incline * (-jnp.sin(beta))
    My_c = M_incline * jnp.cos(beta)

    # ---- fully submerged ----
    V_s, hc_s = frustum_vcv(dA, dB, m.seg_l, m.seg_circ)
    r_center_s = rA_s + qv * hc_s[..., None]

    # ---- select by case ----
    V = jnp.where(crossing, V_c, jnp.where(submerged, V_s, 0.0))
    r_center = jnp.where(
        crossing[..., None], r_center_c, jnp.where(submerged[..., None], r_center_s, 0.0)
    )

    F6_c = jnp.zeros((*V.shape, 6), dtype=V.dtype)
    F6_c = F6_c.at[..., 2].set(Fz_c)
    F6_c = F6_c.at[..., 3].set(Mx_c + Fz_c * rA_s[..., 1])
    F6_c = F6_c.at[..., 4].set(My_c - Fz_c * rA_s[..., 0])
    fz_s = jnp.stack([jnp.zeros_like(V_s), jnp.zeros_like(V_s), rho * g * V_s], axis=-1)
    F6_s = translate_force_3to6(r_center_s, fz_s)
    F6 = jnp.where(crossing[..., None], F6_c, jnp.where(submerged[..., None], F6_s, 0.0))

    C6 = jnp.zeros((*V.shape, 6, 6), dtype=V.dtype)
    rgAWP = rho * g * AWP_c
    C6 = C6.at[..., 2, 2].set(rgAWP / cosPhi)
    C6 = C6.at[..., 2, 3].set(-rgAWP * yWP)
    C6 = C6.at[..., 3, 2].set(-rgAWP * yWP)
    C6 = C6.at[..., 2, 4].set(rgAWP * xWP)
    C6 = C6.at[..., 4, 2].set(rgAWP * xWP)
    C6 = C6.at[..., 3, 3].set(rho * g * (IxWP + AWP_c * yWP**2))
    C6 = C6.at[..., 4, 4].set(rho * g * (IyWP + AWP_c * xWP**2))
    C6 = C6.at[..., 3, 4].set(rgAWP * xWP * yWP)
    C6 = C6.at[..., 4, 3].set(rgAWP * xWP * yWP)
    C6 = jnp.where(crossing[..., None, None], C6, 0.0)
    # both crossing and submerged add the rho*g*V*z_CB restoring terms
    rgVz = rho * g * V * r_center[..., 2]
    C6 = C6.at[..., 3, 3].add(rgVz)
    C6 = C6.at[..., 4, 4].add(rgVz)

    return {
        "F6": F6,
        "C6": C6,
        "V": V,
        "r_center": r_center,
        "AWP": jnp.where(crossing, AWP_c, 0.0),
        "IxWP": jnp.where(crossing, IxWP, 0.0),
        "IyWP": jnp.where(crossing, IyWP, 0.0),
        "xWP": jnp.where(crossing, xWP, 0.0),
        "yWP": jnp.where(crossing, yWP, 0.0),
    }


@jax.jit
def assemble_statics(m: MemberSet, rna: RNA, env: Env) -> RigidBodyCoeffs:
    """Full statics assembly (cf. FOWT.calcStatics, raft/raft.py:1836-2012)."""
    g = env.g
    smask = m.seg_mask
    w = smask.astype(m.seg_l.dtype)

    mass, center, M6, m_shell_seg, m_fill_seg = segment_inertia(m)
    mass = mass * w
    M6 = M6 * w[..., None, None]

    W_struc = translate_force_3to6(
        center, jnp.stack([jnp.zeros_like(mass), jnp.zeros_like(mass), -g * mass], axis=-1)
    ).sum(axis=-2)
    M_struc = M6.sum(axis=-3)
    Sum_M_center = (mass[..., None] * center).sum(axis=-2)

    # tower (type<=1) vs substructure (type>1) split, raft/raft.py:1898-1912
    is_tow = (m.seg_type <= 1) & smask
    is_sub = (m.seg_type > 1) & smask
    wt = is_tow.astype(mass.dtype)
    ws = is_sub.astype(mass.dtype)
    m_tower = (mass * wt).sum(axis=-1)
    rCG_tower = ((mass * wt)[..., None] * center).sum(axis=-2) / jnp.where(m_tower > 0, m_tower, 1.0)[..., None]
    m_sub = (mass * ws).sum(axis=-1)
    rCG_sub = ((mass * ws)[..., None] * center).sum(axis=-2) / jnp.where(m_sub > 0, m_sub, 1.0)[..., None]
    m_shell = (m_shell_seg * ws).sum(axis=-1)
    m_ballast = (m_fill_seg * ws).sum(axis=-1)

    # substructure MOIs about PRP and about substructure CG (parallel axis)
    I44B = (M6[..., 3, 3] * ws).sum(axis=-1)
    I55B = (M6[..., 4, 4] * ws).sum(axis=-1)
    I66B = (M6[..., 5, 5] * ws).sum(axis=-1)
    x2 = rCG_sub[..., 1] ** 2 + rCG_sub[..., 2] ** 2
    y2 = rCG_sub[..., 0] ** 2 + rCG_sub[..., 2] ** 2
    z2 = rCG_sub[..., 0] ** 2 + rCG_sub[..., 1] ** 2
    I44 = I44B - m_sub * x2
    I55 = I55B - m_sub * y2
    I66 = I66B - m_sub * z2

    # ---- hydrostatics ----
    hs = segment_hydrostatics(m, env)
    W_hydro = (hs["F6"] * w[..., None]).sum(axis=-2)
    C_hydro = (hs["C6"] * w[..., None, None]).sum(axis=-3)
    V = (hs["V"] * w).sum(axis=-1)
    rCB = _safe_div(
        (hs["V"][..., None] * hs["r_center"]).sum(axis=-2), V[..., None]
    )
    AWP = (hs["AWP"] * w).sum(axis=-1)
    IWPx = ((hs["IxWP"] + hs["AWP"] * hs["yWP"] ** 2) * w).sum(axis=-1)
    IWPy = ((hs["IyWP"] + hs["AWP"] * hs["xWP"] ** 2) * w).sum(axis=-1)

    # ---- RNA lumped properties (raft/raft.py:1943-1949) ----
    dtype = mass.dtype
    rna_center = jnp.stack(
        [jnp.asarray(rna.xCG_RNA, dtype), jnp.zeros_like(jnp.asarray(rna.xCG_RNA, dtype)),
         jnp.asarray(rna.hHub, dtype)], axis=-1
    )
    rna_M = jnp.zeros((*jnp.shape(rna.mRNA), 6, 6), dtype=dtype)
    mR = jnp.asarray(rna.mRNA, dtype)
    rna_M = rna_M.at[..., 0, 0].set(mR).at[..., 1, 1].set(mR).at[..., 2, 2].set(mR)
    rna_M = rna_M.at[..., 3, 3].set(jnp.asarray(rna.IxRNA, dtype))
    rna_M = rna_M.at[..., 4, 4].set(jnp.asarray(rna.IrRNA, dtype))
    rna_M = rna_M.at[..., 5, 5].set(jnp.asarray(rna.IrRNA, dtype))
    W_struc = W_struc + translate_force_3to6(
        rna_center, jnp.stack([mR * 0, mR * 0, -g * mR], axis=-1)
    )
    M_struc = M_struc + translate_matrix_6to6(rna_center, rna_M)
    Sum_M_center = Sum_M_center + mR[..., None] * rna_center

    # ---- totals ----
    mTOT = M_struc[..., 0, 0]
    rCG = Sum_M_center / mTOT[..., None]
    zMeta = jnp.where(V > 0, rCB[..., 2] + _safe_div(IWPx, V), 0.0)

    C_struc = jnp.zeros_like(M_struc)
    cg_term = -mTOT * g * rCG[..., 2]
    C_struc = C_struc.at[..., 3, 3].set(cg_term)
    C_struc = C_struc.at[..., 4, 4].set(cg_term)

    return RigidBodyCoeffs(
        M_struc=M_struc,
        C_struc=C_struc,
        W_struc=W_struc,
        C_hydro=C_hydro,
        W_hydro=W_hydro,
        mass=mTOT,
        rCG=rCG,
        V=V,
        rCB=rCB,
        AWP=AWP,
        IWPx=IWPx,
        IWPy=IWPy,
        zMeta=zMeta,
        m_tower=m_tower,
        rCG_tower=rCG_tower,
        m_sub=m_sub,
        rCG_sub=rCG_sub,
        m_shell=m_shell,
        m_ballast=m_ballast,
        I44=I44,
        I55=I55,
        I66=I66,
        I44B=I44B,
        I55B=I55B,
    )
