"""Benchmark: batched design x frequency RAO solves per second per chip.

Two workloads, both on one TPU chip:

* **north star** (BASELINE.json): 1,000 VolturnUS-S draft/column-radius
  variants x 200 frequency bins through the full drag-linearized RAO fixed
  point, with the native-BEM potential-flow coefficients A(w), B(w), F(w)
  precomputed on host (coarse grid + interpolation, content-addressed cache)
  and staged as device arrays.  Per-lane convergence is checked: strict
  mode (RAFT_TPU_STRICT, default ON) fails loudly on any bad lane;
  non-strict quarantines + ladder-salvages and reports a ``resilience``
  block.  Target: < 60 s wall-clock.
* **oc3 strip**: 2,048 OC3-spar variants x 200 bins, strip theory only (the
  round-1/2 workload, kept for cross-round comparability).

The baseline is the reference-style serial NumPy path (per-node Python loop
drag linearization + per-frequency 6x6 solve, the structure of
raft/raft.py:1497-1552 and :2160-2264) measured on this host on the same
physics — the reference publishes no numbers (BASELINE.md).

Prints exactly one JSON line:
  {"metric": "design-freq RAO solves/sec/chip", "value": ..., "unit":
   "solves/s", "vs_baseline": ..., "workloads": {...}}
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Probe budget: worst case ~2 probes x 45 s + 15 s backoff before the CPU
# fallback kicks in, keeping the whole bench inside a driver wall-clock
# budget even when the device backend is wedged.
_PROBE_TIMEOUT_S = int(os.environ.get("RAFT_TPU_PROBE_TIMEOUT", "45"))
_PROBE_RETRIES = int(os.environ.get("RAFT_TPU_PROBE_RETRIES", "2"))


def _probe_backend(timeout=_PROBE_TIMEOUT_S, retries=_PROBE_RETRIES,
                   env=None):
    """Check the pinned JAX backend actually works, WITHOUT risking this
    process: backend init on a remote-tunnel plugin can block indefinitely
    when its service is wedged, so the probe runs one trivial jitted op in a
    SUBPROCESS under a hard timeout, with bounded retry + backoff — the
    shared resilience retry discipline (:mod:`raft_tpu.resilience.retry`),
    not a bespoke loop: same 15 s backoff, same error-dict shapes, plus
    stderr redaction on the diagnostic.

    Returns (platform_name, None) on success or (None, error_dict) after the
    final failure — the caller then falls back to CPU and reports the error
    in the output JSON instead of dying with a stack trace.
    """
    from raft_tpu.resilience import retry as _retry

    code = (
        "import jax, jax.numpy as jnp;"
        "jax.jit(lambda x: x * 2 + 1)(jnp.ones(8)).block_until_ready();"
        "print(jax.devices()[0].platform)"
    )
    try:
        r = _retry.retry_call(
            lambda attempt: _retry.checked_subprocess(
                [sys.executable, "-c", code], timeout_s=timeout, env=env,
                describe="backend probe", require_stdout=True),
            retries=retries, backoff_s=15.0, growth=1.0,
            retry_on=(_retry.SubprocessFailed,), describe="backend probe",
        )
        return r.stdout.strip().splitlines()[-1], None
    except _retry.RetryExhausted as e:
        last = e.last
        if getattr(last, "kind", "") == "timeout":
            probe_env = env if env is not None else os.environ
            return None, {
                "class": "BackendInitTimeout",
                "detail": f"trivial jitted op did not complete within "
                          f"{timeout}s ({e.attempts} attempt(s)); "
                          f"probe env pinned to "
                          f"{probe_env.get('JAX_PLATFORMS', '<default>')!r}",
            }
        return None, {
            "class": "BackendInitError",
            "returncode": getattr(last, "returncode", None),
            "detail": (getattr(last, "stderr_tail", "")
                       or getattr(last, "detail", "")
                       or str(last))[-500:],
        }


def _pick_chunk(batch: int, requested: int) -> int:
    """Largest divisor of ``batch`` that is <= ``requested``.

    Proper divisor scan (sqrt enumeration), not a decrement loop: the
    answer is the same, but the scan makes the degenerate case explicit —
    a prime-ish ``batch`` has no divisor near the request, and silently
    running ``chunk=1`` would serialize the whole sweep into per-design
    dispatches.  When the best divisor is below half the request a
    warning names the problem (pick a batch with friendlier factors).
    """
    requested = max(1, min(int(requested), int(batch)))
    best = 1
    for d in range(1, int(batch ** 0.5) + 1):
        if batch % d == 0:
            for c in (d, batch // d):
                if c <= requested and c > best:
                    best = c
    if best < max(1, requested // 2):
        import warnings

        warnings.warn(
            f"batch={batch} has no divisor in [{max(1, requested // 2)}, "
            f"{requested}]: chunking degenerates to chunk={best} "
            f"(worst case 1 for a prime batch). Choose a batch size with "
            f"a divisor near the requested chunk.", stacklevel=2)
    return best


def _flops_per_call(compiled):
    """XLA's own FLOP estimate for a compiled executable (None if the
    backend doesn't expose cost analysis)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):           # older jax returns [dict]
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", 0.0))
        return f if f > 0 else None
    except Exception:
        return None


def _volturn_setup(nw: int = 200, nw_bem: int = 48):
    """VolturnUS-S members/env/wave/mooring + staged BEM coefficients.

    BEM coefficients are solved on a coarse frequency grid by the native
    panel solver (cached content-addressed) and interpolated to the model
    grid — the reference's own staging pattern (its Capytaine fixture holds
    28 frequencies that get interpolated to the design grid,
    tests/test_capytaine_integration.py:36-78).  ``nw_bem=48`` is the
    measured-convergence choice: vs a 2x denser solve the staged response
    error is <1% (a 24-point grid leaves 3-5%) —
    tests/test_bem_staging.py pins this.  The staged coefficients
    are those of the nominal hull, applied across the +-10% geometry
    variants: the standard linearized-sweep approximation (re-running the
    panel solver per variant is exactly what staging exists to avoid).
    """
    import jax.numpy as jnp

    from raft_tpu.build.members import build_member_set, build_rna
    from raft_tpu.core.types import Env, WaveState
    from raft_tpu.core.waves import jonswap, wave_number
    from raft_tpu.hydro.mesh import mesh_design
    from raft_tpu.hydro.native_bem import solve_bem
    from raft_tpu.model import load_design
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.parallel import stage_bem

    here = os.path.dirname(os.path.abspath(__file__))
    design_path = os.path.join(here, "raft_tpu", "designs", "VolturnUS-S.yaml")
    design = load_design(design_path)
    members = build_member_set(design)
    rna = build_rna(design)
    depth = float(design["mooring"]["water_depth"])
    env = Env(Hs=8.0, Tp=12.0, depth=depth)
    w = np.linspace(0.05, 2.95, nw)
    wave = WaveState(
        w=jnp.asarray(w),
        k=wave_number(jnp.asarray(w), depth),
        zeta=jnp.sqrt(jonswap(jnp.asarray(w), 8.0, 12.0)),
    )
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"].get("yaw_stiffness", 0.0)
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))

    # host-side BEM precompute: coarse grid -> interpolate to the model grid
    # (tests/test_bem_staging.py pins this interpolation's response error
    # against a 2x denser coarse grid).  The whole block — meshing, panel
    # solve, interpolation — is a pure function of the design file + grids,
    # so the warm-start staging cache memoizes its (A, B, F) output on
    # disk: a repeat process skips the 3 s setup_bem_stage phase entirely.
    from raft_tpu import cache
    from raft_tpu.hydro.bem_io import interp_to_grid

    def _stage_abf():
        panels = mesh_design(design, dz_max=3.0, da_max=2.0)
        w_bem = np.linspace(w[0], w[-1], nw_bem)
        A_c, B_c, F_c = solve_bem(panels, w_bem, rho=float(env.rho),
                                  g=float(env.g), beta=0.0, depth=depth)
        return (
            interp_to_grid(w_bem, np.asarray(A_c), w),
            interp_to_grid(w_bem, np.asarray(B_c), w),
            interp_to_grid(w_bem, np.asarray(F_c), w),
        )

    if cache.is_enabled():
        A, B, F = cache.cached_arrays(
            "volturn_bem_stage",
            (cache.FileKey(design_path), w, int(nw_bem), float(env.rho),
             float(env.g), float(depth), 3.0, 2.0),
            _stage_abf,
        )
    else:
        A, B, F = _stage_abf()
    bem = stage_bem((A, B, F), wave)
    return design, members, rna, env, wave, C_moor, bem


def north_star(batch: int = 1000, nw: int = 200, reps: int = 3, setup=None,
               chunk: int = 250):
    """1k VolturnUS-S draft/column-radius variants x 200 w with BEM staged.

    The variant axes are BASELINE.json's own ("1,000 VolturnUS-S
    draft/column-radius variants"): a grid over draft stretch x plan-radius
    scale via the shape-static affine warps (parallel/geometry.py), so all
    1,000 geometries share one compiled solve.  Per-lane convergence is
    checked: strict mode (default) fails loud, non-strict quarantines
    failed lanes and salvages them through the escalation ladder
    (``resilience`` block in the output either way).
    The batch runs in ``chunk``-sized sub-batches (one
    compilation, reused) so per-step HBM stays bounded: the dominant live
    tensors are the per-lane node wave kinematics, ~6 MB x chunk for this
    hull/grid.  Chunks execute through the dispatch-ahead pipeline
    (``raft_tpu.parallel.pipeline``, depth ``RAFT_TPU_PIPELINE_DEPTH``):
    staging chunk k+1 and fetching chunk k-depth's results overlap the
    device compute of the in-flight chunks, and only per-lane response
    statistics (std dev reduced on device, the sweep's ``return_xi=False``
    semantics) cross back to host.
    """
    import jax
    import jax.numpy as jnp

    from raft_tpu.parallel import (
        forward_response, make_scale_plan, make_stretch_draft, response_std,
    )

    design, members, rna, env, wave, C_moor, bem = setup or _volturn_setup(nw=nw)
    chunk = _pick_chunk(batch, chunk)
    draft = make_stretch_draft(members)
    plan = make_scale_plan(members)

    def one(theta):
        # n_iter matches Model.solveDynamics' cap (the early-exit while
        # driver makes the headroom free; typical lanes converge in ~8-15)
        m = plan(draft(members, theta[1]), theta[0])
        out = forward_response(
            m, rna, env, wave, C_moor, bem=bem, n_iter=40, method="while",
        )
        # response std dev reduced ON DEVICE (sweep's return_xi=False
        # mode): the (chunk, nw, 6) spectra never cross to host — the
        # fetch is (chunk, 6) statistics plus the convergence flags
        return (response_std(out.Xi.abs2(), wave.w), out.converged,
                out.n_iter)

    # near-square grid over (plan radius, draft) covering +-10%
    n_d = int(np.sqrt(batch))
    while batch % n_d != 0:
        n_d -= 1
    n_p = batch // n_d

    def axis(n):     # a 1-point axis sits at the nominal design, not 0.9
        return np.linspace(0.9, 1.1, n) if n > 1 else np.array([1.0])

    dd, pp = np.meshgrid(axis(n_d), axis(n_p))
    scales = np.stack([pp.ravel(), dd.ravel()], axis=1).reshape(
        batch // chunk, chunk, 2
    )  # HOST chunk table: each chunk is staged fresh per dispatch

    from raft_tpu.utils import profiling as prof

    # AOT-compile once (all chunks share one shape) so the timed loop is
    # pure execution AND the executable exposes XLA's own FLOP estimate.
    # The compile goes through the warm-start registry: a repeat process
    # deserializes the stored executable (or at worst re-traces into the
    # persistent XLA cache) instead of paying the full compile.
    # No donate_argnums here: the only argument is the (chunk, 2) theta
    # table, and donation needs an output of identical shape/dtype to
    # alias — the north star's large tensors are closure consts (staged
    # BEM) or XLA-managed internals.  The donating path is the DLC
    # sweep's per-chunk staged excitation (sweep_sea_states(chunk=...)).
    from raft_tpu import cache
    from raft_tpu.parallel import pipeline as pipe

    args0 = (jnp.asarray(scales[0]),)
    with prof.phase("north_star/compile"):
        compiled = cache.cached_compile(
            "bench.north_star", jax.vmap(one), args0,
            consts=(members, rna, env, wave, C_moor, bem),
            # bench.py sits OUTSIDE the package code_fingerprint walk, so
            # the traced closure must salt the key itself: an edit to
            # `one` may never be served a pre-edit executable
            extra=("n_iter", 40, "method", "while",
                   *cache.callable_salt(one)),
        )
    flops_chunk = _flops_per_call(compiled)
    depth = pipe.dispatch_depth()

    def run_all(ckpt=None):
        """Dispatch-ahead chunk pipeline: chunk k+1 staged (host->device)
        and dispatched before chunk k-depth's results are fetched."""
        return pipe.run_pipelined(
            compiled, scales, depth=depth,
            stage=lambda c: (jax.device_put(jnp.asarray(c)),),
            ckpt=ckpt,
        )

    # durable chunk store (RAFT_TPU_CKPT): the VALIDATE pass checkpoints
    # each fetched chunk, so a killed bench resumes at the first missing
    # chunk.  The timed reps never touch the store — they must measure
    # device compute, not npz loads.
    from raft_tpu.resilience import checkpoint as rckpt
    from raft_tpu.resilience import health as rhealth
    from raft_tpu.resilience import ladder as rladder

    store = rckpt.store_for(
        "bench.north_star", args0,
        consts=(members, rna, env, wave, C_moor, bem),
        extra=("n_iter", 40, "method", "while", *cache.callable_salt(one)),
        n_chunks=batch // chunk)

    rung_fns = {}   # one executable per rung even with the cache off

    def solve_lane(idx, n_iter_r, relax_r, tik_r):
        """Escalation-ladder rung for one quarantined design lane: the
        same per-design program as `one` with the rung's knobs, its own
        AOT-cached single-lane executable (the healthy chunk executable
        never recompiles).  Lanes share shapes, so the rung knobs fully
        determine the program — memoized like sweep.py's lane solvers so
        a rung used twice compiles once with the warm-start cache off."""
        th = jnp.asarray(scales.reshape(-1, 2)[idx])
        fn1 = rung_fns.get((n_iter_r, relax_r, tik_r))
        if fn1 is None:
            def f(theta, _n=n_iter_r, _r=relax_r, _t=tik_r):
                m = plan(draft(members, theta[1]), theta[0])
                out = forward_response(
                    m, rna, env, wave, C_moor, bem=bem, n_iter=_n,
                    method="while", relax=_r, tik=_t,
                )
                return (response_std(out.Xi.abs2(), wave.w),
                        out.converged, out.n_iter)

            fn1 = cache.cached_callable(
                "resilience.ladder.bench", f, (th,),
                consts=(members, rna, env, wave, C_moor, bem),
                extra=("n_iter", n_iter_r, "relax", relax_r, "tik", tik_r,
                       "method", "while", *cache.callable_salt(f)),
            )
            rung_fns[(n_iter_r, relax_r, tik_r)] = fn1
        s_i, c_i, i_i = fn1(th)
        s_h = np.asarray(s_i)
        return ((s_h, np.asarray(i_i)),
                bool(np.asarray(c_i)), bool(np.isfinite(s_h).all()),
                int(np.asarray(i_i)))

    with prof.phase("north_star/warmup_validate"):
        outs, warm_stats = run_all(ckpt=store)    # warm + validate
        sig = np.concatenate([np.asarray(s) for s, _, _ in outs])
        conv = np.concatenate([np.asarray(c) for _, c, _ in outs])
        itr = np.concatenate([np.asarray(i) for _, _, i in outs])
        # structured degradation instead of batch-aborting asserts: failed
        # lanes are quarantined and (non-strict mode) re-solved through
        # the escalation ladder; RAFT_TPU_STRICT (default ON) preserves
        # the historical all-or-nothing contract, but reports the same
        # block before failing.
        strict = rhealth.strict()
        records, conv, _fin = rladder.quarantine_and_salvage(
            [sig, itr], conv, None, solve_lane, 40,
            escalate=not strict, iters=itr)
        n_conv = int(conv.sum())
        resil = rhealth.summarize(records, batch, extra={
            "strict": strict,
            "chunks_resumed": warm_stats.chunks_resumed,
            "chunks_computed": warm_stats.chunks_computed,
            "ckpt_corrupt": warm_stats.ckpt_corrupt,
            **({"checkpoint": store.to_dict()} if store is not None else {}),
        })
        if strict and (n_conv != batch or not np.isfinite(sig).all()):
            raise RuntimeError(
                f"only {n_conv}/{batch} design lanes converged finite "
                f"(strict mode; RAFT_TPU_STRICT=0 quarantines + salvages "
                f"instead): resilience={json.dumps(resil)}")
        iters = int(itr.max())
    best = np.inf
    pipe_stats = None
    with prof.phase("north_star/run"):
        for _ in range(reps):
            t0 = time.perf_counter()
            _, stats = run_all()
            dt = time.perf_counter() - t0
            if dt < best:
                best, pipe_stats = dt, stats
    from raft_tpu.core import pallas6

    out = {
        "batch": batch,
        "nw": nw,
        "chunk": chunk,
        "axes": f"plan_radius({n_p}) x draft({n_d}), +-10%",
        "wallclock_s": round(best, 4),
        "solves_per_s": round(batch * nw / best, 1),
        "converged_lanes": n_conv,
        "max_iterations": iters,
        "target_s": 60.0,
        # which solve path this artifact measured (the kernel is auto-on
        # on TPU since round 5) — cross-round comparisons need this
        "pallas_active": pallas6.enabled(),
        # provenance of this PR's hot-path changes: the fused
        # assemble+solve (never materializing Z in HBM) and the
        # dispatch-ahead chunk pipeline with device-side std-dev
        # reduction (return_xi=False semantics)
        "fused_solve": True,
        "return_xi": False,
        "pipeline": pipe_stats.to_dict() if pipe_stats is not None else None,
        # per-lane health accounting (raft_tpu.resilience): quarantined /
        # salvaged lanes, ladder rungs used, chunks resumed from the
        # checkpoint store — degradation is visible, never silent
        "resilience": resil,
    }
    if flops_chunk is not None:
        # achieved FLOP/s over the whole batch: XLA's static per-chunk
        # estimate x chunk count / best wall-clock.  The while-loop driver
        # early-exits, so the static estimate (trip count = cap) is an
        # UPPER bound on work actually done — judge MFU trends, not the
        # absolute value.
        out["xla_flops_per_chunk"] = flops_chunk
        out["achieved_gflop_s"] = round(
            flops_chunk * (batch // chunk) / best / 1e9, 1
        )
    return out


def pallas6_microbench(batch: int = 16384, reps: int = 5):
    """Pallas vs XLA on the hot op: ``batch`` independent 6x6 complex
    solves (the RAO engine's inner operation).  Only meaningful on a real
    TPU (Mosaic is TPU-only; off-TPU the kernel runs interpreted and this
    measurement is skipped by the caller).  Returns timings + speedup +
    max-abs cross-check so the kernel's keep/enable/delete decision is a
    measured one (core/pallas6.py)."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.core import linalg6, pallas6
    from raft_tpu.core.cplx import Cx

    key = jax.random.PRNGKey(0)
    kr, ki, kb1, kb2 = jax.random.split(key, 4)
    # diagonally dominant systems: well-conditioned at any batch size
    Ar = jax.random.normal(kr, (batch, 6, 6)) + 8.0 * jnp.eye(6)
    Ai = 0.3 * jax.random.normal(ki, (batch, 6, 6))
    A = Cx(Ar, Ai)
    b = Cx(jax.random.normal(kb1, (batch, 6)),
           jax.random.normal(kb2, (batch, 6)))
    x_fn = jax.jit(linalg6.solve_cx)
    p_fn = jax.jit(lambda A, b: pallas6.solve_cx_pallas(A, b, interpret=False))
    xx = x_fn(A, b)
    xp = p_fn(A, b)
    err = float(jnp.max(jnp.abs(xx.re - xp.re))
                + jnp.max(jnp.abs(xx.im - xp.im)))

    def best_of(fn):
        t_best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(A, b).re.block_until_ready()
            t_best = min(t_best, time.perf_counter() - t0)
        return t_best

    t_x, t_p = best_of(x_fn), best_of(p_fn)
    return {
        "batch": batch,
        "xla_s": round(t_x, 6),
        "pallas_s": round(t_p, 6),
        "pallas_speedup_vs_xla": round(t_x / t_p, 3),
        "max_abs_diff": err,
    }


def oc3_strip_throughput(batch: int = 2048, nw: int = 200, reps: int = 3):
    import jax
    import jax.numpy as jnp

    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.parallel import forward_response, scale_diameters

    design, members, rna, env, wave = ge._base(nw=nw)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))

    # early-exit while_loop driver: under vmap it runs until every design
    # lane converges (~10 iterations here) instead of a fixed cap
    def one(s):
        out = forward_response(
            scale_diameters(members, s), rna, env, wave, C_moor,
            n_iter=40, method="while"
        )
        return out.Xi.abs2(), out.converged

    from raft_tpu import cache
    from raft_tpu.utils import profiling as prof

    scales = jnp.linspace(0.9, 1.1, batch)
    with prof.phase("oc3_strip/compile"):
        fwd = cache.cached_callable(
            "bench.oc3_strip", jax.vmap(one), (scales,),
            consts=(members, rna, env, wave, C_moor),
            # out-of-package closure: salt the key (see bench.north_star)
            extra=("n_iter", 40, "method", "while",
                   *cache.callable_salt(one)),
        )
    out, conv = fwd(scales)
    out.block_until_ready()                       # compile + warm cache
    # structured verdict instead of a batch-aborting assert: strict mode
    # (the default) still fails loudly, but carries the lane indices.
    # escalate=False: this workload has no ladder wiring — quarantine is
    # report-only (shared record-building, no bespoke LaneHealth code)
    from raft_tpu.resilience import health as rhealth
    from raft_tpu.resilience import ladder as rladder

    records, _, _ = rladder.quarantine_and_salvage(
        [np.asarray(out)], np.asarray(conv), None, None, 0, escalate=False)
    resil = rhealth.summarize(records, batch, extra={"strict": rhealth.strict()})
    if rhealth.strict() and records:
        raise RuntimeError(
            f"{len(records)}/{batch} OC3 lanes unconverged/non-finite "
            f"(strict mode; RAFT_TPU_STRICT=0 reports instead): "
            f"resilience={json.dumps(resil)}")
    best = np.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        o, _ = fwd(scales)
        o.block_until_ready()
        best = min(best, time.perf_counter() - t0)
    from raft_tpu.core import pallas6

    return {
        "batch": batch,
        "nw": nw,
        "wallclock_s": round(best, 4),
        "solves_per_s": round(batch * nw / best, 1),
        "pallas_active": pallas6.enabled(),
        "resilience": resil,
    }


def hetero_buckets(nw: int = 64, n_iter: int = 30):
    """Shape-bucket megabatch proof (the ``buckets`` bench block): a mixed
    stream of the four shipped platform designs solves as one padded
    dispatch per shape bucket (``sweep_designs``), so the executable count
    is the BUCKET count — strictly fewer than the design count — while a
    per-design solo stream compiles once per design.  Mixed-batch results
    are checked against the solo solves (max relative std-dev error
    recorded; the padded lanes must reproduce the unpadded physics).

    Compile counts come from the AOT registry's own compile-event log
    (``raft_tpu.cache.aot.compile_count``): an executable served from any
    warm layer (memo / disk / persistent XLA cache) is NOT an event, so a
    warm process legitimately reports zero compiles for both streams.
    """
    from raft_tpu import cache
    from raft_tpu.model import stage_design_base
    from raft_tpu.parallel import forward_response, response_std, sweep_designs

    here = os.path.dirname(os.path.abspath(__file__))
    names = ["OC3spar", "VolturnUS-S", "OC4semi", "OC4semi_2"]
    fnames = [os.path.join(here, "raft_tpu", "designs", n + ".yaml")
              for n in names]
    kw = dict(nw=nw, Hs=8.0, Tp=12.0, w_min=0.05, w_max=2.95)

    # compile_count, not len(compile_events()): the event log is a
    # bounded ring, so len() deltas can undercount in a long multi-phase
    # run; the per-tag counters stay exact past the wrap
    e0 = cache.compile_count("sweep_designs")
    t0 = time.perf_counter()
    out = sweep_designs(fnames, n_iter=n_iter, return_xi=False, **kw)
    dt_mixed = time.perf_counter() - t0
    compiles = cache.compile_count("sweep_designs") - e0

    s0 = cache.compile_count("bench.hetero_solo")
    errs = []
    t0 = time.perf_counter()
    for i, fn in enumerate(fnames):
        _, m, rna, env, wv, C = stage_design_base(fn, **kw)

        def solo(m_, r_, e_, w_, c_):
            o = forward_response(m_, r_, e_, w_, c_, n_iter=n_iter)
            return response_std(o.Xi.abs2(), w_.w), o.n_iter

        fn1 = cache.cached_callable(
            "bench.hetero_solo", solo, (m, rna, env, wv, C),
            extra=("n_iter", n_iter, *cache.callable_salt(solo)))
        sig = np.asarray(fn1(m, rna, env, wv, C)[0])
        # error relative to the design's response SCALE: the unexcited
        # symmetric DOFs (sway/roll/yaw in head seas) are zero-mean f32
        # noise in both runs, so a componentwise noise/noise ratio would
        # report O(1) "error" where the physics agrees exactly
        errs.append(float(np.max(np.abs(out["std dev"][i] - sig))
                          / np.max(np.abs(sig))))
    dt_solo = time.perf_counter() - t0
    solo_compiles = cache.compile_count("bench.hetero_solo") - s0
    bk = out["buckets"]
    return {
        "designs": names,
        "n_designs": bk["n_designs"],
        "n_buckets": bk["n_buckets"],
        "signatures": bk["signatures"],
        "ladder": bk["ladder"],
        "promotions": bk["promotions"],
        "nw": nw,
        "cache_enabled": cache.is_enabled(),
        # compile-collapse claim: mixed stream pays one compile per
        # BUCKET (zero when warm); the per-design solo stream pays one
        # per DESIGN.  compile counting only sees the AOT
        # registry — with the cache disabled there is nothing to measure,
        # so the claim fields are null rather than vacuously true
        "compiles_mixed": compiles if cache.is_enabled() else None,
        "compiles_solo": solo_compiles if cache.is_enabled() else None,
        "compiles_leq_buckets": (compiles <= bk["n_buckets"]
                                 if cache.is_enabled() else None),
        "fewer_compiles_than_designs": (compiles < bk["n_designs"]
                                        if cache.is_enabled() else None),
        "max_rel_err_vs_solo": max(errs),
        "wallclock_mixed_s": round(dt_mixed, 3),
        "wallclock_solo_s": round(dt_solo, 3),
    }


def _cylinder_mesh(n_panels: int, radius: float, draft: float):
    """A closed-bottom cylinder shell with EXACTLY ``n_panels`` panels
    (``nth`` around x ``nz`` down the wall + ``nth`` bottom triangles),
    used by the panels-ladder sweep to land precisely on each ``panels``
    bucket class (``n_panels`` must be a multiple of 8)."""
    nth = 8 if n_panels <= 256 else 16
    nz = n_panels // nth - 1
    th = np.linspace(0.0, 2 * np.pi, nth + 1)
    zz = np.linspace(0.0, -draft, nz + 1)
    pans = []
    for i in range(nth):
        a, b = th[i], th[i + 1]
        for j in range(nz):
            z0, z1 = zz[j], zz[j + 1]
            pans.append([
                [radius * np.cos(a), radius * np.sin(a), z0],
                [radius * np.cos(b), radius * np.sin(b), z0],
                [radius * np.cos(b), radius * np.sin(b), z1],
                [radius * np.cos(a), radius * np.sin(a), z1]])
        pans.append([[0.0, 0.0, -draft],
                     [radius * np.cos(b), radius * np.sin(b), -draft],
                     [radius * np.cos(a), radius * np.sin(a), -draft],
                     [0.0, 0.0, -draft]])
    assert len(pans) == n_panels, (len(pans), n_panels)
    return np.asarray(pans)


def _bem_ladder(sizes, nw: int, kw: dict, budget_s: float):
    """The panels-ladder sweep of :func:`bem_block`: per bucket class,
    panel rows/s and staging seconds for native host vs jax-XLA vs
    jax-pallas.  All legs cache-cold; each jax route pays its compile on
    a first geometry, then a same-class NOVEL geometry (never seen by
    any cache) gives the warm rows/s — the per-(route, panels) roofline
    the ledger wants.  Wall-clock guarded: before each leg the cost is
    extrapolated cubically from the last completed size of the same
    route, and legs that would blow the remaining budget are recorded as
    ``skipped`` (honest truncation beats a driver timeout).
    """
    import jax

    from raft_tpu.hydro import jax_bem
    from raft_tpu.hydro.native_bem import solve_bem

    t_start = time.perf_counter()
    w = np.linspace(0.3, 1.8, nw)
    backend = jax.default_backend()
    routes = ("native", "jax_xla", "jax_pallas")
    last: dict = {}      # route -> (panels, measured leg seconds)
    entries: dict = {}

    def remaining():
        return budget_s - (time.perf_counter() - t_start)

    for n in sizes:
        ent: dict = {}
        for route in routes:
            prev = last.get(route)
            if prev is not None:
                est = prev[1] * (n / prev[0]) ** 3
                if est > remaining():
                    ent[route] = {"skipped":
                                  f"extrapolated ~{est:.0f}s > "
                                  f"{max(remaining(), 0.0):.0f}s budget left"}
                    continue
            try:
                if route == "native":
                    t0 = time.perf_counter()
                    solve_bem(_cylinder_mesh(n, 1.41, 8.3), w, **kw)
                    dt = time.perf_counter() - t0
                    ent[route] = {
                        "solve_s": round(dt, 3),
                        "rows_per_s": round(n * nw / max(dt, 1e-9), 1)}
                else:
                    asm = "xla" if route == "jax_xla" else "pallas"
                    t0 = time.perf_counter()
                    jax_bem.solve_bem_jax(
                        _cylinder_mesh(n, 1.41, 8.3), w, assembly=asm, **kw)
                    cold = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    _, _, _, diag = jax_bem.solve_bem_jax(
                        _cylinder_mesh(n, 1.37, 7.9), w, assembly=asm,
                        return_diagnostics=True, **kw)
                    dt = time.perf_counter() - t0
                    ent[route] = {
                        "staging_s": round(cold, 3),
                        "solve_s": round(dt, 3),
                        "rows_per_s": round(n * nw / max(dt, 1e-9), 1),
                        "max_residual": float(diag["max_residual"])}
            except Exception as e:                    # honest partial sweep
                ent[route] = {"error":
                              f"{type(e).__name__}: {str(e)[-200:]}"}
                continue
            last[route] = (n, max(dt, 1e-3))
        rps = {r: ent[r].get("rows_per_s") for r in routes}
        if rps["jax_pallas"]:
            ent["pallas_beats_xla"] = bool(
                rps["jax_xla"] and rps["jax_pallas"] > rps["jax_xla"])
            ent["pallas_beats_native"] = bool(
                rps["native"] and rps["jax_pallas"] > rps["native"])
        entries[str(n)] = ent
    return {
        "sizes": [int(s) for s in sizes],
        "nw": nw,
        "budget_s": budget_s,
        "backend": backend,
        # honest-label clause: off-TPU the pallas route runs the Pallas
        # INTERPRETER (numerics-exact, not performance-representative)
        "pallas_interpreted": backend != "tpu",
        "entries": entries,
    }


def bem_block(nw: int = 16, dz_max: float = 1.0, da_max: float = 0.9,
              ladder_sizes=(128, 512, 2048), ladder_budget_s: float = 600.0):
    """The ``bem`` bench block: novel-geometry BEM staging, native host
    vs on-device (``workloads.bem`` -> ``bench.bem`` in EVIDENCE.json).

    The staging-cliff claim (ROADMAP item 2): with the native C++ path
    every geometry that misses the content-addressed result cache pays a
    serial host solve; the on-device path
    (:func:`raft_tpu.hydro.jax_bem.solve_bem_jax`) compiles one
    executable PER PANEL SIZE CLASS, so a *novel* geometry on a warm
    process pays only the device solve.  Three legs, all cache-cold
    (``cache=False`` — no result-cache hits anywhere):

    * ``native_solve_s`` — the host OpenMP f64 solve on novel geometry A;
    * ``jax_cold_s`` — geometry A on device, first-ever (compile+solve);
    * ``jax_novel_s`` — geometry B (different dimensions, same ``panels``
      ladder class, never seen by any cache) on the now-warm executable:
      THE novel-geometry cost the tentpole removes.

    Parity vs the f64 oracle and the refinement residual ride along so
    the speedup is never quoted without its accuracy bill.  The
    ``ladder`` sub-block (:func:`_bem_ladder`) extends the claim
    per-size: rows/s and staging seconds for native vs jax-XLA vs
    jax-pallas at each ``panels`` bucket class.
    """
    from raft_tpu.hydro import jax_bem
    from raft_tpu.hydro.bem_smoke import novel_mesh
    from raft_tpu.hydro.native_bem import solve_bem

    mesh_a = novel_mesh(1.45, 7.3, 9.1, dz_max=dz_max, da_max=da_max)
    mesh_b = novel_mesh(1.33, 6.9, 8.7, dz_max=dz_max, da_max=da_max)
    w = np.linspace(0.3, 1.8, nw)
    kw = dict(rho=1025.0, g=9.81, beta=0.2, depth=50.0, cache=False)

    t0 = time.perf_counter()
    A_n, B_n, F_n = solve_bem(mesh_a, w, **kw)
    native_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    A_j, B_j, F_j, diag_a = jax_bem.solve_bem_jax(
        mesh_a, w, return_diagnostics=True, **kw)
    jax_cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    _, _, _, diag_b = jax_bem.solve_bem_jax(
        mesh_b, w, return_diagnostics=True, **kw)
    jax_novel_s = time.perf_counter() - t0

    err = jax_bem.parity_err
    parity = {"A": err(A_j, A_n), "B": err(B_j, B_n), "F": err(F_j, F_n)}
    padded = int(diag_b["padded"])
    return {
        "mode": jax_bem.resolved_mode(),
        "panels": {"a": int(len(mesh_a)), "b": int(len(mesh_b)),
                   "padded": padded},
        "nw": nw,
        "native_solve_s": round(native_s, 3),
        "jax_cold_s": round(jax_cold_s, 3),
        "jax_novel_s": round(jax_novel_s, 3),
        # the headline: novel-geometry staging, host path vs warm device
        "novel_speedup_vs_native": round(native_s / max(jax_novel_s, 1e-9),
                                         2),
        "novel_faster_than_native": bool(jax_novel_s < native_s),
        # padded influence-matrix rows solved per second on the warm path
        "panel_rows_per_s": round(padded * nw / max(jax_novel_s, 1e-9), 1),
        "refine_iters": int(diag_b["refine_iters"]),
        "max_residual": float(max(diag_a["max_residual"],
                                  diag_b["max_residual"])),
        "parity_vs_native": parity,
        "parity_rtol": jax_bem.PARITY_RTOL,
        "parity_ok": bool(all(v <= jax_bem.PARITY_RTOL
                              for v in parity.values())),
        "ladder": _bem_ladder(ladder_sizes, nw, kw, ladder_budget_s),
    }


def serving_block(n_requests: int = 48, rate: float = 400.0,
                  nw: int = 24, n_iter: int = 15, batch_max: int = 8,
                  deadline_ms: float = 50.0):
    """The ``serving`` bench block: the resident solver service under a
    synthetic OPEN-LOOP mixed-design load (closed-form arrival schedule,
    zero wall-clock randomness — :mod:`raft_tpu.serve.loadgen`), vs the
    sequential one-request-at-a-time baseline, plus a warm-restart leg.

    The daemon runs IN-PROCESS (server threads + a real AF_UNIX socket
    client — the same code path ``python -m raft_tpu.serve`` runs;
    process-boundary behavior incl. SIGTERM is proven separately by
    ``make serve-smoke``).  Measurement protocol: arm the executables
    (warmup), run one UNmeasured pass of the stream so the staging memo
    is warm (steady-state daemon, not cold-start amortization), reset
    the occupancy window, then measure.  Reported: p50/p99 request
    latency, solves/s for both modes and their ratio (the >= 3x
    acceptance gate), mean batch occupancy per bucket, ``compile_count``
    over the whole run (== n_buckets when the warm layers are armed),
    and the restart leg — a fresh server instance after the in-process
    executable memo is dropped, i.e. the AOT-disk path a
    killed-and-restarted daemon takes, timed to ready with its compile
    count (0 when warm).
    """
    import tempfile

    from raft_tpu import cache
    from raft_tpu.serve import loadgen
    from raft_tpu.serve.client import SolveClient
    from raft_tpu.serve.config import ServeConfig
    from raft_tpu.serve.server import SolverServer

    sock = os.path.join(tempfile.mkdtemp(prefix="raft_bench_serve_"),
                        "bench.sock")
    cfg = ServeConfig(batch_deadline_s=deadline_ms / 1e3,
                      batch_max=batch_max, nw=nw, n_iter=n_iter,
                      socket_path=sock)
    c0 = cache.compile_count("sweep_designs")
    # bounded sea-state variety: the measured pass runs on a warm staging
    # memo (6 distinct design x sea-state pairs; the warm pass below pays
    # each staging once)
    sched_kw = {"n_hs": 2, "n_tp": 1}

    def run_server(measure):
        srv = SolverServer(cfg, socket_path=sock)
        srv.start()
        try:
            t_warm0 = time.perf_counter()
            srv.warmup(loadgen.DEFAULT_DESIGNS)
            ready_s = time.perf_counter() - t_warm0
            with SolveClient(sock) as cl:
                out = measure(cl, srv)
            stats = srv.core.stats()
        finally:
            srv.stop()
        return out, stats, ready_s

    # ---- open loop (batched) + sequential baseline, one server ----
    def measure(cl, srv):
        # warm pass: staging memo + executables hot, results discarded
        loadgen.run_open_loop(cl, n_requests, rate, **sched_kw)
        srv.core.reset_stats()
        srv.reset_telemetry()        # SLO window starts at the measured pass
        open_out, _results = loadgen.run_open_loop(cl, n_requests, rate,
                                                   **sched_kw)
        # occupancy + SLO snapshots BEFORE the sequential leg: its
        # 1-lane batches would dilute the open-loop occupancy claim,
        # and its completion-driven latencies would pollute the window
        open_stats = srv.core.stats()
        open_tel = srv.telemetry()
        seq_out = loadgen.run_sequential(cl, max(6, n_requests // 4),
                                         rate, **sched_kw)
        return open_out, seq_out, open_stats, open_tel

    ((open_out, seq_out, open_stats, open_tel),
     _stats, _ready) = run_server(measure)
    stats = open_stats
    compiles = cache.compile_count("sweep_designs") - c0

    # ---- warm-restart leg: drop the in-process executable memo (what a
    # process death destroys; the AOT disk artifacts survive) and time a
    # fresh server to ready-to-serve ----
    cache.evict_memory("sweep_designs")
    c1 = cache.compile_count("sweep_designs")
    (_ign, _stats2, restart_ready_s) = run_server(lambda cl, srv: None)
    restart_compiles = cache.compile_count("sweep_designs") - c1

    n_buckets = len(stats["buckets"])
    ratio = (round(open_out["solves_per_s"] / seq_out["solves_per_s"], 2)
             if seq_out["solves_per_s"] else None)
    try:
        os.unlink(sock)
        os.rmdir(os.path.dirname(sock))
    except OSError:
        pass

    # ---- windowed SLO: the server's sliding-window quantiles
    # cross-checked against the loadgen's client-side rank quantiles
    # (the window covers the warm + measured passes of the SAME
    # schedule; the server quantile is a log-bucket upper edge, i.e. at
    # most ~26% above the true value, and the client latency includes
    # the socket round-trip on top of the server's) ----
    win = open_tel.get("latency", {})
    client_p50 = open_out.get("latency_p50_s")
    slo = {
        "window_s": open_tel.get("window_s"),
        "server_p50_s": win.get("p50"),
        "server_p99_s": win.get("p99"),
        "server_count": win.get("count"),
        "server_error_rate": win.get("error_rate"),
        "client_p50_s": client_p50,
        "client_p99_s": open_out.get("latency_p99_s"),
        "server_vs_client_p50": (
            round(win["p50"] / client_p50, 3)
            if win.get("p50") and client_p50 else None),
        # the server histogram reports a log-bucket UPPER edge (5
        # buckets/decade: at most 10^(1/5) ~ 1.585x above the true
        # value), and the true server latency is <= the client's (the
        # client adds the socket round-trip and schedule lag) — so the
        # reported server p50 can never legitimately exceed the
        # client's by more than one bucket of quantization
        "consistent_with_client": (
            bool(win.get("p50", 0) > 0 and client_p50
                 and win["p50"] <= client_p50 * 1.585 + 0.05)),
        "error_budget": open_tel.get("error_budget"),
    }

    # ---- measured-performance ledger: achieved FLOP/s + roofline
    # fraction per warm bucket, persisted next to the AOT cache (null
    # when the warm-start cache is off — no artifact identity to key
    # by, hetero_buckets precedent) ----
    from raft_tpu import obs as _obs

    ledger_block = None
    if cache.is_enabled():
        # best-effort like every other telemetry call site: a malformed
        # RAFT_TPU_ROOFLINE (flush raises at peak-model time) must
        # degrade this block to an error note, never discard the whole
        # bench's already-computed workload results
        try:
            _obs.ledger.flush()
            ledger_block = [{
                "bucket": e.get("bucket"),
                "count": e.get("count"),
                "best_s": e.get("best_s"),
                "achieved_flops_per_s": e.get("achieved_flops_per_s"),
                "achieved_bytes_per_s": e.get("achieved_bytes_per_s"),
                "roofline_fraction": e.get("roofline_fraction"),
                "peak_source": (e.get("peak") or {}).get("source"),
            } for e in _obs.ledger.entries()
                if e.get("entry") == "sweep_designs"]
        except Exception as e:
            ledger_block = {"error": f"{type(e).__name__}: {str(e)[-200:]}"}
    return {
        "nw": nw,
        "n_iter": n_iter,
        "batch_max": batch_max,
        "batch_deadline_ms": deadline_ms,
        "designs": list(loadgen.DEFAULT_DESIGNS),
        "open_loop": open_out,
        "sequential": seq_out,
        "batched_vs_sequential": ratio,
        "n_buckets": n_buckets,
        "occupancy": {k: v["mean_occupancy"]
                      for k, v in stats["buckets"].items()},
        "cache_enabled": cache.is_enabled(),
        # one executable per bucket across the WHOLE serving run (the
        # warm-start registry is what makes the claim measurable; null
        # when it is off, hetero_buckets precedent)
        "compiles": compiles if cache.is_enabled() else None,
        "compiles_eq_buckets": (compiles == n_buckets
                                if cache.is_enabled() else None),
        "warm_restart": {
            "mode": "in-process memo evicted; AOT disk path "
                    "(cross-process SIGTERM proof: make serve-smoke)",
            "ready_s": round(restart_ready_s, 3),
            "compiles": (restart_compiles if cache.is_enabled() else None),
        },
        "slo": slo,
        "ledger": ledger_block,
    }


#: the fleet bench stream: FOUR labels so bucket-affinity routing splits
#: evenly over 2 and 4 replicas (with 3 labels the ideal 2-replica split
#: is 2:1 and the scaling ceiling 1.5x — a routing artifact, not a
#: serving one)
FLEET_DESIGNS = ("oc3", "oc4", "oc4_2", "volturnus")


def serving_fleet_block(n_requests: int = 36, rate: float = 400.0,
                        replica_counts=(1, 2, 4), n_step: int = 24,
                        nw: int = 64, n_iter: int = 25, batch_max: int = 4,
                        deadline_ms: float = 40.0):
    """The ``serving_fleet`` bench block: replica scaling through the
    fault-tolerant fleet (:mod:`raft_tpu.serve.fleet`) — REAL daemon
    children (one process per replica, CPU-pinned: a device fleet needs
    one chip per replica) behind the in-process failover router.

    Legs, all on ONE shared AOT cache root (only the first fleet pays
    compiles; every later replica arms warm):

    * **scaling**: the same open-loop 4-design stream at 1, 2, and 4
      replicas; ``solves/s`` per count and the 2x/4x ratios (the
      ``>= 1.7x at 2 replicas`` acceptance gate — four labels split 2:2
      under bucket-affinity routing, so near-linear is achievable).
      Each child is pinned to ONE intra-op XLA thread so a replica
      models one device, not the whole host (unpinned, a single XLA CPU
      process saturates every core and replica scaling is flat by
      construction).  On a host with fewer than 2 cores the ratios are
      still reported but the gate is ``null`` — N processes multiplexing
      one core cannot scale, and pretending otherwise would be a
      measurement of the scheduler, not the fleet;
    * **load step** (at 2 replicas): p99 at half the measured capacity
      vs at 3x capacity — the queueing-delay cliff, measured;
    * **kill leg** (at 2 replicas): the counted ``kill_replica:1`` fault
      SIGKILLs a replica on the first dispatch of a measured pass; every
      request still answers exactly once (failover resubmission) and the
      leg's p99 prices the disruption against the steady-state p99.
    """
    import shutil
    import tempfile

    from raft_tpu.resilience import faults
    from raft_tpu.serve import loadgen
    from raft_tpu.serve.client import SolveClient
    from raft_tpu.serve.fleet import Fleet, FleetConfig
    from raft_tpu.serve.fleet_smoke import (_fleet_env,
                                            _replica_solver_stats)

    tmp = tempfile.mkdtemp(prefix="raft_bench_fleet_")
    cache_dir = os.path.join(tmp, "cache")
    serve_args = ["--nw", str(nw), "--n-iter", str(n_iter),
                  "--batch-max", str(batch_max),
                  "--deadline-ms", str(deadline_ms),
                  "--warm", ",".join(FLEET_DESIGNS)]
    env = _fleet_env(cache_dir)
    # one intra-op XLA thread per replica child: a replica models one
    # device; unpinned, one XLA CPU process grabs every host core and
    # 2-replica scaling is flat no matter how good the router is
    env["XLA_FLAGS"] = ("--xla_cpu_multi_thread_eigen=false "
                        "intra_op_parallelism_threads=1")
    cores = os.cpu_count() or 1
    # bounded sea-state variety (8 distinct design x sea-state pairs):
    # the warm pass below pays each staging once per owning replica
    sched_kw = {"designs": FLEET_DESIGNS, "n_hs": 2, "n_tp": 1}

    def run_fleet(tag, n_replicas, measure):
        # queue_max sized so the full open-loop burst (n_requests in
        # flight at once at the default rate) is ADMITTED even on one
        # replica — this block measures throughput, not shedding (the
        # shed path is proven by fleet-smoke / phase C)
        cfg = FleetConfig.from_env(
            replicas=n_replicas, queue_max=max(64, 2 * n_requests),
            socket_path=os.path.join(tmp, f"fleet_{tag}.sock"))
        run_dir = os.path.join(tmp, f"run_{tag}")
        os.makedirs(run_dir, exist_ok=True)
        fleet = Fleet(cfg, serve_args=serve_args, child_env=env,
                      run_dir=run_dir)
        ready = fleet.start()
        try:
            with SolveClient(fleet.router.socket_path,
                             connect_timeout=30.0) as cl:
                # warm pass: per-replica staging memos hot under the SAME
                # affinity pins the measured pass will see
                loadgen.run_open_loop(cl, n_requests, rate, **sched_kw)
                fleet.router.reset_telemetry()
                out = measure(cl, fleet)
            solver = _replica_solver_stats(fleet)
        finally:
            fleet.stop()
        return ready, out, solver

    def counters(fleet):
        return dict(fleet.router.telemetry()["counters"])

    def leg_summary(open_out, delta):
        return {
            "solves_per_s": open_out["solves_per_s"],
            "latency_p50_s": open_out["latency_p50_s"],
            "latency_p99_s": open_out["latency_p99_s"],
            "wall_s": open_out["wall_s"],
            "relayed": delta["relayed"],
            "failover": delta["failover"],
            "shed": delta["shed"],
        }

    legs: dict = {}
    warm_ready: dict = {}
    cold = None
    step = kill = None
    for n_rep in replica_counts:
        if n_rep == 2:
            def measure(cl, fleet):
                c0 = counters(fleet)
                base = loadgen.run_open_loop(cl, n_requests, rate,
                                             **sched_kw)[0]
                d_base = _dict_delta(counters(fleet), c0)
                # ---- load step: below capacity, then 3x capacity ----
                cap = base["solves_per_s"] or 1.0
                lo = loadgen.run_open_loop(cl, n_step,
                                           max(1.0, 0.5 * cap),
                                           **sched_kw)[0]
                hi = loadgen.run_open_loop(cl, n_step, 3.0 * cap,
                                           **sched_kw)[0]
                # ---- kill leg: counted fault on the first dispatch ----
                c1 = counters(fleet)
                faults.reset_counts()
                os.environ["RAFT_TPU_FAULT_INJECT"] = "kill_replica:1"
                try:
                    kl = loadgen.run_open_loop(cl, n_requests, rate,
                                               **sched_kw)[0]
                finally:
                    os.environ.pop("RAFT_TPU_FAULT_INJECT", None)
                    faults.reset_counts()
                d_kill = _dict_delta(counters(fleet), c1)
                return base, d_base, lo, hi, kl, d_kill

            ready, (base, d_base, lo, hi, kl, d_kill), solver = run_fleet(
                "r2", 2, measure)
            legs["2"] = leg_summary(base, d_base)
            step = {
                "n_requests": n_step,
                "rate_lo_req_per_s": lo["rate_req_per_s"],
                "rate_hi_req_per_s": hi["rate_req_per_s"],
                "p99_lo_s": lo["latency_p99_s"],
                "p99_hi_s": hi["latency_p99_s"],
                "p99_ratio": (round(hi["latency_p99_s"]
                                    / lo["latency_p99_s"], 2)
                              if lo["latency_p99_s"] else None),
            }
            kill = {
                **leg_summary(kl, d_kill),
                "all_answered_exactly_once": (
                    d_kill["relayed"] == n_requests),
                "restarts": d_kill["restart"],
                "p99_vs_steady": (round(kl["latency_p99_s"]
                                        / base["latency_p99_s"], 2)
                                  if base["latency_p99_s"] else None),
            }
        else:
            def measure(cl, fleet):
                c0 = counters(fleet)
                out = loadgen.run_open_loop(cl, n_requests, rate,
                                            **sched_kw)[0]
                return out, _dict_delta(counters(fleet), c0)

            ready, (open_out, delta), solver = run_fleet(
                f"r{n_rep}", n_rep, measure)
            legs[str(n_rep)] = leg_summary(open_out, delta)
        if cold is None:
            # the FIRST fleet is the cold one: its replica pays the
            # bucket compiles the shared root then amortizes
            cold = {"compiles": solver[0]["compiles"],
                    "n_buckets": len(solver[0]["buckets"])}
        else:
            warm_ready[str(n_rep)] = [
                r.get("compiles_at_ready")
                for r in ready["replicas"].values()]
    shutil.rmtree(tmp, ignore_errors=True)

    sps = {k: v["solves_per_s"] for k, v in legs.items()}
    s1 = sps.get("1")

    def scaling(k):
        return (round(sps[k] / s1, 2)
                if s1 and sps.get(k) else None)

    return {
        "mode": "real daemon children, one CPU process per replica "
                "(a device fleet needs one chip per replica), behind "
                "the in-process failover router",
        "nw": nw, "n_iter": n_iter, "batch_max": batch_max,
        "batch_deadline_ms": deadline_ms,
        "designs": list(FLEET_DESIGNS),
        "n_requests": n_requests,
        "rate_req_per_s": rate,
        "replicas": legs,
        "cores": cores,
        "scaling_2x": scaling("2"),
        "scaling_4x": scaling("4"),
        # the acceptance gate: 2 replicas >= 1.7x one replica's
        # solves/s — assessable only where 2 replicas can actually run
        # in parallel (null on a < 2-core host, note below)
        "near_linear_2x": (
            None if cores < 2 or scaling("2") is None
            else bool(scaling("2") >= 1.7)),
        **({"note": f"{cores}-core host: replica processes multiplex "
                    "one core, so the scaling ratios measure the OS "
                    "scheduler, not the fleet; the near-linear gate "
                    "needs >= 2 cores"} if cores < 2 else {}),
        "cold": cold,
        # every fleet after the first arms entirely warm off the shared
        # AOT root: zero compiles at ready, per replica
        "warm_fleets_zero_compiles": all(
            all(c == 0 for c in v) for v in warm_ready.values()),
        "warm_compiles_at_ready": warm_ready,
        "load_step": step,
        "kill_leg": kill,
    }


def _dict_delta(after: dict, before: dict) -> dict:
    return {k: after[k] - before.get(k, 0) for k in after}


def _serial_rao(members, rna, wave, env, C_moor, bem=None, nw=200, n_iter=40, tol=0.01):
    """Reference-style serial path: per-node Python-loop drag linearization +
    per-frequency 6x6 solve, same convergence rule (raft/raft.py:1542-1547).
    ``bem``: optional staged (A[nw,6,6], B[nw,6,6], F Cx[nw,6]) device arrays
    folded in exactly as the device path does.
    """
    import jax.numpy as jnp  # noqa: F401

    from raft_tpu.hydro import node_kinematics, strip_added_mass, strip_excitation
    from raft_tpu.statics import assemble_statics

    exclude = bem is not None
    stat = assemble_statics(members, rna, env)
    kin = node_kinematics(members, wave, env)
    A = np.asarray(strip_added_mass(members, env, exclude_potmod=exclude))
    F0 = np.asarray(strip_excitation(members, kin, env, exclude_potmod=exclude).to_complex())
    M = np.asarray(stat.M_struc) + A
    C = np.asarray(stat.C_struc) + np.asarray(stat.C_hydro) + np.asarray(C_moor)
    M_w = np.broadcast_to(M, (nw, 6, 6)).copy()
    B_w = np.zeros((nw, 6, 6))
    if bem is not None:
        A_b, B_b, F_b = bem
        M_w += np.asarray(A_b)
        B_w += np.asarray(B_b)
        F0 = F0 + np.asarray(F_b.to_complex())

    w = np.asarray(wave.w)
    u = np.asarray(kin.u.to_complex())            # (N,nw,3)
    mask = np.asarray((members.node_r[:, 2] < 0) & members.node_mask)
    r = np.asarray(members.node_r)
    q, p1, p2 = (np.asarray(x) for x in (members.node_q, members.node_p1, members.node_p2))
    ds, drs, dls = (np.asarray(x) for x in (members.node_ds, members.node_drs, members.node_dls))
    circ = np.asarray(members.node_circ)
    Cd = {k: np.asarray(getattr(members, f"node_Cd_{k}")) for k in ("q", "p1", "p2", "end")}
    rho = float(env.rho)
    c_sqrt = np.sqrt(8.0 / np.pi)

    def get_h(rv):
        return np.array([[0, -rv[2], rv[1]], [rv[2], 0, -rv[0]], [-rv[1], rv[0], 0]])

    Xi = np.full((nw, 6), 0.1 + 0j)
    t0 = time.perf_counter()
    for _ in range(n_iter):
        B6 = np.zeros((6, 6))
        Fd = np.zeros((nw, 6), dtype=complex)
        for i in range(len(dls)):                 # serial per-node loop
            if not mask[i]:
                continue
            H = get_h(r[i])
            vnode = 1j * w[:, None] * (Xi[:, :3] + np.cross(Xi[:, 3:], r[i]))
            vrel = u[i] - vnode
            a_end = abs(
                np.pi * ds[i, 0] * drs[i, 0]
                if circ[i]
                else (ds[i, 0] + drs[i, 0]) * (ds[i, 1] + drs[i, 1])
                - (ds[i, 0] - drs[i, 0]) * (ds[i, 1] - drs[i, 1])
            )
            Bmat = np.zeros((3, 3))
            for unit, ck, area in (
                (q[i], "q", (np.pi * ds[i, 0] if circ[i] else 2 * (ds[i].sum())) * dls[i]),
                (q[i], "end", a_end),
                (p1[i], "p1", ds[i, 0] * dls[i]),
                (p2[i], "p2", (ds[i, 0] if circ[i] else ds[i, 1]) * dls[i]),
            ):
                vrms = np.sqrt(np.sum(np.abs(vrel * unit) ** 2))
                Bmat += (
                    c_sqrt * vrms * 0.5 * rho * area * Cd[ck][i] * np.outer(unit, unit)
                )
            B6[:3, :3] += Bmat
            B6[:3, 3:] += Bmat @ H.T
            B6[3:, :3] += H @ Bmat
            B6[3:, 3:] += H @ Bmat @ H.T
            f3 = vrel @ Bmat.T
            Fd[:, :3] += f3
            Fd[:, 3:] += (H @ f3.T).T
        Xi_new = np.zeros_like(Xi)
        for ii in range(nw):                      # serial per-frequency solve
            Z = -(w[ii] ** 2) * M_w[ii] + 1j * w[ii] * (B6 + B_w[ii]) + C
            Xi_new[ii] = np.linalg.solve(Z, F0[ii] + Fd[ii])
        if np.max(np.abs(Xi_new - Xi) / (np.abs(Xi_new) + tol)) < tol:
            Xi = Xi_new
            break
        Xi = 0.2 * Xi + 0.8 * Xi_new
    elapsed = time.perf_counter() - t0
    return nw / elapsed                           # design-freq solves/sec


def serial_baseline_volturn(nw: int = 200, setup=None):
    design, members, rna, env, wave, C_moor, bem = setup or _volturn_setup(nw=nw)
    return _serial_rao(members, rna, wave, env, C_moor, bem=bem, nw=nw)


def serial_baseline_oc3(nw: int = 200):
    import jax.numpy as jnp

    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring

    design, members, rna, env, wave = ge._base(nw=nw)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))
    return _serial_rao(members, rna, wave, env, C_moor, nw=nw)


def _stderr_tail(stderr, n: int = 300) -> str:
    """Last ~n chars of a child's stderr for an error dict, with
    credential-looking tokens masked (these diagnostics land verbatim in
    committed bench artifacts).  The redaction rule lives in
    :func:`raft_tpu.resilience.retry.redacted_tail` — ONE rule shared by
    the bench, the native-build failures, and the retry wrappers, so the
    masking patterns cannot drift between artifacts."""
    from raft_tpu.resilience.retry import redacted_tail

    return redacted_tail(stderr, n)


def _device_child_timeout(budget_s: float, elapsed_s: float,
                          reserve_s: float = 240.0,
                          floor_s: float = 60.0):
    """How long the device-bench child may run inside the driver budget:
    ``budget - elapsed - reserve`` (the reserve keeps room for the
    in-process CPU rescue), or ``None`` when that leaves less than the
    ``floor_s`` a device bench minimally needs — the caller then SKIPS
    the child entirely instead of granting a floor that would overshoot
    the wall clock (the pre-round-5 ``max(60, remaining)`` bug)."""
    t = budget_s - elapsed_s - reserve_s
    return None if t < floor_s else t


def _spawn_full_bench(env, timeout_s: float):
    """Run the FULL bench in a fresh child (``ASSUME_DEVICE=1``: no
    re-probing) and parse its one stdout JSON line.  The ONE
    spawn-and-parse convention shared by the parent's bounded device run
    and the end-of-window wedge-clear retry, including the guard that a
    child which silently fell back to CPU (plugin registration failure
    after a good probe) is a FAILURE, not a device number.

    A child that dies without a parseable JSON line (OOM kill,
    interpreter crash) surfaces a redacted tail of its stderr in the
    error dict — the actual diagnostic, not just a JSONDecodeError.

    Returns (parsed dict, None) for a genuine device measurement, else
    (None, error dict)."""
    env = dict(env)
    env["RAFT_TPU_BENCH_ASSUME_DEVICE"] = "1"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired as e:
        err = {"class": "DeviceBenchTimeout",
               "detail": f"device bench did not finish in "
                         f"{timeout_s:.0f}s"}
        tail = _stderr_tail(getattr(e, "stderr", None))
        if tail:
            err["stderr_tail"] = tail
        return None, err
    except Exception as e:
        return None, {"class": type(e).__name__, "detail": str(e)[-300:]}
    line = (r.stdout.strip().splitlines() or [""])[-1]
    try:
        out = json.loads(line)
    except json.JSONDecodeError:
        out = None
    if not isinstance(out, dict):
        # no JSON at all, or a stray non-dict line ('null', a number, a
        # progress list): either way there is no child result — surface
        # the diagnostics instead of raising out of the rescue path
        err = {"class": "DeviceBenchFailed",
               "detail": f"child stdout had no JSON result line "
                         f"(rc={r.returncode}): {line[:200]!r}"}
        tail = _stderr_tail(r.stderr)
        if tail:
            err["stderr_tail"] = tail
        return None, err
    if out.get("value") and out.get("platform") not in (None, "cpu"):
        return out, None
    err = {"class": "DeviceBenchFailed",
           "detail": out.get("error") or line[:500]}
    tail = _stderr_tail(r.stderr)
    if tail:
        err["stderr_tail"] = tail
    return None, err


def _retry_device_bench(budget_s: float):
    """One last chance at a real device number after a CPU fallback: the
    wedge can clear mid-window, so re-probe the pinned backend and, if it
    answers, run the FULL bench in a fresh subprocess (this process is
    already pinned to CPU) under whatever wall-clock budget remains.

    Returns the subprocess's parsed JSON dict on success, else an error
    dict explaining why the retry did not produce a device number.
    """
    if budget_s < 120:
        return None, {"class": "RetrySkipped",
                      "detail": f"only {budget_s:.0f}s of bench budget left"}
    t0 = time.perf_counter()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)          # undo this process's CPU pin
    platform, probe_err = _probe_backend(retries=1, env=env)
    if platform in (None, "cpu"):           # cpu = the pin, not the device
        return None, {"class": "RetryProbeFailed", **(probe_err or {})}
    # the probe spent part of the remaining budget; the subprocess gets
    # what is left so the whole bench stays inside the driver wall-clock
    sub_timeout = budget_s - (time.perf_counter() - t0)
    if sub_timeout < 60:
        return None, {"class": "RetrySkipped",
                      "detail": f"probe left only {sub_timeout:.0f}s"}
    out, err = _spawn_full_bench(env, sub_timeout)
    if out is not None:
        return out, None
    return None, {"class": "RetryBenchFailed", "device_error": err}


def main():
    """Probe the backend, run the workloads, print exactly ONE JSON line.

    Wedge-resilient by construction: the pinned device backend is probed in
    a subprocess under a timeout (bounded retry + backoff), a dead backend
    falls back to a reduced CPU workload (clearly labeled, with the probe
    error embedded), and any later failure still emits a parseable
    diagnostic JSON line instead of a stack trace — a wedged TPU costs the
    round a TPU number, not the whole artifact.  Because a wedge can also
    CLEAR mid-window, a fallback run re-probes the device after the CPU
    workloads finish and promotes a successful full device bench (in a
    fresh subprocess) to the primary result.  And because a device that
    answered the probe can still die MID-BENCH (its client retries
    UNAVAILABLE internally, unbounded and un-interruptible in-process),
    the device bench itself runs in a child under a parent wall-clock —
    on child timeout/failure the parent, whose jax is still
    uninitialized, measures the labeled CPU fallback in-process.
    """
    t_start = time.perf_counter()
    budget_s = float(os.environ.get("RAFT_TPU_BENCH_BUDGET", "1200"))
    metric = "design-freq RAO solves/sec/chip (1k VolturnUS-S x 200w, BEM staged)"
    assume_device = bool(os.environ.get("RAFT_TPU_BENCH_ASSUME_DEVICE"))
    device_died = None
    if assume_device:
        # child subprocess: the parent probed (or re-probed) the backend a
        # moment ago — run the full device bench directly, no probing
        platform, probe_err = "device", None
        fallback = False
    else:
        platform, probe_err = _probe_backend()
        fallback = platform is None
    if not fallback and not assume_device:
        # The device answered the probe, but it can still hang or die
        # MID-BENCH (e.g. the tunnel drops): its client retries
        # UNAVAILABLE internally for tens of minutes, unbounded and
        # un-interruptible in-process.  So the device bench runs in a
        # CHILD under a parent wall-clock, and this parent keeps its own
        # jax uninitialized (the probe is also a subprocess) — on child
        # timeout/failure it falls back to the labeled in-process CPU
        # path below, so the artifact is a measurement, not a null.
        reserve = 240.0                      # time kept for the CPU rescue
        sub_timeout = _device_child_timeout(
            budget_s, time.perf_counter() - t_start, reserve)
        if sub_timeout is None:
            # a 60 s floor here could overshoot a small driver budget:
            # when less than the floor remains after the CPU-rescue
            # reserve, skip the device child entirely and go straight to
            # the in-process CPU fallback
            out, device_died = None, {
                "class": "DeviceBenchSkipped",
                "detail": f"budget {budget_s:.0f}s leaves less than the "
                          f"60s floor for the device child after the "
                          f"{reserve:.0f}s CPU-rescue reserve",
            }
        else:
            out, device_died = _spawn_full_bench(os.environ, sub_timeout)
        if out is not None:
            print(json.dumps(out))
            return
        # fall through to the CPU fallback, carrying the device error
        fallback = True
        platform = None
        probe_err = {"class": "DeviceDiedMidBench",
                     "device_error": device_died}
    if fallback:
        # the pinned backend is unreachable: measure on CPU with reduced
        # batches so the artifact stays inside the driver's time budget.
        # BOTH the env var and the config knob are needed: this host's
        # sitecustomize registers the device plugin and pins the platform
        # via jax.config, which takes precedence over the env var — with
        # only the env var set, the first device op would still dial the
        # wedged plugin backend and hang.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    # warm-start subsystem: persistent XLA compile cache + AOT executable
    # registry + BEM staging cache.  Armed AFTER the platform decision (the
    # registry keys by backend) and before any workload; RAFT_TPU_CACHE_DIR
    # governs (``off`` disables, keeping the run bit-identical to an
    # uncached build).  Cache wall-clock shows up as cache/* phases and
    # hit/miss counts in the warm_start block below.
    from raft_tpu import cache as _warm

    _warm.enable()
    ns_kw = {} if not fallback else {"batch": 100, "chunk": 50, "reps": 1}
    oc3_kw = {} if not fallback else {"batch": 128, "reps": 1}
    try:
        from raft_tpu import obs as _obs
        from raft_tpu.utils import profiling as prof

        with prof.phase("setup_bem_stage"):
            setup = _volturn_setup()           # shared host-side precompute
        ns = north_star(setup=setup, **ns_kw)
        oc3 = oc3_strip_throughput(**oc3_kw)
        with prof.phase("hetero_buckets"):
            # mixed-design shape-bucket proof; small nw — the claim is
            # about compile counts and padded-lane parity, not throughput
            hb = hetero_buckets(**({} if not fallback else {"nw": 32}))
        with prof.phase("serving"):
            # resident-service block: open-loop mixed stream vs the
            # sequential baseline through the real daemon loop + socket
            sv = serving_block(**({} if not fallback else
                                  {"n_requests": 24, "nw": 16,
                                   "n_iter": 10}))
        with prof.phase("serving_fleet"):
            # replica-scaling block: real daemon children behind the
            # failover router (CPU processes either way — a device
            # fleet needs one chip per replica); a fleet failure
            # degrades to a note, never kills the run
            try:
                sf = serving_fleet_block(**({} if not fallback else
                                            {"n_requests": 24,
                                             "n_step": 16}))
            except Exception as e:
                sf = {"error": f"{type(e).__name__}: {str(e)[-300:]}"}
        with prof.phase("bem_block"):
            # novel-geometry BEM staging: native host vs on-device (the
            # jax_bem staging-cliff claim; reduced mesh on CPU fallback)
            try:
                # CPU fallback: reduced mesh, small w grid, and a ladder
                # truncated to the classes the interpreter can afford
                bem = bem_block(**({} if not fallback else
                                   {"nw": 6, "dz_max": 1.6,
                                    "da_max": 1.3,
                                    "ladder_sizes": (64, 128),
                                    "ladder_budget_s": 240.0}))
            except Exception as e:
                bem = {"error": f"{type(e).__name__}: {str(e)[-300:]}"}
        pallas = None
        if not fallback and platform not in (None, "cpu"):
            # measure the hand-written kernel on the hardware it exists
            # for (a plain-CPU host has no Mosaic — skip, as documented);
            # a Mosaic failure degrades to a note, never kills the run
            try:
                with prof.phase("pallas6_microbench"):
                    pallas = pallas6_microbench()
            except Exception as e:
                pallas = {"error": f"{type(e).__name__}: {str(e)[-300:]}"}
        with prof.phase("serial_baselines"):
            base_v = serial_baseline_volturn(setup=setup)
            base_o = serial_baseline_oc3()
        if platform == "device":             # resolve the real plugin name
            import jax

            platform = jax.devices()[0].platform
        value = ns["solves_per_s"]
        out = {
            "metric": metric,
            "value": value,
            "unit": "solves/s",
            "vs_baseline": round(value / base_v, 1),
            "platform": platform,
            "workloads": {
                "north_star_volturn_bem": ns,
                "oc3_strip": {
                    **oc3,
                    "vs_baseline": round(oc3["solves_per_s"] / base_o, 1),
                },
                "hetero_buckets": hb,
                "serving": sv,
                "serving_fleet": sf,
                "bem": bem,
                **({"pallas6_microbench": pallas} if pallas else {}),
            },
            "serial_baseline_solves_per_s": {
                "volturn_bem": round(base_v, 1),
                "oc3_strip": round(base_o, 1),
            },
            # unified observability block (raft_tpu.obs): the span
            # roll-up supersedes the bespoke phases_s dict (same nested
            # names, now with call counts), plus the full metric
            # snapshot (latency histogram quantiles included) and the
            # exact per-tag compile counts
            "obs": _obs.obs_block(),
            # cold/warm split: cache hit/miss counts + saved seconds per
            # layer — a warm process shows aot disk_hits / staging hits
            # with north_star/compile + setup_bem_stage collapsed
            "warm_start": _warm.report(),
        }
        if fallback:
            out["note"] = (
                "device backend unavailable -> CPU fallback with reduced "
                "batches; value is NOT a TPU number"
            )
            out["backend_probe_error"] = probe_err
            # the wedge may have cleared while the CPU workloads ran:
            # re-probe, and promote a successful full device bench (but
            # not after a mid-bench death — that device is flapping, not
            # wedged-at-start, and re-dialing it would just flap again)
            remaining = (-1.0 if device_died is not None else
                         budget_s - (time.perf_counter() - t_start) - 30)
            dev_out, retry_err = _retry_device_bench(remaining)
            if dev_out is not None:
                dev_out["note"] = (
                    "device recovered mid-window: full bench re-run on the "
                    "device after an initial CPU fallback"
                )
                dev_out["initial_probe_error"] = probe_err
                dev_out["cpu_fallback_preview"] = {
                    "value": out["value"], "workloads": out["workloads"],
                }
                out = dev_out
            else:
                out["tpu_retry"] = retry_err
        # with RAFT_TPU_OBS armed, the bench additionally leaves the
        # JSONL event log + Chrome trace + Prometheus snapshot behind
        # (no-op when the knob is off — the default; forced past the
        # auto-publish debounce so the final snapshot is complete)
        _obs.maybe_publish("bench", force=True)
        print(json.dumps(out))
    except Exception as e:  # emit a diagnostic line, not a stack trace
        # (a child with ASSUME_DEVICE lands here on a mid-bench device
        # death; its parent parses this line and runs the CPU fallback)
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": None,
                    "unit": "solves/s",
                    "vs_baseline": None,
                    "platform": platform,
                    "error": {
                        "class": type(e).__name__,
                        "detail": str(e)[-500:],
                    },
                    "backend_probe_error": probe_err,
                }
            )
        )


if __name__ == "__main__":
    main()
