"""Benchmark: batched design x frequency RAO solves per second per chip.

Workload (the BASELINE.json north star): a batch of OC3-spar geometry
variants, each solved on a 200-bin frequency grid through the full
drag-linearized RAO fixed point, on one TPU chip.  The baseline is the
reference-style serial NumPy path (per-node Python loop drag linearization +
per-frequency 6x6 solve, the structure of raft/raft.py:1497-1552 and
:2160-2264) measured on this host — the reference publishes no numbers
(BASELINE.md), so the comparison is measured-vs-measured on identical physics.

Prints exactly one JSON line:
  {"metric": "design-freq RAO solves/sec/chip", "value": ..., "unit": "solves/s", "vs_baseline": ...}
"""
from __future__ import annotations

import json
import time

import numpy as np


def tpu_throughput(batch: int = 2048, nw: int = 200, reps: int = 3):
    import jax
    import jax.numpy as jnp

    import __graft_entry__ as ge
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.parallel import forward_response, scale_diameters

    design, members, rna, env, wave = ge._base(nw=nw)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = mooring_stiffness(moor, jnp.zeros(6))

    # early-exit while_loop driver: under vmap it runs until every design
    # lane converges (~10 iterations here) instead of a fixed 15
    fwd = jax.jit(
        jax.vmap(
            lambda s: forward_response(
                scale_diameters(members, s), rna, env, wave, C_moor, method="while"
            ).Xi.abs2()
        )
    )
    scales = jnp.linspace(0.9, 1.1, batch)
    out = fwd(scales)
    out.block_until_ready()                       # compile + warm cache
    best = np.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        fwd(scales).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return batch * nw / best


def numpy_baseline(nw: int = 200, n_iter: int = 15, tol: float = 0.01):
    """Reference-style serial path: one design, same grid, iterate to the
    same convergence rule as the device path (raft/raft.py:1542-1547)."""
    import jax.numpy as jnp

    import __graft_entry__ as ge
    from raft_tpu.hydro import node_kinematics, strip_added_mass, strip_excitation
    from raft_tpu.mooring import mooring_stiffness, parse_mooring
    from raft_tpu.statics import assemble_statics

    design, members, rna, env, wave = ge._base(nw=nw)
    moor = parse_mooring(
        design["mooring"], yaw_stiffness=design["turbine"]["yaw_stiffness"]
    )
    C_moor = np.asarray(mooring_stiffness(moor, jnp.zeros(6)))
    stat = assemble_statics(members, rna, env)
    kin = node_kinematics(members, wave, env)
    A = np.asarray(strip_added_mass(members, env))
    F0 = np.asarray(strip_excitation(members, kin, env).to_complex())
    M = np.asarray(stat.M_struc) + A
    C = np.asarray(stat.C_struc) + np.asarray(stat.C_hydro) + C_moor

    w = np.asarray(wave.w)
    u = np.asarray(kin.u.to_complex())            # (N,nw,3)
    mask = np.asarray((members.node_r[:, 2] < 0) & members.node_mask)
    r = np.asarray(members.node_r)
    q, p1, p2 = (np.asarray(x) for x in (members.node_q, members.node_p1, members.node_p2))
    ds, drs, dls = (np.asarray(x) for x in (members.node_ds, members.node_drs, members.node_dls))
    circ = np.asarray(members.node_circ)
    Cd = {k: np.asarray(getattr(members, f"node_Cd_{k}")) for k in ("q", "p1", "p2", "end")}
    rho = float(env.rho)
    c_sqrt = np.sqrt(8.0 / np.pi)

    def get_h(rv):
        return np.array([[0, -rv[2], rv[1]], [rv[2], 0, -rv[0]], [-rv[1], rv[0], 0]])

    Xi = np.full((nw, 6), 0.1 + 0j)
    t0 = time.perf_counter()
    for _ in range(n_iter):
        B6 = np.zeros((6, 6))
        Fd = np.zeros((nw, 6), dtype=complex)
        for i in range(len(dls)):                 # serial per-node loop
            if not mask[i]:
                continue
            H = get_h(r[i])
            vnode = 1j * w[:, None] * (Xi[:, :3] + np.cross(Xi[:, 3:], r[i]))
            vrel = u[i] - vnode
            a_end = abs(
                np.pi * ds[i, 0] * drs[i, 0]
                if circ[i]
                else (ds[i, 0] + drs[i, 0]) * (ds[i, 1] + drs[i, 1])
                - (ds[i, 0] - drs[i, 0]) * (ds[i, 1] - drs[i, 1])
            )
            vrms_q = np.sqrt(np.sum(np.abs(vrel * q[i]) ** 2))
            Bmat = np.zeros((3, 3))
            for unit, ck, area in (
                (q[i], "q", (np.pi * ds[i, 0] if circ[i] else 2 * (ds[i].sum())) * dls[i]),
                (q[i], "end", a_end),
                (p1[i], "p1", ds[i, 0] * dls[i]),
                (p2[i], "p2", (ds[i, 0] if circ[i] else ds[i, 1]) * dls[i]),
            ):
                vrms = np.sqrt(np.sum(np.abs(vrel * unit) ** 2))
                Bmat += (
                    c_sqrt * vrms * 0.5 * rho * area * Cd[ck][i] * np.outer(unit, unit)
                )
            B6[:3, :3] += Bmat
            B6[:3, 3:] += Bmat @ H.T
            B6[3:, :3] += H @ Bmat
            B6[3:, 3:] += H @ Bmat @ H.T
            f3 = vrel @ Bmat.T
            Fd[:, :3] += f3
            Fd[:, 3:] += (H @ f3.T).T
        Xi_new = np.zeros_like(Xi)
        for ii in range(nw):                      # serial per-frequency solve
            Z = -(w[ii] ** 2) * M + 1j * w[ii] * B6 + C
            Xi_new[ii] = np.linalg.solve(Z, F0[ii] + Fd[ii])
        if np.max(np.abs(Xi_new - Xi) / (np.abs(Xi_new) + tol)) < tol:
            Xi = Xi_new
            break
        Xi = 0.2 * Xi + 0.8 * Xi_new
    elapsed = time.perf_counter() - t0
    return nw / elapsed                           # design-freq solves/sec


def main():
    value = tpu_throughput()
    base = numpy_baseline()
    print(
        json.dumps(
            {
                "metric": "design-freq RAO solves/sec/chip",
                "value": round(value, 1),
                "unit": "solves/s",
                "vs_baseline": round(value / base, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
