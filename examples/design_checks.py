"""Feasibility screening of one design: constraint margins + air gap.

The checks the reference only sketches in commented-out legacy code
(raft/raft.py:1655-1698), as a working screening recipe: solve a severe
sea state, then report the slack-line margin, the dynamic-pitch margin,
and the 3-sigma deck clearance at the platform corners — the numbers a
designer looks at before anything else.
"""
import os

import numpy as np

from raft_tpu.model import Model, load_design

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGN = os.path.join(HERE, "..", "raft_tpu", "designs", "OC3spar.yaml")


def main(nw: int = 60, Hs: float = 10.0, Tp: float = 14.0,
         deck_z: float = 12.0):
    model = Model(load_design(DESIGN), w=np.linspace(0.05, 2.95, nw))
    model.setEnv(Hs=Hs, Tp=Tp, Fthrust=800e3)
    model.calcSystemProps()
    model.calcMooringAndOffsets()
    model.solveDynamics()
    model.calcOutputs()

    c = model.results["constraints"]
    print(f"design screening: OC3 spar in Hs={Hs} m, Tp={Tp} s")
    print(f"  slack line margin (T - 3 sigma): {c['slack line margin']:.4g} N"
          f"  -> {'OK' if c['slack line margin'] > 0 else 'SLACK RISK'}")
    print(f"  dynamic pitch |static| + 3 sigma: {c['dynamic pitch']:.2f} deg"
          f" (limit {c['dynamic pitch limit']:.0f})"
          f"  -> {'OK' if c['dynamic pitch'] < c['dynamic pitch limit'] else 'EXCEEDED'}")

    # deck clearance at the spar edge, up/down-wave and abeam
    r = 3.25                                       # OC3 top radius [m] (6.5 m dia)
    pts = [[r, 0.0], [-r, 0.0], [0.0, r], [0.0, -r]]
    gap = model.airgap(pts, deck_z=deck_z)
    worst = int(np.argmin(gap["margin 3 sigma"]))
    for (x, y), m3 in zip(pts, gap["margin 3 sigma"]):
        print(f"  air gap at ({x:5.1f},{y:5.1f}): {m3:6.2f} m"
              f"  -> {'OK' if m3 > 0 else 'DECK IMPACT RISK'}")
    print(f"  critical deck point: ({pts[worst][0]:.1f}, {pts[worst][1]:.1f})")


if __name__ == "__main__":
    main()
