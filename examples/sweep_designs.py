"""Mixed-design megabatch sweep: heterogeneous platforms, bucketed shapes.

The reference analyzes one design per process run; the earlier form of
this example batched diameter *variants of a single platform* (the
geometry was a closure constant of one compiled sweep).  This one runs
the real mixed-design path: geometry variants of FOUR different platforms
(OC3 spar, VolturnUS-S, the two OC4 semis — different member topologies,
different water depths, different moorings) are bucketized into a small
ladder of padded shape classes (raft_tpu/build/buckets.py) and solved as
ONE padded device dispatch per bucket — compile count is the number of
buckets, not the number of designs (raft_tpu/parallel/sweep.py
``sweep_designs``).
"""
import os
import time

import numpy as np

from raft_tpu.model import load_design
from raft_tpu.parallel import sweep_designs

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGN_DIR = os.path.join(HERE, "..", "raft_tpu", "designs")
PLATFORMS = ["OC3spar", "VolturnUS-S", "OC4semi", "OC4semi_2"]


def _scale_profile(v, s):
    """Scale a YAML diameter spec (scalar / list / list of pairs) by s."""
    if isinstance(v, (list, tuple)):
        return [_scale_profile(x, s) for x in v]
    return float(v) * s


def make_variant(design: dict, scale: float) -> dict:
    """A diameter-scaled copy of a design dict: same member topology (same
    shape bucket), different geometry values."""
    import copy

    d = copy.deepcopy(design)
    for mi in d["platform"]["members"]:
        mi["d"] = _scale_profile(mi["d"], scale)
    return d


def main(batch: int = 256, nw: int = 100):
    bases = [load_design(os.path.join(DESIGN_DIR, p + ".yaml"))
             for p in PLATFORMS]
    # round-robin the platforms through a +-10% diameter-scale ladder:
    # a heterogeneous stream, like mixed user traffic
    labels, designs = [], []
    for i in range(batch):
        p = i % len(bases)
        s = 0.9 + 0.2 * (i // len(bases)) / max(1, batch // len(bases) - 1)
        designs.append(make_variant(bases[p], s))
        labels.append((PLATFORMS[p], s))

    t0 = time.perf_counter()
    out = sweep_designs(designs, nw=nw, Hs=8.0, Tp=12.0,
                        w_min=0.05, w_max=2.95, n_iter=30)
    dt = time.perf_counter() - t0
    bk = out["buckets"]
    print(f"{batch} designs x {nw} bins in {dt:.2f} s "
          f"(incl. compile; {batch * nw / dt:.0f} solves/s)")
    print(f"{bk['n_designs']} mixed designs -> {bk['n_buckets']} shape "
          f"buckets (one compiled dispatch each): "
          + "; ".join(f"{s['designs']}x({s['segments']}seg,{s['nodes']}node,"
                      f"{s['nw']}w)" for s in bk["signatures"]))
    sig = out["std dev"]
    best = int(np.argmin(sig[:, 4]))
    plat, s = labels[best]
    print(f"pitch std dev range [{sig[:, 4].min():.4f}, "
          f"{sig[:, 4].max():.4f}] rad")
    print(f"best pitch response: {plat} at diameter scale {s:.3f} "
          f"(surge std {sig[best, 0]:.3f} m)")
    print(f"iterations per lane: max {out['iterations'].max()}")


if __name__ == "__main__":
    main()
