"""Batched design sweep: hundreds of variants in one compiled call.

The reference analyzes one design per process run; here 256 OC3-spar
diameter variants x 100 frequency bins go through the full drag-linearized
RAO fixed point as a single jit(vmap(...)) — the pattern that scales to the
1,000-design north-star bench (bench.py) and shards over a TPU mesh
(raft_tpu/parallel/sweep.py).
"""
import os
import time

import numpy as np
import jax.numpy as jnp

from raft_tpu.build.members import build_member_set, build_rna
from raft_tpu.core.types import Env, WaveState
from raft_tpu.core.waves import jonswap, wave_number
from raft_tpu.model import load_design
from raft_tpu.mooring import mooring_stiffness, parse_mooring
from raft_tpu.parallel import sweep

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGN = os.path.join(HERE, "..", "raft_tpu", "designs", "OC3spar.yaml")


def main(batch: int = 256, nw: int = 100):
    design = load_design(DESIGN)
    members = build_member_set(design)
    rna = build_rna(design)
    depth = float(design["mooring"]["water_depth"])
    env = Env(Hs=8.0, Tp=12.0, depth=depth)
    w = jnp.asarray(np.linspace(0.05, 2.95, nw))
    wave = WaveState(w=w, k=wave_number(w, depth),
                     zeta=jnp.sqrt(jonswap(w, 8.0, 12.0)))
    moor = parse_mooring(design["mooring"],
                         yaw_stiffness=design["turbine"]["yaw_stiffness"])
    C_moor = mooring_stiffness(moor, jnp.zeros(6))

    scales = jnp.linspace(0.85, 1.15, batch)
    t0 = time.perf_counter()
    out = sweep(members, rna, env, wave, C_moor, scales)
    dt = time.perf_counter() - t0
    sig = out["std dev"]
    print(f"{batch} designs x {nw} bins in {dt:.2f} s "
          f"(incl. compile; {batch * nw / dt:.0f} solves/s)")
    best = int(np.argmin(sig[:, 4]))
    print(f"pitch std dev range [{sig[:, 4].min():.4f}, {sig[:, 4].max():.4f}] rad")
    print(f"best pitch response: diameter scale {float(scales[best]):.3f} "
          f"(surge std {sig[best, 0]:.3f} m)")
    print(f"iterations per lane: max {out['iterations'].max()}")


if __name__ == "__main__":
    main()
