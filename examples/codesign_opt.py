"""Co-design optimization: gradient descent on nacelle acceleration.

The WEIS inner loop (BASELINE.json configs[4]): sigma of the nacelle
fore-aft acceleration, differentiated exactly through statics, Morison
hydro, and the drag-linearized RAO fixed point, minimized with optax Adam
under box bounds — first over TWO hull parameters (diameter scale and
draft stretch, the north star's own sweep axes), then over FIVE:
hull + mooring (line length, anchor radius, axial stiffness EA), the
mooring stiffness recomputed differentiably through the catenary Newton
solve each step (raft_tpu.mooring.scale_mooring).
"""
import os

import numpy as np
import jax.numpy as jnp

from raft_tpu.build.members import build_member_set, build_rna
from raft_tpu.core.types import Env, WaveState
from raft_tpu.core.waves import jonswap, wave_number
from raft_tpu.model import load_design
from raft_tpu.mooring import mooring_stiffness, parse_mooring, scale_mooring
from raft_tpu.parallel import (
    grad_nacelle_accel_std,
    make_stretch_draft,
    optimize_design,
    scale_diameters,
)

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGN = os.path.join(HERE, "..", "raft_tpu", "designs", "OC3spar.yaml")


def main(steps: int = 10, nw: int = 60):
    design = load_design(DESIGN)
    members = build_member_set(design)
    rna = build_rna(design)
    depth = float(design["mooring"]["water_depth"])
    env = Env(Hs=8.0, Tp=12.0, depth=depth)
    w = jnp.asarray(np.linspace(0.05, 2.95, nw))
    wave = WaveState(w=w, k=wave_number(w, depth),
                     zeta=jnp.sqrt(jonswap(w, 8.0, 12.0)))
    moor = parse_mooring(design["mooring"],
                         yaw_stiffness=design["turbine"]["yaw_stiffness"])
    C_moor = mooring_stiffness(moor, jnp.zeros(6))

    draft = make_stretch_draft(members)

    def apply2(m, theta):
        """theta = [diameter scale, draft stretch]."""
        return draft(scale_diameters(m, theta[0]), theta[1])

    g0 = np.asarray(grad_nacelle_accel_std(
        members, rna, env, wave, C_moor, jnp.array([1.0, 1.0]),
        apply_fn=apply2,
    ))
    print(f"d sigma_nac / d [diam, draft] at stock: "
          f"[{g0[0]:+.4f}, {g0[1]:+.4f}] (m/s^2)/-")

    res = optimize_design(
        members, rna, env, wave, C_moor, theta0=jnp.array([1.0, 1.0]),
        apply_fn=apply2, steps=steps, learning_rate=0.02,
        bounds=(jnp.array([0.85, 0.85]), jnp.array([1.2, 1.2])),
    )
    for i, (v, t) in enumerate(zip(res.history, res.thetas)):
        print(f"  step {i:2d}: diam {t[0]:.4f} draft {t[1]:.4f}  "
              f"sigma_nac {v:.5f} m/s^2")
    print(f"optimized: diam {res.theta[0]:.4f}, draft {res.theta[1]:.4f}, "
          f"sigma_nac {res.objective:.5f} m/s^2 "
          f"({100 * (1 - res.objective / res.history[0]):.1f}% better than stock)")

    # hull + mooring co-design: theta = [diam, draft, L, R, EA]
    res5 = optimize_design(
        members, rna, env, wave, None,
        theta0=jnp.ones(5),
        apply_fn=lambda m, t: apply2(m, t[:2]),
        moor=moor, moor_apply_fn=lambda s, t: scale_mooring(s, t[2:5]),
        steps=steps, learning_rate=0.02,
        bounds=(0.85 * jnp.ones(5), 1.2 * jnp.ones(5)),
    )
    t = res5.theta
    print(f"hull+mooring: diam {t[0]:.4f} draft {t[1]:.4f} "
          f"L {t[2]:.4f} R {t[3]:.4f} EA {t[4]:.4f}  "
          f"sigma_nac {res5.objective:.5f} m/s^2 "
          f"({100 * (1 - res5.objective / res5.history[0]):.1f}% better)")


if __name__ == "__main__":
    main()
