"""Design-load-case table evaluation: one design x many sea states.

The WEIS outer-loop pattern the reference runs as N separate processes:
here an [Hs, Tp, heading] case table evaluates in ONE compiled vmapped
call (the drag linearization is sea-state-dependent, so each case carries
its own fixed point; each lane carries its own wave heading through the
node kinematics), optionally sharded over a device mesh.
"""
import os

import numpy as np
import jax.numpy as jnp

from raft_tpu.build import build_bucketed_member_set
from raft_tpu.build.members import build_rna
from raft_tpu.core.types import Env
from raft_tpu.model import load_design
from raft_tpu.mooring import mooring_stiffness, parse_mooring
from raft_tpu.parallel import (
    directional_response, make_wave_states, spread_sea_state, sweep_sea_states,
)

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGN = os.path.join(HERE, "..", "raft_tpu", "designs", "OC3spar.yaml")

# a small IEC-flavoured scatter: (Hs [m], Tp [s], heading [rad])
CASES = [
    [1.5, 7.0, 0.0], [2.5, 8.0, 0.0], [3.5, 9.0, 0.5],
    [4.5, 10.0, 0.5], [6.0, 11.0, 1.0], [8.0, 12.0, 1.0],
    [10.0, 13.5, 1.5], [12.0, 15.0, 1.5],
]


def main(nw: int = 100):
    design = load_design(DESIGN)
    # bucketed (masked-padded) staging: the case table compiles against
    # the design's shape CLASS, so any other design of the same class
    # reuses the executable (raft_tpu/build/buckets.py)
    members, sig = build_bucketed_member_set(design)
    print(f"shape bucket: {sig.segments} segments x {sig.nodes} nodes")
    rna = build_rna(design)
    depth = float(design["mooring"]["water_depth"])
    env = Env(depth=depth)
    w = np.linspace(0.05, 2.95, nw)
    waves = make_wave_states(w, CASES, depth)
    moor = parse_mooring(design["mooring"],
                         yaw_stiffness=design["turbine"]["yaw_stiffness"])
    C_moor = mooring_stiffness(moor, jnp.zeros(6))

    out = sweep_sea_states(members, rna, env, waves, C_moor)
    print(f"{'Hs':>5} {'Tp':>5} {'beta':>5} | {'surge std':>9} "
          f"{'sway std':>9} {'heave std':>9} {'pitch std':>9} {'iters':>5}")
    for (Hs, Tp, beta), sig, it in zip(CASES, out["std dev"],
                                       out["iterations"]):
        print(f"{Hs:5.1f} {Tp:5.1f} {np.rad2deg(beta):4.0f}d | "
              f"{sig[0]:9.3f} {sig[1]:9.3f} {sig[2]:9.3f} "
              f"{np.rad2deg(sig[4]):8.3f}d {int(it):5d}")

    # the same (8 m, 12 s) sea as short-crested: cos^2s spreading splits
    # the energy into direction lanes that ride the same batched solve
    waves_dir = spread_sea_state(np.asarray(w), 8.0, 12.0, depth,
                                 beta0=0.0, n_dir=7, s=2.0)
    sc = directional_response(members, rna, env, waves_dir, C_moor)
    print(f"short-crested 8.0m/12.0s (n_dir=7, s=2): surge std "
          f"{sc['std dev'][0]:.3f}, sway std {sc['std dev'][1]:.3f}")


if __name__ == "__main__":
    main()
