"""Single-design end-to-end analysis: the reference's canonical recipe.

Mirrors runRAFT (raft/runRAFT.py:23-82) through the Model facade: design
YAML -> setEnv -> calcSystemProps -> solveEigen -> calcMooringAndOffsets ->
solveDynamics -> calcOutputs -> report.
"""
import os

from raft_tpu.model import Model, load_design

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGN = os.path.join(HERE, "..", "raft_tpu", "designs", "OC3spar.yaml")


def main(save_plots: bool = False):
    design = load_design(DESIGN)
    model = Model(design)
    model.setEnv(Hs=8.0, Tp=12.0, V=10.0,
                 Fthrust=design["turbine"].get("Fthrust", 0.0))
    model.calcSystemProps()
    model.solveEigen()
    model.calcMooringAndOffsets()
    model.solveDynamics()
    model.calcOutputs()
    model.print_report()

    resp = model.results["response"]
    ipk = resp["RAO magnitude"][:, 0].argmax()
    print(f"surge RAO peak {resp['RAO magnitude'][ipk, 0]:.3f} m/m "
          f"at w = {resp['w'][ipk]:.2f} rad/s")
    print(f"nacelle accel std dev {resp['nacelle acceleration std dev']:.3f} m/s^2")

    if save_plots:
        try:
            import matplotlib
        except ImportError:
            print("matplotlib not installed: skipping the RAO figure")
            return
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        model.plot_raos()
        plt.savefig("oc3_raos.png", dpi=120)
        print("wrote oc3_raos.png")


if __name__ == "__main__":
    main(save_plots=True)
