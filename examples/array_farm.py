"""Three-turbine farm: the N-FOWT array the reference only sketches.

The reference grows fowtList/nDOF (raft/raft.py:1292-1298) but every solve
hard-wires turbine 0; ArrayModel stacks the turbines on a leading device
axis and solves all of them in one vmapped pipeline — shared incident wave
with per-position phase lags, per-turbine mooring, nDOF = 6N.
"""
import os

import numpy as np

from raft_tpu.array import ArrayModel
from raft_tpu.model import load_design

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGN = os.path.join(HERE, "..", "raft_tpu", "designs", "OC3spar.yaml")


def main():
    design = load_design(DESIGN)
    # one row of three spars, 800 m spacing, waves along the row
    farm = ArrayModel(design, positions=[[0, 0], [800, 0], [1600, 0]])
    farm.setEnv(Hs=8.0, Tp=12.0, beta=0.0,
                Fthrust=design["turbine"].get("Fthrust", 0.0))
    farm.calcSystemProps()
    farm.solveEigen()
    farm.calcMooringAndOffsets()
    farm.solveDynamics()
    farm.calcOutputs()
    farm.print_report()

    Xi = farm.results["response"]["Xi per turbine"]       # (3, nw, 6)
    w = farm.results["response"]["w"]
    ipk = np.abs(Xi[0, :, 0]).argmax()
    print("surge response phase at the spectral peak, per turbine "
          f"(w = {w[ipk]:.2f} rad/s):")
    for t in range(Xi.shape[0]):
        print(f"  turbine {t} at x = {float(farm.positions[t, 0]):6.0f} m: "
              f"phase {np.degrees(np.angle(Xi[t, ipk, 0])):+7.1f} deg, "
              f"|Xi| {np.abs(Xi[t, ipk, 0]):.3f} m")


if __name__ == "__main__":
    main()
