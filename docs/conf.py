"""Sphinx configuration for raft_tpu."""
project = "raft_tpu"
author = "raft_tpu developers"
release = "0.1.0"

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
]
html_theme = "alabaster"
exclude_patterns = ["_build"]
